"""Native-engine telemetry plane (observability/telemetry.py), the
regression sentinel (observability/sentinel.py), and the r14
observability satellites: ephemeral metrics port, OpenMetrics schema
completeness by construction, perf_doctor round-trip, doctor rendering
of unknown engine families.
"""
import io
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from accl_tpu import ReduceFunction
from accl_tpu.observability import health as obs_health
from accl_tpu.observability import metrics as obs_metrics
from accl_tpu.observability import sentinel as obs_sentinel
from accl_tpu.observability import telemetry as obs_telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_world(nranks=2, iters=4, count=64):
    from accl_tpu.backends.emu import EmuWorld

    world = EmuWorld(nranks)

    def body(accl, rank):
        send = accl.create_buffer_like(
            np.arange(count, dtype=np.float32) + rank)
        recv = accl.create_buffer(count, np.float32)
        for _ in range(iters):
            accl.allreduce(send, recv, count, ReduceFunction.SUM,
                           from_fpga=True, to_fpga=True)

    world.run(body)
    return world


# ---------------------------------------------------------------------------
# engine_stats: the versioned capi snapshot
# ---------------------------------------------------------------------------
def test_engine_stats_schema_and_traffic():
    world = _run_world()
    try:
        stats = world.engine_stats()
        assert len(stats) == world.nranks
        for st in stats:
            assert st["version"] == 3
            for field in obs_telemetry.ENGINE_STATS_FIELDS_V3:
                assert field in st, f"missing v3 field {field}"
            # no unknown fields from a same-version engine
            assert not any(k.startswith("unknown_field_") for k in st)
        # traffic really flowed through the counters
        assert all(st["tx_msgs"] > 0 for st in stats)
        assert all(st["seeks"] > 0 for st in stats)
        assert all(st["wire_accepted_frames"] > 0 for st in stats)
        # eager sends were captured into the retransmit store
        assert any(st["retrans_store_depth"] > 0 for st in stats)
        # the rx pool saw occupancy
        assert any(st["rx_occupancy_hwm"] > 0 for st in stats)
        # quiesced world: transient depths drained back to zero
        assert all(st["egress_depth"] == 0 for st in stats)
        assert all(st["seek_misses"] == 0 for st in stats)
    finally:
        world.close()


def test_engine_stats_closed_world_raises():
    from accl_tpu.constants import ACCLError

    world = _run_world(iters=1)
    dev = world.devices[0]
    world.close()
    with pytest.raises(ACCLError):
        dev.engine_stats()


def test_decode_keeps_newer_engine_fields():
    n = len(obs_telemetry.ENGINE_STATS_FIELDS_V2)
    values = list(range(n + 2))  # a newer engine returned 2 extra
    st = obs_telemetry.decode_engine_stats(values, version=2,
                                           total_fields=n + 2)
    assert st[obs_telemetry.ENGINE_STATS_FIELDS_V2[0]] == 0
    assert st[f"unknown_field_{n}"] == n
    assert st[f"unknown_field_{n + 1}"] == n + 1


@pytest.mark.parametrize("decoder_version,engine_fields,expect_known", [
    # v1 decoder over a v2 engine's array: field 25 (link_rows) must
    # surface as unknown_field_25, never silently vanish or mis-name
    (1, obs_telemetry.ENGINE_STATS_FIELDS_V2,
     obs_telemetry.ENGINE_STATS_FIELDS_V1),
    # v2 decoder over a v1 engine's (shorter) array: a clean prefix
    (2, obs_telemetry.ENGINE_STATS_FIELDS_V1,
     obs_telemetry.ENGINE_STATS_FIELDS_V1),
    # same-version both ways
    (1, obs_telemetry.ENGINE_STATS_FIELDS_V1,
     obs_telemetry.ENGINE_STATS_FIELDS_V1),
    (2, obs_telemetry.ENGINE_STATS_FIELDS_V2,
     obs_telemetry.ENGINE_STATS_FIELDS_V2),
    # v3 (r17 quantized-wire pair) both ways
    (2, obs_telemetry.ENGINE_STATS_FIELDS_V3,
     obs_telemetry.ENGINE_STATS_FIELDS_V2),
    (3, obs_telemetry.ENGINE_STATS_FIELDS_V3,
     obs_telemetry.ENGINE_STATS_FIELDS_V3),
])
def test_decode_engine_stats_version_table(decoder_version,
                                           engine_fields, expect_known):
    """Table-driven forward/backward compat: the decoder's version
    selects ITS field table; extra engine fields become
    unknown_field_<i>, missing ones are simply absent."""
    values = list(range(len(engine_fields)))
    st = obs_telemetry.decode_engine_stats(
        values, version=decoder_version,
        total_fields=len(engine_fields))
    for i, name in enumerate(expect_known):
        assert st[name] == i, name
    known = obs_telemetry.ENGINE_STATS_FIELDS_BY_VERSION[decoder_version]
    for i in range(len(known), len(engine_fields)):
        assert st[f"unknown_field_{i}"] == i
    # nothing mis-sliced: every value accounted for exactly once
    assert sorted(v for k, v in st.items() if k != "version") == \
        list(range(len(engine_fields)))


def test_decode_link_stats_strict_stride():
    """The link decoder must refuse a flat array that is not a whole
    number of rows — mis-slicing would shift every counter into the
    wrong field (the compat-hardening satellite)."""
    from accl_tpu.constants import ACCLError

    stride = len(obs_telemetry.LINK_STATS_FIELDS_V2)
    rows = obs_telemetry.decode_link_stats(list(range(2 * stride)))
    assert len(rows) == 2
    assert rows[0]["comm"] == 0 and rows[0]["peer"] == 1
    assert rows[1]["comm"] == stride
    assert obs_telemetry.decode_link_stats([]) == []
    for bad_len in (1, stride - 1, stride + 1, 2 * stride - 3):
        with pytest.raises(ACCLError, match="stride"):
            obs_telemetry.decode_link_stats(list(range(bad_len)))


# ---------------------------------------------------------------------------
# the sampler: engine/* families, counter-delta discipline, off switch
# ---------------------------------------------------------------------------
def test_sampler_publishes_engine_families():
    reg = obs_metrics.MetricsRegistry()
    world = _run_world()
    try:
        sampler = obs_telemetry.TelemetrySampler(
            [d.engine_stats for d in world.devices], registry=reg,
            interval_s=30.0)
        sampler.sample()
        snap = reg.snapshot()
        assert snap["counters"].get("engine/tx_msgs", 0) > 0
        assert snap["counters"].get("engine/seeks", 0) > 0
        assert "engine/rx_occupancy_hwm" in snap["gauges"]
        total_first = snap["counters"]["engine/tx_msgs"]
        # second sample without new traffic: counters must NOT double
        sampler.sample()
        assert reg.snapshot()["counters"]["engine/tx_msgs"] == total_first
        # counters aggregate as the SUM over ranks
        per_rank = sum(st["tx_msgs"] for st in world.engine_stats())
        assert total_first == per_rank
    finally:
        world.close()


def test_sampler_env_gate(monkeypatch):
    monkeypatch.delenv("ACCL_TELEMETRY_INTERVAL_MS", raising=False)
    assert obs_telemetry.sampler_from_env([lambda: {}]) is None
    monkeypatch.setenv("ACCL_TELEMETRY_INTERVAL_MS", "0")
    assert obs_telemetry.sampler_from_env([lambda: {}]) is None
    monkeypatch.setenv("ACCL_TELEMETRY_INTERVAL_MS", "50")
    reg = obs_metrics.MetricsRegistry()
    sampler = obs_telemetry.sampler_from_env(
        [lambda: {"tx_msgs": 3, "egress_depth": 1}], registry=reg)
    try:
        assert sampler is not None and sampler.interval_s == 0.05
        sampler.sample()
        assert reg.counter("engine/tx_msgs") == 3
        assert reg.snapshot()["gauges"]["engine/egress_depth"] == 1
    finally:
        sampler.stop()


def test_sampler_survives_dying_source():
    reg = obs_metrics.MetricsRegistry()

    def dead():
        raise RuntimeError("world closed mid-poll")

    sampler = obs_telemetry.TelemetrySampler(
        [dead, lambda: {"tx_msgs": 7}], registry=reg, interval_s=30.0)
    sampler.sample()
    assert reg.counter("engine/tx_msgs") == 7


def test_tpu_engine_stats_schema():
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(2) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(32, dtype=np.float32) + rank)
            recv = accl.create_buffer(32, np.float32)
            for _ in range(3):
                accl.allreduce(send, recv, 32, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)

        world.run(body)
        st = world.devices[0].engine_stats()
        assert st["version"] == 3
        assert st["link_rows"] >= 1  # the link twin saw ring traffic
        assert st["leader_dispatches"] + st["executor_dispatches"] > 0
        for k in ("plans_live", "plan_ring_refs",
                  "plan_ring_generation", "ready_depth"):
            assert k in st
        # every field classifies cleanly (counter or known gauge HELP)
        for k in st:
            if k == "version" or k in obs_telemetry.COUNTER_FIELDS:
                continue
            assert obs_metrics.metric_help_for(f"accl_engine_{k}"), k


# ---------------------------------------------------------------------------
# satellite: metrics schema completeness, by construction
# ---------------------------------------------------------------------------
def _sanitize(name: str) -> str:
    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return n if n.startswith("accl_") else f"accl_{n}"


def test_every_registered_family_has_help():
    """Grep the library tree for every literal metric family minted via
    inc/set_gauge/observe_value and require each to resolve through
    METRIC_HELP (or a registered dynamic-name prefix) — the drift class
    'new family ships without HELP' fails here, not in review."""
    pattern = re.compile(
        r"\.(?:inc|set_gauge|observe_value)\(\s*(f?)\"([^\"]+)\"")
    families: dict = {}
    root = os.path.join(REPO, "accl_tpu")
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                text = f.read()
            for m in pattern.finditer(text):
                is_f, literal = m.group(1) == "f", m.group(2)
                prefix_only = is_f and "{" in literal
                name = literal.split("{")[0] if prefix_only else literal
                families[(name, prefix_only)] = path
    assert families, "grep found no metric registrations — pattern rot?"
    missing = []
    exact_keys = list(obs_metrics.METRIC_HELP)
    prefix_keys = list(obs_metrics.METRIC_HELP_PREFIXES)
    for (name, prefix_only), path in sorted(families.items()):
        s = _sanitize(name)
        if prefix_only:
            ok = any(k.startswith(s) for k in exact_keys) or \
                any(k.startswith(s) or s.startswith(k)
                    for k in prefix_keys)
        else:
            ok = obs_metrics.metric_help_for(s) is not None
        if not ok:
            missing.append(f"{name!r} ({path})")
    assert not missing, (
        "metric families without METRIC_HELP entries (add HELP text in "
        "observability/metrics.py): " + ", ".join(missing))


def test_exporter_body_validates_as_openmetrics():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("watchdog/checks", 3)
    reg.inc("engine/tx_msgs", 9)
    reg.set_gauge("accl_health", 0)
    reg.set_gauge("engine/rx_occupancy_hwm", 4)
    reg.observe_value("recovery/latency_us", 1234.5)
    reg.observe_call("allreduce", "float32", 4096, 250_000.0, 4)
    reg.observe_call("allreduce", "float32", 4096, 90_000.0, 4)
    problems = obs_metrics.validate_openmetrics(reg.to_openmetrics())
    assert problems == []


def test_validator_catches_schema_breakage():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("watchdog/checks")
    body = reg.to_openmetrics()
    assert obs_metrics.validate_openmetrics(body) == []
    # a family without HELP knowledge
    reg2 = obs_metrics.MetricsRegistry()
    reg2.inc("totally/unknown")
    probs = obs_metrics.validate_openmetrics(reg2.to_openmetrics())
    assert any("METRIC_HELP" in p for p in probs)
    # missing EOF
    assert any("EOF" in p for p in obs_metrics.validate_openmetrics(
        body.replace("# EOF", "")))
    # a sample without a TYPE declaration
    probs = obs_metrics.validate_openmetrics(
        "orphan_sample 1\n# EOF\n")
    assert any("TYPE" in p for p in probs)
    # non-cumulative histogram buckets
    bad = ("# TYPE accl_recovery_latency_us histogram\n"
           'accl_recovery_latency_us_bucket{le="1"} 5\n'
           'accl_recovery_latency_us_bucket{le="4"} 3\n'
           'accl_recovery_latency_us_bucket{le="+Inf"} 5\n'
           "accl_recovery_latency_us_sum 10\n"
           "accl_recovery_latency_us_count 5\n# EOF\n")
    assert any("cumulative" in p
               for p in obs_metrics.validate_openmetrics(bad))


# ---------------------------------------------------------------------------
# satellite: ACCL_METRICS_PORT=0 binds an ephemeral port
# ---------------------------------------------------------------------------
def test_metrics_port_zero_binds_ephemeral(monkeypatch):
    import urllib.request

    obs_health.stop_exporter()
    monkeypatch.setenv("ACCL_METRICS_PORT", "0")
    try:
        exporter = obs_health.ensure_exporter_from_env()
        assert exporter is not None, "port 0 must mean ephemeral, not off"
        port = obs_health.exporter_port()
        assert port == exporter.port and port > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["health"] in (
                "ok", "degraded", "hung", "aborted", "recovering",
                "slow")
    finally:
        obs_health.stop_exporter()
    assert obs_health.exporter_port() is None


def test_metrics_port_unset_means_off(monkeypatch):
    obs_health.stop_exporter()
    monkeypatch.delenv("ACCL_METRICS_PORT", raising=False)
    assert obs_health.ensure_exporter_from_env() is None
    monkeypatch.setenv("ACCL_METRICS_PORT", "")
    assert obs_health.ensure_exporter_from_env() is None


# ---------------------------------------------------------------------------
# regression sentinel: drift detection + the `slow` health verdict
# ---------------------------------------------------------------------------
def _observe(reg, us, n=30):
    for _ in range(n):
        reg.observe_call("allreduce", "float32", 4096, us * 1e3, 4)


def test_quantile_estimate_tracks_buckets():
    hist = [0] * (len(obs_metrics.LATENCY_BUCKETS_US) + 1)
    hist[5] = 100  # everything in the <=1024us bucket (4**5)
    p50 = obs_sentinel.quantile_us(hist, 0.5)
    assert 256 <= p50 <= 1024
    assert obs_sentinel.quantile_us([0] * len(hist), 0.5) == 0.0


def test_sentinel_flags_drift_and_degrades_health():
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=200.0)
    baseline = obs_sentinel.Baseline.from_snapshot(reg.snapshot())
    assert baseline.entries, "baseline capture produced nothing"

    live = obs_metrics.MetricsRegistry()
    _observe(live, us=9000.0)  # ~45x the baseline p50
    sen = obs_sentinel.Sentinel(baseline, registry=live, p50_ratio=2.0,
                                p99_ratio=3.0, min_calls=10)
    findings = sen.check()
    assert findings, "45x latency drift not flagged"
    f = findings[0]
    assert f["collective"] == "allreduce" and f["axis"] in ("p50_us",
                                                           "p99_us")
    assert f["ratio"] > 2.0
    assert live.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_SLOW
    assert live.counter("sentinel/findings") >= 1
    # recovery: a fresh registry state below threshold clears the verdict
    live.reset()
    _observe(live, us=200.0)
    assert sen.check() == []
    assert live.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_OK


def test_sentinel_slow_never_masks_stronger_verdicts():
    reg = obs_metrics.MetricsRegistry()
    obs_health.note_slow(reg, True)
    assert reg.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_SLOW
    # a recovery episode outranks slow
    obs_health.note_recovering(reg, True)
    assert reg.snapshot()["gauges"]["accl_health"] == \
        obs_health.HEALTH_RECOVERING
    obs_health.note_recovering(reg, False)
    obs_health.note_slow(reg, False)
    assert reg.snapshot()["gauges"]["accl_health"] == obs_health.HEALTH_OK


def test_sentinel_min_calls_guard():
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=100.0)
    baseline = obs_sentinel.Baseline.from_snapshot(reg.snapshot())
    live = obs_metrics.MetricsRegistry()
    _observe(live, us=9000.0, n=3)  # below min_calls
    sen = obs_sentinel.Sentinel(baseline, registry=live, min_calls=10)
    assert sen.compare_snapshot(live.snapshot()) == []


def test_baseline_loads_committed_formats(tmp_path):
    # callrate record
    cb = obs_sentinel.Baseline.load(
        os.path.join(REPO, "bench/results/callrate_r12_plan_on.json"))
    assert any(k[0] == "allreduce" for k in cb.entries)
    assert any(k[3] == "*" for k in cb.entries)
    # sweep-gate CSV
    sb = obs_sentinel.Baseline.load(
        os.path.join(REPO, "bench/results/sweep_gate_baseline_r12.csv"))
    assert any(k[0] == "allreduce" for k in sb.entries)
    # native round-trip
    p = tmp_path / "base.json"
    cb.save(str(p))
    rb = obs_sentinel.Baseline.load(str(p))
    assert rb.entries == cb.entries
    # merge: self wins on conflicts, union otherwise
    merged = cb.merge(sb)
    assert len(merged.entries) >= max(len(cb.entries), len(sb.entries))


def test_sentinel_env_gate(monkeypatch, tmp_path):
    obs_sentinel.stop_sentinel()
    monkeypatch.delenv("ACCL_SENTINEL", raising=False)
    assert obs_sentinel.ensure_sentinel_from_env() is None
    monkeypatch.setenv("ACCL_SENTINEL", "/nonexistent/base.json")
    assert obs_sentinel.ensure_sentinel_from_env() is None  # never raises
    reg = obs_metrics.MetricsRegistry()
    _observe(reg, us=100.0)
    p = tmp_path / "base.json"
    obs_sentinel.Baseline.from_snapshot(reg.snapshot()).save(str(p))
    monkeypatch.setenv("ACCL_SENTINEL", str(p))
    monkeypatch.setenv("ACCL_SENTINEL_INTERVAL_MS", "60000")
    try:
        sen = obs_sentinel.ensure_sentinel_from_env()
        assert sen is not None
        assert obs_sentinel.ensure_sentinel_from_env() is sen  # idempotent
    finally:
        obs_sentinel.stop_sentinel()


# ---------------------------------------------------------------------------
# perf_doctor CLI round-trip (+ --ci schema gate)
# ---------------------------------------------------------------------------
def test_perf_doctor_cli_roundtrip(tmp_path):
    import time as _time

    from accl_tpu.backends.emu import EmuWorld
    from accl_tpu.observability import flight

    reg = obs_metrics.default_registry()
    with EmuWorld(2) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(64, dtype=np.float32) + rank)
            recv = accl.create_buffer(64, np.float32)
            for _ in range(6):
                if rank == 1:
                    _time.sleep(0.002)
                accl.allreduce(send, recv, 64, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)

        world.run(body)
        fdump = tmp_path / "flight.json"
        # THIS world's recorders only: dump_all() sweeps every live
        # recorder in the process, and closed worlds from earlier tests
        # survive until a gc cycle collects their reference cycles
        doc = flight.merge_flight_dumps(
            [a.flight_recorder.dump() for a in world.accls])
        fdump.write_text(json.dumps(doc))
    mdump = tmp_path / "metrics.json"
    mdump.write_text(json.dumps(reg.snapshot()))
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--ci", "--metrics", str(mdump), "--flight", str(fdump),
         "--baseline",
         os.path.join(REPO, "bench/results/callrate_r12_plan_on.json"),
         "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["schema_errors"] == []
    assert "attribution" in report and "sentinel" in report
    assert "engine_telemetry" in report
    d = next(iter(report["attribution"]["collectives"].values()))
    assert d["dominant_straggler"]["rank"] == 1
    assert "straggler" in proc.stdout


def test_perf_doctor_ci_fails_on_malformed_snapshot(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"not": "a snapshot"}))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--ci", "--metrics", str(bad)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "SCHEMA ERROR" in proc.stderr


# ---------------------------------------------------------------------------
# satellite: doctor --live renders unknown engine families gracefully
# ---------------------------------------------------------------------------
def test_doctor_live_renders_unknown_engine_family():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import accl_doctor
    finally:
        sys.path.pop(0)
    reg = obs_metrics.MetricsRegistry()
    reg.inc("engine/tx_msgs", 5)
    reg.set_gauge("engine/rx_occupancy_hwm", 2)
    metrics_text = reg.to_openmetrics() + (
        "# TYPE accl_engine_zz_future_field gauge\n"
        "accl_engine_zz_future_field 42\n# EOF\n")
    scraped = {
        "healthz": {"health": "ok", "accl_health": 0,
                    "watchdog_fires": 0, "watchdog_checks": 1},
        "metrics": metrics_text,
        "flight": {"generated_ns": 0, "nranks": 0, "ranks": [],
                   "analysis": {"desyncs": [], "hangs": [],
                                "stragglers": [], "truncated_comms": [],
                                "torn_dumps": [], "ok": True}},
    }
    out = io.StringIO()
    findings = accl_doctor.report_live(scraped, out)
    text = out.getvalue()
    assert not findings
    assert "engine telemetry" in text
    assert "accl_engine_tx_msgs_total 5" in text
    assert "unrecognized (newer world?)" in text
    # the known family is NOT tagged unrecognized
    known_line = [ln for ln in text.splitlines()
                  if "accl_engine_rx_occupancy_hwm" in ln][0]
    assert "unrecognized" not in known_line


# ---------------------------------------------------------------------------
# r15 wire layer: per-link counters, the world link matrix, chaos
# attribution, and the slowest-link acceptance drills
# ---------------------------------------------------------------------------
def test_link_stats_schema_and_ring_traffic():
    world = _run_world(nranks=4)
    try:
        per_rank = world.link_stats()
        assert set(per_rank) == {0, 1, 2, 3}
        for rank, rows in per_rank.items():
            for row in rows:
                assert set(row) == set(obs_telemetry.LINK_STATS_FIELDS_V2)
                assert row["peer"] != rank  # never the local rank
        m = world.link_matrix()
        assert m["nranks"] == 4
        tx = m["fields"]["tx_bytes"]
        # the ring schedule sends to the right neighbor and receives
        # from the left: every rank's tx row names (r+1) % 4
        for r in range(4):
            assert tx[r][(r + 1) % 4] > 0
            assert m["fields"]["rx_msgs"][r][(r + 3) % 4] > 0
        # link_rows gauge agrees with the decoded row count
        for rank, st in enumerate(world.engine_stats()):
            assert st["link_rows"] == len(per_rank[rank])
    finally:
        world.close()


def _pairwise_world_matrix(chaos: str, nranks: int = 4,
                           count: int = 64, rounds: int = 4) -> dict:
    """Run independent pairwise EAGER transfers under a chaos plan and
    return the link matrix.  Pairwise — NOT a ring schedule: a ring's
    serial relay makes every late hop solicit its upstream, so only
    independent routes can pin WHICH peer a counter belongs to.  Every
    send (all rounds) stages before any recv blocks — the egress
    writer drains them independently of the blocked engine loop — so
    only routes FROM the chaos-targeted rank ever need recovery or run
    slow.  Payloads stay small enough for the eager lane (the
    rendezvous lane's in-process p2p fast path bypasses the wire and
    the chaos funnel entirely) and few enough that every outstanding
    segment fits the rx pool — recovery must never fight head-of-line
    pool exhaustion in this drill."""
    from accl_tpu.backends.emu import EmuWorld

    with EmuWorld(nranks, chaos=chaos) as world:
        def body(accl, rank):
            src = accl.create_buffer_like(
                np.arange(count, dtype=np.float32) + rank)
            dst = accl.create_buffer(count, np.float32)
            reqs = [accl.send(src, count, q, tag=10 + it,
                              run_async=True)
                    for it in range(rounds)
                    for q in range(nranks) if q != rank]
            for it in range(rounds):
                for q in range(nranks):
                    if q != rank:
                        accl.recv(dst, count, q, tag=10 + it)
            for r_ in reqs:
                r_.wait()

        world.run(body)
        return world.link_matrix()


def test_chaos_attribution_to_true_peer():
    """Under a seeded drop plan targeting ONE peer's egress, >= 95% of
    the world's NACK/retransmit link counters must sit on links naming
    that peer — pinning that per-peer counters are stamped at the TRUE
    peer, not the local rank (a local-rank stamp would spread them
    across the observers' own cells instead)."""
    culprit = 1
    P = 4
    m = _pairwise_world_matrix(f"seed=11,drop_rank={culprit}:0.25",
                               nranks=P)
    nacks = m["fields"]["nacks_tx"]
    retrans = m["fields"]["retrans_sent"]
    nacks_total = sum(v for row in nacks for v in row)
    assert nacks_total > 0, "drop plan produced no NACK traffic"
    # NACKs are sent BY receivers TOWARD the losing sender: column
    # `culprit` holds them; retransmits are served BY the culprit
    # toward its requesters: row `culprit`
    nacks_at_culprit = sum(nacks[r][culprit] for r in range(P))
    retrans_total = sum(v for row in retrans for v in row)
    retrans_by_culprit = sum(retrans[culprit])
    assert nacks_at_culprit / nacks_total >= 0.95, (
        f"NACKs mis-attributed: {nacks}")
    if retrans_total:
        assert retrans_by_culprit / retrans_total >= 0.95, (
            f"retransmits mis-attributed: {retrans}")


def test_slowest_link_names_chaos_slowed_peer_emu():
    """Acceptance drill (emu): a 4-rank world with one chaos-slowed
    peer must produce a link matrix whose slowest link names that
    peer."""
    slow = 2
    m = _pairwise_world_matrix(f"seed=3,slow_rank={slow}:5000")
    link = obs_telemetry.slowest_link(m, "seek_wait_ns")
    assert link is not None
    observer, peer = link
    assert peer == slow, (
        f"slowest link {link} does not name the slowed peer {slow}: "
        f"{m['fields']['seek_wait_ns']}")
    # and the wait concentrates there: the slowed peer's column
    # dominates the world's total blocked time
    wait = m["fields"]["seek_wait_ns"]
    col = sum(wait[r][slow] for r in range(4))
    total = sum(v for row in wait for v in row)
    assert col / total >= 0.5


def test_slowest_link_names_straggler_peer_tpu():
    """Acceptance drill (tpu-interpret rung): the gang scheduler's link
    twin must attribute assembly wait to the straggling peer's links."""
    import time as _time

    from accl_tpu.backends.tpu import TpuWorld

    slow = 2
    with TpuWorld(4) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(32, dtype=np.float32) + rank)
            recv = accl.create_buffer(32, np.float32)
            for _ in range(4):
                if rank == slow:
                    _time.sleep(0.004)
                accl.allreduce(send, recv, 32, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)

        world.run(body)
        m = world.link_matrix()
        # ring byte accounting: every rank's tx row names its right
        # ring neighbor with the busbw-corrected payload
        tx = m["fields"]["tx_bytes"]
        for r in range(4):
            assert tx[r][(r + 1) % 4] > 0
    link = obs_telemetry.slowest_link(m, "seek_wait_ns")
    assert link is not None and link[1] == slow, (
        f"straggler wait mis-attributed: {m['fields']['seek_wait_ns']}")


def test_sampler_publishes_link_families():
    reg = obs_metrics.MetricsRegistry()
    world = _run_world(nranks=2)
    try:
        sampler = obs_telemetry.TelemetrySampler(
            [d.engine_stats for d in world.devices], registry=reg,
            interval_s=30.0,
            link_sources=[(r, d.link_stats)
                          for r, d in enumerate(world.devices)])
        sampler.sample()
        snap = reg.snapshot()
        cells = {k: v for k, v in snap["counters"].items()
                 if k.startswith("link/")}
        assert cells.get("link/tx_bytes", 0) > 0  # world total
        assert any(k.startswith("link/tx_bytes/r") for k in cells)
        # delta discipline: a second sample with no traffic publishes 0
        total_first = snap["counters"]["link/tx_bytes"]
        sampler.sample()
        assert reg.counter("link/tx_bytes") == total_first
        # world total equals the matrix sum
        msum = sum(v for row in
                   sampler.last_link_matrix["fields"]["tx_bytes"]
                   for v in row)
        assert total_first == msum
    finally:
        world.close()


def test_link_matrix_helpers_synthetic():
    rows = {
        0: [{"comm": 0, "peer": 1, "tx_msgs": 2, "tx_bytes": 100,
             "rx_msgs": 0, "rx_bytes": 0, "retrans_sent": 0,
             "nacks_tx": 0, "nacks_rx": 0, "fenced_drops": 0,
             "seeks": 1, "seek_wait_ns": 500},
            {"comm": 7, "peer": 1, "tx_msgs": 9, "tx_bytes": 999,
             "rx_msgs": 0, "rx_bytes": 0, "retrans_sent": 0,
             "nacks_tx": 0, "nacks_rx": 0, "fenced_drops": 0,
             "seeks": 0, "seek_wait_ns": 0}],
        1: [{"comm": 0, "peer": 0, "tx_msgs": 1, "tx_bytes": 40,
             "rx_msgs": 2, "rx_bytes": 100, "retrans_sent": 3,
             "nacks_tx": 0, "nacks_rx": 0, "fenced_drops": 0,
             "seeks": 2, "seek_wait_ns": 9000}],
    }
    m = obs_telemetry.link_matrix(rows, nranks=2)
    assert m["fields"]["tx_bytes"][0][1] == 100  # comm 7 filtered out
    assert m["fields"]["tx_bytes"][1][0] == 40
    assert obs_telemetry.slowest_link(m, "seek_wait_ns") == (1, 0)
    assert obs_telemetry.slowest_link(m, "fenced_drops") is None
    # comm=None folds every comm
    m_all = obs_telemetry.link_matrix(rows, nranks=2, comm=None)
    assert m_all["fields"]["tx_bytes"][0][1] == 1099
    # imbalance over nonzero cells
    assert obs_telemetry.link_imbalance(m, "tx_bytes") == \
        pytest.approx(100 / 70)


def test_perf_doctor_link_matrix_section(tmp_path):
    """The --ci report grows a schema-validated link_matrix section
    whenever the snapshot carries link/* families."""
    reg = obs_metrics.MetricsRegistry()
    world = _run_world(nranks=2)
    try:
        sampler = obs_telemetry.TelemetrySampler(
            [d.engine_stats for d in world.devices], registry=reg,
            interval_s=30.0,
            link_sources=[(r, d.link_stats)
                          for r, d in enumerate(world.devices)])
        sampler.sample()
    finally:
        world.close()
    mdump = tmp_path / "metrics.json"
    mdump.write_text(json.dumps(reg.snapshot()))
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/perf_doctor.py"),
         "--ci", "--metrics", str(mdump), "--out", str(out)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["schema_errors"] == []
    lm = report["link_matrix"]
    P = lm["matrix"]["nranks"]
    assert P == 2
    for f in obs_telemetry.LINK_COUNTER_FIELDS:
        assert len(lm["matrix"]["fields"][f]) == P
    assert "tx_imbalance_ratio" in lm["findings"]
    assert "link matrix" in proc.stdout


def test_tpu_plan_replay_traffic_lands_in_link_matrix():
    """The plan-replay lane is the dominant steady-state traffic under
    ACCL_PLAN_AUTO — replayed collectives must account into the link
    twin exactly like eager gang dispatches (a matrix that goes dark
    when plans kick in would mis-model precisely the hot traffic)."""
    from accl_tpu.backends.tpu import TpuWorld

    with TpuWorld(4) as world:
        def body(accl, rank):
            send = accl.create_buffer_like(
                np.arange(32, dtype=np.float32) + rank)
            recv = accl.create_buffer(32, np.float32)
            plan = accl.capture_plan(
                lambda a: a.allreduce(send, recv, 32, ReduceFunction.SUM,
                                      from_fpga=True, to_fpga=True))
            for _ in range(3):
                plan.replay()

        base = world.link_matrix()["fields"]["tx_bytes"]
        world.run(body)
        m = world.link_matrix()
    tx = m["fields"]["tx_bytes"]
    # capture (1 eager) + 3 replays = 4 instances; allreduce of 128 B
    # at busbw 2*(P-1)/P -> 192 B per right-neighbor link each
    for r in range(4):
        assert tx[r][(r + 1) % 4] - base[r][(r + 1) % 4] == 4 * 192, tx


def test_sampler_dead_rank_keeps_world_shape():
    """A source that dies mid-poll must not shrink the matrix: live
    ranks' cells toward the dead rank keep publishing."""
    reg = obs_metrics.MetricsRegistry()

    def dead():
        raise RuntimeError("rank 3 closed mid-poll")

    rows0 = [{"comm": 0, "peer": 3, "tx_msgs": 1, "tx_bytes": 64,
              "rx_msgs": 0, "rx_bytes": 0, "retrans_sent": 0,
              "nacks_tx": 0, "nacks_rx": 0, "fenced_drops": 0,
              "seeks": 0, "seek_wait_ns": 0}]
    sampler = obs_telemetry.TelemetrySampler(
        [], registry=reg,
        link_sources=[(0, lambda: rows0), (1, lambda: []),
                      (2, lambda: []), (3, dead)])
    sampler.sample()
    m = sampler.last_link_matrix
    assert m["nranks"] == 4  # NOT shrunk to the answering ranks
    assert m["fields"]["tx_bytes"][0][3] == 64
    assert reg.counter("link/tx_bytes/r0->r3") == 64
