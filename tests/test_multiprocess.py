"""Process-per-rank emulation over real TCP sockets.

The reference's multi-node-without-cluster mechanism: one emulator
process per MPI rank, network = sockets between processes (SURVEY §4;
test/model/emulator/run.py).  Here each rank is a separate Python
process running scripts/run_emu_rank.py with its own native engine;
only the TCP transport connects them.
"""
import os
import subprocess
import sys

import pytest

ROOT = os.path.join(os.path.dirname(__file__), os.pardir)


@pytest.mark.parametrize("nranks", [2, 3])
def test_multiprocess_tcp_world(nranks):
    port = 21000 + (os.getpid() % 1500) + nranks * 100
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join("scripts", "run_emu_rank.py"),
             "--rank", str(r), "--nranks", str(nranks),
             "--port", str(port), "--count", "512"],
            cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for r in range(nranks)
    ]
    outs = []
    try:
        for p in procs:
            # generous ceiling: on an oversubscribed 1-core CI host the
            # peer processes' python+numpy imports alone can lag minutes;
            # run_emu_rank absorbs that skew in a long-budget barrier and
            # normal runs finish in seconds
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r}/{nranks}: OK" in out


def test_multihost_two_processes():
    """REAL multi-host bring-up: two OS processes join a
    jax.distributed cluster through utils.bringup.initialize_multihost
    (ACCL_* env path), build the hybrid DCN x ICI mesh, and run a
    hierarchical all-reduce end to end — the reference's MPI-launch +
    QP-exchange role (test/host/Coyote/test.cpp:351-397), exercised
    for real instead of dry_run (r4 VERDICT item 7)."""
    port = 23100 + (os.getpid() % 1500)
    nproc = 2
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join("scripts", "run_multihost_rank.py")],
            cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
            env={**os.environ,
                 "JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS":
                     "--xla_force_host_platform_device_count=4",
                 "ACCL_COORDINATOR": f"127.0.0.1:{port}",
                 "ACCL_NUM_PROCESSES": str(nproc),
                 "ACCL_PROCESS_ID": str(r)},
        )
        for r in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {r} failed:\n{out}"
        assert f"MULTIHOST_OK process={r}" in out
