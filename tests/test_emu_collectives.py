"""Collective tests against the native emulator backend.

Port of the reference host-driven test strategy (test/host/xrt/src/
test.cpp: one driver per MPI rank against one emulator each); here ranks
are threads in one process against the in-proc native engine world
(SURVEY §4 rung 1).  Coverage mirrors the reference corpus: primitives,
every collective, rooted collectives over every root, multiple dtypes,
segmentation boundaries, rx-fifo exhaustion, barrier.
"""
import numpy as np
import pytest

from accl_tpu import TAG_ANY, ReduceFunction
from accl_tpu.backends.emu import EmuWorld

NRANKS = 4
COUNT = 64


@pytest.fixture(scope="module")
def world():
    with EmuWorld(NRANKS) as w:
        yield w


def _fill(accl, count, dtype, rank, salt=0):
    rng = np.random.default_rng(1234 + rank + salt * 100)
    if np.issubdtype(np.dtype(dtype), np.integer):
        data = rng.integers(-1000, 1000, size=count).astype(dtype)
    else:
        data = rng.standard_normal(count).astype(dtype)
    buf = accl.create_buffer_like(data)
    return buf, data


def _all_inputs(count, dtype, salt=0):
    return [
        _fill_data(count, dtype, r, salt) for r in range(NRANKS)
    ]


def _fill_data(count, dtype, rank, salt=0):
    rng = np.random.default_rng(1234 + rank + salt * 100)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return rng.integers(-1000, 1000, size=count).astype(dtype)
    return rng.standard_normal(count).astype(dtype)


# ---------------------------------------------------------------------------
# primitives (reference: test.cpp test_copy :30, test_combine :87)
# ---------------------------------------------------------------------------
def test_copy(world):
    def fn(accl, rank):
        src, data = _fill(accl, COUNT, np.float32, rank)
        dst = accl.create_buffer(COUNT, np.float32)
        accl.copy(src, dst, COUNT)
        np.testing.assert_array_equal(dst.host, data)

    world.run(fn)


@pytest.mark.parametrize("func", [ReduceFunction.SUM, ReduceFunction.MAX])
def test_combine(world, func):
    def fn(accl, rank):
        op0, d0 = _fill(accl, COUNT, np.float32, rank, salt=1)
        op1, d1 = _fill(accl, COUNT, np.float32, rank, salt=2)
        res = accl.create_buffer(COUNT, np.float32)
        accl.combine(COUNT, func, op0, op1, res)
        exp = d0 + d1 if func == ReduceFunction.SUM else np.maximum(d0, d1)
        np.testing.assert_allclose(res.host, exp, rtol=1e-6)

    world.run(fn)


# ---------------------------------------------------------------------------
# send/recv (reference: test_sendrcv :117, segmentation variants :265)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("count", [16, 256, 257])  # eager, multi-seg, ragged
def test_sendrecv_pairs(world, count):
    # ring exchange MPI-style: async send to next, recv from prev, wait.
    # (count=257 crosses the eager->rendezvous threshold: a sync send would
    # deadlock by MPI semantics, exactly as a rendezvous MPI_Send would.)
    def fn(accl, rank):
        nxt, prv = (rank + 1) % NRANKS, (rank - 1) % NRANKS
        src, data = _fill(accl, count, np.float32, rank)
        dst = accl.create_buffer(count, np.float32)
        sreq = accl.send(src, count, nxt, tag=7, run_async=True)
        accl.recv(dst, count, prv, tag=7)
        assert sreq.wait(timeout=30)
        sreq.check()
        np.testing.assert_array_equal(dst.host, _fill_data(count, np.float32, prv))

    world.run(fn)


def test_sendrecv_rendezvous(world):
    # > max_eager (1KB) -> rendezvous protocol with address exchange
    count = 4096  # 16 KB fp32
    def fn(accl, rank):
        if rank == 0:
            src, data = _fill(accl, count, np.float32, 0)
            accl.send(src, count, 1, tag=42)
        elif rank == 1:
            dst = accl.create_buffer(count, np.float32)
            accl.recv(dst, count, 0, tag=42)
            np.testing.assert_array_equal(dst.host, _fill_data(count, np.float32, 0))

    world.run(fn)


def test_sendrecv_tag_any_and_mixed_ordering(world):
    # SAME scenario as the TPU backend's wildcard tests — the two rungs
    # must provably share matching semantics (rxpool seek,
    # native/src/rxpool.hpp:67-78; reference rxbuf_seek.cpp:19-78): the
    # per-src seqn counter is shared across tags, so in-order tagged
    # recvs match their sends and a wildcard drains whatever is oldest
    def fn(accl, rank):
        if rank == 0:
            a, _ = _fill(accl, COUNT, np.float32, 0, salt=21)
            b, _ = _fill(accl, COUNT, np.float32, 0, salt=22)
            accl.send(a, COUNT, 1, tag=5)
            accl.send(b, COUNT, 1, tag=7)
        elif rank == 1:
            import time
            time.sleep(0.2)  # both sends pending before any recv posts
            d5 = accl.create_buffer(COUNT, np.float32)
            dany = accl.create_buffer(COUNT, np.float32)
            accl.recv(d5, COUNT, 0, tag=5)
            accl.recv(dany, COUNT, 0, tag=TAG_ANY)
            np.testing.assert_array_equal(
                d5.host, _fill_data(COUNT, np.float32, 0, salt=21))
            np.testing.assert_array_equal(
                dany.host, _fill_data(COUNT, np.float32, 0, salt=22))

    world.run(fn)


def test_fifo_exhaustion(world):
    # more in-flight eager messages than rx buffers (reference
    # test_sendrcv_fifo_exhaustion): staging backpressure must absorb
    count, nmsg = 128, 40  # 40 x 512B messages > 16 rx buffers
    def fn(accl, rank):
        if rank == 0:
            bufs = [_fill(accl, count, np.float32, 0, salt=i) for i in range(nmsg)]
            for i, (b, _) in enumerate(bufs):
                accl.send(b, count, 1, tag=100 + i)
        elif rank == 1:
            import time
            time.sleep(0.2)  # let sends pile up beyond the pool
            dst = accl.create_buffer(count, np.float32)
            for i in range(nmsg):
                accl.recv(dst, count, 0, tag=100 + i)
                np.testing.assert_array_equal(
                    dst.host, _fill_data(count, np.float32, 0, salt=i))

    world.run(fn)


# ---------------------------------------------------------------------------
# collectives (reference: test.cpp :381-1002; rooted ones over every root
# via INSTANTIATE testing::Range(0, size) :1028)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("root", range(NRANKS))
def test_bcast(world, root):
    def fn(accl, rank):
        buf, _ = _fill(accl, COUNT, np.float32, rank, salt=root)
        accl.bcast(buf, COUNT, root)
        np.testing.assert_array_equal(
            buf.host, _fill_data(COUNT, np.float32, root, salt=root))

    world.run(fn)


@pytest.mark.parametrize("root", range(NRANKS))
def test_scatter(world, root):
    def fn(accl, rank):
        send, data = _fill(accl, COUNT * NRANKS, np.float32, rank, salt=root)
        recv = accl.create_buffer(COUNT, np.float32)
        accl.scatter(send, recv, COUNT, root)
        exp = _fill_data(COUNT * NRANKS, np.float32, root, salt=root)
        np.testing.assert_array_equal(
            recv.host, exp[rank * COUNT:(rank + 1) * COUNT])

    world.run(fn)


@pytest.mark.parametrize("root", range(NRANKS))
def test_gather(world, root):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT, np.float32, rank)
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.gather(send, recv, COUNT, root)
        if rank == root:
            exp = np.concatenate(
                [_fill_data(COUNT, np.float32, r) for r in range(NRANKS)])
            np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)


def test_allgather(world):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT, np.float32, rank)
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.allgather(send, recv, COUNT)
        exp = np.concatenate(
            [_fill_data(COUNT, np.float32, r) for r in range(NRANKS)])
        np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)


@pytest.mark.parametrize("root", range(NRANKS))
@pytest.mark.parametrize("func", [ReduceFunction.SUM, ReduceFunction.MAX])
def test_reduce(world, root, func):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT, np.float32, rank)
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce(send, recv, COUNT, root, func)
        if rank == root:
            inputs = [_fill_data(COUNT, np.float32, r) for r in range(NRANKS)]
            exp = (np.sum(inputs, axis=0) if func == ReduceFunction.SUM
                   else np.max(inputs, axis=0))
            np.testing.assert_allclose(recv.host, exp, rtol=1e-5)

    world.run(fn)


@pytest.mark.parametrize("count", [COUNT, 61, NRANKS * 300 + 3])
def test_allreduce(world, count):
    def fn(accl, rank):
        send, _ = _fill(accl, count, np.float32, rank)
        recv = accl.create_buffer(count, np.float32)
        accl.allreduce(send, recv, count, ReduceFunction.SUM)
        inputs = [_fill_data(count, np.float32, r) for r in range(NRANKS)]
        np.testing.assert_allclose(recv.host, np.sum(inputs, axis=0), rtol=1e-5)

    world.run(fn)


def test_reduce_scatter(world):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT * NRANKS, np.float32, rank)
        recv = accl.create_buffer(COUNT, np.float32)
        accl.reduce_scatter(send, recv, COUNT, ReduceFunction.SUM)
        inputs = [_fill_data(COUNT * NRANKS, np.float32, r)
                  for r in range(NRANKS)]
        exp = np.sum(inputs, axis=0)[rank * COUNT:(rank + 1) * COUNT]
        np.testing.assert_allclose(recv.host, exp, rtol=1e-5)

    world.run(fn)


def test_alltoall(world):
    def fn(accl, rank):
        send, data = _fill(accl, COUNT * NRANKS, np.float32, rank)
        recv = accl.create_buffer(COUNT * NRANKS, np.float32)
        accl.alltoall(send, recv, COUNT)
        exp = np.concatenate([
            _fill_data(COUNT * NRANKS, np.float32, r)[rank * COUNT:(rank + 1) * COUNT]
            for r in range(NRANKS)
        ])
        np.testing.assert_array_equal(recv.host, exp)

    world.run(fn)


def test_barrier(world):
    # reference test_barrier :1003 — just completes without error
    def fn(accl, rank):
        for _ in range(3):
            accl.barrier()

    world.run(fn)


# ---------------------------------------------------------------------------
# dtype coverage (reference: arith configs for f16/f32/f64/i32/i64)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float64, np.int32, np.int64, np.float16])
def test_allreduce_dtypes(world, dtype):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT, dtype, rank)
        recv = accl.create_buffer(COUNT, dtype)
        accl.allreduce(send, recv, COUNT, ReduceFunction.SUM)
        inputs = [_fill_data(COUNT, dtype, r) for r in range(NRANKS)]
        exp = np.sum(np.stack(inputs).astype(np.float64), axis=0)
        if np.dtype(dtype) == np.float16:
            np.testing.assert_allclose(recv.host.astype(np.float64), exp,
                                       rtol=5e-2, atol=5e-2)
        elif np.issubdtype(np.dtype(dtype), np.integer):
            np.testing.assert_array_equal(recv.host.astype(np.float64), exp)
        else:
            np.testing.assert_allclose(recv.host, exp, rtol=1e-9)

    world.run(fn)


# ---------------------------------------------------------------------------
# perf counter sanity (reference: test.cpp :1010)
# ---------------------------------------------------------------------------
def test_duration_counter(world):
    def fn(accl, rank):
        send, _ = _fill(accl, COUNT, np.float32, rank)
        recv = accl.create_buffer(COUNT, np.float32)
        req = accl.allreduce(send, recv, COUNT)
        assert accl.get_duration(req) > 0

    world.run(fn)
