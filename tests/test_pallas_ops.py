"""Pallas kernel tests (interpret mode on the CPU rung; the same code
compiles for TPU hardware).  Reference plugin coverage: reduce_ops,
hp_compression, ring schedules, vadd_put fusion."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from accl_tpu.ops import (
    compress_cast,
    decompress_cast,
    fused_matmul_allreduce,
    pallas_add,
    pallas_max,
    ring_all_gather_pallas,
    ring_all_reduce_pallas,
    ring_reduce_scatter_pallas,
)
from accl_tpu.ops.fused import pallas_matmul
from accl_tpu.parallel import make_mesh

# any non-CPU backend is the real chip (the bench chip claims as
# "axon", not "tpu" — same idiom as bench.py's on_tpu check)
ON_TPU = jax.default_backend() not in ("cpu",)
INTERP = not ON_TPU


def _rand(shape, dtype=np.float32, seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(dtype)


# ---------------------------------------------------------------------------
# reduce_ops lanes (reference: reduce_ops.cpp:31-107)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
def test_pallas_add_max(dtype):
    a = (_rand(1000, np.float32, 1) * 100).astype(dtype)
    b = (_rand(1000, np.float32, 2) * 100).astype(dtype)
    out = pallas_add(jnp.asarray(a), jnp.asarray(b), interpret=INTERP)
    np.testing.assert_allclose(np.asarray(out), a + b, rtol=1e-6)
    out = pallas_max(jnp.asarray(a), jnp.asarray(b), interpret=INTERP)
    np.testing.assert_array_equal(np.asarray(out), np.maximum(a, b))


def test_pallas_add_ragged_tail():
    # non-multiple of the 8x128 tile (segmentation boundary analog)
    a, b = _rand(1031, seed=3), _rand(1031, seed=4)
    out = pallas_add(jnp.asarray(a), jnp.asarray(b), interpret=INTERP)
    np.testing.assert_allclose(np.asarray(out), a + b, rtol=1e-6)


# ---------------------------------------------------------------------------
# compression lanes (reference: hp_compression.cpp:70-144)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [jnp.float16, jnp.bfloat16])
def test_compress_roundtrip(dtype):
    x = _rand(4096, seed=5)
    c = compress_cast(jnp.asarray(x), dtype, interpret=INTERP)
    assert c.dtype == dtype
    d = decompress_cast(c, jnp.float32, interpret=INTERP)
    tol = 2e-3 if dtype == jnp.float16 else 2e-2
    np.testing.assert_allclose(np.asarray(d), x, rtol=tol, atol=tol)


@pytest.mark.skipif(not ON_TPU, reason="stochastic rounding needs the TPU PRNG")
def test_stochastic_round_tpu():
    x = jnp.full((4096,), 1.0 + 2.0 ** -12, jnp.float32)
    c = compress_cast(x, jnp.bfloat16, stochastic=True, seed=7)
    vals = np.unique(np.asarray(c.astype(jnp.float32)))
    assert len(vals) == 2  # rounds both ways


# ---------------------------------------------------------------------------
# fused compute + collective (reference: vadd_put.cpp:23-86)
# ---------------------------------------------------------------------------
def test_pallas_matmul():
    x, w = _rand((256, 128), seed=6), _rand((128, 256), seed=7)
    out = pallas_matmul(jnp.asarray(x), jnp.asarray(w), interpret=INTERP)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-4, atol=1e-4)


def test_fused_matmul_allreduce():
    P_ = 4
    if len(jax.devices()) < P_:
        pytest.skip("needs a 4-device mesh")
    mesh = make_mesh(tp=P_)
    x = _rand((8, P_ * 16), seed=8)
    w = _rand((P_ * 16, 32), seed=9)
    xs = x.reshape(8, P_, 16).transpose(1, 0, 2)  # K-shards
    ws = w.reshape(P_, 16, 32)

    def body(xb, wb):
        return fused_matmul_allreduce(xb[0], wb[0], axis="tp",
                                      use_pallas=False)[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("tp", None, None),) * 2,
                  out_specs=P("tp", None, None))
    out = jax.jit(f)(jnp.asarray(xs), jnp.asarray(ws))
    np.testing.assert_allclose(np.asarray(out)[0], x @ w, rtol=1e-4,
                               atol=1e-4)


# ---------------------------------------------------------------------------
# ring collectives over remote DMA (reference ring schedules; run under
# the Pallas TPU interpreter on CPU)
# ---------------------------------------------------------------------------
NR = 4


def _ring_mesh():
    if len(jax.devices()) < NR:
        pytest.skip("needs a 4-device mesh")
    return make_mesh(dp=NR)


def test_ring_all_gather_pallas():
    mesh = _ring_mesh()
    d = _rand((NR, 8, 128), seed=10)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None, None)))

    def body(xb):
        return ring_all_gather_pallas(xb[0], "dp", interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None, None),
                  out_specs=P("dp", None, None, None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    for r in range(NR):
        np.testing.assert_array_equal(out[r], d)


def test_ring_reduce_scatter_pallas():
    mesh = _ring_mesh()
    d = _rand((NR, NR, 8, 128), seed=11)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None, None, None)))

    def body(xb):
        return ring_reduce_scatter_pallas(xb[0], "dp", interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None, None, None),
                  out_specs=P("dp", None, None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    exp = d.sum(axis=0)
    for r in range(NR):
        np.testing.assert_allclose(out[r], exp[r], rtol=1e-4, atol=1e-4)


def test_ring_all_reduce_pallas():
    mesh = _ring_mesh()
    d = _rand((NR, NR * 8, 128), seed=12)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None, None)))

    def body(xb):
        return ring_all_reduce_pallas(xb[0], "dp", interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None, None),
                  out_specs=P("dp", None, None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    exp = d.sum(axis=0)
    for r in range(NR):
        np.testing.assert_allclose(out[r], exp, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n", [96, 1000])  # multi-segment + ragged tail
def test_ring_all_reduce_segmented(n):
    from accl_tpu.ops.ring import ring_all_reduce_segmented

    mesh = _ring_mesh()
    d = _rand((NR, n), seed=13)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None)))

    def body(xb):
        return ring_all_reduce_segmented(xb[0], "dp", seg_elems=32,
                                         interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    exp = d.sum(axis=0)
    for r in range(NR):
        np.testing.assert_allclose(out[r], exp, rtol=1e-4, atol=1e-4)


def test_ring_all_gather_segmented_interleaving():
    from accl_tpu.ops.ring import ring_all_gather_segmented

    mesh = _ring_mesh()
    n = 50  # 2 segments of 32 + ragged 18
    d = _rand((NR, n), seed=14)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None)))

    def body(xb):
        return ring_all_gather_segmented(xb[0], "dp", seg_elems=32,
                                         interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    exp = d.reshape(-1)  # rank-major whole-payload layout
    for r in range(NR):
        np.testing.assert_array_equal(out[r], exp)


def test_ring_reduce_scatter_segmented():
    from accl_tpu.ops.ring import ring_reduce_scatter_segmented

    mesh = _ring_mesh()
    n = 70  # ragged: 3 segments of 32/32/6 per chunk
    d = _rand((NR, NR * n), seed=15)
    x = jax.device_put(d, NamedSharding(mesh, P("dp", None)))

    def body(xb):
        return ring_reduce_scatter_segmented(xb[0], "dp", seg_elems=32,
                                             interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None), check_vma=False)
    out = np.asarray(jax.jit(f)(x))
    exp = d.reshape(NR, NR, n).sum(axis=0)  # [rank chunk, n]
    for r in range(NR):
        np.testing.assert_allclose(out[r], exp[r], rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# single-device virtual self-ring (ring_size override): the compiled
# semaphore/remote-DMA code path executable on ONE chip — the
# reference's execute-the-artifact rung (cclo_sim.cpp:57-559).  On the
# CPU rung these run under the interpreter; on the bench chip they run
# COMPILED (bench.py's selfring stage and the chip worker's test leg).
# ---------------------------------------------------------------------------
def _one_dev_mesh():
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("r",))


def _smap1(f):
    mesh = _one_dev_mesh()
    return jax.jit(shard_map(f, mesh=mesh, in_specs=P(),
                             out_specs=P(), check_vma=False))


def test_selfring_all_gather():
    V = 4
    d = _rand((8, 128), seed=20)
    f = _smap1(lambda v: ring_all_gather_pallas(v, "r", ring_size=V,
                                                interpret=INTERP))
    out = np.asarray(f(jnp.asarray(d)))
    # every virtual rank is this device: out = x tiled V times
    np.testing.assert_array_equal(out, np.broadcast_to(d, (V, 8, 128)))


def test_selfring_reduce_scatter():
    V = 4
    d = _rand((V, 8, 128), seed=21)
    f = _smap1(lambda v: ring_reduce_scatter_pallas(v, "r", ring_size=V,
                                                    interpret=INTERP))
    out = np.asarray(f(jnp.asarray(d)))
    # each hop's incoming partial is our own accumulator: full fold
    np.testing.assert_allclose(out, d.sum(axis=0), rtol=1e-4, atol=1e-4)


def test_selfring_all_reduce():
    V = 4
    d = _rand((V * 8, 128), seed=22)
    f = _smap1(lambda v: ring_all_reduce_pallas(v, "r", ring_size=V,
                                                interpret=INTERP))
    out = np.asarray(f(jnp.asarray(d)))
    exp = np.broadcast_to(d.reshape(V, 8, 128).sum(axis=0),
                          (V, 8, 128)).reshape(V * 8, 128)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-4)


def test_selfring_requires_single_member_axis():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = make_mesh(dp=2)
    d = _rand((8, 128), seed=23)

    def body(xb):
        return ring_all_gather_pallas(xb[0], "dp", ring_size=4,
                                      interpret=INTERP)[None]

    f = shard_map(body, mesh=mesh, in_specs=P("dp", None),
                  out_specs=P("dp", None, None, None), check_vma=False)
    with pytest.raises(ValueError, match="ring_size"):
        jax.jit(f)(jax.device_put(
            np.broadcast_to(d, (2, 8, 128)).copy(),
            NamedSharding(mesh, P("dp", None, None))))
