"""Persistent collective plans (accl_tpu/plans.py): capture/replay
bitwise fidelity, capture-time validation, invalidation fencing, the
ACCL_PLAN=0 kill switch, and the ACCL_PLAN_AUTO transparent lane.

The bitwise contract: a captured plan replayed N times must produce
exactly the byte streams the same N iterations produce through the
eager per-call driver path — on both the emulator engine (native C plan
ring, one FFI per replay) and the TPU backend (PlanRing, one rendezvous
per replay).
"""
import os

import numpy as np
import pytest

from accl_tpu import ACCLError, ReduceFunction
from accl_tpu import plans as plans_mod
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.backends.tpu import TpuWorld

NRANKS = 4
COUNT = 64
SCATTER = COUNT // NRANKS


def _data(rank):
    rng = np.random.default_rng(100 + rank)
    return rng.standard_normal(COUNT).astype(np.float32)


def _chain_eager(accl, rank, iters):
    """The reference loop: allreduce + reduce_scatter + a sendrecv ring
    hop, through the normal per-call path."""
    s = accl.create_buffer_like(_data(rank))
    r = accl.create_buffer(COUNT, np.float32)
    rs = accl.create_buffer(SCATTER, np.float32)
    pr = accl.create_buffer(COUNT, np.float32)
    outs = []
    for _ in range(iters):
        accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
        accl.reduce_scatter(s, rs, SCATTER, ReduceFunction.SUM)
        if rank % 2 == 0:
            accl.send(s, COUNT, (rank + 1) % NRANKS)
            accl.recv(pr, COUNT, (rank - 1) % NRANKS)
        else:
            accl.recv(pr, COUNT, (rank - 1) % NRANKS)
            accl.send(s, COUNT, (rank + 1) % NRANKS)
        outs.append((r.host.copy(), rs.host.copy(), pr.host.copy()))
    return outs


def _chain_planned(accl, rank, iters, plans_out):
    s = accl.create_buffer_like(_data(rank))
    r = accl.create_buffer(COUNT, np.float32)
    rs = accl.create_buffer(SCATTER, np.float32)
    pr = accl.create_buffer(COUNT, np.float32)

    def body(a):
        a.allreduce(s, r, COUNT, ReduceFunction.SUM)
        a.reduce_scatter(s, rs, SCATTER, ReduceFunction.SUM)
        if rank % 2 == 0:
            a.send(s, COUNT, (rank + 1) % NRANKS)
            a.recv(pr, COUNT, (rank - 1) % NRANKS)
        else:
            a.recv(pr, COUNT, (rank - 1) % NRANKS)
            a.send(s, COUNT, (rank + 1) % NRANKS)

    plan = accl.capture_plan(body)
    plans_out[rank] = plan
    outs = [(r.host.copy(), rs.host.copy(), pr.host.copy())]  # capture it
    for _ in range(iters - 1):
        plan.replay()
        outs.append((r.host.copy(), rs.host.copy(), pr.host.copy()))
    return outs


@pytest.mark.parametrize("world_cls", [EmuWorld, TpuWorld],
                         ids=["emu", "tpu-interpret"])
def test_capture_replay_bitwise_equals_eager(world_cls):
    """allreduce/reduce_scatter/sendrecv chains: replay == eager,
    bit for bit, iteration by iteration, on both engines."""
    iters = 3
    with world_cls(NRANKS) as w:
        ref = w.run(_chain_eager, iters)
    plans: dict = {}
    with world_cls(NRANKS) as w:
        got = w.run(_chain_planned, iters, plans)
    for rank in range(NRANKS):
        assert plans[rank].stats["replays"] == iters - 1
        for it in range(iters):
            for k, name in enumerate(("allreduce", "reduce_scatter",
                                      "sendrecv")):
                assert np.array_equal(got[rank][it][k],
                                      ref[rank][it][k]), \
                    f"{name} diverged at rank {rank} iter {it}"


def test_plan_async_replay_bitwise():
    """Async replay (ticket wait/check) produces the same results as
    sync replay on the TPU ring."""
    with TpuWorld(NRANKS) as w:
        store: dict = {}
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            s.sync_to_device()
            r = accl.create_buffer(COUNT, np.float32)
            store[rank] = (s, r)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM, from_fpga=True,
                to_fpga=True))

        w.run(cap)

        def rep(accl, rank):
            tickets = [plans[rank].replay(run_async=True)
                       for _ in range(4)]
            for t in tickets:
                assert t.wait(30)
                t.check()
            s, r = store[rank]
            r.sync_from_device()
            return r.host.copy()

        outs = w.run(rep)
    expected = sum(_data(rank) for rank in range(NRANKS))
    for rank in range(NRANKS):
        assert np.allclose(outs[rank], expected, atol=1e-4)


def test_replay_after_abort_raises_never_runs():
    """The invalidation contract: a replay after abort raises with the
    plan named invalid — it never silently runs on the fenced epoch."""
    with EmuWorld(NRANKS) as w:
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            r = accl.create_buffer(COUNT, np.float32)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM))

        w.run(cap)
        assert w.devices[0].plan_count() == 1
        w.accls[0].abort(0)

        def rep(accl, rank):
            with pytest.raises(ACCLError) as ei:
                plans[rank].replay()
            return str(ei.value)

        msgs = w.run(rep)
        for rank in range(NRANKS):
            assert "plan" in msgs[rank] or "aborted" in msgs[rank]
            assert plans[rank].invalidated or rank != 0
        # engine-side eviction: the aborted comm's plans are fenced
        assert w.devices[0].plan_count() == 0


def test_replay_after_shrink_raises_and_engine_evicts():
    """Satellite: plan-cache eviction fires on shrink_communicator for
    the emu backend too (not only on abort) — a healed world never
    replays a dead comm's plan."""
    with EmuWorld(NRANKS) as w:
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            r = accl.create_buffer(COUNT, np.float32)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM))

        w.run(cap)
        assert w.devices[0].plan_count() == 1

        def shrink_then_replay(accl, rank):
            new_id = accl.shrink_communicator(0, window_s=1.0)
            with pytest.raises(ACCLError):
                plans[rank].replay()
            return new_id

        ids = w.run(shrink_then_replay)
        assert len(set(ids)) == 1
        assert all(plans[r].invalidated for r in range(NRANKS))
        assert w.devices[0].plan_count() == 0


def test_replay_after_reset_errors_raises():
    """Satellite: eviction fires on reset_errors() too."""
    with EmuWorld(NRANKS) as w:
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            r = accl.create_buffer(COUNT, np.float32)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM))

        w.run(cap)
        w.reset_errors()
        assert w.devices[0].plan_count() == 0
        for rank in range(NRANKS):
            assert plans[rank].invalidated
            with pytest.raises(ACCLError):
                plans[rank].replay()


def test_tpu_ring_fenced_by_rebuild_gang_tables():
    """The grow path (rebuild_gang_tables) fences TPU plan rings."""
    with TpuWorld(2) as w:
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            s.sync_to_device()
            r = accl.create_buffer(COUNT, np.float32)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM, from_fpga=True,
                to_fpga=True))

        w.run(cap)
        w.engine.rebuild_gang_tables(0)

        def rep(accl, rank):
            with pytest.raises(ACCLError) as ei:
                plans[rank].replay()
            assert "invalidated" in str(ei.value) \
                or "fenced" in str(ei.value)

        w.run(rep)


def test_capture_time_sanitizer_finding_fails_capture():
    """A hazardous captured program fails capture_plan NAMING the
    finding (here: partial operand overlap, the buffer-overlap
    checker) — validated once at build time, not corrupted at
    iteration 10^6."""
    with TpuWorld(1) as w:
        accl = w.accls[0]
        buf = accl.create_buffer(COUNT, np.float32)
        shifted = buf.slice(8, COUNT // 2 + 8)
        with pytest.raises(ACCLError) as ei:
            accl.capture_plan(lambda a: a.allreduce(
                buf, shifted, COUNT // 2, ReduceFunction.SUM))
        msg = str(ei.value)
        assert "sanitizer finding" in msg
        assert "buffer-overlap" in msg


def test_capture_requires_collective_calls():
    with TpuWorld(1) as w:
        with pytest.raises(ACCLError) as ei:
            w.accls[0].capture_plan(lambda a: None)
        assert "no collective calls" in str(ei.value)


def test_plan_kill_switch_eager_lane():
    """ACCL_PLAN=0: capture_plan degrades to the eager fallback — same
    results through the unchanged per-call path, no engine plans."""
    plans_mod.set_enabled(False)
    try:
        with EmuWorld(NRANKS) as w:
            store: dict = {}

            def run(accl, rank):
                s = accl.create_buffer_like(_data(rank))
                r = accl.create_buffer(COUNT, np.float32)
                store[rank] = r
                plan = accl.capture_plan(lambda a: a.allreduce(
                    s, r, COUNT, ReduceFunction.SUM))
                assert plan.is_eager
                first = r.host.copy()
                plan.replay()
                assert np.array_equal(r.host, first)
                t = plan.replay(run_async=True)
                assert t.wait() and t.done
                t.check()
                return r.host.copy()

            outs = w.run(run)
            assert w.devices[0].plan_count() == 0
        with EmuWorld(NRANKS) as w:
            def eager(accl, rank):
                s = accl.create_buffer_like(_data(rank))
                r = accl.create_buffer(COUNT, np.float32)
                accl.allreduce(s, r, COUNT, ReduceFunction.SUM)
                return r.host.copy()

            ref = w.run(eager)
        for rank in range(NRANKS):
            assert np.array_equal(outs[rank], ref[rank])
    finally:
        plans_mod.set_enabled(True)


def test_auto_capture_lane():
    """ACCL_PLAN_AUTO=N: after N identical resident sync gang calls the
    world transparently arms a one-step ring and replays through it —
    results identical, engine counters prove the lane fired."""
    os.environ["ACCL_PLAN_AUTO"] = "3"
    try:
        with TpuWorld(NRANKS) as w:
            store: dict = {}

            def setup(accl, rank):
                s = accl.create_buffer_like(_data(rank))
                s.sync_to_device()
                r = accl.create_buffer(COUNT, np.float32)
                store[rank] = (s, r)

            w.run(setup)

            def loop(accl, rank):
                s, r = store[rank]
                for _ in range(10):
                    accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                   from_fpga=True, to_fpga=True)
                r.sync_from_device()
                return r.host.copy()

            outs = w.run(loop)
            stats = w.engine.stats
            assert stats["plan_auto_captures"] == 1
            assert stats["plan_replays"] >= 5
        expected = sum(_data(rank) for rank in range(NRANKS))
        for rank in range(NRANKS):
            assert np.allclose(outs[rank], expected, atol=1e-4)
    finally:
        del os.environ["ACCL_PLAN_AUTO"]


def test_auto_capture_refenced_after_abort():
    """Auto lane + abort: the fenced ring is dropped, the next call
    fast-fails on the aborted comm (never a silent stale replay), and
    after recovery the lane re-captures transparently."""
    os.environ["ACCL_PLAN_AUTO"] = "2"
    try:
        with TpuWorld(2) as w:
            store: dict = {}

            def setup(accl, rank):
                s = accl.create_buffer_like(_data(rank))
                s.sync_to_device()
                r = accl.create_buffer(COUNT, np.float32)
                store[rank] = (s, r)

            w.run(setup)

            def loop(accl, rank, iters):
                s, r = store[rank]
                for _ in range(iters):
                    accl.allreduce(s, r, COUNT, ReduceFunction.SUM,
                                   from_fpga=True, to_fpga=True)

            w.run(loop, 5)
            assert w.engine.stats["plan_auto_captures"] == 1
            w.accls[0].abort(0)

            def fenced(accl, rank):
                with pytest.raises(ACCLError) as ei:
                    loop(accl, rank, 1)
                assert "aborted" in str(ei.value)

            w.run(fenced)

            def recover(accl, rank):
                accl.reset_errors()

            w.run(recover)
            w.run(loop, 5)  # re-captures and finishes clean
            assert w.engine.stats["plan_auto_captures"] == 2
        expected = sum(_data(rank) for rank in range(2))
        for rank in range(2):
            s, r = store[rank]
            r.sync_from_device()
            assert np.allclose(r.host, expected, atol=1e-4)
    finally:
        del os.environ["ACCL_PLAN_AUTO"]


def test_plan_metrics_family():
    """plans/{captures,replays,invalidations} land in the metrics
    registry when metrics are enabled."""
    from accl_tpu.observability import metrics as _metrics

    if not _metrics.enabled():
        pytest.skip("metrics disabled in this environment")
    reg = _metrics.default_registry()
    before = {k: reg.counters().get(k, 0)
              for k in ("plans/captures", "plans/replays",
                        "plans/invalidations")}
    with EmuWorld(2) as w:
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_data(rank))
            r = accl.create_buffer(COUNT, np.float32)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, COUNT, ReduceFunction.SUM))
            plans[rank].replay()

        w.run(cap)
        w.accls[0].abort(0)
    after = reg.counters()
    assert after["plans/captures"] >= before["plans/captures"] + 2
    assert after["plans/replays"] >= before["plans/replays"] + 2
    assert after["plans/invalidations"] >= \
        before["plans/invalidations"] + 1
