"""r18 fused compute/communication overlap: the chunked pipelined ring.

Pins the four contracts of the fused lane:

- **Exactness** — the fp32 chunked collectives are BITWISE the C=1
  chain (same fold order as the Pallas ring), fused matmul-allreduce
  is bitwise the unfused matmul+psum sequence, and the int8 wire stays
  inside the r17 error bound with the quantize/dequantize fused into
  the chunk loop.
- **Opt-in dispatch** — with ACCL_FUSED unset every gang plan compiles
  with the fused bit off (bit-identical to the pre-r18 dispatch); the
  per-call ``fused=`` arg and the env default both arm it.
- **Observability** — under ACCL_DEVICE_TRACE the C=1 rows carry the
  sequential 3-phase stamp clock and C>1 rows the overlapped clock, so
  ``attribution.device_overlap`` reports the fused timeline's exposed
  fraction strictly below the sequential one.
- **Lifecycle** — plan capture/replay of a fused call is bitwise
  stable, and the abort fence fast-fails a fused call like any other.

The tier-3 Pallas kernels need a jax whose interpreter implements
remote DMA signals; on older jax those tests self-skip exactly like
the pallas ring test files do.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

try:
    from jax import shard_map
except ImportError:  # older jax spells it experimental
    from jax.experimental.shard_map import shard_map

from jax.sharding import NamedSharding, PartitionSpec as P

import accl_tpu.ops.fused as F
import accl_tpu.ops.ring as ring
from accl_tpu import ACCLError, ReduceFunction
from accl_tpu.backends.emu import EmuWorld
from accl_tpu.backends.tpu import TpuWorld
from accl_tpu.constants import DataType
from accl_tpu.observability import attribution
from accl_tpu.observability import trace as obs_trace
from accl_tpu.ops.quantized import DEFAULT_BLOCK

NR = 4


@pytest.fixture
def devtrace(monkeypatch):
    """Restore the device-trace gate, the fused-chunks cache, and the
    collector around each test."""
    yield monkeypatch
    ring._reset_device_trace_cache()
    F._reset_fused_chunks_cache()
    obs_trace.collector().clear()


def _mesh(n=NR, axis="dp"):
    if len(jax.devices()) < n:
        pytest.skip(f"needs a {n}-device mesh")
    from accl_tpu.parallel import make_mesh

    return make_mesh(**{axis: n})


def _smap(mesh, fn, in_spec, out_spec):
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_spec,
                         out_specs=out_spec, check_vma=False)
    except TypeError:  # older shard_map spells the flag check_rep
        return shard_map(fn, mesh=mesh, in_specs=in_spec,
                         out_specs=out_spec, check_rep=False)


def _sharded(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("dp", None)))


def _run_chunked(mesh, x, fn):
    xs = _sharded(mesh, x)
    body = _smap(mesh, lambda xb: fn(xb[0])[None], P("dp", None),
                 P("dp", None))
    return np.asarray(jax.jit(body)(xs))


# ---------------------------------------------------------------------------
# exactness: fp32 bitwise, int8 within the r17 bound
# ---------------------------------------------------------------------------
def test_pick_chunks_divides():
    assert F._pick_chunks(64, 4) == 4
    assert F._pick_chunks(6, 4) == 3  # largest divisor <= request
    assert F._pick_chunks(7, 4) == 1
    assert F._pick_chunks(4, None) >= 1


def test_chunked_allreduce_bitwise_vs_single_chain(devtrace, rng):
    mesh = _mesh()
    x = rng.standard_normal((NR, 256)).astype(np.float32)
    out_c1 = _run_chunked(
        mesh, x, lambda v: F.chunked_ring_all_reduce(v, "dp", chunks=1))
    out_c4 = _run_chunked(
        mesh, x, lambda v: F.chunked_ring_all_reduce(v, "dp", chunks=4))
    # chunking NEVER changes the bits: each chunk folds the same
    # (local + incoming) chain, only in C independent pipelines
    np.testing.assert_array_equal(out_c1, out_c4)
    np.testing.assert_allclose(out_c4[0], x.sum(axis=0), rtol=1e-5)


def test_chunked_allreduce_pads_ragged_lengths(devtrace, rng):
    mesh = _mesh()
    x = rng.standard_normal((NR, 100)).astype(np.float32)  # not % P*C
    out = _run_chunked(
        mesh, x, lambda v: F.chunked_ring_all_reduce(v, "dp", chunks=4))
    np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=1e-5)
    assert out.shape == x.shape


def test_chunked_reduce_scatter_bitwise_and_guard(devtrace, rng):
    mesh = _mesh()
    x = rng.standard_normal((NR, NR * 64)).astype(np.float32)
    out_c1 = _run_chunked(
        mesh, x,
        lambda v: F.chunked_ring_reduce_scatter(v, "dp", chunks=1))
    out_c4 = _run_chunked(
        mesh, x,
        lambda v: F.chunked_ring_reduce_scatter(v, "dp", chunks=4))
    np.testing.assert_array_equal(out_c1, out_c4)
    ref = x.sum(axis=0).reshape(NR, 64)
    for r in range(NR):
        np.testing.assert_allclose(out_c4[r], ref[r], rtol=1e-5)
    with pytest.raises(ValueError, match="divisible"):
        _run_chunked(
            mesh, rng.standard_normal((NR, NR * 64 + 1)).astype(
                np.float32),
            lambda v: F.chunked_ring_reduce_scatter(v, "dp"))


def test_chunked_all_gather_matches_jnp(devtrace, rng):
    mesh = _mesh()
    x = rng.standard_normal((NR, 96)).astype(np.float32)
    out = _run_chunked(
        mesh, x, lambda v: F.chunked_ring_all_gather(v, "dp", chunks=3))
    np.testing.assert_array_equal(out[0], x.reshape(-1))


def test_chunked_allreduce_int8_ef_within_r17_bound(devtrace, rng):
    """The fused int8 lane (per-hop requantize + error feedback inside
    the chunk loop) keeps the r17 bound: P * amax / 254 * 2."""
    mesh = _mesh()
    x = rng.standard_normal((NR, 512)).astype(np.float32)
    out = _run_chunked(
        mesh, x, lambda v: F.chunked_ring_all_reduce(
            v, "dp", chunks=4, wire=(DEFAULT_BLOCK, True)))
    exact = x.sum(axis=0, dtype=np.float64)
    bound = NR * np.abs(x).max() / 254 * 2
    assert np.abs(out[0] - exact).max() <= bound


def test_fused_matmul_allreduce_bitwise_vs_unfused(devtrace, rng):
    """allreduce-into-matmul: the pipelined per-hop (dot_block + fold)
    chain is bitwise the unfused matmul+psum sequence (same fp32
    contraction per row block, same fold order as the C=1 chain)."""
    mesh = _mesh()
    K, N = 32, 48
    x = rng.standard_normal((NR, 64, K)).astype(np.float32)
    w = rng.standard_normal((NR, K, N)).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, None)))
    ws = jax.device_put(w, NamedSharding(mesh, P("dp", None, None)))

    def fused(xb, wb):
        return F.fused_matmul_allreduce(xb[0], wb[0], axis="dp",
                                        use_pallas=False, chunks=4)[None]

    def seq(xb, wb):
        from jax import lax

        part = jnp.dot(xb[0], wb[0],
                       preferred_element_type=jnp.float32)
        return lax.psum(part, "dp")[None]

    spec = (P("dp", None, None), P("dp", None, None))
    out_f = np.asarray(jax.jit(_smap(mesh, fused, spec,
                                     P("dp", None, None)))(xs, ws))
    out_s = np.asarray(jax.jit(_smap(mesh, seq, spec,
                                     P("dp", None, None)))(xs, ws))
    # same fp32 contraction per row block; the ring fold sums in ring
    # order vs psum's tree, so allclose (not bitwise) across the seam
    np.testing.assert_allclose(out_f, out_s, rtol=1e-5, atol=1e-4)
    ref = np.einsum("rmk,rkn->mn", x, w)
    np.testing.assert_allclose(out_f[0], ref, rtol=1e-4, atol=1e-3)


def test_fused_expert_ffn_matches_dispatch_combine(devtrace, rng):
    """reduce_scatter-into-MoE-dispatch: capacity-chunked a2a -> ffn ->
    a2a equals the expert_dispatch/expert_combine sequence bitwise."""
    from accl_tpu.parallel.strategies import (expert_combine,
                                              expert_dispatch)

    mesh = _mesh(NR, "ep")
    T, D = 32, 16
    x = rng.standard_normal((NR, T, D)).astype(np.float32)
    idxs = rng.integers(0, NR, size=(NR, T)).astype(np.int32)

    def ffn(t):
        return t * 2.0 + 1.0

    def fused(xb, ib):
        return F.fused_expert_ffn(xb[0], ib[0], ffn, axis="ep",
                                  chunks=4)[None]

    def seq(xb, ib):
        inp, info = expert_dispatch(xb[0], ib[0], "ep")
        return expert_combine(ffn(inp), info, "ep")[None]

    xs = jax.device_put(x, NamedSharding(mesh, P("ep", None, None)))
    is_ = jax.device_put(idxs, NamedSharding(mesh, P("ep", None)))

    def smap(fn):
        try:
            return shard_map(fn, mesh=mesh,
                             in_specs=(P("ep", None, None),
                                       P("ep", None)),
                             out_specs=P("ep", None, None),
                             check_vma=False)
        except TypeError:
            return shard_map(fn, mesh=mesh,
                             in_specs=(P("ep", None, None),
                                       P("ep", None)),
                             out_specs=P("ep", None, None),
                             check_rep=False)

    out_f = np.asarray(jax.jit(smap(fused))(xs, is_))
    out_s = np.asarray(jax.jit(smap(seq))(xs, is_))
    np.testing.assert_array_equal(out_f, out_s)


# ---------------------------------------------------------------------------
# device-trace stamp clocks + device_overlap A/B
# ---------------------------------------------------------------------------
def _trace_allreduce(mesh, chunks, collective):
    x = np.stack([np.arange(256, dtype=np.float32) + r
                  for r in range(NR)])
    _run_chunked(mesh, x, lambda v: F.chunked_ring_all_reduce(
        v, "dp", chunks=chunks, collective=collective))


def test_stamp_clock_c1_sequential_c4_overlapped(devtrace):
    """C=1 has one chain (nothing pipelines against it), so its rows
    carry the honest sequential 3-phase clock; C>1 rows carry the
    overlapped clock where slot i+1's xfer covers slot i's reduce."""
    devtrace.setenv("ACCL_DEVICE_TRACE", "1")
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()
    mesh = _mesh()
    _trace_allreduce(mesh, 1, "seq_ar")
    _trace_allreduce(mesh, 4, "fused_ar")
    recs = obs_trace.collector().device_records()
    by_coll = {}
    for rec in recs:
        by_coll.setdefault(rec["collective"], []).extend(rec["rows"])
    assert set(by_coll) == {"seq_ar", "fused_ar"}
    fields = obs_trace.DEVICE_TRACE_FIELDS
    for raw in by_coll["seq_ar"]:
        row = dict(zip(fields, raw))
        assert row["seq_send"] == 3 * row["step"]
        assert row["seq_wait"] == row["seq_send"] + 1
        assert row["seq_phase"] == row["seq_send"] + 2
        assert row["tx_peer"] == (row["rank"] + 1) % NR
        assert row["rx_peer"] == (row["rank"] - 1) % NR
        assert row["tx_bytes"] > 0
    for raw in by_coll["fused_ar"]:
        row = dict(zip(fields, raw))
        assert row["seq_send"] == 2 * row["step"]
        assert row["seq_wait"] == row["seq_send"] + 2
        assert row["seq_phase"] == row["seq_send"] + 4
    # RS + AG phases, (P-1)*C slots each
    assert len(by_coll["fused_ar"]) == NR * 2 * (NR - 1) * 4
    assert len(by_coll["seq_ar"]) == NR * 2 * (NR - 1)


def test_device_overlap_fused_below_sequential(devtrace):
    """attribution.device_overlap on the stamp timeline: the C=1 clock
    reports full exposure (1.0), the pipelined clock reports ~1/slots
    — the in-kernel half of the r18 gate criterion."""
    devtrace.setenv("ACCL_DEVICE_TRACE", "1")
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()
    mesh = _mesh()
    _trace_allreduce(mesh, 1, "seq_ar")
    _trace_allreduce(mesh, 4, "fused_ar")
    rep = attribution.device_overlap(obs_trace.collector().to_perfetto())
    seq = rep["collectives"]["seq_ar"]
    fus = rep["collectives"]["fused_ar"]
    assert seq["exposed_fraction"] == pytest.approx(1.0)
    assert fus["exposed_fraction"] < seq["exposed_fraction"]
    assert fus["recovered_mxu_fraction"] > 0.5
    assert seq["ranks"] == fus["ranks"] == NR


def test_device_trace_off_emits_nothing(devtrace):
    devtrace.delenv("ACCL_DEVICE_TRACE", raising=False)
    ring._reset_device_trace_cache()
    obs_trace.collector().clear()
    mesh = _mesh()
    _trace_allreduce(mesh, 4, "fused_ar")
    assert obs_trace.collector().device_records() == []


# ---------------------------------------------------------------------------
# driver dispatch: opt-in, exactness, plan replay, abort fence
# ---------------------------------------------------------------------------
def _wdata(rank, count=256):
    return (np.random.default_rng(7 + rank)
            .standard_normal(count).astype(np.float32))


def test_driver_fused_allreduce_matches_unfused():
    count = 256
    with TpuWorld(NR) as w:

        def body(fused):
            def run(accl, rank):
                s = accl.create_buffer_like(_wdata(rank, count))
                r = accl.create_buffer(count, np.float32)
                accl.allreduce(s, r, count, ReduceFunction.SUM,
                               fused=fused)
                return r.host.copy()

            return run

        out_f = w.run(body(True))
        out_u = w.run(body(False))
        # every plan compiled for the fused calls carries the fused bit,
        # and the unfused ones the r2 dispatch (fn_args[9])
        flags = {p["fn_args"][9] for p in
                 w.engine._gang_plans.values()}
        assert flags == {True, False}
    exact = sum(_wdata(r, count) for r in range(NR))
    for r in range(NR):
        np.testing.assert_allclose(out_f[r], exact, atol=1e-4)
        np.testing.assert_allclose(out_f[r], out_u[r], atol=1e-4)


def test_driver_fused_reduce_scatter_and_int8():
    count = 256  # per-rank result length
    with TpuWorld(NR) as w:

        def run(accl, rank):
            data = np.tile(_wdata(rank, count), NR)
            s = accl.create_buffer_like(data)
            r = accl.create_buffer(count, np.float32)
            accl.reduce_scatter(s, r, count, ReduceFunction.SUM,
                                fused=True)
            q = accl.create_buffer(count * NR, np.float32)
            a = accl.create_buffer_like(np.tile(_wdata(rank, count), NR))
            accl.allreduce(a, q, count * NR, ReduceFunction.SUM,
                           compress_dtype=DataType.int8, fused=True)
            return r.host.copy(), q.host.copy()

        outs = w.run(run)
    exact = sum(_wdata(r, count) for r in range(NR))
    tiled = np.tile(exact, NR)
    amax = max(np.abs(_wdata(r, count)).max() for r in range(NR))
    bound = NR * amax / 254 * 2
    for r in range(NR):
        rs, ar8 = outs[r]
        np.testing.assert_allclose(rs, exact, atol=1e-4)
        assert np.abs(ar8 - tiled).max() <= bound + 1e-4


def test_accl_fused_env_default(monkeypatch):
    """ACCL_FUSED=1 arms the driver default; unset leaves every gang
    plan on the pre-r18 dispatch (the bit-identity contract)."""
    monkeypatch.delenv("ACCL_FUSED", raising=False)
    count = 64
    with TpuWorld(2) as w:
        assert all(a._fused_default is False for a in w.accls)

        def run(accl, rank):
            s = accl.create_buffer_like(_wdata(rank, count))
            r = accl.create_buffer(count, np.float32)
            accl.allreduce(s, r, count, ReduceFunction.SUM)
            return r.host.copy()

        w.run(run)
        assert all(p["fn_args"][9] is False
                   for p in w.engine._gang_plans.values())
    monkeypatch.setenv("ACCL_FUSED", "1")
    with TpuWorld(2) as w:
        assert all(a._fused_default is True for a in w.accls)
        w.run(run)
        assert any(p["fn_args"][9] for p in
                   w.engine._gang_plans.values())


def test_selection_policy_arms_fused_descriptor():
    """A table cell won by the ``fused`` lane arms the memoized call
    descriptor on first consult: subsequent dispatch rides the fused
    gang plan with no per-call flag from the caller."""
    from accl_tpu.tuning.autotune import (SelectionPolicy,
                                          SelectionTable, cell_key)

    count = 256  # 1 KiB fp32 -> the <=1KiB bucket
    tab = SelectionTable(
        {cell_key("allreduce", "float32", "<=1KiB", NR): {
            "algorithm": "fused", "busbw_GBps": 1.0,
            "static_busbw_GBps": 0.5, "bytes": count * 4,
            "overlap": 0.25}},
        {"backend": "tpu", "nranks": NR, "dtype": "float32"})
    with TpuWorld(NR) as w:
        for a in w.accls:
            a._tune_policy = SelectionPolicy(tab)

        def run(accl, rank):
            s = accl.create_buffer_like(_wdata(rank, count))
            r = accl.create_buffer(count, np.float32)
            accl.allreduce(s, r, count, ReduceFunction.SUM)
            return r.host.copy()

        outs = w.run(run)
        assert any(p["fn_args"][9] for p in
                   w.engine._gang_plans.values())
    exact = sum(_wdata(r, count) for r in range(NR))
    for r in range(NR):
        np.testing.assert_allclose(outs[r], exact, atol=1e-4)


def test_plan_capture_replay_fused_bitwise():
    """A captured fused call replays bitwise-stable: N replays produce
    exactly the bytes of N eager fused calls."""
    count = 256
    with TpuWorld(NR) as w:
        store: dict = {}
        plans: dict = {}

        def cap(accl, rank):
            s = accl.create_buffer_like(_wdata(rank, count))
            s.sync_to_device()
            r = accl.create_buffer(count, np.float32)
            store[rank] = (s, r)
            plans[rank] = accl.capture_plan(lambda a: a.allreduce(
                s, r, count, ReduceFunction.SUM, from_fpga=True,
                to_fpga=True, fused=True))

        w.run(cap)

        def rep(accl, rank):
            outs = []
            for _ in range(3):
                plans[rank].replay()
                s, r = store[rank]
                r.sync_from_device()
                outs.append(r.host.copy())
            return outs

        outs = w.run(rep)
    exact = sum(_wdata(r, count) for r in range(NR))
    for rank in range(NR):
        first = outs[rank][0]
        np.testing.assert_allclose(first, exact, atol=1e-4)
        for rep_out in outs[rank][1:]:
            np.testing.assert_array_equal(first, rep_out)


def test_fused_call_abort_fence_raises():
    """The abort fast-fail precedes dispatch: a fused call on a fenced
    communicator raises COMM_ABORTED, never runs."""
    count = 64
    with EmuWorld(2) as w:

        def run(accl, rank):
            accl.abort(0)
            s = accl.create_buffer_like(_wdata(rank, count))
            r = accl.create_buffer(count, np.float32)
            with pytest.raises(ACCLError, match="aborted"):
                accl.allreduce(s, r, count, ReduceFunction.SUM,
                               fused=True)

        w.run(run)


# ---------------------------------------------------------------------------
# models: the fused flag is parity-neutral
# ---------------------------------------------------------------------------
def test_transformer_tp_forward_fused_bitwise(devtrace, rng):
    from accl_tpu.models import transformer as tf

    mesh = _mesh(2, "tp")
    cfg = tf.ModelConfig(vocab=64, d_model=32, n_heads=2, d_head=8,
                         n_layers=1, d_ff=64)
    params = tf.init_params(np.random.default_rng(0), cfg)
    tokens = rng.integers(0, cfg.vocab, size=(2, 8)).astype(np.int32)

    def fwd(fused):
        def body(p, t):
            return tf.forward(p, t, cfg, tp_axis="tp", fused=fused)

        specs = jax.tree.map(lambda _: P(), params)
        try:
            f = shard_map(body, mesh=mesh, in_specs=(specs, P()),
                          out_specs=P(), check_vma=False)
        except TypeError:
            f = shard_map(body, mesh=mesh, in_specs=(specs, P()),
                          out_specs=P(), check_rep=False)
        return np.asarray(jax.jit(f)(params, tokens))

    np.testing.assert_array_equal(fwd(False), fwd(True))


# ---------------------------------------------------------------------------
# tier 3: the hand-scheduled Pallas kernels (skip on jax without
# remote-DMA interpret support, like the pallas ring tests)
# ---------------------------------------------------------------------------
def test_fused_matmul_allreduce_pallas_kernel(devtrace, rng):
    mesh = _mesh()
    K, N = 32, 128
    x = rng.standard_normal((NR, 128, K)).astype(np.float32)
    w = rng.standard_normal((NR, K, N)).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, None)))
    ws = jax.device_put(w, NamedSharding(mesh, P("dp", None, None)))

    def body(xb, wb):
        return F.fused_matmul_allreduce_pallas(
            xb[0], wb[0], axis="dp", interpret=True)[None]

    spec = (P("dp", None, None), P("dp", None, None))
    try:
        out = np.asarray(jax.jit(_smap(mesh, body, spec,
                                       P("dp", None, None)))(xs, ws))
    except NotImplementedError as e:  # jax-skew: no remote DMA interp
        pytest.skip(f"pallas interpreter lacks remote DMA: {e}")
    ref = np.einsum("rmk,rkn->mn", x, w)
    np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-3)
