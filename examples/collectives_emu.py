"""Collectives on the CPU emulator rung — the 60-second tour.

Runs a 4-rank world on the native C++ engine (in-process transport),
exercising the driver the way the reference's getting-started flow does
(reference: test/host/xrt/src/test.cpp basic tests + README): buffers,
send/recv over both wire protocols, allreduce with on-path arithmetic,
fp16 wire compression, and a sub-communicator.

    python examples/collectives_emu.py
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from accl_tpu.constants import DataType, ReduceFunction
from accl_tpu.utils.bringup import Design, initialize_world

NRANKS = 4
COUNT = 1024  # 4 KB fp32 — above the default 1 KB eager threshold


def rank_main(world, r, results):
    a = world.accls[r]

    # buffers: host numpy span + device residence (the reference's
    # FPGABuffer model; collective calls sync them automatically)
    src = a.create_buffer(COUNT, np.float32)
    dst = a.create_buffer(COUNT, np.float32)
    src.host[:] = np.arange(COUNT, dtype=np.float32) + 1000 * r

    # 1. neighbor send/recv, async submit: 4 KB rides the RENDEZVOUS
    # protocol (one-sided write once the receiver posts its landing
    # address), so the send completes only after the matching recv —
    # submit it async and wait after our own recv (the reference's
    # call_async flow)
    peer = (r + 1) % NRANKS
    frm = (r - 1) % NRANKS
    sreq = a.send(src, COUNT, dst=peer, tag=7, run_async=True)
    a.recv(dst, COUNT, src=frm, tag=7)
    sreq.wait()
    sreq.check()  # raises (with the flight record) on error OR timeout
    assert dst.host[0] == 1000 * frm, (r, dst.host[0])

    # 2. allreduce with on-path sum (the reduce_ops lane's role)
    out = a.create_buffer(COUNT, np.float32)
    a.allreduce(src, out, COUNT, ReduceFunction.SUM)
    expect = (np.arange(COUNT, dtype=np.float32) * NRANKS
              + 1000 * sum(range(NRANKS)))
    np.testing.assert_allclose(out.host, expect)

    # 3. the same allreduce with fp16 wire compression (the
    # hp_compression lane): every hop moves half the bytes
    outc = a.create_buffer(COUNT, np.float32)
    a.allreduce(src, outc, COUNT, ReduceFunction.SUM,
                compress_dtype=DataType.float16)
    np.testing.assert_allclose(outc.host, expect, rtol=2e-3, atol=4.0)

    # 4. sub-communicator: even ranks only (reference test_multicomm)
    members = list(range(0, NRANKS, 2))
    if r in members:
        cid = a.create_communicator(members)
        sub_out = a.create_buffer(COUNT, np.float32)
        a.allreduce(src, sub_out, COUNT, ReduceFunction.SUM, comm_id=cid)
        sub_expect = (np.arange(COUNT, dtype=np.float32) * len(members)
                      + 1000 * sum(members))
        np.testing.assert_allclose(sub_out.host, sub_expect)

    results[r] = "ok"


def main():
    world = initialize_world(Design.EMU_INPROC, nranks=NRANKS)
    try:
        results = {}
        threads = [threading.Thread(target=rank_main,
                                    args=(world, r, results))
                   for r in range(NRANKS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results.get(r) == "ok" for r in range(NRANKS)), results
        print(f"collectives_emu: {NRANKS} ranks x rendezvous send/recv + "
              "allreduce + compressed allreduce + sub-communicator: OK")
    finally:
        world.close()


if __name__ == "__main__":
    main()
