"""Device-initiated collectives — the reference's vadd_put demo.

In the reference, an FPGA compute kernel streams its result straight
into the CCLO and issues `stream_put` itself, no host on the data path
(kernels/plugins/vadd_put/vadd_put.cpp:23-86 through
driver/hls/accl_hls.h).  Here the same roles: a "compute kernel" per
rank pushes x+1 into its engine stream and fires stream_put at its
neighbor; the neighbor's kernel pulls the payload from its own stream.
A second act shows a kernel-issued allreduce by raw device addresses
(the client_arbiter's second-client path).

    python examples/device_vadd_put.py
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from accl_tpu.constants import DataType, ReduceFunction
from accl_tpu.device_api import ACCLCommand, ACCLData
from accl_tpu.utils.bringup import Design, initialize_world

NRANKS = 2
COUNT = 64
STREAM_ID = 9


def rank_main(world, r, results):
    a = world.accls[r]
    cmd = ACCLCommand(a.device, arithcfg=a.arithcfg_id(DataType.float32))
    data = ACCLData(a.device)

    # act 1: vadd_put — compute x+1, stream it out, remote kernel pulls
    x = np.arange(COUNT, dtype=np.float32) + 100 * r
    data.push(x + 1.0)                       # the "vadd" compute
    cmd.stream_put(COUNT, stream_id=STREAM_ID, dst=(r + 1) % NRANKS)
    got = data.pull(COUNT, np.float32, stream_id=STREAM_ID)
    frm = (r - 1) % NRANKS
    np.testing.assert_allclose(
        got, np.arange(COUNT, dtype=np.float32) + 100 * frm + 1.0)

    # act 2: kernel-issued allreduce by raw device addresses
    src = a.create_buffer(COUNT, np.float32)
    dst = a.create_buffer(COUNT, np.float32)
    src.host[:] = x
    src.sync_to_device()
    cmd.allreduce(COUNT, int(ReduceFunction.SUM), src.address,
                  dst.address)
    dst.sync_from_device()
    expect = sum(np.arange(COUNT, dtype=np.float32) + 100 * m
                 for m in range(NRANKS))
    np.testing.assert_allclose(dst.host, expect)

    results[r] = "ok"


def main():
    world = initialize_world(Design.EMU_INPROC, nranks=NRANKS)
    try:
        results = {}
        threads = [threading.Thread(target=rank_main,
                                    args=(world, r, results))
                   for r in range(NRANKS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results.get(r) == "ok" for r in range(NRANKS)), results
        print("device_vadd_put: stream compute -> stream_put -> remote "
              "pull + kernel-issued allreduce: OK")
    finally:
        world.close()


if __name__ == "__main__":
    main()
