"""Serve the flagship transformer: train briefly, then generate with
the KV cache.

The inference tour: a GQA + RoPE + swiglu model (the Llama-family
dialect) takes a few training steps, then `generate` runs one
jit-compiled program — prefill banks the prompt's K/V in the grouped
cache, and a lax.scan of decode steps extends it one token at a time.
Teacher-forced parity with the training forward is the tested contract
(tests/test_decode.py); this tour shows the user-facing surface.

    python examples/generate_text.py

Set ACCL_FUSED=1 to route any tensor-parallel collectives in the
forward/decode path through the r18 fused lane (no-op on this
single-device demo, but the flag plumbs through `generate`/`prefill`
the same way it does on a tp-sharded serving mesh).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# pin the CPU platform unless explicitly told to use an accelerator:
# querying the backend would CLAIM it, and a busy shared chip blocks
# the claim indefinitely (see docs/troubleshooting.md)
if not os.environ.get("ACCL_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from accl_tpu.models import ModelConfig, forward, init_params
from accl_tpu.models.decode import decode_step, generate, init_kv_cache, prefill
from accl_tpu.models.transformer import loss_fn


def main() -> None:
    fused = os.environ.get("ACCL_FUSED", "0") not in ("", "0")
    cfg = ModelConfig(vocab=64, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_head=16, d_ff=128,
                      mlp="swiglu", rope=True)
    rng = np.random.default_rng(0)
    params = init_params(rng, cfg)

    # a few SGD steps on a toy copy task so generation is not pure noise
    data = jnp.asarray(rng.integers(0, cfg.vocab, size=(4, 32),
                                    dtype=np.int32))
    def mean_loss(p, t):  # loss_fn returns (sum, count) per device
        s, c = loss_fn(p, t, cfg)
        return s / c

    grad_fn = jax.jit(jax.grad(mean_loss))
    n_steps = int(os.environ.get("ACCL_EXAMPLE_STEPS", "3"))
    for _ in range(n_steps):
        grads = grad_fn(params, data)
        params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
    print(f"trained {n_steps} steps")

    prompt = data[:2, :8]
    out = generate(params, prompt, cfg, max_new=6, fused=fused)
    print("generated:", np.asarray(out).tolist())

    # the cache contract, demonstrated: teacher-forced decode logits
    # equal the training forward's, position for position
    tokens = data[:2, :12]
    want = np.asarray(forward(params, tokens, cfg))
    cache = init_kv_cache(cfg, 2, tokens.shape[1])
    lg, cache = prefill(params, tokens[:, :6], cache, cfg)
    np.testing.assert_allclose(np.asarray(lg), want[:, :6], rtol=3e-5,
                               atol=3e-5)
    step_fn = jax.jit(decode_step, static_argnames=("cfg",))
    for t in range(6, tokens.shape[1]):
        lg, cache = step_fn(params, tokens[:, t], cache, cfg)
        np.testing.assert_allclose(np.asarray(lg), want[:, t],
                                   rtol=3e-5, atol=3e-5)
    print("decode parity OK")
    print("OK")


if __name__ == "__main__":
    main()
