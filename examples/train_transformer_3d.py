"""Train the flagship transformer on a dp x tp x sp device mesh.

The distributed-training tour: an 8-device mesh (virtual CPU devices
here — the same code runs unchanged on a TPU slice over ICI) carved
into data, tensor, and sequence axes; parameters sharded by
PartitionSpec; the train step jitted once over the mesh with gradient
sync, tensor-parallel matmuls, and zigzag ring attention over the
sequence axis all compiled into one SPMD program.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_transformer_3d.py

Set ACCL_FUSED=1 to route the tensor-parallel allreduces through the
r18 fused lane (chunked collectives drained under the MXU — bitwise
vs the default schedule; see docs/performance.md).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from accl_tpu.utils.platform import ensure_host_device_count

ensure_host_device_count(8)

import jax

# pin the CPU platform unless explicitly told to use an accelerator:
# querying the backend would CLAIM it, and a busy shared chip blocks
# the claim indefinitely (see docs/troubleshooting.md)
if not os.environ.get("ACCL_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from accl_tpu.models.transformer import ModelConfig, init_params, make_train_step, shard_params
from accl_tpu.parallel.mesh import make_mesh
from accl_tpu.parallel.ring_attention import zigzag_indices

B, T = 4, 64
STEPS = int(os.environ.get("ACCL_EXAMPLE_STEPS", "5"))
FUSED = os.environ.get("ACCL_FUSED", "0") not in ("", "0")


def main():
    mesh = make_mesh(dp=2, tp=2, sp=2)
    # n_kv_heads=2: grouped-query attention (the Llama-family layout).
    # On TPU the flash ring reads the grouped layout without expansion
    # and rotates half-size K/V shards; this CPU demo's dense ring
    # expands per q head first (the reference-path contract)
    cfg = ModelConfig(vocab=256, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_head=16, d_ff=128,
                      sp_schedule="zigzag")
    params = init_params(np.random.default_rng(0), cfg)

    step, (param_specs, tok_spec) = make_train_step(mesh, cfg, lr=1e-2,
                                                    fused=FUSED)
    params = shard_params(params, mesh, cfg)

    # zigzag: feed tokens in the load-balanced causal layout (rank i
    # holds sequence chunk i and its mirror — every ring hop does
    # identical causal work on every rank)
    perm = np.asarray(zigzag_indices(T, 2))
    rng = np.random.default_rng(1)

    for i in range(STEPS):
        tokens = rng.integers(0, cfg.vocab, (B, T))[:, perm]
        tokens = jax.device_put(jnp.asarray(tokens),
                                NamedSharding(mesh, tok_spec))
        params, loss = step(params, tokens)
        print(f"step {i}: loss {float(loss):.4f}")

    lane = "fused (r18 chunked overlap)" if FUSED else "default"
    print(f"train_transformer_3d: {STEPS} steps on dp=2 x tp=2 x sp=2 "
          f"({len(jax.devices())} devices, {lane} tp collectives): OK")


if __name__ == "__main__":
    main()
