"""The same driver API on the TPU backend — switching rungs, not code.

The point of the rung ladder: the imperative per-rank driver program
from examples/collectives_emu.py runs unchanged against the TPU
backend, where each rank's buffers live on a device of the mesh and
every matched gang of calls executes as ONE AOT-compiled XLA SPMD
collective over ICI (backends/tpu.py).  Here: 4 virtual CPU devices
standing in for 4 TPU chips — on real hardware only the platform pin
changes.

    python examples/collectives_tpu_gang.py
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from accl_tpu.utils.platform import ensure_host_device_count

ensure_host_device_count(4)

import jax

# pin CPU unless told otherwise — a busy shared chip blocks the claim
# (docs/troubleshooting.md)
if not os.environ.get("ACCL_EXAMPLE_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

from accl_tpu.constants import DataType, ReduceFunction
from accl_tpu.utils.bringup import Design, initialize_world

NRANKS = 4
COUNT = 1024


def rank_main(world, r, results):
    a = world.accls[r]
    src = a.create_buffer(COUNT, np.float32)
    out = a.create_buffer(COUNT, np.float32)
    src.host[:] = np.arange(COUNT, dtype=np.float32) + 1000 * r

    # the gang scheduler pairs the four ranks' descriptors and runs one
    # compiled psum over the mesh (repeat calls hit the plan cache)
    a.allreduce(src, out, COUNT, ReduceFunction.SUM)
    expect = (np.arange(COUNT, dtype=np.float32) * NRANKS
              + 1000 * sum(range(NRANKS)))
    np.testing.assert_allclose(out.host, expect, rtol=1e-5)

    # compressed wire representation on the same backend
    outc = a.create_buffer(COUNT, np.float32)
    a.allreduce(src, outc, COUNT, ReduceFunction.SUM,
                compress_dtype=DataType.float16)
    np.testing.assert_allclose(outc.host, expect, rtol=2e-3, atol=4.0)

    results[r] = "ok"


def main():
    world = initialize_world(Design.TPU, nranks=NRANKS)
    try:
        results = {}
        threads = [threading.Thread(target=rank_main,
                                    args=(world, r, results))
                   for r in range(NRANKS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(results.get(r) == "ok" for r in range(NRANKS)), results
        print(f"collectives_tpu_gang: {NRANKS} ranks x gang allreduce "
              "(plain + fp16 wire) as compiled SPMD collectives: OK")
    finally:
        world.close()


if __name__ == "__main__":
    main()
