// Eager RX buffer pool with notification matching.
//
// Equivalent of the reference rx-buffer offload engines: a table of spare
// buffers cycling IDLE -> RESERVED -> IDLE, a notification queue written
// at ingress, and a seek operation matching (src, tag, seqn) with
// wildcard tags (reference: kernels/cclo/hls/rxbuf_offload/
// rxbuf_enqueue.cpp / rxbuf_dequeue.cpp / rxbuf_seek.cpp; status machine
// ccl_offload_control.h:287-290).  Overflowing ingress parks in a staging
// queue, modeling the transport backpressure the reference gets from its
// TCP/RDMA stacks when no spare buffer is free.
#pragma once

#include <algorithm>

#include "common.hpp"
#include "transport.hpp"

namespace accl {

struct RxNotification {
  uint32_t index = 0;  // buffer table index
  uint32_t bytes = 0;  // payload bytes in buffer (wire size)
  uint32_t tag = 0;
  uint32_t src = 0;
  uint32_t seqn = 0;
  uint32_t comm = 0;
  uint32_t compressed = 0;
};

class RxPool {
 public:
  enum class Status : uint8_t { IDLE = 0, RESERVED = 1 };

  void configure(uint32_t nbufs, uint64_t bufsize) {
    MutexLock g(m_);
    bufs_.assign(nbufs, std::vector<uint8_t>(bufsize));
    status_.assign(nbufs, Status::IDLE);
    bufsize_.store(bufsize);
    occupancy_.store(0);  // fresh table: nothing RESERVED yet
    // The transport (and ingress) is live from engine construction, so a
    // peer racing ahead through bring-up can deliver BEFORE this pool is
    // configured; those deposits staged against zero buffers and — with
    // no reserved buffer ever consumed — release() would never drain
    // them: a silent permanent loss that deadlocks the first collective
    // (both sides retry forever).  Install them now.
    while (!staging_.empty()) {
      int idx = find_idle_locked();
      if (idx < 0) break;
      Message msg = std::move(staging_.front());
      staging_.pop_front();
      install_locked(uint32_t(idx), msg);
    }
  }

  uint64_t buf_size() const { return bufsize_.load(); }

  // Ingress path (called from the transport sink).
  void deposit(Message&& msg) {
    {
      MutexLock g(m_);
      int idx = find_idle_locked();
      if (idx >= 0) {
        install_locked(uint32_t(idx), msg);
        return;
      }
      staging_.push_back(std::move(msg));
      uint64_t s = staging_.size(), h = staged_hwm_.load();
      while (s > h && !staged_hwm_.compare_exchange_weak(h, s)) {
      }
      // pool exhausted: this deposit parked in staging, which only
      // release() drains — the precondition for cross-comm pinning.
      // Tell the model checker so exhaustion-induced timeout orderings
      // become explorable state (no-op outside detsched runs).
      det_note_pressure();
    }
  }

  // ---- occupancy telemetry (r14 engine stats): RESERVED buffers now /
  // high-water, staged-overflow depth/high-water, pending notification
  // count.  Atomics written under m_ where they shadow guarded state,
  // readable lock-free by the sampler thread — a stale read is fine,
  // telemetry is not a synchronization primitive. ----
  uint64_t occupancy() const { return occupancy_.load(); }
  uint64_t occupancy_hwm() const { return occupancy_hwm_.load(); }
  uint64_t staged() const {
    MutexLock g(m_);
    return staging_.size();
  }
  uint64_t staged_hwm() const { return staged_hwm_.load(); }
  uint64_t pending() const { return notif_.size(); }

  // Seek a notification matching (comm, src, tag|TAG_ANY, seqn); blocks up
  // to `timeout`.  Returns nullopt on timeout (-> RECEIVE_TIMEOUT_ERROR).
  std::optional<RxNotification> seek(uint32_t comm, uint32_t src, uint32_t tag,
                                     uint32_t seqn,
                                     std::chrono::nanoseconds timeout) {
    return notif_.pop_match(
        [=](const RxNotification& n) {
          return n.comm == comm && n.src == src && n.seqn == seqn &&
                 (tag == TAG_ANY || n.tag == tag);
        },
        timeout);
  }

  // Sequence-number discipline (reference: dma_mover.cpp:579-611 checks
  // seqn at seek; PACK_SEQ_NUMBER_ERROR eth_ack :333-353): a pending
  // notification from the same (comm, src, tag) with a seqn BEHIND the
  // expected one is a stale duplicate — its slot can never match again,
  // so it is evicted and the buffer released.  Ahead-of-sequence
  // entries stay queued: the per-src seqn counter is shared across
  // tags, so a recv posted in a different tag order than the sends is
  // a legal future match, not corruption (a past regression evicted
  // those too and turned a recoverable timeout into
  // PACK_SEQ_NUMBER_ERROR).  Returns the number evicted.
  // Non-destructive: is any notification queued on (comm, src, tag)?
  // After a failed seek this means a wrong-seqn segment is present —
  // the sequence-error signal — without consuming entries that could
  // still match a differently-ordered future recv.
  bool has_route_entry(uint32_t comm, uint32_t src, uint32_t tag) const {
    return notif_.any([=](const RxNotification& x) {
      return x.comm == comm && x.src == src &&
             (tag == TAG_ANY || x.tag == tag);
    });
  }

  // Forced reclamation of a broken route: evict EVERY queued entry on
  // (comm, src, tag) regardless of seqn.  Used when the pool is under
  // pressure (no idle buffer) and a sequence error was just classified
  // on the route — a genuinely corrupted stream must not pin buffers
  // until the whole world starves.  Returns the number evicted.
  int evict_route(uint32_t comm, uint32_t src, uint32_t tag) {
    int evicted = 0;
    for (;;) {
      auto n = notif_.pop_match(
          [=](const RxNotification& x) {
            return x.comm == comm && x.src == src &&
                   (tag == TAG_ANY || x.tag == tag);
          },
          std::chrono::nanoseconds(0));
      if (!n) return evicted;
      release(n->index);
      ++evicted;
    }
  }

  // Is a queued entry with exactly this seqn present on the route (any
  // tag)?  Distinguishes a tag-mismatched seek (expected seqn present,
  // documented PACK_SEQ semantics) from a genuine loss hole (seqn absent
  // forever on a lossy rung) — only the latter may resync.
  bool has_seqn(uint32_t comm, uint32_t src, uint32_t seqn) const {
    return notif_.any([=](const RxNotification& x) {
      return x.comm == comm && x.src == src && x.seqn == seqn;
    });
  }

  // Evict queued entries on (comm, src, tag) whose seqn lies in the
  // wrap-aware window [from, from + count) — the surviving segments of
  // a partially-lost message, which a future same-tag seek must never
  // consume as its own data.  Returns the number evicted.
  int evict_window(uint32_t comm, uint32_t src, uint32_t tag, uint32_t from,
                   uint32_t count) {
    int evicted = 0;
    for (;;) {
      auto n = notif_.pop_match(
          [=](const RxNotification& x) {
            return x.comm == comm && x.src == src &&
                   (tag == TAG_ANY || x.tag == tag) &&
                   int32_t(x.seqn - from) >= 0 &&
                   uint32_t(x.seqn - from) < count;
          },
          std::chrono::nanoseconds(0));
      if (!n) return evicted;
      release(n->index);
      ++evicted;
    }
  }

  // Evict EVERY queued entry belonging to one communicator (any src,
  // any tag, any seqn) — abort/epoch-bump reclamation: once a comm is
  // fenced, nothing queued on it can legally match a future seek, and
  // pinned buffers must return to the pool.  Returns the number evicted.
  int evict_comm(uint32_t comm) {
    int evicted = 0;
    for (;;) {
      auto n = notif_.pop_match(
          [=](const RxNotification& x) { return x.comm == comm; },
          std::chrono::nanoseconds(0));
      if (!n) return evicted;
      release(n->index);
      ++evicted;
    }
  }

  // Drain everything transient: queued notifications, reserved buffers,
  // staged overflow (reset_errors seqn-resync support — the pool starts
  // from a clean slate, matching the zeroed sequence counters).
  void clear_pending() {
    for (;;) {
      auto n = notif_.pop_match(
          [](const RxNotification&) { return true; },
          std::chrono::nanoseconds(0));
      if (!n) break;
      release(n->index);
    }
    MutexLock g(m_);
    staging_.clear();
    std::fill(status_.begin(), status_.end(), Status::IDLE);
    occupancy_.store(0);  // forced reclaim: every buffer is IDLE again
  }

  // Is at least one buffer IDLE right now?  (pressure probe)
  bool has_idle() const {
    MutexLock g(m_);
    for (auto s : status_)
      if (s == Status::IDLE) return true;
    return false;
  }

  // Pull a STAGED message matching (comm, src, tag|TAG_ANY, seqn)
  // straight out of the overflow queue, bypassing the buffer table.
  // The sub-comm wedge rescue: under cross-comm pool pinning the
  // expected segment can sit in staging FOREVER — release() is the only
  // drain, and the comm whose segments pin every buffer will not
  // release until ITS peer progresses, which may in turn wait on this
  // receiver (a cross-comm dependency cycle through the pool).  A
  // receiver about to burn its budget takes the payload directly.
  std::optional<Message> take_staged(uint32_t comm, uint32_t src,
                                     uint32_t tag, uint32_t seqn) {
    MutexLock g(m_);
    for (auto it = staging_.begin(); it != staging_.end(); ++it) {
      if (it->hdr.comm_id == comm && it->hdr.src == src &&
          it->hdr.seqn == seqn && (tag == TAG_ANY || it->hdr.tag == tag)) {
        Message msg = std::move(*it);
        staging_.erase(it);
        return msg;
      }
    }
    return std::nullopt;
  }

  // Non-destructive probe: is a staged message matching the seek
  // present?  A timeout classified while this is true is the wedge
  // observable — the data arrived, the pool just never surfaced it.
  bool has_staged_match(uint32_t comm, uint32_t src, uint32_t tag,
                        uint32_t seqn) const {
    MutexLock g(m_);
    for (const auto& msg : staging_)
      if (msg.hdr.comm_id == comm && msg.hdr.src == src &&
          msg.hdr.seqn == seqn && (tag == TAG_ANY || msg.hdr.tag == tag))
        return true;
    return false;
  }

  // Drop queued notifications on (comm, src, tag) whose seqn is at or
  // behind `upto_seqn` (wrap-aware) — duplicates of already-consumed
  // segments that would otherwise pin pool buffers until a timeout
  // happens to run eviction on the route.  Called after a successful
  // seek consumes `upto_seqn`.
  int drop_stale(uint32_t comm, uint32_t src, uint32_t tag,
                 uint32_t upto_seqn) {
    int evicted = 0;
    for (;;) {
      auto n = notif_.pop_match(
          [=](const RxNotification& x) {
            return x.comm == comm && x.src == src &&
                   (tag == TAG_ANY || x.tag == tag) &&
                   int32_t(x.seqn - upto_seqn) <= 0;
          },
          std::chrono::nanoseconds(0));
      if (!n) return evicted;
      release(n->index);
      ++evicted;
    }
  }

  // Pointer into a RESERVED buffer: contents are stable until the
  // caller release()s the index, and the buffer table itself only
  // changes in configure() (bring-up, before traffic) — the lock here
  // covers the table lookup, the returned pointer rides the RESERVED
  // guarantee (pre-r14 this read the table bare, which a configure()
  // racing live traffic could have invalidated mid-copy).
  const uint8_t* data(uint32_t index) const {
    MutexLock g(m_);
    return bufs_[index].data();
  }

  // Release a buffer back to IDLE and pull one staged message in
  // (rxbuf_seek release path + re-enqueue).
  void release(uint32_t index) {
    MutexLock g(m_);
    if (status_[index] == Status::RESERVED && occupancy_.load() > 0)
      occupancy_.fetch_sub(1);
    status_[index] = Status::IDLE;
    if (!staging_.empty()) {
      Message msg = std::move(staging_.front());
      staging_.pop_front();
      install_locked(index, msg);
    }
  }

  std::string dump() const {
    MutexLock g(m_);
    std::string out = "rx pool: " + std::to_string(bufs_.size()) + " x " +
                      std::to_string(bufsize_.load()) + "B, " +
                      std::to_string(staging_.size()) + " staged, " +
                      std::to_string(notif_.size()) + " pending\n";
    for (size_t i = 0; i < bufs_.size(); ++i) {
      out += "  buf " + std::to_string(i) + ": " +
             (status_[i] == Status::IDLE ? "IDLE" : "RESERVED") + "\n";
    }
    return out;
  }

 private:
  int find_idle_locked() ACCL_REQUIRES(m_) {
    for (size_t i = 0; i < status_.size(); ++i)
      if (status_[i] == Status::IDLE) return int(i);
    return -1;
  }

  void install_locked(uint32_t idx, Message& msg) ACCL_REQUIRES(m_) {
    status_[idx] = Status::RESERVED;
    uint64_t o = occupancy_.fetch_add(1) + 1, h = occupancy_hwm_.load();
    while (o > h && !occupancy_hwm_.compare_exchange_weak(h, o)) {
    }
    size_t n = std::min<size_t>(msg.payload.size(), bufs_[idx].size());
    if (n) std::memcpy(bufs_[idx].data(), msg.payload.data(), n);
    RxNotification note;
    note.index = idx;
    note.bytes = uint32_t(n);
    note.tag = msg.hdr.tag;
    note.src = msg.hdr.src;
    note.seqn = msg.hdr.seqn;
    note.comm = msg.hdr.comm_id;
    note.compressed = msg.hdr.compressed;
    notif_.push(note);
  }

  mutable Mutex m_;
  std::vector<std::vector<uint8_t>> bufs_ ACCL_GUARDED_BY(m_);
  std::vector<Status> status_ ACCL_GUARDED_BY(m_);
  std::deque<Message> staging_ ACCL_GUARDED_BY(m_);
  Fifo<RxNotification> notif_;  // internally locked
  std::atomic<uint64_t> bufsize_{0};  // hot-path read (frame_ok, eager segmentation)
  // telemetry shadows (see the occupancy accessors): written under m_,
  // read lock-free by the stats sampler
  std::atomic<uint64_t> occupancy_{0}, occupancy_hwm_{0}, staged_hwm_{0};
};

}  // namespace accl
