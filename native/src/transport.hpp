// Transport layer: moves framed {header, payload} messages between ranks.
//
// Role of the reference's protocol-offload engines + ZMQ emulation glue
// (SURVEY §5 "Distributed communication backend"): the reference drives
// TCP/UDP/RDMA offload engines on hardware and ZMQ pub/sub in emulation
// (test/model/zmq/zmq_server.h).  Here:
//  - InprocTransport: all ranks in one process, lock-free handoff to the
//    receiver's dispatcher (the reference's axis3x single-board loopback
//    analog).
//  - TcpTransport: one process per rank, length-prefixed frames over
//    sockets with a rank-indexed port convention (the reference emulator's
//    multi-process ZMQ rung; zmq_server.cpp port scheme).
// On TPU hardware the ICI mesh replaces this layer entirely.
#pragma once

#include <functional>

#include "common.hpp"

namespace accl {

struct Message {
  WireHeader hdr;
  std::vector<uint8_t> payload;
};

class Transport {
 public:
  using Sink = std::function<void(Message&&)>;
  virtual ~Transport() = default;
  // Send to a global rank endpoint; must be thread-safe.
  virtual void send(uint32_t global_dst, Message&& msg) = 0;
  virtual void start(Sink sink) = 0;
  virtual void stop() = 0;

  // ---- explicit session lifecycle (reference tcp_session_handler +
  // driver open_port/open_con/close_con, accl.hpp:1069-1083).  Session
  // transports (TCP) implement real bring-up/teardown with surfaced
  // errors; connectionless rungs (inproc hub, datagram) report success
  // — there is nothing to open, exactly like the reference's UDP/RDMA
  // designs which ship without the session handler kernel. ----
  // Returns 0 on success, -1 on connection failure.
  virtual int open_session(uint32_t global_dst) {
    (void)global_dst;
    return 0;
  }
  // Returns 0 if a session was closed, -1 if none was open.
  virtual int close_session(uint32_t global_dst) {
    (void)global_dst;
    return 0;
  }
  // open_port: is the inbound endpoint live?
  virtual bool listening() const { return true; }
};

// Shared in-process hub: global rank -> sink.
//
// Teardown discipline (r13, TSan-found): deliver() invokes the sink
// OUTSIDE the hub lock (holding it would deadlock against engine
// backpressure), so detach() must wait out any in-flight delivery —
// otherwise a peer thread can still be executing inside the detached
// engine's ingress while its destructor tears the members down (the
// same delivering/cv drain the datagram and RDMA hubs already use).
class InprocHub {
 public:
  explicit InprocHub(int nranks) {
    for (int i = 0; i < nranks; ++i)
      slots_.push_back(std::make_unique<Slot>());
  }
  // Elastic membership: mint a delivery slot for a joining rank.  The
  // slot exists (deliver() can route to it) before the engine attaches,
  // so a survivor's early message to the joiner is dropped — exactly a
  // not-yet-listening process — rather than out-of-bounds.
  int add_rank() {
    MutexLock g(m_);
    slots_.push_back(std::make_unique<Slot>());
    return int(slots_.size()) - 1;
  }
  int size() const {
    MutexLock g(m_);
    return int(slots_.size());
  }
  void attach(int rank, Transport::Sink sink) {
    MutexLock g(m_);
    slots_[size_t(rank)]->sink = std::move(sink);
  }
  void detach(int rank) {
    UniqueLock g(m_);
    Slot& s = *slots_[size_t(rank)];
    s.sink = nullptr;
#if !defined(ACCL_FAULT_DETACH_RACE)
    // wait out in-flight deliveries: a sender thread that copied the
    // sink may be mid-call into the engine being detached
    s.cv.wait(g, [&]() ACCL_REQUIRES(m_) { return s.inflight == 0; });
#endif
    // ACCL_FAULT_DETACH_RACE reverts the r13 TSan fix: detach returns
    // while a peer thread may still be mid-delivery into the detached
    // engine.  Compile-time fault seed for the model checker's
    // sensitivity drill (scripts/model_check.py --drill detach_race
    // must REDISCOVER this interleaving; docs/static_analysis.md).
  }
  void deliver(uint32_t dst, Message&& msg) {
    Slot* s = nullptr;
    Transport::Sink sink;
    {
      MutexLock g(m_);
      if (dst < slots_.size() && slots_[dst]->sink) {
        s = slots_[dst].get();
        sink = s->sink;
        ++s->inflight;
      }
    }
    if (!sink) return;
    sink(std::move(msg));
    {
      MutexLock g(m_);
      --s->inflight;
    }
    s->cv.notify_all();
  }

 private:
  // unique_ptr slots: add_rank must not move live Slot objects (their
  // cv state is waited on) when the vector grows.  sink/inflight are
  // guarded by the hub's m_ (a nested type cannot name the enclosing
  // instance's capability in a GUARDED_BY, so the discipline is
  // documented here and enforced by deliver()/attach()/detach() all
  // locking m_).
  struct Slot {
    Transport::Sink sink;
    int inflight = 0;  // guarded by m_
    CondVar cv;
  };
  mutable Mutex m_;
  std::vector<std::unique_ptr<Slot>> slots_ ACCL_GUARDED_BY(m_);
};

class InprocTransport : public Transport {
 public:
  InprocTransport(std::shared_ptr<InprocHub> hub, int rank)
      : hub_(std::move(hub)), rank_(rank) {}
  void send(uint32_t dst, Message&& msg) override {
    hub_->deliver(dst, std::move(msg));
  }
  void start(Sink sink) override { hub_->attach(rank_, std::move(sink)); }
  void stop() override { hub_->detach(rank_); }

 private:
  std::shared_ptr<InprocHub> hub_;
  int rank_;
};

// One-process-per-rank sockets.  Rank r listens on base_port + r;
// connections to peers are opened lazily on first send.
class TcpTransport : public Transport {
 public:
  TcpTransport(int rank, int nranks, int base_port,
               std::vector<std::string> peer_ips);
  ~TcpTransport() override;
  void send(uint32_t dst, Message&& msg) override;
  void start(Sink sink) override;
  void stop() override;
  // Explicit session bring-up: ONE bounded connect attempt window
  // (~2 s) so a dead peer surfaces as an error instead of the lazy
  // path's long startup-skew retry.  Re-opening an open session is a
  // success no-op (the reference session handler returns the existing
  // session's status).
  int open_session(uint32_t dst) override;
  int close_session(uint32_t dst) override;
  bool listening() const override { return listen_fd_ >= 0; }

 private:
  int connect_to(uint32_t dst, int max_attempts = 400);
  void accept_loop();
  void reader_loop(int fd);

  int rank_, nranks_, base_port_;
  std::vector<std::string> peer_ips_;
  int listen_fd_ = -1;
  // peer_fds_[d] is guarded by peer_mu_[d] (per-element locking the
  // analysis cannot express on a dynamic array; the pairing is local
  // to open_session/close_session/send)
  std::vector<int> peer_fds_;       // lazily-opened outbound sockets
  std::vector<Mutex> peer_mu_;      // serialize writes per peer
  Sink sink_;  // set once in start(), before any reader thread exists
  std::atomic<bool> running_{false};
  // Deliberately std::thread, not accl::Thread: these block in
  // accept(2)/read(2), which the deterministic scheduler cannot
  // virtualize — TCP worlds are out of detsched drills' scope.
  std::vector<std::thread> threads_ ACCL_GUARDED_BY(conn_mu_);
  Mutex conn_mu_;
  std::vector<int> accepted_fds_ ACCL_GUARDED_BY(conn_mu_);
};

}  // namespace accl
