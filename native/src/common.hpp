// Common types for the ACCL-TPU native collective engine.
//
// This library is the TPU build's equivalent of the reference's on-device
// control plane + dataplane, re-hosted as portable C++ so the whole
// framework is testable without accelerator hardware — the role the
// reference's cclo_emu CPU emulator plays (test/model/emulator/cclo_emu.cpp).
// Nothing here is a translation of the reference sources; the wire header
// field set and the 15-word call ABI are kept compatible so the Python
// driver can treat the emulator and the TPU backend identically.
#pragma once

#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// Compile-time lock discipline: Clang Thread Safety Analysis attributes.
//
// Every mutex-guarded field and lock-requiring function in the native
// engine is annotated with these macros; `make tsa` builds the tree
// with -Werror=thread-safety (scripts/tsa_check.py drives a real
// clang++ when one is installed, or the libclang frontend otherwise)
// so an unlocked read of a guarded field, a missing REQUIRES on a
// helper, or a lock-order inversion against the declared
// ACQUIRED_BEFORE edges fails the build.  Policy mirrors the r13
// sanitizer wall: ZERO waivers under accl:: — ACCL_NO_TSA exists for
// third-party interop only and scripts/tsa_check.py greps it banned
// from native/src.  Under gcc (plain/ASan/TSan lanes) every macro
// expands to nothing, so non-clang builds are bit-identical.
// ---------------------------------------------------------------------------
#if defined(__clang__)
#define ACCL_TSA(x) __attribute__((x))
#else
#define ACCL_TSA(x)
#endif
#define ACCL_CAPABILITY(x) ACCL_TSA(capability(x))
#define ACCL_SCOPED_CAPABILITY ACCL_TSA(scoped_lockable)
#define ACCL_GUARDED_BY(x) ACCL_TSA(guarded_by(x))
#define ACCL_PT_GUARDED_BY(x) ACCL_TSA(pt_guarded_by(x))
#define ACCL_REQUIRES(...) ACCL_TSA(requires_capability(__VA_ARGS__))
#define ACCL_ACQUIRE(...) ACCL_TSA(acquire_capability(__VA_ARGS__))
#define ACCL_RELEASE(...) ACCL_TSA(release_capability(__VA_ARGS__))
#define ACCL_TRY_ACQUIRE(...) ACCL_TSA(try_acquire_capability(__VA_ARGS__))
#define ACCL_EXCLUDES(...) ACCL_TSA(locks_excluded(__VA_ARGS__))
#define ACCL_ACQUIRED_BEFORE(...) ACCL_TSA(acquired_before(__VA_ARGS__))
#define ACCL_ACQUIRED_AFTER(...) ACCL_TSA(acquired_after(__VA_ARGS__))
#define ACCL_RETURN_CAPABILITY(x) ACCL_TSA(lock_returned(x))
// Third-party interop escape hatch.  NEVER legal under accl:: —
// scripts/tsa_check.py fails the lane if it appears in native/src.
#define ACCL_NO_TSA ACCL_TSA(no_thread_safety_analysis)

// Deterministic schedule exploration (docs/static_analysis.md): the
// ACCL_DETSCHED build routes every blocking primitive below through
// the virtual scheduler in detsched.hpp, serializing all engine
// threads onto one deterministic schedule so small-world drills can be
// model-checked exhaustively.  Plain builds never include it.
#if defined(ACCL_DETSCHED)
#include "detsched.hpp"
#include "detsched_pred.hpp"
#endif

namespace accl {

// ---------------------------------------------------------------------------
// ABI constants (kept bit-compatible with accl_tpu/constants.py and the
// reference driver; see driver/xrt/include/accl/constants.hpp:191-210).
// ---------------------------------------------------------------------------
enum class Op : uint32_t {
  Config = 0,
  Copy = 1,
  Combine = 2,
  Send = 3,
  Recv = 4,
  Bcast = 5,
  Scatter = 6,
  Gather = 7,
  Reduce = 8,
  Allgather = 9,
  Allreduce = 10,
  ReduceScatter = 11,
  Barrier = 12,
  Alltoall = 13,
  Nop = 255,
};

enum class CfgFunc : uint32_t {
  ResetPeriph = 0,
  EnablePkt = 1,
  SetTimeout = 2,
  SetMaxEagerMsgSize = 3,
  SetMaxRendezvousMsgSize = 4,
};

// Error bits (reference: constants.hpp:355-387; bits 27/28 are this
// build's fault-tolerance extension, mirrored in accl_tpu/constants.py).
enum Err : uint32_t {
  OK = 0,
  RECEIVE_TIMEOUT_ERROR = 1u << 11,
  COLLECTIVE_NOT_IMPLEMENTED = 1u << 14,
  EAGER_THRESHOLD_INVALID = 1u << 16,
  RENDEZVOUS_THRESHOLD_INVALID = 1u << 17,
  DMA_SIZE_ERROR = 1u << 18,
  ARITH_ERROR = 1u << 19,
  PACK_SEQ_NUMBER_ERROR = 1u << 21,
  COMPRESSION_ERROR = 1u << 22,
  SEGMENTER_EXPECTED_BTT_ERROR = 1u << 25,
  // the communicator this call ran on was aborted (epoch fenced); every
  // pending call on all live ranks finalizes fast with this bit
  COMM_ABORTED = 1u << 27,
  // the abort was triggered by a peer declared dead (watchdog action or
  // liveness probe) rather than an application-initiated abort
  RANK_FAILED = 1u << 28,
};

// Wire message types (reference: eth_intf.h:42-45; types >= 4 are this
// build's resilience control plane — no reference analog).
enum class MsgType : uint8_t {
  EgrMsg = 0,
  RndzvsMsg = 1,
  RndzvsInit = 2,
  RndzvsWrDone = 3,
  // receiver -> sender: "resend eager segments of (comm, tag) from seqn"
  // (hdr.seqn = first missing sequence number); answered from the
  // sender's bounded retransmit store
  Nack = 4,
  // liveness ping/pong piggybacked on the control plane (hdr.count = 1
  // requests a reply; 0 is the reply); any ingress traffic also counts
  // as proof of life for the sending peer
  Heartbeat = 5,
  // epoch-tagged communicator abort: hdr.epoch carries the NEW epoch,
  // hdr.count the error bits every pending call must finalize with
  Abort = 6,
  // ---- elastic membership (r11): the join control plane ----
  // joiner -> sponsor: "I am session hdr.src, send me your world state"
  // (the joiner is in NO communicator table yet, so it is addressed by
  // raw session id — the one piece of addressing that predates comms)
  Join = 7,
  // sponsor -> joiner: join accepted; hdr.count = number of comm slots
  // the StateSync payload will describe
  Welcome = 8,
  // sponsor -> joiner: serialized per-comm recovery state (see
  // Engine::ingress Join handling for the word layout): comm count,
  // then per comm {size, epoch, abort_bits} + the sponsor's per-peer
  // inbound/outbound seqn rows.  The joiner adopts the epoch/abort
  // fence table (so dead-epoch traffic can never land on it and its
  // comm-id space aligns with the survivors') and records the seqn
  // rows for introspection — fresh comms it joins start with clean
  // pairwise seqn state on every member by construction.
  StateSync = 9,
};

constexpr uint32_t TAG_ANY = 0xFFFFFFFFu;
constexpr uint32_t MAX_PACKETSIZE = 4096;  // transport write-chunk quantum

// Compression flag bits of descriptor word 7 (reference:
// constants.hpp:320-325; bit-compatible with accl_tpu/constants.py) —
// shared by the engine's flag algebra and the C++ host driver's
// prepare_call marshaling.
enum CompFlag : uint32_t {
  OP0_COMPRESSED = 1,
  OP1_COMPRESSED = 2,
  RES_COMPRESSED = 4,
  ETH_COMPRESSED = 8,
};

// ---------------------------------------------------------------------------
// Wire header: 64 bytes, self-describing, field set equivalent to the
// reference's eth_header {count,tag,src,seqn,strm,dst,msg_type,host,vaddr}
// (eth_intf.h:94-151) with a comm id in previously-reserved space (the
// reference derives the communicator from the session id; carrying it
// explicitly keeps the socket transport stateless).
// ---------------------------------------------------------------------------
struct WireHeader {
  uint32_t count = 0;  // payload bytes (compressed size if compressed)
  uint32_t tag = 0;
  uint32_t src = 0;   // source rank within comm
  uint32_t seqn = 0;  // per (comm, src->dst) sequence number
  uint32_t strm = 0;  // nonzero: route to compute stream id, not memory
  uint16_t dst_session = 0;
  uint8_t msg_type = 0;
  uint8_t host = 0;
  uint64_t vaddr = 0;  // rendezvous target address
  uint32_t comm_id = 0;
  uint32_t compressed = 0;  // wire payload is in the compressed
                            // representation (diagnostic only: both ends
                            // derive the wire format from their OWN
                            // arithcfg + flags, like the reference's
                            // marker-free eth header)
  uint32_t epoch = 0;  // communicator epoch (abort fencing): ingress
                       // drops data messages whose epoch trails the
                       // receiver's, so traffic from a dead epoch can
                       // never land after an abort
  uint8_t pad[64 - 44] = {0};
};
static_assert(sizeof(WireHeader) == 64, "wire header must be 64 bytes");

// ---------------------------------------------------------------------------
// 15-word call descriptor (reference ABI: hostctrl.cpp:19-63).
// ---------------------------------------------------------------------------
struct CallDesc {
  std::array<uint32_t, 15> w{};
  uint64_t id = 0;
  uint32_t current_step = 0;  // rendezvous resume point (fw :34,:2336)
  // scratch device-memory leases that persist across retries (the role of
  // the reference's SPARE1-3 rendezvous scratch buffers, accl.cpp:1190)
  uint64_t scratch0 = 0, scratch1 = 0;
  // first time this call was attempted at its CURRENT resume step (ns
  // since steady epoch; 0 = not yet tried; reset whenever current_step
  // advances so the budget is per-receive, like the blocking eager
  // path's seek, not per-call).  The retry queue expires calls against
  // the engine's receive budget — the reference retries NOT_READY
  // forever (fw :2460-2479), which turns a dead peer into an opaque
  // host-side hang; here the same timeout register that bounds blocking
  // receives bounds the cooperative retry loop, so a stuck rendezvous
  // finalizes with RECEIVE_TIMEOUT_ERROR.
  uint64_t first_try_ns = 0;
  // (comm, src, tag, vaddr) landing records this call advertised
  // (receiver role); torn down if the call expires so a late one-sided
  // write cannot land into reused memory and a late completion cannot
  // satisfy a future call.
  std::vector<std::array<uint64_t, 4>> rndzv_posts;

  Op scenario() const { return static_cast<Op>(w[0]); }
  uint32_t count() const { return w[1]; }
  uint32_t comm() const { return w[2]; }
  uint32_t root_src_dst() const { return w[3]; }
  uint32_t function() const { return w[4]; }
  uint32_t tag() const { return w[5]; }
  uint32_t arithcfg() const { return w[6]; }
  uint32_t compression() const { return w[7]; }
  uint32_t stream_flags() const { return w[8] & 0xFF; }
  uint32_t host_flags() const { return (w[8] >> 8) & 0xFF; }
  uint64_t addr0() const { return uint64_t(w[9]) | (uint64_t(w[10]) << 32); }
  uint64_t addr1() const { return uint64_t(w[11]) | (uint64_t(w[12]) << 32); }
  uint64_t addr2() const { return uint64_t(w[13]) | (uint64_t(w[14]) << 32); }
};

// Thrown by a rendezvous wait-point whose peer state has not arrived;
// the engine loop re-queues the whole call with its resume step
// (reference retry path: ccl_offload_control.c:2460-2479).
struct NotReadyEx {
  uint32_t step;
};

// Thrown when a message exceeds the configured rendezvous maximum size —
// the transfer cannot be expressed by either protocol, so the call
// finalizes immediately with the accumulated error code (the reference
// stores this register but never enforces it; here it is a hard cap).
struct SizeCapEx {};

// ---------------------------------------------------------------------------
// Synchronization wrappers: the compile-time lock discipline's
// capability types AND the deterministic scheduler's hook points.
//
// accl::Mutex / MutexLock / UniqueLock / CondVar / Thread replace the
// raw std primitives everywhere under accl:: so that
//  (a) clang Thread Safety Analysis sees every acquire/release (std::
//      mutex carries no capability attributes on libstdc++), and
//  (b) the ACCL_DETSCHED build can serialize every blocking operation
//      onto the virtual scheduler (detsched.hpp) — the hooks live in
//      exactly one place, inside these wrappers.
// Plain builds compile the wrappers down to the raw std calls.
// ---------------------------------------------------------------------------
class ACCL_CAPABILITY("mutex") Mutex {
 public:
  Mutex() = default;
  Mutex(const Mutex&) = delete;
  Mutex& operator=(const Mutex&) = delete;
  void lock() ACCL_ACQUIRE() {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      det::lock_hooked(&m_);
      return;
    }
#endif
    m_.lock();
  }
  void unlock() ACCL_RELEASE() {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      det::unlock_hooked(&m_);
      return;
    }
#endif
    m_.unlock();
  }
  bool try_lock() ACCL_TRY_ACQUIRE(true) { return m_.try_lock(); }
  std::mutex& native() { return m_; }

 private:
  std::mutex m_;
};

// std::lock_guard replacement (scoped capability so the analysis
// tracks the critical section's extent).
class ACCL_SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& m) ACCL_ACQUIRE(m) : m_(m) { m_.lock(); }
  ~MutexLock() ACCL_RELEASE() { m_.unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& m_;
};

// std::unique_lock replacement.  Derives the std type so condition
// waits (CondVar, cv_wait_for_pred) take it unchanged; lock/unlock are
// shadowed with capability-annotated, scheduler-aware versions.
class ACCL_SCOPED_CAPABILITY UniqueLock : public std::unique_lock<std::mutex> {
 public:
  explicit UniqueLock(Mutex& m) ACCL_ACQUIRE(m)
      : std::unique_lock<std::mutex>(acquire_adopted(m)), mu_(&m) {}
  ~UniqueLock() ACCL_RELEASE() {
    if (owns_lock()) {
      std::unique_lock<std::mutex>::release();
      mu_->unlock();
    }
  }
  void unlock() ACCL_RELEASE() {
    std::unique_lock<std::mutex>::release();
    mu_->unlock();
  }
  void lock() ACCL_ACQUIRE() {
    mu_->lock();
    static_cast<std::unique_lock<std::mutex>&>(*this) =
        std::unique_lock<std::mutex>(mu_->native(), std::adopt_lock);
  }
  UniqueLock(const UniqueLock&) = delete;
  UniqueLock& operator=(const UniqueLock&) = delete;

 private:
  static std::unique_lock<std::mutex> acquire_adopted(Mutex& m)
      ACCL_ACQUIRE(m) {
    m.lock();  // capability-aware + det-aware acquire
    return std::unique_lock<std::mutex>(m.native(), std::adopt_lock);
  }
  Mutex* mu_;
};

// std::condition_variable replacement; notify and the untimed waits
// are scheduler hook points.  Untimed pthread_cond_wait is intercepted
// by every sanitizer runtime, so no TSan workaround is needed here
// (only the TIMED waits below need one — see cv_wait_for_pred).
class CondVar {
 public:
  CondVar() = default;
  CondVar(const CondVar&) = delete;
  CondVar& operator=(const CondVar&) = delete;
  void notify_all() {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      det::cv_notify(this, true);
      return;
    }
#endif
    cv_.notify_all();
  }
  void notify_one() {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      det::cv_notify(this, false);
      return;
    }
#endif
    cv_.notify_one();
  }
  // Untimed predicate wait; `g` holds the Mutex associated with the
  // guarded state the predicate reads.
  template <typename Pred>
  void wait(std::unique_lock<std::mutex>& g, Pred pred) {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      while (!det::invoke_pred(pred)) det::cv_block(this, g, det::kInf);
      return;
    }
#endif
    cv_.wait(g, pred);
  }
  void wait(std::unique_lock<std::mutex>& g) {
#if defined(ACCL_DETSCHED)
    if (det::on()) {
      det::cv_block(this, g, det::kInf);
      return;
    }
#endif
    cv_.wait(g);
  }
  std::condition_variable& native() { return cv_; }

 private:
  std::condition_variable cv_;
};

// std::thread replacement: under ACCL_DETSCHED a child spawned during
// an active run registers with the scheduler before its body runs, so
// the scheduler serializes it from its first instruction; join parks
// on the virtual scheduler instead of blocking the token.
class Thread {
 public:
  Thread() noexcept = default;
  template <typename F>
  explicit Thread(F fn) {
#if defined(ACCL_DETSCHED)
    if (det::run_active()) {
      det_id_ = det::Sched::inst().pre_spawn();
      int id = det_id_;
      t_ = std::thread([id, fn2 = std::move(fn)]() mutable {
        det::Sched::inst().child_enter(id);
        fn2();
        det::Sched::inst().child_exit();
      });
      // deterministic spawn: the child is registered (and parked for
      // its first grant) before the parent's next instruction
      det::Sched::inst().await_child_enter(det_id_);
      return;
    }
#endif
    t_ = std::thread(std::move(fn));
  }
  Thread(Thread&&) noexcept = default;
  Thread& operator=(Thread&&) noexcept = default;
  bool joinable() const { return t_.joinable(); }
  void join() {
#if defined(ACCL_DETSCHED)
    if (det_id_ >= 0 && det::on()) det::Sched::inst().join_wait_slot(det_id_);
#endif
    t_.join();
  }

 private:
  std::thread t_;
#if defined(ACCL_DETSCHED)
  int det_id_ = -1;
#endif
};

// Scheduler-aware sleep/yield (the engine loop's retry pacing, chaos
// stalls, liveness-probe polls): virtual time under ACCL_DETSCHED,
// the real thing everywhere else.
inline void det_sleep_for(std::chrono::nanoseconds d) {
#if defined(ACCL_DETSCHED)
  if (det::on()) {
    det::sleep_hooked(uint64_t(d.count() > 0 ? d.count() : 1));
    return;
  }
#endif
  std::this_thread::sleep_for(d);
}

inline void det_yield() {
#if defined(ACCL_DETSCHED)
  if (det::on()) {
    det::yield_hooked();
    return;
  }
#endif
  std::this_thread::yield();
}

// Budget clock: virtual time under an active detsched run, the real
// steady clock everywhere else.  The engine's receive budgets MUST be
// measured with this — a budget read off the real clock never expires
// inside an explored schedule (cv waits are virtual, so wall time
// barely advances), which made the whole RECEIVE_TIMEOUT classification
// class unreachable to the checker: ROADMAP item 5's "wall-clock
// ingredient the virtual clock hides".
inline std::chrono::steady_clock::time_point det_clock_now() {
#if defined(ACCL_DETSCHED)
  // Free-run (the deadlock escape hatch) freezes the virtual clock and
  // runs teardown on real primitives; budgets must switch back to the
  // real clock with it or they never expire and teardown hangs.
  if (det::on() && !det::free_running())
    return std::chrono::steady_clock::time_point(
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::nanoseconds(det::now_ns())));
#endif
  return std::chrono::steady_clock::now();
}

// Resource-exhaustion hook: a modeled resource (rx pool, retransmit
// store) just saturated.  Under detsched this arms the checker's
// timeout-injection window (exhaustion-induced orderings become
// explored state); a no-op everywhere else.
inline void det_note_pressure() {
#if defined(ACCL_DETSCHED)
  if (det::on()) det::note_pressure();
#endif
}

// Liveness tokens: one per submitted engine call, returned when the
// call finalizes.  Tokens still outstanding when a drill returns are
// the stuck-progress finding; no-ops outside detsched runs.
inline void det_live_begin() {
#if defined(ACCL_DETSCHED)
  if (det::on()) det::live_begin();
#endif
}

inline void det_live_end() {
#if defined(ACCL_DETSCHED)
  if (det::on()) det::live_end();
#endif
}

// ---------------------------------------------------------------------------
// TSan-safe timed condition waits (r13).  libstdc++ (gcc 10) lowers
// every steady-clock timed CV wait to pthread_cond_clockwait, which
// this toolchain's ThreadSanitizer runtime does NOT intercept: the
// checker then never observes the mutex being released inside the wait
// and reports impossible "double lock of a mutex"/"race with mutex
// held" findings on perfectly locked queues.  Under
// __SANITIZE_THREAD__ these helpers replace the timed wait with a
// bounded unlock/sleep/relock poll (1 ms granularity — every caller
// re-checks its predicate, so the observable semantics are identical);
// all other builds use the real futex-backed wait.  Policy + rationale:
// docs/static_analysis.md "Native sanitizer lanes".
// Under ACCL_DETSCHED the deadline is VIRTUAL: the wait parks on the
// scheduler and the clock jumps when nothing is runnable, so receive
// budgets cost microseconds of wall time per explored schedule.
// ---------------------------------------------------------------------------
template <typename Pred>
inline bool cv_wait_for_pred(CondVar& cv, std::unique_lock<std::mutex>& g,
                             std::chrono::nanoseconds timeout, Pred pred) {
#if defined(ACCL_DETSCHED)
  if (det::on()) {
    uint64_t deadline =
        det::now_ns() + uint64_t(timeout.count() > 0 ? timeout.count() : 0);
    for (;;) {
      if (det::invoke_pred(pred)) return true;
      if (det::free_running()) {
        // escape hatch fired: the virtual clock is frozen, so finish the
        // wait against the REAL clock or this slice never expires and
        // teardown hangs instead of reporting the finding
        auto rdl = std::chrono::steady_clock::now() + timeout;
        for (;;) {
          if (det::invoke_pred(pred)) return true;
          if (std::chrono::steady_clock::now() >= rdl)
            return det::invoke_pred(pred);
          det::cv_block(&cv, g, 1000000);  // 1 ms real poll in free-run
        }
      }
      uint64_t now = det::now_ns();
      if (now >= deadline) return det::invoke_pred(pred);
      det::cv_block(&cv, g, deadline - now);
    }
  }
#endif
#if defined(__SANITIZE_THREAD__)
  auto deadline = std::chrono::steady_clock::now() + timeout;
  for (;;) {
    if (pred()) return true;
    if (std::chrono::steady_clock::now() >= deadline) return pred();
    g.unlock();
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
    g.lock();
  }
#else
  return cv.native().wait_for(g, timeout, pred);
#endif
}

inline std::cv_status cv_wait_until_point(
    CondVar& cv, std::unique_lock<std::mutex>& g,
    std::chrono::steady_clock::time_point deadline) {
#if defined(ACCL_DETSCHED)
  if (det::on()) {
    auto now = std::chrono::steady_clock::now();
    if (now >= deadline) return std::cv_status::timeout;
    uint64_t ns = uint64_t(
        std::chrono::duration_cast<std::chrono::nanoseconds>(deadline - now)
            .count());
    return det::cv_block(&cv, g, ns) ? std::cv_status::no_timeout
                                     : std::cv_status::timeout;
  }
#endif
#if defined(__SANITIZE_THREAD__)
  if (std::chrono::steady_clock::now() >= deadline)
    return std::cv_status::timeout;
  g.unlock();
  std::this_thread::sleep_for(std::chrono::milliseconds(1));
  g.lock();
  return std::chrono::steady_clock::now() >= deadline
             ? std::cv_status::timeout
             : std::cv_status::no_timeout;
#else
  return cv.native().wait_until(g, deadline);
#endif
}

// ---------------------------------------------------------------------------
// Bounded-ish MPMC fifo used for command/status/notification streams
// (role of the hlslib FIFOs wiring the reference emulator threads).
// ---------------------------------------------------------------------------
template <typename T>
class Fifo {
 public:
  void push(T v) {
    {
      MutexLock g(m_);
      q_.push_back(std::move(v));
    }
    cv_.notify_all();
  }

  std::optional<T> pop_wait(std::chrono::nanoseconds timeout) {
    UniqueLock g(m_);
    // the predicate runs with m_ held (cv_wait_for_pred's contract);
    // the REQUIRES annotation tells the analysis, which otherwise
    // checks lambda bodies as lock-free contexts
    if (!cv_wait_for_pred(cv_, g, timeout,
                          [&]() ACCL_REQUIRES(m_) { return !q_.empty() || closed_; }))
      return std::nullopt;
    if (q_.empty()) return std::nullopt;
    T v = std::move(q_.front());
    q_.pop_front();
    return v;
  }

  std::optional<T> try_pop() {
    MutexLock g(m_);
    if (q_.empty()) return std::nullopt;
    T v = std::move(q_.front());
    q_.pop_front();
    return v;
  }

  // Wait until pred matches an element; remove and return it.  Other
  // elements stay queued (out-of-order matching for rendezvous queues).
  // Expressed as one predicate wait so the deterministic scheduler's
  // virtual deadline applies (and the post-timeout last scan the r13
  // version did by hand falls out of cv_wait_for_pred's contract).
  std::optional<T> pop_match(std::function<bool(const T&)> pred,
                             std::chrono::nanoseconds timeout) {
    UniqueLock g(m_);
    auto find = [&]() ACCL_REQUIRES(m_) {
      for (auto it = q_.begin(); it != q_.end(); ++it)
        if (pred(*it)) return it;
      return q_.end();
    };
    cv_wait_for_pred(cv_, g, timeout,
                     [&]() ACCL_REQUIRES(m_) { return closed_ || find() != q_.end(); });
    auto it = find();
    if (it == q_.end()) return std::nullopt;
    T v = std::move(*it);
    q_.erase(it);
    return v;
  }

  bool empty() const {
    MutexLock g(m_);
    return q_.empty();
  }

  // Non-destructive scan: does any queued element satisfy pred?
  bool any(std::function<bool(const T&)> pred) const {
    MutexLock g(m_);
    for (const auto& v : q_)
      if (pred(v)) return true;
    return false;
  }

  // Non-destructive visit of every queued element.
  void for_each(std::function<void(const T&)> fn) const {
    MutexLock g(m_);
    for (const auto& v : q_) fn(v);
  }

  size_t size() const {
    MutexLock g(m_);
    return q_.size();
  }

  void close() {
    {
      MutexLock g(m_);
      closed_ = true;
    }
    cv_.notify_all();
  }

 private:
  mutable Mutex m_;
  CondVar cv_;
  std::deque<T> q_ ACCL_GUARDED_BY(m_);
  bool closed_ ACCL_GUARDED_BY(m_) = false;
};

// fp16 <-> fp32 conversion (the emulator arithmetic/compression lanes'
// scalar core; the reference uses Vitis HLS half types in
// kernels/plugins/hp_compression/hp_compression.cpp).
inline uint16_t f32_to_f16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  uint32_t sign = (x >> 16) & 0x8000u;
  int32_t exp = int32_t((x >> 23) & 0xFF) - 127 + 15;
  uint32_t mant = x & 0x7FFFFFu;
  if (((x >> 23) & 0xFF) == 0xFF) {  // inf/nan
    return uint16_t(sign | 0x7C00u | (mant ? 0x200u : 0));
  }
  if (exp >= 0x1F) return uint16_t(sign | 0x7C00u);  // overflow -> inf
  if (exp <= 0) {                                    // subnormal / zero
    if (exp < -10) return uint16_t(sign);
    mant |= 0x800000u;
    uint32_t shift = uint32_t(14 - exp);
    uint32_t half_mant = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    if (rem > (1u << (shift - 1)) ||
        (rem == (1u << (shift - 1)) && (half_mant & 1)))
      half_mant++;
    return uint16_t(sign | half_mant);
  }
  uint32_t half = sign | (uint32_t(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1FFFu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) half++;
  return uint16_t(half);
}

inline float f16_to_f32(uint16_t h) {
  uint32_t sign = uint32_t(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t mant = h & 0x3FFu;
  uint32_t x;
  if (exp == 0) {
    if (mant == 0) {
      x = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3FFu;
      x = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 0x1F) {
    x = sign | 0x7F800000u | (mant << 13);
  } else {
    x = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float f;
  std::memcpy(&f, &x, 4);
  return f;
}

}  // namespace accl
