// Engine implementation: event loop, protocol primitives, collective
// schedules.  See engine.hpp for the reference mapping.
#include "engine.hpp"

#include <algorithm>
#include <cassert>
#include <cstdio>
#include <set>

namespace accl {

using namespace std::chrono;
using std::chrono::nanoseconds;

// Tag reserved for barrier traffic (the reference exchanges empty
// rendezvous notifications instead, fw :2077-2120; a reserved eager tag
// keeps the same synchronization with the socket transport).
static constexpr uint32_t BARRIER_TAG = 0xBA771E12u;
// Stream ids >= 9 address compute-kernel streams (reference: accl.cpp:197).
static constexpr uint32_t FIRST_KRNL_STREAM = 9;

Engine::Engine(uint32_t global_rank, uint64_t devmem_bytes,
               std::unique_ptr<Transport> transport)
    : global_rank_(global_rank),
      devicemem_(devmem_bytes),
      host_region_bytes_(devmem_bytes / 2),
      transport_(std::move(transport)) {
  free_spans_[0x1000] = devmem_bytes - 0x1000;
  // hostmem_ is committed lazily on first alloc_host: most worlds never
  // use host-only buffers and should not pay half a devmem of RSS.
  // The tables behind comms_/arithcfgs_ are heap-pinned (unique_ptr
  // slots), so growth can never move a table the engine loop holds a
  // pointer into; the reserve only avoids pointer-vector churn.
  comms_.reserve(64);
  arithcfgs_.reserve(64);
  transport_->start([this](Message&& m) { ingress(std::move(m)); });
  loop_thread_ = Thread([this] { loop(); });
  egress_thread_ = Thread([this] { egress_loop(); });
  delay_thread_ = Thread([this] { delay_loop(); });
}

Engine::~Engine() { shutdown(); }

void Engine::shutdown() {
  if (stopped_.exchange(true)) return;  // idempotent
  running_ = false;
  cmd_q_.close();
  completions_.close();
  pending_addrs_.close();
  krnl_in_.close();  // unblock drain_krnl_to/send-from-stream waits
  if (loop_thread_.joinable()) loop_thread_.join();
  {
    // chaos-delayed messages still pending at teardown are dropped (the
    // world is going away; the peer's receive machinery is too)
    MutexLock g(delay_mu_);
    delay_running_ = false;
    delayed_.clear();
  }
  delay_cv_.notify_all();
  if (delay_thread_.joinable()) delay_thread_.join();
  {
    // drain staged segments so tail messages of completed calls are not
    // lost, then stop the writer
    UniqueLock g(egress_mu_);
    cv_wait_for_pred(egress_cv_, g, std::chrono::seconds(2),
                     [&]() ACCL_REQUIRES(egress_mu_) { return egress_q_.empty(); });
    egress_running_ = false;
  }
  egress_cv_.notify_all();
  if (egress_thread_.joinable()) egress_thread_.join();
  transport_->stop();
  // unblock host-side stream readers parked in pop_stream
  {
    MutexLock g(streams_mu_);
    for (auto& [strm, fifo] : streams_)
      if (fifo) fifo->close();
  }
  // finalize every call the stopped loop left pending, so a host
  // waiter polling its id returns NOW instead of burning its full wait
  // budget against a dead engine (and then touching freed memory — the
  // suite-exit segfault)
  {
    MutexLock g(results_mu_);
    for (auto& [id, r] : results_) {
      if (!r.done) {
        r.retcode = COMM_ABORTED | RANK_FAILED;
        r.done = true;
        det_live_end();
      }
    }
  }
}

// ---------------------------------------------------------------------------
// host-facing config
// ---------------------------------------------------------------------------
void Engine::cfg_rx_buffers(uint32_t nbufs, uint64_t bufsize) {
  rx_.configure(nbufs, bufsize);
}

int Engine::set_comm(const uint32_t* words, int nwords) {
  // build the table FULLY before publication: rows of a published
  // table are immutable and may be read lock-free (see CommTable)
  auto t = std::make_unique<CommTable>();
  t->size = words[0];
  t->local = words[1];
  if (nwords < int(2 + 4 * t->size)) return -1;
  for (uint32_t i = 0; i < t->size; ++i) {
    CommTable::Row r;
    r.ip = words[2 + 4 * i];
    r.port = words[3 + 4 * i];
    r.session = words[4 + 4 * i];
    r.max_seg = words[5 + 4 * i];
    t->rows.push_back(r);
  }
  t->inbound_seq.assign(t->size, 0);
  t->outbound_seq.assign(t->size, 0);
  MutexLock g(cfg_mu_);
  comms_.push_back(std::move(t));
  return int(comms_.size()) - 1;
}

int Engine::set_arithcfg(const uint32_t* words, int nwords) {
  auto a = std::make_unique<ArithCfgN>();
  a->ubits = words[0];
  a->cbits = words[1];
  a->ratio_log = words[2];
  a->compressor = words[3];
  a->decompressor = words[4];
  a->arith_compressed = words[5];
  uint32_t nlanes = words[6];
  for (uint32_t i = 0; i < nlanes && int(7 + i) < nwords; ++i)
    a->lanes.push_back(words[7 + i]);
  // r17 append-only trailing words (arithconfig.py to_words): block
  // geometry of the int8 block-scaled wire lane + error-feedback flag.
  // Older 7+nlanes-word uploads simply leave the defaults (0 = cast).
  if (int(7 + nlanes) < nwords) a->block = words[7 + nlanes];
  if (int(8 + nlanes) < nwords) a->error_feedback = words[8 + nlanes];
  if (a->block > I8_BLOCK_MAX) return -1;
  MutexLock g(cfg_mu_);
  arithcfgs_.push_back(std::move(a));
  return int(arithcfgs_.size()) - 1;
}

// ---------------------------------------------------------------------------
// device memory (first-fit free-list allocator over the flat devicemem,
// playing the role of the reference's per-bank XRT BO allocation)
// ---------------------------------------------------------------------------
// One first-fit body for both address spaces; `tag` is OR'd into the
// recorded and returned address (0 for device, HOST_ADDR_BIT for host).
static uint64_t alloc_first_fit(std::map<uint64_t, uint64_t>& spans,
                                std::map<uint64_t, uint64_t>& sizes,
                                uint64_t nbytes, uint64_t align,
                                uint64_t tag) {
  if (align == 0) align = 64;
  if (nbytes == 0) nbytes = align;
  for (auto it = spans.begin(); it != spans.end(); ++it) {
    uint64_t base = it->first, size = it->second;
    uint64_t aligned = (base + align - 1) / align * align;
    uint64_t pad = aligned - base;
    if (size < pad + nbytes) continue;
    spans.erase(it);
    if (pad) spans[base] = pad;
    uint64_t rest = size - pad - nbytes;
    if (rest) spans[aligned + nbytes] = rest;
    sizes[aligned | tag] = nbytes;
    return aligned | tag;
  }
  return 0;  // OOM
}

uint64_t Engine::alloc(uint64_t nbytes, uint64_t align) {
  MutexLock g(mem_mu_);
  return alloc_first_fit(free_spans_, alloc_sizes_, nbytes, align, 0);
}

// Host-region allocator: same first-fit discipline over the host span
// map; returned addresses carry HOST_ADDR_BIT.
uint64_t Engine::alloc_host(uint64_t nbytes, uint64_t align) {
  MutexLock g(mem_mu_);
  if (hostmem_.empty()) {
    hostmem_.resize(host_region_bytes_);
    host_spans_[0x1000] = hostmem_.size() - 0x1000;
  }
  return alloc_first_fit(host_spans_, alloc_sizes_, nbytes, align,
                         HOST_ADDR_BIT);
}

void Engine::free_addr(uint64_t addr) {
  MutexLock g(mem_mu_);
  auto it = alloc_sizes_.find(addr);
  if (it == alloc_sizes_.end()) return;
  uint64_t size = it->second;
  alloc_sizes_.erase(it);
  auto& spans = (addr & HOST_ADDR_BIT) ? host_spans_ : free_spans_;
  addr &= ~HOST_ADDR_BIT;
  // insert + merge with neighbors
  auto next = spans.lower_bound(addr);
  if (next != spans.end() && addr + size == next->first) {
    size += next->second;
    next = spans.erase(next);
  }
  if (next != spans.begin()) {
    auto prev = std::prev(next);
    if (prev->first + prev->second == addr) {
      prev->second += size;
      return;
    }
  }
  spans[addr] = size;
}

// Host-side reads/writes take mem_mu_ like every other memory toucher
// (before r14 they ran bare — an unlocked-read class the TSA lane now
// rejects: a host read racing the hostmem_ lazy commit in alloc_host
// observed a vector mid-resize).
bool Engine::read_mem(uint64_t addr, void* dst, uint64_t n) {
  MutexLock g(mem_mu_);
  auto& region = (addr & HOST_ADDR_BIT) ? hostmem_ : devicemem_;
  addr &= ~HOST_ADDR_BIT;
  if (addr + n > region.size()) return false;
  std::memcpy(dst, region.data() + addr, n);
  return true;
}

bool Engine::write_mem(uint64_t addr, const void* src, uint64_t n) {
  MutexLock g(mem_mu_);
  auto& region = (addr & HOST_ADDR_BIT) ? hostmem_ : devicemem_;
  addr &= ~HOST_ADDR_BIT;
  if (addr + n > region.size()) return false;
  std::memcpy(region.data() + addr, src, n);
  return true;
}

uint8_t* Engine::mem(uint64_t addr, uint64_t n) {
  auto& region = (addr & HOST_ADDR_BIT) ? hostmem_ : devicemem_;
  bool host = addr & HOST_ADDR_BIT;
  addr &= ~HOST_ADDR_BIT;
  if (addr + n > region.size() || (n > 0 && addr == 0)) {
    // schedule addressing bug: flag it AND make it loud — the sticky
    // error alone surfaces at retcode-decode distance, far from the
    // faulting schedule step (round-2 review weak #6/#7).  Writes land
    // in a thread-local bitbucket so the engine stays memory-safe.
    sticky_err_ |= DMA_SIZE_ERROR;
    std::fprintf(stderr,
                 "[accl engine %u] OOB %s-mem access addr=%#llx n=%llu "
                 "(region %llu bytes) — DMA_SIZE_ERROR\n",
                 global_rank_, host ? "host" : "device",
                 (unsigned long long)addr, (unsigned long long)n,
                 (unsigned long long)region.size());
    static thread_local std::vector<uint8_t> bitbucket;
    bitbucket.assign(std::max<uint64_t>(n, 64), 0);
    return bitbucket.data();
  }
  return region.data() + addr;
}

// ---------------------------------------------------------------------------
// call path
// ---------------------------------------------------------------------------
uint64_t Engine::start_call(const uint32_t* w15) {
  CallDesc c;
  std::copy(w15, w15 + 15, c.w.begin());
  c.id = next_call_id_++;
  {
    MutexLock g(results_mu_);
    results_[c.id] = CallResult{};
  }
  det_live_begin();  // liveness token, returned when the call finalizes
  cmd_q_.push(c);
  // a submission racing shutdown(): the finalize sweep may already
  // have run, leaving this call pending forever (its waiter would burn
  // the full wait budget against a dead engine) — finalize inline
  if (stopped_.load()) {
    MutexLock g(results_mu_);
    auto& r = results_[c.id];
    if (!r.done) {
      r.retcode = COMM_ABORTED | RANK_FAILED;
      r.done = true;
      det_live_end();
    }
  }
  return c.id;
}

bool Engine::poll_call(uint64_t id, uint32_t* retcode, double* duration_ns) {
  MutexLock g(results_mu_);
  auto it = results_.find(id);
  if (it == results_.end() || !it->second.done) return false;
  if (retcode) *retcode = it->second.retcode;
  if (duration_ns) *duration_ns = it->second.duration_ns;
  results_.erase(it);
  return true;
}

// ---------------------------------------------------------------------------
// persistent collective plans (r12): parse once, replay whole batches
// ---------------------------------------------------------------------------
int Engine::plan_create(const uint32_t* words, int ncalls) {
  if (!words || ncalls <= 0) return -1;
  EnginePlan plan;
  std::set<uint32_t> comms;
  for (int i = 0; i < ncalls; ++i) {
    std::array<uint32_t, 15> w{};
    std::copy(words + i * 15, words + (i + 1) * 15, w.begin());
    Op op = static_cast<Op>(w[0]);
    if (op != Op::Config && op != Op::Nop && op != Op::Copy &&
        op != Op::Combine)
      comms.insert(w[2]);
    plan.descs.push_back(w);
  }
  for (uint32_t c : comms) {
    if (abort_err(c)) return -1;  // arming against a fenced comm
    plan.comm_epochs.emplace_back(c, epoch_of(c));
  }
  MutexLock g(plans_mu_);
  plans_.push_back(std::move(plan));
  return int(plans_.size()) - 1;
}

long long Engine::plan_replay(int plan_id) {
  std::vector<std::array<uint32_t, 15>> descs;
  {
    MutexLock g(plans_mu_);
    if (plan_id < 0 || plan_id >= int(plans_.size())) return -1;
    EnginePlan& p = plans_[size_t(plan_id)];
    if (!p.valid) return -2;
    // epoch fence: any abort/epoch bump since arm invalidates the
    // plan — a replay must never run on a fenced world
    for (auto& [comm, ep] : p.comm_epochs) {
      if (epoch_of(comm) != ep || abort_err(comm)) {
        p.valid = false;
        return -2;
      }
    }
    descs = p.descs;  // cheap: 15 words per call
  }
  std::vector<uint64_t> ids;
  ids.reserve(descs.size());
  for (auto& w : descs) ids.push_back(start_call(w.data()));
  plan_replays_.fetch_add(1);
  MutexLock g(plans_mu_);
  long long token = next_plan_token_++;
  plan_tokens_[token] = std::move(ids);
  // opportunistic reaper: tokens abandoned without a successful poll
  // (dropped async tickets, timed-out waits) would otherwise pin their
  // id vectors AND the calls' CallResults forever.  Reclaim fully-done
  // stale tokens oldest-first once the map grows past its watermark —
  // bounds the leak at ~256 in-flight/abandoned replays.
  if (plan_tokens_.size() > 256) {
    MutexLock r(results_mu_);
    for (auto it = plan_tokens_.begin();
         it != plan_tokens_.end() && plan_tokens_.size() > 256;) {
      if (it->first == token) break;  // never reap the fresh token
      bool all_done = true;
      for (uint64_t id : it->second) {
        auto rit = results_.find(id);
        if (rit != results_.end() && !rit->second.done) {
          all_done = false;
          break;
        }
      }
      if (all_done) {
        for (uint64_t id : it->second) results_.erase(id);
        it = plan_tokens_.erase(it);
      } else {
        ++it;
      }
    }
  }
  return token;
}

int Engine::plan_poll(long long token, uint32_t* retcode,
                      double* duration_ns) {
  std::vector<uint64_t> ids;
  {
    MutexLock g(plans_mu_);
    auto it = plan_tokens_.find(token);
    if (it == plan_tokens_.end()) return -1;
    ids = it->second;
  }
  uint32_t ret = 0;
  double dur = 0.0;
  {
    MutexLock g(results_mu_);
    for (uint64_t id : ids) {
      auto it = results_.find(id);
      if (it == results_.end() || !it->second.done) return 0;
    }
    for (uint64_t id : ids) {
      auto it = results_.find(id);
      ret |= it->second.retcode;
      dur += it->second.duration_ns;
      results_.erase(it);
    }
  }
  {
    MutexLock g(plans_mu_);
    plan_tokens_.erase(token);
  }
  if (retcode) *retcode = ret;
  if (duration_ns) *duration_ns = dur;
  return 1;
}

void Engine::invalidate_plans(int comm_id) {
  MutexLock g(plans_mu_);
  for (EnginePlan& p : plans_) {
    bool hit = comm_id < 0;
    for (auto& [comm, ep] : p.comm_epochs)
      if (comm_id >= 0 && comm == uint32_t(comm_id)) hit = true;
    if (hit) {
      p.valid = false;
      // an invalid plan can never replay again: free its descriptor
      // storage now (slots are vector indices, so the slot stays)
      p.descs.clear();
      p.descs.shrink_to_fit();
    }
  }
}

void Engine::plan_release(int plan_id) {
  MutexLock g(plans_mu_);
  if (plan_id < 0 || plan_id >= int(plans_.size())) return;
  EnginePlan& p = plans_[size_t(plan_id)];
  p.valid = false;
  p.descs.clear();
  p.descs.shrink_to_fit();
}

int Engine::plan_count() const {
  MutexLock g(plans_mu_);
  int n = 0;
  for (const EnginePlan& p : plans_)
    if (p.valid) ++n;
  return n;
}

// ---------------------------------------------------------------------------
// engine telemetry snapshot (r14): the versioned flat export behind
// capi accl_engine_stats.  FIELD ORDER IS THE ABI — append only, and
// keep ENGINE_STATS_FIELDS_V3 in accl_tpu/observability/telemetry.py
// in lockstep (v2 appends link_rows, r15; v3 appends the quantized
// wire accounting pair, r17).
// ---------------------------------------------------------------------------
int Engine::engine_stats(uint64_t* out, int cap) {
  uint64_t egress_depth = 0;
  {
    MutexLock g(egress_mu_);
    egress_depth = egress_q_.size();
  }
  uint64_t plans_live = 0, plan_tokens = 0;
  {
    MutexLock g(plans_mu_);
    for (const EnginePlan& p : plans_)
      if (p.valid) ++plans_live;
    plan_tokens = plan_tokens_.size();
  }
  uint64_t link_rows = 0;
  {
    MutexLock g(link_mu_);
    link_rows = links_.size();
  }
  const uint64_t fields[] = {
      // -- retransmit store --
      retrans_used_.load(),        // 0 retrans_store_depth
      retrans_evictions_.load(),   // 1 retrans_store_evictions
      retrans_sent_.load(),        // 2 retrans_sent
      nacks_tx_.load(),            // 3 nacks_tx
      nacks_rx_.load(),            // 4 nacks_rx
      fenced_drops_.load(),        // 5 fenced_drops
      // -- rx pool --
      rx_.occupancy(),             // 6 rx_occupancy
      rx_.occupancy_hwm(),         // 7 rx_occupancy_hwm
      rx_.staged(),                // 8 rx_staged
      rx_.staged_hwm(),            // 9 rx_staged_hwm
      rx_.pending(),               // 10 rx_pending
      // -- transport queues --
      egress_depth,                // 11 egress_depth
      egress_hwm_.load(),          // 12 egress_hwm
      uint64_t(std::max(ingress_depth_.load(), 0)),  // 13 ingress_depth
      // -- seek discipline --
      seeks_.load(),               // 14 seeks
      seek_misses_.load(),         // 15 seek_misses
      // -- persistent plans --
      plans_live,                  // 16 plans_live
      plan_tokens,                 // 17 plan_tokens
      plan_replays_.load(),        // 18 plan_replays
      // -- wire validation --
      frames_accepted_.load(),     // 19 wire_accepted_frames
      frames_rejected_.load(),     // 20 wire_rejected_frames
      // -- egress traffic --
      tx_msgs_.load(),             // 21 tx_msgs
      tx_payload_bytes_.load(),    // 22 tx_payload_bytes
      // -- elastic membership --
      joins_sponsored_.load(),     // 23 joins_sponsored
      joins_completed_.load(),     // 24 joins_completed
      // -- per-link wire telemetry (v2, r15) --
      link_rows,                   // 25 link_rows
      // -- quantized wire accounting (v3, r17) --
      compressed_tx_bytes_.load(),          // 26 compressed_tx_bytes
      compressed_tx_logical_bytes_.load(),  // 27 compressed_tx_logical_bytes
  };
  const int total = int(sizeof(fields) / sizeof(fields[0]));
  if (out) {
    int n = cap < total ? (cap < 0 ? 0 : cap) : total;
    for (int i = 0; i < n; ++i) out[i] = fields[i];
  }
  return total;
}

// ---------------------------------------------------------------------------
// per-link wire telemetry (r15): (comm, peer) counter rows behind capi
// accl_engine_link_stats.  ROW FIELD ORDER IS THE ABI — keep
// LINK_STATS_FIELDS_V2 in accl_tpu/observability/telemetry.py in
// lockstep.  Bump helpers are leaf-lock one-liners so the egress/
// ingress funnels pay one uncontended lock + map find per message.
// ---------------------------------------------------------------------------
void Engine::link_count(uint32_t comm, uint32_t peer,
                        uint64_t LinkCounters::*field, uint64_t add) {
  if (!link_peer_ok(comm, peer)) return;
  MutexLock g(link_mu_);
  links_[{comm, peer}].*field += add;
}

void Engine::link_tx(uint32_t comm, uint32_t peer, uint64_t bytes) {
  if (!link_peer_ok(comm, peer)) return;
  MutexLock g(link_mu_);
  LinkCounters& c = links_[{comm, peer}];
  c.tx_msgs += 1;
  c.tx_bytes += bytes;
}

void Engine::link_rx(uint32_t comm, uint32_t peer, uint64_t bytes) {
  if (!link_peer_ok(comm, peer)) return;
  MutexLock g(link_mu_);
  LinkCounters& c = links_[{comm, peer}];
  c.rx_msgs += 1;
  c.rx_bytes += bytes;
}

int Engine::link_stats(uint64_t* out, int cap) {
  MutexLock g(link_mu_);
  const int total = int(links_.size()) * kLinkStatsStride;
  if (out && cap > 0) {
    // whole rows only: a short buffer truncates at a row boundary so
    // the decoder can never mis-slice a torn row
    int rows = std::min(cap, total) / kLinkStatsStride;
    int i = 0;
    for (const auto& [key, c] : links_) {
      if (i >= rows) break;
      uint64_t* row = out + ptrdiff_t(i) * kLinkStatsStride;
      row[0] = key.first;       // comm
      row[1] = key.second;      // peer (comm-local rank)
      row[2] = c.tx_msgs;
      row[3] = c.tx_bytes;
      row[4] = c.rx_msgs;
      row[5] = c.rx_bytes;
      row[6] = c.retrans_sent;
      row[7] = c.nacks_tx;
      row[8] = c.nacks_rx;
      row[9] = c.fenced_drops;
      row[10] = c.seeks;
      row[11] = c.seek_wait_ns;
      row[12] = c.comp_tx_bytes;
      ++i;
    }
  }
  return total;
}

void Engine::push_krnl(const uint8_t* data, uint64_t n) {
  krnl_in_.push(std::vector<uint8_t>(data, data + n));
}

std::shared_ptr<Fifo<std::vector<uint8_t>>> Engine::stream_for(uint32_t strm) {
  MutexLock g(streams_mu_);
  auto& slot = streams_[strm];
  if (!slot) slot = std::make_shared<Fifo<std::vector<uint8_t>>>();
  return slot;
}

bool Engine::pop_stream(uint32_t strm, uint8_t* dst, uint64_t cap,
                        uint64_t* got, int timeout_ms) {
  auto v = stream_for(strm)->pop_wait(milliseconds(timeout_ms));
  if (!v) return false;
  uint64_t n = std::min<uint64_t>(cap, v->size());
  if (n) std::memcpy(dst, v->data(), n);
  if (got) *got = n;
  return true;
}

// ---------------------------------------------------------------------------
// egress funnel — every wire message leaves through here so the test
// harness can inject one-shot faults (drop / duplicate / seqn corruption)
// against the detection machinery (SURVEY §5 failure detection)
// ---------------------------------------------------------------------------
void Engine::send_out(uint32_t session, Message&& msg) {
  // kill-rank chaos: a dead engine transmits nothing — its peers see
  // exactly what a crashed process would leave behind
  if (killed_.load()) return;
  // egress accounting (tx_stats): proves in tests whether a payload
  // actually crossed the wire (the p2p direct path must not add here)
  tx_msgs_.fetch_add(1);
  tx_payload_bytes_.fetch_add(msg.payload.size());
  // fault resolution: the one-shot injector forces the draw for the
  // next message (legacy inject_fault semantics, any message type); the
  // seeded chaos plan draws probabilistically for eager dataplane
  // segments only — rendezvous/abort/NACK control is not a chaos target,
  // so recovery under a seeded plan stays deterministic.
  uint32_t kind = fault_.exchange(0);
  if (kind == 0 && msg.hdr.msg_type == uint8_t(MsgType::EgrMsg))
    kind = chaos_draw();
  switch (kind) {
    case 1:  // drop: the message never reaches the wire
      return;
    case 2: {  // duplicate: deliver twice with identical header/seqn
      Message dup;
      dup.hdr = msg.hdr;
      dup.payload = msg.payload;
      stage_egress(session, std::move(dup));
      break;
    }
    case 3:  // corrupt the sequence number
      msg.hdr.seqn += 7;
      break;
    case 4: {  // delay: hold the message past its siblings (reordering)
      uint32_t us;
      {
        MutexLock g(chaos_mu_);
        us = chaos_.delay_us ? chaos_.delay_us : 2000;
      }
      MutexLock g(delay_mu_);
      if (delay_running_) {
        delayed_.push_back(Delayed{
            steady_clock::now() + microseconds(us), session,
            std::move(msg)});
        delay_cv_.notify_all();
        return;
      }
      break;  // teardown already underway: deliver immediately
    }
    default:
      break;
  }
  stage_egress(session, std::move(msg));
}

// Background releaser for chaos-delayed messages: re-stages each held
// segment once its deadline passes, producing REAL reordering on the
// wire (a FIFO stall would delay everything behind it and never open a
// sequence gap for the NACK path to close).
void Engine::delay_loop() {
  UniqueLock lk(delay_mu_);
  while (delay_running_) {
    if (delayed_.empty()) {
      delay_cv_.wait(lk);
      continue;
    }
    auto it = std::min_element(
        delayed_.begin(), delayed_.end(),
        [](const Delayed& a, const Delayed& b) { return a.release < b.release; });
    auto now = steady_clock::now();
    if (it->release > now) {
      cv_wait_until_point(delay_cv_, lk, it->release);
      continue;
    }
    Delayed d = std::move(*it);
    delayed_.erase(it);
    lk.unlock();
    stage_egress(d.session, std::move(d.msg));
    lk.lock();
  }
}

uint32_t Engine::chaos_draw() {
  MutexLock g(chaos_mu_);
  if (!chaos_.armed) return 0;
  // xorshift64*: deterministic per (seed, draw index) — a seeded plan
  // replays the same fault schedule run after run
  uint64_t x = chaos_.rng;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  chaos_.rng = x;
  uint32_t u = uint32_t((x * 0x2545F4914F6CDD1Dull) >> 40) % 1000000u;
  if (u < chaos_.drop_ppm) return 1;
  u -= chaos_.drop_ppm;
  if (u < chaos_.dup_ppm) return 2;
  u -= chaos_.dup_ppm;
  if (u < chaos_.corrupt_ppm) return 3;
  u -= chaos_.corrupt_ppm;
  if (u < chaos_.delay_ppm) return 4;
  return 0;
}

void Engine::set_chaos(uint64_t seed, uint32_t drop_ppm, uint32_t dup_ppm,
                       uint32_t delay_ppm, uint32_t delay_us,
                       uint32_t corrupt_ppm, uint32_t slow_us) {
  MutexLock g(chaos_mu_);
  chaos_.drop_ppm = drop_ppm;
  chaos_.dup_ppm = dup_ppm;
  chaos_.delay_ppm = delay_ppm;
  chaos_.delay_us = delay_us;
  chaos_.corrupt_ppm = corrupt_ppm;
  chaos_.rng = seed ? seed : 0x9E3779B97F4A7C15ull;
  // each rank folds its id in so per-rank streams decorrelate while the
  // whole world stays reproducible from one seed
  chaos_.rng ^= (uint64_t(global_rank_) + 1) * 0xA24BAED4963EE407ull;
  chaos_.armed = drop_ppm || dup_ppm || delay_ppm || corrupt_ppm;
  slow_us_.store(slow_us);
}

void Engine::kill() {
  killed_.store(true);
  // local abort of every comm (no propagation — a dead rank cannot
  // send): this rank's own pending calls finalize fast with RANK_FAILED
  // instead of burning their receive budget against silence
  uint32_t n = comm_count();
  for (uint32_t c = 0; c < n && c < kMaxComms; ++c) {
    comm_epoch_[c].fetch_add(1);
    comm_abort_[c].fetch_or(COMM_ABORTED | RANK_FAILED);
  }
}

// Stage one wire message into the bounded egress window; blocks while
// `pipeline_depth_` segments are already outstanding (the end_move()
// backpressure point of the reference's pipelined send).
void Engine::stage_egress(uint32_t session, Message&& msg) {
  if (tap_on_.load()) {
    // fuzz seed-corpus capture: serialize exactly the wire framing
    // (64-byte header + payload) into a bounded ring.  Taps here, not
    // in send_out, because the control plane (NACK/pong/abort/join)
    // stages directly and must be capturable too.
    std::vector<uint8_t> raw(sizeof(WireHeader) + msg.payload.size());
    std::memcpy(raw.data(), &msg.hdr, sizeof(WireHeader));
    if (!msg.payload.empty())
      std::memcpy(raw.data() + sizeof(WireHeader), msg.payload.data(),
                  msg.payload.size());
    MutexLock g(tap_mu_);
    if (tap_frames_.size() >= kTapCap) tap_frames_.pop_front();
    tap_frames_.push_back(std::move(raw));
  }
  {
    UniqueLock g(egress_mu_);
    // BOUNDED backpressure: ingress handlers send too (NACK, pong,
    // retransmit, rendezvous control) and ingress runs in the SENDER's
    // egress thread, so with every queue at depth the engines form a
    // backpressure cycle through each other — egress thread A parked in
    // B's window, B's in C's, nobody draining.  Waiting forever turns
    // that transient into a distributed deadlock (and wedges shutdown,
    // which joins the loop thread before it stops this writer).  After
    // a receive budget with no slot, overflow the window instead: the
    // deque is unbounded storage, depth is a pacing knob, and a counted
    // overflow beats a silent standstill.
    bool slot = cv_wait_for_pred(
        egress_cv_, g, timeout_budget(), [&]() ACCL_REQUIRES(egress_mu_) {
          return egress_q_.size() < pipeline_depth_.load() ||
                 !egress_running_ || !running_.load();
        });
    if (!egress_running_) return;
    if (!slot) egress_overflows_.fetch_add(1);
    egress_q_.emplace_back(session, std::move(msg));
    uint64_t d = egress_q_.size(), h = egress_hwm_.load();
    while (d > h && !egress_hwm_.compare_exchange_weak(h, d)) {
    }
  }
  egress_cv_.notify_all();
}

void Engine::egress_loop() {
  for (;;) {
    std::pair<uint32_t, Message> item;
    {
      UniqueLock g(egress_mu_);
      egress_cv_.wait(g, [&]() ACCL_REQUIRES(egress_mu_) {
        return !egress_q_.empty() || !egress_running_;
      });
      if (egress_q_.empty()) {
        if (!egress_running_) return;
        continue;
      }
      item = std::move(egress_q_.front());
      egress_q_.pop_front();
    }
    egress_cv_.notify_all();  // wake staging waiters + the drain in ~Engine
    // slow-rank chaos: stall the egress writer per message so this rank
    // lags the gang without dropping anything
    uint32_t stall = slow_us_.load();
    if (stall) det_sleep_for(microseconds(stall));
    try {
      transport_->send(item.first, std::move(item.second));
    } catch (const std::exception& e) {
      // a transport failure (connect refused, peer gone) must not
      // escape this thread — std::terminate would kill the process.
      // The message is dropped; the peer's receive timeout reports it.
      std::fprintf(stderr, "[accl engine %u] egress send failed: %s\n",
                   global_rank_, e.what());
    }
  }
}

// ---------------------------------------------------------------------------
// ingress demux — the depacketizer role: eager payloads to the rx pool,
// kernel-stream payloads to stream FIFOs, rendezvous control up to the
// engine's pending/completion queues (reference: udp_depacketizer.cpp
// strm routing :136-147, rdma_depacketizer notification routing)
// ---------------------------------------------------------------------------
// Structural frame validation — the contract every conforming sender in
// this file upholds, enforced at the receiver so a corrupted/hostile
// frame is COUNTED and DROPPED before any routing interprets it:
//  - msg_type must be a known MsgType;
//  - payload-bearing types (EgrMsg/RndzvsMsg/StateSync) must carry
//    count == payload size (their senders always stamp it so);
//  - comm-addressed types must carry comm_id < kMaxComms (the fence
//    arrays index by it; conforming comm ids are < 64 by construction);
//  - a pool-routed eager segment larger than one rx buffer cannot come
//    from a conforming sender (segmentation quantum) and would be
//    silently TRUNCATED at install — rejected instead.
// Join/Welcome are session-addressed (pre-communicator) and carry no
// payload contract; RndzvsInit's count is an element count, not bytes.
// Block-scaled segments (hdr.compressed == 2, the r17 int8 wire lane)
// additionally carry a self-describing framing header whose scale-row/
// count consistency is validated HERE — a truncated scale row, a
// count/block mismatch or an oversized block is a counted rejection
// before any routing interprets the payload.
static bool i8_segment_ok(const std::vector<uint8_t>& payload) {
  return i8_wire_elems(payload.data(), payload.size()) != UINT64_MAX;
}

bool Engine::frame_ok(const WireHeader& hdr,
                      const std::vector<uint8_t>& payload) {
  const uint64_t payload_bytes = payload.size();
  switch (static_cast<MsgType>(hdr.msg_type)) {
    case MsgType::EgrMsg:
      if (hdr.count != payload_bytes) return false;
      if (hdr.comm_id >= kMaxComms) return false;
      if (hdr.compressed == 2 && !i8_segment_ok(payload)) return false;
      if (hdr.strm < FIRST_KRNL_STREAM && rx_.buf_size() &&
          payload_bytes > rx_.buf_size())
        return false;
      if (hdr.strm >= FIRST_KRNL_STREAM) {
        // stream-route state is minted per (comm, src, strm) from
        // attacker-controlled header fields: reject BEFORE any state
        // exists once the route count or the total parked holdback
        // would exceed its bound (a conforming sender uses a handful
        // of stream ids and an out-of-order window no deeper than the
        // egress pipeline).  Checked here — not in classify() — so a
        // dropped frame is a single counted rejection and
        // ingest_bytes' return code matches the counter.
        MutexLock g(strm_seq_mu_);
        StrmKey key{hdr.comm_id, hdr.src, hdr.strm};
        auto it = strm_in_seq_.find(key);
        if (it == strm_in_seq_.end() &&
            strm_in_seq_.size() >= kMaxStrmRoutes)
          return false;
        uint32_t expect = it == strm_in_seq_.end() ? 0 : it->second;
        if (hdr.seqn > expect) {  // would park in holdback
          if (strm_holdback_.size() >= kMaxStrmHoldbackTotal)
            return false;
          if (!lossy_transport_.load()) {
            size_t held = 0;
            for (const auto& kv : strm_holdback_)
              if (kv.first.first == key) ++held;
            if (held >= kStrmHoldbackLimit) return false;
          }
        }
      }
      return true;
    case MsgType::RndzvsMsg:
      if (hdr.compressed == 2 && !i8_segment_ok(payload)) return false;
      return hdr.comm_id < kMaxComms && hdr.count == payload_bytes;
    case MsgType::RndzvsInit:
    case MsgType::RndzvsWrDone:
    case MsgType::Nack:
    case MsgType::Heartbeat:
    case MsgType::Abort:
      return hdr.comm_id < kMaxComms;
    case MsgType::Join:
    case MsgType::Welcome:
      return true;
    case MsgType::StateSync:
      return hdr.count == payload_bytes;
  }
  return false;  // unknown message type
}

// RAII depth marker for ingress_depth() (see engine.hpp): lets the
// detsched shutdown drill assert no delivery is mid-flight inside a
// detached engine.
namespace {
struct DepthGuard {
  explicit DepthGuard(std::atomic<int>& d) : d_(d) { d_.fetch_add(1); }
  ~DepthGuard() { d_.fetch_sub(1); }
  std::atomic<int>& d_;
};
}  // namespace

void Engine::ingress(Message&& msg) {
  DepthGuard depth(ingress_depth_);
  // kill-rank chaos: a dead engine hears nothing — no pongs, no
  // completions, no deposits (the peer-visible half of kill())
  if (killed_.load()) return;
  if (!frame_ok(msg.hdr, msg.payload)) {
    frames_rejected_.fetch_add(1);
    return;
  }
  frames_accepted_.fetch_add(1);
  classify(std::move(msg));
}

// Test/fuzz hook: the raw-bytes twin of a transport delivery.  Same
// gates, same validation, same routing; returns the accept/reject
// verdict the transport path only counts.
int Engine::ingest_bytes(const uint8_t* data, uint64_t nbytes) {
  if (!data || nbytes < sizeof(WireHeader)) {
    frames_rejected_.fetch_add(1);
    return 1;
  }
  Message msg;
  std::memcpy(&msg.hdr, data, sizeof(WireHeader));
  msg.payload.assign(data + sizeof(WireHeader), data + nbytes);
  if (!frame_ok(msg.hdr, msg.payload)) {
    frames_rejected_.fetch_add(1);
    return 1;
  }
  frames_accepted_.fetch_add(1);
  if (!killed_.load()) classify(std::move(msg));
  return 0;
}

int Engine::tap_read(int idx, uint8_t* out, int cap) const {
  MutexLock g(tap_mu_);
  if (idx < 0 || idx >= int(tap_frames_.size())) return -1;
  const std::vector<uint8_t>& f = tap_frames_[size_t(idx)];
  if (out && cap > 0) {
    size_t n = std::min<size_t>(f.size(), size_t(cap));
    std::memcpy(out, f.data(), n);
  }
  return int(f.size());
}

int Engine::tap_drain(uint8_t* out, int cap) {
  MutexLock g(tap_mu_);
  int off = 0;
  while (!tap_frames_.empty()) {
    const std::vector<uint8_t>& f = tap_frames_.front();
    int need = int(sizeof(uint32_t) + f.size());
    if (off + need > cap) {
      // oversized lone frame can never fit any buffer of this cap
      if (off == 0 && need > cap) tap_frames_.pop_front();
      break;
    }
    uint32_t len = uint32_t(f.size());
    std::memcpy(out + off, &len, sizeof len);
    if (len) std::memcpy(out + off + sizeof len, f.data(), len);
    off += need;
    tap_frames_.pop_front();
  }
  return off;
}

void Engine::classify(Message&& msg) {
  switch (static_cast<MsgType>(msg.hdr.msg_type)) {
    case MsgType::Nack:
      nacks_rx_.fetch_add(1);
      // per-link: hdr.src is the comm-local RECEIVER soliciting us —
      // the peer whose link the loss (and the recovery) belongs to
      link_count(msg.hdr.comm_id, msg.hdr.src, &LinkCounters::nacks_rx);
      note_alive(msg.hdr.comm_id, msg.hdr.src);
      handle_nack(msg.hdr);
      return;
    case MsgType::Heartbeat: {
      // liveness control plane: epoch-agnostic (survivors probe the
      // ABORTED comm while agreeing on the shrink set)
      note_alive(msg.hdr.comm_id, msg.hdr.src);
      if (msg.hdr.count == 1) {  // ping: pong back (count = 0)
        const CommTable* t = comm_ptr(msg.hdr.comm_id);
        if (t && msg.hdr.src < t->rows.size()) {
          Message pong;
          pong.hdr.msg_type = uint8_t(MsgType::Heartbeat);
          pong.hdr.comm_id = msg.hdr.comm_id;
          pong.hdr.src = t->local;
          pong.hdr.count = 0;
          pong.hdr.dst_session = uint16_t(t->rows[msg.hdr.src].session);
          stage_egress(t->rows[msg.hdr.src].session, std::move(pong));
        }
      }
      return;
    }
    case MsgType::Abort:
      note_alive(msg.hdr.comm_id, msg.hdr.src);
      handle_abort(msg.hdr);
      return;
    case MsgType::Join:
      // elastic membership: a joiner (addressed by raw session in
      // hdr.src — it is in no comm table yet) asks for a state sync
      handle_join(msg.hdr);
      return;
    case MsgType::Welcome:
      // informational ack; the payload-bearing StateSync is the apply
      // point (ordering vs StateSync is not guaranteed on every rung,
      // so the joiner keys on StateSync alone)
      return;
    case MsgType::StateSync: {
      std::vector<uint32_t> words(msg.payload.size() / 4);
      if (!words.empty())
        std::memcpy(words.data(), msg.payload.data(), words.size() * 4);
      join_state_.push(std::move(words));
      return;
    }
    default:
      break;
  }
  // epoch fence: data/rendezvous traffic stamped with a dead epoch is
  // dropped at the pool boundary — after an abort, stragglers from the
  // old world can neither land in memory nor satisfy a future seek
  if (msg.hdr.comm_id < kMaxComms &&
      msg.hdr.epoch != comm_epoch_[msg.hdr.comm_id].load()) {
    fenced_drops_.fetch_add(1);
    link_count(msg.hdr.comm_id, msg.hdr.src, &LinkCounters::fenced_drops);
    return;
  }
  // per-link rx accounting: hdr.src is the comm-local SENDER — the
  // peer whose link this dataplane frame crossed (the chaos-
  // attribution test pins that counters land on the true peer, never
  // the local rank)
  link_rx(msg.hdr.comm_id, msg.hdr.src, msg.payload.size());
  // NB: no note_alive here — liveness piggybacks on the CONTROL plane
  // only (Heartbeat/Nack/Abort above).  The probe actively pings, so
  // stamping every data segment would buy nothing and cost the hot
  // ingress path a mutex + map walk per message.
  switch (static_cast<MsgType>(msg.hdr.msg_type)) {
    case MsgType::EgrMsg:
      if (msg.hdr.strm >= FIRST_KRNL_STREAM) {
        // resequence per (comm, src, stream): non-FIFO transports (the
        // datagram rung) may deliver stream messages out of order, and
        // the stream FIFO has no other ordering discipline
        MutexLock g(strm_seq_mu_);
        StrmKey key{msg.hdr.comm_id, msg.hdr.src, msg.hdr.strm};
        uint32_t& expect = strm_in_seq_[key];
        if (msg.hdr.seqn == expect) {
          stream_for(msg.hdr.strm)->push(std::move(msg.payload));
          ++expect;
          for (auto it = strm_holdback_.find({key, expect});
               it != strm_holdback_.end();
               it = strm_holdback_.find({key, expect})) {
            stream_for(msg.hdr.strm)->push(std::move(it->second));
            strm_holdback_.erase(it);
            ++expect;
          }
        } else if (msg.hdr.seqn > expect) {
          // holdback growth is pre-bounded by frame_ok (route count,
          // per-route window on reliable rungs, total across routes) —
          // a frame reaching this insertion was already admitted
          strm_holdback_[{key, msg.hdr.seqn}] = std::move(msg.payload);
          // loss recovery: a hole that parks too many successors means
          // the expected message was lost on a lossy rung — resync to
          // the oldest held seqn so the stream drains (bounded memory;
          // the lost payload is simply absent from the FIFO)
          size_t held = 0;
          uint32_t oldest = 0;
          bool have_oldest = false;
          for (const auto& kv : strm_holdback_)
            if (kv.first.first == key) {
              ++held;
              if (!have_oldest ||
                  int32_t(kv.first.second - oldest) < 0) {
                oldest = kv.first.second;
                have_oldest = true;
              }
            }
          if (lossy_transport_ && held > kStrmHoldbackLimit && have_oldest) {
            expect = oldest;
            for (auto it = strm_holdback_.find({key, expect});
                 it != strm_holdback_.end();
                 it = strm_holdback_.find({key, expect})) {
              stream_for(msg.hdr.strm)->push(std::move(it->second));
              strm_holdback_.erase(it);
              ++expect;
            }
          }
        }  // else: stale duplicate, drop
      } else {
        rx_.deposit(std::move(msg));
      }
      break;
    case MsgType::RndzvsInit:
      pending_addrs_.push(RndzvAddr{msg.hdr.comm_id, msg.hdr.src, msg.hdr.tag,
                                    msg.hdr.vaddr, msg.hdr.count});
      break;
    case MsgType::RndzvsMsg:
      // one-sided write into our device memory (the RDMA WRITE landing);
      // the shared land_one_sided applies the consume-write-complete
      // discipline (also run by the direct p2p path)
      land_one_sided(msg.hdr, msg.payload.data(), msg.payload.size());
      break;
    case MsgType::RndzvsWrDone:
      completions_.push(RndzvDone{msg.hdr.comm_id, msg.hdr.src, msg.hdr.tag,
                                  msg.hdr.vaddr});
      break;
    default:  // control types handled above
      break;
  }
}

// ---------------------------------------------------------------------------
// resilience: retransmission lane (NACK-driven eager resend)
// ---------------------------------------------------------------------------
void Engine::store_retrans(uint32_t comm, uint32_t dst, const Message& msg) {
  MutexLock g(retrans_mu_);
  if (retrans_ring_.empty()) retrans_ring_.resize(kRetransCap);
  RetransSlot& s = retrans_ring_[retrans_pos_];
  retrans_pos_ = (retrans_pos_ + 1) % kRetransCap;
  if (s.used)
    retrans_evictions_.fetch_add(1);  // ring wrap over a live slot
  else
    retrans_used_.fetch_add(1);
  s.used = true;
  s.comm = comm;
  s.dst = dst;
  s.msg.hdr = msg.hdr;
  // assign() reuses the recycled slot's capacity: the steady-state
  // per-segment cost is one bounded memcpy, no allocator traffic
  s.msg.payload.assign(msg.payload.begin(), msg.payload.end());
}

void Engine::send_nack(uint32_t comm, uint32_t src, uint32_t tag,
                       uint32_t seqn) {
  const CommTable* t = comm_ptr(comm);
  if (!t || src >= t->rows.size()) return;
  Message m;
  m.hdr.msg_type = uint8_t(MsgType::Nack);
  m.hdr.comm_id = comm;
  m.hdr.tag = tag;
  m.hdr.seqn = seqn;
  m.hdr.src = t->local;
  m.hdr.epoch = epoch_of(comm);
  m.hdr.dst_session = uint16_t(t->rows[src].session);
  nacks_tx_.fetch_add(1);
  // per-link: the NACK solicits the SENDER `src` — the peer whose
  // link lost the segment
  link_count(comm, src, &LinkCounters::nacks_tx);
  // control plane: staged directly (not a chaos target, see send_out)
  stage_egress(t->rows[src].session, std::move(m));
}

void Engine::handle_nack(const WireHeader& hdr) {
  // resend every stored segment on (comm, requester, tag) from the
  // requested seqn on, in seqn order — one NACK round closes a
  // multi-segment hole (the receiver evicted its suspect window).
  // Linear ring scan: this is the fault path; the no-fault store stays
  // index-free so the hot path pays nothing for our convenience here.
  std::vector<Message> out;
  {
    MutexLock g(retrans_mu_);
    for (const RetransSlot& s : retrans_ring_) {
      // a wildcard-tag NACK (a TAG_ANY recv's seek pairs with any
      // tag, so its solicitation must too) matches the whole route —
      // tag-exact matching there would strand concretely-tagged
      // segments the receiver evicted and is now waiting for
      if (s.used && s.comm == hdr.comm_id && s.dst == hdr.src &&
          (hdr.tag == TAG_ANY || s.msg.hdr.tag == hdr.tag) &&
          int32_t(s.msg.hdr.seqn - hdr.seqn) >= 0)
        out.push_back(s.msg);  // copy: the store keeps serving NACKs
    }
  }
  std::sort(out.begin(), out.end(),
            [](const Message& a, const Message& b) {
              return int32_t(a.hdr.seqn - b.hdr.seqn) < 0;
            });
  for (auto& m : out) {
    retrans_sent_.fetch_add(1);
    // per-link: the retransmit serves requester hdr.src's link
    link_count(hdr.comm_id, hdr.src, &LinkCounters::retrans_sent);
    // clean stored copy, staged directly: a retransmit is the recovery
    // path and must not re-enter the chaos funnel
    if (!killed_.load()) stage_egress(m.hdr.dst_session, std::move(m));
  }
}

// ---------------------------------------------------------------------------
// resilience: abort + epoch fencing
// ---------------------------------------------------------------------------
int Engine::abort_comm(uint32_t comm_id, uint32_t err_bits, bool propagate) {
  const CommTable* t = comm_ptr(comm_id);
  if (!t || comm_id >= kMaxComms) return -1;
  uint32_t new_epoch = comm_epoch_[comm_id].fetch_add(1) + 1;
  comm_abort_[comm_id].fetch_or(err_bits | COMM_ABORTED);
  // reclaim pool buffers pinned by the dead epoch's traffic; fence
  // every persistent plan armed against the pre-abort epoch
  rx_.evict_comm(comm_id);
  invalidate_plans(int(comm_id));
  // stale quantization residuals must not leak into the healed world's
  // error-feedback stream (the dead epoch's error is not ours to carry)
  drop_ef_residuals(int(comm_id));
  if (propagate && !killed_.load()) {
    for (uint32_t i = 0; i < t->rows.size(); ++i) {
      if (i == t->local) continue;
      Message m;
      m.hdr.msg_type = uint8_t(MsgType::Abort);
      m.hdr.comm_id = comm_id;
      m.hdr.src = t->local;
      m.hdr.count = err_bits | COMM_ABORTED;
      m.hdr.epoch = new_epoch;
      m.hdr.dst_session = uint16_t(t->rows[i].session);
      stage_egress(t->rows[i].session, std::move(m));
    }
  }
  return 0;
}

void Engine::handle_abort(const WireHeader& hdr) {
  uint32_t comm = hdr.comm_id;
  if (comm >= kMaxComms || !comm_ptr(comm)) return;
  // adopt the highest epoch seen (monotonic: a replayed abort cannot
  // roll the fence back)
  uint32_t cur = comm_epoch_[comm].load();
  while (int32_t(hdr.epoch - cur) > 0 &&
         !comm_epoch_[comm].compare_exchange_weak(cur, hdr.epoch)) {
  }
  comm_abort_[comm].fetch_or(hdr.count | COMM_ABORTED);
  rx_.evict_comm(comm);
  invalidate_plans(int(comm));
  drop_ef_residuals(int(comm));
  // pending calls on this comm finalize on the engine loop's next
  // sweep; blocked eager seeks notice within one recovery slice
}

void Engine::reset_errors() {
  // collective recovery op on a QUIESCED world: zero both directions'
  // sequence counters (every rank does the same, so the world agrees),
  // drain transient receive/retransmit state, clear armed faults and
  // abort flags.  Epochs stay bumped: old-epoch stragglers remain
  // fenced forever.
  {
    MutexLock g(cfg_mu_);
    for (auto& t : comms_) {
      std::fill(t->inbound_seq.begin(), t->inbound_seq.end(), 0);
      std::fill(t->outbound_seq.begin(), t->outbound_seq.end(), 0);
    }
  }
  rx_.clear_pending();
  {
    MutexLock g(retrans_mu_);
    for (RetransSlot& s : retrans_ring_) s.used = false;
    retrans_pos_ = 0;
    retrans_used_.store(0);
  }
  {
    MutexLock g(strm_seq_mu_);
    strm_in_seq_.clear();
    strm_holdback_.clear();
  }
  fault_.store(0);
  for (uint32_t c = 0; c < kMaxComms; ++c) comm_abort_[c].store(0);
  // plan-cache eviction fires here too (not only on abort): a healed
  // world must re-capture, never replay pre-reset descriptor state
  invalidate_plans(-1);
  drop_ef_residuals(-1);
}

// ---------------------------------------------------------------------------
// elastic membership (r11): Join/Welcome/StateSync
// ---------------------------------------------------------------------------
// Sponsor side: serialize this engine's per-comm recovery state and send
// it to the joiner.  Word layout (all u32):
//   [ncomms, then per comm: size, epoch, abort_bits,
//    then size x {inbound_seq[i], outbound_seq[i]}]
// The epoch/abort columns are the load-bearing state (the joiner must
// fence the dead world's traffic and align its comm-id space); the seqn
// rows document the sponsor's pairwise view — a comm the joiner becomes
// a member of is always a FRESH id, whose pairwise seqn state starts at
// zero on every member by construction.
void Engine::handle_join(const WireHeader& hdr) {
  joins_sponsored_.fetch_add(1);
  uint32_t joiner = hdr.src;  // raw session id, pre-communicator
  std::vector<uint32_t> words;
  {
    MutexLock g(cfg_mu_);
    words.push_back(uint32_t(comms_.size()));
    for (uint32_t ci = 0; ci < comms_.size(); ++ci) {
      const CommTable& t = *comms_[ci];
      words.push_back(t.size);
      words.push_back(epoch_of(ci));
      words.push_back(abort_err(ci));
      for (uint32_t i = 0; i < t.size; ++i) {
        words.push_back(i < t.inbound_seq.size() ? t.inbound_seq[i] : 0);
        words.push_back(i < t.outbound_seq.size() ? t.outbound_seq[i] : 0);
      }
    }
  }
  Message wel;
  wel.hdr.msg_type = uint8_t(MsgType::Welcome);
  wel.hdr.src = global_rank_;
  wel.hdr.count = words[0];
  wel.hdr.dst_session = uint16_t(joiner);
  stage_egress(joiner, std::move(wel));
  Message ss;
  ss.hdr.msg_type = uint8_t(MsgType::StateSync);
  ss.hdr.src = global_rank_;
  ss.hdr.count = uint32_t(words.size() * 4);
  ss.hdr.dst_session = uint16_t(joiner);
  ss.payload.resize(words.size() * 4);
  std::memcpy(ss.payload.data(), words.data(), ss.payload.size());
  stage_egress(joiner, std::move(ss));
}

int Engine::join_sync(uint32_t sponsor_session, int timeout_ms) {
  if (killed_.load()) return -1;
  Message m;
  m.hdr.msg_type = uint8_t(MsgType::Join);
  m.hdr.src = global_rank_;
  m.hdr.count = 1;
  m.hdr.dst_session = uint16_t(sponsor_session);
  stage_egress(sponsor_session, std::move(m));
  auto words = join_state_.pop_wait(milliseconds(timeout_ms));
  if (!words) return -1;  // sponsor deaf/dead inside the wait budget
  apply_state_sync(*words);
  joins_completed_.fetch_add(1);
  return 0;
}

void Engine::apply_state_sync(const std::vector<uint32_t>& w) {
  if (w.empty()) return;
  uint32_t ncomms = w[0];
  size_t i = 1;
  MutexLock g(cfg_mu_);
  for (uint32_t ci = 0; ci < ncomms && ci < kMaxComms; ++ci) {
    if (i >= w.size()) break;
    uint32_t size = w[i++];
    uint32_t epoch = i < w.size() ? w[i++] : 0;
    uint32_t abort = i < w.size() ? w[i++] : 0;
    i += size_t(size) * 2;  // sponsor's pairwise seqn rows (diagnostic)
    // pad with placeholder slots so the NEXT set_comm on this engine
    // lands at the same index as the survivors' next create; a call on
    // a placeholder finalizes fast in loop() instead of scheduling
    while (comms_.size() <= ci) comms_.push_back(std::make_unique<CommTable>());
    // adopt the fence monotonically (a replayed sync cannot roll back)
    uint32_t cur = comm_epoch_[ci].load();
    while (int32_t(epoch - cur) > 0 &&
           !comm_epoch_[ci].compare_exchange_weak(cur, epoch)) {
    }
    comm_abort_[ci].fetch_or(abort);
  }
}

uint32_t Engine::comm_count() const {
  MutexLock g(cfg_mu_);
  return uint32_t(comms_.size());
}

// ---------------------------------------------------------------------------
// resilience: liveness
// ---------------------------------------------------------------------------
void Engine::note_alive(uint32_t comm, uint32_t src) {
  uint64_t now = uint64_t(
      duration_cast<nanoseconds>(steady_clock::now().time_since_epoch())
          .count());
  MutexLock g(live_mu_);
  last_heard_ns_[{comm, src}] = now;
}

uint64_t Engine::probe_liveness(uint32_t comm_id, uint32_t window_us) {
  const CommTable* t = comm_ptr(comm_id);
  if (!t) return 0;
  // rows are immutable after publication: lock-free reads (CommTable)
  uint32_t local = t->local, nranks = t->size;
  std::vector<uint32_t> sessions;
  for (const auto& r : t->rows) sessions.push_back(r.session);
  uint64_t start_ns = uint64_t(
      duration_cast<nanoseconds>(steady_clock::now().time_since_epoch())
          .count());
  uint64_t alive = nranks < 64 ? (1ull << local) : 0;
  if (killed_.load()) return alive;
  for (uint32_t i = 0; i < nranks; ++i) {
    if (i == local) continue;
    Message m;
    m.hdr.msg_type = uint8_t(MsgType::Heartbeat);
    m.hdr.comm_id = comm_id;
    m.hdr.src = local;
    m.hdr.count = 1;  // ping: reply requested
    m.hdr.dst_session = uint16_t(sessions[i]);
    stage_egress(sessions[i], std::move(m));
  }
  auto deadline = steady_clock::now() + microseconds(window_us);
  uint64_t want = nranks < 64 ? (1ull << nranks) - 1 : ~0ull;
  for (;;) {
    {
      MutexLock g(live_mu_);
      for (uint32_t i = 0; i < nranks && i < 64; ++i) {
        if (i == local) continue;
        auto it = last_heard_ns_.find({comm_id, i});
        if (it != last_heard_ns_.end() && it->second >= start_ns)
          alive |= 1ull << i;
      }
    }
    if (alive == want || steady_clock::now() >= deadline) break;
    det_sleep_for(microseconds(500));
  }
  return alive;
}

// Shared landing for one-sided writes (wire ingress AND direct p2p).
//
// The depacketizer converts the wire representation into the landing
// representation using OUR OWN posted-address record (the eager path's
// own-flag-algebra discipline; the sender's header is advisory only) —
// this is the ETH-compressed rendezvous path.
//
// The whole consume-write-complete sequence holds posted_mu_:
// retry-queue expiry tears records down under the same lock, so a
// concurrent landing either fully completes BEFORE the teardown (its
// completion is then drained) or finds no record and drops — there is
// no window where a write lands or a completion surfaces after the
// teardown decided the call is dead.
void Engine::land_one_sided(const WireHeader& hdr, const uint8_t* payload,
                            uint64_t payload_bytes) {
  MutexLock pg(posted_mu_);
  std::optional<PostedRndzv> post;
  {
    auto it =
        posted_.find(PostedKey{hdr.comm_id, hdr.src, hdr.tag, hdr.vaddr});
    if (it != posted_.end()) {
      post = it->second;
      posted_.erase(it);
    }
  }
  // Landing REQUIRES our own posted record: every legitimate write
  // answers an RNDZVS_INIT we sent, so a write with no record is a
  // stale arrival for an expired call — dropping it (and emitting no
  // completion) is what keeps reused memory safe after retry-queue
  // expiry tears the record down.
  if (!post) return;
  {
    // the landing address may be tagged host-resident (host-only
    // rendezvous buffers); resolve the region like mem() does — the
    // region reference is bound UNDER mem_mu_ (binding it outside was
    // itself an unlocked read of the lazily-committed hostmem_)
    MutexLock g(mem_mu_);
    auto& region = (hdr.vaddr & HOST_ADDR_BIT) ? hostmem_ : devicemem_;
    uint64_t vaddr = hdr.vaddr & ~HOST_ADDR_BIT;
    if (post->wire_c && post->blk) {
      // block-scaled rendezvous landing: the segment is
      // self-describing — decode/validate against our posted geometry
      // and dequantize into the fp32 landing buffer (lnd_c is always
      // false for the int8 pair; the driver rejects int8 residence).
      // A segment that fails the pinned-geometry decode (divergent
      // block size, elems beyond the posted count) must NOT surface a
      // completion: the landing buffer was never written, and a
      // completed recv over stale bytes would be silent corruption —
      // withholding RndzvDone lets the receiver's budget classify the
      // failure loudly (sticky_err_ is loop-thread-only, so the
      // ingress thread cannot stamp COMPRESSION_ERROR itself).
      uint64_t elems = i8_wire_elems(payload, payload_bytes, post->blk);
      uint64_t lnd_bytes =
          elems == UINT64_MAX ? 0 : elems * post->ub;
      if (elems == UINT64_MAX || elems > post->elems ||
          vaddr + lnd_bytes > region.size() ||
          dequantize_i8_block(payload, payload_bytes,
                              reinterpret_cast<float*>(region.data() + vaddr),
                              elems, post->blk) != OK) {
        frames_rejected_.fetch_add(1);
        return;
      }
    } else if (post->wire_c != post->lnd_c) {
      // clamp to what actually arrived: a short payload (divergent
      // arithcfg, stale posted entry) must not read past the wire
      // buffer
      uint64_t wire_eb = post->wire_c ? post->cb : post->ub;
      uint64_t elems = std::min<uint64_t>(
          post->elems, payload_bytes / std::max<uint64_t>(1, wire_eb));
      uint64_t lnd_bytes = elems * (post->lnd_c ? post->cb : post->ub);
      if (vaddr + lnd_bytes <= region.size()) {
        if (post->wire_c)
          run_decompress_lane(post->comp_kind, payload,
                              region.data() + vaddr, elems);
        else
          run_compress_lane(post->comp_kind, payload,
                            region.data() + vaddr, elems);
      }
    } else if (payload_bytes && vaddr + payload_bytes <= region.size()) {
      std::memcpy(region.data() + vaddr, payload, payload_bytes);
    }
  }
  completions_.push(RndzvDone{hdr.comm_id, hdr.src, hdr.tag, hdr.vaddr});
}

// ---------------------------------------------------------------------------
// explicit session lifecycle (reference tcp_session_handler; see engine.hpp)
// ---------------------------------------------------------------------------
int Engine::open_con(uint32_t comm_id) {
  // row reads are lock-free (immutable after publication) — holding
  // cfg_mu_ across the blocking connect attempts would stall ingress
  const CommTable* t = comm_ptr(comm_id);
  if (!t || t->rows.empty()) return -1;
  for (uint32_t i = 0; i < t->rows.size(); ++i) {
    if (i == t->local) continue;
    if (transport_->open_session(t->rows[i].session) != 0) return int(i) + 1;
  }
  return 0;
}

int Engine::close_con(uint32_t comm_id) {
  const CommTable* t = comm_ptr(comm_id);
  if (!t || t->rows.empty()) return -1;
  for (uint32_t i = 0; i < t->rows.size(); ++i) {
    if (i == t->local) continue;
    // closing a never-opened session is not a failure of the teardown
    // sweep (the lazy path may simply never have connected yet)
    transport_->close_session(t->rows[i].session);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// p2p buffer windows (FPGABufferP2P analog — see engine.hpp)
// ---------------------------------------------------------------------------
void Engine::register_p2p(uint64_t addr, uint64_t bytes) {
  MutexLock g(p2p_mu_);
  p2p_spans_[addr] = bytes;
}

void Engine::unregister_p2p(uint64_t addr) {
  MutexLock g(p2p_mu_);
  p2p_spans_.erase(addr);
}

bool Engine::p2p_covers(uint64_t addr, uint64_t bytes) const {
  MutexLock g(p2p_mu_);
  auto it = p2p_spans_.upper_bound(addr);
  if (it == p2p_spans_.begin()) return false;
  --it;
  return addr >= it->first && addr + bytes <= it->first + it->second;
}

uint8_t* Engine::raw_mem(uint64_t addr, uint64_t bytes) {
  MutexLock g(mem_mu_);
  if (addr & HOST_ADDR_BIT) return nullptr;  // p2p windows are devicemem
  if (addr == 0 || addr + bytes > devicemem_.size()) return nullptr;
  return devicemem_.data() + addr;
}

void Engine::land_p2p(const WireHeader& hdr, const uint8_t* payload,
                      uint64_t payload_bytes) {
  // same gates as wire ingress: a killed rank hears nothing, and
  // dead-epoch traffic is fenced (the posted-record requirement below
  // already drops writes for torn-down calls; this keeps the two
  // ingress paths gate-for-gate identical)
  if (killed_.load()) return;
  if (hdr.comm_id < kMaxComms &&
      hdr.epoch != comm_epoch_[hdr.comm_id].load()) {
    fenced_drops_.fetch_add(1);
    link_count(hdr.comm_id, hdr.src, &LinkCounters::fenced_drops);
    return;
  }
  // per-link rx: the direct p2p landing is the same inter-rank traffic
  // as a wire delivery (gate-for-gate identical ingress discipline)
  link_rx(hdr.comm_id, hdr.src, payload_bytes);
  land_one_sided(hdr, payload, payload_bytes);
}

// ---------------------------------------------------------------------------
// engine event loop (fw run_accl :2264-2306): new calls take priority;
// retried rendezvous calls progress cooperatively in between.
// ---------------------------------------------------------------------------
void Engine::loop() {
  while (running_) {
    CallDesc c;
    bool have = false;
    if (auto o = cmd_q_.try_pop()) {
      c = *o;
      have = true;
    } else if (!retry_q_.empty()) {
      c = retry_q_.front();
      retry_q_.pop_front();
      have = true;
    } else if (auto o2 = cmd_q_.pop_wait(milliseconds(2))) {
      c = *o2;
      have = true;
    }
    if (!have) continue;

    if (c.first_try_ns == 0)
      retry_idle_sweeps_ = 0;  // new call admitted: reset retry pacing

    // abort fence: a call on an aborted communicator finalizes fast with
    // the abort's error bits — whether it was freshly admitted or came
    // back through the retry queue (this is what wakes a rendezvous
    // blocked on a dead peer within one retry sweep).  Config/Nop stay
    // executable: bring-up and soft reset must work on any comm state.
    if (c.scenario() != Op::Config && c.scenario() != Op::Nop) {
      uint32_t ab = abort_err(c.comm());
      // elastic membership: a placeholder comm slot (minted by a join
      // state sync to align comm-id spaces, size 0) carries no rank
      // table — a call on it must finalize as a fenced/dead comm, not
      // divide a collective schedule by zero.  Local ops (copy/combine)
      // never consult the table and stay executable.
      if (!ab && c.scenario() != Op::Copy && c.scenario() != Op::Combine &&
          comm_for(c).size == 0)
        ab = COMM_ABORTED | RANK_FAILED;
      if (ab) {
        teardown_call(c);
        MutexLock g(results_mu_);
        auto& r = results_[c.id];
        r.retcode = ab;
        r.duration_ns = 0.0;
        r.done = true;
        det_live_end();
        continue;
      }
    }

    auto t0 = steady_clock::now();
    // the retry budget ticks on the det-aware clock (virtual under the
    // model checker) while duration telemetry stays on the real one
    if (c.first_try_ns == 0)
      c.first_try_ns = uint64_t(
          duration_cast<nanoseconds>(det_clock_now().time_since_epoch())
              .count() +
          1);
    uint32_t step_before = c.current_step;
    sticky_err_ = 0;
    bool retry = false;
    try {
      uint32_t ret = execute(c);
      retry_idle_sweeps_ = 0;  // a call completed: the world moved
      auto dt = duration_cast<nanoseconds>(steady_clock::now() - t0).count();
      MutexLock g(results_mu_);
      auto& r = results_[c.id];
      r.retcode = ret;
      r.duration_ns = double(dt);
      r.done = true;
      det_live_end();
    } catch (NotReadyEx&) {
      retry = true;
    }
    if (retry) {
      // the budget is PER RECEIVE, like the blocking eager seek: any
      // step progress restarts the clock (+1 keeps the stamp distinct
      // from the 0 = "never tried" sentinel on the virtual clock,
      // whose epoch starts at 0)
      if (c.current_step != step_before)
        c.first_try_ns = uint64_t(
            duration_cast<nanoseconds>(det_clock_now().time_since_epoch())
                .count() +
            1);
      // expire stalled calls against the receive budget (see CallDesc
      // .first_try_ns): a peer that never arrives must surface as the
      // engine's own RECEIVE_TIMEOUT_ERROR, not as a host-side hang
      auto waited = duration_cast<nanoseconds>(
                        det_clock_now().time_since_epoch())
                        .count() -
                    int64_t(c.first_try_ns);
      if (waited > timeout_budget().count()) {
        teardown_call(c);
        MutexLock g(results_mu_);
        auto& r = results_[c.id];
        r.retcode = sticky_err_ | RECEIVE_TIMEOUT_ERROR;
        r.duration_ns = double(waited);
        r.done = true;
        det_live_end();
      } else {
        retry_q_.push_back(c);
        // cooperative pacing: the firmware round-robins between the
        // host cmd stream and the retry FIFO with no sleep at all
        // (fw :2264-2288).  A fixed sleep here puts a latency floor
        // under every contended rendezvous, so pace adaptively —
        // yield while the queue is freshly unproductive (the peer is
        // usually microseconds away), escalate to a growing bounded
        // sleep only when sweeps keep coming back empty-handed.
        if (c.current_step != step_before) {
          retry_idle_sweeps_ = 0;  // step progress: stay hot
        } else if (++retry_idle_sweeps_ <= 64) {
          det_yield();
        } else {
          det_sleep_for(microseconds(
              std::min<uint32_t>(200, retry_idle_sweeps_ - 64)));
        }
      }
    }
  }
}

// Tear down one call's rendezvous protocol state + scratch leases —
// shared by retry-budget expiry and abort finalization: erase the
// landing records it advertised (a late one-sided write must NOT land
// into memory about to be reused) and drain any completions already
// surfaced for them (a future call reusing the address must not see a
// stale success).  posted_mu_ is held across BOTH so a landing racing
// with teardown either completes fully before the drain (ingress holds
// the same lock through consume-write-complete) or finds no record and
// drops; the drain matches the exact posted vaddr so a concurrent
// healthy call's completion on the same (comm, src, tag) survives.
void Engine::teardown_call(CallDesc& c) {
  {
    MutexLock g(posted_mu_);
    for (const auto& k : c.rndzv_posts) {
      posted_.erase(PostedKey{uint32_t(k[0]), uint32_t(k[1]),
                              uint32_t(k[2]), k[3]});
      while (completions_.pop_match(
          [&](const RndzvDone& d) {
            return d.comm == uint32_t(k[0]) && d.src == uint32_t(k[1]) &&
                   d.tag == uint32_t(k[2]) && d.vaddr == k[3];
          },
          nanoseconds(0))) {
      }
    }
  }
  // release scratch leases the retries kept alive
  if (c.scratch0) { free_addr(c.scratch0); c.scratch0 = 0; }
  if (c.scratch1) { free_addr(c.scratch1); c.scratch1 = 0; }
}

int Engine::set_tuning(uint32_t key, uint32_t value) {
  switch (key) {
    case BCAST_FLAT_TREE_MAX_RANKS: bcast_flat_max_ranks_ = value; break;
    case REDUCE_FLAT_TREE_MAX_RANKS: reduce_flat_max_ranks_ = value; break;
    case GATHER_FLAT_TREE_MAX_FANIN:
      gather_flat_max_fanin_ = value ? value : 1;
      break;
    case EGRESS_PIPELINE_DEPTH:
      pipeline_depth_ = value ? value : 1;
      break;
    case GATHER_FLAT_TREE_MAX_COUNT:
      gather_flat_max_count_ = value;
      break;
    case REDUCE_FLAT_TREE_MAX_COUNT:
      reduce_flat_max_count_ = value;
      break;
    default:
      return -1;  // unknown register: reject, never silently ignore
  }
  return 0;
}

uint32_t Engine::execute(CallDesc& c) {
  Progress p(c);
  try {
    dispatch(c, p);
  } catch (SizeCapEx&) {
    // size-cap violation: finalize immediately with the sticky error
    // (NotReadyEx, by contrast, propagates to the retry queue)
  }
  // release rendezvous scratch leases (kept alive across retries)
  if (c.scratch0) {
    free_addr(c.scratch0);
    c.scratch0 = 0;
  }
  if (c.scratch1) {
    free_addr(c.scratch1);
    c.scratch1 = 0;
  }
  return sticky_err_;
}

void Engine::dispatch(CallDesc& c, Progress& p) {
  switch (c.scenario()) {
    case Op::Config: do_config(c); break;
    case Op::Nop: break;
    case Op::Copy: {
      // mem<->stream copy variants (reference: accl.cpp copy_to_stream/
      // copy_from_stream wrap copy with RES_STREAM/OP0_STREAM; the
      // dma_mover routes the lane to the external-kernel switch port)
      Dom d = dom(c);
      uint64_t elems = c.count();
      uint64_t bytes = elems * d.ub;  // streams carry uncompressed
      bool op_stream = c.stream_flags() & 0x1;   // OP0_STREAM
      bool res_stream = c.stream_flags() & 0x2;  // RES_STREAM
      // a consumer must not be handed a correctly-sized but corrupt
      // payload: push to the stream only with a clean error state (same
      // guard as the streamed-result reduce path)
      if (op_stream && res_stream) {
        // kernel input port -> named local stream, staged via scratch
        uint64_t tmp = alloc(bytes, 64);
        if (tmp && drain_krnl_to(tmp, bytes) && sticky_err_ == 0)
          push_local_stream(c.tag(), tmp, bytes);
        else if (!tmp)
          sticky_err_ |= DMA_SIZE_ERROR;
        if (tmp) free_addr(tmp);
      } else if (op_stream) {
        if (d.res) {
          // stream -> compressed result buffer: stage then compress
          uint64_t tmp = alloc(bytes, 64);
          if (tmp && drain_krnl_to(tmp, bytes))
            local_move(c, tmp, c.addr2(), elems, false, true);
          else if (!tmp)
            sticky_err_ |= DMA_SIZE_ERROR;
          if (tmp) free_addr(tmp);
        } else {
          drain_krnl_to(c.addr2(), bytes);
        }
      } else if (res_stream) {
        if (d.op0) {
          // compressed operand -> stream: decompress into scratch first
          uint64_t tmp = alloc(bytes, 64);
          if (tmp && local_move(c, c.addr0(), tmp, elems, true, false) == 0)
            push_local_stream(c.tag(), tmp, bytes);
          else if (!tmp)
            sticky_err_ |= DMA_SIZE_ERROR;
          if (tmp) free_addr(tmp);
        } else if (sticky_err_ == 0) {
          push_local_stream(c.tag(), c.addr0(), bytes);
        }
      } else {
        local_move(c, c.addr0(), c.addr2(), elems, d.op0, d.res);
      }
      break;
    }
    case Op::Combine: {
      Dom d = dom(c);
      uint64_t elems = c.count();
      MutexLock g(mem_mu_);
      uint8_t* a0 = mem(c.addr0(), elems * d.eb(d.op0));
      uint8_t* a1 = mem(c.addr1(), elems * d.eb(d.op1));
      uint8_t* r = mem(c.addr2(), elems * d.eb(d.res));
      reduce_mixed(c, a0, d.op0, a1, d.op1, r, d.res, elems);
      break;
    }
    case Op::Send: coll_send(c, p); break;
    case Op::Recv: coll_recv(c, p); break;
    case Op::Bcast: coll_bcast(c, p); break;
    case Op::Scatter: coll_scatter(c, p); break;
    case Op::Gather: coll_gather(c, p); break;
    case Op::Allgather: coll_allgather(c, p); break;
    case Op::Reduce: coll_reduce(c, p); break;
    case Op::ReduceScatter: coll_reduce_scatter(c, p); break;
    case Op::Allreduce: coll_allreduce(c, p); break;
    case Op::Alltoall: coll_alltoall(c, p); break;
    case Op::Barrier: coll_barrier(c, p); break;
    default: sticky_err_ |= COLLECTIVE_NOT_IMPLEMENTED; break;
  }
}

static uint32_t floor_log2(uint32_t v) {
  uint32_t r = 0;
  while (v >>= 1) ++r;
  return r;
}

// Binomial tree broadcast (fw :816-869): each round doubles the set of
// ranks holding the payload; position is measured from the root.
void Engine::tree_bcast(CallDesc& c, Progress& p, uint32_t root,
                        uint64_t src_addr, uint64_t dst_addr, uint64_t elems,
                        bool src_c, bool dst_c) {
  const CommTable& t = comm_for(c);
  uint32_t P = t.size;
  uint32_t pos = (t.local + P - root) % P;
  uint64_t from = src_addr;
  bool from_c = src_c;
  uint32_t k0 = 0;
  if (pos != 0) {
    uint32_t pk = floor_log2(pos);
    uint32_t parent = pos - (1u << pk);
    rndzv_recv(c, p, (root + parent) % P, c.tag(), dst_addr, elems, dst_c);
    // relay: the buffer we received with RES domain becomes the OP0
    // source of the forwarding hops (fw :1408-1411)
    from = dst_addr;
    from_c = dst_c;
    k0 = pk + 1;
  }
  for (uint32_t k = k0; (1u << k) < P; ++k) {
    uint32_t child = pos + (1u << k);
    if (child < P)
      rndzv_send(c, p, (root + child) % P, c.tag(), from, elems, from_c);
  }
}

// Binomial tree reduce (fw :1603-1728): leaves push partials up; interior
// positions fold each child's partial into an accumulator, then forward.
// tmp scratch always holds the uncompressed representation.
void Engine::tree_reduce(CallDesc& c, Progress& p, uint32_t root,
                         uint64_t src_addr, uint64_t acc_addr,
                         uint64_t tmp_addr, uint64_t elems, bool src_c,
                         bool acc_c) {
  const CommTable& t = comm_for(c);
  uint32_t P = t.size;
  uint32_t pos = (t.local + P - root) % P;
  step_local(p, [&] { local_move(c, src_addr, acc_addr, elems, src_c, acc_c); });
  for (uint32_t k = 0; (1u << k) < P; ++k) {
    uint32_t bit = 1u << k;
    if (pos & bit) {
      rndzv_send(c, p, (root + pos - bit) % P, c.tag(), acc_addr, elems,
                 acc_c);
      return;
    }
    if (pos + bit < P) {
      rndzv_recv(c, p, (root + pos + bit) % P, c.tag(), tmp_addr, elems,
                 false);
      step_local(p, [&] {
        Dom d = dom(c);
        MutexLock g(mem_mu_);
        uint8_t* acc = mem(acc_addr, elems * d.eb(acc_c && d.pair));
        uint8_t* tmp = mem(tmp_addr, elems * d.ub);
        reduce_mixed(c, acc, acc_c, tmp, false, acc, acc_c, elems);
      });
    }
  }
}

void Engine::do_config(CallDesc& c) {
  switch (static_cast<CfgFunc>(c.function())) {
    case CfgFunc::ResetPeriph: {
      // soft reset (fw HOUSEKEEP_SWRST :2420-2423): drop transient state
      retry_q_.clear();
      while (pending_addrs_.try_pop()) {}
      while (completions_.try_pop()) {}
      {
        MutexLock g(posted_mu_);
        posted_.clear();
      }
      {
        MutexLock g(strm_seq_mu_);
        strm_in_seq_.clear();
        strm_holdback_.clear();
      }
      strm_out_seq_.clear();
      {
        // the loop thread owns the seq columns, but the pointer vector
        // itself is cfg_mu_-guarded (a concurrent set_comm may grow it)
        MutexLock g(cfg_mu_);
        for (auto& t : comms_) {
          std::fill(t->inbound_seq.begin(), t->inbound_seq.end(), 0);
          std::fill(t->outbound_seq.begin(), t->outbound_seq.end(), 0);
        }
      }
      pkt_enabled_ = false;
      break;
    }
    case CfgFunc::EnablePkt: pkt_enabled_ = true; break;
    case CfgFunc::SetTimeout: timeout_ = c.count(); break;
    case CfgFunc::SetMaxEagerMsgSize:
      // must cover at least one rx buffer (fw :2432-2441)
      if (rx_.buf_size() && c.count() < rx_.buf_size())
        sticky_err_ |= EAGER_THRESHOLD_INVALID;
      else
        max_eager_ = c.count();
      break;
    case CfgFunc::SetMaxRendezvousMsgSize:
      if (c.count() < max_eager_)
        sticky_err_ |= RENDEZVOUS_THRESHOLD_INVALID;
      else
        max_rndzv_ = c.count();
      break;
  }
}

// ---------------------------------------------------------------------------
// helpers
// ---------------------------------------------------------------------------
// Stable-pointer fetch: cfg_mu_ guards the pointer vector (growth);
// the pointee tables are heap-pinned and follow CommTable's per-field
// ownership discipline, so the returned pointer is usable lock-free
// for the rest of the call.
CommTable* Engine::comm_ptr(uint32_t id) const {
  MutexLock g(cfg_mu_);
  return id < comms_.size() ? comms_[id].get() : nullptr;
}

ArithCfgN* Engine::arith_ptr(uint32_t id) const {
  MutexLock g(cfg_mu_);
  return id < arithcfgs_.size() ? arithcfgs_[id].get() : nullptr;
}

// The fallback tables are IMMORTAL by design (leaked, never destroyed):
// a world the host leaked at interpreter exit still has engine threads
// running when __cxa_finalize destroys this library's function-local
// statics — a destroyed fallback under a live loop thread is a
// use-after-free at process exit (the r13 suite-exit segfault class).
const CommTable& Engine::comm_for(const CallDesc& c) const {
  static const CommTable& empty = *new CommTable();
  const CommTable* t = comm_ptr(c.comm());
  return t ? *t : empty;
}

const ArithCfgN& Engine::arith_for(const CallDesc& c) const {
  static const ArithCfgN& dflt = *new ArithCfgN();
  const ArithCfgN* a = arith_ptr(c.arithcfg());
  return a ? *a : dflt;
}

uint64_t Engine::elem_bytes(const CallDesc& c) const {
  return arith_for(c).ubits / 8;
}

Engine::Dom Engine::dom(const CallDesc& c) const {
  const ArithCfgN& a = arith_for(c);
  Dom d;
  d.ub = a.ubits ? a.ubits / 8 : 4;
  d.cb = a.cbits ? a.cbits / 8 : d.ub;
  d.ratio_log = a.ratio_log;
  d.comp_kind = a.compressor;
  d.pair = a.ratio_log > 0;
  uint32_t f = c.compression();
  d.op0 = d.pair && (f & OP0_COMPRESSED);
  d.op1 = d.pair && (f & OP1_COMPRESSED);
  d.res = d.pair && (f & RES_COMPRESSED);
  d.eth = d.pair && (f & ETH_COMPRESSED);
  if (d.pair && a.compressor == I8_BLOCK_COMPRESSOR) {
    d.blk = a.block ? a.block : I8_BLOCK_DEFAULT;
    d.ef = a.error_feedback != 0;
    // per-operand residence is undefined for a scaled segment (the
    // driver rejects it too); only the wire bit is meaningful
    d.op0 = d.op1 = d.res = false;
  }
  return d;
}

uint32_t Engine::convert_elems(const Dom& d, const uint8_t* in, bool in_c,
                               uint8_t* out, bool out_c, uint64_t elems) {
  // zero-element moves are legal (barrier's empty messages) but the
  // pointers may then be null (an empty vector's data()) — and
  // memmove/the lanes declare their pointers nonnull (UBSan)
  if (elems == 0) return OK;
  if (in_c == out_c) {
    std::memmove(out, in, d.wbytes(elems, in_c));
    return OK;
  }
  uint32_t err;
  if (d.blk) {
    // int8 block-scaled lane: the compressed side is a self-describing
    // segment (arith.hpp framing); accumulate/operand side is fp32
    err = in_c ? dequantize_i8_block(in, d.wbytes(elems, true),
                                     reinterpret_cast<float*>(out), elems,
                                     d.blk)
               : (quantize_i8_block(reinterpret_cast<const float*>(in), out,
                                    elems, d.blk),
                  OK);
  } else {
    err = in_c ? run_decompress_lane(d.comp_kind, in, out, elems)
               : run_compress_lane(d.comp_kind, in, out, elems);
  }
  sticky_err_ |= err;
  return err;
}

// Egress quantization with optional EQuARX error feedback: the plain
// path is quantize_i8_block; with the arithcfg's error_feedback word
// set, the per-site residual (comm, dst, source address) is folded in
// and refreshed.  Sites whose element count changed (buffer reuse at a
// different size) reset their residual; the total float budget is
// bounded — saturated worlds quantize feedback-free rather than grow.
void Engine::quantize_egress(const Dom& d, bool use_ef, uint32_t comm,
                             uint32_t dst, uint64_t src_addr,
                             const float* in, uint8_t* out,
                             uint64_t elems) {
  if (!use_ef || elems == 0) {
    quantize_i8_block(in, out, elems, d.blk);
    return;
  }
  MutexLock g(ef_mu_);
  auto it = ef_residual_.find(EfKey{comm, dst, src_addr});
  if (it == ef_residual_.end()) {
    if (ef_floats_ + elems > kEfResidualCapFloats) {
      quantize_i8_block(in, out, elems, d.blk);
      return;
    }
    it = ef_residual_.emplace(EfKey{comm, dst, src_addr},
                              std::vector<float>(elems, 0.0f)).first;
    ef_floats_ += elems;
  } else if (it->second.size() != elems) {
    // same cap discipline as creation: a site regrowing past the
    // budget drops its residual and quantizes feedback-free rather
    // than blowing the bound (buffer reuse at a new size)
    uint64_t grown = ef_floats_ - uint64_t(it->second.size()) + elems;
    if (grown > kEfResidualCapFloats) {
      ef_floats_ -= uint64_t(it->second.size());
      ef_residual_.erase(it);
      quantize_i8_block(in, out, elems, d.blk);
      return;
    }
    ef_floats_ = grown;
    it->second.assign(elems, 0.0f);
  }
  quantize_i8_block(in, out, elems, d.blk, it->second.data());
}

void Engine::drop_ef_residuals(int comm_id) {
  MutexLock g(ef_mu_);
  if (comm_id < 0) {
    ef_residual_.clear();
    ef_floats_ = 0;
    return;
  }
  for (auto it = ef_residual_.begin(); it != ef_residual_.end();) {
    if (std::get<0>(it->first) == uint32_t(comm_id)) {
      ef_floats_ -= it->second.size();
      it = ef_residual_.erase(it);
    } else {
      ++it;
    }
  }
}

uint32_t Engine::reduce_mixed(const CallDesc& c, const uint8_t* a0, bool a0c,
                              const uint8_t* a1, bool a1c, uint8_t* r, bool rc,
                              uint64_t elems) {
  const ArithCfgN& a = arith_for(c);
  Dom d = dom(c);
  // the arithcfg chooses the accumulate domain: mixed-precision pairs run
  // their lanes on the compressed representation when arith_compressed
  // (reference DEFAULT_ARITH_CONFIG {f32,f16} pair, arithconfig.hpp:106-119)
  bool ac = d.pair && a.arith_compressed != 0;
  uint32_t lane =
      c.function() < a.lanes.size() ? a.lanes[c.function()] : uint32_t(NUM_LANES);
  uint64_t abytes = elems * d.eb(ac);
  if (a0c == ac && a1c == ac && rc == ac) {
    uint32_t err = run_reduce_lane(lane, a0, a1, r, abytes);
    sticky_err_ |= err;
    return err;
  }
  thread_local std::vector<uint8_t> s0, s1, sr;
  const uint8_t* p0 = a0;
  const uint8_t* p1 = a1;
  if (a0c != ac) {
    s0.resize(abytes);
    if (convert_elems(d, a0, a0c, s0.data(), ac, elems)) return sticky_err_;
    p0 = s0.data();
  }
  if (a1c != ac) {
    s1.resize(abytes);
    if (convert_elems(d, a1, a1c, s1.data(), ac, elems)) return sticky_err_;
    p1 = s1.data();
  }
  if (rc == ac) {
    uint32_t err = run_reduce_lane(lane, p0, p1, r, abytes);
    sticky_err_ |= err;
    return err;
  }
  sr.resize(abytes);
  uint32_t err = run_reduce_lane(lane, p0, p1, sr.data(), abytes);
  sticky_err_ |= err;
  if (err) return err;
  return convert_elems(d, sr.data(), ac, r, rc, elems);
}

nanoseconds Engine::timeout_budget() const {
  // 1 emulated cycle = 1us (the reference counts 4ns cycles on hardware;
  // the emulator scales so the default 1e6-cycle timeout is 1s of wall
  // clock, tolerant of CI scheduling)
  return microseconds(timeout_);
}

bool Engine::use_rendezvous(const CallDesc& c, uint64_t elems) {
  // eager if small or streamed (fw send :589, recv :669).  Unlike the
  // reference firmware — which forces eager for any nonzero compression
  // flag and leaves compressed rendezvous as a TODO (fw :589, :615-620) —
  // the rendezvous primitives here are domain-aware, so protocol
  // selection depends only on size.  The threshold is measured against
  // the WIRE payload: that is the one quantity both peers of a
  // directional pair (e.g. f16 sender / f32+compress receiver) derive
  // identically from their own arithcfg + ETH flag, so protocol choice
  // can never diverge across ranks.
  Dom d = dom(c);
  uint64_t bytes = d.wbytes(elems, d.eth);
  if (bytes <= max_eager_) return false;
  if (c.stream_flags() != 0) return false;
  // enforce the rendezvous size register as a hard cap (the reference
  // validates the register, fw :2442-2448, but never checks transfers
  // against it; transfers over the cap fail fast instead of wedging)
  if (bytes > max_rndzv_) {
    sticky_err_ |= DMA_SIZE_ERROR;
    throw SizeCapEx{};
  }
  return true;
}

bool Engine::drain_krnl_to(uint64_t addr, uint64_t bytes) {
  uint64_t off = 0;
  while (off < bytes) {
    auto v = krnl_in_.pop_wait(timeout_budget());
    if (!v) {
      sticky_err_ |= SEGMENTER_EXPECTED_BTT_ERROR;
      return false;
    }
    uint64_t n = std::min<uint64_t>(v->size(), bytes - off);
    if (v->size() > bytes - off) sticky_err_ |= SEGMENTER_EXPECTED_BTT_ERROR;
    MutexLock g(mem_mu_);
    if (n) std::memcpy(mem(addr + off, n), v->data(), n);
    off += n;
  }
  return true;
}

void Engine::push_local_stream(uint32_t strm, uint64_t addr, uint64_t bytes) {
  std::vector<uint8_t> out;
  {
    MutexLock g(mem_mu_);
    uint8_t* p = mem(addr, bytes);
    out.assign(p, p + bytes);
  }
  stream_for(strm)->push(std::move(out));
}

uint32_t Engine::local_copy(uint64_t src, uint64_t dst, uint64_t bytes) {
  MutexLock g(mem_mu_);
  uint8_t* s = mem(src, bytes);
  uint8_t* d = mem(dst, bytes);
  std::memmove(d, s, bytes);
  return sticky_err_;
}

// Domain-aware element copy: routes through the compressor/decompressor
// lane when source and destination representations differ (the role of
// the reference dma_mover's per-operand lane routing).
uint32_t Engine::local_move(const CallDesc& c, uint64_t src, uint64_t dst,
                            uint64_t elems, bool src_c, bool dst_c) {
  Dom d = dom(c);
  src_c = src_c && d.pair;
  dst_c = dst_c && d.pair;
  MutexLock g(mem_mu_);
  uint8_t* s = mem(src, elems * d.eb(src_c));
  uint8_t* t = mem(dst, elems * d.eb(dst_c));
  convert_elems(d, s, src_c, t, dst_c, elems);
  return sticky_err_;
}

uint32_t Engine::local_reduce(uint32_t lane, uint64_t a, uint64_t b,
                              uint64_t dst, uint64_t bytes) {
  MutexLock g(mem_mu_);
  uint8_t* pa = mem(a, bytes);
  uint8_t* pb = mem(b, bytes);
  uint8_t* pd = mem(dst, bytes);
  sticky_err_ |= run_reduce_lane(lane, pa, pb, pd, bytes);
  return sticky_err_;
}

// ---------------------------------------------------------------------------
// eager protocol primitives
// ---------------------------------------------------------------------------
void Engine::send_eager(CallDesc& c, uint32_t dst, uint32_t tag, uint64_t addr,
                        uint64_t elems, bool from_stream, uint32_t to_strm,
                        uint32_t comp, bool reduce_stream) {
  // loop() already finalized calls on unknown/placeholder comms, so the
  // fetch cannot miss here (same contract the old direct index relied on)
  CommTable& t = *comm_ptr(c.comm());
  Dom d = dom(c);
  bool src_c = d.pair && (comp & OP0_COMPRESSED) && !from_stream;
  bool wire_c = d.pair && (comp & ETH_COMPRESSED);
  uint64_t seg_wire = t.rows[dst].max_seg ? t.rows[dst].max_seg
                                          : (rx_.buf_size() ? rx_.buf_size()
                                                            : 1024);
  // segmentation is against the rx buffer in WIRE representation: a
  // compressed wire carries ratio-more elements per segment (fw :621-623
  // computes max_seg_count from the element size the same way); the
  // block-scaled lane additionally rounds to whole blocks so every
  // segment is a self-contained (scales, data) unit
  uint64_t seg_elems = d.seg_elems(seg_wire, wire_c);

  uint64_t off = 0;
  bool first = true;
  while (off < elems || (first && elems == 0)) {
    first = false;
    uint64_t chunk = std::min(seg_elems, elems - off);
    Message msg;
    if (from_stream) {
      // operand streamed from the local compute kernel (OP0_STREAM;
      // reference vadd_put path accl_hls.h / fw :575) — streams carry
      // the uncompressed representation
      auto v = krnl_in_.pop_wait(timeout_budget());
      if (!v || v->size() != chunk * d.ub) {
        sticky_err_ |= SEGMENTER_EXPECTED_BTT_ERROR;
        return;
      }
      msg.payload = std::move(*v);
      if (wire_c) {
        std::vector<uint8_t> packed(d.wbytes(chunk, true));
        if (convert_elems(d, msg.payload.data(), false, packed.data(), true,
                          chunk))
          return;
        msg.payload = std::move(packed);
      }
    } else {
      MutexLock g(mem_mu_);
      uint8_t* p = mem(addr + off * d.eb(src_c), chunk * d.eb(src_c));
      msg.payload.resize(d.wbytes(chunk, wire_c));
      if (wire_c && d.blk && !src_c) {
        // block-scaled egress: quantize (with the per-site EQuARX
        // residual when the arithcfg arms error feedback AND this is
        // a reduction-stream hop)
        if (sticky_err_) return;
        quantize_egress(d, d.ef && reduce_stream, c.comm(), dst,
                        addr + off * d.eb(src_c),
                        reinterpret_cast<const float*>(p),
                        msg.payload.data(), chunk);
      } else if (convert_elems(d, p, src_c, msg.payload.data(), wire_c,
                               chunk)) {
        return;
      }
    }
    if (wire_c) {
      compressed_tx_bytes_.fetch_add(msg.payload.size());
      compressed_tx_logical_bytes_.fetch_add(chunk * d.ub);
      link_count(c.comm(), dst, &LinkCounters::comp_tx_bytes,
                 msg.payload.size());
    }
    msg.hdr.compressed = wire_c ? (d.blk ? 2 : 1) : 0;
    msg.hdr.count = uint32_t(msg.payload.size());
    msg.hdr.tag = tag;
    msg.hdr.src = t.local;
    // stream-destined messages bypass the rx pool on the receiver, so
    // they must not consume the eager sequence space (seqn discipline is
    // per rx-pool route); they carry their own per-(comm,dst,strm)
    // sequence so ingress can resequence on non-FIFO transports
    // outbound counter keyed per destination (the receiver resequences
    // per source, so each src->dst stream route has its own space)
    msg.hdr.seqn =
        to_strm >= FIRST_KRNL_STREAM
            ? strm_out_seq_[StrmKey{c.comm(), dst, to_strm}]++
            : t.outbound_seq[dst]++;
    msg.hdr.strm = to_strm;
    msg.hdr.dst_session = uint16_t(t.rows[dst].session);
    msg.hdr.msg_type = uint8_t(MsgType::EgrMsg);
    msg.hdr.comm_id = c.comm();
    msg.hdr.epoch = epoch_of(c.comm());
    // retransmission lane: capture the clean copy BEFORE the chaos
    // funnel (the wire may drop/corrupt it; the source data survives).
    // Stream-destined messages bypass the rx pool and its NACK
    // machinery, so only pool-routed segments are stored.
    if (to_strm < FIRST_KRNL_STREAM && retrans_enabled())
      store_retrans(c.comm(), dst, msg);
    link_tx(c.comm(), dst, msg.payload.size());
    send_out(t.rows[dst].session, std::move(msg));
    off += chunk;
  }
}

// Seek with recovery: the receive budget is sliced so (a) an abort
// wakes a blocked receiver within one slice instead of after the whole
// budget, and (b) with retransmission enabled a miss NACKs the sender
// and backs off exponentially (base ACCL_RETRY_BASE_US, deterministic
// jitter from (rank, seqn, attempt)) up to ACCL_RETRY_MAX rounds.  The
// TOTAL budget is unchanged: a peer that never sent anything still
// classifies exactly like today, on the same clock.
std::optional<RxNotification> Engine::seek_recover(CallDesc& c, uint32_t src,
                                                   uint32_t tag,
                                                   int* evicted_out,
                                                   Message* staged_out) {
  CommTable& t = *comm_ptr(c.comm());
  seeks_.fetch_add(1);
  link_count(c.comm(), src, &LinkCounters::seeks);
  // per-link seek latency: how long THIS peer's missing data kept the
  // receiver blocked — the slow-link observable the link matrix ranks
  // (a chaos-slowed peer's links dominate seek_wait_ns).  RAII so
  // every return path (success, miss, abort, shutdown) stamps it.
  struct SeekWaitStamp {
    Engine* e;
    uint32_t comm, src;
    steady_clock::time_point t0 = steady_clock::now();
    ~SeekWaitStamp() {
      e->link_count(comm, src, &LinkCounters::seek_wait_ns,
                    uint64_t(std::chrono::duration_cast<nanoseconds>(
                                 steady_clock::now() - t0)
                                 .count()));
    }
  } seek_stamp{this, c.comm(), src};
  // budget measured on the det-aware clock: virtual time under the
  // model checker (so explored schedules can actually reach expiry —
  // the wall-clock ingredient the virtual clock used to hide), the
  // real steady clock in production builds
  auto budget = timeout_budget();
  auto deadline = det_clock_now() + budget;
  uint32_t retry_max = retrans_enabled() ? retry_max_.load() : 0;
  uint32_t attempts = 0;  // fast-phase NACK rounds consumed
  uint32_t chunks = 0;    // steady-state 50 ms slices elapsed
  for (;;) {
    // engine shutdown mid-seek: give the call back to the loop (which
    // is exiting) so shutdown's finalize sweep retires it — a blocked
    // receive must never hold the loop-thread join hostage for the
    // rest of its receive budget
    if (!running_.load()) {
      sticky_err_ |= COMM_ABORTED | RANK_FAILED;
      return std::nullopt;
    }
    uint32_t ab = abort_err(c.comm());
    if (ab) {
      sticky_err_ |= ab;
      return std::nullopt;
    }
    uint32_t expect = t.inbound_seq[src];
    auto now = det_clock_now();
    if (now >= deadline) {
#if !defined(ACCL_FAULT_SUBCOMM_WEDGE)
      // last-gasp rescue: the segment may have been staged during the
      // FINAL slice (after this iteration's seek already missed), so a
      // timeout must re-probe staging before it classifies — otherwise
      // a message that did arrive is reported as a slow peer.  Taken
      // regardless of pool idleness: the budget is gone, in-order
      // delivery via the normal drain is no longer an option.
      if (staged_out) {
        auto sm = rx_.take_staged(c.comm(), src, tag, expect);
        if (sm) {
          staged_takes_.fetch_add(1);
          *staged_out = std::move(*sm);
          RxNotification n;
          n.index = UINT32_MAX;  // sentinel: payload rides *staged_out
          n.bytes = uint32_t(staged_out->payload.size());
          n.tag = staged_out->hdr.tag;
          n.src = staged_out->hdr.src;
          n.seqn = staged_out->hdr.seqn;
          n.comm = staged_out->hdr.comm_id;
          n.compressed = staged_out->hdr.compressed;
          return n;
        }
      }
#endif
      // classifying a timeout while the expected segment sits in the
      // staging queue is NOT a slow peer — the data arrived and the
      // pool never surfaced it (cross-comm pinning).  Counted in every
      // build: the detsched drill invariant reads this to tell a
      // genuine wedge from a legitimately-injected slow-peer timeout.
      if (rx_.has_staged_match(c.comm(), src, tag, expect))
        wedged_timeouts_.fetch_add(1);
      // a genuine matching failure (timeout after the recovery budget),
      // not an abort/shutdown wake — the seek-miss telemetry observable
      seek_misses_.fetch_add(1);
      return std::nullopt;
    }
    nanoseconds slice;
    bool fast_phase = attempts < retry_max;
    if (fast_phase) {
      // exponential backoff with deterministic jitter: reproducible
      // under a seeded chaos plan, decorrelated across ranks/seqns
      uint64_t base = retry_base_us_.load();
      uint64_t us = base << attempts;
      uint64_t j = (uint64_t(global_rank_ + 1) * 2654435761u) ^
                   (uint64_t(expect + 1) * 40503u) ^ attempts;
      us += j % (base / 2 + 1);
      slice = std::min<nanoseconds>(microseconds(us), deadline - now);
    } else {
      // fast phase exhausted (or lane disabled): 50 ms slices keep the
      // abort-wake latency bounded for the rest of the budget
      slice = std::min<nanoseconds>(milliseconds(50), deadline - now);
    }
    auto note = rx_.seek(c.comm(), src, tag, expect, slice);
    if (note) return note;
#if !defined(ACCL_FAULT_SUBCOMM_WEDGE)
    // Staged-segment rescue (the 8-rank sub-comm allgather wedge fix):
    // when every buffer is RESERVED, the expected segment may be parked
    // in the staging queue with nothing left to drain it — the comm
    // pinning the pool will not release() until ITS peer progresses,
    // which can transitively wait on this very receiver (a cross-comm
    // dependency cycle through the shared pool).  Instead of burning
    // the rest of the budget into a RECEIVE_TIMEOUT, consume the
    // payload straight from staging.  Only under pressure: with an idle
    // buffer present the normal deposit->notify path is at most one
    // release() away and must keep its in-order semantics.
    if (staged_out && !rx_.has_idle()) {
      auto sm = rx_.take_staged(c.comm(), src, tag, expect);
      if (sm) {
        staged_takes_.fetch_add(1);
        *staged_out = std::move(*sm);
        RxNotification n;
        n.index = UINT32_MAX;  // sentinel: payload rides *staged_out
        n.bytes = uint32_t(staged_out->payload.size());
        n.tag = staged_out->hdr.tag;
        n.src = staged_out->hdr.src;
        n.seqn = staged_out->hdr.seqn;
        n.comm = staged_out->hdr.comm_id;
        n.compressed = staged_out->hdr.compressed;
        return n;
      }
    }
#endif
    // Solicit a retransmission: the fast phase NACKs after every miss
    // (µs-scale recovery for a drop that already happened); afterwards
    // a steady-state NACK every ~200 ms covers a segment dropped LATER
    // than the fast phase — e.g. a slow sender whose first message hit
    // the chaos funnel after our backoff rounds were spent.  Without
    // the steady phase, recovery would race sender start time.
    bool steady_nack = retry_max > 0 && !fast_phase && (++chunks % 4 == 0);
    if ((fast_phase && retry_max) || steady_nack) {
      // a same-route entry sitting in the pool while the expected seqn
      // is missing is untrustworthy once a wire fault is in play (a
      // corrupt-seqn copy must never be consumable as future data):
      // evict the route — anything legitimate comes back with the
      // retransmission the NACK is about to trigger
      if (rx_.has_route_entry(c.comm(), src, tag)) {
        int n = rx_.evict_route(c.comm(), src, tag);
        if (evicted_out) *evicted_out += n;
      }
      send_nack(c.comm(), src, tag, expect);
      if (fast_phase) ++attempts;
    }
  }
}

void Engine::recv_eager(CallDesc& c, uint32_t src, uint32_t tag, uint64_t addr,
                        uint64_t elems, RecvMode mode, uint32_t strm,
                        uint32_t comp) {
  CommTable& t = *comm_ptr(c.comm());
  Dom d = dom(c);
  bool dst_c = d.pair && (comp & RES_COMPRESSED) && mode != RecvMode::STREAM;
  bool wire_c = d.pair && (comp & ETH_COMPRESSED);
  uint64_t seg_wire = t.rows[t.local].max_seg
                          ? t.rows[t.local].max_seg
                          : (rx_.buf_size() ? rx_.buf_size() : 1024);
  // must mirror the sender's wire-domain segmentation exactly
  uint64_t seg_elems = d.seg_elems(seg_wire, wire_c);

  uint64_t off = 0;
  uint64_t consumed_chunks = 0;
  bool first = true;
  while (off < elems || (first && elems == 0)) {
    first = false;
    uint64_t chunk = std::min(seg_elems, elems - off);
    int evicted_in_recovery = 0;
    Message staged_msg;  // payload home for a staging-queue rescue
    auto note = seek_recover(c, src, tag, &evicted_in_recovery, &staged_msg);
    if (!note) {
      // abort-wake: seek_recover already stamped the abort bits; this
      // call is fenced, not timed out — no fault classification
      if (sticky_err_ & COMM_ABORTED) return;
      // distinguish "nothing arrived" from "a segment with the wrong
      // sequence number is sitting in the pool" (out-of-order /
      // corrupted wire traffic — the reference's PACK_SEQ error class).
      // Stale duplicates (seqn behind expected) can never match and are
      // evicted so the pool doesn't leak; ahead-of-sequence entries
      // stay queued — they may legally match a recv posted later in a
      // different tag order — but their presence on this route still
      // classifies the failure as a sequence error, not a bare timeout.
      // Entries the NACK recovery evicted count the same way: they WERE
      // on the route when the expected seqn went missing.
      int stale = rx_.drop_stale(c.comm(), src, tag, t.inbound_seq[src] - 1);
      bool mismatched = stale > 0 || evicted_in_recovery > 0 ||
                        rx_.has_route_entry(c.comm(), src, tag);
      // reclamation bound: if the pool is exhausted, the broken route's
      // pinned segments would starve every other route (deposit() parks
      // everything in staging with no release to drain it) — force-evict
      // the route under pressure; otherwise leave ahead entries queued
      // for a possibly differently-ordered future recv
      if (mismatched && !rx_.has_idle())
        rx_.evict_route(c.comm(), src, tag);
      // lossy-rung self-heal: the expected seqn never arrived within the
      // timeout (fragment loss on the datagram rung) and will never
      // arrive.  Advance the route cursor to the oldest queued survivor
      // so FUTURE receives on the route proceed — but THIS call always
      // fails: a queued same-tag successor is indistinguishable from
      // this recv's own message, and silently splicing it in would
      // substitute wrong data with no error (at-most-once delivery with
      // an explicit error, never silent substitution).
      // Guards: only lossy rungs resync (on reliable transports an
      // absent expected seqn is corruption, kept as a hard error for the
      // fault-injection contract); and a PRESENT expected seqn under a
      // different tag is the documented misordered-recv case (PACK_SEQ
      // error, entry kept for the correctly-ordered recv).
      if (lossy_transport_ &&
          !rx_.has_seqn(c.comm(), src, t.inbound_seq[src])) {
        // The hole sits inside THIS message, whose remaining segments
        // occupy exactly the seqn window [expected, expected+remaining).
        // Evict any survivors in that window (a stranded tail segment
        // carries this recv's tag and a future same-tag seek would
        // silently consume it as shifted data) and advance the cursor
        // past the whole message — a queued FUTURE same-tag message
        // starts after the window, survives untouched, and matches the
        // next recv, which is exactly the in-order matching contract.
        // Tradeoff: a recv that merely timed out waiting for a slow (not
        // lost) sender also skips; its late segments arrive behind the
        // cursor and are dropped as stale — loss semantics, by design,
        // on the lossy rung only.
        uint64_t total_chunks =
            elems ? (elems + seg_elems - 1) / seg_elems : 1;
        uint32_t remaining = uint32_t(total_chunks - consumed_chunks);
        rx_.evict_window(c.comm(), src, tag, t.inbound_seq[src], remaining);
        t.inbound_seq[src] += remaining;
      }
      sticky_err_ |= mismatched ? PACK_SEQ_NUMBER_ERROR
                                : RECEIVE_TIMEOUT_ERROR;
      return;
    }
    t.inbound_seq[src]++;
    // a staged rescue carries its payload in staged_msg, not the pool
    const uint8_t* data = note->index == UINT32_MAX
                              ? staged_msg.payload.data()
                              : rx_.data(note->index);
    // interpret the arriving bytes via OUR OWN flag algebra — the
    // reference eth header carries no compressed marker; each end derives
    // the wire representation from its arithcfg + ETH flag, which is what
    // makes directional pairs (f16 sender / f32+compress receiver) agree
    bool got_c = wire_c;
    uint64_t got_elems;
    if (got_c && d.blk) {
      // self-describing block-scaled segment: decode + validate the
      // framing against our own arithcfg geometry (a mismatched or
      // truncated segment is a compression error, never an OOB read)
      got_elems = i8_wire_elems(data, note->bytes, d.blk);
      if (got_elems == UINT64_MAX) {
        sticky_err_ |= COMPRESSION_ERROR;
        got_elems = 0;
      }
    } else {
      got_elems = note->bytes / std::max<uint64_t>(1, d.eb(got_c));
    }
    if (got_elems != chunk) sticky_err_ |= SEGMENTER_EXPECTED_BTT_ERROR;
    uint64_t n = std::min(got_elems, chunk);
    switch (mode) {
      case RecvMode::COPY: {
        MutexLock g(mem_mu_);
        uint8_t* dst = mem(addr + off * d.eb(dst_c), n * d.eb(dst_c));
        convert_elems(d, data, got_c, dst, dst_c, n);
        break;
      }
      case RecvMode::REDUCE: {
        // fused recv-reduce: the wire payload is OP1, the accumulator at
        // addr is OP0 and RES (mixed-precision accumulate per arithcfg;
        // ETH>>2 -> OP1_COMPRESSED shifting, fw :1953-1955)
        MutexLock g(mem_mu_);
        uint8_t* acc = mem(addr + off * d.eb(dst_c), n * d.eb(dst_c));
        reduce_mixed(c, acc, dst_c, data, got_c, acc, dst_c, n);
        break;
      }
      case RecvMode::STREAM: {
        // compute streams carry the uncompressed representation
        std::vector<uint8_t> out(n * d.ub);
        if (convert_elems(d, data, got_c, out.data(), false, n) == OK)
          stream_for(strm)->push(std::move(out));
        break;
      }
    }
    if (note->index != UINT32_MAX) rx_.release(note->index);
    // a duplicated segment's stale copy (seqn <= the one just consumed)
    // can never match a future seek; drop it now instead of letting it
    // pin a pool buffer until some later timeout runs eviction
    rx_.drop_stale(c.comm(), src, tag, note->seqn);
    off += chunk;
    ++consumed_chunks;
  }
}

// ---------------------------------------------------------------------------
// rendezvous protocol primitives (fw :142-350; SURVEY §3.5)
// ---------------------------------------------------------------------------
void Engine::rndzv_post_addr(CallDesc& c, Progress& p, uint32_t src,
                             uint32_t tag, uint64_t addr, uint64_t elems,
                             bool dst_c) {
  CommTable& t = *comm_ptr(c.comm());
  Dom d = dom(c);
  if (p.pending()) {
    // record the wire->landing conversion the depacketizer must apply
    // when the peer's one-sided write arrives; both peers derive the
    // wire representation from their own arithcfg + ETH flag
    {
      MutexLock g(posted_mu_);
      posted_[PostedKey{c.comm(), src, tag, addr}] =
          PostedRndzv{elems, d.eth, dst_c && d.pair, d.comp_kind,
                      uint32_t(d.ub), uint32_t(d.cb), d.blk};
    }
    c.rndzv_posts.push_back({c.comm(), src, tag, addr});
    // advertise our landing address to the sender (RNDZVS_INIT)
    Message msg;
    msg.hdr.count = uint32_t(elems);
    msg.hdr.tag = tag;
    msg.hdr.src = t.local;
    msg.hdr.vaddr = addr;
    msg.hdr.msg_type = uint8_t(MsgType::RndzvsInit);
    msg.hdr.comm_id = c.comm();
    msg.hdr.epoch = epoch_of(c.comm());
    send_out(t.rows[src].session, std::move(msg));
  }
  p.done();
}

void Engine::rndzv_wait_done(CallDesc& c, Progress& p, uint32_t src,
                             uint32_t tag) {
  if (p.pending()) {
    // wait for the write-done completion — matched against the address
    // THIS call advertised for (src, tag), so concurrent calls sharing
    // (comm, src, tag) can only consume their own completions
    auto done = completions_.pop_match(
        [&](const RndzvDone& d) {
          if (d.comm != c.comm() || d.src != src || d.tag != tag)
            return false;
          for (const auto& k : c.rndzv_posts)
            if (uint32_t(k[0]) == c.comm() && uint32_t(k[1]) == src &&
                uint32_t(k[2]) == tag && k[3] == d.vaddr)
              return true;
          return c.rndzv_posts.empty();  // no record: legacy tag match
        },
        milliseconds(2));
    if (!done) throw NotReadyEx{c.current_step};
  }
  p.done();
}

void Engine::rndzv_recv(CallDesc& c, Progress& p, uint32_t src, uint32_t tag,
                        uint64_t addr, uint64_t elems, bool dst_c) {
  rndzv_post_addr(c, p, src, tag, addr, elems, dst_c);
  rndzv_wait_done(c, p, src, tag);
}

void Engine::rndzv_send(CallDesc& c, Progress& p, uint32_t dst, uint32_t tag,
                        uint64_t addr, uint64_t elems, bool src_c) {
  CommTable& t = *comm_ptr(c.comm());
  Dom d = dom(c);
  src_c = src_c && d.pair;
  if (p.pending()) {
    // step: match the receiver's advertised address, then issue the
    // one-sided write (single step so the INIT can't be consumed twice)
    auto a = pending_addrs_.pop_match(
        [&](const RndzvAddr& r) {
          return r.comm == c.comm() && r.src == dst && r.tag == tag;
        },
        milliseconds(2));
    if (!a) throw NotReadyEx{c.current_step};
    // Direct p2p fast path (FPGABufferP2P role): when the receiver's
    // advertised landing address lies inside a peer-registered p2p
    // window of an engine we can reach in-process, write the payload
    // straight into the peer's devicemem — no wire message, no framing
    // copy.  Restricted to the plain domain on the SENDER side (no ETH
    // compression, uncompressed source, devicemem operand) so the
    // single copy below is the whole data movement; the receiver's own
    // posted-record conversion still runs inside land_p2p, identical
    // to the wire path.  Own mem_mu_ is NOT held across the peer call
    // (two engines direct-writing at each other would deadlock on
    // crossed mem locks); devicemem_ never reallocates, so the raw
    // pointer stays valid.
    // an armed one-shot egress fault must not be skipped (or left armed
    // for an unrelated later message) by the wire bypass — faulted sends
    // take the wire path where send_out applies the injection
    if (peer_hook_ && !d.eth && !src_c && !(addr & HOST_ADDR_BIT) &&
        fault_.load() == 0 && !killed_.load()) {
      Engine* peer = peer_hook_(t.rows[dst].session);
      uint64_t nbytes = elems * d.ub;
      if (peer && peer != this && peer->p2p_covers(a->vaddr, nbytes)) {
        uint8_t* pdata;
        {
          MutexLock g(mem_mu_);
          pdata = mem(addr, nbytes);
        }
        if (sticky_err_ == 0) {
          WireHeader hdr;
          hdr.count = uint32_t(nbytes);
          hdr.tag = tag;
          hdr.src = t.local;
          hdr.vaddr = a->vaddr;
          hdr.msg_type = uint8_t(MsgType::RndzvsMsg);
          hdr.comm_id = c.comm();
          hdr.epoch = epoch_of(c.comm());
          hdr.compressed = 0;
          // per-link: the p2p write moved `nbytes` across this rank
          // pair even though the wire (and tx_stats) never saw it
          link_tx(c.comm(), dst, nbytes);
          peer->land_p2p(hdr, pdata, nbytes);
          p.done();
          return;
        }
      }
    }
    Message msg;
    msg.hdr.tag = tag;
    msg.hdr.src = t.local;
    msg.hdr.vaddr = a->vaddr;
    msg.hdr.msg_type = uint8_t(MsgType::RndzvsMsg);
    msg.hdr.comm_id = c.comm();
    msg.hdr.epoch = epoch_of(c.comm());
    {
      // convert the operand into OUR wire representation (own arithcfg +
      // ETH flag, same rule as eager); the receiver's depacketizer
      // applies its own wire->landing conversion on arrival — this is
      // the ETH-compressed rendezvous the reference leaves as a TODO
      MutexLock g(mem_mu_);
      uint8_t* pdata = mem(addr, elems * d.eb(src_c));
      msg.payload.resize(d.wbytes(elems, d.eth));
      // on conversion failure (unknown compressor lane) fall through to
      // p.done() with the sticky error set and no wire message — an
      // early return here would desynchronize the schedule's resume
      // cursor after the RNDZVS_INIT was already consumed
      if (d.eth && d.blk && !src_c && sticky_err_ == 0) {
        // rendezvous sends: EF only for reduction scenarios (tree
        // reduce contributions) — bcast/gather/scatter one-sided
        // writes must quantize cleanly
        Op sc = c.scenario();
        bool use_ef = d.ef && (sc == Op::Reduce || sc == Op::Allreduce ||
                               sc == Op::ReduceScatter);
        quantize_egress(d, use_ef, c.comm(), dst, addr,
                        reinterpret_cast<const float*>(pdata),
                        msg.payload.data(), elems);
      } else {
        convert_elems(d, pdata, src_c, msg.payload.data(), d.eth, elems);
      }
      msg.hdr.compressed = d.eth ? (d.blk ? 2 : 1) : 0;
    }
    if (sticky_err_ == 0) {
      msg.hdr.count = uint32_t(msg.payload.size());
      if (d.eth) {
        compressed_tx_bytes_.fetch_add(msg.payload.size());
        compressed_tx_logical_bytes_.fetch_add(elems * d.ub);
        link_count(c.comm(), dst, &LinkCounters::comp_tx_bytes,
                   msg.payload.size());
      }
      link_tx(c.comm(), dst, msg.payload.size());
      send_out(t.rows[dst].session, std::move(msg));
    }
  }
  p.done();
}

// ---------------------------------------------------------------------------
// collective schedules
// ---------------------------------------------------------------------------
void Engine::coll_send(CallDesc& c, Progress& p) {
  uint64_t elems = c.count();
  uint32_t dst = c.root_src_dst();
  uint32_t comp = c.compression();
  bool from_stream = c.stream_flags() & 0x1;  // OP0_STREAM
  uint32_t to_strm =
      (c.stream_flags() & 0x2) ? c.tag() : 0;  // RES_STREAM: remote stream
  if (use_rendezvous(c, elems)) {
    rndzv_send(c, p, dst, c.tag(), c.addr0(), elems, comp & OP0_COMPRESSED);
  } else {
    send_eager(c, dst, c.tag(), c.addr0(), elems, from_stream, to_strm, comp);
  }
}

void Engine::coll_recv(CallDesc& c, Progress& p) {
  uint64_t elems = c.count();
  uint32_t src = c.root_src_dst();
  uint32_t comp = c.compression();
  if (use_rendezvous(c, elems)) {
    rndzv_recv(c, p, src, c.tag(), c.addr2(), elems, comp & RES_COMPRESSED);
  } else {
    RecvMode mode =
        (c.stream_flags() & 0x2) ? RecvMode::STREAM : RecvMode::COPY;
    recv_eager(c, src, c.tag(), c.addr2(), elems, mode, c.tag(), comp);
  }
}

// Broadcast (fw :798-990): eager = root loops over ranks; rendezvous =
// out-of-order flat tree for small worlds, binomial tree otherwise
// (threshold = BCAST_FLAT_TREE_MAX_RANKS tuning register).
void Engine::coll_bcast(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  uint64_t elems = c.count();
  uint32_t root = c.root_src_dst();
  uint32_t comp = c.compression();
  if (t.size <= 1) return;
  if (use_rendezvous(c, elems)) {
    if (t.size > bcast_flat_max_ranks_) {
      tree_bcast(c, p, root, t.local == root ? c.addr0() : 0, c.addr2(),
                 elems, comp & OP0_COMPRESSED, comp & RES_COMPRESSED);
    } else if (t.local == root) {
      for (uint32_t r = 0; r < t.size; ++r)
        if (r != root)
          rndzv_send(c, p, r, c.tag(), c.addr0(), elems,
                     comp & OP0_COMPRESSED);
    } else {
      rndzv_recv(c, p, root, c.tag(), c.addr2(), elems,
                 comp & RES_COMPRESSED);
    }
    return;
  }
  if (t.local == root) {
    for (uint32_t r = 0; r < t.size; ++r)
      if (r != root)
        send_eager(c, r, c.tag(), c.addr0(), elems, false, 0, comp);
  } else {
    recv_eager(c, root, c.tag(), c.addr2(), elems, RecvMode::COPY, 0, comp);
  }
}

// Scatter: root walks the rank-strided source (the reference's
// MOVE_INCREMENT addressing, fw :1082-1124), local chunk copied in place.
void Engine::coll_scatter(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();
  uint32_t root = c.root_src_dst();
  uint32_t comp = c.compression();
  if (t.local == root) {
    // source slices stride in the OP0 representation (MOVE_INCREMENT
    // addressing over the operand's own element width, fw :1082-1124)
    uint64_t src_stride = elems * d.eb(d.op0);
    for (uint32_t r = 0; r < t.size; ++r) {
      uint64_t src = c.addr0() + uint64_t(r) * src_stride;
      if (r == root) {
        local_move(c, src, c.addr2(), elems, d.op0, d.res);
      } else if (use_rendezvous(c, elems)) {
        rndzv_send(c, p, r, c.tag(), src, elems, d.op0);
      } else {
        send_eager(c, r, c.tag(), src, elems, false, 0, comp);
      }
    }
  } else {
    if (use_rendezvous(c, elems))
      rndzv_recv(c, p, root, c.tag(), c.addr2(), elems, d.res);
    else
      recv_eager(c, root, c.tag(), c.addr2(), elems, RecvMode::COPY, 0, comp);
  }
}

// Gather: eager ring relay — every non-root forwards toward the root,
// which receives blocks in ring order (fw :1207-1295).  Large payloads
// use direct rendezvous writes to the root (flat; fan-in control comes
// with the tuning milestone, fw :1163).
void Engine::coll_gather(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();
  uint32_t root = c.root_src_dst();
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  uint64_t res_stride = elems * d.eb(d.res);
  if (P == 1) {
    local_move(c, c.addr0(), c.addr2(), elems, d.op0, d.res);
    return;
  }
  bool rndzv = use_rendezvous(c, elems);
  uint32_t dist = (t.local + P - root) % P;  // distance to root along ring
  if (rndzv) {
    // flat tree with out-of-order address arrival (fw :1011-1081 shape):
    // the root publishes landing addresses in windows of at most
    // GATHER_FLAT_TREE_MAX_FANIN (fw :1163) and collects completions in
    // whatever order the writes land
    if (t.local == root) {
      local_move(c, c.addr0(), c.addr2() + uint64_t(root) * res_stride,
                 elems, d.op0, d.res);
      // count-based fan-in (fw :1163): small gathers publish every
      // landing address at once; above GATHER_FLAT_TREE_MAX_COUNT bytes
      // the fan-in window caps concurrent inbound writes
      // root-only decision, so cross-rank divergence is impossible, but
      // wire width keeps the threshold meaning consistent with reduce
      uint32_t fanin = (d.wbytes(elems, d.eth) > gather_flat_max_count_.load())
                           ? gather_flat_max_fanin_.load()
                           : P - 1;
      fanin = std::max(1u, fanin);
      uint32_t i = 1;
      while (i < P) {
        uint32_t w = std::min(fanin, P - i);
        for (uint32_t j = 0; j < w; ++j) {
          uint32_t r = (root + i + j) % P;
          rndzv_post_addr(c, p, r, c.tag(),
                          c.addr2() + uint64_t(r) * res_stride, elems, d.res);
        }
        for (uint32_t j = 0; j < w; ++j)
          rndzv_wait_done(c, p, (root + i + j) % P, c.tag());
        i += w;
      }
    } else {
      rndzv_send(c, p, root, c.tag(), c.addr0(), elems, d.op0);
    }
    return;
  }
  if (t.local == root) {
    local_move(c, c.addr0(), c.addr2() + uint64_t(root) * res_stride, elems,
               d.op0, d.res);
    uint32_t next = (t.local + 1) % P;
    for (uint32_t i = 0; i < P - 1; ++i) {
      uint32_t origin = (root + 1 + i) % P;
      recv_eager(c, next, c.tag(), c.addr2() + uint64_t(origin) * res_stride,
                 elems, RecvMode::COPY, 0, comp);
    }
  } else {
    uint32_t prev = (t.local + P - 1) % P;
    uint32_t next = (t.local + 1) % P;
    send_eager(c, prev, c.tag(), c.addr0(), elems, false, 0, comp);
    // relay the blocks of everyone farther from the root through an
    // uncompressed scratch staging buffer (wire -> u -> wire)
    uint64_t tmp = alloc(elems * d.ub, 64);
    // the scratch is uncompressed on both sides of the relay, so only
    // the wire bit survives the hop
    uint32_t relay = comp & ETH_COMPRESSED;
    for (uint32_t i = 0; i < P - 1 - dist; ++i) {
      recv_eager(c, next, c.tag(), tmp, elems, RecvMode::COPY, 0,
                 comp & ~uint32_t(RES_COMPRESSED));
      send_eager(c, prev, c.tag(), tmp, elems, false, 0, relay);
    }
    free_addr(tmp);
  }
}

// All-gather: ring relay with a local self-copy first (fw :1404-1502).
// The relay operates on result-buffer slices, so sends read the RES
// representation (RES->OP0 relay algebra, fw :1408-1411).
void Engine::coll_allgather(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  uint64_t res_stride = elems * d.eb(d.res);
  local_move(c, c.addr0(), c.addr2() + uint64_t(t.local) * res_stride, elems,
             d.op0, d.res);
  if (P == 1) return;
  uint32_t next = (t.local + 1) % P;
  uint32_t prev = (t.local + P - 1) % P;
  // sends read result-buffer slices, so their OP0 domain is the call's
  // RES bit (fw :1408-1411 relay algebra applied to the slice source)
  uint32_t send_comp = (d.res ? uint32_t(OP0_COMPRESSED) : 0u)
                       | (comp & ETH_COMPRESSED);
  for (uint32_t s = 0; s < P - 1; ++s) {
    uint32_t send_origin = (t.local + P - s) % P;
    uint32_t recv_origin = (t.local + P - 1 - s) % P;
    send_eager(c, next, c.tag(),
               c.addr2() + uint64_t(send_origin) * res_stride, elems, false,
               0, send_comp);
    recv_eager(c, prev, c.tag(),
               c.addr2() + uint64_t(recv_origin) * res_stride, elems,
               RecvMode::COPY, 0, comp);
  }
}

// Reduce: eager ring/daisy-chain with fused recv-reduce(-send) at the
// interior ranks (fw :1730-1743); rendezvous = flat gather-and-accumulate
// for small worlds (fw :1533-1602) or binomial tree with scratchpads
// (fw :1603-1728).
void Engine::coll_reduce(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();
  uint64_t bytes = elems * d.ub;  // scratch/stream staging is uncompressed
  uint32_t root = c.root_src_dst();
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  // mem<->stream reduce variants (reference: test.cpp:813-910): a
  // streamed operand is materialized from the kernel stream into a
  // scratch lease, and a streamed result is pushed to the local compute
  // stream addressed by the tag, after the schedule runs over buffers.
  bool op_stream = c.stream_flags() & 0x1;   // OP0_STREAM
  bool res_stream = c.stream_flags() & 0x2;  // RES_STREAM
  uint64_t op_addr = c.addr0();
  uint64_t res_addr = c.addr2();
  bool is_root = t.local == root;
  // scratch leases live in the descriptor so execute() frees them on
  // every exit path (stream-flagged calls never reach the rendezvous
  // schedules, which use the same lease slots)
  // operand/result domains: scratch staging (streams) is uncompressed
  bool op_c = d.op0;
  bool res_c = d.res;
  if (op_stream) {
    if (!c.scratch0) c.scratch0 = alloc(bytes, 64);
    if (!drain_krnl_to(c.scratch0, bytes)) return;
    op_addr = c.scratch0;
    op_c = false;
  }
  if (res_stream && is_root) {
    if (!c.scratch1) c.scratch1 = alloc(bytes, 64);
    res_addr = c.scratch1;
    res_c = false;
  }
  if (P == 1) {
    local_move(c, op_addr, res_addr, elems, op_c, res_c);
    if (res_stream && is_root && sticky_err_ == 0)
      push_local_stream(c.tag(), res_addr, bytes);
    return;
  }
  if (use_rendezvous(c, elems)) {
    // stream-flagged calls never reach rendezvous (use_rendezvous forces
    // eager for them), so the scratch slots are free for the schedules
    // count threshold measured on WIRE bytes, like use_rendezvous: the
    // rank-local uncompressed width diverges across directional arithcfg
    // pairs and a schedule-selection split would wedge the rendezvous
    // handshake (fw :1533 consults its own width, but its compression is
    // symmetric by construction — ours is not)
    uint64_t wire_bytes = d.wbytes(elems, d.eth);
    if (P <= reduce_flat_max_ranks_ || wire_bytes <= reduce_flat_max_count_) {
      // flat when the world is small OR the payload is small: tree setup
      // overhead beats the flat fan-in only for large payloads on large
      // worlds
      if (t.local == root) {
        if (!c.scratch0) c.scratch0 = alloc(bytes, 64);
        step_local(p, [&] {
          local_move(c, c.addr0(), c.addr2(), elems, d.op0, d.res);
        });
        for (uint32_t i = 1; i < P; ++i) {
          rndzv_recv(c, p, (root + i) % P, c.tag(), c.scratch0, elems, false);
          step_local(p, [&] {
            MutexLock g(mem_mu_);
            uint8_t* acc = mem(c.addr2(), elems * d.eb(d.res));
            uint8_t* tmp = mem(c.scratch0, bytes);
            reduce_mixed(c, acc, d.res, tmp, false, acc, d.res, elems);
          });
        }
      } else {
        rndzv_send(c, p, root, c.tag(), c.addr0(), elems, d.op0);
      }
    } else {
      // binomial tree: root accumulates in the result buffer, interior
      // nodes in an uncompressed scratch lease; every receiver needs a
      // landing pad
      uint64_t acc = t.local == root ? c.addr2() : 0;
      bool acc_c = t.local == root ? d.res : false;
      if (t.local != root) {
        if (!c.scratch0) c.scratch0 = alloc(bytes, 64);
        acc = c.scratch0;
      }
      if (!c.scratch1) c.scratch1 = alloc(bytes, 64);
      tree_reduce(c, p, root, c.addr0(), acc, c.scratch1, elems, d.op0,
                  acc_c);
    }
    return;
  }
  uint32_t pos = (t.local + P - root) % P;  // chain position; root = 0
  uint32_t next = (t.local + 1) % P;
  uint32_t prev = (t.local + P - 1) % P;
  if (pos == 1) {
    // head of the chain: just forward our contribution (a reduction
    // operand — the EF residual's legal habitat)
    send_eager(c, next, c.tag(), op_addr, elems, false, 0,
               (op_c ? uint32_t(OP0_COMPRESSED) : 0u) | (comp & ETH_COMPRESSED),
               /*reduce_stream=*/true);
  } else if (pos != 0) {
    // interior: receive partial, fold our contribution, forward through
    // an uncompressed scratch accumulator
    uint64_t tmp = alloc(bytes, 64);
    local_move(c, op_addr, tmp, elems, op_c, false);
    recv_eager(c, prev, c.tag(), tmp, elems, RecvMode::REDUCE, 0,
               comp & ETH_COMPRESSED);
    send_eager(c, next, c.tag(), tmp, elems, false, 0,
               comp & ETH_COMPRESSED, /*reduce_stream=*/true);
    free_addr(tmp);
  } else {
    // root: receive the chain's partial, fold our contribution into res
    local_move(c, op_addr, res_addr, elems, op_c, res_c);
    recv_eager(c, prev, c.tag(), res_addr, elems, RecvMode::REDUCE, 0,
               (res_c ? uint32_t(RES_COMPRESSED) : 0u) | (comp & ETH_COMPRESSED));
    // deliver to the compute stream only on success — a consumer must
    // not be handed a correctly-sized but partially-reduced payload
    if (res_stream && sticky_err_ == 0)
      push_local_stream(c.tag(), res_addr, bytes);
  }
}

// Ring reduce-scatter core shared by reduce_scatter and allreduce
// (fw :1782-1850, :1888-2071): step 0 sends chunk (rank-1); interior
// steps fuse recv+reduce+forward; the final step folds chunk `rank`.
void Engine::ring_reduce_scatter(CallDesc& c, uint64_t src_base,
                                 const std::vector<uint64_t>& off,
                                 const std::vector<uint64_t>& len,
                                 uint64_t own_dst) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  uint32_t r = t.local;
  uint32_t next = (r + 1) % P;
  uint32_t prev = (r + P - 1) % P;
  if (P == 1) {
    local_move(c, src_base + off[0] * d.eb(d.op0), own_dst, len[0], d.op0,
               d.res);
    return;
  }
  uint32_t first = (r + P - 1) % P;
  // per-step algebra (fw :1929-1955): sends keep OP0, replace RES by the
  // wire bit; the fused recv-reduce takes the wire payload as OP1.
  // Every send here carries a reduction partial — the EF residual's
  // legal habitat (reduce_stream=true).
  send_eager(c, next, c.tag(), src_base + off[first] * d.eb(d.op0),
             len[first], false, 0,
             (d.op0 ? uint32_t(OP0_COMPRESSED) : 0u) | (comp & ETH_COMPRESSED),
             /*reduce_stream=*/true);
  uint64_t maxlen = *std::max_element(len.begin(), len.end());
  uint64_t tmp = alloc(std::max<uint64_t>(maxlen * d.ub, 64), 64);
  for (uint32_t s = 1; s <= P - 1; ++s) {
    // chunk index arriving this step: (r - 1 - s) mod P
    uint32_t chunk =
        uint32_t(((int64_t(r) - 1 - int64_t(s)) % int64_t(P) + P) % P);
    // stage our contribution uncompressed, fold the wire partial in
    local_move(c, src_base + off[chunk] * d.eb(d.op0), tmp, len[chunk],
               d.op0, false);
    recv_eager(c, prev, c.tag(), tmp, len[chunk], RecvMode::REDUCE, 0,
               comp & ETH_COMPRESSED);
    if (chunk == r) {
      // wire-form agreement (EQuARX discipline): under an allreduce's
      // compressed wire the owner's finished chunk will be RELAYED to
      // every peer in the gather phase as quant(chunk) — consume the
      // SAME wire form locally, or ranks would disagree on exactly
      // the chunks they own by a full quantization step.  The
      // roundtrip mirrors the gather phase's SEGMENTATION (block
      // partitions are segment-relative), so owner and peers land
      // within one ulp of scale arithmetic of each other.
      // reduce_scatter keeps the exact accumulate: its chunk is
      // rank-private by contract.
      if (d.eth && d.blk && c.scenario() == Op::Allreduce &&
          sticky_err_ == 0) {
        uint64_t seg_wire = t.rows[next].max_seg
                                ? t.rows[next].max_seg
                                : (rx_.buf_size() ? rx_.buf_size() : 1024);
        uint64_t seg = d.seg_elems(seg_wire, true);
        thread_local std::vector<uint8_t> rt;
        MutexLock g(mem_mu_);
        for (uint64_t o = 0; o < len[chunk]; o += seg) {
          uint64_t n = std::min<uint64_t>(seg, len[chunk] - o);
          rt.resize(d.wbytes(n, true));
          uint8_t* p = mem(tmp + o * d.ub, n * d.ub);
          if (convert_elems(d, p, false, rt.data(), true, n) != OK) break;
          convert_elems(d, rt.data(), true, p, false, n);
        }
      }
      local_move(c, tmp, own_dst, len[chunk], false, d.res);
    } else {
      send_eager(c, next, c.tag(), tmp, len[chunk], false, 0,
                 comp & ETH_COMPRESSED, /*reduce_stream=*/true);
    }
  }
  free_addr(tmp);
}

// Ring all-gather over chunks already resident in dst (fw :1990-2066);
// slices live in the RES representation throughout.
void Engine::ring_allgather(CallDesc& c, uint64_t base,
                            const std::vector<uint64_t>& off,
                            const std::vector<uint64_t>& len) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  uint32_t r = t.local;
  if (P == 1) return;
  uint32_t next = (r + 1) % P;
  uint32_t prev = (r + P - 1) % P;
  // slices live in the RES representation; sends treat that as OP0
  uint32_t send_comp = (d.res ? uint32_t(OP0_COMPRESSED) : 0u)
                       | (comp & ETH_COMPRESSED);
  for (uint32_t s = 0; s < P - 1; ++s) {
    uint32_t send_chunk = uint32_t(((int64_t(r) - int64_t(s)) % int64_t(P) + P) % P);
    uint32_t recv_chunk = uint32_t(((int64_t(r) - 1 - int64_t(s)) % int64_t(P) + P) % P);
    send_eager(c, next, c.tag(), base + off[send_chunk] * d.eb(d.res),
               len[send_chunk], false, 0, send_comp);
    recv_eager(c, prev, c.tag(), base + off[recv_chunk] * d.eb(d.res),
               len[recv_chunk], RecvMode::COPY, 0, comp);
  }
}

void Engine::coll_reduce_scatter(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();  // per-rank result elements
  uint32_t P = t.size;
  if (P > 1 && use_rendezvous(c, elems * P)) {
    // rendezvous: tree-reduce the whole vector to rank 0 through
    // uncompressed scratch, then scatter the slices
    // (fw :1768-1781 reduce-to-0 + scatter)
    uint64_t total_u = elems * P * d.ub;
    if (!c.scratch0) c.scratch0 = alloc(total_u, 64);
    if (!c.scratch1) c.scratch1 = alloc(total_u, 64);
    tree_reduce(c, p, 0, c.addr0(), c.scratch0, c.scratch1, elems * P,
                d.op0, false);
    if (t.local == 0) {
      step_local(p, [&] {
        local_move(c, c.scratch0, c.addr2(), elems, false, d.res);
      });
      for (uint32_t r = 1; r < P; ++r)
        rndzv_send(c, p, r, c.tag(), c.scratch0 + uint64_t(r) * elems * d.ub,
                   elems, false);
    } else {
      rndzv_recv(c, p, 0, c.tag(), c.addr2(), elems, d.res);
    }
    return;
  }
  std::vector<uint64_t> off(P), len(P, elems);
  for (uint32_t i = 0; i < P; ++i) off[i] = uint64_t(i) * elems;
  ring_reduce_scatter(c, c.addr0(), off, len, c.addr2());
}

void Engine::coll_allreduce(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint32_t P = t.size;
  uint64_t total = uint64_t(c.count());
  if (P == 1) {
    local_move(c, c.addr0(), c.addr2(), total, d.op0, d.res);
    return;
  }
  if (use_rendezvous(c, total)) {
    // rendezvous: tree reduce to rank 0 accumulating directly in every
    // rank's result buffer, then tree broadcast the final value
    // (fw :1878-1887 reduce-then-bcast)
    if (!c.scratch0) c.scratch0 = alloc(total * d.ub, 64);
    tree_reduce(c, p, 0, c.addr0(), c.addr2(), c.scratch0, total, d.op0,
                d.res);
    tree_bcast(c, p, 0, c.addr2(), c.addr2(), total, d.res, d.res);
    return;
  }
  // chunk the element range across ranks (bulk/tail split for ragged
  // sizes, fw :1909-1912)
  std::vector<uint64_t> off(P), len(P);
  uint64_t base_elems = total / P, extra = total % P, cursor = 0;
  for (uint32_t i = 0; i < P; ++i) {
    uint64_t e = base_elems + (i < extra ? 1 : 0);
    off[i] = cursor;
    len[i] = e;
    cursor += e;
  }
  ring_reduce_scatter(c, c.addr0(), off, len,
                      c.addr2() + off[t.local] * d.eb(d.res));
  ring_allgather(c, c.addr2(), off, len);
}

// All-to-all: send every peer its slice, then collect ours (the
// reference's eager path is unimplemented — COLLECTIVE_NOT_IMPLEMENTED,
// fw :2213-2215 — we implement it; the rendezvous path mirrors the
// reference's fused simultaneous flat trees :2123-2218).
void Engine::coll_alltoall(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  Dom d = dom(c);
  uint64_t elems = c.count();
  uint32_t comp = c.compression();
  uint32_t P = t.size;
  uint64_t op_stride = elems * d.eb(d.op0);
  uint64_t res_stride = elems * d.eb(d.res);
  local_move(c, c.addr0() + uint64_t(t.local) * op_stride,
             c.addr2() + uint64_t(t.local) * res_stride, elems, d.op0, d.res);
  bool rndzv = use_rendezvous(c, elems);
  if (rndzv) {
    // fused simultaneous flat trees (fw :2123-2218): publish all landing
    // addresses, write as peer addresses arrive (out of order), then
    // drain completions
    for (uint32_t i = 1; i < P; ++i) {
      uint32_t r = (t.local + P - i) % P;
      rndzv_post_addr(c, p, r, c.tag(),
                      c.addr2() + uint64_t(r) * res_stride, elems, d.res);
    }
    for (uint32_t i = 1; i < P; ++i) {
      uint32_t r = (t.local + i) % P;
      rndzv_send(c, p, r, c.tag(), c.addr0() + uint64_t(r) * op_stride,
                 elems, d.op0);
    }
    for (uint32_t i = 1; i < P; ++i)
      rndzv_wait_done(c, p, (t.local + P - i) % P, c.tag());
    return;
  }
  for (uint32_t i = 1; i < P; ++i) {
    uint32_t r = (t.local + i) % P;
    send_eager(c, r, c.tag(), c.addr0() + uint64_t(r) * op_stride, elems,
               false, 0, comp);
  }
  // receive in the same relative order every peer sends (peer local+1
  // sent to us first): consuming earliest arrivals first drains the rx
  // pool instead of pinning it behind a not-yet-arrived route, which
  // matters when (P-1) x segments approaches the pool size
  for (uint32_t i = 1; i < P; ++i) {
    uint32_t r = (t.local + P - i) % P;  // peer for whom we are (their+i)
    recv_eager(c, r, c.tag(), c.addr2() + uint64_t(r) * res_stride, elems,
               RecvMode::COPY, 0, comp);
  }
}

// Barrier: gather-to-0 + scatter-from-0 of empty messages (fw :2077-2120).
void Engine::coll_barrier(CallDesc& c, Progress& p) {
  const CommTable& t = comm_for(c);
  uint32_t P = t.size;
  if (P == 1) return;
  if (t.local == 0) {
    for (uint32_t r = 1; r < P; ++r)
      recv_eager(c, r, BARRIER_TAG, 0, 0, RecvMode::COPY, 0, 0);
    for (uint32_t r = 1; r < P; ++r)
      send_eager(c, r, BARRIER_TAG, 0, 0, false, 0, 0);
  } else {
    send_eager(c, 0, BARRIER_TAG, 0, 0, false, 0, 0);
    recv_eager(c, 0, BARRIER_TAG, 0, 0, RecvMode::COPY, 0, 0);
  }
}

}  // namespace accl
