// C API exposed to the Python driver over ctypes.
//
// Boundary analog of the reference's ZMQ control protocol between the
// host driver (SimDevice) and the emulator process — mmio/mem read/write
// plus "call with 15 args" (test/model/zmq/zmq_server.h:49-156) — but as
// an in-process FFI: the Python EmuDevice backend calls these directly.
#include <chrono>
#include <cstring>
#include <thread>

#include "dgram.hpp"
#include "rdma.hpp"
#include "engine.hpp"

using namespace accl;

namespace {

struct World {
  std::vector<std::unique_ptr<Engine>> engines;
  std::shared_ptr<InprocHub> hub;
  std::shared_ptr<DgramHub> dgram_hub;
  std::shared_ptr<RdmaHub> rdma_hub;
  std::vector<RdmaTransport*> rdma_transports;  // borrowed, engine-owned
  bool tcp = false;
  uint64_t devmem_bytes = 64ull << 20;  // per-engine, for elastic joins

  Engine* get(int rank) {
    if (tcp) return engines.empty() ? nullptr : engines[0].get();
    return rank >= 0 && rank < int(engines.size()) ? engines[rank].get()
                                                   : nullptr;
  }
};

// Every FFI entry resolves its engine through this null-tolerant
// helper: ctypes passes Python None as NULL, and a late waiter thread
// entering the FFI after the driver nulled its world handle must get a
// clean "no engine" error, never a null dereference — the deterministic
// half of the r13 suite-exit segfault (rc=139 after the pytest
// summary: a daemon waiter scheduled after EmuWorld.close()).
Engine* world_get(void* wp, int rank) {
  World* w = static_cast<World*>(wp);
  return w ? w->get(rank) : nullptr;
}

}  // namespace

extern "C" {

// In-process world: N engines wired through a shared hub (the reference's
// single-board axis3x loopback rung of the test ladder).
void* accl_world_create(int nranks, uint64_t devmem_bytes) {
  auto* w = new World();
  w->hub = std::make_shared<InprocHub>(nranks);
  // headroom for elastic joins: accl_world_add_rank push_backs must
  // never reallocate the vector while peer hooks walk it from engine
  // threads (the same live-write discipline as comms_.reserve(64))
  w->engines.reserve(size_t(nranks) + 64);
  w->devmem_bytes = devmem_bytes;
  for (int r = 0; r < nranks; ++r) {
    w->engines.push_back(std::make_unique<Engine>(
        uint32_t(r), devmem_bytes,
        std::make_unique<InprocTransport>(w->hub, r)));
  }
  // shared address space: enable the direct p2p landing path (session
  // ids are rank ids in inproc worlds)
  for (auto& e : w->engines)
    e->set_peer_hook([w](uint32_t session) -> Engine* {
      return session < w->engines.size() ? w->engines[session].get()
                                         : nullptr;
    });
  return w;
}

// One-process-per-rank world over TCP sockets (the reference's
// emulator-per-MPI-rank rung).  Returns a world holding this rank only.
void* accl_world_create_tcp(int rank, int nranks, int base_port,
                            uint64_t devmem_bytes) {
  auto* w = new World();
  w->tcp = true;
  try {
    w->engines.push_back(std::make_unique<Engine>(
        uint32_t(rank), devmem_bytes,
        std::make_unique<TcpTransport>(rank, nranks, base_port,
                                       std::vector<std::string>{})));
  } catch (...) {
    delete w;
    return nullptr;
  }
  return w;
}

// Datagram world: N engines over the fragmenting/reordering datagram
// rung (the reference's UDP POE + depacketizer + rxbuf_session stack).
void* accl_world_create_dgram(int nranks, uint64_t devmem_bytes,
                              uint32_t mtu, uint32_t reorder_window) {
  auto* w = new World();
  w->dgram_hub = std::make_shared<DgramHub>(nranks, mtu, reorder_window);
  for (int r = 0; r < nranks; ++r) {
    w->engines.push_back(std::make_unique<Engine>(
        uint32_t(r), devmem_bytes,
        std::make_unique<DatagramTransport>(w->dgram_hub, r)));
    w->engines.back()->set_lossy_transport(true);
  }
  return w;
}

// RDMA world: N engines over the queue-pair transport (the reference's
// CoyoteDevice rung) — ordered message plane for control/eager, a
// separate one-sided memory plane for rendezvous WRITEs.
void* accl_world_create_rdma(int nranks, uint64_t devmem_bytes) {
  auto* w = new World();
  w->rdma_hub = std::make_shared<RdmaHub>(nranks);
  for (int r = 0; r < nranks; ++r) {
    auto t = std::make_unique<RdmaTransport>(w->rdma_hub, r, nranks);
    w->rdma_transports.push_back(t.get());
    w->engines.push_back(std::make_unique<Engine>(
        uint32_t(r), devmem_bytes, std::move(t)));
  }
  return w;
}

// Queue-pair observability (dump_communicator analog for the RDMA rung).
int accl_dump_qps(void* wp, int rank, char* out, int cap) {
  auto* w = static_cast<World*>(wp);
  if (!w || cap <= 0) return -1;
  if (rank < 0 || rank >= int(w->rdma_transports.size())) return -1;
  std::string s = w->rdma_transports[rank]->dump_qps();
  int n = int(std::min<size_t>(s.size(), size_t(cap) - 1));
  std::memcpy(out, s.data(), size_t(n));
  out[n] = 0;
  return n;
}

// One-shot datagram-level fault on the shared hub (1=drop next fragment,
// 2=duplicate next fragment); -1 if this world has no datagram rung.
int accl_dgram_fault(void* wp, uint32_t kind) {
  auto* w = static_cast<World*>(wp);
  if (!w || !w->dgram_hub) return -1;
  w->dgram_hub->inject_fault(kind);
  return 0;
}

void accl_world_destroy(void* wp) { delete static_cast<World*>(wp); }

// Two-phase teardown, phase 1 (see Engine::shutdown): stop every
// engine's threads and finalize every pending call so host-side
// waiters return promptly; storage stays valid until
// accl_world_destroy.  The driver calls this, then joins its waiter
// threads, then destroys — the ordering that makes "a waiter was still
// inside the engine when the world died" impossible.
void accl_world_shutdown(void* wp) {
  auto* w = static_cast<World*>(wp);
  if (!w) return;
  for (auto& e : w->engines)
    if (e) e->shutdown();
}

int accl_cfg_rx(void* wp, int rank, int nbufs, uint64_t bufsize) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->cfg_rx_buffers(uint32_t(nbufs), bufsize);
  return 0;
}

int accl_set_comm(void* wp, int rank, const uint32_t* words, int n) {
  Engine* e = world_get(wp, rank);
  return e ? e->set_comm(words, n) : -1;
}

int accl_set_arithcfg(void* wp, int rank, const uint32_t* words, int n) {
  Engine* e = world_get(wp, rank);
  return e ? e->set_arithcfg(words, n) : -1;
}

int accl_set_tuning(void* wp, int rank, uint32_t key, uint32_t value) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  return e->set_tuning(key, value) == 0 ? 0 : -2;  // -2: unknown key
}

int accl_inject_fault(void* wp, int rank, uint32_t kind) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->inject_fault(kind);
  return 0;
}

// ---- resilience control plane (retransmission / abort / shrink /
// chaos; the driver-side knobs live in accl_tpu/resilience) ----

// Eager retransmission config: retry_max NACK rounds with exponential
// backoff from retry_base_us (0 rounds = the lane is off).
int accl_set_resilience(void* wp, int rank, uint32_t retry_max,
                        uint32_t retry_base_us) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->set_resilience(retry_max, retry_base_us);
  return 0;
}

// Epoch-tagged communicator abort (ULFM-style revoke): every pending
// call on all live ranks finalizes fast with err_bits | COMM_ABORTED.
int accl_abort(void* wp, int rank, int comm_id, uint32_t err_bits) {
  Engine* e = world_get(wp, rank);
  return e ? e->abort_comm(uint32_t(comm_id), err_bits, true) : -1;
}

// Seqn resync + transient-state drain after a classified fault; a
// collective recovery op — every rank of a quiesced world calls it.
int accl_reset_errors(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->reset_errors();
  return 0;
}

// Seeded chaos plan (probabilities in parts-per-million; slow_us stalls
// this rank's egress writer per message).
int accl_set_chaos(void* wp, int rank, uint64_t seed, uint32_t drop_ppm,
                   uint32_t dup_ppm, uint32_t delay_ppm, uint32_t delay_us,
                   uint32_t corrupt_ppm, uint32_t slow_us) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->set_chaos(seed, drop_ppm, dup_ppm, delay_ppm, delay_us, corrupt_ppm,
               slow_us);
  return 0;
}

// Kill-rank chaos: the engine goes silent and aborts its own comms
// with RANK_FAILED so local pending calls finalize fast.
int accl_chaos_kill(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->kill();
  return 0;
}

// Liveness probe: heartbeat every peer of a communicator, collect
// proof-of-life for up to window_us; alive_bitmap bit i = comm-local
// rank i responded (the local rank is always alive).
int accl_probe_liveness(void* wp, int rank, int comm_id, uint32_t window_us,
                        uint64_t* alive_bitmap) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  uint64_t bm = e->probe_liveness(uint32_t(comm_id), window_us);
  if (alive_bitmap) *alive_bitmap = bm;
  return 0;
}

// ---- elastic membership (r11): live rank join ----

// Mint a NEW rank in a live inproc world: a fresh engine wired to the
// shared hub at the next session id (the replacement process of the
// emulator rung — on hardware this is a new host joining the fabric).
// Returns the new global rank / session id, or -1 when the world's
// transport cannot grow (TCP/dgram/RDMA rungs, or join headroom
// exhausted — see the engines.reserve in accl_world_create).
int accl_world_add_rank(void* wp) {
  auto* w = static_cast<World*>(wp);
  if (!w || !w->hub) return -1;
  if (w->engines.size() >= w->engines.capacity()) return -1;
  int r = w->hub->add_rank();
  w->engines.push_back(std::make_unique<Engine>(
      uint32_t(r), w->devmem_bytes,
      std::make_unique<InprocTransport>(w->hub, r)));
  w->engines.back()->set_peer_hook([w](uint32_t session) -> Engine* {
    return session < w->engines.size() ? w->engines[session].get() : nullptr;
  });
  return r;
}

// Joiner side of the Join/Welcome/StateSync exchange (see Engine::
// join_sync): sync epochs/abort fences + comm-slot count from a live
// sponsor session.  0 on success, -1 on timeout (sponsor deaf/dead).
int accl_join_sync(void* wp, int rank, uint32_t sponsor_session,
                   int timeout_ms) {
  Engine* e = world_get(wp, rank);
  return e ? e->join_sync(sponsor_session, timeout_ms) : -1;
}

// Introspection: number of comm slots (real + placeholder) an engine
// knows, and a comm's current epoch — lets the driver and tests assert
// that a joiner's id space and fences really aligned.
int accl_comm_count(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  return e ? int(e->comm_count()) : -1;
}

uint32_t accl_comm_epoch(void* wp, int rank, int comm_id) {
  Engine* e = world_get(wp, rank);
  return e ? e->comm_epoch(uint32_t(comm_id)) : 0;
}

// Membership counters: joins answered as sponsor / completed as joiner.
void accl_join_stats(void* wp, int rank, uint64_t* sponsored,
                     uint64_t* joined) {
  Engine* e = world_get(wp, rank);
  if (e) e->join_stats(sponsored, joined);
}

// Resilience observability: retransmitted segments, NACKs sent/received,
// epoch-fenced ingress drops.
void accl_resilience_stats(void* wp, int rank, uint64_t* retrans_sent,
                           uint64_t* nacks_tx, uint64_t* nacks_rx,
                           uint64_t* fenced_drops) {
  Engine* e = world_get(wp, rank);
  if (e) e->resilience_stats(retrans_sent, nacks_tx, nacks_rx, fenced_drops);
}

uint64_t accl_alloc(void* wp, int rank, uint64_t nbytes, uint64_t align) {
  Engine* e = world_get(wp, rank);
  return e ? e->alloc(nbytes, align) : 0;
}

// Host-resident buffer region (the reference's host-only buffers /
// external_dma path); returned addresses carry the engine's host tag.
uint64_t accl_alloc_host(void* wp, int rank, uint64_t nbytes,
                         uint64_t align) {
  Engine* e = world_get(wp, rank);
  return e ? e->alloc_host(nbytes, align) : 0;
}

// P2P buffer: a devicemem allocation registered as a peer-writable
// window (FPGABufferP2P analog) — in shared-address-space worlds a
// peer's rendezvous write lands by direct memcpy, bypassing the wire.
uint64_t accl_alloc_p2p(void* wp, int rank, uint64_t nbytes,
                        uint64_t align) {
  Engine* e = world_get(wp, rank);
  if (!e) return 0;
  uint64_t addr = e->alloc(nbytes, align);
  if (addr) e->register_p2p(addr, nbytes);
  return addr;
}

void accl_free_p2p(void* wp, int rank, uint64_t addr) {
  Engine* e = world_get(wp, rank);
  if (!e) return;
  e->unregister_p2p(addr);
  e->free_addr(addr);
}

// Zero-copy host mapping of a devicemem span (the reference's
// bo.map<dtype*>() on a p2p BO).  Valid for the world's lifetime;
// nullptr when out of range.
void* accl_mem_ptr(void* wp, int rank, uint64_t addr, uint64_t nbytes) {
  Engine* e = world_get(wp, rank);
  return e ? e->raw_mem(addr, nbytes) : nullptr;
}

// Egress traffic counters (see Engine::tx_stats) — lets tests assert
// the p2p path moved no payload over the transport.
void accl_tx_stats(void* wp, int rank, uint64_t* msgs,
                   uint64_t* payload_bytes) {
  Engine* e = world_get(wp, rank);
  if (e) e->tx_stats(msgs, payload_bytes);
}

// Explicit session lifecycle (reference open_port/open_con/close_con
// over the tcp_session_handler; see Engine).  open/close return 0 on
// success or (1 + peer_local_rank) / -1 on failure.
int accl_open_port(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  return e ? e->open_port() : -1;
}

int accl_open_con(void* wp, int rank, int comm_id) {
  Engine* e = world_get(wp, rank);
  return e ? e->open_con(uint32_t(comm_id)) : -1;
}

int accl_close_con(void* wp, int rank, int comm_id) {
  Engine* e = world_get(wp, rank);
  return e ? e->close_con(uint32_t(comm_id)) : -1;
}

void accl_free(void* wp, int rank, uint64_t addr) {
  Engine* e = world_get(wp, rank);
  if (e) e->free_addr(addr);
}

int accl_read_mem(void* wp, int rank, uint64_t addr, void* dst, uint64_t n) {
  Engine* e = world_get(wp, rank);
  return e && e->read_mem(addr, dst, n) ? 0 : -1;
}

int accl_write_mem(void* wp, int rank, uint64_t addr, const void* src,
                   uint64_t n) {
  Engine* e = world_get(wp, rank);
  return e && e->write_mem(addr, src, n) ? 0 : -1;
}

uint64_t accl_start_call(void* wp, int rank, const uint32_t* w15) {
  Engine* e = world_get(wp, rank);
  return e ? e->start_call(w15) : 0;
}

// ---- persistent collective plans (r12): pre-marshaled descriptor
// batches replayed with ONE host->engine entry per replay instead of
// one FFI round trip per call (see Engine::plan_create). ----

// Create a plan from ncalls x 15 descriptor words; returns the plan id
// (>= 0) or -1 (malformed input / a referenced comm is aborted).
int accl_plan_create(void* wp, int rank, const uint32_t* words, int ncalls) {
  Engine* e = world_get(wp, rank);
  return e ? e->plan_create(words, ncalls) : -1;
}

// Queue one replay of the whole batch; returns a completion token
// (> 0), -1 for an unknown plan, -2 when the plan was invalidated by
// an abort/epoch fence/reset (the caller must re-capture).
long long accl_plan_replay(void* wp, int rank, int plan_id) {
  Engine* e = world_get(wp, rank);
  return e ? e->plan_replay(plan_id) : -1;
}

// Poll a replay token: 1 = done (retcode = OR of every call's bits,
// duration = sum), 0 = in flight, -1 = unknown token.
int accl_plan_poll(void* wp, int rank, long long token, uint32_t* ret,
                   double* dur) {
  Engine* e = world_get(wp, rank);
  return e ? e->plan_poll(token, ret, dur) : -1;
}

// Blocking twin of accl_plan_poll (the sync replay lane): 1 = done,
// 0 = timeout, -1 = unknown token.
int accl_plan_wait(void* wp, int rank, long long token, int timeout_ms,
                   uint32_t* ret, double* dur) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int rc = e->plan_poll(token, ret, dur);
    if (rc != 0) return rc;
    if (std::chrono::steady_clock::now() >= deadline) return 0;
    accl::det_sleep_for(std::chrono::microseconds(100));
  }
}

// Fence plans touching comm_id (-1 = all): the driver-side half of the
// shrink/grow eviction contract (abort and reset_errors fence
// engine-side on their own).
int accl_plan_invalidate(void* wp, int rank, int comm_id) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->invalidate_plans(comm_id);
  return 0;
}

// Live (valid) plan count — eviction introspection for tests.
int accl_plan_count(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  return e ? e->plan_count() : -1;
}

// Release one plan's engine-side storage (driver plan object died or
// was closed) — the id's slot stays but pins nothing.
int accl_plan_release(void* wp, int rank, int plan_id) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->plan_release(plan_id);
  return 0;
}

int accl_poll_call(void* wp, int rank, uint64_t id, uint32_t* ret,
                   double* dur) {
  Engine* e = world_get(wp, rank);
  return e && e->poll_call(id, ret, dur) ? 1 : 0;
}

int accl_wait_call(void* wp, int rank, uint64_t id, int timeout_ms,
                   uint32_t* ret, double* dur) {
  Engine* e = world_get(wp, rank);
  if (!e) return 0;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    if (e->poll_call(id, ret, dur)) return 1;
    accl::det_sleep_for(std::chrono::microseconds(100));
  }
  return 0;
}

void accl_push_krnl(void* wp, int rank, const void* data, uint64_t n) {
  Engine* e = world_get(wp, rank);
  if (e) e->push_krnl(static_cast<const uint8_t*>(data), n);
}

int accl_pop_stream(void* wp, int rank, uint32_t strm, void* dst, uint64_t cap,
                    uint64_t* got, int timeout_ms) {
  Engine* e = world_get(wp, rank);
  return e && e->pop_stream(strm, static_cast<uint8_t*>(dst), cap, got,
                            timeout_ms)
             ? 1
             : 0;
}

// ---- wire-protocol correctness surface (r13): raw-frame ingest for
// the deterministic fuzzer + malformed-frame counters + egress frame
// tap (seed-corpus capture).  See Engine::ingest_bytes. ----

// Feed one raw frame (64-byte header + payload) to an engine's real
// ingress classification path.  Returns 0 = consumed (or legally
// dropped by the kill/epoch gates), 1 = rejected as malformed, -1 =
// bad rank.
int accl_engine_ingest_bytes(void* wp, int rank, const void* data,
                             uint64_t nbytes) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  return e->ingest_bytes(static_cast<const uint8_t*>(data), nbytes);
}

// Frame counters: frames that passed structural validation vs frames
// rejected as malformed (the latter is the fuzz/abuse observable,
// exported as engine/wire/rejected_frames through the metrics
// registry on the Python side).
void accl_frame_stats(void* wp, int rank, uint64_t* accepted,
                      uint64_t* rejected) {
  Engine* e = world_get(wp, rank);
  if (e) e->frame_stats(accepted, rejected);
}

// ---- engine telemetry snapshot (r14): the native-engine stats plane
// the observability sampler polls (accl_tpu/observability/telemetry.py).
// Versioned flat-array ABI: the schema version names a fixed field
// ORDER (append-only across versions); the caller passes a u64 buffer
// of `cap` entries, the engine fills min(cap, fields) and returns how
// many fields this build knows — an older caller reads a prefix, a
// newer caller learns exactly how much arrived.  -1 = unknown rank. ----
int accl_engine_stats_version(void) { return Engine::kEngineStatsVersion; }

int accl_engine_stats(void* wp, int rank, uint64_t* out, int cap) {
  Engine* e = world_get(wp, rank);
  return e ? e->engine_stats(out, cap) : -1;
}

// ---- per-link wire telemetry (r15): flat (comm, peer) counter rows
// behind the v2 stats plane.  Each row is
// accl_engine_link_stats_stride() u64s (comm, peer, tx/rx msgs+bytes,
// retransmits, NACKs both directions, fenced drops, seeks,
// seek_wait_ns — see Engine::link_stats for the authoritative order);
// only whole rows are written and the TOTAL u64 count is returned, so
// a short buffer truncates at a row boundary and the caller retries
// bigger.  -1 = unknown rank. ----
int accl_engine_link_stats_stride(void) { return Engine::kLinkStatsStride; }

int accl_engine_link_stats(void* wp, int rank, uint64_t* out, int cap) {
  Engine* e = world_get(wp, rank);
  return e ? e->link_stats(out, cap) : -1;
}

// Egress frame tap on/off (bounded ring of the last 256 staged frames).
int accl_frame_tap(void* wp, int rank, int on) {
  Engine* e = world_get(wp, rank);
  if (!e) return -1;
  e->set_frame_tap(on != 0);
  return 0;
}

int accl_frame_tap_count(void* wp, int rank) {
  Engine* e = world_get(wp, rank);
  return e ? e->tap_count() : -1;
}

// Read captured frame `idx` (oldest first); returns the frame's full
// byte size (retry with a bigger buffer if > cap), or -1 when idx is
// out of range / the rank is unknown.  Index->frame identity is only
// stable while nothing rotates the ring — concurrent readers of a
// live tap must use accl_frame_tap_drain.
int accl_frame_tap_read(void* wp, int rank, int idx, void* out, int cap) {
  Engine* e = world_get(wp, rank);
  return e ? e->tap_read(idx, static_cast<uint8_t*>(out), cap) : -1;
}

// Atomically drain captured frames into out as consecutive
// [u32 len][bytes] records (one lock hold — frames can never tear
// against live traffic rotating the ring); returns bytes written,
// 0 when the tap is empty, -1 for an unknown rank.
int accl_frame_tap_drain(void* wp, int rank, void* out, int cap) {
  Engine* e = world_get(wp, rank);
  return e ? e->tap_drain(static_cast<uint8_t*>(out), cap) : -1;
}

int accl_dump_rx(void* wp, int rank, char* out, int cap) {
  Engine* e = world_get(wp, rank);
  if (!e || cap <= 0) return -1;
  std::string s = e->dump_rx();
  int n = int(std::min<size_t>(s.size(), size_t(cap) - 1));
  std::memcpy(out, s.data(), size_t(n));
  out[n] = 0;
  return n;
}

}  // extern "C"
