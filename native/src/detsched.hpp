// Deterministic schedule-exploration scheduler (ACCL_DETSCHED builds).
//
// The engine's synchronization wrappers in common.hpp (accl::Mutex,
// accl::CondVar, accl::Thread, det_sleep_for/det_yield) route every
// blocking operation through the hooks below when a controlled run is
// active.  All registered threads are serialized onto ONE virtual
// scheduler: exactly one thread runs at a time, every hook is a
// scheduling point, and which thread runs next is decided by an
// explicit schedule (a choice string) — so a drill's interleaving is a
// pure function of (schedule, seed) and can be replayed bit-for-bit
// from the failing-schedule artifact scripts/model_check.py dumps
// (hex trace + seed, mirroring fuzz_wire.py's failing-frame artifact).
//
// Blocking never really blocks: timed waits park on a VIRTUAL clock
// that jumps to the earliest deadline whenever no thread is runnable,
// so a drill that would spend seconds in receive budgets finishes in
// microseconds and a lost wakeup surfaces as a detected deadlock, not
// a hung harness.
//
// The explorer at the bottom does stateless bounded exploration over
// choice prefixes: DFS over decision points, preemption bounding
// (alternatives that would exceed the bound are not expanded), and a
// DPOR-flavored persistent-set prune — a decision point only branches
// when at least two runnable threads' pending operations CONFLICT
// (same mutex, or a notify against a wait on the same condvar);
// interleavings of independent operations commute and are explored
// once.  Duplicate complete traces are hash-deduplicated.
//
// This header is self-contained (std only) so common.hpp can include
// it before defining the wrapper classes.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace accl {
namespace det {

constexpr int kMaxThreads = 64;
constexpr uint64_t kInf = ~0ull;

// Operation a thread is about to perform at its scheduling point —
// the conflict relation below drives the partial-order prune.
enum class OpKind : uint8_t {
  None = 0,
  Lock,      // about to acquire obj (mutex)
  Unlock,    // just released obj (mutex)
  CvWait,    // about to park on obj (condvar)
  CvNotify,  // about to notify obj (condvar)
  Sleep,
  Yield,
  Spawn,
  Exit,
  Join,
};

struct Decision {
  uint8_t nen = 0;        // total option count: enabled threads + injections
  uint8_t chosen = 0;     // index chosen into the sorted enabled list
  uint8_t inj_from = 0;   // options >= inj_from are timeout injections
  bool branchable = false;  // thread alternatives worth exploring
  bool inj_branch = false;  // injection alternatives worth exploring
};

struct RunResult {
  bool failed = false;
  std::string what;          // first invariant violation / deadlock text
  uint64_t fail_step = 0;
  std::vector<uint8_t> choices;     // chosen index per decision (the trace)
  std::vector<Decision> decisions;  // full decision metadata
  uint64_t steps = 0;
  bool free_ran = false;  // budget/deadlock escape hatch fired (see below)
  uint64_t injections = 0;       // timeout injections taken this run
  uint64_t pressure_events = 0;  // resource-pressure arming events observed
  uint64_t live_leak = 0;        // submitted calls never finalized (liveness)
};

class Sched {
 public:
  static Sched& inst() {
    static Sched* s = new Sched();  // immortal: engine threads may outlive main
    return *s;
  }

  // ---- hook-side queries (hot; called from every wrapper) ----
  bool on() const { return active_.load(std::memory_order_relaxed) && slot() >= 0; }
  bool run_active() const { return active_.load(std::memory_order_relaxed); }
  // True once the escape hatch fired: the run is tearing down on real
  // primitives, so time-based code (receive budgets) must read the REAL
  // clock again — the virtual clock is frozen and would never expire
  // them, wedging the very teardown the hatch exists to guarantee.
  bool free_running() const {
    return free_run_flag_.load(std::memory_order_relaxed);
  }

  // ---- virtual clock ----
  uint64_t now_ns() {
    std::lock_guard<std::mutex> g(mu_);
    return vnow_;
  }

  // ---- mutex protocol (wrapper holds no real lock on entry) ----
  // Deterministic acquire: yield at the decision point, then take
  // logical ownership (the real lock is guaranteed free when the owner
  // table says so — ownership mirrors the real lock exactly at every
  // scheduling point).  m is the address of the underlying std::mutex.
  void lock_hooked(std::mutex* m) {
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    for (;;) {
      yield_locked(g, me, OpKind::Lock, m);
      if (free_run_) break;  // escape hatch: fall through to real lock
      auto it = owner_.find(m);
      if (it == owner_.end()) {
        owner_[m] = me;
        break;
      }
      // owner holds it: park until the unlock hook wakes this slot
      th_[me].st = St::BlockedMutex;
      th_[me].obj = m;
      schedule_locked(g, me);
    }
    g.unlock();
    m->lock();  // uncontended by construction (or free-run: real race)
  }

  void unlock_hooked(std::mutex* m) {
    m->unlock();
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    owner_.erase(m);
    wake_mutex_waiters_locked(m);
    // release is a scheduling point too: schedules where a waiter (or
    // anyone else) runs between unlock and the owner's next action are
    // reachable — the InprocHub::detach race needs exactly this window
    yield_locked(g, me, OpKind::Unlock, m);
  }

  // ---- condvar protocol ----
  // Caller holds `lk` (a std::unique_lock over the user mutex).
  // Releases it, parks on the virtual condvar until a notify or the
  // virtual deadline (timeout_ns == kInf: untimed), then deterministically
  // reacquires.  Returns true if woken by a notify, false on timeout.
  bool cv_block(const void* cv, std::unique_lock<std::mutex>& lk,
                uint64_t timeout_ns) {
    std::mutex* m = lk.mutex();
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    if (free_run_) {
      g.unlock();
      return free_run_cv_wait(lk, timeout_ns);
    }
    // Hurried thread (a timeout injection granted it charges): its timed
    // waits expire IMMEDIATELY, advancing the virtual clock by the full
    // slice while every peer stays parked — one injection decision burns
    // a whole sliced receive budget "atomically", which is exactly the
    // "budget expires while conflicting ops are still pending" ordering
    // quiescence can never produce (it only advances time when NOTHING
    // is runnable).  No scheduling point: the burn must not let peers
    // interleave, or the injected expiry degenerates into quiescence.
    if (me >= 0 && th_[me].hurry > 0 && timeout_ns != kInf) {
      --th_[me].hurry;
      vnow_ += timeout_ns;
      wake_expired_locked();
      return false;  // timeout; the user mutex stays held
    }
    yield_locked(g, me, OpKind::CvWait, cv);
    if (free_run_) {
      g.unlock();
      return free_run_cv_wait(lk, timeout_ns);
    }
    // release the user mutex while parked (what a real cv wait does)
    lk.unlock();
    owner_.erase(m);
    wake_mutex_waiters_locked(m);
    th_[me].st = St::BlockedCv;
    th_[me].obj = cv;
    th_[me].deadline = timeout_ns == kInf ? kInf : vnow_ + timeout_ns;
    th_[me].notified = false;
    th_[me].cv_seq = cv_seq_++;
    // a timed park arms the injection window: the very next decision may
    // offer "this waiter's budget slice expires now" as an alternative
    if (timeout_ns != kInf) inj_window_ = true;
    schedule_locked(g, me);
    bool notified = th_[me].notified;
    th_[me].deadline = kInf;
    // deterministic reacquire of the user mutex
    for (;;) {
      if (free_run_) break;
      auto it = owner_.find(m);
      if (it == owner_.end()) {
        owner_[m] = me;
        break;
      }
      th_[me].st = St::BlockedMutex;
      th_[me].obj = m;
      schedule_locked(g, me);
    }
    g.unlock();
    lk.lock();
    return notified;
  }

  void cv_notify(const void* cv, bool all) {
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    yield_locked(g, me, OpKind::CvNotify, cv);
    if (free_run_) return;
    // FIFO wake order (by park sequence): deterministic notify_one
    int best = -1;
    do {
      best = -1;
      uint64_t best_seq = kInf;
      for (int i = 0; i < nth_; ++i) {
        Th& t = th_[i];
        if (t.used && t.st == St::BlockedCv && t.obj == cv &&
            t.cv_seq < best_seq) {
          best = i;
          best_seq = t.cv_seq;
        }
      }
      if (best >= 0) {
        th_[best].notified = true;
        th_[best].st = St::Ready;
        th_[best].pending = OpKind::Lock;  // it reacquires its mutex next
        th_[best].obj = nullptr;
      }
    } while (all && best >= 0);
  }

  // ---- sleep / yield ----
  void sleep_hooked(uint64_t ns) {
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    yield_locked(g, me, OpKind::Sleep, nullptr);
    if (free_run_) return;  // virtual sleep: no real time passes
    th_[me].st = St::BlockedSleep;
    th_[me].deadline = vnow_ + (ns ? ns : 1);
    schedule_locked(g, me);
    th_[me].deadline = kInf;
  }

  void yield_hooked() {
    std::unique_lock<std::mutex> g(mu_);
    yield_locked(g, slot(), OpKind::Yield, nullptr);
  }

  // ---- thread lifecycle ----
  // Parent side, BEFORE std::thread construction: reserve the child's
  // slot so quiescence can never be declared while a spawn is in
  // flight.  Returns the slot id the child adopts, or -1 when the run
  // table is full (the child then runs unmanaged — real primitives).
  int pre_spawn() {
    std::lock_guard<std::mutex> g(mu_);
    if (!active_.load() || free_run_) return -1;
    if (nth_ >= kMaxThreads) return -1;
    int id = nth_++;
    th_[id].used = true;
    th_[id].exited = false;
    th_[id].st = St::Spawning;  // not schedulable until child_enter
    th_[id].pending = OpKind::Spawn;
    th_[id].obj = nullptr;
    th_[id].notified = false;
    th_[id].deadline = kInf;
    return id;
  }

  void child_enter(int id) {
    if (id < 0) return;
    std::unique_lock<std::mutex> g(mu_);
    slot_ref() = id;
    th_[id].tid = std::this_thread::get_id();
    th_[id].st = St::Ready;
    cv_.notify_all();  // release the parent's await_child_enter
    // if the token is parked (everyone was waiting for this spawn to
    // land), hand it on now; otherwise wait for the first grant
    if (cur_ < 0) {
      pick_next_locked();
      cv_.notify_all();
    }
    schedule_locked(g, id, /*reschedule=*/false);
  }

  // Parent side, right after std::thread construction: block (real,
  // microseconds) until the child has REGISTERED.  This makes spawn a
  // deterministic synchronization point — whether the child is in the
  // enabled set no longer depends on OS thread-start timing, which
  // would otherwise misalign prefix replay run-to-run.
  void await_child_enter(int id) {
    if (id < 0) return;
    std::unique_lock<std::mutex> g(mu_);
    cv_.wait(g, [&] { return th_[id].st != St::Spawning || free_run_; });
  }

  void child_exit() {
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    if (me < 0) return;
    th_[me].st = St::Done;
    th_[me].exited = true;
    // wake joiners parked on this slot
    for (int i = 0; i < nth_; ++i)
      if (th_[i].used && th_[i].st == St::BlockedJoin &&
          th_[i].join_slot == me)
        th_[i].st = St::Ready;
    slot_ref() = -1;
    pick_next_locked();  // hand the token on; this thread is done
    cv_.notify_all();
  }

  // Joiner side: park until the target SLOT exits, then the caller
  // does the real std::thread::join (the exiting thread is past its
  // last managed instruction — the real join returns promptly).
  // Keyed by slot id, not thread id: a child that has not yet
  // registered must read as not-exited, never as already-gone.
  void join_wait_slot(int id) {
    if (id < 0) return;
    std::unique_lock<std::mutex> g(mu_);
    int me = slot();
    for (;;) {
      yield_locked(g, me, OpKind::Join, nullptr);
      if (free_run_) return;
      if (th_[id].exited) return;
      th_[me].st = St::BlockedJoin;
      th_[me].join_slot = id;
      schedule_locked(g, me);
    }
  }

  // ---- drill-side invariant check ----
  void expect(bool cond, const char* what) {
    if (cond) return;
    std::lock_guard<std::mutex> g(mu_);
    if (!result_.failed) {
      result_.failed = true;
      result_.what = what;
      result_.fail_step = step_;
    }
  }

  // ---- resource-pressure modeling ----
  // Called (via det_note_pressure) when a modeled resource saturates —
  // e.g. the rx pool staging an ingress because no buffer is IDLE.
  // Arms the timeout-injection window: exhaustion is the precondition
  // for the interesting timeout class (a budget expiring because pinned
  // resources, not a slow peer, starve the match), so the explorer gets
  // an injection alternative at exactly the decision where it matters.
  void note_pressure() {
    std::lock_guard<std::mutex> g(mu_);
    if (free_run_) return;
    ++pressure_events_;
    inj_window_ = true;
  }

  // Injections taken so far this run — drills consult this to decide
  // which invariants still hold (an injected timeout legalizes
  // RECEIVE_TIMEOUT retcodes that would be findings on a clean run).
  uint64_t timeout_injections() {
    std::lock_guard<std::mutex> g(mu_);
    return injections_;
  }

  // ---- liveness tokens ----
  // One token per submitted engine call; the finalize paths return it.
  // Tokens still outstanding when the drill returns (without the free-
  // run escape hatch muddying the schedule) are calls that never
  // finalized under this fair schedule — the stuck-progress finding.
  void live_begin() {
    std::lock_guard<std::mutex> g(mu_);
    ++live_tokens_;
  }
  void live_end() {
    std::lock_guard<std::mutex> g(mu_);
    if (live_tokens_ > 0) --live_tokens_;
  }

  // ---- run control (explorer side; call from ONE driver thread) ----
  RunResult run(const std::vector<uint8_t>& prefix, uint64_t seed,
                uint64_t max_steps, const std::function<void()>& drill) {
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int i = 0; i < kMaxThreads; ++i) th_[i] = Th{};
      nth_ = 1;  // slot 0 = this driver thread
      th_[0].used = true;
      th_[0].st = St::Running;
      th_[0].tid = std::this_thread::get_id();
      slot_ref() = 0;
      cur_ = 0;
      vnow_ = 0;
      step_ = 0;
      cv_seq_ = 0;
      preempts_ = 0;
      owner_.clear();
      prefix_ = prefix;
      prefix_pos_ = 0;
      seed_ = seed ? seed : 1;
      max_steps_ = max_steps;
      free_run_ = false;
      free_run_flag_.store(false, std::memory_order_relaxed);
      injections_ = 0;
      pressure_events_ = 0;
      inj_window_ = false;
      live_tokens_ = 0;
      result_ = RunResult{};
      active_.store(true);
    }
    drill();
    RunResult out;
    {
      std::lock_guard<std::mutex> g(mu_);
      active_.store(false);
      // liveness: every submitted call must have finalized by drill
      // return.  Suppressed when the escape hatch fired (the schedule
      // already failed) or an earlier finding owns the run.
      if (live_tokens_ != 0 && !free_run_ && !result_.failed) {
        result_.failed = true;
        result_.what =
            "liveness: submitted call(s) never finalized (stuck-progress)";
        result_.fail_step = step_;
      }
      out = result_;
      out.free_ran = free_run_;
      out.steps = step_;
      out.injections = injections_;
      out.pressure_events = pressure_events_;
      out.live_leak = live_tokens_;
      slot_ref() = -1;
    }
    cv_.notify_all();  // release anything the escape hatch left parked
    return out;
  }

  // exploration knobs (see Explorer)
  int preempt_bound = 3;
  uint64_t branch_depth = 4096;  // decisions beyond this: default policy only
  // Timeout injections allowed per run.  0 (the default) disables the
  // mechanism entirely: decision spaces, prefix consumption, and traces
  // are bit-identical to the pre-injection checker, so artifacts
  // recorded without --ibound replay unchanged.
  int inject_bound = 0;
  // Charges an injection grants its victim: enough immediate-expiry
  // slices to burn a whole engine receive budget (1 s default budget /
  // 50 ms steady slices = 20) with headroom for the fast-phase slices.
  int hurry_charges = 64;

 private:
  enum class St : uint8_t {
    Ready,
    Running,
    Spawning,
    BlockedMutex,
    BlockedCv,
    BlockedSleep,
    BlockedJoin,
    Done,
  };
  struct Th {
    bool used = false, exited = false, notified = false;
    std::thread::id tid{};
    St st = St::Ready;
    const void* obj = nullptr;  // blocked-on / pending-op object
    OpKind pending = OpKind::None;
    uint64_t deadline = kInf;
    uint64_t cv_seq = 0;
    int join_slot = -1;
    int hurry = 0;  // immediate-expiry charges from a timeout injection
  };

  static int& slot_ref() {
    thread_local int s = -1;
    return s;
  }
  static int slot() { return slot_ref(); }

  // Wake every parked thread whose deadline the virtual clock has
  // passed (quiescence and injected burns share these semantics: a cv
  // deadline passing is a timeout, never a notify).
  void wake_expired_locked() {
    for (int i = 0; i < nth_; ++i)
      if (th_[i].used && th_[i].deadline <= vnow_ &&
          (th_[i].st == St::BlockedSleep || th_[i].st == St::BlockedCv)) {
        bool was_cv = th_[i].st == St::BlockedCv;
        th_[i].notified = false;
        th_[i].st = St::Ready;
        th_[i].pending = was_cv ? OpKind::Lock : OpKind::None;
        th_[i].obj = nullptr;
      }
  }

  void wake_mutex_waiters_locked(std::mutex* m) {
    for (int i = 0; i < nth_; ++i)
      if (th_[i].used && th_[i].st == St::BlockedMutex && th_[i].obj == m) {
        th_[i].st = St::Ready;
        th_[i].pending = OpKind::Lock;
        th_[i].obj = m;
      }
  }

  // Two pending ops conflict when reordering them could change the
  // outcome: same mutex, or a notify against a wait on the same cv.
  static bool conflict(const Th& a, const Th& b) {
    if (a.pending == OpKind::Spawn || b.pending == OpKind::Spawn) return true;
    if (a.pending == OpKind::Lock && b.pending == OpKind::Lock)
      return a.obj && a.obj == b.obj;
    if ((a.pending == OpKind::Unlock && b.pending == OpKind::Lock) ||
        (a.pending == OpKind::Lock && b.pending == OpKind::Unlock))
      return a.obj && a.obj == b.obj;
    auto cvpair = [](const Th& x, const Th& y) {
      return x.pending == OpKind::CvNotify && y.pending == OpKind::CvWait &&
             x.obj && x.obj == y.obj;
    };
    return cvpair(a, b) || cvpair(b, a);
  }

  // The core decision point.  Called with mu_ held by the thread that
  // holds the token; records its pending op, picks who runs next, and
  // parks the caller until it is scheduled again.
  void yield_locked(std::unique_lock<std::mutex>& g, int me, OpKind kind,
                    const void* obj) {
    if (me < 0 || free_run_) return;
    th_[me].pending = kind;
    th_[me].obj = obj;
    th_[me].st = St::Ready;
    pick_next_locked();
    cv_.notify_all();
    schedule_locked(g, me, /*reschedule=*/false);
  }

  // Park until this slot is granted the token (st == Running), or the
  // escape hatch fires.  When `reschedule`, the caller just blocked
  // itself (st set by the caller) and the token must be handed on first.
  void schedule_locked(std::unique_lock<std::mutex>& g, int me,
                       bool reschedule = true) {
    if (free_run_) return;
    if (reschedule) {
      pick_next_locked();
      cv_.notify_all();
    }
    cv_.wait(g, [&] { return th_[me].st == St::Running || free_run_; });
  }

  // Pick the next token holder among Ready threads; advance the
  // virtual clock past sleeps/timeouts when nothing is runnable.
  void pick_next_locked() {
    for (;;) {
      int en[kMaxThreads];
      int nen = 0;
      for (int i = 0; i < nth_; ++i)
        if (th_[i].used && th_[i].st == St::Ready) en[nen++] = i;
      // Timeout-injection candidates: parked TIMED waiters, offered as
      // extra decision alternatives [nen, nen+ninj) while the window is
      // armed (a timed park or a resource-pressure event just happened)
      // and the per-run injection budget has room.  Choosing one means
      // "that waiter's budget slice expires NOW, with these enabled
      // threads' conflicting ops still pending".  The window is one-shot
      // per arming event so the branching factor stays tied to the
      // interesting program points instead of every decision.
      int inj[kMaxThreads];
      int ninj = 0;
      bool window = inj_window_;
      inj_window_ = false;
      if (nen > 0 && window && inject_bound > 0 &&
          injections_ < uint64_t(inject_bound)) {
        for (int i = 0; i < nth_; ++i)
          if (th_[i].used && th_[i].st == St::BlockedCv &&
              th_[i].deadline != kInf)
            inj[ninj++] = i;
      }
      if (nen > 0) {
        if (++step_ > max_steps_) {
          if (!result_.failed) {
            result_.failed = true;
            result_.what = "step budget exceeded (possible livelock)";
            result_.fail_step = step_;
          }
          enter_free_run_locked();
          return;
        }
        int ntot = nen + ninj;
        int choice = 0;
        bool from_prefix = prefix_pos_ < prefix_.size();
        if (from_prefix) {
          // consumed at EVERY decision (also forced nen==1 ones) so a
          // prefix copied from a recorded trace stays index-aligned
          choice = prefix_[prefix_pos_++] % ntot;
        } else if (nen == 1 && ninj == 0) {
          choice = 0;
        } else {
          // default policy: keep the current thread running when it is
          // still enabled (short traces), else a seeded pick — varied
          // but fully reproducible from (prefix, seed).  Injections are
          // never taken by default: only an explorer-expanded (or
          // replayed) prefix byte reaches the [nen, ntot) range.
          choice = -1;
          for (int k = 0; k < nen; ++k)
            if (en[k] == cur_) choice = k;
          if (choice < 0)
            choice = int(mix(seed_ ^ (step_ * 0x9E3779B97F4A7C15ull)) % nen);
        }
        // preemption accounting: picking another thread while the
        // current one is still runnable is a preemption.  An injection
        // is NOT one: no runner is displaced, the enabled set simply
        // grows before the re-pick.
        if (choice < nen) {
          bool cur_enabled = false;
          for (int k = 0; k < nen; ++k)
            if (en[k] == cur_) cur_enabled = true;
          if (cur_enabled && en[choice] != cur_) ++preempts_;
        }
        // branchable: >= 2 enabled, a real conflict among pending ops,
        // inside the branch window, preemption budget left
        bool conf = false;
        for (int a = 0; a < nen && !conf; ++a)
          for (int b = a + 1; b < nen && !conf; ++b)
            if (conflict(th_[en[a]], th_[en[b]])) conf = true;
        Decision d;
        d.nen = uint8_t(ntot);
        d.chosen = uint8_t(choice);
        d.inj_from = uint8_t(nen);
        d.branchable = nen > 1 && conf &&
                       result_.decisions.size() < branch_depth &&
                       preempts_ < uint64_t(preempt_bound);
        d.inj_branch = ninj > 0 && result_.decisions.size() < branch_depth;
        result_.decisions.push_back(d);
        result_.choices.push_back(uint8_t(choice));
        if (choice >= nen) {
          // timeout injection: jump the virtual clock to the victim's
          // deadline even though threads are runnable — the wall-clock
          // ordering quiescence hides — wake it as timed out, and grant
          // hurry charges so its subsequent budget slices burn through
          // without peers interleaving.
          int vi = inj[choice - nen];
          ++injections_;
          if (th_[vi].deadline > vnow_) vnow_ = th_[vi].deadline;
          th_[vi].hurry = hurry_charges;
          wake_expired_locked();  // wakes vi + anything the jump passed
          if (debug_)
            std::fprintf(stderr,
                         "[ds] step=%llu INJECT slot %d (vnow -> %llu)\n",
                         (unsigned long long)step_, vi,
                         (unsigned long long)vnow_);
          continue;  // re-pick with the woken waiter(s) enabled
        }
        cur_ = en[choice];
        th_[cur_].st = St::Running;
        if (debug_) {
          std::fprintf(stderr, "[ds] step=%llu nen=%d chose=%d -> slot %d",
                       (unsigned long long)step_, nen, choice, cur_);
          for (int k = 0; k < nen; ++k)
            std::fprintf(stderr, " e%d(p=%d)", en[k],
                         int(th_[en[k]].pending));
          std::fprintf(stderr, "\n");
        }
        return;
      }
      // nothing runnable: advance the virtual clock to the earliest
      // deadline (sleeps + timed cv waits)
      uint64_t dl = kInf;
      for (int i = 0; i < nth_; ++i)
        if (th_[i].used &&
            (th_[i].st == St::BlockedSleep || th_[i].st == St::BlockedCv) &&
            th_[i].deadline < dl)
          dl = th_[i].deadline;
      if (dl == kInf) {
        // spawning threads still on their way in: let them arrive (the
        // parent holds no token; real wait is bounded by thread start)
        bool spawning = false;
        for (int i = 0; i < nth_; ++i)
          if (th_[i].used && th_[i].st == St::Spawning) spawning = true;
        if (spawning) {
          cur_ = -1;
          return;  // child_enter will call schedule_locked -> picks next
        }
        if (!result_.failed) {
          result_.failed = true;
          result_.what = "deadlock: no runnable thread and no deadline";
          result_.fail_step = step_;
        }
        enter_free_run_locked();
        return;
      }
      vnow_ = dl;
      wake_expired_locked();  // cv deadline passing: a timeout, not a wake
    }
  }

  // Escape hatch for deadlock/budget findings: stop scheduling, wake
  // every parked thread, and let the drill finish on REAL primitives
  // (engine receive budgets unstick anything genuinely wedged) so the
  // harness can tear down and report instead of hanging.
  void enter_free_run_locked() {
    if (debug_)
      std::fprintf(stderr, "[ds] FREE-RUN step=%llu what=%s\n",
                   (unsigned long long)step_,
                   result_.failed ? result_.what.c_str() : "(none)");
    free_run_ = true;
    free_run_flag_.store(true, std::memory_order_relaxed);
    for (int i = 0; i < nth_; ++i)
      if (th_[i].used && th_[i].st != St::Done) th_[i].st = St::Running;
    cv_.notify_all();
  }

  bool free_run_cv_wait(std::unique_lock<std::mutex>& lk, uint64_t ns) {
    // free-run fallback: the caller's mutex MUST be released across
    // the wait (the predicate it re-checks only changes under that
    // lock — holding it here would wedge the very thread that has to
    // flip it, hanging the harness instead of reporting the finding)
    (void)ns;
    if (debug_) {
      static thread_local uint64_t spins = 0;
      if (++spins % 5000 == 0)
        std::fprintf(stderr, "[ds] free-run spin slot=%d spins=%llu\n",
                     slot(), (unsigned long long)spins);
    }
    lk.unlock();
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
    lk.lock();
    return true;  // caller re-checks its predicate
  }

  static uint64_t mix(uint64_t x) {
    x ^= x >> 33;
    x *= 0xFF51AFD7ED558CCDull;
    x ^= x >> 33;
    x *= 0xC4CEB9FE1A85EC53ull;
    x ^= x >> 33;
    return x;
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::atomic<bool> active_{false};
  bool free_run_ = false;               // guarded by mu_
  std::atomic<bool> free_run_flag_{false};  // lock-free mirror for hooks
  Th th_[kMaxThreads];
  int nth_ = 0;
  int cur_ = -1;
  uint64_t vnow_ = 0, step_ = 0, cv_seq_ = 0, preempts_ = 0;
  std::map<const std::mutex*, int> owner_;
  std::vector<uint8_t> prefix_;
  size_t prefix_pos_ = 0;
  uint64_t seed_ = 1, max_steps_ = 200000;
  uint64_t injections_ = 0, pressure_events_ = 0;
  bool inj_window_ = false;
  int64_t live_tokens_ = 0;
  RunResult result_;
  bool debug_ = std::getenv("ACCL_DS_DEBUG") != nullptr;
};

// ---- thin hook surface used by common.hpp wrappers ----
inline bool on() { return Sched::inst().on(); }
inline bool run_active() { return Sched::inst().run_active(); }
inline uint64_t now_ns() { return Sched::inst().now_ns(); }
inline bool free_running() { return Sched::inst().free_running(); }
inline void lock_hooked(std::mutex* m) { Sched::inst().lock_hooked(m); }
inline void unlock_hooked(std::mutex* m) { Sched::inst().unlock_hooked(m); }
inline bool cv_block(const void* cv, std::unique_lock<std::mutex>& lk,
                     uint64_t timeout_ns) {
  return Sched::inst().cv_block(cv, lk, timeout_ns);
}
inline void cv_notify(const void* cv, bool all) {
  Sched::inst().cv_notify(cv, all);
}
inline void sleep_hooked(uint64_t ns) { Sched::inst().sleep_hooked(ns); }
inline void yield_hooked() { Sched::inst().yield_hooked(); }
inline void expect(bool cond, const char* what) {
  Sched::inst().expect(cond, what);
}
inline void note_pressure() { Sched::inst().note_pressure(); }
inline uint64_t timeout_injections() {
  return Sched::inst().timeout_injections();
}
inline void live_begin() { Sched::inst().live_begin(); }
inline void live_end() { Sched::inst().live_end(); }

// ---------------------------------------------------------------------------
// Explorer: stateless bounded exploration over choice prefixes.
// ---------------------------------------------------------------------------
struct ExploreOpts {
  uint64_t max_runs = 2000;
  uint64_t max_steps = 200000;   // per run
  uint64_t seed = 1;
  int preempt_bound = 3;
  uint64_t branch_depth = 4096;
  bool stop_on_first = true;
  double budget_s = 0;  // 0 = unbounded
  int inject_bound = 0;  // timeout injections per run (0 = disabled)
  // Trace-guided exploration: replay this observed choice prefix
  // bit-for-bit, then explore the SUFFIX only — the r13 --replay hex
  // idiom turned into a DFS seed, so a captured artifact from a live
  // wedge repro focuses the budget on the neighborhood that matters.
  std::vector<uint8_t> seed_prefix;
};

struct ExploreStats {
  uint64_t runs = 0;            // schedules executed
  uint64_t unique_traces = 0;   // distinct complete traces (hash-deduped)
  uint64_t findings = 0;
  RunResult first_failure;      // valid when findings > 0
  std::vector<uint8_t> first_failure_prefix;  // minimal failing prefix
  uint64_t seed = 1;
  uint64_t injected_runs = 0;    // runs where >= 1 timeout was injected
  uint64_t pressure_events = 0;  // resource-pressure arming events, summed
};

inline uint64_t trace_hash(const std::vector<uint8_t>& v) {
  uint64_t h = 1469598103934665603ull;
  for (uint8_t b : v) {
    h ^= b;
    h *= 1099511628211ull;
  }
  h ^= v.size();
  return h;
}

// Shortest failing prefix: re-run with successively shorter prefixes of
// the failing choice string (default policy beyond) and keep the
// shortest that still fails — the replay artifact stays minimal.
inline std::vector<uint8_t> minimize_prefix(
    const std::function<void()>& drill, const std::vector<uint8_t>& failing,
    uint64_t seed, uint64_t max_steps) {
  std::vector<uint8_t> best = failing;
  size_t lo = 0, hi = failing.size();
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    std::vector<uint8_t> probe(failing.begin(), failing.begin() + long(mid));
    RunResult r = Sched::inst().run(probe, seed, max_steps, drill);
    if (r.failed) {
      best = probe;
      hi = mid;
    } else {
      lo = mid + 1;
    }
  }
  return best;
}

inline ExploreStats explore(const std::function<void()>& drill,
                            const ExploreOpts& opts) {
  Sched& S = Sched::inst();
  S.preempt_bound = opts.preempt_bound;
  S.branch_depth = opts.branch_depth;
  S.inject_bound = opts.inject_bound;
  ExploreStats st;
  st.seed = opts.seed;
  std::set<uint64_t> seen;
  // DFS frontier of prefixes; each entry remembers the decision index
  // from which new alternatives may be expanded (alternatives before it
  // are covered by the branch that generated the prefix)
  struct Item {
    std::vector<uint8_t> prefix;
    size_t expand_from;
  };
  std::vector<Item> stack;
  // trace-guided: the seed prefix is replayed verbatim; only decisions
  // past it are expanded (expand_from counts DECISIONS, and prefix
  // bytes map 1:1 onto decisions, so its length is the right floor)
  stack.push_back({opts.seed_prefix, opts.seed_prefix.size()});
  auto t0 = std::chrono::steady_clock::now();
  while (!stack.empty() && st.runs < opts.max_runs) {
    if (opts.budget_s > 0) {
      double el = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0)
                      .count();
      if (el > opts.budget_s) break;
    }
    Item it = std::move(stack.back());
    stack.pop_back();
    RunResult r = S.run(it.prefix, opts.seed, opts.max_steps, drill);
    ++st.runs;
    if (r.injections > 0) ++st.injected_runs;
    st.pressure_events += r.pressure_events;
    if (seen.insert(trace_hash(r.choices)).second) ++st.unique_traces;
    if (r.failed) {
      ++st.findings;
      if (st.findings == 1) {
        st.first_failure = r;
        st.first_failure_prefix =
            minimize_prefix(drill, r.choices, opts.seed, opts.max_steps);
      }
      if (opts.stop_on_first) break;
      continue;  // do not expand a failing schedule further
    }
    // expand alternatives at branchable decision points: thread choices
    // below inj_from under the conflict rule, timeout injections at or
    // above it under the injection rule
    for (size_t i = it.expand_from; i < r.decisions.size(); ++i) {
      const Decision& d = r.decisions[i];
      if (!d.branchable && !d.inj_branch) continue;
      for (uint8_t alt = 0; alt < d.nen; ++alt) {
        if (alt == d.chosen) continue;
        if (alt < d.inj_from ? !d.branchable : !d.inj_branch) continue;
        std::vector<uint8_t> p(r.choices.begin(),
                               r.choices.begin() + long(i));
        p.push_back(alt);
        stack.push_back({std::move(p), i + 1});
      }
    }
  }
  return st;
}

}  // namespace det
}  // namespace accl
