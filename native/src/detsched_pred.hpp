// Predicate trampoline for the ACCL_DETSCHED wait paths.
//
// The deterministic scheduler's wait loops re-check caller predicates
// between virtual blocks, exactly like std::condition_variable's
// wait_for does in the plain build.  Those predicates are annotated
// ACCL_REQUIRES(<their mutex>) — correct at every invocation site,
// because both wait paths hold the caller's lock when they test the
// predicate — but a generic template cannot NAME the caller's mutex,
// so clang's thread-safety analysis would flag the invocation.  The
// plain build never sees this because libstdc++ invokes predicates
// from a system header, where diagnostics are suppressed; this header
// gives the det lane the identical boundary via the same mechanism.
// It contains exactly one function and nothing under accl:: data —
// the ACCL_NO_TSA waiver ban (scripts/tsa_check.py) is untouched.
#pragma once
#pragma GCC system_header

#include <utility>

namespace accl {
namespace det {

template <typename Pred>
inline bool invoke_pred(Pred&& p) {
  return std::forward<Pred>(p)();
}

}  // namespace det
}  // namespace accl
