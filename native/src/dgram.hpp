// Datagram transport rung: MTU fragmentation, out-of-order delivery, and
// interleaved per-session reassembly.
//
// Role of the reference's UDP protocol stack: the udp packetizer splits
// segments into MTU-sized datagrams and the depacketizer + rxbuf_session
// reassemble interleaved per-session fragments into rx-pool buffers
// (kernels/cclo/hls/eth_intf/udp_depacketizer.cpp:30-180,
// rxbuf_offload/rxbuf_session.cpp:1-202).  This rung is deliberately
// adversarial: the hub delivers each batch of queued datagrams in
// REVERSE order (deterministic worst-case reordering), so fragments of
// concurrent messages interleave and arrive out of order — the protocol
// layer above (seqn discipline, stream resequencing, reassembly table)
// must recover.  One-shot drop/duplicate faults model datagram loss.
#pragma once

#include <set>
#include <unordered_map>

#include "transport.hpp"

namespace accl {

// One MTU-sized fragment of a wire message.  Every fragment carries the
// original header (like the reference's STRIDE-offset rxbuf_session
// commands carrying the session id) plus reassembly coordinates.
struct Datagram {
  WireHeader hdr;
  uint32_t src_global = 0;  // sending endpoint (reassembly key half)
  uint32_t msg_id = 0;      // per-sender message counter (key other half)
  uint32_t frag_idx = 0, nfrags = 1;
  uint32_t frag_off = 0;       // byte offset of chunk within the payload
  uint32_t payload_bytes = 0;  // total message payload size (hdr.count is
                               // NOT usable: rendezvous INITs carry an
                               // element count with an empty payload)
  std::vector<uint8_t> chunk;
};

enum DgramFault : uint32_t {
  DGRAM_DROP_NEXT = 1,  // next datagram posted anywhere is lost
  DGRAM_DUP_NEXT = 2,   // next datagram posted is delivered twice
};

// Shared hub: per-destination queue + delivery worker.  Each worker
// drains up to `reorder_window` queued datagrams and delivers the batch
// in reverse order.
class DgramHub {
 public:
  using DgSink = std::function<void(Datagram&&)>;

  DgramHub(int nranks, uint32_t mtu, uint32_t reorder_window)
      : mtu_(mtu ? mtu : 256),
        window_(reorder_window ? reorder_window : 1),
        states_(nranks) {
    for (int r = 0; r < nranks; ++r)
      workers_.emplace_back([this, r] { worker(r); });
  }

  ~DgramHub() {
    running_ = false;
    for (auto& st : states_) st.cv.notify_all();
    for (auto& t : workers_) t.join();
  }

  uint32_t mtu() const { return mtu_; }

  void attach(int rank, DgSink sink) {
    MutexLock g(states_[rank].mu);
    states_[rank].sink = std::move(sink);
  }
  void detach(int rank) {
    // clear the sink AND wait out any in-flight delivery: a worker that
    // already copied the sink may be mid-call into the engine, and the
    // caller is about to destruct it (teardown use-after-free guard)
    auto& st = states_[rank];
    UniqueLock g(st.mu);
    st.sink = nullptr;
    st.cv.wait(g, [&]() ACCL_REQUIRES(st.mu) { return !st.delivering; });
  }

  void post(uint32_t dst, Datagram&& d) {
    if (dst >= states_.size()) return;
    switch (fault_.exchange(0)) {
      case DGRAM_DROP_NEXT:
        return;  // the fragment never reaches the wire
      case DGRAM_DUP_NEXT: {
        Datagram dup = d;
        enqueue(dst, std::move(dup));
        break;
      }
      default:
        break;
    }
    enqueue(dst, std::move(d));
  }

  // Arm a one-shot datagram-level fault (test harness; the engine-level
  // inject_fault drops whole messages — this drops single fragments).
  void inject_fault(uint32_t kind) { fault_.store(kind); }

 private:
  struct DstState {
    Mutex mu;
    CondVar cv;
    std::deque<Datagram> q ACCL_GUARDED_BY(mu);
    DgSink sink ACCL_GUARDED_BY(mu);
    // a worker holds a copy of sink right now
    bool delivering ACCL_GUARDED_BY(mu) = false;
  };

  void enqueue(uint32_t dst, Datagram&& d) {
    auto& st = states_[dst];
    {
      MutexLock g(st.mu);
      st.q.push_back(std::move(d));
    }
    st.cv.notify_one();
  }

  void worker(int rank) {
    auto& st = states_[rank];
    while (running_) {
      std::vector<Datagram> batch;
      DgSink sink;
      {
        UniqueLock g(st.mu);
        cv_wait_for_pred(st.cv, g, std::chrono::milliseconds(50),
                         [&]() ACCL_REQUIRES(st.mu) {
                           return !st.q.empty() || !running_;
                         });
        if (!running_ && st.q.empty()) return;
        for (uint32_t i = 0; i < window_ && !st.q.empty(); ++i) {
          batch.push_back(std::move(st.q.front()));
          st.q.pop_front();
        }
        sink = st.sink;
        if (sink) st.delivering = true;
      }
      if (!sink) continue;
      // worst-case deterministic reordering: deliver the batch reversed
      for (auto it = batch.rbegin(); it != batch.rend(); ++it)
        sink(std::move(*it));
      {
        MutexLock g(st.mu);
        st.delivering = false;
      }
      st.cv.notify_all();
    }
  }

  uint32_t mtu_, window_;
  std::vector<DstState> states_;
  std::vector<Thread> workers_;  // det-managed: dgram worlds are drillable
  std::atomic<bool> running_{true};
  std::atomic<uint32_t> fault_{0};
};

// Transport facade: fragments on egress, reassembles on ingress (the
// packetizer / depacketizer + rxbuf_session pair).  The reassembly table
// is bounded like the reference's session-buffer memory (rxbuf_session
// mem[512]); when full, the oldest incomplete session is evicted — that
// message is lost and the protocol layer's timeout/seqn machinery
// reports it (fault-injection tests exercise exactly this).
class DatagramTransport : public Transport {
 public:
  DatagramTransport(std::shared_ptr<DgramHub> hub, int rank,
                    uint32_t max_sessions = 64)
      : hub_(std::move(hub)), rank_(rank), max_sessions_(max_sessions) {}

  void send(uint32_t dst, Message&& msg) override {
    uint32_t mtu = hub_->mtu();
    uint64_t total = msg.payload.size();
    uint32_t nfrags = uint32_t(std::max<uint64_t>(1, (total + mtu - 1) / mtu));
    uint32_t id = next_msg_id_++;
    for (uint32_t f = 0; f < nfrags; ++f) {
      Datagram d;
      d.hdr = msg.hdr;
      d.src_global = uint32_t(rank_);
      d.msg_id = id;
      d.frag_idx = f;
      d.nfrags = nfrags;
      d.frag_off = f * mtu;
      d.payload_bytes = uint32_t(total);
      uint64_t len = std::min<uint64_t>(mtu, total - uint64_t(f) * mtu);
      d.chunk.assign(msg.payload.begin() + d.frag_off,
                     msg.payload.begin() + d.frag_off + len);
      hub_->post(dst, std::move(d));
    }
  }

  void start(Sink sink) override {
    sink_ = std::move(sink);
    hub_->attach(rank_, [this](Datagram&& d) { reassemble(std::move(d)); });
  }

  void stop() override { hub_->detach(rank_); }

 private:
  struct Slot {
    WireHeader hdr;
    uint32_t nfrags = 0, got = 0;
    uint64_t stamp = 0;  // insertion order for eviction
    std::vector<uint8_t> buf;
    std::vector<bool> seen;  // duplicate-fragment guard
  };

  void reassemble(Datagram&& d) {
    Message out;
    bool complete = false;
    {
      MutexLock g(mu_);
      uint64_t key = (uint64_t(d.src_global) << 32) | d.msg_id;
      // duplicate of an already-delivered message (e.g. a duplicated
      // single-fragment datagram): must not re-deliver — rendezvous
      // traffic has no seqn dedup above this layer
      auto& done = done_ids_[d.src_global];
      if (done.count(d.msg_id)) return;
      auto it = slots_.find(key);
      if (it == slots_.end()) {
        if (slots_.size() >= max_sessions_) evict_oldest_locked();
        Slot s;
        s.hdr = d.hdr;
        s.nfrags = d.nfrags;
        s.stamp = stamp_++;
        s.buf.resize(d.payload_bytes);
        s.seen.assign(d.nfrags, false);
        it = slots_.emplace(key, std::move(s)).first;
      }
      Slot& s = it->second;
      if (d.frag_idx < s.nfrags && !s.seen[d.frag_idx] &&
          d.frag_off + d.chunk.size() <= s.buf.size()) {
        // empty chunk (zero-payload message): data() may be null and
        // memcpy declares its args nonnull (UBSan)
        if (!d.chunk.empty())
          std::memcpy(s.buf.data() + d.frag_off, d.chunk.data(),
                      d.chunk.size());
        s.seen[d.frag_idx] = true;
        s.got++;
      }
      if (s.got == s.nfrags) {
        out.hdr = s.hdr;
        out.payload = std::move(s.buf);
        slots_.erase(it);
        complete = true;
        // remember the id so late duplicates are dropped; ids are
        // sequential per sender, so prune far-behind entries to bound
        // the window
        done.insert(d.msg_id);
        while (!done.empty() && d.msg_id - *done.begin() > 512)
          done.erase(done.begin());
      }
    }
    if (complete && sink_) sink_(std::move(out));
  }

  void evict_oldest_locked() ACCL_REQUIRES(mu_) {
    auto oldest = slots_.end();
    for (auto it = slots_.begin(); it != slots_.end(); ++it)
      if (oldest == slots_.end() || it->second.stamp < oldest->second.stamp)
        oldest = it;
    if (oldest != slots_.end()) slots_.erase(oldest);
  }

  std::shared_ptr<DgramHub> hub_;
  int rank_;
  uint32_t max_sessions_;
  std::atomic<uint32_t> next_msg_id_{1};
  Sink sink_;  // set once in start(), before hub delivery is attached
  Mutex mu_;
  std::unordered_map<uint64_t, Slot> slots_ ACCL_GUARDED_BY(mu_);
  // per-sender ids already delivered (duplicate suppression window)
  std::unordered_map<uint32_t, std::set<uint32_t>> done_ids_
      ACCL_GUARDED_BY(mu_);
  uint64_t stamp_ ACCL_GUARDED_BY(mu_) = 0;
};

}  // namespace accl
