// The collective engine: control plane + dataplane for one rank.
//
// This is the TPU build's equivalent of the reference's on-device control
// plane — the MicroBlaze firmware event loop that reads 15-word call
// descriptors, decomposes collectives into data movement + arithmetic,
// and re-queues rendezvous calls whose peers aren't ready (reference:
// kernels/cclo/fw/sw_apps/ccl_offload_control/src/ccl_offload_control.c:
// run_accl :2485, dispatch :2375-2459, retry queue :2460-2479).  The
// decomposition here is expressed directly over a transport + rx pool +
// arithmetic lanes rather than the reference's DMA-mover micro-ISA; the
// observable protocol (eager segmentation against rx buffers, sequence
// numbers, rendezvous address exchange, ring/tree schedules) matches.
#pragma once

#include "arith.hpp"
#include "common.hpp"
#include "rxpool.hpp"
#include "transport.hpp"

namespace accl {

struct CommTable {
  // Ownership discipline (the TSA annotations on Engine::comms_ lean
  // on this): size/local/rows are IMMUTABLE after publication —
  // set_comm builds the whole table before the cfg_mu_-guarded push,
  // so a published row may be read lock-free from any thread.  The
  // seq columns are owned by the ENGINE LOOP thread after publication;
  // the only cross-thread writers (reset_errors, ResetPeriph) run on a
  // quiesced world by the r10 recovery contract.
  uint32_t size = 0;
  uint32_t local = 0;
  struct Row {
    uint32_t ip = 0, port = 0, session = 0, max_seg = 0;
  };
  std::vector<Row> rows;
  // Device-side per-peer sequence numbers (reference keeps these in the
  // exchange-memory communicator, communicator.hpp:34-39).
  std::vector<uint32_t> inbound_seq, outbound_seq;
};

struct ArithCfgN {
  uint32_t ubits = 32, cbits = 32, ratio_log = 0;
  uint32_t compressor = 0, decompressor = 0;
  uint32_t arith_compressed = 0;
  std::vector<uint32_t> lanes;  // indexed by ReduceFunction
  // r17 block-scaled extension (append-only serialization in
  // arithconfig.py to_words: two trailing words after the lanes):
  // elements per fp32 scale on the int8 wire (0 = cast lane), and
  // whether egress quantization folds an EQuARX error-feedback
  // residual (per (comm, dst, source-address) site) into each pass.
  uint32_t block = 0;
  uint32_t error_feedback = 0;
};

// Rendezvous bookkeeping records (reference: firmware pending queues,
// rendezvous_get_addr :154-212 / _get_completion :280).
struct RndzvAddr {
  uint32_t comm, src, tag;
  uint64_t vaddr;
  uint64_t elems;
};
struct RndzvDone {
  uint32_t comm, src, tag;
  // the landing address of the write this completion reports: lets a
  // wait match exactly ITS OWN posted address, so concurrent calls with
  // the same (comm, src, tag) can't consume each other's completions
  // and retry-expiry teardown can't drain a healthy call's success
  uint64_t vaddr = 0;
};

struct CallResult {
  uint32_t retcode = 0;
  double duration_ns = 0.0;
  bool done = false;
};

class Engine {
 public:
  Engine(uint32_t global_rank, uint64_t devmem_bytes,
         std::unique_ptr<Transport> transport);
  ~Engine();

  // Two-phase teardown (r13, the suite-exit segfault fix): stop every
  // engine thread, close the queues, and finalize every still-pending
  // call with COMM_ABORTED|RANK_FAILED — WITHOUT freeing any storage.
  // After shutdown() a host-side waiter parked in a poll/wait loop
  // returns within one poll interval, so the world can be destroyed
  // with a hard guarantee that no thread is still inside the engine
  // (the crash class: accl_world_destroy racing a waiter thread's
  // poll_call).  Idempotent; the destructor runs it first.
  void shutdown();

  // ---- host-facing config (driver bring-up path) ----
  void cfg_rx_buffers(uint32_t nbufs, uint64_t bufsize);
  int set_comm(const uint32_t* words, int nwords);
  int set_arithcfg(const uint32_t* words, int nwords);

  // ---- device memory ----
  uint64_t alloc(uint64_t nbytes, uint64_t align);
  void free_addr(uint64_t addr);
  bool read_mem(uint64_t addr, void* dst, uint64_t n);
  bool write_mem(uint64_t addr, const void* src, uint64_t n);

  // ---- host-resident memory (the reference's host-only buffers /
  // external_dma path: the engine reaches into host memory when an
  // operand carries OP0/OP1/RES_HOST, ccl_offload_control.h:128-138).
  // Host addresses are tagged with HOST_ADDR_BIT and resolve into a
  // separate host region; the same engine primitives move data to and
  // from it transparently, like the reference's host-capable movers. ----
  static constexpr uint64_t HOST_ADDR_BIT = 1ull << 62;
  uint64_t alloc_host(uint64_t nbytes, uint64_t align);

  // ---- call path ----
  uint64_t start_call(const uint32_t* w15);
  bool poll_call(uint64_t id, uint32_t* retcode, double* duration_ns);

  // ---- persistent collective plans (r12): pre-marshaled descriptor
  // ring.  A plan is an ordered batch of 15-word descriptors parsed
  // ONCE at creation; a replay re-queues the whole batch through the
  // normal engine loop with fresh call ids — one host->engine entry
  // per replay instead of one per call (the ACCL+ pre-armed command
  // sequence, arxiv 2312.11742).  Each plan snapshots the epoch of
  // every communicator it touches: a replay after any abort/epoch
  // bump (or reset_errors, which invalidates every plan) fails fast
  // with -2 instead of silently running on a fenced world. ----
  // Returns the plan id (>= 0), or -1 on malformed input.
  int plan_create(const uint32_t* words, int ncalls);
  // Queue one replay; returns a completion token (> 0), -1 for an
  // unknown plan id, or -2 when the plan was invalidated/fenced.
  long long plan_replay(int plan_id);
  // Poll a replay token: 1 = all calls done (retcode = OR of every
  // call's bits, duration = sum), 0 = still in flight, -1 = unknown.
  int plan_poll(long long token, uint32_t* retcode, double* duration_ns);
  // Fence plans touching comm_id (-1 = every plan); called from
  // abort_comm/handle_abort/reset_errors and by the driver's
  // shrink/grow plan-fencing contract.  Fencing also frees the plan's
  // descriptor storage — an invalid plan can never replay again.
  void invalidate_plans(int comm_id);
  // Release one plan's storage (the driver plan object died/closed);
  // the slot stays (ids are vector indices) but holds nothing.
  void plan_release(int plan_id);
  // Live (still-valid) plan count — eviction introspection for tests.
  int plan_count() const;

  // ---- compute-kernel streams (PL-kernel equivalent) ----
  void push_krnl(const uint8_t* data, uint64_t n);
  bool pop_stream(uint32_t strm, uint8_t* dst, uint64_t cap, uint64_t* got,
                  int timeout_ms);

  std::string dump_rx() const { return rx_.dump(); }
  uint32_t rank() const { return global_rank_; }

  // Deterministic-schedule introspection (ACCL_DETSCHED drills): how
  // many transport deliveries are executing inside this engine right
  // now.  The shutdown-vs-traffic drill asserts it is zero after the
  // transport detached — the invariant the r13 InprocHub::detach drain
  // establishes (and the ACCL_FAULT_DETACH_RACE build breaks).
  int ingress_depth() const { return ingress_depth_.load(); }

  // ---- wire-protocol correctness surface (r13) ----
  // Feed one raw frame (64-byte WireHeader + payload) through the real
  // ingress classification path, exactly as if the transport delivered
  // it.  Returns 0 when the frame was consumed (including a legal drop
  // by the kill/epoch gates) and 1 when it was REJECTED as malformed —
  // truncated header, unknown MsgType, count/payload mismatch,
  // out-of-range comm id, oversized eager segment.  The same
  // validation runs on every transport-delivered frame; rejections
  // increment the counter either way.
  int ingest_bytes(const uint8_t* data, uint64_t nbytes);
  void frame_stats(uint64_t* accepted, uint64_t* rejected) const {
    if (accepted) *accepted = frames_accepted_.load();
    if (rejected) *rejected = frames_rejected_.load();
  }

  // ---- engine telemetry snapshot (r14): the versioned flat stats
  // export behind capi accl_engine_stats.  Fills up to `cap` u64
  // fields of the current layout (field order is the ABI — APPEND
  // ONLY; the Python twin is ENGINE_STATS_FIELDS_V<n> in
  // accl_tpu/observability/telemetry.py) and returns the total field
  // count this build knows, so an older caller reads a prefix and a
  // newer caller sees exactly how much the engine filled.  Cheap by
  // construction: atomics plus three short lock holds (egress depth,
  // plan table, rx staging) — pollable at 10 Hz without touching the
  // call hot path.  v2 (r15) appends link_rows: the number of
  // (comm, peer) link rows the link plane below is tracking. ----
  // v3 (r17) appends the quantized-wire accounting pair:
  // compressed_tx_bytes (wire bytes actually sent through a compressed
  // lane) and compressed_tx_logical_bytes (their uncompressed
  // equivalent — the difference is the "bytes saved" family).
  static constexpr int kEngineStatsVersion = 3;
  int engine_stats(uint64_t* out, int cap);

  // ---- per-link wire telemetry (r15): the flat (comm, peer) counter
  // plane behind capi accl_engine_link_stats.  One row of
  // kLinkStatsStride u64s per (comm, peer comm-local rank) this engine
  // has exchanged traffic with — tx/rx message+byte counters,
  // retransmits served to that peer, NACKs exchanged with it, frames
  // dropped at an epoch fence, and the seek count/blocked-wait time
  // attributed to it (the receiver's measure of how long that peer's
  // data kept it waiting).  Row field order is the ABI twin of
  // LINK_STATS_FIELDS_V2 in accl_tpu/observability/telemetry.py:
  //   0 comm, 1 peer, 2 tx_msgs, 3 tx_bytes, 4 rx_msgs, 5 rx_bytes,
  //   6 retrans_sent, 7 nacks_tx, 8 nacks_rx, 9 fenced_drops,
  //   10 seeks, 11 seek_wait_ns, 12 comp_tx_bytes (r17: wire bytes
  //   sent to this peer through a compressed lane)
  // Only WHOLE rows are ever written (a short buffer truncates at a
  // row boundary, never mid-row); the return value is the total u64
  // count this engine holds so a caller with a small buffer can retry.
  static constexpr int kLinkStatsStride = 13;
  int link_stats(uint64_t* out, int cap);

  // Egress frame tap: bounded ring of the last kTapCap frames this
  // engine staged (serialized header + payload) — the wire fuzzer's
  // seed-corpus capture (scripts/fuzz_wire.py records one real frame
  // of every MsgType through this before mutating).
  void set_frame_tap(bool on) { tap_on_.store(on); }
  int tap_count() const {
    MutexLock g(tap_mu_);
    return int(tap_frames_.size());
  }
  // Copy frame `idx` (oldest first) into out; returns the frame's full
  // size in bytes (even if > cap — caller retries with a bigger
  // buffer), or -1 for an out-of-range index.  NB index->frame identity
  // is only stable while nothing rotates the ring: concurrent readers
  // of a LIVE tap must use tap_drain, which is atomic per batch.
  int tap_read(int idx, uint8_t* out, int cap) const;
  // Atomically drain captured frames (oldest first) into out as
  // consecutive [u32 len][frame bytes] records under one lock hold;
  // returns bytes written.  Frames that don't fit stay for the next
  // drain; a single frame larger than the whole buffer is dropped
  // (it could never fit).
  int tap_drain(uint8_t* out, int cap);

  // ---- fault injection (test harness; SURVEY §5 failure detection) ----
  // Forces the chaos funnel's NEXT egress draw: 1=drop, 2=duplicate,
  // 3=corrupt sequence number, 4=delay.  One-shot sugar over the seeded
  // chaos plan below — both resolve in the same send_out switch, so the
  // detection/recovery machinery (seqn discipline, receive timeout,
  // NACK retransmission) is exercised identically either way.
  void inject_fault(uint32_t kind) { fault_.store(kind); }

  // ---- resilience: retransmission + abort/epoch + liveness + chaos ----
  // Eager retransmission config: on a seek miss the receiver NACKs the
  // sender and retries with exponential backoff + deterministic jitter,
  // up to retry_max NACK rounds inside the unchanged receive budget
  // (ACCL_RETRY_MAX / ACCL_RETRY_BASE_US on the driver side).
  // retry_max = 0 disables the whole lane (no store, no NACKs) and
  // restores the pure detect-and-classify behavior.
  void set_resilience(uint32_t retry_max, uint32_t retry_base_us) {
    retry_max_.store(retry_max);
    if (retry_base_us) retry_base_us_.store(retry_base_us);
  }
  void resilience_stats(uint64_t* retrans_sent, uint64_t* nacks_tx,
                        uint64_t* nacks_rx, uint64_t* fenced_drops) const {
    if (retrans_sent) *retrans_sent = retrans_sent_.load();
    if (nacks_tx) *nacks_tx = nacks_tx_.load();
    if (nacks_rx) *nacks_rx = nacks_rx_.load();
    if (fenced_drops) *fenced_drops = fenced_drops_.load();
  }

  // Epoch-tagged communicator abort: bump the epoch, mark the comm
  // aborted with `err_bits` (COMM_ABORTED is always OR'd in), finalize
  // every pending call on it fast, and — when propagate — send an Abort
  // control message to every peer so THEIR pending calls fail fast too.
  // Returns 0, or -1 for an unknown comm id.
  int abort_comm(uint32_t comm_id, uint32_t err_bits, bool propagate);

  // Seqn resync + transient-state drain after a CLASSIFIED fault: zero
  // both directions' sequence counters, drain the rx pool and the
  // retransmit store, clear armed one-shot faults and abort flags
  // (epochs stay bumped — old-epoch stragglers remain fenced).  A
  // collective recovery op: every rank of a quiesced world must call it.
  void reset_errors();

  // Chaos plan (seeded, probabilistic, dataplane-targeted): each eager
  // egress segment draws drop/dup/delay/corrupt with the given
  // per-million probabilities from a deterministic xorshift stream;
  // slow_us stalls this rank's egress writer per message (slow-rank).
  void set_chaos(uint64_t seed, uint32_t drop_ppm, uint32_t dup_ppm,
                 uint32_t delay_ppm, uint32_t delay_us,
                 uint32_t corrupt_ppm, uint32_t slow_us);

  // Kill this rank (chaos kill-rank): the engine goes silent — egress
  // drops everything, ingress hears nothing — and every local comm is
  // aborted with RANK_FAILED so the rank's own pending calls finalize
  // fast instead of burning their receive budget.
  void kill();
  bool is_killed() const { return killed_.load(); }

  // Liveness probe over one communicator: ping every peer with a
  // Heartbeat and collect proof-of-life (a pong, or any control-plane
  // traffic — NACK/abort ingress also stamps last-heard; the data hot
  // path deliberately does not) for up to window_us.  Returns a bitmap
  // of alive comm-local ranks (the local rank is always alive).
  uint64_t probe_liveness(uint32_t comm_id, uint32_t window_us);

  // ---- elastic membership (r11): the join control plane ----
  // Joiner side of the Join/Welcome/StateSync exchange: ask the sponsor
  // session for its world state and apply it — adopt every comm's
  // epoch + abort fence (so dead-epoch traffic can never land here and
  // a replayed abort stays fenced) and pad the comm table with
  // placeholder slots so this engine's comm-id space aligns with the
  // survivors' before the grown communicator is uploaded.  Returns 0,
  // or -1 when the sponsor never answered inside timeout_ms (a dead or
  // killed sponsor is deaf — pick another and retry).
  int join_sync(uint32_t sponsor_session, int timeout_ms);
  // Introspection for the driver/tests: comm slots this engine knows
  // (real + placeholder) and a comm's current epoch.
  uint32_t comm_count() const;
  uint32_t comm_epoch(uint32_t comm) const { return epoch_of(comm); }
  // membership counters: joins answered as sponsor / completed as joiner
  void join_stats(uint64_t* sponsored, uint64_t* joined) const {
    if (sponsored) *sponsored = joins_sponsored_.load();
    if (joined) *joined = joins_completed_.load();
  }

  // Lossy-transport mode (set by datagram worlds): a seek timeout with
  // the expected seqn absent but later seqns queued is treated as an
  // unrecoverable loss hole and the route cursor resyncs.  On reliable
  // FIFO rungs the same signature means corruption and stays a hard
  // PACK_SEQ error (fault-injection contract).
  void set_lossy_transport(bool on) { lossy_transport_ = on; }

  // ---- explicit session lifecycle (reference open_port/open_con/
  // close_con, accl.hpp:1069-1083, backed by the tcp_session_handler
  // plugin).  Connection state lives in the transport; these surface
  // bring-up/teardown per communicator with a distinct error (the
  // index of the first peer whose session failed), so a dead peer is a
  // decodable setup failure instead of a mid-collective hang. ----
  // open_port: is the inbound endpoint live?  0 ok, -1 not listening.
  int open_port() const { return transport_ && transport_->listening() ? 0 : -1; }
  // open_con / close_con over every peer of a communicator.
  // Returns 0 on success, or (1 + peer_local_rank) of the first failure.
  int open_con(uint32_t comm_id);
  int close_con(uint32_t comm_id);

  // ---- peer-to-peer buffer windows (FPGABufferP2P analog,
  // driver/xrt/include/accl/fpgabufferp2p.hpp: a device buffer directly
  // addressable by peers without staging).  A registered span lets an
  // in-process peer engine land its rendezvous one-sided write by
  // DIRECT memcpy into this engine's devicemem — the wire is bypassed
  // entirely (the PCIe-p2p DMA of the reference).  Worlds with shared
  // address space install the peer hook; wire-only worlds leave it
  // unset and p2p buffers degrade gracefully to normal buffers. ----
  void register_p2p(uint64_t addr, uint64_t bytes);
  void unregister_p2p(uint64_t addr);
  bool p2p_covers(uint64_t addr, uint64_t bytes) const;
  void set_peer_hook(std::function<Engine*(uint32_t session)> hook) {
    peer_hook_ = std::move(hook);
  }
  // Raw pointer into devicemem for zero-copy host mapping (the
  // reference's bo.map<dtype*>() on a p2p BO).  nullptr when OOB.
  uint8_t* raw_mem(uint64_t addr, uint64_t bytes);
  // Receiver side of a direct p2p landing: same consume-write-complete
  // discipline as the wire ingress (shared land_one_sided below).
  void land_p2p(const WireHeader& hdr, const uint8_t* payload,
                uint64_t payload_bytes);
  // Egress traffic counters (message count / payload bytes actually
  // handed to the transport) — lets tests PROVE the p2p path moved no
  // payload over the wire.
  void tx_stats(uint64_t* msgs, uint64_t* payload_bytes) const {
    if (msgs) *msgs = tx_msgs_.load();
    if (payload_bytes) *payload_bytes = tx_payload_bytes_.load();
  }

 private:
  // engine loop
  void loop();
  uint32_t execute(CallDesc& c);
  struct Progress;
  void dispatch(CallDesc& c, Progress& p);

  // transport ingress demux (the depacketizer role, eth_intf routing):
  // frame validation + rejection counting in ingress(), the per-type
  // routing in classify() — ingest_bytes shares both.
  void ingress(Message&& msg);
  void classify(Message&& msg);
  // Structural validation of one frame BEFORE any routing touches it:
  // a malformed frame must be counted and dropped, never interpreted.
  // Takes the payload (not just its size): block-scaled segments
  // (hdr.compressed == 2, r17) carry a self-describing framing header
  // whose scale-row/count consistency is validated here.  Non-const:
  // the stream-route pressure checks read the resequencer maps under
  // their mutex so rejection happens BEFORE any per-route state is
  // minted from attacker-controlled header fields.
  bool frame_ok(const WireHeader& hdr, const std::vector<uint8_t>& payload);
  //: bounds on state minted from inbound stream headers (comm, src and
  //: strm are attacker-controlled): max distinct inbound stream routes,
  //: and max total parked out-of-order payloads across ALL routes
  static constexpr size_t kMaxStrmRoutes = 256;
  static constexpr size_t kMaxStrmHoldbackTotal = 1024;
  std::atomic<uint64_t> frames_accepted_{0}, frames_rejected_{0};
  std::atomic<int> ingress_depth_{0};
  std::atomic<bool> tap_on_{false};
  static constexpr size_t kTapCap = 256;
  mutable Mutex tap_mu_;
  std::deque<std::vector<uint8_t>> tap_frames_ ACCL_GUARDED_BY(tap_mu_);

  // ---- primitives (firmware primitive layer, fw :533-791) ----
  struct Progress {
    CallDesc& call;
    uint32_t cursor = 0;
    explicit Progress(CallDesc& c) : call(c) {}
    bool pending() const { return cursor >= call.current_step; }
    void done() {
      ++cursor;
      if (cursor > call.current_step) call.current_step = cursor;
    }
  };

  const CommTable& comm_for(const CallDesc& c) const;
  const ArithCfgN& arith_for(const CallDesc& c) const;
  uint64_t elem_bytes(const CallDesc& c) const;
  std::chrono::nanoseconds timeout_budget() const;

  // Per-call compression domains, decoded from the descriptor's
  // compression flags + arithmetic config (the per-operand flag algebra
  // of the reference, constants.hpp:320-325; per-step shifting
  // ccl_offload_control.c:1408-1411, :1929-1955).  Every primitive below
  // is element-based so each operand can carry its own representation.
  struct Dom {
    uint32_t ub = 4, cb = 4, ratio_log = 0;
    uint32_t comp_kind = 0;       // compressor id (arithconfig.py)
    bool pair = false;            // a real compressed representation exists
    bool op0 = false, op1 = false, res = false, eth = false;
    // r17 int8 block-scaled wire lane: block != 0 selects the
    // self-describing segment format (arith.hpp i8_* helpers) whose
    // byte size is NOT linear per element — every wire-size site must
    // go through wbytes()/welems(), never eb(), for the wire domain.
    // Per-operand compressed residence is meaningless for a scaled
    // segment (the scales don't fit a flat int8 buffer), so dom()
    // forces op0/op1/res off when blk is set.
    uint32_t blk = 0;
    bool ef = false;              // error-feedback egress quantization
    uint64_t eb(bool compressed) const { return compressed ? cb : ub; }
    // wire/operand byte size of `elems` elements in a representation
    uint64_t wbytes(uint64_t elems, bool compressed) const {
      return (compressed && blk) ? i8_wire_bytes(elems, blk)
                                 : elems * eb(compressed);
    }
    // elements per segment against a wire-byte budget
    uint64_t seg_elems(uint64_t wire_cap, bool compressed) const {
      if (compressed && blk) return i8_seg_elems(wire_cap, blk);
      return std::max<uint64_t>(1, wire_cap / eb(compressed));
    }
  };
  Dom dom(const CallDesc& c) const;

  // Egress quantization for the block-scaled lane: plain unless
  // `use_ef` (the arithcfg arms error feedback AND the send carries a
  // REDUCTION stream — relays/gathers/bcasts must quantize cleanly,
  // folding a residual into non-reduced data would corrupt it), in
  // which case the per-site residual (key = (comm, dst, source
  // address)) is folded in and refreshed — a training loop's repeated
  // collective re-quantizes the same sites every iteration, so the
  // error of pass k rides into pass k+1, EQuARX-style.
  void quantize_egress(const Dom& d, bool use_ef, uint32_t comm,
                       uint32_t dst, uint64_t src_addr, const float* in,
                       uint8_t* out, uint64_t elems);

  // Convert `elems` elements between representations (identity when the
  // domains match); returns sticky error bits on unknown compressor.
  uint32_t convert_elems(const Dom& d, const uint8_t* in, bool in_c,
                         uint8_t* out, bool out_c, uint64_t elems);
  // acc/op1/res each in their own domain; arithmetic runs in the domain
  // selected by the arithcfg's arith_is_compressed (mixed-precision
  // accumulate, reference arithconfig.hpp:106-119 {f32,f16} pair).
  uint32_t reduce_mixed(const CallDesc& c, const uint8_t* a0, bool a0c,
                        const uint8_t* a1, bool a1c, uint8_t* r, bool rc,
                        uint64_t elems);

  // Eager segmented send of `elems` elements from devicemem `addr` (or
  // the kernel stream when from_stream).  comp bits: OP0_COMPRESSED =
  // memory at addr holds the compressed representation; ETH_COMPRESSED =
  // compress payloads on the wire (fw send :575-651).
  // `reduce_stream`: this send carries a reduction partial/operand (a
  // ring reduce-scatter or reduce-chain hop) — the only sends the
  // error-feedback residual may legally fold into.
  void send_eager(CallDesc& c, uint32_t dst, uint32_t tag, uint64_t addr,
                  uint64_t elems, bool from_stream, uint32_t to_strm,
                  uint32_t comp, bool reduce_stream = false);
  // Eager segmented receive of `elems` elements into devicemem `addr`;
  // mode selects plain copy, reduce-accumulate into dst (fused
  // recv-reduce), or routing to a kernel stream.  comp bits:
  // RES_COMPRESSED = the landing buffer (or accumulator) holds the
  // compressed representation; ETH_COMPRESSED = segmentation follows the
  // compressed wire width (fw recv :655-712, fused_recv_reduce :718).
  enum class RecvMode { COPY, REDUCE, STREAM };
  void recv_eager(CallDesc& c, uint32_t src, uint32_t tag, uint64_t addr,
                  uint64_t elems, RecvMode mode, uint32_t strm, uint32_t comp);

  // Rendezvous primitives (fw :142-350, rdma_sq_handler.cpp:53-130),
  // element-based: the receiver advertises its landing representation and
  // the sender converts, so compressed operands ride rendezvous too.
  void rndzv_post_addr(CallDesc& c, Progress& p, uint32_t src, uint32_t tag,
                       uint64_t addr, uint64_t elems, bool dst_c);
  void rndzv_wait_done(CallDesc& c, Progress& p, uint32_t src, uint32_t tag);
  void rndzv_recv(CallDesc& c, Progress& p, uint32_t src, uint32_t tag,
                  uint64_t addr, uint64_t elems, bool dst_c);
  void rndzv_send(CallDesc& c, Progress& p, uint32_t dst, uint32_t tag,
                  uint64_t addr, uint64_t elems, bool src_c);

  bool use_rendezvous(const CallDesc& c, uint64_t elems);

  // Materialize a kernel-stream operand (OP0_STREAM) into device memory
  // so reduction schedules can treat it like a buffer operand.
  bool drain_krnl_to(uint64_t addr, uint64_t bytes);
  // Push a device-memory range into a local compute stream (RES_STREAM).
  void push_local_stream(uint32_t strm, uint64_t addr, uint64_t bytes);
  // Get-or-create the FIFO backing compute stream `strm`.
  std::shared_ptr<Fifo<std::vector<uint8_t>>> stream_for(uint32_t strm);

  // local ops — byte-based raw copy plus domain-aware element movers
  // (the dma_mover's compressor/decompressor lane routing, SURVEY §2.4)
  uint32_t local_copy(uint64_t src, uint64_t dst, uint64_t bytes);
  uint32_t local_move(const CallDesc& c, uint64_t src, uint64_t dst,
                      uint64_t elems, bool src_c, bool dst_c);
  uint32_t local_reduce(uint32_t lane, uint64_t a, uint64_t b, uint64_t dst,
                        uint64_t bytes);

  // ---- collective schedules (fw :793-2218) ----
  void coll_send(CallDesc& c, Progress& p);
  void coll_recv(CallDesc& c, Progress& p);
  void coll_bcast(CallDesc& c, Progress& p);
  void coll_scatter(CallDesc& c, Progress& p);
  void coll_gather(CallDesc& c, Progress& p);
  void coll_allgather(CallDesc& c, Progress& p);
  void coll_reduce(CallDesc& c, Progress& p);
  void coll_reduce_scatter(CallDesc& c, Progress& p);
  void coll_allreduce(CallDesc& c, Progress& p);
  void coll_alltoall(CallDesc& c, Progress& p);
  void coll_barrier(CallDesc& c, Progress& p);
  void do_config(CallDesc& c);

  // binomial tree schedules for the rendezvous protocol (fw tree bcast
  // :816-869, tree reduce :1603-1728); resume-safe via Progress.  Domain
  // bits: src_c/dst_c/acc_c describe the representation of the caller's
  // buffers (relays re-derive per the RES->OP0 algebra, fw :1408-1411).
  void tree_bcast(CallDesc& c, Progress& p, uint32_t root, uint64_t src_addr,
                  uint64_t dst_addr, uint64_t elems, bool src_c, bool dst_c);
  void tree_reduce(CallDesc& c, Progress& p, uint32_t root, uint64_t src_addr,
                   uint64_t acc_addr, uint64_t tmp_addr, uint64_t elems,
                   bool src_c, bool acc_c);
  // a local op as one resumable step (local side effects must not replay
  // when a rendezvous retry re-enters the schedule)
  template <typename F>
  void step_local(Progress& p, F&& f) {
    if (p.pending()) f();
    p.done();
  }

  // ring schedule cores shared by reduce_scatter/allreduce (fw :1782-2071);
  // off/len are in elements
  void ring_reduce_scatter(CallDesc& c, uint64_t src_base,
                           const std::vector<uint64_t>& off,
                           const std::vector<uint64_t>& len, uint64_t own_dst);
  void ring_allgather(CallDesc& c, uint64_t base,
                      const std::vector<uint64_t>& off,
                      const std::vector<uint64_t>& len);

  // Resolve an engine address to backing storage.  REQUIRES(mem_mu_):
  // every caller stages its copy/convert/reduce under the lock, so the
  // TSA lane proves no primitive ever touches devicemem/hostmem bytes
  // without it.
  uint8_t* mem(uint64_t addr, uint64_t n) ACCL_REQUIRES(mem_mu_);

  // ---- state ----
  uint32_t global_rank_;
  std::vector<uint8_t> devicemem_ ACCL_GUARDED_BY(mem_mu_);
  std::vector<uint8_t> hostmem_ ACCL_GUARDED_BY(mem_mu_);  // lazily committed
  uint64_t host_region_bytes_ = 0;  // immutable after the constructor
  // addr -> size maps for both address spaces
  std::map<uint64_t, uint64_t> free_spans_ ACCL_GUARDED_BY(mem_mu_);
  std::map<uint64_t, uint64_t> host_spans_ ACCL_GUARDED_BY(mem_mu_);
  std::map<uint64_t, uint64_t> alloc_sizes_ ACCL_GUARDED_BY(mem_mu_);
  // LOCK ORDER: mem_mu_ may be taken while holding posted_mu_ (the
  // rendezvous landing path holds posted_mu_ across its payload copy,
  // engine.cpp RndzvsMsg) — NEVER take posted_mu_ while holding mem_mu_.
  // The ACQUIRED_AFTER edge makes the TSA lane enforce this statically.
  Mutex mem_mu_ ACCL_ACQUIRED_AFTER(posted_mu_);

  // Landing-pad registry for one-sided writes: rndzv_post_addr records
  // the conversion the depacketizer must apply when the peer's write
  // lands (wire representation -> landing representation), keyed by
  // (comm, src, tag, vaddr) so a stale entry from a failed transfer
  // cannot be consumed by a later collective that reuses the address.
  // Receiver-local state — the sender's header is never trusted for
  // domain decisions, matching the eager path's own-flag-algebra
  // discipline.
  struct PostedRndzv {
    uint64_t elems;
    bool wire_c, lnd_c;
    uint32_t comp_kind;
    uint32_t ub, cb;   // bytes/element in each representation
    uint32_t blk = 0;  // block-scaled wire geometry (0 = cast lane)
  };
  using PostedKey = std::tuple<uint32_t, uint32_t, uint32_t, uint64_t>;
  std::map<PostedKey, PostedRndzv> posted_ ACCL_GUARDED_BY(posted_mu_);
  // Shared landing logic for one-sided writes: wire ingress (RndzvsMsg)
  // and the direct p2p path both run exactly this (consume posted
  // record under posted_mu_, convert/copy under mem_mu_, surface the
  // completion) so the two paths cannot diverge.
  void land_one_sided(const WireHeader& hdr, const uint8_t* payload,
                      uint64_t payload_bytes);

  // p2p window registry + peer resolution (see public section)
  mutable Mutex p2p_mu_;
  std::map<uint64_t, uint64_t> p2p_spans_ ACCL_GUARDED_BY(p2p_mu_);
  // set once at world wiring, before traffic (no guard needed)
  std::function<Engine*(uint32_t session)> peer_hook_;
  std::atomic<uint64_t> tx_msgs_{0}, tx_payload_bytes_{0};
  // r17 quantized-wire accounting: bytes that left through a
  // compressed lane (any pair — f16/bf16 cast or int8 block-scaled)
  // and their uncompressed equivalent; saved = logical - compressed.
  std::atomic<uint64_t> compressed_tx_bytes_{0};
  std::atomic<uint64_t> compressed_tx_logical_bytes_{0};

  // ---- error-feedback residuals (r17, EQuARX arxiv 2506.17615) ----
  // One fp32 residual vector per quantization site (comm, dst,
  // source address), written by quantize_egress when the arithcfg's
  // error_feedback word is set.  Leaf lock taken under mem_mu_ (the
  // egress conversion sites hold mem_mu_); total floats are bounded —
  // sites past the cap quantize without feedback rather than grow.
  static constexpr uint64_t kEfResidualCapFloats = 8ull << 20;  // 32 MiB
  using EfKey = std::tuple<uint32_t, uint32_t, uint64_t>;
  std::map<EfKey, std::vector<float>> ef_residual_ ACCL_GUARDED_BY(ef_mu_);
  uint64_t ef_floats_ ACCL_GUARDED_BY(ef_mu_) = 0;
  Mutex ef_mu_ ACCL_ACQUIRED_AFTER(mem_mu_);
  void drop_ef_residuals(int comm_id);  // -1 = all (reset_errors)
  // LOCK ORDER: posted_mu_ comes BEFORE mem_mu_ (see mem_mu_ above);
  // acquiring posted_mu_ under mem_mu_ would invert the order = deadlock.
  Mutex posted_mu_;

  std::unique_ptr<Transport> transport_;
  //: pending one-shot egress fault (0 = none); see inject_fault()
  std::atomic<uint32_t> fault_{0};
  //: egress funnel applying any injected fault before the transport
  void send_out(uint32_t session, Message&& msg);

  // ---- retransmission lane (resilience layer 1) ----
  // Bounded ring of sent eager segments keyed by (comm, dst comm-local
  // rank, tag, seqn); the clean copy is captured BEFORE the chaos
  // funnel, modeling a real sender whose source data survives a wire
  // fault.  A NACK for (comm, tag, seqn) resends every stored segment
  // on the route from that seqn on (one round recovers a multi-segment
  // hole).  Retransmits bypass the chaos funnel — the recovery path
  // stays deterministic under seeded chaos.
  // Hot-path discipline: the no-fault cost per segment is ONE payload
  // copy into a RECYCLED slot (vector::assign reuses capacity — zero
  // steady-state allocation) under an uncontended mutex; there is no
  // index structure to churn.  The NACK handler pays a linear ring
  // scan instead — it only runs on the fault path.
  struct RetransSlot {
    bool used = false;
    uint32_t comm = 0, dst = 0;
    Message msg;
  };
  static constexpr size_t kRetransCap = 1024;
  std::vector<RetransSlot> retrans_ring_ ACCL_GUARDED_BY(retrans_mu_);
  size_t retrans_pos_ ACCL_GUARDED_BY(retrans_mu_) = 0;
  Mutex retrans_mu_;
  std::atomic<uint32_t> retry_max_{4};
  std::atomic<uint32_t> retry_base_us_{200};
  std::atomic<uint64_t> retrans_sent_{0}, nacks_tx_{0}, nacks_rx_{0};
  std::atomic<uint64_t> fenced_drops_{0};
  // telemetry shadows (engine_stats): live slot count and the number
  // of times a still-used slot was overwritten by ring wrap (store
  // pressure — a NACK after an eviction can no longer be served).
  // Written under retrans_mu_, read lock-free by the sampler.
  std::atomic<uint64_t> retrans_used_{0}, retrans_evictions_{0};
  bool retrans_enabled() const {
    return retry_max_.load() > 0 && !lossy_transport_.load();
  }
  void store_retrans(uint32_t comm, uint32_t dst, const Message& msg);
  void send_nack(uint32_t comm, uint32_t src, uint32_t tag, uint32_t seqn);
  void handle_nack(const WireHeader& hdr);
  // Seek with recovery: slices the receive budget so an abort wakes a
  // blocked receiver promptly, and (retransmission on) NACKs the sender
  // with exponential backoff + deterministic jitter on each miss.
  // `evicted_out` counts suspicious same-route entries evicted during
  // recovery (they classify a final failure as PACK_SEQ, like the
  // entries themselves would have).
  // `staged_out` (when non-null) may receive a message rescued straight
  // from the rx pool's staging queue; the returned notification then
  // carries index == UINT32_MAX and the payload rides *staged_out.
  std::optional<RxNotification> seek_recover(CallDesc& c, uint32_t src,
                                             uint32_t tag, int* evicted_out,
                                             Message* staged_out = nullptr);
  // telemetry: recovered-seek entries vs final misses (timeout /
  // lossy-hole classification — NOT abort/shutdown wakes, which are
  // fencing, not matching failures).  miss/seek is the seek-miss rate.
  std::atomic<uint64_t> seeks_{0}, seek_misses_{0};
  // sub-comm wedge observables: timeouts classified while the expected
  // segment sat in staging (the cross-comm pool-pinning failure — must
  // stay 0 on a healthy engine) and staged-rescue consumptions (the fix
  // firing).  Counted in BOTH normal and ACCL_FAULT_SUBCOMM_WEDGE
  // builds so the detsched drill invariant reads the same signal.
  std::atomic<uint64_t> wedged_timeouts_{0}, staged_takes_{0};

 public:
  uint64_t wedged_timeouts() const { return wedged_timeouts_.load(); }
  uint64_t staged_takes() const { return staged_takes_.load(); }
  uint64_t egress_overflows() const { return egress_overflows_.load(); }

 private:

  // ---- abort + epoch fencing (resilience layer 2) ----
  static constexpr uint32_t kMaxComms = 64;  // comms_.reserve(64) twin
  std::array<std::atomic<uint32_t>, kMaxComms> comm_epoch_{};
  std::array<std::atomic<uint32_t>, kMaxComms> comm_abort_{};
  uint32_t epoch_of(uint32_t comm) const {
    return comm < kMaxComms ? comm_epoch_[comm].load() : 0;
  }
  uint32_t abort_err(uint32_t comm) const {
    return comm < kMaxComms ? comm_abort_[comm].load() : 0;
  }
  // rendezvous/scratch teardown shared by retry expiry and abort
  void teardown_call(CallDesc& c);
  void handle_abort(const WireHeader& hdr);

  // ---- per-link wire telemetry (r15): (comm, peer) counter rows ----
  // A leaf mutex (taken around a map bump, never while holding it is
  // any other lock acquired): the per-message cost on the egress path
  // is one uncontended lock + map find, the same discipline as the
  // retransmit store.  Peers are COMM-LOCAL ranks — the link matrix
  // aggregator on the Python side maps them through the communicator.
  struct LinkCounters {
    uint64_t tx_msgs = 0, tx_bytes = 0, rx_msgs = 0, rx_bytes = 0;
    uint64_t retrans_sent = 0, nacks_tx = 0, nacks_rx = 0;
    uint64_t fenced_drops = 0, seeks = 0, seek_wait_ns = 0;
    uint64_t comp_tx_bytes = 0;  // r17: compressed wire bytes to peer
  };
  mutable Mutex link_mu_;
  std::map<std::pair<uint32_t, uint32_t>, LinkCounters> links_
      ACCL_GUARDED_BY(link_mu_);
  // Row-mint guard: the rx-side bump sites key rows off WIRE-HEADER
  // fields (hdr.comm_id is bounded by frame_ok, hdr.src is NOT) — a
  // fuzzed/hostile src must not mint unbounded map entries, so every
  // bump validates the peer against the comm table first.  The tx
  // sites pass table-derived values and the check is a cheap true.
  bool link_peer_ok(uint32_t comm, uint32_t peer) const {
    const CommTable* t = comm_ptr(comm);
    return t && peer < t->rows.size();
  }
  // one-counter bump via pointer-to-member (the common case)
  void link_count(uint32_t comm, uint32_t peer,
                  uint64_t LinkCounters::*field, uint64_t add = 1);
  // paired msg+byte bumps for the tx / rx funnels
  void link_tx(uint32_t comm, uint32_t peer, uint64_t bytes);
  void link_rx(uint32_t comm, uint32_t peer, uint64_t bytes);

  // ---- liveness (resilience layer 3) ----
  mutable Mutex live_mu_;
  std::map<std::pair<uint32_t, uint32_t>, uint64_t> last_heard_ns_
      ACCL_GUARDED_BY(live_mu_);
  void note_alive(uint32_t comm, uint32_t src);

  // ---- elastic membership (r11): join control plane ----
  Fifo<std::vector<uint32_t>> join_state_;  // StateSync payloads (joiner)
  std::atomic<uint64_t> joins_sponsored_{0}, joins_completed_{0};
  void handle_join(const WireHeader& hdr);            // sponsor side
  void apply_state_sync(const std::vector<uint32_t>& words);  // joiner

  // ---- seeded chaos (generalized injector) ----
  struct Chaos {
    bool armed = false;
    uint32_t drop_ppm = 0, dup_ppm = 0, delay_ppm = 0, delay_us = 0;
    uint32_t corrupt_ppm = 0;
    uint64_t rng = 0x9E3779B97F4A7C15ull;
  };
  Chaos chaos_ ACCL_GUARDED_BY(chaos_mu_);
  Mutex chaos_mu_;
  std::atomic<uint32_t> slow_us_{0};
  std::atomic<bool> killed_{false};
  uint32_t chaos_draw();  // fault kind for this message (0 = none)
  // delayed-egress releaser (chaos delay = real reordering, not a stall)
  struct Delayed {
    std::chrono::steady_clock::time_point release;
    uint32_t session;
    Message msg;
  };
  std::deque<Delayed> delayed_ ACCL_GUARDED_BY(delay_mu_);
  Mutex delay_mu_;
  CondVar delay_cv_;
  bool delay_running_ ACCL_GUARDED_BY(delay_mu_) = true;
  Thread delay_thread_;
  void delay_loop();

  // ---- egress pipeline: bounded outstanding-segment window ----
  // The engine loop stages each prepared segment here and immediately
  // starts preparing the next (memory read + conversion of segment k+1
  // overlaps wire transmission of segment k); a dedicated writer thread
  // drains to the transport in FIFO order.  Staging blocks once
  // `pipeline_depth_` segments are outstanding — the reference firmware's
  // 2-3-deep eager software-pipelining discipline (its send keeps
  // expected_ack_count <= 3 moves in flight and end_move()s beyond that,
  // ccl_offload_control.c:628-649, :1981-1986).
  void egress_loop();
  void stage_egress(uint32_t session, Message&& msg);
  std::deque<std::pair<uint32_t, Message>> egress_q_ ACCL_GUARDED_BY(egress_mu_);
  Mutex egress_mu_;
  CondVar egress_cv_;
  // telemetry: egress staging high-water (depth is read live under
  // egress_mu_ by engine_stats); written at stage time under the lock
  std::atomic<uint64_t> egress_hwm_{0};
  // backpressure-cycle escape valve: stagings that overflowed the
  // pipeline window after a full receive budget with no slot (see
  // stage_egress — ingress-context senders can cycle through each
  // other's windows; a counted overflow beats a distributed deadlock)
  std::atomic<uint64_t> egress_overflows_{0};
  std::atomic<uint32_t> pipeline_depth_{3};
  bool egress_running_ ACCL_GUARDED_BY(egress_mu_) = true;
  Thread egress_thread_;
  RxPool rx_;
  Fifo<RndzvAddr> pending_addrs_;
  Fifo<RndzvDone> completions_;
  std::map<uint32_t, std::shared_ptr<Fifo<std::vector<uint8_t>>>> streams_
      ACCL_GUARDED_BY(streams_mu_);
  Mutex streams_mu_;

  // Stream-destined messages bypass the rx pool, so they carry their own
  // per-(comm, peer, stream) sequence space and ingress resequences them
  // before pushing to the stream FIFO — FIFO transports never exercise
  // this, but the datagram rung delivers out of order (closes the
  // engine.cpp seqn exemption noted in round 2's review).
  using StrmKey = std::tuple<uint32_t, uint32_t, uint32_t>;  // comm,peer,strm
  //: max out-of-order stream messages parked per route before a lossy
  //: rung declares the gap a loss hole and resyncs (bounds holdback)
  static constexpr size_t kStrmHoldbackLimit = 64;
  std::map<StrmKey, uint32_t> strm_out_seq_;  // engine loop thread only
  std::map<StrmKey, uint32_t> strm_in_seq_ ACCL_GUARDED_BY(strm_seq_mu_);
  std::map<std::pair<StrmKey, uint32_t>, std::vector<uint8_t>> strm_holdback_
      ACCL_GUARDED_BY(strm_seq_mu_);
  Mutex strm_seq_mu_;
  Fifo<std::vector<uint8_t>> krnl_in_;

  // Communicator/arithcfg tables as stable heap pointers: cfg_mu_
  // guards the pointer VECTORS (growth by set_comm / join padding);
  // the pointees are never moved, so the engine loop fetches a row
  // pointer once under the lock and then uses it lock-free for the
  // whole call under CommTable's per-field ownership discipline.
  // (Before r14 these were value vectors whose safety hung on a
  // reserve(64) never-reallocate convention the analysis could not
  // see; the pointer indirection makes the guarded structure explicit
  // AND lifts the hard 64-comm growth ceiling.)
  std::vector<std::unique_ptr<CommTable>> comms_ ACCL_GUARDED_BY(cfg_mu_);
  std::vector<std::unique_ptr<ArithCfgN>> arithcfgs_ ACCL_GUARDED_BY(cfg_mu_);
  mutable Mutex cfg_mu_;
  // stable-pointer fetch (nullptr when out of range); see comms_ above
  CommTable* comm_ptr(uint32_t id) const;
  ArithCfgN* arith_ptr(uint32_t id) const;

  std::atomic<bool> lossy_transport_{false};
  uint64_t timeout_ = 1'000'000;  // in emulated cycles; 1 cycle = 1us here
  uint64_t max_eager_ = 32 * 1024;
  uint64_t max_rndzv_ = 32 * 1024;
  bool pkt_enabled_ = false;

 public:
  // Runtime tuning registers (the reference's exchange-memory flat-tree
  // thresholds, ccl_offload_control.h:86-90, written by the driver at
  // bring-up accl.cpp:1214-1224).
  enum TuningKey : uint32_t {
    BCAST_FLAT_TREE_MAX_RANKS = 0,
    REDUCE_FLAT_TREE_MAX_RANKS = 1,
    GATHER_FLAT_TREE_MAX_FANIN = 2,
    //: outstanding eager segments per engine (1 = strictly serial; the
    //: reference pipelines 2-3 moves, fw :628-649)
    EGRESS_PIPELINE_DEPTH = 3,
    //: byte thresholds for the count-based schedule selection (the
    //: reference's *_MAX_COUNT exchange-memory registers,
    //: ccl_offload_control.h:86-90): gather caps its flat-tree fan-in
    //: above this size (fw :1163); reduce prefers the flat tree at or
    //: below it regardless of rank count (fw :1533)
    GATHER_FLAT_TREE_MAX_COUNT = 4,
    REDUCE_FLAT_TREE_MAX_COUNT = 5,
  };
  // returns 0 on success, -1 for an unknown key (the clear-error
  // contract: the Python twin raises an ACCLError naming the key and
  // the known set instead of silently writing nothing)
  int set_tuning(uint32_t key, uint32_t value);

 private:
  // tuning registers: written by the host thread (set_tuning) while
  // the engine loop reads them mid-schedule — atomics, like
  // pipeline_depth_, so the live-write is well-defined on every lane
  std::atomic<uint32_t> bcast_flat_max_ranks_{4};
  std::atomic<uint32_t> reduce_flat_max_ranks_{4};
  std::atomic<uint32_t> gather_flat_max_fanin_{64};
  // byte thresholds (accl.cpp:1216-1224)
  std::atomic<uint64_t> gather_flat_max_count_{32 * 1024};
  std::atomic<uint64_t> reduce_flat_max_count_{32 * 1024};

  // ---- persistent-plan storage (see plan_create/plan_replay) ----
  struct EnginePlan {
    std::vector<std::array<uint32_t, 15>> descs;  // pre-parsed, pinned
    std::vector<std::pair<uint32_t, uint32_t>> comm_epochs;  // at arm
    bool valid = true;
  };
  std::vector<EnginePlan> plans_ ACCL_GUARDED_BY(plans_mu_);
  // token -> call ids
  std::map<long long, std::vector<uint64_t>> plan_tokens_
      ACCL_GUARDED_BY(plans_mu_);
  long long next_plan_token_ ACCL_GUARDED_BY(plans_mu_) = 1;
  std::atomic<uint64_t> plan_replays_{0};  // telemetry: replays queued
  // LOCK ORDER: plans_mu_ before results_mu_ (the replay token reaper
  // scans results under both); never the inverse.
  mutable Mutex plans_mu_ ACCL_ACQUIRED_BEFORE(results_mu_);

  Fifo<CallDesc> cmd_q_;
  std::deque<CallDesc> retry_q_;  // firmware retry FIFO (fw :2460-2479)
  //: consecutive unproductive retry sweeps, for adaptive pacing in
  //: loop(): yield first, escalate to a bounded sleep (engine thread
  //: only — no locking needed)
  uint32_t retry_idle_sweeps_ = 0;
  std::map<uint64_t, CallResult> results_ ACCL_GUARDED_BY(results_mu_);
  Mutex results_mu_;
  std::atomic<uint64_t> next_call_id_{1};
  uint32_t sticky_err_ = 0;  // per-call error accumulator (loop thread only)

  Thread loop_thread_;
  std::atomic<bool> running_{true};
  std::atomic<bool> stopped_{false};  // shutdown() ran to completion
};

}  // namespace accl
