// RDMA transport rung: queue pairs, a one-sided memory plane, and an
// ordered message plane.
//
// Role of the reference's Coyote RDMA backend + cyt_adapter
// (driver/xrt CoyoteDevice; cclo cyt_adapter glue): session setup
// exchanges queue pairs, control traffic (eager messages, RNDZVS_INIT
// address advertisements) flows on an ordered send/recv plane, and
// rendezvous payloads move as one-sided RDMA WRITEs on a SEPARATE
// memory plane with send-queue/completion-queue accounting.
//
// The split is behaviorally meaningful, not cosmetic: memory-plane
// writes are delivered by their own worker and can overtake the ordered
// plane, exactly like RDMA WRITEs bypassing a TCP byte stream — the
// engine's out-of-order WR_DONE matching (pop_match on the completion
// queue) is what keeps the protocol correct, and this rung exercises
// it on every rendezvous transfer.
#pragma once

#include "transport.hpp"

namespace accl {

// Per-destination queue pair bookkeeping (reference: Coyote ibvQpConn;
// observability analog of dump_communicator for the RDMA backend).
struct QueuePair {
  uint32_t local = 0, peer = 0;
  uint64_t sq_posted = 0;    // WRITE work requests posted
  uint64_t cq_completed = 0; // local send completions
  uint64_t bytes_written = 0;
};

class RdmaHub {
 public:
  explicit RdmaHub(int nranks)
      : msg_plane_(nranks), mem_states_(nranks) {
    for (int r = 0; r < nranks; ++r)
      mem_workers_.emplace_back([this, r] { mem_worker(r); });
  }

  ~RdmaHub() {
    running_ = false;
    for (auto& st : mem_states_) st.cv.notify_all();
    for (auto& t : mem_workers_) t.join();
  }

  // ordered message plane (control + eager): composed InprocHub, so
  // its delivery/teardown semantics stay in one place
  void attach(int rank, Transport::Sink sink) {
    msg_plane_.attach(rank, std::move(sink));
  }
  void detach(int rank) {
    msg_plane_.detach(rank);
    auto& st = mem_states_[rank];
    UniqueLock g(st.mu);
    st.sink = nullptr;
    st.cv.wait(g, [&]() ACCL_REQUIRES(st.mu) { return !st.delivering; });
  }
  void attach_mem(int rank, Transport::Sink sink) {
    auto& st = mem_states_[rank];
    MutexLock g(st.mu);
    st.sink = std::move(sink);
  }

  void deliver_msg(uint32_t dst, Message&& msg) {
    msg_plane_.deliver(dst, std::move(msg));
  }

  // memory plane: queue the WRITE for the destination's worker
  void post_write(uint32_t dst, Message&& msg) {
    if (dst >= mem_states_.size()) return;
    auto& st = mem_states_[dst];
    {
      MutexLock g(st.mu);
      st.q.push_back(std::move(msg));
    }
    st.cv.notify_one();
  }

 private:
  struct MemState {
    Mutex mu;
    CondVar cv;
    std::deque<Message> q ACCL_GUARDED_BY(mu);
    Transport::Sink sink ACCL_GUARDED_BY(mu);
    bool delivering ACCL_GUARDED_BY(mu) = false;
  };

  void mem_worker(int rank) {
    auto& st = mem_states_[rank];
    while (running_) {
      Message msg;
      Transport::Sink sink;
      {
        UniqueLock g(st.mu);
        cv_wait_for_pred(st.cv, g, std::chrono::milliseconds(50),
                         [&]() ACCL_REQUIRES(st.mu) {
                           return !st.q.empty() || !running_;
                         });
        if (st.q.empty()) {
          if (!running_) return;
          continue;
        }
        msg = std::move(st.q.front());
        st.q.pop_front();
        sink = st.sink;
        if (sink) st.delivering = true;
      }
      if (!sink) continue;
      sink(std::move(msg));
      {
        MutexLock g(st.mu);
        st.delivering = false;
      }
      st.cv.notify_all();
    }
  }

  InprocHub msg_plane_;
  std::vector<MemState> mem_states_;
  std::vector<Thread> mem_workers_;  // det-managed, like the dgram workers
  std::atomic<bool> running_{true};
};

class RdmaTransport : public Transport {
 public:
  RdmaTransport(std::shared_ptr<RdmaHub> hub, int rank, int nranks)
      : hub_(std::move(hub)), rank_(rank) {
    // session setup: one queue pair per peer (Coyote exchanges these
    // out-of-band at configure time)
    qps_.resize(nranks);
    for (int p = 0; p < nranks; ++p)
      qps_[p] = QueuePair{uint32_t(rank), uint32_t(p)};
  }

  void send(uint32_t dst, Message&& msg) override {
    {
      // the bounds read rides the same lock as the accounting (the
      // table never resizes after the constructor, but the analysis —
      // rightly — wants one discipline, not a prose argument)
      MutexLock g(qp_mu_);
      if (dst >= qps_.size()) return;  // bad session id: drop, like the hubs
      if (msg.hdr.msg_type == uint8_t(MsgType::RndzvsMsg)) {
        // one-sided WRITE on the memory plane: SQ/CQ accounting, then
        // out-of-band delivery that may overtake ordered traffic
        auto& qp = qps_[dst];
        qp.sq_posted++;
        qp.bytes_written += msg.payload.size();
        qp.cq_completed++;  // local completion: buffer ownership returns
      }
    }
    if (msg.hdr.msg_type == uint8_t(MsgType::RndzvsMsg)) {
      hub_->post_write(dst, std::move(msg));
      return;
    }
    hub_->deliver_msg(dst, std::move(msg));
  }

  void start(Sink sink) override {
    // both planes land in the same engine ingress; the engine's demux
    // routes RndzvsMsg to the depacketizer landing path
    hub_->attach(rank_, sink);
    hub_->attach_mem(rank_, std::move(sink));
  }

  void stop() override { hub_->detach(rank_); }

  std::string dump_qps() const {
    MutexLock g(qp_mu_);
    std::string out = "queue pairs (rank " + std::to_string(rank_) + "):\n";
    for (const auto& qp : qps_) {
      out += "  -> " + std::to_string(qp.peer) +
             ": sq=" + std::to_string(qp.sq_posted) +
             " cq=" + std::to_string(qp.cq_completed) +
             " bytes=" + std::to_string(qp.bytes_written) + "\n";
    }
    return out;
  }

 private:
  std::shared_ptr<RdmaHub> hub_;
  int rank_;
  mutable Mutex qp_mu_;
  std::vector<QueuePair> qps_ ACCL_GUARDED_BY(qp_mu_);
};

}  // namespace accl
