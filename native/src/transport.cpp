// TCP transport implementation (see transport.hpp).
#include "transport.hpp"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>

namespace accl {

static bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    // chunk writes at the reference's max packet size; purely a pacing
    // quantum here (TCP re-frames anyway)
    size_t chunk = n < MAX_PACKETSIZE ? n : size_t(MAX_PACKETSIZE);
    ssize_t w = ::write(fd, p, chunk);
    if (w <= 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= size_t(w);
  }
  return true;
}

static bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= size_t(r);
  }
  return true;
}

TcpTransport::TcpTransport(int rank, int nranks, int base_port,
                           std::vector<std::string> peer_ips)
    : rank_(rank),
      nranks_(nranks),
      base_port_(base_port),
      peer_ips_(std::move(peer_ips)),
      peer_fds_(nranks, -1),
      peer_mu_(nranks) {}

TcpTransport::~TcpTransport() { stop(); }

void TcpTransport::start(Sink sink) {
  sink_ = std::move(sink);
  running_ = true;

  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(uint16_t(base_port_ + rank_));
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    throw std::runtime_error("TcpTransport: bind failed on port " +
                             std::to_string(base_port_ + rank_));
  ::listen(listen_fd_, nranks_ + 4);
  MutexLock g(conn_mu_);
  threads_.emplace_back([this] { accept_loop(); });
}

void TcpTransport::stop() {
  if (!running_.exchange(false)) return;
  if (listen_fd_ >= 0) {
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  for (int& fd : peer_fds_) {
    if (fd >= 0) {
      ::shutdown(fd, SHUT_RDWR);
      ::close(fd);
      fd = -1;
    }
  }
  std::vector<std::thread> to_join;
  {
    // unblock reader threads parked in read(2) on ACCEPTED sockets —
    // without this, a same-process peer that still holds its outbound
    // end open leaves our reader blocked and the join below deadlocks
    // (only surfaced once ranks could share a process; the
    // process-per-rank rung tears the peer end down at process exit).
    // threads_ is swapped out UNDER conn_mu_: a connection accepted in
    // the closing window can no longer emplace into the vector we are
    // iterating (accept_loop re-checks running_ under the same lock
    // and closes the fd instead).
    MutexLock g(conn_mu_);
    for (int fd : accepted_fds_) ::shutdown(fd, SHUT_RDWR);
    to_join.swap(threads_);
  }
  for (auto& t : to_join)
    if (t.joinable()) t.join();
}

void TcpTransport::accept_loop() {
  while (running_) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (!running_) break;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    MutexLock g(conn_mu_);
    if (!running_) {  // raced with stop(): the join sweep already ran
      ::close(fd);
      break;
    }
    accepted_fds_.push_back(fd);
    threads_.emplace_back([this, fd] { reader_loop(fd); });
  }
}

void TcpTransport::reader_loop(int fd) {
  while (running_) {
    uint32_t len = 0;
    if (!read_all(fd, &len, 4)) break;
    if (len < sizeof(WireHeader)) break;
    Message msg;
    if (!read_all(fd, &msg.hdr, sizeof(WireHeader))) break;
    msg.payload.resize(len - sizeof(WireHeader));
    if (!msg.payload.empty() &&
        !read_all(fd, msg.payload.data(), msg.payload.size()))
      break;
    if (sink_) sink_(std::move(msg));
  }
  {
    // deregister before close so stop() never shuts down a recycled fd
    MutexLock g(conn_mu_);
    for (auto it = accepted_fds_.begin(); it != accepted_fds_.end(); ++it)
      if (*it == fd) {
        accepted_fds_.erase(it);
        break;
      }
  }
  ::close(fd);
}

int TcpTransport::connect_to(uint32_t dst, int max_attempts) {
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(base_port_ + int(dst)));
  const std::string& ip =
      dst < peer_ips_.size() && !peer_ips_[dst].empty() ? peer_ips_[dst]
                                                        : "127.0.0.1";
  ::inet_pton(AF_INET, ip.c_str(), &addr.sin_addr);
  // retry: peers race to come up (the reference exchanges sessions at
  // configure time; we tolerate startup skew instead).  A fresh socket
  // per attempt — after a failed connect(2) the fd is in an unspecified
  // state and further connects on it can fail instantly.
  for (int attempt = 0; attempt < max_attempts; ++attempt) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(25));
  }
  return -1;
}

int TcpTransport::open_session(uint32_t dst) {
  if (dst >= peer_fds_.size()) return -1;
  MutexLock g(peer_mu_[dst]);
  if (peer_fds_[dst] >= 0) return 0;  // already open: success no-op
  peer_fds_[dst] = connect_to(dst, /*max_attempts=*/80);  // ~2 s window
  return peer_fds_[dst] >= 0 ? 0 : -1;
}

int TcpTransport::close_session(uint32_t dst) {
  if (dst >= peer_fds_.size()) return -1;
  MutexLock g(peer_mu_[dst]);
  if (peer_fds_[dst] < 0) return -1;  // nothing open on this session
  ::shutdown(peer_fds_[dst], SHUT_RDWR);
  ::close(peer_fds_[dst]);
  peer_fds_[dst] = -1;
  return 0;
}

void TcpTransport::send(uint32_t dst, Message&& msg) {
  MutexLock g(peer_mu_[dst]);
  if (peer_fds_[dst] < 0) {
    peer_fds_[dst] = connect_to(dst);
    if (peer_fds_[dst] < 0)
      throw std::runtime_error("TcpTransport: connect to rank " +
                               std::to_string(dst) + " failed");
  }
  uint32_t len = uint32_t(sizeof(WireHeader) + msg.payload.size());
  int fd = peer_fds_[dst];
  if (!write_all(fd, &len, 4) || !write_all(fd, &msg.hdr, sizeof(WireHeader)) ||
      (!msg.payload.empty() &&
       !write_all(fd, msg.payload.data(), msg.payload.size())))
    throw std::runtime_error("TcpTransport: write to rank " +
                             std::to_string(dst) + " failed");
}

}  // namespace accl
