// On-path reduction arithmetic and wire-compression lanes.
//
// Equivalent of the reference plugins:
//  - reduce_ops: 512-bit SIMD elementwise sum/max, lane selected by TDEST,
//    10 functions over {fp32,fp64,i32,i64,fp16}x{sum,max}
//    (kernels/plugins/reduce_ops/reduce_ops.cpp:31-107)
//  - hp_compression: streaming fp32<->fp16 cast at 2:1 width
//    (kernels/plugins/hp_compression/hp_compression.cpp:70-144)
//
// Lane numbering matches accl_tpu/arithconfig.py ARITH_LANE.  On TPU the
// same lanes are Pallas kernels (accl_tpu/ops/); the emulator runs these
// scalar loops, which auto-vectorize under -O2.
#pragma once

#include <cstdint>
#include <cstring>

#include "common.hpp"

namespace accl {

enum ArithLane : uint32_t {
  F32_SUM = 0,
  F32_MAX = 1,
  F64_SUM = 2,
  F64_MAX = 3,
  I32_SUM = 4,
  I32_MAX = 5,
  I64_SUM = 6,
  I64_MAX = 7,
  F16_SUM = 8,
  F16_MAX = 9,
  BF16_SUM = 10,
  BF16_MAX = 11,
  NUM_LANES = 12,
};

template <typename T, bool MAX>
static void reduce_typed(const uint8_t* a, const uint8_t* b, uint8_t* r,
                         uint64_t nbytes) {
  uint64_t n = nbytes / sizeof(T);
  const T* pa = reinterpret_cast<const T*>(a);
  const T* pb = reinterpret_cast<const T*>(b);
  T* pr = reinterpret_cast<T*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    if constexpr (MAX)
      pr[i] = pa[i] > pb[i] ? pa[i] : pb[i];
    else
      pr[i] = T(pa[i] + pb[i]);
  }
}

static inline void reduce_f16(const uint8_t* a, const uint8_t* b, uint8_t* r,
                              uint64_t nbytes, bool is_max) {
  uint64_t n = nbytes / 2;
  const uint16_t* pa = reinterpret_cast<const uint16_t*>(a);
  const uint16_t* pb = reinterpret_cast<const uint16_t*>(b);
  uint16_t* pr = reinterpret_cast<uint16_t*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    float fa = f16_to_f32(pa[i]), fb = f16_to_f32(pb[i]);
    pr[i] = f32_to_f16(is_max ? (fa > fb ? fa : fb) : (fa + fb));
  }
}

// bfloat16 <-> fp32: bf16 is the top 16 bits of an ieee fp32 (the TPU's
// native 16-bit float; round-to-nearest-even on the way down).
static inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = uint32_t(h) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1);
  return uint16_t((bits + rounding) >> 16);
}

static inline void reduce_bf16(const uint8_t* a, const uint8_t* b, uint8_t* r,
                               uint64_t nbytes, bool is_max) {
  uint64_t n = nbytes / 2;
  const uint16_t* pa = reinterpret_cast<const uint16_t*>(a);
  const uint16_t* pb = reinterpret_cast<const uint16_t*>(b);
  uint16_t* pr = reinterpret_cast<uint16_t*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    float fa = bf16_to_f32(pa[i]), fb = bf16_to_f32(pb[i]);
    pr[i] = f32_to_bf16(is_max ? (fa > fb ? fa : fb) : (fa + fb));
  }
}

// r[0:n] = lane(a, b); returns an Err bit on unknown lane / ragged size.
inline uint32_t run_reduce_lane(uint32_t lane, const uint8_t* a,
                                const uint8_t* b, uint8_t* r,
                                uint64_t nbytes) {
  switch (lane) {
    case F32_SUM: reduce_typed<float, false>(a, b, r, nbytes); break;
    case F32_MAX: reduce_typed<float, true>(a, b, r, nbytes); break;
    case F64_SUM: reduce_typed<double, false>(a, b, r, nbytes); break;
    case F64_MAX: reduce_typed<double, true>(a, b, r, nbytes); break;
    case I32_SUM: reduce_typed<int32_t, false>(a, b, r, nbytes); break;
    case I32_MAX: reduce_typed<int32_t, true>(a, b, r, nbytes); break;
    case I64_SUM: reduce_typed<int64_t, false>(a, b, r, nbytes); break;
    case I64_MAX: reduce_typed<int64_t, true>(a, b, r, nbytes); break;
    case F16_SUM: reduce_f16(a, b, r, nbytes, false); break;
    case F16_MAX: reduce_f16(a, b, r, nbytes, true); break;
    case BF16_SUM: reduce_bf16(a, b, r, nbytes, false); break;
    case BF16_MAX: reduce_bf16(a, b, r, nbytes, true); break;
    default: return ARITH_ERROR;
  }
  return OK;
}

// fp32 -> fp16 wire compression, out must hold nbytes/2.
inline void compress_f32_f16(const uint8_t* in, uint8_t* out, uint64_t nbytes) {
  uint64_t n = nbytes / 4;
  const float* pi = reinterpret_cast<const float*>(in);
  uint16_t* po = reinterpret_cast<uint16_t*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f32_to_f16(pi[i]);
}

// fp16 -> fp32 decompression, out must hold nbytes*2.
inline void decompress_f16_f32(const uint8_t* in, uint8_t* out,
                               uint64_t nbytes) {
  uint64_t n = nbytes / 2;
  const uint16_t* pi = reinterpret_cast<const uint16_t*>(in);
  float* po = reinterpret_cast<float*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f16_to_f32(pi[i]);
}

// fp32 -> bf16 wire compression (TPU-native 16-bit pair; no reference
// analog — the hp_compression plugin only ships f32<->f16).
inline void compress_f32_bf16(const uint8_t* in, uint8_t* out,
                              uint64_t nbytes) {
  uint64_t n = nbytes / 4;
  const float* pi = reinterpret_cast<const float*>(in);
  uint16_t* po = reinterpret_cast<uint16_t*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f32_to_bf16(pi[i]);
}

inline void decompress_bf16_f32(const uint8_t* in, uint8_t* out,
                                uint64_t nbytes) {
  uint64_t n = nbytes / 2;
  const uint16_t* pi = reinterpret_cast<const uint16_t*>(in);
  float* po = reinterpret_cast<float*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = bf16_to_f32(pi[i]);
}

// Compressor-lane dispatch (arithconfig.py ids: compressor 0=f32->f16,
// 2=f32->bf16; decompressor = compressor+1).  Element-count based.
inline uint32_t run_compress_lane(uint32_t kind, const uint8_t* in,
                                  uint8_t* out, uint64_t elems) {
  switch (kind) {
    case 0: compress_f32_f16(in, out, elems * 4); return OK;
    case 2: compress_f32_bf16(in, out, elems * 4); return OK;
    default: return COMPRESSION_ERROR;
  }
}

inline uint32_t run_decompress_lane(uint32_t kind, const uint8_t* in,
                                    uint8_t* out, uint64_t elems) {
  switch (kind) {
    case 0: decompress_f16_f32(in, out, elems * 2); return OK;
    case 2: decompress_bf16_f32(in, out, elems * 2); return OK;
    default: return COMPRESSION_ERROR;
  }
}

}  // namespace accl
