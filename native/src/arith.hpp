// On-path reduction arithmetic and wire-compression lanes.
//
// Equivalent of the reference plugins:
//  - reduce_ops: 512-bit SIMD elementwise sum/max, lane selected by TDEST,
//    10 functions over {fp32,fp64,i32,i64,fp16}x{sum,max}
//    (kernels/plugins/reduce_ops/reduce_ops.cpp:31-107)
//  - hp_compression: streaming fp32<->fp16 cast at 2:1 width
//    (kernels/plugins/hp_compression/hp_compression.cpp:70-144)
//
// Lane numbering matches accl_tpu/arithconfig.py ARITH_LANE.  On TPU the
// same lanes are Pallas kernels (accl_tpu/ops/); the emulator runs these
// scalar loops, which auto-vectorize under -O2.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>

#include "common.hpp"

namespace accl {

enum ArithLane : uint32_t {
  F32_SUM = 0,
  F32_MAX = 1,
  F64_SUM = 2,
  F64_MAX = 3,
  I32_SUM = 4,
  I32_MAX = 5,
  I64_SUM = 6,
  I64_MAX = 7,
  F16_SUM = 8,
  F16_MAX = 9,
  BF16_SUM = 10,
  BF16_MAX = 11,
  NUM_LANES = 12,
};

template <typename T, bool MAX>
static void reduce_typed(const uint8_t* a, const uint8_t* b, uint8_t* r,
                         uint64_t nbytes) {
  uint64_t n = nbytes / sizeof(T);
  const T* pa = reinterpret_cast<const T*>(a);
  const T* pb = reinterpret_cast<const T*>(b);
  T* pr = reinterpret_cast<T*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    if constexpr (MAX)
      pr[i] = pa[i] > pb[i] ? pa[i] : pb[i];
    else
      pr[i] = T(pa[i] + pb[i]);
  }
}

static inline void reduce_f16(const uint8_t* a, const uint8_t* b, uint8_t* r,
                              uint64_t nbytes, bool is_max) {
  uint64_t n = nbytes / 2;
  const uint16_t* pa = reinterpret_cast<const uint16_t*>(a);
  const uint16_t* pb = reinterpret_cast<const uint16_t*>(b);
  uint16_t* pr = reinterpret_cast<uint16_t*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    float fa = f16_to_f32(pa[i]), fb = f16_to_f32(pb[i]);
    pr[i] = f32_to_f16(is_max ? (fa > fb ? fa : fb) : (fa + fb));
  }
}

// bfloat16 <-> fp32: bf16 is the top 16 bits of an ieee fp32 (the TPU's
// native 16-bit float; round-to-nearest-even on the way down).
static inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = uint32_t(h) << 16;
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

static inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1);
  return uint16_t((bits + rounding) >> 16);
}

static inline void reduce_bf16(const uint8_t* a, const uint8_t* b, uint8_t* r,
                               uint64_t nbytes, bool is_max) {
  uint64_t n = nbytes / 2;
  const uint16_t* pa = reinterpret_cast<const uint16_t*>(a);
  const uint16_t* pb = reinterpret_cast<const uint16_t*>(b);
  uint16_t* pr = reinterpret_cast<uint16_t*>(r);
  for (uint64_t i = 0; i < n; ++i) {
    float fa = bf16_to_f32(pa[i]), fb = bf16_to_f32(pb[i]);
    pr[i] = f32_to_bf16(is_max ? (fa > fb ? fa : fb) : (fa + fb));
  }
}

// r[0:n] = lane(a, b); returns an Err bit on unknown lane / ragged size.
inline uint32_t run_reduce_lane(uint32_t lane, const uint8_t* a,
                                const uint8_t* b, uint8_t* r,
                                uint64_t nbytes) {
  switch (lane) {
    case F32_SUM: reduce_typed<float, false>(a, b, r, nbytes); break;
    case F32_MAX: reduce_typed<float, true>(a, b, r, nbytes); break;
    case F64_SUM: reduce_typed<double, false>(a, b, r, nbytes); break;
    case F64_MAX: reduce_typed<double, true>(a, b, r, nbytes); break;
    case I32_SUM: reduce_typed<int32_t, false>(a, b, r, nbytes); break;
    case I32_MAX: reduce_typed<int32_t, true>(a, b, r, nbytes); break;
    case I64_SUM: reduce_typed<int64_t, false>(a, b, r, nbytes); break;
    case I64_MAX: reduce_typed<int64_t, true>(a, b, r, nbytes); break;
    case F16_SUM: reduce_f16(a, b, r, nbytes, false); break;
    case F16_MAX: reduce_f16(a, b, r, nbytes, true); break;
    case BF16_SUM: reduce_bf16(a, b, r, nbytes, false); break;
    case BF16_MAX: reduce_bf16(a, b, r, nbytes, true); break;
    default: return ARITH_ERROR;
  }
  return OK;
}

// fp32 -> fp16 wire compression, out must hold nbytes/2.
inline void compress_f32_f16(const uint8_t* in, uint8_t* out, uint64_t nbytes) {
  uint64_t n = nbytes / 4;
  const float* pi = reinterpret_cast<const float*>(in);
  uint16_t* po = reinterpret_cast<uint16_t*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f32_to_f16(pi[i]);
}

// fp16 -> fp32 decompression, out must hold nbytes*2.
inline void decompress_f16_f32(const uint8_t* in, uint8_t* out,
                               uint64_t nbytes) {
  uint64_t n = nbytes / 2;
  const uint16_t* pi = reinterpret_cast<const uint16_t*>(in);
  float* po = reinterpret_cast<float*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f16_to_f32(pi[i]);
}

// fp32 -> bf16 wire compression (TPU-native 16-bit pair; no reference
// analog — the hp_compression plugin only ships f32<->f16).
inline void compress_f32_bf16(const uint8_t* in, uint8_t* out,
                              uint64_t nbytes) {
  uint64_t n = nbytes / 4;
  const float* pi = reinterpret_cast<const float*>(in);
  uint16_t* po = reinterpret_cast<uint16_t*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = f32_to_bf16(pi[i]);
}

inline void decompress_bf16_f32(const uint8_t* in, uint8_t* out,
                                uint64_t nbytes) {
  uint64_t n = nbytes / 2;
  const uint16_t* pi = reinterpret_cast<const uint16_t*>(in);
  float* po = reinterpret_cast<float*>(out);
  for (uint64_t i = 0; i < n; ++i) po[i] = bf16_to_f32(pi[i]);
}

// Compressor-lane dispatch (arithconfig.py ids: compressor 0=f32->f16,
// 2=f32->bf16; decompressor = compressor+1).  Element-count based.
inline uint32_t run_compress_lane(uint32_t kind, const uint8_t* in,
                                  uint8_t* out, uint64_t elems) {
  switch (kind) {
    case 0: compress_f32_f16(in, out, elems * 4); return OK;
    case 2: compress_f32_bf16(in, out, elems * 4); return OK;
    default: return COMPRESSION_ERROR;
  }
}

inline uint32_t run_decompress_lane(uint32_t kind, const uint8_t* in,
                                    uint8_t* out, uint64_t elems) {
  switch (kind) {
    case 0: decompress_f16_f32(in, out, elems * 2); return OK;
    case 2: decompress_bf16_f32(in, out, elems * 2); return OK;
    default: return COMPRESSION_ERROR;
  }
}

// ---------------------------------------------------------------------------
// int8 block-scaled wire lane (r17; EQuARX-style 4:1 quantized wire,
// arxiv 2506.17615).  Unlike the elementwise cast lanes above, the
// compressed representation is a self-describing SEGMENT:
//   [u32 nblocks][u32 block][f32 scale x nblocks][i8 q x elems]
// with one symmetric-absmax fp32 scale per `block` elements and
// elems = payload - 8 - 4*nblocks.  Both ends derive block geometry
// from their own arithcfg (same table upload), and the header makes
// the frame independently VALIDATABLE at ingress (frame_ok): a
// truncated scale row, a count/block mismatch, or an oversized block
// is a counted rejection, never an OOB read.  Accumulation stays fp32
// (arith_is_compressed=false on the int8 pair): the reduce funnel
// dequantizes into the fp32 accumulator — dequantize-accumulate, the
// EQuARX discipline.
// ---------------------------------------------------------------------------
constexpr uint32_t I8_BLOCK_COMPRESSOR = 4;  // arithconfig.py COMPRESS_F32_I8
constexpr uint32_t I8_BLOCK_HDR_BYTES = 8;
constexpr uint32_t I8_BLOCK_MAX = 65536;     // sanity cap on wire block size
constexpr uint32_t I8_BLOCK_DEFAULT = 256;

inline uint64_t i8_nblocks(uint64_t elems, uint32_t block) {
  return block ? (elems + block - 1) / block : 0;
}

// Wire bytes of one `elems`-element block-scaled segment.
inline uint64_t i8_wire_bytes(uint64_t elems, uint32_t block) {
  return I8_BLOCK_HDR_BYTES + i8_nblocks(elems, block) * 4 + elems;
}

// Elements per segment that fit `wire_cap` bytes.  Every segment
// carries its OWN scale rows, so a trailing partial block is fully
// decodable — packing is maximized rather than rounded to whole
// blocks (whole-block rounding wasted up to a block's width of every
// rx buffer).  At least one element.
inline uint64_t i8_seg_elems(uint64_t wire_cap, uint32_t block) {
  if (!block) return 1;
  if (wire_cap <= I8_BLOCK_HDR_BYTES + 5) return 1;
  uint64_t body = wire_cap - I8_BLOCK_HDR_BYTES;
  // e + 4*ceil(e/block) <= body; solve via whole blocks then top up
  uint64_t per_block = uint64_t(block) + 4;
  uint64_t nblocks = body / per_block;
  uint64_t elems = nblocks * block;
  uint64_t used = nblocks * per_block;
  uint64_t rest = body - used;
  if (rest > 4) elems += std::min<uint64_t>(block, rest - 4);
  return elems ? elems : 1;
}

// Decode + validate a block-scaled segment header.  Returns the
// element count, or UINT64_MAX when the framing is malformed
// (truncated scale rows, count/block mismatch, oversized/zero block).
// `expect_block` != 0 additionally pins the block size (the receiver's
// own arithcfg geometry — sender/receiver tables match by upload).
inline uint64_t i8_wire_elems(const uint8_t* p, uint64_t bytes,
                              uint32_t expect_block = 0) {
  if (!p || bytes < I8_BLOCK_HDR_BYTES + 4 + 1) return UINT64_MAX;
  uint32_t nblocks, block;
  std::memcpy(&nblocks, p, 4);
  std::memcpy(&block, p + 4, 4);
  if (block == 0 || block > I8_BLOCK_MAX) return UINT64_MAX;
  if (expect_block && block != expect_block) return UINT64_MAX;
  if (nblocks == 0 || uint64_t(nblocks) * 4 + I8_BLOCK_HDR_BYTES > bytes)
    return UINT64_MAX;  // truncated scale rows
  uint64_t elems = bytes - I8_BLOCK_HDR_BYTES - uint64_t(nblocks) * 4;
  // exactly ceil(elems/block) blocks: anything else is a count/block
  // mismatch (extra blocks = truncated data; fewer = oversized blocks)
  if (i8_nblocks(elems, block) != nblocks) return UINT64_MAX;
  return elems;
}

// The block kernels below are the emulator's wire hot path: gcc at
// -O2 (the production lane) does not auto-vectorize, which leaves the
// quantizer ~10x slower than the memcpys it replaces and erases the
// 4:1 wire win.  Function-level O3 + fast-math turns the absmax /
// scale / convert loops into SIMD (measured 1.3 -> ~10 GB/s); the
// semantics stay deterministic for finite inputs — fmax reassociation
// is exact and the convert loop is elementwise — only NaN/Inf inputs
// (garbage either way on a quantized wire) lose their IEEE ordering.
// clang (the TSA lane) and sanitizer builds ignore the attribute and
// compute identical finite results, just slower.
#if defined(__GNUC__) && !defined(__clang__)
#define ACCL_VEC_HOT __attribute__((optimize("O3", "fast-math")))
#else
#define ACCL_VEC_HOT
#endif

// fp32 -> block-scaled int8 segment; out must hold i8_wire_bytes().
// With `residual` non-null (error feedback, EQuARX): the stored
// quantization error of the previous pass through this site is folded
// into the input first, and the new error is written back — the bias
// of hop/iteration k is carried into k+1 instead of being lost.
ACCL_VEC_HOT inline void quantize_i8_block(const float* in, uint8_t* out,
                                           uint64_t elems, uint32_t block,
                                           float* residual = nullptr) {
  uint32_t nblocks = uint32_t(i8_nblocks(elems, block));
  std::memcpy(out, &nblocks, 4);
  std::memcpy(out + 4, &block, 4);
  float* scales = reinterpret_cast<float*>(out + I8_BLOCK_HDR_BYTES);
  int8_t* q = reinterpret_cast<int8_t*>(out + I8_BLOCK_HDR_BYTES +
                                        uint64_t(nblocks) * 4);
  for (uint32_t b = 0; b < nblocks; ++b) {
    const uint64_t lo = uint64_t(b) * block;
    const uint64_t hi = std::min<uint64_t>(lo + block, elems);
    const uint64_t n = hi - lo;
    const float* x = in + lo;
    const float* r = residual ? residual + lo : nullptr;
    float amax = 0.0f;
    if (r) {
      for (uint64_t i = 0; i < n; ++i) {
        float v = x[i] + r[i];
        float a = v < 0 ? -v : v;
        amax = a > amax ? a : amax;
      }
    } else {
      for (uint64_t i = 0; i < n; ++i) {
        float a = x[i] < 0 ? -x[i] : x[i];
        amax = a > amax ? a : amax;
      }
    }
    const float scale = amax == 0.0f ? 1.0f : amax / 127.0f;
    scales[b] = scale;
    const float inv = 1.0f / scale;
    int8_t* qb = q + lo;
    if (r) {
      float* rb = residual + lo;
      for (uint64_t i = 0; i < n; ++i) {
        float v = x[i] + rb[i];
        float t = v * inv;
        t = t < -127.0f ? -127.0f : (t > 127.0f ? 127.0f : t);
        // round-half-away, branchless (deterministic vs fenv)
        int32_t iv = int32_t(t + (t >= 0.0f ? 0.5f : -0.5f));
        qb[i] = int8_t(iv);
        rb[i] = v - float(iv) * scale;
      }
    } else {
      for (uint64_t i = 0; i < n; ++i) {
        float t = x[i] * inv;
        t = t < -127.0f ? -127.0f : (t > 127.0f ? 127.0f : t);
        int32_t iv = int32_t(t + (t >= 0.0f ? 0.5f : -0.5f));
        qb[i] = int8_t(iv);
      }
    }
  }
}

// block-scaled int8 segment -> fp32; validates framing against the
// caller's expected element count + block geometry.  Returns OK or
// COMPRESSION_ERROR (malformed/mismatched segment; out untouched).
ACCL_VEC_HOT inline uint32_t dequantize_i8_block(const uint8_t* in,
                                                 uint64_t in_bytes,
                                                 float* out, uint64_t elems,
                                                 uint32_t block) {
  uint64_t got = i8_wire_elems(in, in_bytes, block);
  if (got == UINT64_MAX || got != elems) return COMPRESSION_ERROR;
  uint32_t nblocks = uint32_t(i8_nblocks(elems, block));
  const float* scales = reinterpret_cast<const float*>(in + I8_BLOCK_HDR_BYTES);
  const int8_t* q = reinterpret_cast<const int8_t*>(
      in + I8_BLOCK_HDR_BYTES + uint64_t(nblocks) * 4);
  for (uint32_t b = 0; b < nblocks; ++b) {
    const uint64_t lo = uint64_t(b) * block;
    const uint64_t hi = std::min<uint64_t>(lo + block, elems);
    const float scale = scales[b];
    const int8_t* qb = q + lo;
    float* ob = out + lo;
    const uint64_t n = hi - lo;
    for (uint64_t i = 0; i < n; ++i) ob[i] = float(qb[i]) * scale;
  }
  return OK;
}

}  // namespace accl
