// Native C++ host driver — the libaccl-equivalent API surface.
//
// Reference analog: class ACCL::ACCL and its buffer/communicator
// surfaces (driver/xrt/include/accl.hpp:46-1148).  This facade drives
// the native engine directly (no FFI), giving C++ applications the same
// collectives the Python driver exposes; the Python layer is an
// alternative binding over the same engine, not the implementation.
//
// Synchronous API: each call marshals the 15-word descriptor, starts it,
// and blocks for the retcode (reference call_sync, accl.cpp:1404-1413).
#pragma once

#include <cstring>
#include <memory>
#include <stdexcept>
#include <vector>

#include "../src/engine.hpp"

namespace accl {
namespace host {

enum class Reduce : uint32_t { SUM = 0, MAX = 1 };

// Typed device buffer handle (reference: Buffer<T>, buffer.hpp:155).
template <typename T>
class Buffer {
 public:
  Buffer(Engine* e, uint64_t n) : e_(e), n_(n) {
    addr_ = e_->alloc(n * sizeof(T), 64);
    if (!addr_) throw std::runtime_error("device memory exhausted");
    host_.resize(n);
  }
  ~Buffer() {
    if (addr_) e_->free_addr(addr_);
  }
  Buffer(const Buffer&) = delete;
  Buffer& operator=(const Buffer&) = delete;

  T* data() { return host_.data(); }
  const T* data() const { return host_.data(); }
  T& operator[](size_t i) { return host_[i]; }
  uint64_t length() const { return n_; }
  uint64_t address() const { return addr_; }

  void sync_to_device() {
    e_->write_mem(addr_, host_.data(), n_ * sizeof(T));
  }
  void sync_from_device() {
    e_->read_mem(addr_, host_.data(), n_ * sizeof(T));
  }

 private:
  Engine* e_;
  uint64_t n_, addr_ = 0;
  std::vector<T> host_;
};

// One rank's driver handle.
class ACCL {
 public:
  explicit ACCL(Engine* engine) : e_(engine) {}

  // Bring-up (reference initialize(), accl.cpp:1082-1130): rx pool,
  // communicator, fp32 arithmetic config, thresholds, enable.
  void initialize(const std::vector<uint32_t>& sessions, uint32_t local_rank,
                  uint32_t n_rx_bufs = 16, uint64_t rx_buf_size = 1024,
                  uint64_t max_eager = 0) {
    config(CfgFunc::ResetPeriph, 0);
    e_->cfg_rx_buffers(n_rx_bufs, rx_buf_size);
    std::vector<uint32_t> words{uint32_t(sessions.size()), local_rank};
    for (uint32_t s : sessions) {
      words.push_back(0);                       // ip (unused in-proc)
      words.push_back(0);                       // port
      words.push_back(s);                       // session = global rank
      words.push_back(uint32_t(rx_buf_size));   // max segment
    }
    comm_ = e_->set_comm(words.data(), int(words.size()));
    // fp32 identity arithcfg: lanes[SUM, MAX] = {F32_SUM, F32_MAX}
    std::vector<uint32_t> acfg{32, 32, 0, 0, 0, 0, 2, F32_SUM, F32_MAX};
    arith_f32_ = e_->set_arithcfg(acfg.data(), int(acfg.size()));
    config(CfgFunc::SetTimeout, 1'000'000);
    config(CfgFunc::SetMaxEagerMsgSize,
           uint32_t(max_eager ? max_eager : rx_buf_size));
    config(CfgFunc::SetMaxRendezvousMsgSize, 64u << 20);
    config(CfgFunc::EnablePkt, 0);
    world_ = uint32_t(sessions.size());
    rank_ = local_rank;
  }

  uint32_t rank() const { return rank_; }
  uint32_t world() const { return world_; }
  Engine* engine() { return e_; }

  template <typename T>
  std::unique_ptr<Buffer<T>> create_buffer(uint64_t n) {
    return std::make_unique<Buffer<T>>(e_, n);
  }

  // ---- collectives (reference accl.cpp entry points) ----
  uint64_t start(Op op, uint32_t count, uint32_t root, uint32_t func,
                 uint32_t tag, uint64_t a0, uint64_t a1, uint64_t a2) {
    std::array<uint32_t, 15> w{};
    w[0] = uint32_t(op);
    w[1] = count;
    w[2] = comm_;
    w[3] = root;
    w[4] = func;
    w[5] = tag;
    w[6] = arith_f32_;
    w[9] = uint32_t(a0);
    w[10] = uint32_t(a0 >> 32);
    w[11] = uint32_t(a1);
    w[12] = uint32_t(a1 >> 32);
    w[13] = uint32_t(a2);
    w[14] = uint32_t(a2 >> 32);
    return e_->start_call(w.data());
  }

  uint32_t wait(uint64_t id, int timeout_ms = 60000) {
    uint32_t ret = 0;
    double dur = 0;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (std::chrono::steady_clock::now() < deadline) {
      if (e_->poll_call(id, &ret, &dur)) {
        last_duration_ns_ = dur;
        return ret;
      }
      std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
    throw std::runtime_error("collective timed out");
  }

  void check(uint32_t ret) {
    if (ret != 0)
      throw std::runtime_error("collective failed, retcode=" +
                               std::to_string(ret));
  }

  double last_duration_ns() const { return last_duration_ns_; }

  template <typename T>
  uint64_t send_async(Buffer<T>& b, uint32_t count, uint32_t dst,
                      uint32_t tag) {
    b.sync_to_device();
    return start(Op::Send, count, dst, 0, tag, b.address(), 0, 0);
  }

  template <typename T>
  void recv(Buffer<T>& b, uint32_t count, uint32_t src, uint32_t tag) {
    check(wait(start(Op::Recv, count, src, 0, tag, 0, 0, b.address())));
    b.sync_from_device();
  }

  template <typename T>
  void allreduce(Buffer<T>& sendb, Buffer<T>& recvb, uint32_t count,
                 Reduce fn = Reduce::SUM) {
    sendb.sync_to_device();
    check(wait(start(Op::Allreduce, count, 0, uint32_t(fn), TAG_ANY,
                     sendb.address(), 0, recvb.address())));
    recvb.sync_from_device();
  }

  template <typename T>
  void bcast(Buffer<T>& b, uint32_t count, uint32_t root) {
    if (rank_ == root) {
      b.sync_to_device();
      check(wait(start(Op::Bcast, count, root, 0, TAG_ANY, b.address(), 0,
                       b.address())));
    } else {
      check(wait(start(Op::Bcast, count, root, 0, TAG_ANY, 0, 0,
                       b.address())));
      b.sync_from_device();
    }
  }

  void barrier() {
    check(wait(start(Op::Barrier, 0, 0, 0, TAG_ANY, 0, 0, 0)));
  }

 private:
  void config(CfgFunc f, uint32_t value) {
    std::array<uint32_t, 15> w{};
    w[0] = uint32_t(Op::Config);
    w[1] = value;
    w[4] = uint32_t(f);
    check(wait(e_->start_call(w.data())));
  }

  Engine* e_;
  uint32_t comm_ = 0, rank_ = 0, world_ = 1;
  int arith_f32_ = 0;
  double last_duration_ns_ = 0;
};

}  // namespace host
}  // namespace accl
