// Native C++ host driver — the libaccl-equivalent API surface.
//
// Reference analog: class ACCL::ACCL and its buffer/communicator
// surfaces (driver/xrt/include/accl.hpp:46-1148, accl.cpp).  This facade
// drives the native engine directly (no FFI), giving C++ applications
// the same collectives the Python driver exposes: all 14 collectives +
// nop, per-operand and wire compression (prepare_call flag algebra,
// accl.cpp:1252-1372), compute-kernel streams, sub-communicators, and
// async request handles.  The Python layer (accl_tpu/accl.py) is an
// alternative binding over the same engine, not the implementation.
#pragma once

#include <chrono>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <thread>
#include <vector>

#include "../src/engine.hpp"

namespace accl {
namespace host {

enum class Reduce : uint32_t { SUM = 0, MAX = 1 };

// Wire/arithmetic datatypes (bit-compatible with accl_tpu/constants.py
// DataType and the reference constants.hpp:254-262).
enum class DType : uint32_t {
  none = 0,
  i8 = 1,
  f16 = 2,
  f32 = 3,
  f64 = 4,
  i32 = 5,
  i64 = 6,
  bf16 = 7,
};

inline uint32_t dtype_bits(DType d) {
  switch (d) {
    case DType::i8: return 8;
    case DType::f16: case DType::bf16: return 16;
    case DType::f32: case DType::i32: return 32;
    case DType::f64: case DType::i64: return 64;
    default: return 0;
  }
}

template <typename T> struct dtype_of;
template <> struct dtype_of<float> { static constexpr DType value = DType::f32; };
template <> struct dtype_of<double> { static constexpr DType value = DType::f64; };
template <> struct dtype_of<int32_t> { static constexpr DType value = DType::i32; };
template <> struct dtype_of<int64_t> { static constexpr DType value = DType::i64; };
// uint16_t carries raw fp16 bits (like the reference's half payloads)
template <> struct dtype_of<uint16_t> { static constexpr DType value = DType::f16; };

// Typed device buffer handle (reference: Buffer<T>, buffer.hpp:155).
// The DType may differ from T's default when the host representation is
// a bit-pattern carrier (e.g. Buffer<uint16_t> holding bf16).
template <typename T>
class Buffer {
 public:
  Buffer(Engine* e, uint64_t n, DType dt = dtype_of<T>::value,
         bool host_only = false, bool p2p = false)
      : e_(e), n_(n), dtype_(dt), host_only_(host_only), p2p_(p2p) {
    addr_ = host_only ? e_->alloc_host(n * sizeof(T), 64)
                      : e_->alloc(n * sizeof(T), 64);
    if (!addr_) throw std::runtime_error("device memory exhausted");
    if (p2p_) {
      // FPGABufferP2P analog (fpgabufferp2p.hpp): the buffer is a
      // registered peer-writable window and the host view is a direct
      // MAPPING of devicemem (bo.map) — no staging vector, syncs are
      // no-ops, and an in-process peer's rendezvous write lands in it
      // by direct memcpy, bypassing the wire.
      e_->register_p2p(addr_, n * sizeof(T));
      mapped_ = reinterpret_cast<T*>(e_->raw_mem(addr_, n * sizeof(T)));
      if (!mapped_) throw std::runtime_error("p2p mapping failed");
    } else {
      host_.resize(n);
    }
  }
  ~Buffer() {
    if (addr_) {
      if (p2p_) e_->unregister_p2p(addr_);
      e_->free_addr(addr_);
    }
  }
  Buffer(const Buffer&) = delete;
  Buffer& operator=(const Buffer&) = delete;

  T* data() { return p2p_ ? mapped_ : host_.data(); }
  const T* data() const { return p2p_ ? mapped_ : host_.data(); }
  T& operator[](size_t i) { return data()[i]; }
  uint64_t length() const { return n_; }
  uint64_t address() const { return addr_; }
  DType dtype() const { return dtype_; }
  bool is_host_only() const { return host_only_; }
  bool is_p2p() const { return p2p_; }

  void sync_to_device() {
    if (!p2p_) e_->write_mem(addr_, host_.data(), n_ * sizeof(T));
  }
  void sync_from_device() {
    if (!p2p_) e_->read_mem(addr_, host_.data(), n_ * sizeof(T));
  }

 private:
  Engine* e_;
  uint64_t n_, addr_ = 0;
  DType dtype_;
  bool host_only_ = false;
  bool p2p_ = false;
  T* mapped_ = nullptr;
  std::vector<T> host_;
};

// One operand of a call: address + dtype + presence (the triple the
// reference's prepare_call consumes per operand, accl.cpp:1259-1281).
struct Operand {
  uint64_t addr = 0;
  DType dtype = DType::none;
  bool present = false;
  bool host = false;  // host-resident (OP0/OP1/RES_HOST flags)

  Operand() = default;
  template <typename T>
  Operand(Buffer<T>& b)
      : addr(b.address()), dtype(b.dtype()), present(true),
        host(b.is_host_only()) {}
  // absent operand carrying only a dtype hint (data_type_io_*)
  static Operand hint(DType d) {
    Operand o;
    o.dtype = d;
    return o;
  }
};

// Async request handle (reference: ACCLRequest, accl.hpp:60-75).
class Request {
 public:
  Request(Engine* e, uint64_t id) : e_(e), id_(id) {}

  // Blocks up to timeout; returns the engine retcode.
  uint32_t wait(int timeout_ms = 60000) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    uint32_t ret = 0;
    double dur = 0;
    while (std::chrono::steady_clock::now() < deadline) {
      if (e_->poll_call(id_, &ret, &dur)) {
        duration_ns_ = dur;
        done_ = true;
        return ret;
      }
      std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
    throw std::runtime_error("collective timed out");
  }
  bool done() const { return done_; }
  double duration_ns() const { return duration_ns_; }

 private:
  Engine* e_;
  uint64_t id_;
  bool done_ = false;
  double duration_ns_ = 0;
};

constexpr uint32_t STREAM_NONE = 0;
constexpr uint32_t OP0_STREAM_F = 1;
constexpr uint32_t RES_STREAM_F = 2;

// One rank's driver handle.
class ACCL {
 public:
  explicit ACCL(Engine* engine) : e_(engine) {}

  // Bring-up (reference initialize(), accl.cpp:1082-1130): rx pool,
  // communicator, the full default arithcfg table (arithconfig.hpp:
  // 106-119 + the TPU-native bf16 pair), thresholds, enable.
  void initialize(const std::vector<uint32_t>& sessions, uint32_t local_rank,
                  uint32_t n_rx_bufs = 16, uint64_t rx_buf_size = 1024,
                  uint64_t max_eager = 0, uint64_t max_rndzv = 64ull << 20) {
    config(CfgFunc::ResetPeriph, 0);
    e_->cfg_rx_buffers(n_rx_bufs, rx_buf_size);
    comm_ = upload_comm(sessions, local_rank, rx_buf_size);
    comm_sizes_[comm_] = uint32_t(sessions.size());
    upload_default_arithcfgs();
    set_timeout(1'000'000);
    set_max_eager_msg_size(uint32_t(max_eager ? max_eager : rx_buf_size));
    set_max_rendezvous_msg_size(uint32_t(max_rndzv));
    // flat-tree tuning registers (reference configure_tuning_parameters,
    // accl.cpp:1214-1224)
    e_->set_tuning(Engine::GATHER_FLAT_TREE_MAX_FANIN, 2);
    e_->set_tuning(Engine::GATHER_FLAT_TREE_MAX_COUNT, 32 * 1024);
    e_->set_tuning(Engine::BCAST_FLAT_TREE_MAX_RANKS, 3);
    e_->set_tuning(Engine::REDUCE_FLAT_TREE_MAX_RANKS, 4);
    e_->set_tuning(Engine::REDUCE_FLAT_TREE_MAX_COUNT,
                   uint32_t(std::min<uint64_t>(max_rndzv / 4, 32 * 1024)));
    config(CfgFunc::EnablePkt, 0);
    world_ = uint32_t(sessions.size());
    rank_ = local_rank;
    rx_buf_size_ = rx_buf_size;
  }

  uint32_t rank() const { return rank_; }
  uint32_t world() const { return world_; }
  Engine* engine() { return e_; }
  int global_comm() const { return comm_; }
  uint32_t comm_size(int comm_id) const {
    auto it = comm_sizes_.find(comm_id);
    return it == comm_sizes_.end() ? 0 : it->second;
  }

  // Sub-communicator from global session ids (reference:
  // accl.cpp:971-978); collective + order-sensitive across members.
  int create_communicator(const std::vector<uint32_t>& members) {
    uint32_t local = 0;
    bool found = false;
    for (uint32_t i = 0; i < members.size(); ++i)
      if (members[i] == rank_) {
        local = i;
        found = true;
      }
    if (!found)
      throw std::runtime_error("create_communicator: caller not a member");
    int id = upload_comm(members, local, rx_buf_size_);
    comm_sizes_[id] = uint32_t(members.size());
    return id;
  }

  template <typename T>
  std::unique_ptr<Buffer<T>> create_buffer(uint64_t n,
                                           DType dt = dtype_of<T>::value) {
    return std::make_unique<Buffer<T>>(e_, n, dt);
  }

  // host-resident buffer (reference create_buffer host-only variants;
  // the engine reaches it over the host path, external_dma analog)
  template <typename T>
  std::unique_ptr<Buffer<T>> create_buffer_host(
      uint64_t n, DType dt = dtype_of<T>::value) {
    return std::make_unique<Buffer<T>>(e_, n, dt, /*host_only=*/true);
  }

  // p2p buffer (reference create_buffer_p2p, accl.hpp + fpgabufferp2p
  // .hpp): zero-copy host mapping + peer-writable window — a peer's
  // rendezvous one-sided write bypasses the wire in shared-address
  // worlds
  template <typename T>
  std::unique_ptr<Buffer<T>> create_buffer_p2p(
      uint64_t n, DType dt = dtype_of<T>::value) {
    return std::make_unique<Buffer<T>>(e_, n, dt, /*host_only=*/false,
                                       /*p2p=*/true);
  }

  // ---- explicit session lifecycle (reference open_port/open_con/
  // close_con, accl.hpp:1069-1083 over tcp_session_handler): session
  // transports really connect/tear down with surfaced errors;
  // connectionless rungs succeed as no-ops. ----
  void open_port() {
    if (e_->open_port() != 0)
      throw std::runtime_error("open_port failed: transport not listening");
  }
  void open_con(int comm_id = -1) {
    int rc = e_->open_con(uint32_t(comm_id < 0 ? comm_ : comm_id));
    if (rc > 0)
      throw std::runtime_error("open_con failed: no session to peer " +
                               std::to_string(rc - 1));
    if (rc < 0) throw std::runtime_error("open_con: unknown communicator");
  }
  void close_con(int comm_id = -1) {
    if (e_->close_con(uint32_t(comm_id < 0 ? comm_ : comm_id)) < 0)
      throw std::runtime_error("close_con: unknown communicator");
  }

  void check(uint32_t ret) {
    if (ret != 0)
      throw std::runtime_error("collective failed, retcode=" +
                               std::to_string(ret));
  }

  // synchronous completion: wait, record the engine perf counter
  // (reference get_duration, accl.cpp:1387), check the retcode
  void run_sync(Request&& r) {
    uint32_t ret = r.wait();
    last_duration_ns_ = r.duration_ns();
    check(ret);
  }

  // ---- compute-kernel streams (PL-kernel ports) ----
  void push_krnl(const void* data, uint64_t nbytes) {
    e_->push_krnl(static_cast<const uint8_t*>(data), nbytes);
  }
  bool pop_stream(uint32_t strm, void* dst, uint64_t cap, uint64_t* got,
                  int timeout_ms = 10000) {
    return e_->pop_stream(strm, static_cast<uint8_t*>(dst), cap, got,
                          timeout_ms);
  }

  // ---- collectives (reference accl.cpp entry points; each has a
  //      synchronous form and an *_async form returning a Request) ----

  Request send_async(Operand src, uint32_t count, uint32_t dst, uint32_t tag,
                     int comm_id = -1, DType compress = DType::none,
                     uint32_t stream = STREAM_NONE) {
    return start(Op::Send, count, cid(comm_id), dst, 0, tag, src, {},
                 Operand::hint(src.dtype), stream, compress);
  }
  template <typename T>
  void send(Buffer<T>& b, uint32_t count, uint32_t dst, uint32_t tag,
            int comm_id = -1, DType compress = DType::none) {
    b.sync_to_device();
    run_sync(send_async(Operand(b), count, dst, tag, comm_id, compress));
  }

  Request recv_async(Operand dst_o, uint32_t count, uint32_t src,
                     uint32_t tag, int comm_id = -1,
                     DType compress = DType::none,
                     uint32_t stream = STREAM_NONE) {
    return start(Op::Recv, count, cid(comm_id), src, 0, tag,
                 Operand::hint(dst_o.dtype), {}, dst_o, stream, compress);
  }
  template <typename T>
  void recv(Buffer<T>& b, uint32_t count, uint32_t src, uint32_t tag,
            int comm_id = -1, DType compress = DType::none) {
    run_sync(recv_async(Operand(b), count, src, tag, comm_id, compress));
    b.sync_from_device();
  }

  // send into a remote compute stream (reference stream_put,
  // accl.cpp:191-250; stream ids < 9 are reserved, accl.cpp:197)
  template <typename T>
  void stream_put(Buffer<T>& b, uint32_t count, uint32_t dst,
                  uint32_t stream_id, int comm_id = -1) {
    if (stream_id < 9) throw std::runtime_error("stream ids < 9 reserved");
    b.sync_to_device();
    run_sync(start(Op::Send, count, cid(comm_id), dst, 0, stream_id, Operand(b),
                {}, Operand::hint(b.dtype()), RES_STREAM_F, DType::none));
  }

  template <typename TS, typename TD>
  void copy(Buffer<TS>& src, Buffer<TD>& dst, uint32_t count) {
    src.sync_to_device();
    run_sync(start(Op::Copy, count, comm_, 0, 0, TAG_ANY, Operand(src), {},
                Operand(dst), STREAM_NONE, DType::none));
    dst.sync_from_device();
  }

  template <typename T>
  void copy_to_stream(Buffer<T>& src, uint32_t count, uint32_t stream_id) {
    if (stream_id < 9) throw std::runtime_error("stream ids < 9 reserved");
    src.sync_to_device();
    run_sync(start(Op::Copy, count, comm_, 0, 0, stream_id, Operand(src), {},
                Operand::hint(src.dtype()), RES_STREAM_F, DType::none));
  }

  template <typename T>
  void copy_from_stream(Buffer<T>& dst, uint32_t count) {
    run_sync(start(Op::Copy, count, comm_, 0, 0, TAG_ANY,
                Operand::hint(dst.dtype()), {}, Operand(dst), OP0_STREAM_F,
                DType::none));
    dst.sync_from_device();
  }

  template <typename TA, typename TB, typename TR>
  void combine(uint32_t count, Reduce fn, Buffer<TA>& a, Buffer<TB>& b,
               Buffer<TR>& r) {
    a.sync_to_device();
    b.sync_to_device();
    run_sync(start(Op::Combine, count, comm_, 0, uint32_t(fn), TAG_ANY,
                Operand(a), Operand(b), Operand(r), STREAM_NONE, DType::none));
    r.sync_from_device();
  }

  template <typename T>
  void bcast(Buffer<T>& b, uint32_t count, uint32_t root, int comm_id = -1,
             DType compress = DType::none) {
    int cm = cid(comm_id);
    if (local_rank(cm) == root) {
      b.sync_to_device();
      run_sync(start(Op::Bcast, count, cm, root, 0, TAG_ANY, Operand(b), {},
                  Operand::hint(b.dtype()), STREAM_NONE, compress));
    } else {
      run_sync(start(Op::Bcast, count, cm, root, 0, TAG_ANY,
                  Operand::hint(b.dtype()), {}, Operand(b), STREAM_NONE,
                  compress));
      b.sync_from_device();
    }
  }

  template <typename TS, typename TD>
  void scatter(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
               uint32_t root, int comm_id = -1,
               DType compress = DType::none) {
    int cm = cid(comm_id);
    bool is_root = local_rank(cm) == root;
    if (is_root) sendb.sync_to_device();
    run_sync(start(Op::Scatter, count, cm, root, 0, TAG_ANY,
                is_root ? Operand(sendb) : Operand::hint(sendb.dtype()), {},
                Operand(recvb), STREAM_NONE, compress));
    recvb.sync_from_device();
  }

  template <typename TS, typename TD>
  void gather(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
              uint32_t root, int comm_id = -1, DType compress = DType::none) {
    int cm = cid(comm_id);
    bool is_root = local_rank(cm) == root;
    sendb.sync_to_device();
    run_sync(start(Op::Gather, count, cm, root, 0, TAG_ANY, Operand(sendb), {},
                is_root ? Operand(recvb) : Operand::hint(recvb.dtype()),
                STREAM_NONE, compress));
    if (is_root) recvb.sync_from_device();
  }

  template <typename TS, typename TD>
  void allgather(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
                 int comm_id = -1, DType compress = DType::none) {
    sendb.sync_to_device();
    run_sync(start(Op::Allgather, count, cid(comm_id), 0, 0, TAG_ANY,
                Operand(sendb), {}, Operand(recvb), STREAM_NONE, compress));
    recvb.sync_from_device();
  }

  template <typename TS, typename TD>
  void reduce(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
              uint32_t root, Reduce fn = Reduce::SUM, int comm_id = -1,
              DType compress = DType::none) {
    int cm = cid(comm_id);
    bool is_root = local_rank(cm) == root;
    sendb.sync_to_device();
    run_sync(start(Op::Reduce, count, cm, root, uint32_t(fn), TAG_ANY,
                Operand(sendb), {},
                is_root ? Operand(recvb) : Operand::hint(recvb.dtype()),
                STREAM_NONE, compress));
    if (is_root) recvb.sync_from_device();
  }

  // streamed-operand reduce (reference test_reduce_stream2mem,
  // test.cpp:813-843): feed `count` elements via push_krnl first
  template <typename TD>
  void reduce_stream2mem(Buffer<TD>& recvb, uint32_t count, uint32_t root,
                         Reduce fn = Reduce::SUM, int comm_id = -1) {
    int cm = cid(comm_id);
    bool is_root = local_rank(cm) == root;
    run_sync(start(Op::Reduce, count, cm, root, uint32_t(fn), TAG_ANY,
                Operand::hint(recvb.dtype()), {},
                is_root ? Operand(recvb) : Operand::hint(recvb.dtype()),
                OP0_STREAM_F, DType::none));
    if (is_root) recvb.sync_from_device();
  }

  // streamed-result reduce (reference test_reduce_mem2stream,
  // test.cpp:844-876): root pops the result from stream `stream_id`
  template <typename TS>
  void reduce_mem2stream(Buffer<TS>& sendb, uint32_t count, uint32_t root,
                         uint32_t stream_id, Reduce fn = Reduce::SUM,
                         int comm_id = -1) {
    if (stream_id < 9) throw std::runtime_error("stream ids < 9 reserved");
    sendb.sync_to_device();
    run_sync(start(Op::Reduce, count, cid(comm_id), root, uint32_t(fn),
                stream_id, Operand(sendb), {},
                Operand::hint(sendb.dtype()), RES_STREAM_F, DType::none));
  }

  template <typename TS, typename TD>
  void allreduce(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
                 Reduce fn = Reduce::SUM, int comm_id = -1,
                 DType compress = DType::none) {
    sendb.sync_to_device();
    run_sync(start(Op::Allreduce, count, cid(comm_id), 0, uint32_t(fn), TAG_ANY,
                Operand(sendb), {}, Operand(recvb), STREAM_NONE, compress));
    recvb.sync_from_device();
  }

  template <typename TS, typename TD>
  void reduce_scatter(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
                      Reduce fn = Reduce::SUM, int comm_id = -1,
                      DType compress = DType::none) {
    sendb.sync_to_device();
    run_sync(start(Op::ReduceScatter, count, cid(comm_id), 0, uint32_t(fn),
                TAG_ANY, Operand(sendb), {}, Operand(recvb), STREAM_NONE,
                compress));
    recvb.sync_from_device();
  }

  template <typename TS, typename TD>
  void alltoall(Buffer<TS>& sendb, Buffer<TD>& recvb, uint32_t count,
                int comm_id = -1) {
    sendb.sync_to_device();
    run_sync(start(Op::Alltoall, count, cid(comm_id), 0, 0, TAG_ANY,
                Operand(sendb), {}, Operand(recvb), STREAM_NONE, DType::none));
    recvb.sync_from_device();
  }

  void barrier(int comm_id = -1) {
    run_sync(start(Op::Barrier, 0, cid(comm_id), 0, 0, TAG_ANY, {}, {}, {},
                STREAM_NONE, DType::none));
  }

  void nop() {
    run_sync(start(Op::Nop, 0, comm_, 0, 0, TAG_ANY, {}, {}, {}, STREAM_NONE,
                DType::none));
  }

  double last_duration_ns() const { return last_duration_ns_; }

  // ---- call marshaling (reference prepare_call, accl.cpp:1252-1372) ----
  Request start(Op op, uint32_t count, int comm_id, uint32_t root,
                uint32_t func, uint32_t tag, Operand op0, Operand op1,
                Operand res, uint32_t stream_flags, DType compress) {
    // validate rooted calls against the communicator size (the engine
    // would otherwise index past its rank table)
    switch (op) {
      case Op::Send: case Op::Recv: case Op::Bcast: case Op::Scatter:
      case Op::Gather: case Op::Reduce: {
        uint32_t sz = comm_size(comm_id);
        if (sz && root >= sz)
          throw std::runtime_error("root/peer out of range for communicator");
        break;
      }
      default:
        break;
    }
    // dtype set across operands (+ hints for absent ones)
    DType dts[3] = {op0.dtype, op1.dtype, res.dtype};
    DType a = DType::none, b = DType::none;
    for (DType d : dts) {
      if (d == DType::none) continue;
      if (a == DType::none || d == a) {
        a = d;
      } else if (b == DType::none || d == b) {
        b = d;
      } else {
        throw std::runtime_error("unsupported dtype combination");
      }
    }
    if (a == DType::none) a = DType::f32;

    uint32_t flags = 0;  // compression flags word
    int arith = 0;
    if (compress == DType::none) {
      if (b == DType::none) {
        arith = arith_id(a, a, op);
      } else {
        // operand compression: narrower dtype is the compressed side
        DType u = dtype_bits(a) >= dtype_bits(b) ? a : b;
        DType c = u == a ? b : a;
        arith = arith_id(u, c, op);
        flags = operand_flags(op0, op1, res, c);
      }
    } else {
      DType u = a;
      if (b != DType::none) {
        if (a == compress) u = b;
        else if (b == compress) u = a;
        else throw std::runtime_error("unsupported dtype combination");
      }
      if (u == compress) {
        arith = arith_id(u, u, op);
        // ETH on an identity pair: ratio-0 no-op, kept for ABI fidelity
        flags = ETH_COMPRESSED;
      } else {
        arith = arith_id(u, compress, op);
        flags = ETH_COMPRESSED | operand_flags(op0, op1, res, compress);
      }
    }

    uint32_t host_flags = (op0.present && op0.host ? 1u : 0u) |
                          (op1.present && op1.host ? 2u : 0u) |
                          (res.present && res.host ? 4u : 0u);
    std::array<uint32_t, 15> w{};
    w[0] = uint32_t(op);
    w[1] = count;
    w[2] = uint32_t(comm_id);
    w[3] = root;
    w[4] = func;
    w[5] = tag;
    w[6] = uint32_t(arith);
    w[7] = flags;
    w[8] = stream_flags | (host_flags << 8);
    w[9] = uint32_t(op0.addr);
    w[10] = uint32_t(op0.addr >> 32);
    w[11] = uint32_t(op1.addr);
    w[12] = uint32_t(op1.addr >> 32);
    w[13] = uint32_t(res.addr);
    w[14] = uint32_t(res.addr >> 32);
    return Request(e_, e_->start_call(w.data()));
  }

  // Runtime config knobs (reference set_timeout / set_max_eager_msg_size /
  // set_max_rendezvous_msg_size, accl.cpp:1112-1120, :1415-1433 — note the
  // reference's rendezvous setter bugs are NOT reproduced here).
  void set_timeout(uint32_t cycles) { config(CfgFunc::SetTimeout, cycles); }
  void set_max_eager_msg_size(uint32_t bytes) {
    config(CfgFunc::SetMaxEagerMsgSize, bytes);
  }
  void set_max_rendezvous_msg_size(uint32_t bytes) {
    config(CfgFunc::SetMaxRendezvousMsgSize, bytes);
  }

 private:
  int cid(int comm_id) const { return comm_id < 0 ? comm_ : comm_id; }

  uint32_t local_rank(int comm_id) const {
    auto it = comm_locals_.find(comm_id);
    return it == comm_locals_.end() ? rank_ : it->second;
  }

  static uint32_t operand_flags(const Operand& op0, const Operand& op1,
                                const Operand& res, DType compressed) {
    uint32_t f = 0;
    if (op0.present && op0.dtype == compressed) f |= OP0_COMPRESSED;
    if (op1.present && op1.dtype == compressed) f |= OP1_COMPRESSED;
    if (res.present && res.dtype == compressed) f |= RES_COMPRESSED;
    return f;
  }

  int upload_comm(const std::vector<uint32_t>& sessions, uint32_t local,
                  uint64_t rx_buf_size) {
    std::vector<uint32_t> words{uint32_t(sessions.size()), local};
    for (uint32_t s : sessions) {
      words.push_back(0);                      // ip (unused in-proc)
      words.push_back(0);                      // port
      words.push_back(s);                      // session = global rank
      words.push_back(uint32_t(rx_buf_size));  // max segment
    }
    int id = e_->set_comm(words.data(), int(words.size()));
    comm_locals_[id] = local;
    return id;
  }

  int arith_id(DType u, DType c, Op op) const {
    auto it = arith_ids_.find({u, c});
    if (it == arith_ids_.end()) {
      if (op == Op::Barrier || op == Op::Nop) return 0;
      throw std::runtime_error("no arithmetic config for dtype pair");
    }
    return it->second;
  }

  // mirror of accl_tpu/arithconfig.py DEFAULT_ARITH_CONFIG: identity
  // pairs + the (f32,f16) mixed-precision pair (arith in the compressed
  // domain, reference arithconfig.hpp:106-119) + TPU-native (f32,bf16)
  void upload_default_arithcfgs() {
    auto up = [&](DType u, DType c, uint32_t comp, uint32_t decomp,
                  bool arith_comp, uint32_t lane_sum, uint32_t lane_max) {
      uint32_t ratio = 0;
      if (dtype_bits(c) && dtype_bits(u) > dtype_bits(c))
        ratio = dtype_bits(u) / dtype_bits(c) == 2 ? 1 : 2;
      std::vector<uint32_t> a{dtype_bits(u), dtype_bits(c), ratio, comp,
                              decomp, uint32_t(arith_comp), 2, lane_sum,
                              lane_max};
      arith_ids_[{u, c}] = e_->set_arithcfg(a.data(), int(a.size()));
    };
    up(DType::f16, DType::f16, 0, 0, false, F16_SUM, F16_MAX);
    up(DType::bf16, DType::bf16, 0, 0, false, BF16_SUM, BF16_MAX);
    up(DType::f32, DType::f32, 0, 0, false, F32_SUM, F32_MAX);
    up(DType::f64, DType::f64, 0, 0, false, F64_SUM, F64_MAX);
    up(DType::i32, DType::i32, 0, 0, false, I32_SUM, I32_MAX);
    up(DType::i64, DType::i64, 0, 0, false, I64_SUM, I64_MAX);
    up(DType::f32, DType::f16, 0, 1, true, F16_SUM, F16_MAX);
    up(DType::f32, DType::bf16, 2, 3, true, BF16_SUM, BF16_MAX);
  }

  void config(CfgFunc f, uint32_t value) {
    std::array<uint32_t, 15> w{};
    w[0] = uint32_t(Op::Config);
    w[1] = value;
    w[4] = uint32_t(f);
    Request r(e_, e_->start_call(w.data()));
    uint32_t ret = r.wait();
    check(ret);
  }

  Engine* e_;
  int comm_ = 0;
  uint32_t rank_ = 0, world_ = 1;
  uint64_t rx_buf_size_ = 1024;
  std::map<std::pair<DType, DType>, int> arith_ids_;
  std::map<int, uint32_t> comm_sizes_;
  std::map<int, uint32_t> comm_locals_;
  double last_duration_ns_ = 0;
};

}  // namespace host
}  // namespace accl
