// Deterministic schedule-exploration driver (ACCL_DETSCHED builds).
//
// CLI over the drills in detsched_drills.hpp and the explorer in
// src/detsched.hpp; scripts/model_check.py is the orchestration layer
// (build, sweep, artifacts, CI budgets).  One JSON result line per
// invocation on stdout — everything else goes to stderr.
//
//   --drill NAME            which drill (see --list)
//   --explore N             bounded exploration, at most N schedules
//   --schedule HEX          run exactly one schedule (artifact replay)
//   --seed S                default-policy seed (part of the artifact)
//   --pbound K              preemption bound (default 3)
//   --ibound K              timeout injections per run (default 0 = off;
//                           part of the artifact — replay needs the same
//                           value or the decision spaces misalign)
//   --explore-from HEX      trace-guided: replay this observed prefix
//                           bit-for-bit, explore only the suffix
//   --max-steps N           per-run scheduling-step budget
//   --budget-s S            wall-clock budget for the exploration
//   --expect-finding        exit 0 iff a finding WAS discovered
//                           (sensitivity runs under the fault build)
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "detsched_drills.hpp"

using accl::det::ExploreOpts;
using accl::det::ExploreStats;
using accl::det::RunResult;
using accl::det::Sched;

static std::string hex_encode(const std::vector<uint8_t>& v) {
  static const char* d = "0123456789abcdef";
  std::string out;
  out.reserve(v.size() * 2);
  for (uint8_t b : v) {
    out.push_back(d[b >> 4]);
    out.push_back(d[b & 15]);
  }
  return out;
}

static std::vector<uint8_t> hex_decode(const std::string& s) {
  std::vector<uint8_t> out;
  for (size_t i = 0; i + 1 < s.size(); i += 2)
    out.push_back(uint8_t(std::stoul(s.substr(i, 2), nullptr, 16)));
  return out;
}

static std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') out.push_back('\\');
    if (c == '\n') {
      out += "\\n";
      continue;
    }
    out.push_back(c);
  }
  return out;
}

int main(int argc, char** argv) {
  std::string drill, schedule_hex;
  ExploreOpts opts;
  bool expect_finding = false, do_explore = false, do_replay = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "missing value for %s\n", a.c_str());
        std::exit(2);
      }
      return argv[++i];
    };
    if (a == "--drill") {
      drill = next();
    } else if (a == "--explore") {
      do_explore = true;
      opts.max_runs = std::strtoull(next(), nullptr, 10);
    } else if (a == "--schedule") {
      do_replay = true;
      schedule_hex = next();
    } else if (a == "--seed") {
      opts.seed = std::strtoull(next(), nullptr, 10);
    } else if (a == "--pbound") {
      opts.preempt_bound = std::atoi(next());
    } else if (a == "--ibound") {
      opts.inject_bound = std::atoi(next());
    } else if (a == "--explore-from") {
      opts.seed_prefix = hex_decode(next());
    } else if (a == "--max-steps") {
      opts.max_steps = std::strtoull(next(), nullptr, 10);
    } else if (a == "--budget-s") {
      opts.budget_s = std::atof(next());
    } else if (a == "--expect-finding") {
      expect_finding = true;
    } else if (a == "--list") {
      for (const auto& [name, fn] : accl::drills::registry()) {
        (void)fn;
        std::printf("%s\n", name.c_str());
      }
      return 0;
    } else {
      std::fprintf(stderr, "unknown arg %s\n", a.c_str());
      return 2;
    }
  }
  const auto& reg = accl::drills::registry();
  auto it = reg.find(drill);
  if (it == reg.end()) {
    std::fprintf(stderr, "unknown drill '%s' (see --list)\n", drill.c_str());
    return 2;
  }
  const auto& fn = it->second;

  if (do_replay) {
    Sched::inst().preempt_bound = opts.preempt_bound;
    Sched::inst().branch_depth = opts.branch_depth;
    Sched::inst().inject_bound = opts.inject_bound;
    RunResult r =
        Sched::inst().run(hex_decode(schedule_hex), opts.seed, opts.max_steps, fn);
    std::printf(
        "{\"drill\":\"%s\",\"mode\":\"replay\",\"failed\":%s,"
        "\"what\":\"%s\",\"steps\":%llu,\"seed\":%llu,\"ibound\":%d,"
        "\"injections\":%llu,\"pressure_events\":%llu}\n",
        drill.c_str(), r.failed ? "true" : "false",
        json_escape(r.what).c_str(), (unsigned long long)r.steps,
        (unsigned long long)opts.seed, opts.inject_bound,
        (unsigned long long)r.injections,
        (unsigned long long)r.pressure_events);
    bool as_expected = expect_finding ? r.failed : !r.failed;
    return as_expected ? 0 : 1;
  }

  if (!do_explore) opts.max_runs = 1;
  opts.stop_on_first = true;
  ExploreStats st = accl::det::explore(fn, opts);
  std::printf(
      "{\"drill\":\"%s\",\"mode\":\"explore\",\"runs\":%llu,"
      "\"unique_traces\":%llu,\"findings\":%llu,\"what\":\"%s\","
      "\"fail_step\":%llu,\"prefix_hex\":\"%s\",\"trace_hex\":\"%s\","
      "\"seed\":%llu,\"pbound\":%d,\"ibound\":%d,\"injected_runs\":%llu,"
      "\"pressure_events\":%llu,\"max_steps\":%llu}\n",
      drill.c_str(), (unsigned long long)st.runs,
      (unsigned long long)st.unique_traces, (unsigned long long)st.findings,
      json_escape(st.first_failure.what).c_str(),
      (unsigned long long)st.first_failure.fail_step,
      hex_encode(st.first_failure_prefix).c_str(),
      hex_encode(st.first_failure.choices).c_str(),
      (unsigned long long)st.seed, opts.preempt_bound, opts.inject_bound,
      (unsigned long long)st.injected_runs,
      (unsigned long long)st.pressure_events,
      (unsigned long long)opts.max_steps);
  bool as_expected = expect_finding ? st.findings > 0 : st.findings == 0;
  return as_expected ? 0 : 1;
}
