// Deterministic-schedule drills for the native engine model checker.
//
// Each drill is a self-contained, re-runnable scenario: it builds a
// tiny in-process world INSIDE the controlled run (so every engine
// thread is serialized from its first instruction), races two
// engine-lifecycle operations against live traffic, asserts the
// drill's invariants through det::expect on EVERY explored schedule,
// and tears the world down before returning.  The explorer
// (test_detsched.cpp, driven by scripts/model_check.py) re-runs a
// drill under thousands of schedules; a failing schedule is minimized
// and dumped as a replayable hex artifact.
//
// Drills (ISSUE r14 / ROADMAP item 5's verification gate):
//   replay_vs_invalidate — persistent-plan replay racing abort/fence
//   abort_vs_traffic     — ACCL.abort racing an in-flight send/recv
//   join_vs_traffic      — elastic join racing live traffic
//   shutdown_vs_waiters  — two-phase shutdown racing blocked receivers
//   detach_race          — InprocHub::detach vs a mid-flight delivery
//                          (sensitivity drill: the ACCL_FAULT_DETACH_RACE
//                          build reverts the r13 drain and the checker
//                          must REDISCOVER the race)
#pragma once

#if !defined(ACCL_DETSCHED)
#error "detsched_drills.hpp requires an ACCL_DETSCHED build"
#endif

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "../src/engine.hpp"

namespace accl {
namespace drills {

// ---- tiny world builder -------------------------------------------------
// Drill rx pools are deliberately SMALL (default 4 x 256 B) so real
// multi-segment payloads exhaust them inside explored schedules —
// resource pressure is modeled state, not an accident of sizing.
// Overridable per invocation for exhaustion-gradient experiments.
inline uint32_t env_u32(const char* key, uint32_t dflt) {
  const char* v = std::getenv(key);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  unsigned long x = std::strtoul(v, &end, 10);
  return (end && *end == '\0' && x > 0) ? uint32_t(x) : dflt;
}

struct DetWorld {
  std::shared_ptr<InprocHub> hub;
  std::vector<std::unique_ptr<Engine>> eng;

  explicit DetWorld(int nranks, uint64_t devmem = 1 << 20) {
    hub = std::make_shared<InprocHub>(nranks);
    for (int r = 0; r < nranks; ++r)
      eng.push_back(std::make_unique<Engine>(
          uint32_t(r), devmem, std::make_unique<InprocTransport>(hub, r)));
    for (int r = 0; r < nranks; ++r) {
      eng[size_t(r)]->cfg_rx_buffers(env_u32("ACCL_DETSCHED_RX_BUFS", 4),
                                     env_u32("ACCL_DETSCHED_RX_BUFSZ", 256));
      setup_comm(r, nranks);
      setup_arith(r);
    }
  }

  // comm 0 over every rank; session id == global rank (inproc scheme)
  void setup_comm(int r, int nranks) {
    std::vector<uint32_t> w{uint32_t(nranks), uint32_t(r)};
    for (int i = 0; i < nranks; ++i) {
      w.push_back(0);             // ip
      w.push_back(0);             // port
      w.push_back(uint32_t(i));   // session
      w.push_back(0);             // max_seg (rx buffer default)
    }
    eng[size_t(r)]->set_comm(w.data(), int(w.size()));
  }

  // plain f32, no compression, copy-only lanes (drills move bytes and
  // synchronize; they never reduce)
  void setup_arith(int r) {
    uint32_t w[7] = {32, 32, 0, 0, 0, 0, 0};
    eng[size_t(r)]->set_arithcfg(w, 7);
  }

  // 15-word descriptors ---------------------------------------------------
  static std::array<uint32_t, 15> desc(Op op, uint32_t count, uint32_t comm,
                                       uint32_t peer, uint32_t tag,
                                       uint64_t addr0, uint64_t addr2) {
    std::array<uint32_t, 15> w{};
    w[0] = uint32_t(op);
    w[1] = count;
    w[2] = comm;
    w[3] = peer;
    w[5] = tag;
    w[9] = uint32_t(addr0 & 0xFFFFFFFFu);
    w[10] = uint32_t(addr0 >> 32);
    w[13] = uint32_t(addr2 & 0xFFFFFFFFu);
    w[14] = uint32_t(addr2 >> 32);
    return w;
  }

  // poll a call to completion on the virtual clock; returns retcode.
  // A schedule where the call never finishes surfaces as a det
  // deadlock/step-budget finding, not a harness hang.
  uint32_t wait_call(int r, uint64_t id, const char* what) {
    uint32_t ret = 0;
    double dur = 0;
    for (int i = 0; i < 200000; ++i) {
      if (eng[size_t(r)]->poll_call(id, &ret, &dur)) return ret;
      det_sleep_for(std::chrono::microseconds(200));
    }
    det::expect(false, what);
    return ret;
  }
};

// mask of bits a call may legally carry after a mid-flight abort
inline bool ok_or_aborted(uint32_t ret) {
  if (ret == 0) return true;
  constexpr uint32_t fence = COMM_ABORTED | RANK_FAILED;
  // once fenced, timeout/seq classification noise from the dying epoch
  // may accompany the fence bits, but the fence itself must be there
  return (ret & fence) != 0;
}

// ---- drill: persistent-plan replay vs invalidate ------------------------
// Both ranks arm a one-call Barrier plan, prove one clean replay, then
// rank 0's replay races an abort of the underlying comm.  Invariants:
// a replay ticket either completes (clean epoch, ret==0 or abort bits)
// or the replay is refused with -2; after the fence settles a fresh
// replay MUST be refused — no schedule may let a fenced epoch replay.
inline void drill_replay_vs_invalidate() {
  DetWorld w(2);
  std::vector<long long> tok(2);
  std::vector<int> plan(2);
  for (int r = 0; r < 2; ++r) {
    auto d = DetWorld::desc(Op::Barrier, 0, 0, 0, 0, 0, 0);
    plan[size_t(r)] = w.eng[size_t(r)]->plan_create(d.data(), 1);
    det::expect(plan[size_t(r)] == 0, "plan_create failed");
  }
  // round 1: clean replay on both ranks
  for (int r = 0; r < 2; ++r) tok[size_t(r)] = w.eng[size_t(r)]->plan_replay(plan[size_t(r)]);
  for (int r = 0; r < 2; ++r) {
    det::expect(tok[size_t(r)] > 0, "clean replay refused");
    uint32_t ret = 1;
    double dur = 0;
    for (int i = 0; i < 200000; ++i) {
      int rc = w.eng[size_t(r)]->plan_poll(tok[size_t(r)], &ret, &dur);
      if (rc == 1) break;
      det::expect(rc == 0, "clean replay token vanished");
      det_sleep_for(std::chrono::microseconds(200));
    }
    det::expect(ret == 0, "clean barrier replay returned error bits");
  }
  // round 2: replays race an abort
  Thread aborter([&] { w.eng[0]->abort_comm(0, 0, true); });
  long long t0 = w.eng[0]->plan_replay(plan[0]);
  long long t1 = w.eng[1]->plan_replay(plan[1]);
  for (int r = 0; r < 2; ++r) {
    long long t = r == 0 ? t0 : t1;
    if (t == -2) continue;  // fenced before the replay queued: legal
    det::expect(t > 0, "raced replay returned bogus token");
    uint32_t ret = 0;
    double dur = 0;
    for (int i = 0; i < 200000; ++i) {
      int rc = w.eng[size_t(r)]->plan_poll(t, &ret, &dur);
      if (rc == 1) break;
      det::expect(rc == 0, "raced replay token vanished");
      det_sleep_for(std::chrono::microseconds(200));
    }
    det::expect(ok_or_aborted(ret), "raced replay: unexpected error bits");
  }
  aborter.join();
  // the fence has settled: a replay on the bumped epoch must refuse
  det::expect(w.eng[0]->plan_replay(plan[0]) == -2,
              "post-abort replay was NOT fenced");
  det::expect(w.eng[1]->plan_replay(plan[1]) == -2,
              "post-abort replay was NOT fenced on the peer");
}

// ---- drill: abort vs traffic --------------------------------------------
// An eager send/recv pair mid-flight while rank 0 aborts the comm.
// Invariants: both calls finalize (no orphaned waiter), and a non-zero
// retcode always carries the fence bits.
inline void drill_abort_vs_traffic() {
  DetWorld w(2);
  uint64_t src = w.eng[0]->alloc(64, 64);
  uint64_t dst = w.eng[1]->alloc(64, 64);
  float payload[8] = {1, 2, 3, 4, 5, 6, 7, 8};
  w.eng[0]->write_mem(src, payload, 32);
  auto sd = DetWorld::desc(Op::Send, 8, 0, 1, 7, src, 0);
  auto rd = DetWorld::desc(Op::Recv, 8, 0, 0, 7, 0, dst);
  uint64_t sid = w.eng[0]->start_call(sd.data());
  uint64_t rid = w.eng[1]->start_call(rd.data());
  Thread aborter([&] { w.eng[0]->abort_comm(0, 0, true); });
  uint32_t sret = w.wait_call(0, sid, "send never finalized under abort");
  uint32_t rret = w.wait_call(1, rid, "recv never finalized under abort");
  aborter.join();
  det::expect(ok_or_aborted(sret), "send retcode lost the fence bits");
  det::expect(ok_or_aborted(rret), "recv retcode lost the fence bits");
  // if the recv claims clean success, the payload must be intact
  if (rret == 0) {
    float got[8] = {0};
    w.eng[1]->read_mem(dst, got, 32);
    det::expect(std::memcmp(got, payload, 32) == 0,
                "recv returned OK but payload is corrupt");
  }
}

// ---- drill: join vs traffic ---------------------------------------------
// A third rank joins (Join/Welcome/StateSync against sponsor 0) while
// ranks 0<->1 run live traffic.  Invariants: the join completes, the
// joiner's comm-id space aligns with the sponsor's, and the racing
// traffic still completes bitwise.
inline void drill_join_vs_traffic() {
  DetWorld w(2);
  int jr = w.hub->add_rank();
  auto joiner = std::make_unique<Engine>(
      uint32_t(jr), 1 << 20,
      std::make_unique<InprocTransport>(w.hub, jr));
  uint64_t src = w.eng[0]->alloc(64, 64);
  uint64_t dst = w.eng[1]->alloc(64, 64);
  float payload[8] = {8, 7, 6, 5, 4, 3, 2, 1};
  w.eng[0]->write_mem(src, payload, 32);
  auto sd = DetWorld::desc(Op::Send, 8, 0, 1, 9, src, 0);
  auto rd = DetWorld::desc(Op::Recv, 8, 0, 0, 9, 0, dst);
  uint64_t sid = w.eng[0]->start_call(sd.data());
  uint64_t rid = w.eng[1]->start_call(rd.data());
  int join_rc = -7;
  Thread joiner_t([&] { join_rc = joiner->join_sync(0, 2000); });
  uint32_t sret = w.wait_call(0, sid, "send never finished under join");
  uint32_t rret = w.wait_call(1, rid, "recv never finished under join");
  joiner_t.join();
  det::expect(join_rc == 0, "join_sync failed against a live sponsor");
  det::expect(joiner->comm_count() == w.eng[0]->comm_count(),
              "joiner comm-id space misaligned with sponsor");
  det::expect(sret == 0 && rret == 0, "traffic failed under a live join");
  float got[8] = {0};
  w.eng[1]->read_mem(dst, got, 32);
  det::expect(std::memcmp(got, payload, 32) == 0,
              "join raced traffic into a corrupt payload");
  joiner->shutdown();
}

// ---- drill: shutdown vs blocked waiters ---------------------------------
// Rank 1 blocks in a receive that no peer will ever satisfy; rank 1's
// two-phase shutdown races it.  Invariants: shutdown returns, the
// blocked call finalizes fast with the fence bits (never left pending
// — the r13 suite-exit segfault class as a schedule invariant), and no
// delivery is mid-flight inside the engine once its transport detached.
inline void drill_shutdown_vs_waiters() {
  DetWorld w(2);
  uint64_t dst = w.eng[1]->alloc(64, 64);
  auto rd = DetWorld::desc(Op::Recv, 8, 0, 0, 5, 0, dst);
  uint64_t rid = w.eng[1]->start_call(rd.data());
  Thread stopper([&] { w.eng[1]->shutdown(); });
  uint32_t ret = 0;
  double dur = 0;
  bool done = false;
  for (int i = 0; i < 200000 && !done; ++i) {
    done = w.eng[1]->poll_call(rid, &ret, &dur);
    if (!done) det_sleep_for(std::chrono::microseconds(200));
  }
  stopper.join();
  det::expect(done, "blocked recv left pending across shutdown");
  det::expect((ret & (COMM_ABORTED | RANK_FAILED)) != 0,
              "shutdown finalized the blocked recv without fence bits");
  det::expect(w.eng[1]->ingress_depth() == 0,
              "a delivery is still inside the engine after shutdown");
}

// ---- sensitivity drill: InprocHub::detach vs a mid-flight delivery ------
// The r13 TSan finding as a model-checking invariant: after detach()
// returns, no delivery may still execute the detached slot's sink (the
// caller is about to destroy the engine behind it).  The fixed hub
// drains in-flight deliveries; the ACCL_FAULT_DETACH_RACE build skips
// the drain and the explorer must find a schedule that fires
// `delivery into detached slot`.
inline void drill_detach_race() {
  auto hub = std::make_shared<InprocHub>(2);
  std::atomic<bool> torn{false};
  hub->attach(1, [&](Message&&) {
    det::expect(!torn.load(), "delivery into detached slot");
  });
  Thread sender([&] {
    Message m;
    m.hdr.msg_type = uint8_t(MsgType::Heartbeat);
    hub->deliver(1, std::move(m));
  });
  hub->detach(1);
  torn.store(true);  // the engine behind the slot is now "destroyed"
  sender.join();
}

// ---- registry ------------------------------------------------------------
// ---- drill: concurrent sub-communicator allgathers ----------------------
// The ROADMAP item 2 KNOWN ISSUE's shape, scaled for exhaustive
// exploration: a 2x2 grid of 2-rank sub-comms (rows {0,1}/{2,3},
// columns {0,2}/{1,3}) over one 4-rank world; every rank allgathers on
// its row comm then on its column comm, so row completions on fast
// ranks overlap column starts on slow ones — the cross-comm
// concurrency the 8-rank emu wedge (intermittent RECEIVE_TIMEOUT)
// arises from, with all four comms contending for ONE small rx pool.
// Invariant: every allgather completes CLEAN on every schedule — a
// schedule that classifies a timeout/seq error is the wedge, minimized
// into a replayable artifact.
inline void subcomm_allgather_impl(int P) {
  DetWorld w(P);
  // sub-comm uploads in identical id order on every engine; ranks
  // outside a group upload an inert self-comm so engine-side comm ids
  // stay aligned with the wire protocol's (the driver's
  // reserve_communicator discipline)
  auto sub = [&](int r, const std::vector<int>& m) {
    auto it = std::find(m.begin(), m.end(), r);
    if (it == m.end()) {
      std::vector<uint32_t> ww{1, 0, 0, 0, uint32_t(r), 0};
      w.eng[size_t(r)]->set_comm(ww.data(), int(ww.size()));
      return;
    }
    std::vector<uint32_t> ww{uint32_t(m.size()),
                             uint32_t(it - m.begin())};
    for (int g : m) {
      ww.push_back(0);            // ip
      ww.push_back(0);            // port
      ww.push_back(uint32_t(g));  // session == global rank
      ww.push_back(0);            // max_seg
    }
    w.eng[size_t(r)]->set_comm(ww.data(), int(ww.size()));
  };
  // rows of width P/2, columns of height 2 — at P=8 exactly the
  // ROADMAP repro's comm family (two 4-rank rows, four 2-rank cols)
  const int W = P / 2;
  std::vector<std::vector<int>> rows(2);
  std::vector<std::vector<int>> cols(static_cast<size_t>(W));
  for (int r = 0; r < P; ++r) {
    rows[size_t(r / W)].push_back(r);
    cols[size_t(r % W)].push_back(r);
  }
  std::vector<uint32_t> row_comm(static_cast<size_t>(P), 0u);
  std::vector<uint32_t> col_comm(static_cast<size_t>(P), 0u);
  uint32_t cid = 1;
  for (auto& m : rows) {
    for (int r = 0; r < P; ++r) sub(r, m);
    for (int g : m) row_comm[size_t(g)] = cid;
    ++cid;
  }
  for (auto& m : cols) {
    for (int r = 0; r < P; ++r) sub(r, m);
    for (int g : m) col_comm[size_t(g)] = cid;
    ++cid;
  }
  // row allgather 128 elems (512 B = 2 rx segments per slice), column
  // 256 (4 segments) — the repro's small-then-large shape with real
  // multi-segment relay pressure on the 4 x 256 B rx pool ALL comms
  // share (the suspected wedge mechanism: cross-comm pool pinning)
  const uint32_t row_n = 128, col_n = 256;
  std::vector<Thread> ranks;
  for (int r = 0; r < P; ++r) {
    ranks.emplace_back(Thread([&w, &rows, &cols, &row_comm, &col_comm,
                               r, W, row_n, col_n] {
      Engine& e = *w.eng[size_t(r)];
      for (int phase = 0; phase < 2; ++phase) {
        uint32_t comm = phase == 0 ? row_comm[size_t(r)]
                                   : col_comm[size_t(r)];
        uint32_t n = phase == 0 ? row_n : col_n;
        uint32_t members = phase == 0 ? uint32_t(W) : 2u;
        uint64_t src = e.alloc(n * 4, 64);
        uint64_t dst = e.alloc(uint64_t(n) * members * 4, 64);
        auto d = DetWorld::desc(Op::Allgather, n, comm, 0, TAG_ANY,
                                src, dst);
        uint64_t id = e.start_call(d.data());
        uint32_t ret = w.wait_call(r, id, "sub-comm allgather never "
                                          "completed");
        // On a schedule with timeout injections a non-zero retcode can
        // be legitimate: an injected expiry IS a slow peer, and a
        // RECEIVE_TIMEOUT (or the cascade it triggers) is the correct
        // classification.  The wedge invariant lives below: a timeout
        // classified while the expected segment sat STAGED is never
        // legitimate, injected or not.
        det::expect(ret == 0 || det::timeout_injections() > 0,
                    phase == 0 ? "row allgather classified an error "
                                 "(the sub-comm wedge)"
                               : "column allgather classified an "
                                 "error (the sub-comm wedge)");
        e.free_addr(src);
        e.free_addr(dst);
      }
    }));
  }
  for (auto& t : ranks) t.join();
  // THE wedge invariant, schedule-independent: no rank may ever have
  // classified RECEIVE_TIMEOUT while the segment it was seeking sat in
  // the rx staging queue (cross-comm pool pinning — data arrived, the
  // pool never surfaced it).  The ACCL_FAULT_SUBCOMM_WEDGE build
  // reverts the staged-rescue fix and the explorer must REDISCOVER
  // this via a timeout injection under pool pressure.
  uint64_t wedged = 0;
  for (auto& e : w.eng) wedged += e->wedged_timeouts();
  det::expect(wedged == 0,
              "sub-comm wedge: RECEIVE_TIMEOUT classified while the "
              "expected segment sat staged (cross-comm rx-pool pinning)");
}

inline void drill_subcomm_allgather() { subcomm_allgather_impl(4); }
// the full ROADMAP repro scale (heavier per schedule — run with an
// explicit budget, not in the default --ci sweep)
inline void drill_subcomm_allgather8() { subcomm_allgather_impl(8); }

// ---- sensitivity drill: a submitted call that never finalizes -----------
// Exercises the liveness invariant directly: two workers each take a
// live token (one per "submitted call"); one finalizes, the other
// returns without handing its token back — the modeled stuck call.  On
// EVERY schedule the run must end with the stuck-progress finding
// (run it with --expect-finding).  The engine drills prove the
// negative: all five finalize paths return their token, so clean runs
// report zero leaks.
inline void drill_liveness_leak() {
  std::atomic<int> done{0};
  Thread good([&] {
    det::live_begin();
    det_sleep_for(std::chrono::microseconds(50));
    det::live_end();
    done.fetch_add(1);
  });
  Thread stuck([&] {
    det::live_begin();  // never returned
    done.fetch_add(1);
  });
  good.join();
  stuck.join();
  det::expect(done.load() == 2, "liveness workers never ran");
}

inline const std::map<std::string, std::function<void()>>& registry() {
  static const auto* m = new std::map<std::string, std::function<void()>>{
      {"replay_vs_invalidate", drill_replay_vs_invalidate},
      {"abort_vs_traffic", drill_abort_vs_traffic},
      {"join_vs_traffic", drill_join_vs_traffic},
      {"shutdown_vs_waiters", drill_shutdown_vs_waiters},
      {"detach_race", drill_detach_race},
      {"subcomm_allgather", drill_subcomm_allgather},
      {"subcomm_allgather8", drill_subcomm_allgather8},
      {"liveness_leak", drill_liveness_leak},
  };
  return *m;
}

}  // namespace drills
}  // namespace accl
