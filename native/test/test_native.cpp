// Native smoke test of the C++ host driver over the in-proc engine
// world (reference analog: the gtest+MPI binaries of test/host/xrt run
// against the emulator; here rank threads in one process).
#include <cassert>
#include <cmath>
#include <atomic>
#include <cstdio>
#include <thread>

#include "../include/accl_host.hpp"

using namespace accl;
using namespace accl::host;

static void run_rank(Engine* e, int rank, int nranks,
                     std::atomic<int>* failures) {
  try {
    ACCL accl(e);
    std::vector<uint32_t> sessions;
    for (int i = 0; i < nranks; ++i) sessions.push_back(uint32_t(i));
    accl.initialize(sessions, uint32_t(rank));

    const uint32_t N = 1024;
    // allreduce
    auto a = accl.create_buffer<float>(N);
    auto b = accl.create_buffer<float>(N);
    for (uint32_t i = 0; i < N; ++i) (*a)[i] = float(rank + 1);
    accl.allreduce(*a, *b, N);
    float expect = nranks * (nranks + 1) / 2.0f;
    for (uint32_t i = 0; i < N; ++i) assert(std::abs((*b)[i] - expect) < 1e-5);

    // ring sendrecv (async send, sync recv)
    auto s = accl.create_buffer<float>(N);
    auto r = accl.create_buffer<float>(N);
    for (uint32_t i = 0; i < N; ++i) (*s)[i] = float(rank);
    uint32_t nxt = uint32_t((rank + 1) % nranks);
    uint32_t prv = uint32_t((rank + nranks - 1) % nranks);
    uint64_t id = accl.send_async(*s, N, nxt, 5);
    accl.recv(*r, N, prv, 5);
    accl.check(accl.wait(id));
    for (uint32_t i = 0; i < N; ++i) assert((*r)[i] == float(prv));

    // bcast from rank 1
    auto c = accl.create_buffer<float>(N);
    if (rank == 1)
      for (uint32_t i = 0; i < N; ++i) (*c)[i] = 42.0f;
    accl.bcast(*c, N, 1);
    for (uint32_t i = 0; i < N; ++i) assert((*c)[i] == 42.0f);

    accl.barrier();
    assert(accl.last_duration_ns() >= 0);
  } catch (const std::exception& ex) {
    std::fprintf(stderr, "rank %d failed: %s\n", rank, ex.what());
    failures->fetch_add(1);
  }
}

int main() {
  const int NRANKS = 3;
  auto hub = std::make_shared<InprocHub>(NRANKS);
  std::vector<std::unique_ptr<Engine>> engines;
  for (int r = 0; r < NRANKS; ++r)
    engines.push_back(std::make_unique<Engine>(
        uint32_t(r), 16ull << 20,
        std::make_unique<InprocTransport>(hub, r)));

  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int r = 0; r < NRANKS; ++r)
    threads.emplace_back(run_rank, engines[r].get(), r, NRANKS, &failures);
  for (auto& t : threads) t.join();
  engines.clear();
  if (failures) {
    std::printf("FAILED (%d ranks)\n", failures.load());
    return 1;
  }
  std::printf("native host driver smoke test: OK\n");
  return 0;
}
