// Native C++ host-driver test corpus over the in-proc engine world.
//
// Reference analog: the gtest+MPI corpus of test/host/xrt/src/test.cpp
// :30-1032 (one driver per MPI rank against one emulator each; here rank
// threads in one process).  Coverage mirrors the reference suite:
// primitives (copy/copy-stream/combine), send/recv (basic, tags,
// segmentation +-1, compressed, stream put), every collective over every
// root and reduce function, compressed variants, mem<->stream reduce,
// sub-communicators, barrier, async requests, rendezvous-size payloads.
#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <functional>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "../include/accl_host.hpp"
#if defined(ACCL_DETSCHED)
#include "detsched_drills.hpp"
#endif

using namespace accl;
using namespace accl::host;

static constexpr int NRANKS = 4;
static constexpr uint32_t RX_BUF = 1024;    // bytes per eager rx buffer
static constexpr uint32_t MAX_EAGER = 8192; // multi-segment eager below this
static constexpr float F16_ATOL = 0.05f;

// deterministic per-(rank,salt) data, like the reference's random_array
static std::vector<float> fill(uint32_t n, int rank, int salt = 0) {
  std::mt19937 gen(1000 + rank + salt * 131);
  std::normal_distribution<float> d(0.f, 1.f);
  std::vector<float> v(n);
  for (auto& x : v) x = d(gen);
  return v;
}

static void expect_close(float got, float want, float atol,
                         const char* what) {
  if (std::fabs(got - want) > atol + 0.005f * std::fabs(want))
    throw std::runtime_error(std::string(what) + ": got " +
                             std::to_string(got) + " want " +
                             std::to_string(want));
}

// ---------------------------------------------------------------------------
// individual tests: fn(accl, rank) run concurrently on every rank
// ---------------------------------------------------------------------------
using TestFn = std::function<void(ACCL&, int)>;

static void test_copy(ACCL& a, int rank) {
  const uint32_t N = 256;
  auto src = a.create_buffer<float>(N);
  auto dst = a.create_buffer<float>(N);
  auto v = fill(N, rank);
  std::memcpy(src->data(), v.data(), N * 4);
  a.copy(*src, *dst, N);
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*dst)[i], v[i], 0.f, "copy");
}

static void test_copy_stream(ACCL& a, int rank) {
  const uint32_t N = 128;
  auto src = a.create_buffer<float>(N);
  auto dst = a.create_buffer<float>(N);
  auto v = fill(N, rank, 1);
  std::memcpy(src->data(), v.data(), N * 4);
  // mem -> local stream 10 -> pop, then krnl push -> mem
  a.copy_to_stream(*src, N, 10);
  std::vector<float> got(N);
  uint64_t nb = 0;
  if (!a.pop_stream(10, got.data(), N * 4, &nb) || nb != N * 4)
    throw std::runtime_error("copy_to_stream: no payload");
  a.push_krnl(got.data(), N * 4);
  a.copy_from_stream(*dst, N);
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*dst)[i], v[i], 0.f, "copy_stream");
}

static void test_combine(ACCL& a, int rank) {
  const uint32_t N = 200;
  auto va = fill(N, rank, 2), vb = fill(N, rank, 3);
  auto b0 = a.create_buffer<float>(N);
  auto b1 = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  std::memcpy(b0->data(), va.data(), N * 4);
  std::memcpy(b1->data(), vb.data(), N * 4);
  a.combine(N, Reduce::SUM, *b0, *b1, *r);
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*r)[i], va[i] + vb[i], 1e-5f, "combine sum");
  a.combine(N, Reduce::MAX, *b0, *b1, *r);
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*r)[i], std::max(va[i], vb[i]), 0.f, "combine max");
  // int lanes
  auto i0 = a.create_buffer<int32_t>(N);
  auto i1 = a.create_buffer<int32_t>(N);
  auto ir = a.create_buffer<int32_t>(N);
  for (uint32_t i = 0; i < N; ++i) {
    (*i0)[i] = int32_t(i) - 50;
    (*i1)[i] = 7 * int32_t(i % 13);
  }
  a.combine(N, Reduce::SUM, *i0, *i1, *ir);
  for (uint32_t i = 0; i < N; ++i)
    if ((*ir)[i] != int32_t(i) - 50 + 7 * int32_t(i % 13))
      throw std::runtime_error("combine i32 sum mismatch");
}

static void test_combine_mixed(ACCL& a, int rank) {
  // OP1_COMPRESSED: f16 second operand against f32 (per-operand algebra)
  const uint32_t N = 96;
  auto va = fill(N, rank, 4), vb = fill(N, rank, 5);
  auto b0 = a.create_buffer<float>(N);
  auto b1 = a.create_buffer<uint16_t>(N);  // dtype f16
  auto r = a.create_buffer<float>(N);
  std::memcpy(b0->data(), va.data(), N * 4);
  for (uint32_t i = 0; i < N; ++i) (*b1)[i] = f32_to_f16(vb[i]);
  a.combine(N, Reduce::SUM, *b0, *b1, *r);
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*r)[i], va[i] + f16_to_f32(f32_to_f16(vb[i])), F16_ATOL,
                 "combine mixed");
}

static void sendrecv_count(ACCL& a, int rank, uint32_t N, uint32_t tag,
                           DType compress = DType::none) {
  int nxt = (rank + 1) % NRANKS, prv = (rank + NRANKS - 1) % NRANKS;
  auto v = fill(N, rank, int(tag));
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  std::memcpy(s->data(), v.data(), N * 4);
  s->sync_to_device();
  // async send + sync recv (rendezvous sends complete on peer arrival)
  Request req = a.send_async(Operand(*s), N, uint32_t(nxt), tag, -1,
                             compress);
  a.recv(*r, N, uint32_t(prv), tag, -1, compress);
  a.check(req.wait());
  auto want = fill(N, prv, int(tag));
  float atol = compress == DType::none ? 0.f : F16_ATOL;
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*r)[i], want[i], atol, "sendrecv");
}

static void test_sendrecv_basic(ACCL& a, int rank) {
  sendrecv_count(a, rank, 64, 11);
}

static void test_sendrecv_segmentation(ACCL& a, int rank) {
  // rx buffer holds RX_BUF/4 f32 elements; probe the +-1 boundaries and
  // a multi-segment ragged size (reference ACCLSegmentationTest)
  const uint32_t seg = RX_BUF / 4;
  uint32_t sizes[] = {seg - 1, seg, seg + 1, 2 * seg + 3};
  uint32_t tag = 20;
  for (uint32_t n : sizes) sendrecv_count(a, rank, n, tag++);
}

static void test_sendrecv_rendezvous(ACCL& a, int rank) {
  // above MAX_EAGER on the wire -> rendezvous protocol
  sendrecv_count(a, rank, MAX_EAGER / 4 + 64, 30);
}

static void test_sendrecv_compressed(ACCL& a, int rank) {
  sendrecv_count(a, rank, 300, 40, DType::f16);          // eager segments
  sendrecv_count(a, rank, MAX_EAGER / 2 + 64, 41, DType::f16);  // rndzv wire
}

static void test_stream_put(ACCL& a, int rank) {
  const uint32_t N = 64;
  int nxt = (rank + 1) % NRANKS, prv = (rank + NRANKS - 1) % NRANKS;
  auto v = fill(N, rank, 7);
  auto s = a.create_buffer<float>(N);
  std::memcpy(s->data(), v.data(), N * 4);
  a.stream_put(*s, N, uint32_t(nxt), 12);
  std::vector<float> got(N);
  uint64_t nb = 0;
  if (!a.pop_stream(12, got.data(), N * 4, &nb) || nb != N * 4)
    throw std::runtime_error("stream_put: no payload");
  auto want = fill(N, prv, 7);
  for (uint32_t i = 0; i < N; ++i)
    expect_close(got[i], want[i], 0.f, "stream_put");
}

static void bcast_root(ACCL& a, int rank, uint32_t root, uint32_t N,
                       DType compress) {
  auto b = a.create_buffer<float>(N);
  auto v = fill(N, int(root), 8);
  if (uint32_t(rank) == root) std::memcpy(b->data(), v.data(), N * 4);
  a.bcast(*b, N, root, -1, compress);
  float atol = compress == DType::none ? 0.f : F16_ATOL;
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*b)[i], v[i], atol, "bcast");
}

static void test_bcast_roots(ACCL& a, int rank) {
  for (uint32_t root = 0; root < NRANKS; ++root)
    bcast_root(a, rank, root, 128, DType::none);
  bcast_root(a, rank, 1, 3000, DType::none);  // rendezvous tree
}

static void test_bcast_compressed(ACCL& a, int rank) {
  for (uint32_t root = 0; root < NRANKS; ++root)
    bcast_root(a, rank, root, 200, DType::f16);
}

static void scatter_root(ACCL& a, int rank, uint32_t root, uint32_t N,
                         DType compress) {
  auto s = a.create_buffer<float>(N * NRANKS);
  auto r = a.create_buffer<float>(N);
  if (uint32_t(rank) == root)
    for (int k = 0; k < NRANKS; ++k) {
      auto v = fill(N, k, 9);
      std::memcpy(s->data() + k * N, v.data(), N * 4);
    }
  a.scatter(*s, *r, N, root, -1, compress);
  auto want = fill(N, rank, 9);
  float atol = compress == DType::none ? 0.f : F16_ATOL;
  for (uint32_t i = 0; i < N; ++i)
    expect_close((*r)[i], want[i], atol, "scatter");
}

static void test_scatter_roots(ACCL& a, int rank) {
  for (uint32_t root = 0; root < NRANKS; ++root)
    scatter_root(a, rank, root, 96, DType::none);
}

static void test_scatter_compressed(ACCL& a, int rank) {
  scatter_root(a, rank, 2, 96, DType::f16);
}

static void gather_root(ACCL& a, int rank, uint32_t root, uint32_t N,
                        DType compress) {
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N * NRANKS);
  auto v = fill(N, rank, 10);
  std::memcpy(s->data(), v.data(), N * 4);
  a.gather(*s, *r, N, root, -1, compress);
  if (uint32_t(rank) == root) {
    float atol = compress == DType::none ? 0.f : F16_ATOL;
    for (int k = 0; k < NRANKS; ++k) {
      auto want = fill(N, k, 10);
      for (uint32_t i = 0; i < N; ++i)
        expect_close((*r)[k * N + i], want[i], atol, "gather");
    }
  }
}

static void test_gather_roots(ACCL& a, int rank) {
  for (uint32_t root = 0; root < NRANKS; ++root)
    gather_root(a, rank, root, 80, DType::none);
}

static void test_gather_compressed(ACCL& a, int rank) {
  gather_root(a, rank, 0, 80, DType::f16);
}

static void test_allgather(ACCL& a, int rank) {
  const uint32_t N = 90;
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N * NRANKS);
  auto v = fill(N, rank, 11);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allgather(*s, *r, N);
  for (int k = 0; k < NRANKS; ++k) {
    auto want = fill(N, k, 11);
    for (uint32_t i = 0; i < N; ++i)
      expect_close((*r)[k * N + i], want[i], 0.f, "allgather");
  }
}

static void test_allgather_compressed(ACCL& a, int rank) {
  const uint32_t N = 90;
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N * NRANKS);
  auto v = fill(N, rank, 12);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allgather(*s, *r, N, -1, DType::f16);
  for (int k = 0; k < NRANKS; ++k) {
    auto want = fill(N, k, 12);
    for (uint32_t i = 0; i < N; ++i)
      expect_close((*r)[k * N + i], want[i], F16_ATOL, "allgather f16");
  }
}

static void reduce_root_fn(ACCL& a, int rank, uint32_t root, Reduce fn,
                           uint32_t N, DType compress) {
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  auto v = fill(N, rank, 13);
  std::memcpy(s->data(), v.data(), N * 4);
  a.reduce(*s, *r, N, root, fn, -1, compress);
  if (uint32_t(rank) == root) {
    float atol = compress == DType::none ? 1e-4f : F16_ATOL;
    for (uint32_t i = 0; i < N; ++i) {
      float want = fn == Reduce::SUM ? 0.f : -1e30f;
      for (int k = 0; k < NRANKS; ++k) {
        float x = fill(N, k, 13)[i];
        want = fn == Reduce::SUM ? want + x : std::max(want, x);
      }
      expect_close((*r)[i], want, atol, "reduce");
    }
  }
}

static void test_reduce_roots_funcs(ACCL& a, int rank) {
  for (uint32_t root = 0; root < NRANKS; ++root) {
    reduce_root_fn(a, rank, root, Reduce::SUM, 120, DType::none);
    reduce_root_fn(a, rank, root, Reduce::MAX, 120, DType::none);
  }
  reduce_root_fn(a, rank, 0, Reduce::SUM, 3000, DType::none);  // rndzv tree
}

static void test_reduce_compressed(ACCL& a, int rank) {
  reduce_root_fn(a, rank, 3, Reduce::SUM, 120, DType::f16);
  reduce_root_fn(a, rank, 1, Reduce::MAX, 120, DType::f16);
}

static void test_reduce_stream2mem(ACCL& a, int rank) {
  const uint32_t N = 64, root = 1;
  auto v = fill(N, rank, 14);
  a.push_krnl(v.data(), N * 4);
  auto r = a.create_buffer<float>(N);
  a.reduce_stream2mem(*r, N, root, Reduce::SUM);
  if (uint32_t(rank) == root)
    for (uint32_t i = 0; i < N; ++i) {
      float want = 0;
      for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 14)[i];
      expect_close((*r)[i], want, 1e-4f, "reduce s2m");
    }
}

static void test_reduce_mem2stream(ACCL& a, int rank) {
  const uint32_t N = 64, root = 2, strm = 11;
  auto v = fill(N, rank, 15);
  auto s = a.create_buffer<float>(N);
  std::memcpy(s->data(), v.data(), N * 4);
  a.reduce_mem2stream(*s, N, root, strm, Reduce::SUM);
  if (uint32_t(rank) == root) {
    std::vector<float> got(N);
    uint64_t nb = 0;
    if (!a.pop_stream(strm, got.data(), N * 4, &nb) || nb != N * 4)
      throw std::runtime_error("reduce m2s: no payload");
    for (uint32_t i = 0; i < N; ++i) {
      float want = 0;
      for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 15)[i];
      expect_close(got[i], want, 1e-4f, "reduce m2s");
    }
  }
}

static void test_allreduce_funcs(ACCL& a, int rank) {
  for (Reduce fn : {Reduce::SUM, Reduce::MAX}) {
    const uint32_t N = 150;
    auto s = a.create_buffer<float>(N);
    auto r = a.create_buffer<float>(N);
    auto v = fill(N, rank, 16);
    std::memcpy(s->data(), v.data(), N * 4);
    a.allreduce(*s, *r, N, fn);
    for (uint32_t i = 0; i < N; ++i) {
      float want = fn == Reduce::SUM ? 0.f : -1e30f;
      for (int k = 0; k < NRANKS; ++k) {
        float x = fill(N, k, 16)[i];
        want = fn == Reduce::SUM ? want + x : std::max(want, x);
      }
      expect_close((*r)[i], want, 1e-4f, "allreduce");
    }
  }
}

static void test_allreduce_rendezvous(ACCL& a, int rank) {
  const uint32_t N = MAX_EAGER / 4 + 200;  // wire > max_eager -> tree path
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  auto v = fill(N, rank, 17);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allreduce(*s, *r, N, Reduce::SUM);
  for (uint32_t i = 0; i < N; i += 97) {
    float want = 0;
    for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 17)[i];
    expect_close((*r)[i], want, 1e-4f, "allreduce rndzv");
  }
}

static void test_allreduce_compressed(ACCL& a, int rank) {
  const uint32_t N = 513;  // ragged multi-segment
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  auto v = fill(N, rank, 18);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allreduce(*s, *r, N, Reduce::SUM, -1, DType::f16);
  for (uint32_t i = 0; i < N; i += 31) {
    float want = 0;
    for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 18)[i];
    expect_close((*r)[i], want, 4 * F16_ATOL, "allreduce f16");
  }
}

static void test_reduce_scatter(ACCL& a, int rank) {
  const uint32_t N = 70;
  auto s = a.create_buffer<float>(N * NRANKS);
  auto r = a.create_buffer<float>(N);
  for (int k = 0; k < NRANKS; ++k) {
    auto v = fill(N, rank, 19 + k);
    std::memcpy(s->data() + k * N, v.data(), N * 4);
  }
  a.reduce_scatter(*s, *r, N, Reduce::SUM);
  for (uint32_t i = 0; i < N; ++i) {
    float want = 0;
    for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 19 + rank)[i];
    expect_close((*r)[i], want, 1e-4f, "reduce_scatter");
  }
}

static void test_alltoall(ACCL& a, int rank) {
  const uint32_t N = 60;
  auto s = a.create_buffer<float>(N * NRANKS);
  auto r = a.create_buffer<float>(N * NRANKS);
  for (int k = 0; k < NRANKS; ++k) {
    auto v = fill(N, rank, 100 + k);  // slice destined for rank k
    std::memcpy(s->data() + k * N, v.data(), N * 4);
  }
  a.alltoall(*s, *r, N);
  for (int k = 0; k < NRANKS; ++k) {
    auto want = fill(N, k, 100 + rank);
    for (uint32_t i = 0; i < N; ++i)
      expect_close((*r)[k * N + i], want[i], 0.f, "alltoall");
  }
}

static void test_multicomm(ACCL& a, int rank) {
  // split {0,1} / {2,3}: allreduce within each half (reference
  // test_multicomm, test.cpp:676-753)
  std::vector<uint32_t> members = rank < 2
                                      ? std::vector<uint32_t>{0, 1}
                                      : std::vector<uint32_t>{2, 3};
  int sub = a.create_communicator(members);
  const uint32_t N = 50;
  auto s = a.create_buffer<float>(N);
  auto r = a.create_buffer<float>(N);
  auto v = fill(N, rank, 21);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allreduce(*s, *r, N, Reduce::SUM, sub);
  int base = rank < 2 ? 0 : 2;
  for (uint32_t i = 0; i < N; ++i) {
    float want = fill(N, base, 21)[i] + fill(N, base + 1, 21)[i];
    expect_close((*r)[i], want, 1e-5f, "multicomm");
  }
}

static void test_host_buffers(ACCL& a, int rank) {
  // host-resident operands (reference host-only buffers / external_dma):
  // allreduce with a host-only send and a host-only recv on every rank
  const uint32_t N = 300;
  auto s = a.create_buffer_host<float>(N);
  auto r = a.create_buffer_host<float>(N);
  auto v = fill(N, rank, 30);
  std::memcpy(s->data(), v.data(), N * 4);
  a.allreduce(*s, *r, N, Reduce::SUM);
  for (uint32_t i = 0; i < N; ++i) {
    float want = 0;
    for (int k = 0; k < NRANKS; ++k) want += fill(N, k, 30)[i];
    expect_close((*r)[i], want, 1e-4f, "host allreduce");
  }
  // mixed residency: device send, host recv over rendezvous sizes
  const uint32_t M = MAX_EAGER / 4 + 128;
  auto ds = a.create_buffer<float>(M);
  auto hr = a.create_buffer_host<float>(M);
  auto w2 = fill(M, rank, 31);
  std::memcpy(ds->data(), w2.data(), M * 4);
  a.allreduce(*ds, *hr, M, Reduce::SUM);
  for (uint32_t i = 0; i < M; i += 101) {
    float want = 0;
    for (int k = 0; k < NRANKS; ++k) want += fill(M, k, 31)[i];
    expect_close((*hr)[i], want, 1e-4f, "mixed-residency allreduce");
  }
}

static void test_count_thresholds(ACCL& a, int rank) {
  // REDUCE_FLAT_TREE_MAX_COUNT: flat schedule below the byte threshold
  // regardless of rank count; tree above (fw :1533).  Both must produce
  // identical results — this drives each side of the boundary.
  const uint32_t N = MAX_EAGER / 4 + 64;  // rendezvous payload
  a.engine()->set_tuning(Engine::REDUCE_FLAT_TREE_MAX_RANKS, 1);
  for (uint32_t max_count : {0u, 1u << 30}) {
    a.engine()->set_tuning(Engine::REDUCE_FLAT_TREE_MAX_COUNT, max_count);
    auto s = a.create_buffer<float>(N);
    auto r = a.create_buffer<float>(N);
    auto v = fill(N, rank, 32 + int(max_count != 0));
    std::memcpy(s->data(), v.data(), N * 4);
    a.reduce(*s, *r, N, 0, Reduce::SUM);
    if (rank == 0)
      for (uint32_t i = 0; i < N; i += 97) {
        float want = 0;
        for (int k = 0; k < NRANKS; ++k)
          want += fill(N, k, 32 + int(max_count != 0))[i];
        expect_close((*r)[i], want, 1e-4f, "count-threshold reduce");
      }
    a.barrier();
  }
  // GATHER_FLAT_TREE_MAX_COUNT: fan-in capped above the threshold
  a.engine()->set_tuning(Engine::GATHER_FLAT_TREE_MAX_COUNT, 0);
  a.engine()->set_tuning(Engine::GATHER_FLAT_TREE_MAX_FANIN, 1);
  gather_root(a, rank, 0, MAX_EAGER / 4 + 32, DType::none);
  a.engine()->set_tuning(Engine::GATHER_FLAT_TREE_MAX_COUNT, 32 * 1024);
  a.engine()->set_tuning(Engine::GATHER_FLAT_TREE_MAX_FANIN, 2);
}

static void test_barrier_and_nop(ACCL& a, int rank) {
  a.nop();
  for (int i = 0; i < 3; ++i) a.barrier();
  if (a.last_duration_ns() < 0) throw std::runtime_error("perf counter");
}

static void test_p2p_buffer(ACCL& a, int rank) {
  // Reference test_copy_p2p (test.cpp:63-85) + the wire-bypass
  // property: a rendezvous send landing in a peer's p2p buffer must
  // move ZERO payload bytes over the transport (direct peer-devicemem
  // write, fpgabufferp2p.hpp role) — only the small RNDZVS_INIT
  // control message crosses.  The p2p buffer's host view is a direct
  // mapping: the landed data is visible WITHOUT sync_from_device.
  const uint32_t N = MAX_EAGER / 4 + 64;  // rendezvous-sized
  auto v = fill(N, 0, 55);
  if (rank == 0) {
    auto src = a.create_buffer<float>(N);
    std::memcpy(src->data(), v.data(), N * 4);
    uint64_t m0, b0, m1, b1;
    a.engine()->tx_stats(&m0, &b0);
    a.send(*src, N, 1, 11);
    a.engine()->tx_stats(&m1, &b1);
    if (b1 != b0)
      throw std::runtime_error("p2p rendezvous send moved " +
                               std::to_string(b1 - b0) +
                               " payload bytes over the wire");
  } else if (rank == 1) {
    auto dst = a.create_buffer_p2p<float>(N);
    a.recv(*dst, N, 0, 11);
    // NO sync_from_device: the mapping is the device memory
    for (uint32_t i = 0; i < N; ++i)
      expect_close(dst->data()[i], v[i], 0.f, "p2p landing");
  }
  // local copy into an own p2p buffer (the reference's test shape)
  auto op = a.create_buffer<float>(64);
  auto p2p = a.create_buffer_p2p<float>(64);
  auto w = fill(64, rank, 56);
  std::memcpy(op->data(), w.data(), 64 * 4);
  a.copy(*op, *p2p, 64);
  for (uint32_t i = 0; i < 64; ++i)
    expect_close(p2p->data()[i], w[i], 0.f, "copy_p2p");
}

static void test_rendezvous_latency(ACCL& a, int rank) {
#if defined(__SANITIZE_THREAD__) || defined(__SANITIZE_ADDRESS__)
  // a latency-RATIO guard is meaningless under a 10-30x sanitizer
  // slowdown (TSan serializes every atomics-heavy path differently
  // than the eager/rendezvous split assumes); the functional corpus
  // still runs — only the pacing assertion is skipped
  (void)a;
  (void)rank;
  return;
#endif
  // Contended-rendezvous pacing guard: every rendezvous call takes at
  // least one NotReady retry (the receiver's address must cross the
  // wire), so a fixed retry sleep puts a hard floor under ping-pong
  // latency — the old 200 us pacing made each round >= ~400 us.  The
  // adaptive spin-then-yield pacing (engine.cpp loop()) must keep the
  // common fast path in the tens of microseconds; assert the best
  // batch stays clearly below the old floor so a pacing regression
  // cannot hide in CI noise (fw analog: the retry round-robin has no
  // sleep at all, fw :2264-2288).
  const uint32_t N = MAX_EAGER / 4 + 64;  // just past eager: rendezvous
  const int ROUNDS = 50, BATCHES = 3;
  if (rank > 1) return;
  auto buf = a.create_buffer<float>(N);
  auto v = fill(N, rank, 77);
  std::memcpy(buf->data(), v.data(), N * 4);
  // machine-speed proxy: an EAGER ping-pong round on the same world
  // carries everything EXCEPT the rendezvous retry path (call submit,
  // engine dispatch, wire hop, driver wait) — on a loaded CI box both
  // numbers inflate together, so the guard is a ratio, not an absolute
  // (repo perf-guard convention, best-of-N both sides)
  const uint32_t NE = 64;  // well under the eager threshold
  auto ebuf = a.create_buffer<float>(NE);
  auto round_us = [&](auto&& one_round, int rounds) {
    auto t0 = std::chrono::steady_clock::now();
    for (int i = 0; i < rounds; ++i) one_round();
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now() - t0)
               .count() /
           double(rounds);
  };
  double best_rndzv = 1e30, best_eager = 1e30;
  for (int b = 0; b < BATCHES; ++b) {
    double eager = round_us(
        [&] {
          if (rank == 0) {
            a.send(*ebuf, NE, 1, 5);
            a.recv(*ebuf, NE, 1, 6);
          } else {
            a.recv(*ebuf, NE, 0, 5);
            a.send(*ebuf, NE, 0, 6);
          }
        },
        ROUNDS);
    double rndzv = round_us(
        [&] {
          if (rank == 0) {
            a.send(*buf, N, 1, 7);
            a.recv(*buf, N, 1, 8);
          } else {
            a.recv(*buf, N, 0, 7);
            a.send(*buf, N, 0, 8);
          }
        },
        ROUNDS);
    best_eager = std::min(best_eager, eager);
    best_rndzv = std::min(best_rndzv, rndzv);
  }
  // old fixed 200 us retry sleep put >= ~400 us under every rendezvous
  // round regardless of machine speed — an absolute floor that dwarfs
  // the eager round.  Adaptive pacing must keep the rendezvous round
  // within a small multiple of eager plus slack for the extra protocol
  // legs (INIT + one-sided write + completion).
  if (best_rndzv > 8.0 * best_eager + 150.0)
    throw std::runtime_error(
        "contended rendezvous round " + std::to_string(best_rndzv) +
        " us vs eager " + std::to_string(best_eager) +
        " us (pacing regression? old fixed-sleep floor was >= ~400 us)");
}

// ---------------------------------------------------------------------------
// harness
// ---------------------------------------------------------------------------
// One world per case: a load-induced timeout in one case must not leave
// stale segments that cascade into seqn/BTT errors in later cases (the
// reference boots one fixture per gtest process; this is the same
// isolation in-proc).
struct World;

// ---------------------------------------------------------------------------
// concurrency drills (r13): the TSan-focused section.  These hammer
// the surfaces the r10-r13 arc made concurrent — raw-frame ingest vs
// live traffic, abort/epoch fencing vs in-flight collectives, the plan
// ring's create/replay/poll/invalidate races, shutdown vs host-side
// pollers (the suite-exit teardown ordering), and the egress frame tap
// — with every thread fully instrumented, which the Python test suite
// cannot be (an uninstrumented CPython hides the GIL from TSan and
// fabricates impossible races; docs/static_analysis.md "Native
// sanitizer lanes").  The drills also run in the plain corpus build:
// same assertions, just without the race checker underneath.
// ---------------------------------------------------------------------------
using DrillFn = std::function<void(World&)>;

static std::vector<uint8_t> make_frame(uint8_t msg_type, uint32_t src,
                                       uint32_t comm, uint32_t count,
                                       uint32_t payload_bytes) {
  WireHeader h;
  h.msg_type = msg_type;
  h.src = src;
  h.comm_id = comm;
  h.count = count;
  std::vector<uint8_t> out(sizeof(WireHeader) + payload_bytes, 0x5A);
  std::memcpy(out.data(), &h, sizeof(WireHeader));
  return out;
}

struct World {
  std::shared_ptr<InprocHub> hub;
  std::vector<std::unique_ptr<Engine>> engines;
  std::vector<std::unique_ptr<ACCL>> accls;

  World() : hub(std::make_shared<InprocHub>(NRANKS)) {
    for (int r = 0; r < NRANKS; ++r)
      engines.push_back(std::make_unique<Engine>(
          uint32_t(r), 64ull << 20,
          std::make_unique<InprocTransport>(hub, r)));
    // shared address space: enable the direct p2p landing (sessions
    // are rank ids), same wiring as the capi inproc world
    for (auto& e : engines)
      e->set_peer_hook([this](uint32_t session) -> Engine* {
        return session < engines.size() ? engines[session].get() : nullptr;
      });
    for (int r = 0; r < NRANKS; ++r) {
      accls.push_back(std::make_unique<ACCL>(engines[r].get()));
      std::vector<uint32_t> sessions;
      for (int i = 0; i < NRANKS; ++i) sessions.push_back(uint32_t(i));
      accls[r]->initialize(sessions, uint32_t(r), 16, RX_BUF, MAX_EAGER);
      // bring-up default is 1s (reference accl.cpp:1112); CI boxes run
      // this corpus alongside other jobs on few cores, where a 1s
      // receive budget fires spuriously — widen it for the corpus
      accls[r]->set_timeout(30'000'000);  // 30 s
    }
  }
};

// All-rank verified allreduce used by the drills as the liveness probe.
static void drill_allreduce_round(World& w, int rounds) {
  std::atomic<int> failures{0};
  std::string first_err;
  std::mutex err_mu;
  std::vector<std::thread> threads;
  for (int r = 0; r < NRANKS; ++r)
    threads.emplace_back([&, r] {
      try {
        auto src = w.accls[r]->create_buffer<float>(64);
        auto dst = w.accls[r]->create_buffer<float>(64);
        for (uint32_t i = 0; i < 64; ++i) src->data()[i] = float(r + 1);
        for (int it = 0; it < rounds; ++it) {
          w.accls[r]->allreduce(*src, *dst, 64, Reduce::SUM);
          float want = float(NRANKS * (NRANKS + 1)) / 2.0f;
          for (uint32_t i = 0; i < 64; ++i)
            if (dst->data()[i] != want)
              throw std::runtime_error("allreduce corrupted under drill");
        }
      } catch (const std::exception& ex) {
        failures.fetch_add(1);
        std::lock_guard<std::mutex> g(err_mu);
        if (first_err.empty()) first_err = ex.what();
      }
    });
  for (auto& t : threads) t.join();
  if (failures) throw std::runtime_error(first_err);
}

static void drill_ingest_vs_traffic(World& w) {
  std::atomic<bool> stop{false};
  // two attacker threads spray every engine with malformed + valid-
  // shaped frames through the REAL ingress path
  std::vector<std::thread> attackers;
  for (int a = 0; a < 2; ++a)
    attackers.emplace_back([&, a] {
      uint64_t rng = 0x9E3779B97F4A7C15ull * (a + 1);
      while (!stop.load()) {
        rng ^= rng >> 12; rng ^= rng << 25; rng ^= rng >> 27;
        Engine* e = w.engines[rng % NRANKS].get();
        switch (rng % 5) {
          case 0: {  // truncated header
            uint8_t junk[16] = {0};
            e->ingest_bytes(junk, sizeof junk);
            break;
          }
          case 1: {  // unknown message type
            auto f = make_frame(uint8_t(40 + rng % 200), 1, 0, 0, 0);
            e->ingest_bytes(f.data(), f.size());
            break;
          }
          case 2: {  // eager count/payload mismatch
            auto f = make_frame(0, 1, 0, 999, 8);
            e->ingest_bytes(f.data(), f.size());
            break;
          }
          case 3: {  // well-formed heartbeat pong
            auto f = make_frame(5, 1, 0, 0, 0);
            e->ingest_bytes(f.data(), f.size());
            break;
          }
          default: {  // out-of-range comm id
            auto f = make_frame(4, 1, 1u << 20, 0, 0);
            e->ingest_bytes(f.data(), f.size());
            break;
          }
        }
      }
    });
  drill_allreduce_round(w, 25);
  stop.store(true);
  for (auto& t : attackers) t.join();
  uint64_t rejected = 0;
  w.engines[0]->frame_stats(nullptr, &rejected);
  if (rejected == 0)
    throw std::runtime_error("ingest drill: nothing was ever rejected");
}

static void drill_abort_vs_traffic(World& w) {
  std::atomic<int> aborted_seen{0};
  std::atomic<long> iters{0};
  std::vector<std::thread> threads;
  for (int r = 0; r < NRANKS; ++r)
    threads.emplace_back([&, r] {
      auto src = w.accls[r]->create_buffer<float>(256);
      auto dst = w.accls[r]->create_buffer<float>(256);
      // effectively unbounded: the loop ends when the abort fences it
      // (the fetch below gates the abort on real progress, so a fixed
      // iteration count racing a fixed sleep can't end the loop first)
      for (int it = 0; it < 1'000'000; ++it) {
        try {
          w.accls[r]->allreduce(*src, *dst, 256, Reduce::SUM);
          iters.fetch_add(1);
        } catch (const std::exception&) {
          aborted_seen.fetch_add(1);
          break;  // fenced: stop issuing on the dead epoch
        }
      }
    });
  // mid-flight abort, gated on PROGRESS (not wall clock): wait until
  // the world demonstrably ran collectives, then fence it
  while (iters.load() < 2 * NRANKS)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  w.engines[0]->abort_comm(0, 0, true);
  for (auto& t : threads) t.join();
  if (aborted_seen.load() == 0)
    throw std::runtime_error("abort drill: no rank ever saw the fence");
  // collective recovery on the SAME world: reset + verified allreduce
  for (auto& e : w.engines) e->reset_errors();
  drill_allreduce_round(w, 3);
}

static void drill_plan_races(World& w) {
  Engine* e = w.engines[0].get();
  // a small plan of Nop descriptors (pure engine-loop traffic — the
  // drill targets the ring/token bookkeeping, not the collectives)
  std::vector<uint32_t> words(15 * 8, 0);
  for (int i = 0; i < 8; ++i) words[size_t(i) * 15] = 255;  // Op::Nop
  int plan = e->plan_create(words.data(), 8);
  if (plan < 0) throw std::runtime_error("plan drill: create failed");
  std::atomic<int> fenced{0}, completed{0}, errors{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 3; ++t)
    threads.emplace_back([&] {
      // never throw out of a drill thread (std::terminate): record and
      // bail, the joiner raises
      for (int it = 0; it < 50; ++it) {
        long long tok = e->plan_replay(plan);
        if (tok == -2) {  // invalidated mid-loop: the fence worked
          fenced.fetch_add(1);
          return;
        }
        if (tok < 0) {
          errors.fetch_add(1);
          return;
        }
        uint32_t ret = 0;
        double dur = 0;
        for (;;) {
          int rc = e->plan_poll(tok, &ret, &dur);
          if (rc == 1) break;
          if (rc < 0) {  // token vanished under a live poller
            errors.fetch_add(1);
            return;
          }
          std::this_thread::yield();
        }
        completed.fetch_add(1);
      }
    });
  std::this_thread::sleep_for(std::chrono::milliseconds(3));
  e->invalidate_plans(-1);
  for (auto& t : threads) t.join();
  if (errors.load())
    throw std::runtime_error("plan drill: bad token / token vanished");
  if (completed.load() == 0 && fenced.load() == 0)
    throw std::runtime_error("plan drill: no thread made progress");
  if (e->plan_count() != 0)
    throw std::runtime_error("plan drill: invalidation left live plans");
}

static void drill_shutdown_vs_pollers(World& w) {
  Engine* e = w.engines[0].get();
  // one never-completing receive PER poller (src rank 1 sends nothing;
  // poll_call is consume-once, so each poller owns its call — exactly
  // the Python waiter-thread shape)
  constexpr int kPollers = 3;
  uint64_t ids[kPollers];
  for (int t = 0; t < kPollers; ++t) {
    uint64_t addr = e->alloc(256, 64);
    std::array<uint32_t, 15> wds{};
    wds[0] = 4;  // Op::Recv
    wds[1] = 64;
    wds[2] = 0;               // comm
    wds[3] = 1;               // src
    wds[5] = uint32_t(t);     // distinct tags
    wds[13] = uint32_t(addr & 0xFFFFFFFFu);
    wds[14] = uint32_t(addr >> 32);
    ids[t] = e->start_call(wds.data());
  }
  std::atomic<uint32_t> final_ret{0};
  std::atomic<int> released{0};
  std::vector<std::thread> pollers;
  for (int t = 0; t < kPollers; ++t)
    pollers.emplace_back([&, t] {
      uint32_t ret = 0;
      double dur = 0;
      // poll until the call finalizes; shutdown() must make this
      // return promptly — the native twin of the Python waiter thread
      for (int spins = 0; spins < 1'000'000; ++spins) {
        if (e->poll_call(ids[t], &ret, &dur)) {
          final_ret.fetch_or(ret);
          released.fetch_add(1);
          return;
        }
        std::this_thread::sleep_for(std::chrono::microseconds(50));
      }
    });
  std::this_thread::sleep_for(std::chrono::milliseconds(10));
  e->shutdown();
  for (auto& t : pollers) t.join();
  if (released.load() != kPollers)
    throw std::runtime_error("shutdown drill: a poller never released");
  if ((final_ret.load() & (COMM_ABORTED | RANK_FAILED)) == 0)
    throw std::runtime_error(
        "shutdown drill: pending calls not finalized with "
        "COMM_ABORTED|RANK_FAILED");
}

static void drill_tap_vs_traffic(World& w) {
  std::atomic<bool> stop{false};
  for (auto& e : w.engines) e->set_frame_tap(true);
  std::thread reader([&] {
    uint8_t buf[4096];
    while (!stop.load()) {
      for (auto& e : w.engines) {
        int n = e->tap_count();
        for (int i = 0; i < n; ++i) e->tap_read(i, buf, sizeof buf);
      }
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  });
  drill_allreduce_round(w, 20);
  stop.store(true);
  reader.join();
  for (auto& e : w.engines) e->set_frame_tap(false);
  if (w.engines[0]->tap_count() == 0)
    throw std::runtime_error("tap drill: no frames captured");
}

int main() {
  struct Case {
    const char* name;
    TestFn fn;
  };
  std::vector<Case> cases = {
      {"copy", test_copy},
      {"copy_stream", test_copy_stream},
      {"combine", test_combine},
      {"combine_mixed", test_combine_mixed},
      {"sendrecv_basic", test_sendrecv_basic},
      {"sendrecv_segmentation", test_sendrecv_segmentation},
      {"sendrecv_rendezvous", test_sendrecv_rendezvous},
      {"sendrecv_compressed", test_sendrecv_compressed},
      {"stream_put", test_stream_put},
      {"bcast_roots", test_bcast_roots},
      {"bcast_compressed", test_bcast_compressed},
      {"scatter_roots", test_scatter_roots},
      {"scatter_compressed", test_scatter_compressed},
      {"gather_roots", test_gather_roots},
      {"gather_compressed", test_gather_compressed},
      {"allgather", test_allgather},
      {"allgather_compressed", test_allgather_compressed},
      {"reduce_roots_funcs", test_reduce_roots_funcs},
      {"reduce_compressed", test_reduce_compressed},
      {"reduce_stream2mem", test_reduce_stream2mem},
      {"reduce_mem2stream", test_reduce_mem2stream},
      {"allreduce_funcs", test_allreduce_funcs},
      {"allreduce_rendezvous", test_allreduce_rendezvous},
      {"allreduce_compressed", test_allreduce_compressed},
      {"reduce_scatter", test_reduce_scatter},
      {"alltoall", test_alltoall},
      {"multicomm", test_multicomm},
      {"host_buffers", test_host_buffers},
      {"count_thresholds", test_count_thresholds},
      {"barrier_and_nop", test_barrier_and_nop},
      {"p2p_buffer", test_p2p_buffer},
      {"rendezvous_latency", test_rendezvous_latency},
  };

  int failed_cases = 0;
  for (auto& c : cases) {
    World w;
    std::atomic<int> failures{0};
    std::string first_err;
    std::mutex err_mu;
    std::vector<std::thread> threads;
    for (int r = 0; r < NRANKS; ++r)
      threads.emplace_back([&, r] {
        try {
          c.fn(*w.accls[r], r);
          w.accls[r]->barrier();  // lockstep before teardown
        } catch (const std::exception& ex) {
          failures.fetch_add(1);
          std::lock_guard<std::mutex> g(err_mu);
          if (first_err.empty())
            first_err = "rank " + std::to_string(r) + ": " + ex.what();
        }
      });
    for (auto& t : threads) t.join();
    if (failures) {
      ++failed_cases;
      std::printf("FAIL %-26s %s\n", c.name, first_err.c_str());
    } else {
      std::printf("PASS %s\n", c.name);
    }
  }

  // concurrency drills (r13): direct World access, fresh world each
  struct Drill {
    const char* name;
    DrillFn fn;
  };
  std::vector<Drill> drills = {
      {"drill_ingest_vs_traffic", drill_ingest_vs_traffic},
      {"drill_abort_vs_traffic", drill_abort_vs_traffic},
      {"drill_plan_races", drill_plan_races},
      {"drill_shutdown_vs_pollers", drill_shutdown_vs_pollers},
      {"drill_tap_vs_traffic", drill_tap_vs_traffic},
  };
  for (auto& d : drills) {
    World w;
    try {
      d.fn(w);
      std::printf("PASS %s\n", d.name);
    } catch (const std::exception& ex) {
      ++failed_cases;
      std::printf("FAIL %-26s %s\n", d.name, ex.what());
    }
  }

  size_t det_cases = 0;
#if defined(ACCL_DETSCHED)
  // model-checked drill under the deterministic scheduler (the rest of
  // this corpus runs with the hooks dormant — no controlled run is
  // active — proving the instrumented build behaves like the plain
  // one).  A bounded exploration of the abort-vs-traffic drill must
  // come back clean; see scripts/model_check.py for the full sweep.
  ++det_cases;
  {
    accl::det::ExploreOpts opts;
    opts.max_runs = 200;
    opts.seed = 3;
    auto st = accl::det::explore(
        accl::drills::registry().at("abort_vs_traffic"), opts);
    if (st.findings == 0 && st.runs >= 1) {
      std::printf("PASS det_drill_smoke (%llu schedules)\n",
                  (unsigned long long)st.runs);
    } else {
      ++failed_cases;
      std::printf("FAIL det_drill_smoke            %s\n",
                  st.first_failure.what.c_str());
    }
  }
#endif

  size_t total = cases.size() + drills.size() + det_cases;
  if (failed_cases) {
    std::printf("native driver corpus: %d/%zu cases FAILED\n", failed_cases,
                total);
    return 1;
  }
  std::printf("native driver corpus: all %zu cases OK\n", total);
  return 0;
}
