"""One-call world bring-up + rank-table generation.

Equivalent of the reference accl_network_utils: the `acclDesign` enum
selecting the transport design, `generate_ranks` building the rank table
from a pattern or file, and `initialize_accl` performing the full
bring-up in one call (driver/utils/accl_network_utils.cpp:264-289,
:292-362).  The TPU designs replace {AXIS3x, TCP, UDP, CYT_TCP,
CYT_RDMA} with:

- ``EMU_INPROC``: native engines in one process (AXIS3x loopback rung)
- ``EMU_TCP``:    one native engine per process over sockets (TCP rung)
- ``TPU``:        XLA collectives over the device mesh (hardware rung)
"""
from __future__ import annotations

import enum
import json
from typing import Optional, Sequence

from ..communicator import Rank
from ..constants import DEFAULT_EAGER_RX_BUF_SIZE


class Design(enum.Enum):
    EMU_INPROC = "emu-inproc"
    EMU_TCP = "emu-tcp"
    TPU = "tpu"


def generate_ranks(nranks: int, base_port: int = 5500,
                   ips: Optional[Sequence[str]] = None,
                   rank_file: Optional[str] = None,
                   max_segment_size: int = DEFAULT_EAGER_RX_BUF_SIZE) -> list[Rank]:
    """Build the rank table from a pattern or a JSON rank file
    (reference: generate_ranks file/pattern variants,
    accl_network_utils.cpp:235-289)."""
    if rank_file:
        with open(rank_file) as f:
            spec = json.load(f)
        return [
            Rank(ip=r.get("ip", "127.0.0.1"), port=r.get("port", base_port + i),
                 session=r.get("session", i),
                 max_segment_size=r.get("max_segment_size", max_segment_size))
            for i, r in enumerate(spec["ranks"])
        ]
    ips = list(ips) if ips else ["127.0.0.1"] * nranks
    return [
        Rank(ip=ips[i % len(ips)], port=base_port + i, session=i,
             max_segment_size=max_segment_size)
        for i in range(nranks)
    ]


def initialize_world(design: Design | str, nranks: int, rank: int = 0,
                     base_port: int = 5500, **kwargs):
    """One-call bring-up (reference: initialize_accl).

    EMU_INPROC / TPU return a world object (all ranks, single process);
    EMU_TCP returns this process's single-rank node."""
    design = Design(design) if not isinstance(design, Design) else design
    if design == Design.EMU_INPROC:
        from ..backends.emu import EmuWorld

        return EmuWorld(nranks, **kwargs)
    if design == Design.EMU_TCP:
        from ..backends.emu import EmuRankTcp

        return EmuRankTcp(rank, nranks, base_port, **kwargs)
    if design == Design.TPU:
        from ..backends.tpu import TpuWorld

        return TpuWorld(nranks, **kwargs)
    raise ValueError(f"unknown design {design}")


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None,
                         local_device_ids=None,
                         dry_run: bool = False) -> dict:
    """Multi-host JAX bring-up — the reference's MPI-launch role
    (test/host/Coyote run scripts start one driver process per node and
    exchange QPs over MPI; here each host process joins the cluster via
    jax.distributed so `jax.devices()` spans every host and the hybrid
    ICI x DCN meshes of :func:`accl_tpu.parallel.make_hybrid_mesh`
    compile against the full device set).

    Call once per host process BEFORE any other jax use.  Arguments
    default from the environment: ``ACCL_COORDINATOR`` (host:port of
    process 0), ``ACCL_NUM_PROCESSES``, ``ACCL_PROCESS_ID`` — on cloud
    TPU pods all three may be omitted entirely (jax auto-detects from
    the TPU metadata).  ``dry_run=True`` returns the resolved kwargs
    without touching jax (arg-assembly testing on CI, where a second
    host doesn't exist)."""
    import os

    def _env_int(name):
        val = os.environ.get(name)
        return int(val) if val is not None else None

    kwargs = {}
    coordinator_address = (coordinator_address
                           or os.environ.get("ACCL_COORDINATOR"))
    num_processes = (num_processes if num_processes is not None
                     else _env_int("ACCL_NUM_PROCESSES"))
    process_id = (process_id if process_id is not None
                  else _env_int("ACCL_PROCESS_ID"))
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    if dry_run:
        return kwargs

    import jax

    jax.distributed.initialize(**kwargs)
    return kwargs
