"""Wire-frame codec: the Python twin of ``native/src/common.hpp``.

One place knows the 64-byte ``WireHeader`` layout outside the native
library: the deterministic wire fuzzer (``scripts/fuzz_wire.py``) and
the malformed-frame rejection tests build and dissect frames through
this module, so a header-layout change breaks loudly in one import
instead of silently corrupting test vectors.

Layout (little-endian, 64 bytes total, ``static_assert``-pinned on the
C++ side)::

    count:u32 tag:u32 src:u32 seqn:u32 strm:u32 dst_session:u16
    msg_type:u8 host:u8 vaddr:u64 comm_id:u32 compressed:u32 epoch:u32
    pad[20]
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field

HEADER_FMT = "<IIIIIHBBQIII20x"
HEADER_SIZE = struct.calcsize(HEADER_FMT)
assert HEADER_SIZE == 64, "wire header must be 64 bytes"

#: MsgType values (common.hpp enum MsgType) — every known frame kind
MSG_TYPES = {
    "egr": 0,
    "rndzvs_msg": 1,
    "rndzvs_init": 2,
    "rndzvs_wrdone": 3,
    "nack": 4,
    "heartbeat": 5,
    "abort": 6,
    "join": 7,
    "welcome": 8,
    "state_sync": 9,
}
MSG_TYPE_NAMES = {v: k for k, v in MSG_TYPES.items()}


@dataclass
class WireFrame:
    """One framed wire message: header fields + payload bytes."""

    count: int = 0
    tag: int = 0
    src: int = 0
    seqn: int = 0
    strm: int = 0
    dst_session: int = 0
    msg_type: int = 0
    host: int = 0
    vaddr: int = 0
    comm_id: int = 0
    compressed: int = 0
    epoch: int = 0
    payload: bytes = field(default=b"")

    def pack(self) -> bytes:
        hdr = struct.pack(
            HEADER_FMT, self.count & 0xFFFFFFFF, self.tag & 0xFFFFFFFF,
            self.src & 0xFFFFFFFF, self.seqn & 0xFFFFFFFF,
            self.strm & 0xFFFFFFFF, self.dst_session & 0xFFFF,
            self.msg_type & 0xFF, self.host & 0xFF,
            self.vaddr & 0xFFFFFFFFFFFFFFFF, self.comm_id & 0xFFFFFFFF,
            self.compressed & 0xFFFFFFFF, self.epoch & 0xFFFFFFFF)
        return hdr + self.payload

    @classmethod
    def unpack(cls, data: bytes) -> "WireFrame":
        if len(data) < HEADER_SIZE:
            raise ValueError(
                f"frame shorter than a wire header: {len(data)} bytes")
        (count, tag, src, seqn, strm, dst_session, msg_type, host, vaddr,
         comm_id, compressed, epoch) = struct.unpack(
             HEADER_FMT, data[:HEADER_SIZE])
        return cls(count=count, tag=tag, src=src, seqn=seqn, strm=strm,
                   dst_session=dst_session, msg_type=msg_type, host=host,
                   vaddr=vaddr, comm_id=comm_id, compressed=compressed,
                   epoch=epoch, payload=bytes(data[HEADER_SIZE:]))

    @property
    def type_name(self) -> str:
        return MSG_TYPE_NAMES.get(self.msg_type,
                                  f"unknown({self.msg_type})")

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (f"WireFrame({self.type_name} src={self.src} "
                f"comm={self.comm_id} tag={self.tag} seqn={self.seqn} "
                f"count={self.count} payload={len(self.payload)}B)")
