"""Tracing/profiling hooks.

Reference analogs (SURVEY §5): per-call hardware cycle counter surfaced
as get_duration (fw :2280-2303), CSV bench pipeline, ACCL_DEBUG call
logs.  The TPU additions here wrap the XLA profiler so collective
timelines (ICI transfers included) can be captured and viewed in
TensorBoard/Perfetto, plus a lightweight per-op timer for quick numbers.
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator


@contextlib.contextmanager
def xla_trace(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace (TPU: includes ICI collective ops)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def timed(label: str, results: dict | None = None) -> Iterator[None]:
    """Wall-clock block timer; appends ns to results[label] if given."""
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        dt = time.perf_counter_ns() - t0
        if results is not None:
            results.setdefault(label, []).append(dt)


def time_fn(fn, *args, iters: int = 10, warmup: int = 2) -> float:
    """Average seconds per call with device sync (bench building block)."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters
