"""Tracing/profiling hooks.

Reference analogs (SURVEY §5): per-call hardware cycle counter surfaced
as get_duration (fw :2280-2303), CSV bench pipeline, ACCL_DEBUG call
logs.  The TPU additions here wrap the XLA profiler so collective
timelines (ICI transfers included) can be captured and viewed in
TensorBoard/Perfetto, plus a lightweight per-op timer for quick numbers.

The structured per-call tracing + metrics layer lives in
accl_tpu/observability (docs/observability.md); its
`traced_window(label, xla_logdir=...)` marks a span in the ACCL trace
AND captures an `xla_trace` of the same window.  The block timer
`timed` is implemented on utils/timing.Timer and re-exported here for
its historical import path.
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator

from .timing import Timer, timed  # noqa: F401 — one implementation


@contextlib.contextmanager
def xla_trace(logdir: str) -> Iterator[None]:
    """Capture an XLA profiler trace (TPU: includes ICI collective ops)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def time_fn(fn, *args, iters: int = 10, warmup: int = 2,
            pipelined: bool = False) -> float:
    """Average seconds per call with device sync (bench building block).

    Each iteration's output is block_until_ready'd, so the reported
    time is true per-call latency — jax dispatch is async, and syncing
    only the last output lets earlier iterations overlap the loop,
    underreporting per-call time.  ``pipelined=True`` restores the
    overlapped measurement (throughput of a dependency-free stream:
    only the final output is synced)."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    if pipelined:
        out = None
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
    else:
        for _ in range(iters):
            jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / iters
