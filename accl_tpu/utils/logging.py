"""Leveled logging (reference: test/log/log.hpp, 5 levels + per-rank files).

Thin wrapper over the stdlib; honors ACCL_DEBUG like the reference
driver's debug log switch (driver/xrt/src/common.cpp:91-135).
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_configured = False


def get_logger(name: str = "accl_tpu", rank: Optional[int] = None) -> logging.Logger:
    global _configured
    logger = logging.getLogger(name if rank is None else f"{name}.rank{rank}")
    if not _configured:
        level = logging.DEBUG if os.environ.get("ACCL_DEBUG") else logging.WARNING
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("[%(levelname).1s %(name)s] %(message)s")
        )
        root = logging.getLogger("accl_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        _configured = True
    return logger
