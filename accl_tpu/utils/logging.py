"""Structured rank-prefixed logging (reference: test/log/log.hpp, 5
levels + per-rank files).

Every line is prefixed ``[accl r3]`` (or ``[accl]`` when no rank is
bound) plus a one-letter level, so interleaved multi-rank output stays
attributable — the discipline the watchdog and backend diagnostics
rely on.  Level comes from ``ACCL_LOG`` (debug/info/warning/error,
default warning); ``ACCL_DEBUG=1`` keeps its reference-era meaning as
an alias for ``ACCL_LOG=debug`` (driver/xrt/src/common.cpp:91-135).
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}

_configured = False


def level_from_env() -> int:
    raw = os.environ.get("ACCL_LOG", "").strip().lower()
    if raw:
        return _LEVELS.get(raw, logging.WARNING)
    return logging.DEBUG if os.environ.get("ACCL_DEBUG") else logging.WARNING


class _RankFormatter(logging.Formatter):
    """``[accl r3] W message`` — rank recovered from the logger name's
    ``.rankN`` suffix (how get_logger binds it), so every handler and
    third-party emit keeps the prefix."""

    def format(self, record: logging.LogRecord) -> str:
        rank = getattr(record, "rank", None)
        if rank is None and ".rank" in record.name:
            tail = record.name.rsplit(".rank", 1)[1]
            if tail.isdigit():
                rank = tail
        prefix = f"[accl r{rank}]" if rank is not None else "[accl]"
        return f"{prefix} {record.levelname[0]} {record.getMessage()}"


def _configure() -> None:
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_RankFormatter())
    root = logging.getLogger("accl_tpu")
    root.addHandler(handler)
    root.setLevel(level_from_env())
    _configured = True


def get_logger(name: str = "accl_tpu",
               rank: Optional[int] = None) -> logging.Logger:
    """Rank-bound structured logger: ``get_logger(rank=3).warning(...)``
    emits ``[accl r3] W ...`` on stderr at the ACCL_LOG level."""
    _configure()
    return logging.getLogger(name if rank is None else f"{name}.rank{rank}")


def set_level(level) -> None:
    """Programmatic override of the env-derived level (accepts a
    logging constant or an ACCL_LOG-style name)."""
    _configure()
    if isinstance(level, str):
        level = _LEVELS.get(level.strip().lower(), logging.WARNING)
    logging.getLogger("accl_tpu").setLevel(level)
