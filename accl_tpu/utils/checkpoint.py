"""Checkpoint/restore for model state.

The reference is a stateless communication library — its only resume
mechanism is the retry queue's current_step (SURVEY §5).  The framework
still ships a minimal checkpointing utility for the model layer so
training loops built on it can snapshot/restore parameter pytrees
without further dependencies (orbax remains the heavyweight option).
"""
from __future__ import annotations

import json
import os
from typing import Any

import numpy as np


def save_pytree(path: str, tree: Any) -> None:
    """Save a pytree of arrays to <path>.npz + <path>.json structure."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    np.savez(path + ".npz", **{
        f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)
    })
    with open(path + ".json", "w") as f:
        json.dump({"treedef": str(treedef), "n_leaves": len(leaves)}, f)


def load_pytree(path: str, like: Any) -> Any:
    """Restore into the structure of `like` (shape/dtype validated)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(like)
    with open(path + ".json") as f:
        meta = json.load(f)
    if meta["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves, "
            f"`like` has {len(leaves)}")
    if meta["treedef"] != str(treedef):
        raise ValueError(
            f"checkpoint tree structure {meta['treedef']} does not match "
            f"`like` structure {treedef}")
    with np.load(path + ".npz") as data:
        loaded = [data[f"leaf_{i}"] for i in range(len(leaves))]
    for i, (got, exp) in enumerate(zip(loaded, leaves)):
        if got.shape != tuple(exp.shape):
            raise ValueError(
                f"checkpoint leaf {i} shape {got.shape} != expected "
                f"{tuple(exp.shape)}")
        if got.dtype != np.dtype(exp.dtype):
            raise ValueError(
                f"checkpoint leaf {i} dtype {got.dtype} != expected "
                f"{np.dtype(exp.dtype)}")
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(x) for x in loaded])
