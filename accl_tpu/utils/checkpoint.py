"""Checkpoint/restore for model state.

The reference is a stateless communication library — its only resume
mechanism is the retry queue's current_step (SURVEY §5).  The framework
still ships checkpointing for the model layer:

- `save_pytree`/`load_pytree`: dependency-free host snapshots of a
  parameter pytree (npz + structure manifest with validation).
- `save_sharded`/`load_sharded`: distributed checkpoints via orbax —
  mesh-sharded train state is written from and restored onto its
  shardings, so a multi-chip training job resumes without gathering
  parameters through one host.
"""
from __future__ import annotations

import json
import os
from typing import Any

import numpy as np


def save_pytree(path: str, tree: Any) -> None:
    """Save a pytree of arrays to <path>.npz + <path>.json structure."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    np.savez(path + ".npz", **{
        f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)
    })
    with open(path + ".json", "w") as f:
        json.dump({"treedef": str(treedef), "n_leaves": len(leaves)}, f)


def load_pytree(path: str, like: Any) -> Any:
    """Restore into the structure of `like` (shape/dtype validated)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(like)
    with open(path + ".json") as f:
        meta = json.load(f)
    if meta["n_leaves"] != len(leaves):
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves, "
            f"`like` has {len(leaves)}")
    if meta["treedef"] != str(treedef):
        raise ValueError(
            f"checkpoint tree structure {meta['treedef']} does not match "
            f"`like` structure {treedef}")
    with np.load(path + ".npz") as data:
        loaded = [data[f"leaf_{i}"] for i in range(len(leaves))]
    for i, (got, exp) in enumerate(zip(loaded, leaves)):
        if got.shape != tuple(exp.shape):
            raise ValueError(
                f"checkpoint leaf {i} shape {got.shape} != expected "
                f"{tuple(exp.shape)}")
        if got.dtype != np.dtype(exp.dtype):
            raise ValueError(
                f"checkpoint leaf {i} dtype {got.dtype} != expected "
                f"{np.dtype(exp.dtype)}")
    return jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(x) for x in loaded])


def _require_absolute(path: str) -> str:
    # each host writes its own shards: a relative path would resolve
    # per-process and scatter the checkpoint across working directories
    if not os.path.isabs(path):
        raise ValueError(f"sharded checkpoint path must be absolute: {path!r}")
    return path


def save_sharded(path: str, tree: Any) -> None:
    """Write a (possibly mesh-sharded) pytree as an orbax checkpoint.

    `path` must be an absolute directory path and must not already
    exist — save each step to its own path (e.g. ``.../step_000100``)
    so a crash mid-write never destroys the previous recovery point."""
    import orbax.checkpoint as ocp

    path = _require_absolute(path)
    if os.path.exists(path):
        raise ValueError(
            f"checkpoint path exists: {path!r} — write each step to a "
            f"fresh path; overwriting would delete the only recovery "
            f"point before the new write is finalized")
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)


def load_sharded(path: str, like: Any) -> Any:
    """Restore an orbax checkpoint onto the shapes/dtypes/shardings of
    `like` (typically the freshly-sharded init state): each device
    reads only its own shards.  Non-array leaves (step counters etc.)
    are restored by shape/dtype via numpy coercion."""
    import jax
    import orbax.checkpoint as ocp

    def abstract(x):
        if not hasattr(x, "shape") or not hasattr(x, "dtype"):
            x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=getattr(x, "sharding", None))

    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_require_absolute(path),
                             jax.tree_util.tree_map(abstract, like))
