"""Device/topology inspection and capability reporting.

Reference analogs: the hwid capability word parse (accl.cpp:1066-1080
parse_hwid — stack type, compression/arith enables, git commit) and the
xclbin metadata scan locating kernels/memories (driver/utils/
xclbin_scan).  On TPU the equivalents are the platform/device attributes
and ICI topology coordinates jax exposes.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Capabilities:
    """The hwid-equivalent capability record."""

    platform: str
    num_devices: int
    device_kind: str
    has_remote_dma: bool  # inter-chip RDMA (multi-device TPU)
    has_arith: bool = True       # reduce lanes always built
    has_compression: bool = True  # compression lanes always built
    coords: list = field(default_factory=list)

    def hwid(self) -> int:
        """Pack into a capability word like the reference hwid
        (accl.cpp:1069-1079 bit layout spirit, not bit-exact)."""
        word = 0
        word |= {"cpu": 0, "tpu": 1, "gpu": 2}.get(self.platform, 7)
        word |= int(self.has_arith) << 4
        word |= int(self.has_compression) << 5
        word |= int(self.has_remote_dma) << 6
        word |= (self.num_devices & 0xFFFF) << 8
        return word


def probe() -> Capabilities:
    import jax

    devs = jax.devices()
    coords = [getattr(d, "coords", None) for d in devs]
    return Capabilities(
        platform=jax.default_backend(),
        num_devices=len(devs),
        device_kind=devs[0].device_kind if devs else "none",
        has_remote_dma=jax.default_backend() == "tpu" and len(devs) > 1,
        coords=coords,
    )


def parse_shape(spec: str) -> tuple:
    """Parse an axis-layout spec like ``"4x2"`` / ``"2x2x2"`` into a
    shape tuple.  Raises a naming error on malformed specs (the env
    clear-error contract for ``ACCL_FABRIC``)."""
    try:
        shape = tuple(int(tok) for tok in str(spec).lower().split("x"))
    except ValueError:
        shape = ()
    if not shape or any(a < 1 for a in shape):
        raise ValueError(
            f"axis layout {spec!r} is not AxBxC... with positive "
            f"extents (e.g. ACCL_FABRIC=4x2)")
    return shape


def grid_coords(nranks: int, shape) -> list:
    """Row-major mesh coordinates for an emu world's configurable axis
    layout (the explicit-coords path of :func:`link_axis`): rank r ->
    (c0, c1, ...) over ``shape``.  The product of the extents must
    cover the world; surplus positions are simply never minted."""
    shape = tuple(int(a) for a in shape)
    total = 1
    for a in shape:
        total *= a
    if total < nranks:
        raise ValueError(
            f"axis layout {'x'.join(map(str, shape))} holds {total} "
            f"ranks but the world has {nranks}")
    coords = []
    for r in range(nranks):
        c, rem = [], r
        for a in reversed(shape):
            c.append(rem % a)
            rem //= a
        coords.append(tuple(reversed(c)))
    return coords


def link_axis(src: int, dst: int, coords=None,
              nranks: int | None = None, shape=None) -> str:
    """Classify a src->dst link against the world's topology axes —
    the rendering key perf_doctor uses for the r15 link matrix and the
    grouping the topology-aware tuner (accl_tpu/tuning) selects per
    axis; both go through the same Fabric so the labels never
    disagree.

    With per-device ICI ``coords`` (utils.topology.probe on TPU) the
    label is the mesh axis the two devices differ on (``x``/``y``/``z``
    single-axis, ``multi-axis`` otherwise).  ``shape`` (an emu world's
    configurable axis layout, e.g. ``(4, 2)`` from ``ACCL_FABRIC=4x2``)
    derives the same labels from row-major grid coordinates — the
    explicit-coords path for worlds whose coords would otherwise
    default from rank.  With neither (emu worlds: a logical ring
    fabric) it is the ring distance: ``ring+1``/``ring-1`` for the two
    neighbor directions, ``hop<k>`` for longer chords."""
    if coords is None and shape is not None and nranks:
        coords = grid_coords(nranks, shape)
    if coords is not None and 0 <= src < len(coords) \
            and 0 <= dst < len(coords) \
            and coords[src] is not None and coords[dst] is not None:
        diffs = [i for i, (a, b) in
                 enumerate(zip(coords[src], coords[dst])) if a != b]
        if len(diffs) == 1:
            return "xyz"[diffs[0]] if diffs[0] < 3 else f"axis{diffs[0]}"
        return "multi-axis" if diffs else "self"
    if nranks and nranks > 1:
        d = (dst - src) % nranks
        if d == 0:
            return "self"
        if d == 1:
            return "ring+1"
        if d == nranks - 1:
            return "ring-1"
        return f"hop{min(d, nranks - d)}"
    return "unknown"


def dump() -> str:
    """Human-readable topology dump (the dump_* observability family)."""
    import jax

    cap = probe()
    lines = [
        f"platform={cap.platform} kind={cap.device_kind} "
        f"n={cap.num_devices} hwid={cap.hwid():#x}"
    ]
    for d in jax.devices():
        lines.append(
            f"  device {d.id}: process={d.process_index} "
            f"coords={getattr(d, 'coords', '-')}"
        )
    return "\n".join(lines)
