"""Device/topology inspection and capability reporting.

Reference analogs: the hwid capability word parse (accl.cpp:1066-1080
parse_hwid — stack type, compression/arith enables, git commit) and the
xclbin metadata scan locating kernels/memories (driver/utils/
xclbin_scan).  On TPU the equivalents are the platform/device attributes
and ICI topology coordinates jax exposes.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Capabilities:
    """The hwid-equivalent capability record."""

    platform: str
    num_devices: int
    device_kind: str
    has_remote_dma: bool  # inter-chip RDMA (multi-device TPU)
    has_arith: bool = True       # reduce lanes always built
    has_compression: bool = True  # compression lanes always built
    coords: list = field(default_factory=list)

    def hwid(self) -> int:
        """Pack into a capability word like the reference hwid
        (accl.cpp:1069-1079 bit layout spirit, not bit-exact)."""
        word = 0
        word |= {"cpu": 0, "tpu": 1, "gpu": 2}.get(self.platform, 7)
        word |= int(self.has_arith) << 4
        word |= int(self.has_compression) << 5
        word |= int(self.has_remote_dma) << 6
        word |= (self.num_devices & 0xFFFF) << 8
        return word


def probe() -> Capabilities:
    import jax

    devs = jax.devices()
    coords = [getattr(d, "coords", None) for d in devs]
    return Capabilities(
        platform=jax.default_backend(),
        num_devices=len(devs),
        device_kind=devs[0].device_kind if devs else "none",
        has_remote_dma=jax.default_backend() == "tpu" and len(devs) > 1,
        coords=coords,
    )


def dump() -> str:
    """Human-readable topology dump (the dump_* observability family)."""
    import jax

    cap = probe()
    lines = [
        f"platform={cap.platform} kind={cap.device_kind} "
        f"n={cap.num_devices} hwid={cap.hwid():#x}"
    ]
    for d in jax.devices():
        lines.append(
            f"  device {d.id}: process={d.process_index} "
            f"coords={getattr(d, 'coords', '-')}"
        )
    return "\n".join(lines)
