"""Best-effort persistent XLA compilation cache.

Chip claim windows on the shared TPU are scarce and short; a cold
bench/sweep attempt pays ~10 program compiles at 20-40 s each before it
measures anything.  Enabling JAX's persistent compilation cache lets
every retry attempt and every chip-facing tool (bench.py worker,
scripts/chip_session.py, scripts/flash_tune.py) reuse the executables
the previous window already paid for, so a brief window goes to
MEASUREMENT instead of recompiles.

Best-effort by design: backends that cannot serialize executables
(some remote/tunneled plugins) simply skip the cache — enabling it
must never break a measurement run.
"""
from __future__ import annotations

import getpass
import os
import tempfile


def _default_dir() -> str:
    # per-user path: a world-shared fixed dir would be created by the
    # first user and silently reject every other user's cache writes
    # (and is an executable-cache-poisoning surface on a shared host)
    try:
        user = getpass.getuser()
    except Exception:  # noqa: BLE001 — no passwd entry in a container
        user = f"uid{os.getuid()}" if hasattr(os, "getuid") else "user"
    return os.path.join(tempfile.gettempdir(), f"accl-jax-cache-{user}")


def enable(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `path` (default:
    $ACCL_COMPILE_CACHE or a per-user tmpdir location).  Returns the
    cache dir, or None when the cache could not be enabled.  Call
    after `import jax` and before the first compile."""
    import jax

    path = path or os.environ.get("ACCL_COMPILE_CACHE", _default_dir())
    # snapshot both settings so a failure restores EXACTLY the prior
    # state — including a cache some earlier call successfully enabled
    prev = {}
    for key in ("jax_persistent_cache_min_compile_time_secs",
                "jax_compilation_cache_dir"):
        try:
            prev[key] = getattr(jax.config, key)
        except AttributeError:
            pass
    try:
        os.makedirs(path, exist_ok=True)
        # 0 = cache every compile: the tunnel RTT makes every remote
        # compile round-trip expensive regardless of XLA's own compile
        # time, so even "quick" programs are worth persisting
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_compilation_cache_dir", path)
        return path
    except Exception as e:  # noqa: BLE001 — never break a bench run
        for key, val in prev.items():
            try:
                jax.config.update(key, val)
            except Exception:  # noqa: BLE001
                pass
        from .logging import get_logger

        get_logger().warning("compile-cache disabled: %s: %s",
                             type(e).__name__, e)
        return None
