"""jax version-compatibility shims.

The codebase targets the current jax spelling (`jax.shard_map` with the
`check_vma` kwarg); older jax releases (< 0.5) ship shard_map under
`jax.experimental.shard_map` with the `check_rep` spelling instead.
Resolving the difference here keeps every call site on one spelling
while both the baked-in container jax and a current install run the
full stack.
"""
from __future__ import annotations

from typing import Callable


def shard_map(f: Callable, mesh, in_specs, out_specs,
              check_vma: bool = True) -> Callable:
    """`jax.shard_map` on current jax; the experimental spelling on
    older jax (where the replication lint is disabled — see below)."""
    import jax

    sm = getattr(jax, "shard_map", None)
    # identity check: test harnesses alias THIS shim onto jax.shard_map
    # for old-jax runs — resolving it back would recurse forever
    if sm is not None and sm is not shard_map:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    # check_rep is always disabled on the old branch: the pre-vma
    # replication checker cannot infer replication through psum-in-grad
    # patterns the current checker handles, and rejects valid programs
    # (e.g. the training step's replicated loss).  It is a static lint,
    # not an execution semantic — numeric parity tests still hold.
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def install(jax_module) -> None:
    """Alias this shim onto `jax.shard_map` when the installed jax
    predates the top-level spelling, so harness/script code written
    against current jax runs unchanged.  Idempotent; a no-op on
    current jax."""
    if not hasattr(jax_module, "shard_map"):
        jax_module.shard_map = shard_map


def tpu_compiler_params(**kwargs):
    """Pallas-TPU compiler params: `pltpu.CompilerParams` on current
    jax, its old spelling `pltpu.TPUCompilerParams` before the rename.
    Kwargs the old dataclass predates (e.g. has_side_effects) are
    dropped there — the old-jax rung only runs kernels in interpret
    mode, where they have no effect anyway."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in kwargs.items() if k in known}
    return cls(**kwargs)


def axis_size(axis) -> int:
    """Static size of a named mesh axis inside shard_map: `lax.axis_size`
    on current jax; on older jax `lax.psum(1, axis)`, whose constant
    fast path returns the same static int."""
    import jax.lax as lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return lax.psum(1, axis)
