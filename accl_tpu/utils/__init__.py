from .timing import Timer  # noqa: F401
from .logging import get_logger  # noqa: F401
