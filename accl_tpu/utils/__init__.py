from .logging import get_logger  # noqa: F401
from .timing import Timer, timed  # noqa: F401
