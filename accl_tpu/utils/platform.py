"""Host-platform environment helpers (jax-free at import time).

One home for the XLA virtual-device-count dance so its rule lives in
one place (tests/conftest.py keeps a private inline copy because its
bootstrap must run before this package can be imported).
"""
from __future__ import annotations

import os

_FLAG = "xla_force_host_platform_device_count"


def ensure_host_device_count(n: int) -> None:
    """Make the CPU platform expose at least `n` virtual devices by
    appending ``--xla_force_host_platform_device_count=n`` to
    ``XLA_FLAGS`` — a no-op if the flag is already set (the caller's
    explicit choice wins).  Must run BEFORE the first jax import; to
    actually select the CPU platform also call
    ``jax.config.update("jax_platforms", "cpu")`` after importing jax
    (environment hooks may pin a hardware platform at interpreter
    start; see docs/troubleshooting.md)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --{_FLAG}={n}".strip()
