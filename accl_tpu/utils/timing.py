"""Benchmark timer (reference: driver/xrt/include/accl/timing.hpp).

One wall-clock timing primitive for the whole tree: :class:`Timer` is
the start/end object (and context manager), and :func:`timed` — the
block-timer previously duplicated in utils/profiling.py — is a thin
context manager over it.  Both expose nanoseconds and microseconds
consistently (duration_ns / duration_us; durationUs is kept as the
reference-shaped alias).
"""
from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


class Timer:
    """Wall-clock timer with the reference Timer's start/end/duration
    shape."""

    def __init__(self):
        self._start = 0.0
        self._end = 0.0
        self._running = False

    def start(self) -> None:
        self._start = time.perf_counter()
        self._running = True

    def end(self) -> None:
        self._end = time.perf_counter()
        self._running = False

    def _elapsed_s(self) -> float:
        end = time.perf_counter() if self._running else self._end
        return end - self._start

    def duration_us(self) -> float:
        return self._elapsed_s() * 1e6

    def duration_ns(self) -> float:
        return self._elapsed_s() * 1e9

    #: reference spelling (timing.hpp durationUs)
    durationUs = duration_us

    def __enter__(self) -> "Timer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.end()


@contextlib.contextmanager
def timed(label: str, results: Optional[dict] = None) -> Iterator[Timer]:
    """Time a block with a :class:`Timer`; appends ns to results[label]
    if given (the profiling.timed shape — importable from either
    module, one implementation)."""
    t = Timer()
    t.start()
    try:
        yield t
    finally:
        t.end()
        if results is not None:
            results.setdefault(label, []).append(t.duration_ns())
