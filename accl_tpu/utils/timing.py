"""Benchmark timer (reference: driver/xrt/include/accl/timing.hpp)."""
from __future__ import annotations

import time


class Timer:
    """Wall-clock timer with the reference Timer's start/end/duration
    shape (duration in microseconds)."""

    def __init__(self):
        self._start = 0.0
        self._end = 0.0
        self._running = False

    def start(self) -> None:
        self._start = time.perf_counter()
        self._running = True

    def end(self) -> None:
        self._end = time.perf_counter()
        self._running = False

    def durationUs(self) -> float:
        end = time.perf_counter() if self._running else self._end
        return (end - self._start) * 1e6

    def duration_ns(self) -> float:
        end = time.perf_counter() if self._running else self._end
        return (end - self._start) * 1e9

    def __enter__(self) -> "Timer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.end()
