"""Shared flash-attention schedule sweep harness.

One sweep loop used by both live-chip tools (scripts/flash_tune.py,
scripts/chip_session.py) so methodology fixes (round structure,
dead-candidate handling, flops accounting, matmul-peak context) happen
in exactly one place.  The matmul peak is measured interleaved with the
candidates because the shared chip's contention windows can depress
identical kernels 30x — only same-window ratios mean anything.
"""
from __future__ import annotations

import sys
import time

#: the bench shape of record (BENCH_r{N} flash_d128 detail keys):
#: head-packed [B*H, T, D] causal attention, f32 inputs, bf16 MXU.
#: D=64 sweeps use H=8, D=64 — same total flops (H*D preserved).
B, T, H, D = 4, 2048, 4, 128
MM_N = 4096


def causal_flops():
    """Matmul flops of the sweep shape (causal halves the score work).
    Invariant under the D=64 variant (H doubles as D halves)."""
    return 4 * B * H * T * T * D / 2


def make_inputs(jax, jnp, d=D):
    """(q, k, v) head-packed operands of the sweep shape; `d` picks the
    head dim (64 or 128) with H scaled to keep total flops fixed."""
    h = (H * D) // d
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    def mk(kk):
        return jax.random.normal(kk, (B * h, T, d), jnp.float32)

    return mk(k1), mk(k2), mk(k3)


def matmul_context(jax, jnp):
    """(fn, a, b) for the bf16 matmul that anchors the MXU peak."""
    ka, kb = jax.random.split(jax.random.PRNGKey(7))
    ma = jax.random.normal(ka, (MM_N, MM_N), jnp.bfloat16)
    mb = jax.random.normal(kb, (MM_N, MM_N), jnp.bfloat16)
    def mm(x, y):
        return (x @ y).astype(jnp.bfloat16)

    return mm, ma, mb


def make_variant(bq, bk, ck=None, qt=1, fd=False, cast=False,
                 kernel="resident", sm=None):
    """A schedule candidate closure over flash_attention_packed.
    ``sm``: static_max pin (the r5 VPU-minimal schedule — drops the
    max/alpha/clamp passes; exact within f32 range of the pin)."""
    from ..ops.flash import flash_attention_packed as fap

    def fn(x, kk, vv):
        return fap(x, kk, vv, causal=True, kernel=kernel, block_q=bq,
                   block_k=bk, chunk_k=ck, q_tiles=qt, fuse_denom=fd,
                   kv_cast_scratch=cast, static_max=sm)
    return fn


def run_sweep(jax, jnp, timed_chain, cands, rounds=3, log=None, d=D):
    """Interleaved best-of-rounds sweep.

    Returns (best, best_mm): best maps candidate name -> best seconds
    (or an error string for candidates that failed to compile/run);
    best_mm is the matmul's best seconds in the same windows.
    """
    if log is None:
        def log(msg):
            print(msg, file=sys.stderr, flush=True)
    q, k, v = make_inputs(jax, jnp, d=d)
    mm, ma, mb = matmul_context(jax, jnp)

    best = {n: None for n in cands}
    best_mm = None
    dead: set = set()
    for r in range(rounds):
        dmm = timed_chain(mm, ma, iters=48, trials=1, consts=(mb,))
        best_mm = dmm if best_mm is None else min(best_mm, dmm)
        for name, fn in cands.items():
            if name in dead:
                continue
            t0 = time.perf_counter()
            try:
                dv = timed_chain(fn, q, iters=64, trials=1, consts=(k, v))
            except Exception as e:  # noqa: BLE001 — one candidate dying
                dead.add(name)      # must not take down the sweep
                best[name] = f"{type(e).__name__}: {e}"
                log(f"  {name}: DEAD {e}")
                continue
            log(f"  [r{r}] {name}: {dv * 1e3:.2f} ms "
                f"(wall {time.perf_counter() - t0:.0f}s)")
            prev = best[name]
            best[name] = dv if prev is None else min(prev, dv)
    return best, best_mm


def report(best, best_mm):
    """{matmul_bf16_tflops, schedules: {name: {tflops, mxu_frac}}}."""
    flops = causal_flops()
    mm_tf = 2 * MM_N**3 / best_mm / 1e12
    res = {"matmul_bf16_tflops": round(mm_tf, 2), "schedules": {}}
    for name, dt in best.items():
        if isinstance(dt, float):
            tf = flops / dt / 1e12
            res["schedules"][name] = {
                "tflops": round(tf, 2), "mxu_frac": round(tf / mm_tf, 3)}
        else:
            res["schedules"][name] = {"error": dt}
    return res
