"""Chained-timing harness for remote-tunneled devices — shared by
bench.py (the metric of record) and scripts/kernel_tune.py.

Methodology (why this shape):
- iterations are CHAINED INSIDE ONE COMPILED PROGRAM (lax.fori_loop;
  the carry feeds forward so no elision is possible) — one dispatch per
  trial regardless of iteration count.  Host-side per-call chaining is
  wrong on a tunneled device in BOTH directions: with few iterations
  the device time is smaller than the RTT being subtracted and the
  residue is noise (observed: a 12 B/elem cast pair "measuring" 3x the
  chip's HBM roofline), with many the dispatch stream is the bottleneck
  and the kernel is underestimated;
- fixed operands ride as traced ARGUMENTS via `consts` (a closure
  would bake them into the program as constants — the remote compile
  tunnel rejects a 256 MB proto with HTTP 413);
- completion is forced by a scalar device->host readback (cannot
  resolve before the producing loop finishes); the MINIMUM observed
  round-trip cost is subtracted — a running min refreshed with one
  probe per timed_chain call, never a median: a congested init window
  once banked a ~10x-inflated sync estimate whose subtraction from
  later clean-window trials reported rates ABOVE the chip's physical
  peak (matmul "431 TF" on a ~197 TF part).  The min can only
  under-subtract, so congestion deflates a sample (and best-of-rounds
  discards it) instead of inflating it past physics;
- minimum over trials, not median: the tunnel lands on different (and
  differently-loaded) chips across windows, swinging identical kernels
  >10x — the fastest window estimates hardware capability; a median
  would report the neighbors' workload.  Quantities that will be
  RATIOED must share windows (interleave via `timed_chain_ab`).
"""
from __future__ import annotations

import time


def make_harness(jax, jnp):
    """Returns (probe, timed_chain, timed_chain_ab, sync_s)."""
    from jax import lax

    probe = jax.jit(lambda x: x.reshape(-1)[-1])

    warm = jnp.zeros((1024,), jnp.float32)
    float(probe(warm))  # compile the probe

    # running MINIMUM of the completion-barrier round trip (see module
    # docstring: a banked median from a congested window over-subtracts
    # and reports rates above the chip's physical peak)
    sync_state = {"min": float("inf")}

    def _sync_sample() -> float:
        t0 = time.perf_counter()
        float(probe(warm))
        dt = time.perf_counter() - t0
        if dt < sync_state["min"]:
            sync_state["min"] = dt
        return dt

    for _ in range(3):
        _sync_sample()
    sync_s = sync_state["min"]

    chain_cache: dict = {}

    def timed_chain(fn, x0, iters, trials=5, consts=()):
        """BEST (minimum) per-iteration seconds of the in-jit chained
        loop `fori_loop(0, iters, lambda _, v: fn(v, *consts), x0)`.
        fn must be shape/dtype-preserving in its first argument."""
        # key includes operand shapes/dtypes: the same fn re-timed on a
        # different shape must pay its compile+warm OUTSIDE the timed
        # trials (jax.jit would otherwise retrace inside the first one)
        sig = tuple((v.shape, str(v.dtype)) for v in (x0, *consts))
        # key on the fn OBJECT (functions/partials are hashable): keying
        # on id(fn) would only be correct while the cached closure keeps
        # fn alive, a lifetime coupling one refactor away from returning
        # a stale compiled chain for a recycled id
        key = (fn, iters, sig)
        chained = chain_cache.get(key)
        if chained is None:
            chained = jax.jit(lambda x, *cs: lax.fori_loop(
                0, iters, lambda _, v: fn(v, *cs), x))
            float(probe(chained(x0, *consts)))  # compile + warm
            chain_cache[key] = chained
        _sync_sample()  # refresh the running-min RTT in this window
        sync_min = sync_state["min"]
        vals = []
        for _ in range(trials):
            t0 = time.perf_counter()
            out = chained(x0, *consts)
            float(probe(out))  # true completion barrier
            elapsed = time.perf_counter() - t0
            # RTT jitter can push elapsed below the observed sync min;
            # fall back to the unsubtracted time, never negative
            net = elapsed - sync_min if elapsed > sync_min else elapsed
            vals.append(net / iters)
        return min(vals)

    def timed_chain_ab(fns: dict, x0, iters, trials=5, consts=()) -> dict:
        """Interleaved A/B timing: one trial of each fn per round, best
        window per fn — ratioed quantities must share windows."""
        best = {k: None for k in fns}
        for _ in range(trials):
            for k, fn in fns.items():
                dt = timed_chain(fn, x0, iters, trials=1, consts=consts)
                if best[k] is None or dt < best[k]:
                    best[k] = dt
        return best

    return probe, timed_chain, timed_chain_ab, sync_s
