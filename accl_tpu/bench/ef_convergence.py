"""EF-convergence lane: does error feedback close the int8 wire gap?

The r17 quantized lane ships gradients as int8 + per-block fp32 scales
(4:1 wire compression); the EQuARX-style error feedback carries each
hop's requantization error into the next hop's quantization input
(ops/quantized.py).  The sweep records whether that per-hop carry
matters where it counts — the LOSS TRAJECTORY of a real training run:

- three lanes train the flagship transformer LM under data parallelism
  with IDENTICAL init, data order, and learning rate — only the
  gradient all-reduce differs:

  * ``fp32``     — exact ``lax.pmean`` (the reference trajectory)
  * ``int8``     — quantized ring, no error carry
  * ``int8_ef``  — quantized ring + per-hop error feedback

- everything is deterministic (no stochastic rounding, fixed seeds),
  so the recorded divergence is pure quantization arithmetic, not
  noise: a re-run reproduces the CSV bit-for-bit on the same jax.

The committed record (bench/results/ef_convergence_rNN.csv/.md) is the
evidence behind the "int8 wire lane tracks fp32" claim in the docs;
the summary gates that EVERY quantized lane's mean |loss - fp32| stays
under TRACK_TOL.  EF vs raw is reported as data, not gated: with
deterministic round-to-nearest the per-hop error carry redistributes
requantization error rather than strictly shrinking it, so at healthy
scales both lanes sit at the same ~1e-4 noise floor — EF's guarantee
(bias that dithers out instead of growing linearly in P) only
separates from raw int8 at large ring sizes or biased rounding.

Run via ``scripts/run_sweep.py --ef-convergence`` (spawns host-platform
virtual devices; no accl world needed — the lanes are jax-level
collectives inside shard_map, the same route sync_gradients takes in
the 3D example).
"""
from __future__ import annotations

import csv
from typing import Optional, Sequence

#: lane -> (compress, error_feedback) for sync_gradients
LANES = {
    "fp32": (None, False),
    "int8": ("int8", False),
    "int8_ef": ("int8", True),
}

#: gate: a quantized lane's mean |loss - fp32| over the run must stay
#: under this (the trajectories at these scales agree to ~1e-4; 5e-3
#: leaves an order of magnitude of slack before "diverged")
TRACK_TOL = 5e-3


def _make_step(mesh, cfg, lane: str, lr: float):
    """One jitted SGD step for a lane.

    Params and tokens enter pre-stacked on a leading dp dim with
    P("dp") specs (every shard holds its own copy/slice and indexes
    [0]) — the repo-wide idiom for driving sync_gradients on old-jax
    shard_map, where replicated-input grads would otherwise be
    auto-psummed by the transpose (no lax.pvary on 0.4.37).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..models.transformer import loss_fn
    from ..parallel.strategies import sync_gradients
    from ..utils.compat import shard_map as _shard_map

    compress, ef = LANES[lane]

    def body(params_stacked, tokens):
        params = jax.tree_util.tree_map(lambda x: x[0], params_stacked)
        toks = tokens[0]

        def local_loss(p):
            s, c = loss_fn(p, toks, cfg)
            return s / c

        loss, grads = jax.value_and_grad(local_loss)(params)
        grads = sync_gradients(grads, axis="dp", compress=compress,
                               mean=True, error_feedback=ef)
        loss = lax.pmean(loss, "dp")
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g.astype(p.dtype), params, grads)
        # re-stack so the outputs ride the same P("dp") layout in
        return (jax.tree_util.tree_map(lambda x: x[None], new_params),
                loss[None])

    fn = _shard_map(body, mesh=mesh, in_specs=(P("dp"), P("dp")),
                    out_specs=(P("dp"), P("dp")))
    return jax.jit(fn)


def run_ef_convergence(writer, steps: int = 40, dp: int = 4,
                       batch: int = 4, seq: int = 32, lr: float = 0.2,
                       seed: int = 0,
                       lanes: Sequence[str] = ("fp32", "int8", "int8_ef"),
                       log=lambda s: None) -> dict:
    """Train one small LM per lane on identical data; write the wide
    per-step loss CSV (step, <lane>...) to `writer` and return the
    summary dict (final losses + deviations vs fp32)."""
    import jax
    import numpy as np

    from ..models.transformer import ModelConfig, init_params
    from ..parallel.mesh import MeshConfig, make_mesh

    devices = jax.devices()
    if len(devices) < dp:
        raise RuntimeError(
            f"need {dp} devices for the dp axis, have {len(devices)} — "
            f"set XLA_FLAGS=--xla_force_host_platform_device_count={dp}")
    mesh = make_mesh(MeshConfig(dp=dp), devices=devices[:dp])

    cfg = ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=4,
                      d_head=16, d_ff=256)
    rng = np.random.default_rng(seed)
    params0 = init_params(rng, cfg)
    # the whole run's token stream up front: [steps, dp, batch, seq] —
    # every lane consumes the exact same bytes in the same order.  A
    # noisy successor chain (next = prev + 1 mod vocab, 10% resets)
    # gives the LM something learnable so the trajectories DESCEND and
    # real gradient signal flows through the quantized ring.
    tokens = np.empty((steps, dp, batch, seq), np.int32)
    tokens[..., 0] = rng.integers(0, cfg.vocab,
                                  size=(steps, dp, batch))
    for t in range(1, seq):
        succ = (tokens[..., t - 1] + 1) % cfg.vocab
        noise = rng.integers(0, cfg.vocab, size=(steps, dp, batch))
        keep = rng.random(size=(steps, dp, batch)) < 0.9
        tokens[..., t] = np.where(keep, succ, noise)

    import jax.numpy as jnp
    traj: dict = {}
    for lane in lanes:
        step = _make_step(mesh, cfg, lane, lr)
        params = jax.tree_util.tree_map(
            lambda x: jnp.stack([x] * dp), params0)
        losses = []
        for i in range(steps):
            params, loss = step(params, jnp.asarray(tokens[i]))
            losses.append(float(loss[0]))
        traj[lane] = losses
        log(f"[ef] lane {lane:8s} loss {losses[0]:.4f} -> "
            f"{losses[-1]:.4f} over {steps} steps")

    w = csv.writer(writer)
    w.writerow(["step"] + list(lanes))
    for i in range(steps):
        w.writerow([i] + [f"{traj[lane][i]:.6f}" for lane in lanes])

    summary = {"steps": steps, "dp": dp, "batch": batch, "seq": seq,
               "lr": lr, "seed": seed,
               "final": {lane: traj[lane][-1] for lane in lanes}}
    if "fp32" in traj:
        ref = np.asarray(traj["fp32"])
        for lane in lanes:
            if lane == "fp32":
                continue
            dev = np.abs(np.asarray(traj[lane]) - ref)
            summary[f"{lane}_mean_abs_dev"] = float(dev.mean())
            summary[f"{lane}_max_abs_dev"] = float(dev.max())
            log(f"[ef] {lane} vs fp32: mean |dloss| {dev.mean():.3e}, "
                f"max {dev.max():.3e}")
    return summary


def write_summary_md(path: str, summary: dict,
                     csv_name: Optional[str] = None) -> None:
    """The committed .md companion: run shape, final losses, and the
    EF-vs-raw deviation verdict."""
    final = summary["final"]
    lines = [
        "# int8 error-feedback convergence record",
        "",
        f"- run: {summary['dp']} dp ranks x {summary['batch']} "
        f"batch x {summary['seq']} seq, {summary['steps']} SGD steps, "
        f"lr {summary['lr']}, seed {summary['seed']} (deterministic — "
        f"no stochastic rounding)",
    ]
    if csv_name:
        lines.append(f"- trajectory: {csv_name} (per-step loss, one "
                     f"column per lane)")
    lines += [
        "",
        "| lane | final loss | mean \\|loss - fp32\\| | "
        "max \\|loss - fp32\\| |",
        "|---|---|---|---|",
    ]
    for lane in final:
        mean_d = summary.get(f"{lane}_mean_abs_dev")
        max_d = summary.get(f"{lane}_max_abs_dev")
        fmt = (lambda v: "—" if v is None else f"{v:.3e}")
        lines.append(f"| {lane} | {final[lane]:.6f} | {fmt(mean_d)} | "
                     f"{fmt(max_d)} |")
    devs = {k[:-len("_mean_abs_dev")]: v for k, v in summary.items()
            if k.endswith("_mean_abs_dev")}
    if devs:
        worst = max(devs.values())
        verdict = "PASS" if worst <= TRACK_TOL else "FAIL"
        lines += [
            "",
            f"- gate ({verdict}): every quantized lane must track the "
            f"fp32 trajectory within mean |dloss| <= {TRACK_TOL:g} "
            f"(worst lane: {worst:.3e})",
            "- EF vs raw int8 is reported, not gated: with "
            "round-to-nearest the per-hop error carry redistributes "
            "requantization error rather than strictly shrinking it — "
            "its bias bound only separates from raw at large ring "
            "sizes or biased rounding",
        ]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
