from .sweep import SweepConfig, run_sweep  # noqa: F401
