"""Driver call-rate / small-message latency benchmark.

Measures how many collective CALLS per second the TPU-backend driver
path sustains (descriptor -> gang scheduler -> compiled SPMD
collective -> scatter-back) against the raw-shard_map ceiling on the
same mesh — the host-side dispatch overhead the reference pays through
its hostctrl MMIO fast path (driver/xrt/src/fpgadevice.cpp:46-180;
per-call work is the FPGAQueue + 8-10 register writes).

Raw ceiling: a jitted shard_map psum on an identical global array,
called in the same loop — everything above that rate is driver
overhead (gang assembly, buffer resolution, scatter-back).

Usage: python -m accl_tpu.bench.callrate [--ranks N] [--count N]
       [--iters N] [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import time


def run(nranks: int = 4, count: int = 1024, iters: int = 300,
        platform: str = "cpu") -> dict:
    import numpy as np

    import jax

    if platform:
        # runtime config update, NOT the env var: site hooks may have
        # pinned a hardware platform at interpreter start and the claim
        # can hang when the chip is busy (same discipline as bench.py
        # workers / tests/conftest.py)
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.tpu import TpuWorld

    out: dict = {"nranks": nranks, "count": count, "iters": iters}

    with TpuWorld(nranks) as w:
        def worker(accl, rank):
            rng = np.random.default_rng(rank)
            s = accl.create_buffer_like(
                rng.standard_normal(count).astype(np.float32))
            r = accl.create_buffer(count, np.float32)
            # warm the compile cache + gang path
            for _ in range(3):
                accl.allreduce(s, r, count, ReduceFunction.SUM)
            t0 = time.perf_counter()
            for _ in range(iters):
                accl.allreduce(s, r, count, ReduceFunction.SUM)
            dt_staged = time.perf_counter() - t0
            # device-resident path (reference zero-copy call path,
            # accl.cpp:796-839 with FPGA-resident buffers): no host
            # staging per call — the training-loop call rate
            t0 = time.perf_counter()
            for _ in range(iters):
                accl.allreduce(s, r, count, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            dt_res = time.perf_counter() - t0
            return dt_staged, dt_res

        dts = w.run(worker)
        # ranks run concurrently; wall time is the slowest member
        wall = max(d[0] for d in dts)
        wall_res = max(d[1] for d in dts)
        out["driver_calls_per_s"] = round(iters / wall, 1)
        out["driver_latency_us"] = round(wall / iters * 1e6, 1)
        out["driver_resident_calls_per_s"] = round(iters / wall_res, 1)
        out["driver_resident_latency_us"] = round(wall_res / iters * 1e6, 1)

    # raw shard_map ceiling on the same device set / payload
    devs = jax.devices()[:nranks]
    mesh = Mesh(np.array(devs), ("rank",))
    x = jnp.zeros((nranks, count), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("rank", None)))
    fn = jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(v, "rank"), mesh=mesh,
        in_specs=P("rank", None), out_specs=P("rank", None)))
    jax.block_until_ready(fn(x))
    t0 = time.perf_counter()
    for _ in range(iters):
        y = fn(x)
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0
    out["raw_shardmap_calls_per_s"] = round(iters / dt, 1)
    out["raw_latency_us"] = round(dt / iters * 1e6, 1)
    out["driver_overhead_x"] = round(
        out["raw_shardmap_calls_per_s"] / out["driver_calls_per_s"], 2)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--count", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--json", type=str, default="")
    ap.add_argument("--platform", type=str, default="cpu")
    args = ap.parse_args()
    res = run(args.ranks, args.count, args.iters, args.platform)
    line = json.dumps(res)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
