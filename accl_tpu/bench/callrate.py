"""Driver call-rate / small-message latency benchmark.

Measures how many collective CALLS per second the TPU-backend driver
path sustains (descriptor -> gang scheduler -> compiled SPMD
collective -> scatter-back) against the raw-shard_map ceiling on the
same mesh — the host-side dispatch overhead the reference pays through
its hostctrl MMIO fast path (driver/xrt/src/fpgadevice.cpp:46-180;
per-call work is the FPGAQueue + 8-10 register writes).

Raw ceiling: a jitted shard_map psum on an identical global array,
called in the same loop — everything above that rate is driver
overhead (gang assembly, buffer resolution, scatter-back).

Lanes (all interleaved, see below):
- staged: host-staged operands, per-call sync in/out (worst case);
- resident: device-resident operands (from_fpga/to_fpga — the
  reference zero-copy call path, accl.cpp:796-839), synchronous calls
  served by the LEADER-DISPATCH fast path: the last-arriving rank runs
  the fused gang program inline, no executor hop;
- resident_exec: the same blocking calls with the fast path forced off
  (ACCL_LEADER_DISPATCH=0 semantics) — every gang pays the executor
  hand-off; the resident/resident_exec ratio isolates the dispatch-lane
  effect from box noise;
- async: resident + run_async with a bounded outstanding window,
  drained at the end — the driver-side twin of the raw loop, which
  also only blocks once at the end (served by the executor + batched
  dispatch);
- plan_sync / plan_async: the same resident call captured ONCE into a
  persistent plan (accl_tpu/plans.py) and replayed through the
  submission ring — no descriptor build, no gang assembly, no per-call
  request plumbing; a replay is a sequence-counter bump and (for the
  generation's last arrival) one pre-compiled dispatch.  Under
  ACCL_PLAN=0 capture degrades to the eager fallback, so the same two
  lanes record the kill-switch A/B (callrate_r12_plan_off);
- raw: the shard_map ceiling.

METHODOLOGY: the lanes are measured INTERLEAVED in rounds, keeping
each lane's best round — single-core boxes swing 2-3x between runs
(scheduler phase, background claims), so only same-window ratios mean
anything (the same best-of-interleaved-windows discipline as
bench/timing.py).

Usage: python -m accl_tpu.bench.callrate [--ranks N] [--count N]
       [--iters N] [--rounds N] [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import time


def run(nranks: int = 4, count: int = 1024, iters: int = 300,
        platform: str = "cpu", rounds: int = 4) -> dict:
    import numpy as np

    import jax

    if platform:
        # runtime config update, NOT the env var: site hooks may have
        # pinned a hardware platform at interpreter start and the claim
        # can hang when the chip is busy (same discipline as bench.py
        # workers / tests/conftest.py)
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from accl_tpu import ReduceFunction
    from accl_tpu.backends.tpu import TpuWorld
    from accl_tpu.utils.compat import shard_map

    out: dict = {"nranks": nranks, "count": count, "iters": iters,
                 "rounds": rounds}
    si = max(10, iters // rounds)  # iterations per lane slice
    out["slice_iters"] = si

    with TpuWorld(nranks) as w:
        bufs: dict = {}

        def setup(accl, rank):
            rng = np.random.default_rng(rank)
            s = accl.create_buffer_like(
                rng.standard_normal(count).astype(np.float32))
            r = accl.create_buffer(count, np.float32)
            bufs[rank] = (s, r)
            for _ in range(3):  # warm compile cache + gang path
                accl.allreduce(s, r, count, ReduceFunction.SUM)

        w.run(setup)

        def staged(accl, rank):
            s, r = bufs[rank]
            t0 = time.perf_counter()
            for _ in range(si):
                accl.allreduce(s, r, count, ReduceFunction.SUM)
            return time.perf_counter() - t0

        def resident(accl, rank):
            s, r = bufs[rank]
            t0 = time.perf_counter()
            for _ in range(si):
                accl.allreduce(s, r, count, ReduceFunction.SUM,
                               from_fpga=True, to_fpga=True)
            # completion means DISPATCH since the async-completion
            # change; force the device chain like the raw lane's final
            # block_until_ready so both lanes time the same work
            jax.block_until_ready(r.dev)
            return time.perf_counter() - t0

        # A/B twin of the resident lane with the leader-dispatch fast
        # path forced OFF (every gang rides the executor hop — the
        # pre-leader design), measured in the same interleaved windows:
        # the leader/executor ratio isolates the dispatch-lane effect
        # from box noise that moves raw and driver lanes together
        def resident_exec(accl, rank):
            return resident(accl, rank)

        def resident_async(accl, rank):
            s, r = bufs[rank]
            window: list = []
            t0 = time.perf_counter()
            for _ in range(si):
                window.append(accl.allreduce(
                    s, r, count, ReduceFunction.SUM, from_fpga=True,
                    to_fpga=True, run_async=True))
                if len(window) >= 8:
                    head = window.pop(0)
                    head.wait()
                    head.check()
            for req in window:
                req.wait()
                req.check()
            # every request is wait()ed AND check()ed: a stalled or
            # failed call must fail the lane loudly, not be timed as if
            # it completed (wait() has a finite default budget; check()
            # raises with the flight record while still in flight)
            jax.block_until_ready(r.dev)  # same-work guarantee as raw
            return time.perf_counter() - t0

        # persistent-plan lanes: capture the resident call once per
        # rank (collective across the world — every rank captures the
        # same one-call program), then replay at ring speed
        plan_handles: dict = {}

        def plan_capture(accl, rank):
            s, r = bufs[rank]
            plan_handles[rank] = accl.capture_plan(
                lambda a: a.allreduce(s, r, count, ReduceFunction.SUM,
                                      from_fpga=True, to_fpga=True))

        w.run(plan_capture)

        def plan_sync(accl, rank):
            p = plan_handles[rank]
            _s, r = bufs[rank]
            t0 = time.perf_counter()
            for _ in range(si):
                p.replay()
            jax.block_until_ready(r.dev)  # same-work guarantee as raw
            return time.perf_counter() - t0

        def plan_async(accl, rank):
            p = plan_handles[rank]
            _s, r = bufs[rank]
            window: list = []
            t0 = time.perf_counter()
            for _ in range(si):
                window.append(p.replay(run_async=True))
                if len(window) >= 8:
                    head = window.pop(0)
                    head.wait()
                    head.check()
            for t in window:
                t.wait()
                t.check()
            jax.block_until_ready(r.dev)
            return time.perf_counter() - t0

        # raw shard_map ceiling on the same device set / payload
        devs = jax.devices()[:nranks]
        mesh = Mesh(np.array(devs), ("rank",))
        x = jnp.zeros((nranks, count), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, P("rank", None)))
        fn = jax.jit(shard_map(
            lambda v: jax.lax.psum(v, "rank"), mesh=mesh,
            in_specs=P("rank", None), out_specs=P("rank", None)))
        jax.block_until_ready(fn(x))

        def raw():
            t0 = time.perf_counter()
            for _ in range(si):
                y = fn(x)
            jax.block_until_ready(y)
            return time.perf_counter() - t0

        # per-ROUND times: every lane is measured once per round, so a
        # round is one shared scheduling window — cross-lane ratios are
        # only computed within a round (the same window-to-window
        # discipline as bench/timing.py; a global per-lane best would
        # pair one lane's lucky window against another's average one)
        times: dict = {lane: [] for lane in (
            "staged", "resident", "resident_exec", "async",
            "plan_sync", "plan_async", "raw")}

        # dispatch-lane attribution per bench lane: the stats delta
        # across one lane slice shows which engine lane (leader inline /
        # executor / fused batch) actually carried its calls
        lane_stats: dict = {}

        def snap():
            return dict(w.engine.stats)

        def delta(before, after):
            return {k: after[k] - before[k] for k in after}

        for _ in range(rounds):
            times["raw"].append(raw())
            s0 = snap()
            times["staged"].append(max(w.run(staged)))
            lane_stats["staged"] = delta(s0, snap())
            s0 = snap()
            times["resident"].append(max(w.run(resident)))
            lane_stats["resident"] = delta(s0, snap())
            w.engine.leader_dispatch = False
            try:
                s0 = snap()
                times["resident_exec"].append(max(w.run(resident_exec)))
                lane_stats["resident_exec"] = delta(s0, snap())
            finally:
                w.engine.leader_dispatch = True
            s0 = snap()
            times["async"].append(max(w.run(resident_async)))
            lane_stats["async"] = delta(s0, snap())
            s0 = snap()
            times["plan_sync"].append(max(w.run(plan_sync)))
            lane_stats["plan_sync"] = delta(s0, snap())
            s0 = snap()
            times["plan_async"].append(max(w.run(plan_async)))
            lane_stats["plan_async"] = delta(s0, snap())

        best = {lane: min(ts) for lane, ts in times.items()}

        def round_ratio(a, b):
            """Best same-round a/b ratio (window-to-window)."""
            return min(x / y for x, y in zip(times[a], times[b]))

        # full per-round latencies: lets a reader audit every ratio and
        # see the box's window-to-window swing instead of trusting the
        # best-of summary
        out["round_latencies_us"] = {
            lane: [round(t / si * 1e6, 1) for t in ts]
            for lane, ts in times.items()}

    # side-by-side lane summary: the sync-resident (leader-dispatch),
    # async (posted-descriptor + executor/batched), and raw shard_map
    # lanes measured in the same interleaved windows, each with its
    # call rate, per-call latency, overhead vs raw, and the engine
    # dispatch lanes that served it
    out["lanes"] = {}
    for lane, label in (("staged", "driver_staged"),
                        ("resident", "driver_sync_resident"),
                        ("resident_exec", "driver_sync_executor_path"),
                        ("async", "driver_async"),
                        ("plan_sync", "driver_plan_sync"),
                        ("plan_async", "driver_plan_async"),
                        ("raw", "raw_shardmap")):
        out["lanes"][label] = {
            "calls_per_s": round(si / best[lane], 1),
            "latency_us": round(best[lane] / si * 1e6, 1),
            "overhead_vs_raw_x": round(round_ratio(lane, "raw"), 2),
        }
        if lane in lane_stats:
            out["lanes"][label]["dispatch"] = lane_stats[lane]

    # flat legacy keys (older round records / parsers read these)
    out["driver_calls_per_s"] = round(si / best["staged"], 1)
    out["driver_latency_us"] = round(best["staged"] / si * 1e6, 1)
    out["driver_resident_calls_per_s"] = round(si / best["resident"], 1)
    out["driver_resident_latency_us"] = round(
        best["resident"] / si * 1e6, 1)
    out["driver_async_calls_per_s"] = round(si / best["async"], 1)
    out["driver_async_latency_us"] = round(best["async"] / si * 1e6, 1)
    out["raw_shardmap_calls_per_s"] = round(si / best["raw"], 1)
    out["raw_latency_us"] = round(best["raw"] / si * 1e6, 1)
    out["driver_overhead_x"] = round(round_ratio("staged", "raw"), 2)
    out["resident_overhead_x"] = round(round_ratio("resident", "raw"), 2)
    out["async_overhead_x"] = round(round_ratio("async", "raw"), 2)
    out["resident_vs_async_x"] = round(
        round_ratio("resident", "async"), 2)
    # the tentpole ratio: leader-dispatch sync lane vs the same lane
    # forced through the executor, same interleaved windows
    out["leader_vs_executor_x"] = round(
        round_ratio("resident", "resident_exec"), 2)
    # the r12 tentpole ratios: plan-replay lanes vs raw, and plan-sync
    # vs the eager resident lane it amortizes (all window-to-window)
    out["plan_sync_overhead_x"] = round(round_ratio("plan_sync", "raw"), 2)
    out["plan_async_overhead_x"] = round(
        round_ratio("plan_async", "raw"), 2)
    out["plan_vs_resident_x"] = round(
        round_ratio("plan_sync", "resident"), 2)
    from accl_tpu import plans as _plans

    out["plan_enabled"] = bool(_plans.enabled())

    # publish into the process metrics registry (observability layer):
    # the bench lanes become queryable gauges next to the driver's own
    # per-call histograms, so one dump_metrics() shows both
    from accl_tpu.observability import metrics as _metrics

    reg = _metrics.default_registry()
    for label, lane in out["lanes"].items():
        reg.set_gauge(f"callrate/{label}/calls_per_s",
                      lane["calls_per_s"])
        reg.set_gauge(f"callrate/{label}/latency_us", lane["latency_us"])
        reg.set_gauge(f"callrate/{label}/overhead_vs_raw_x",
                      lane["overhead_vs_raw_x"])
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--count", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--json", type=str, default="")
    ap.add_argument("--platform", type=str, default="cpu")
    args = ap.parse_args()
    res = run(args.ranks, args.count, args.iters, args.platform,
              args.rounds)
    line = json.dumps(res)
    print(line)
    if args.json:
        with open(args.json, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
