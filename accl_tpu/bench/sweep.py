"""Collective benchmark sweep — the reference bench harness.

Equivalent of the reference ACCLSweepBenchmark: parameterized sweep over
2^4..2^19 elements for every collective, timing via the engine's
performance counter, CSV rows out (test/host/xrt/src/bench.cpp:25-61;
csv fixture.hpp:75-85,126-133; parse_bench_results.py).

Works against any world object exposing `accls` + `run` (EmuWorld or
TpuWorld), so the same sweep runs on the emulator rung and the TPU
backend — and the busbw column is directly comparable to the
allreduce-busbw metric of record (BASELINE.md).
"""
from __future__ import annotations

import contextlib
import csv
import io
import os
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from ..constants import ReduceFunction
from ..observability import metrics as _metrics


def claim_platform(prefer: str = "tpu",
                   timeout_s: Optional[float] = None,
                   attempts: int = 2) -> str:
    """Claim an accelerator with the r16 fail-fast contract: probe the
    ``prefer`` platform in a SUBPROCESS bounded by
    ``ACCL_TPU_CLAIM_TIMEOUT_S`` (default 60 s) — a wedged libtpu
    claim (metadata retries, chip held elsewhere) aborts with a clear
    message instead of hanging the harness, the claim is retried
    (contention is transient), and on exhaustion this process is
    pinned to the CPU rung via ``JAX_PLATFORMS`` so whichever rung
    succeeds gets recorded.  Call BEFORE anything imports jax.
    Returns the platform actually claimed (``"tpu"``/``"cpu"``)."""
    if prefer != "tpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        return "cpu"
    if timeout_s is None:
        timeout_s = float(os.environ.get("ACCL_TPU_CLAIM_TIMEOUT_S",
                                         "60"))
    probe = ("import jax; print(jax.default_backend())")
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for attempt in range(max(1, attempts)):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe], capture_output=True,
                text=True, timeout=timeout_s, env=env)
        except subprocess.TimeoutExpired:
            print(f"[sweep] TPU claim attempt {attempt + 1}/{attempts} "
                  f"exceeded ACCL_TPU_CLAIM_TIMEOUT_S={timeout_s:.0f}s "
                  f"— aborted (libtpu metadata retries / chip held by "
                  f"another process)", file=sys.stderr)
            continue
        backend = proc.stdout.strip().splitlines()[-1] \
            if proc.stdout.strip() else ""
        if proc.returncode == 0 and backend == "tpu":
            # symmetric with the failure path below: a leftover
            # JAX_PLATFORMS=cpu (prior fallback, user env) would make
            # the REAL run silently land on CPU while labeled tpu
            os.environ.pop("JAX_PLATFORMS", None)
            return "tpu"
        print(f"[sweep] TPU claim attempt {attempt + 1}/{attempts} "
              f"landed on {backend or 'nothing'} "
              f"(rc={proc.returncode})", file=sys.stderr)
    print("[sweep] TPU unavailable — falling back to the CPU rung "
          "(interpret-mode collectives; NOT a hardware number)",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


def claim_watchdog(label: str, timeout_s: Optional[float] = None,
                   advice: str = ""):
    """Arm the in-process half of the claim fail-fast: a daemon timer
    that aborts THIS process (exit code 3, the orchestrator's
    retry/fallback signal) if the real libtpu claim wedges past
    ``ACCL_TPU_CLAIM_TIMEOUT_S`` — the probe in :func:`claim_platform`
    releases the chip, so the actual claim can still block when
    another process grabs it in between.  Returns the started Timer
    (``.cancel()`` once the claim lands) or None when the knob is 0.
    Shared by bench.py's TPU worker and scripts/accl_tune.py."""
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("ACCL_TPU_CLAIM_TIMEOUT_S",
                                         "60"))
    if timeout_s <= 0:
        return None

    def _fire():
        print(f"[{label}] TPU claim exceeded "
              f"ACCL_TPU_CLAIM_TIMEOUT_S={timeout_s:.0f}s (libtpu "
              f"metadata retries / chip held by another process) — "
              f"aborting the claim{'; ' + advice if advice else ''}",
              file=sys.stderr, flush=True)
        os._exit(3)

    timer = threading.Timer(timeout_s, _fire)
    timer.daemon = True
    timer.start()
    return timer

COLLECTIVES = ("sendrecv", "bcast", "scatter", "gather", "allgather",
               "reduce", "allreduce", "reduce_scatter", "alltoall")


@dataclass
class SweepConfig:
    collectives: tuple = COLLECTIVES
    count_pows: Iterable[int] = tuple(range(4, 20))  # 2^4 .. 2^19 elements
    dtype: str = "float32"
    repetitions: int = 3
    root: int = 0


def _resolve_dtype(name) -> np.dtype:
    """np.dtype, accepting accelerator dtypes (bfloat16 via ml_dtypes)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(name)))


# bandwidth conventions (nccl-tests): one implementation, shared with
# the metrics registry the driver publishes into.  The payload factor
# matters: r4's CSVs recorded count*itemsize for all collectives, which
# made the x P collectives read as super-linear against byte-equal
# allreduce rows when the real per-byte cost was BETTER (VERDICT r4
# weak #4 — an accounting artifact, not a lowering cost).
_busbw_factor = _metrics.busbw_factor
_payload_factor = _metrics.payload_factor


def run_sweep(world, config: SweepConfig = SweepConfig(),
              writer: Optional[io.TextIOBase] = None) -> list[dict]:
    """Run the sweep; returns rows and optionally streams CSV."""
    rows: list[dict] = []
    csv_writer = None
    if writer is not None:
        csv_writer = csv.DictWriter(writer, fieldnames=[
            "collective", "count", "bytes", "duration_us", "algbw_GBps",
            "busbw_GBps", "repetition"])
        # only emit the header at the start of the stream, so several
        # sweeps (e.g. one per dtype) can append to one CSV
        try:
            at_start = writer.tell() == 0
        except (OSError, AttributeError):
            at_start = True
        if at_start:
            csv_writer.writeheader()

    P = world.nranks
    dtype = _resolve_dtype(config.dtype)

    for coll in config.collectives:
        for pw in config.count_pows:
            count = 1 << pw
            # one untimed warmup per (collective, size): on the
            # TPU-backend rung the first call pays the jit compile
            # (observed 6-30x the steady-state time), which would
            # dominate the recorded curve
            _run_once(world, coll, count, dtype, config.root)
            for rep in range(config.repetitions):
                dur_s = _run_once(world, coll, count, dtype, config.root)
                nbytes = count * _payload_factor(coll, P) * dtype.itemsize
                algbw = nbytes / dur_s / 1e9 if dur_s > 0 else 0.0
                row = {
                    "collective": coll,
                    "count": count,
                    "bytes": nbytes,
                    "duration_us": round(dur_s * 1e6, 2),
                    "algbw_GBps": round(algbw, 4),
                    "busbw_GBps": round(algbw * _busbw_factor(coll, P), 4),
                    "repetition": rep,
                }
                rows.append(row)
                if csv_writer:
                    csv_writer.writerow(row)

    # publish per-collective peak bandwidth into the process metrics
    # registry so `dump_metrics()` after a sweep reports the same
    # busbw-of-record numbers the CSV carries
    reg = _metrics.default_registry()
    best: dict = {}
    for row in rows:
        best[row["collective"]] = max(best.get(row["collective"], 0.0),
                                      row["busbw_GBps"])
    for coll, bw in best.items():
        reg.set_gauge(f"sweep/{coll}/busbw_peak_GBps", bw)
    return rows


# ---------------------------------------------------------------------------
# compression-lane sweep (r17): bandwidth vs exactness per wire lane
# ---------------------------------------------------------------------------

#: measurable wire lanes: the lossless baseline, the cast pairs, and
#: the int8 block-scaled lane with and without EQuARX error feedback
COMPRESSION_LANES = ("lossless", "float16", "bfloat16", "int8", "int8_ef")


def _lane_compress_dtype(lane: str):
    from ..constants import DataType

    return {"float16": DataType.float16, "bfloat16": DataType.bfloat16,
            "int8": DataType.int8, "int8_ef": None,
            "lossless": None}[lane]


def run_compression_sweep(world, collectives=("allreduce",
                                              "reduce_scatter"),
                          count_pows=range(12, 18), repetitions: int = 3,
                          writer: Optional[io.TextIOBase] = None,
                          log=None) -> list[dict]:
    """Sweep the wire-compression lanes: per (lane, collective, size),
    best-of-reps bus bandwidth PLUS the exactness columns — max
    absolute error and max ULP distance vs the fp64-accumulated
    reference.  The lossless lane comes back within summation-order
    noise (a few ULP — the engine's ring sums f32 sequentially; the
    BITWISE lossless gate runs on integer-valued data in
    tests/test_quantized_wire.py); the int8 lanes trade bounded error
    for ~4:1 wire width (the bandwidth-vs-exactness record
    scripts/check_bench_delta.py --quantized gates).  ``int8_ef`` runs
    through an armed
    CompressionPolicy (error feedback is a per-comm policy property,
    not a per-call flag)."""
    from ..arithconfig import CompressionPolicy
    from ..constants import DataType

    P = world.nranks
    dtype = np.dtype(np.float32)
    rows: list[dict] = []
    csv_writer = None
    if writer is not None:
        csv_writer = csv.DictWriter(writer, fieldnames=[
            "lane", "collective", "count", "bytes", "duration_us",
            "algbw_GBps", "busbw_GBps", "max_abs_err", "max_ulp"])
        csv_writer.writeheader()

    def arm(lane):
        pol = None
        if lane == "int8_ef":
            pol = CompressionPolicy(dtype=DataType.int8, min_bytes=0,
                                    error_feedback=True)
        for a in world.accls:
            a.set_compression(pol)

    def body_factory(coll, count, lane):
        cd = _lane_compress_dtype(lane)

        def body(accl, rank):
            made = []

            def mk(factory, *a):
                buf = factory(*a)
                made.append(buf)
                return buf

            data = (np.random.default_rng(rank)
                    .standard_normal(count * (P if coll ==
                                              "reduce_scatter" else 1))
                    .astype(np.float32))
            try:
                src = mk(accl.create_buffer_like, data)
                recv_n = count
                dst = mk(accl.create_buffer, recv_n, dtype)
                t0 = time.perf_counter()
                if coll == "allreduce":
                    accl.allreduce(src, dst, count, ReduceFunction.SUM,
                                   compress_dtype=cd)
                else:
                    accl.reduce_scatter(src, dst, count,
                                        ReduceFunction.SUM,
                                        compress_dtype=cd)
                dur = time.perf_counter() - t0
                dst.sync_from_device()
                return dur, data, dst.host.copy()
            finally:
                for buf in made:
                    free = getattr(buf, "free", None)
                    if free is not None:
                        free()

        return body

    try:
        for coll in collectives:
            for pw in count_pows:
                count = 1 << pw
                bodies = {}
                for lane in COMPRESSION_LANES:
                    arm(lane)
                    bodies[lane] = body_factory(coll, count, lane)
                    world.run(bodies[lane])  # warmup (jit/path setup)
                # INTERLEAVED rep rounds (the r16 compare() discipline):
                # every round measures every lane once, best-of per
                # lane, so box drift hits all lanes alike instead of
                # skewing whichever lane ran in the slow phase
                best: dict = {}
                for _ in range(repetitions):
                    for lane in COMPRESSION_LANES:
                        arm(lane)
                        out = world.run(bodies[lane])
                        dur = max(d for d, _i, _g in out)
                        if lane not in best or dur < best[lane][0]:
                            best[lane] = (dur, out)
                for lane in COMPRESSION_LANES:
                    dur, out = best[lane]
                    inputs = [i for _d, i, _g in out]
                    exact = np.sum(inputs, axis=0, dtype=np.float64) \
                        .astype(np.float32)
                    max_err = max_ulp = 0.0
                    for rank, (_d, _i, got) in enumerate(out):
                        exp = (exact if coll == "allreduce"
                               else exact.reshape(P, count)[rank])
                        err = np.abs(got.astype(np.float64)
                                     - exp.astype(np.float64))
                        max_err = max(max_err, float(err.max()))
                        ulp = err / np.spacing(np.abs(exp) + 1e-30)
                        max_ulp = max(max_ulp, float(ulp.max()))
                    nbytes = count * _payload_factor(coll, P) \
                        * dtype.itemsize
                    algbw = nbytes / dur / 1e9 if dur > 0 else 0.0
                    row = {
                        "lane": lane,
                        "collective": coll,
                        "count": count,
                        "bytes": nbytes,
                        "duration_us": round(dur * 1e6, 2),
                        "algbw_GBps": round(algbw, 4),
                        "busbw_GBps": round(
                            algbw * _busbw_factor(coll, P), 4),
                        "max_abs_err": float(f"{max_err:.6g}"),
                        "max_ulp": float(f"{max_ulp:.6g}"),
                    }
                    rows.append(row)
                    if csv_writer:
                        csv_writer.writerow(row)
                    if log:
                        log(f"  {lane:>9} {coll:<14} {count:>8} elems "
                            f"{row['busbw_GBps']:>8.3f} GB/s  "
                            f"err {row['max_abs_err']:.3g} "
                            f"ulp {row['max_ulp']:.3g}")
    finally:
        arm("lossless")
    return rows


# ---------------------------------------------------------------------------
# fused-overlap A/B lane (r18): exposed wire vs compute cover per cell
# ---------------------------------------------------------------------------

#: wire lanes the fused A/B measures: lossless fp32 and the r17 int8
#: block-scaled lane fused into the chunk loop (no whole-buffer pack)
FUSED_WIRE_LANES = ("fp32", "int8")


@contextlib.contextmanager
def _rank_window(rank: int, label: str):
    """Per-RANK compute window span (trace.traced_window stamps the
    host pseudo-rank 9999; the overlap accountant intersects wire
    intervals with compute windows on the SAME rank, so the A/B lane
    needs the span pinned to the calling rank's pid)."""
    from ..observability import trace as _trace

    span = _trace.new_span(f"window:{label}", rank=rank)
    if span is not None:
        span.t_submit = span.t_queue = span.t_dispatch = _trace.now_ns()
        span.lane = "window"
    try:
        yield
    finally:
        if span is not None:
            span.t_device_begin = span.t_submit
            span.t_device_end = span.t_complete = _trace.now_ns()
            _trace.collector().add(span)


def _flight_marks() -> dict:
    """Per-recorder flight-ring seq watermark — records landed after
    this mark belong to the current cell (same discipline as the
    autotuner's overlap column, tuning/autotune._overlap_marks)."""
    from ..observability import flight as _flight

    return {id(r): (r, max((rec.seq for rec in r.records()),
                           default=-1))
            for r in _flight.recorders()}


def _exposed_since(marks: dict) -> Optional[float]:
    """Measured ``attribution.overlap`` exposed-wire fraction
    (exposed_us / wire_us summed over collectives) of the flight
    records landed since ``marks``, against the trace collector's
    current compute cover (host ``window:`` spans + device stamp
    slices).  None when nothing completed."""
    from ..constants import ACCLError
    from ..observability import attribution as _attr
    from ..observability import flight as _flight
    from ..observability import trace as _trace

    docs = []
    for rec, mark in marks.values():
        d = rec.dump()
        d["records"] = [r for r in d["records"] if r["seq"] > mark]
        docs.append(d)
    if not docs:
        return None
    try:
        rep = _attr.overlap(_flight.merge_flight_dumps(docs),
                            trace_doc=_trace.collector().to_perfetto())
    except (ACCLError, ValueError, KeyError):
        return None
    wire = sum(c["wire_us"] for c in rep["collectives"].values())
    exposed = sum(c["exposed_us"] for c in rep["collectives"].values())
    return round(exposed / wire, 4) if wire > 0 else None


def run_fused_overlap_sweep(world, collectives=("allreduce",
                                                "reduce_scatter"),
                            count_pows=range(14, 17),
                            repetitions: int = 3, mm_dim: int = 256,
                            mm_loops: int = 2,
                            writer: Optional[io.TextIOBase] = None,
                            log=None) -> list[dict]:
    """A/B the r18 fused compute/communication lane against the
    sequential schedule, per (wire lane, collective, size) cell.

    Both arms run the SAME matmul workload and the SAME collective:

    - ``sequential`` — compute first, then issue the collective
      synchronously: zero cover, the wire is fully exposed (the
      measured exposed-wire fraction sits at ~1.0).
    - ``fused`` — dispatch the chunked fused collective async
      (``fused=True, run_async=True``) and run the matmul while the
      wire drains, then wait: the wire interval intersects the
      rank's compute window and the exposed fraction drops by the
      covered share.

    Columns per row: best-of-reps step time, busbw of the collective
    payload, and the measured ``attribution.overlap`` exposed-wire
    fraction over the cell's timed reps (host ``window:mxu`` spans as
    compute cover — the same accountant scripts/perf_doctor.py and the
    autotuner's overlap column run).  Sizes default to 64-256 KiB
    fp32 payloads (the ISSUE's >= 64 KiB floor)."""
    import jax.numpy as jnp

    from ..constants import DataType
    from ..observability import trace as _trace

    if not _trace.enabled():
        _trace.enable()
    P = world.nranks
    dtype = np.dtype(np.float32)
    rows: list[dict] = []
    csv_writer = None
    if writer is not None:
        csv_writer = csv.DictWriter(writer, fieldnames=[
            "wire", "collective", "count", "bytes", "mode",
            "duration_us", "busbw_GBps", "exposed_wire_fraction"])
        csv_writer.writeheader()

    def body_factory(coll, count, cd, mode):
        fused = mode == "fused"

        def compute(rank):
            # fixed per-rank matmul chain — the "MXU work" both arms
            # pay identically; block_until_ready keeps the window span
            # honest (jax would otherwise return before the FLOPs)
            with _rank_window(rank, "mxu"):
                a = jnp.full((mm_dim, mm_dim), (rank + 1) / mm_dim,
                             jnp.float32)
                for _ in range(mm_loops):
                    a = (a @ a) * (1.0 / mm_dim)
                a.block_until_ready()

        def body(accl, rank):
            made = []

            def mk(factory, *a):
                buf = factory(*a)
                made.append(buf)
                return buf

            data = np.full(count * (P if coll == "reduce_scatter"
                                    else 1), rank + 1, dtype)
            try:
                src = mk(accl.create_buffer_like, data)
                dst = mk(accl.create_buffer, count, dtype)

                def issue(run_async):
                    if coll == "allreduce":
                        return accl.allreduce(
                            src, dst, count, ReduceFunction.SUM,
                            compress_dtype=cd, run_async=run_async,
                            fused=fused)
                    return accl.reduce_scatter(
                        src, dst, count, ReduceFunction.SUM,
                        compress_dtype=cd, run_async=run_async,
                        fused=fused)

                t0 = time.perf_counter()
                if mode == "sequential":
                    compute(rank)
                    issue(run_async=False)
                else:
                    req = issue(run_async=True)
                    compute(rank)
                    req.wait(60)
                return time.perf_counter() - t0
            finally:
                for buf in made:
                    free = getattr(buf, "free", None)
                    if free is not None:
                        free()

        return body

    for coll in collectives:
        for pw in count_pows:
            count = 1 << pw
            for wire in FUSED_WIRE_LANES:
                cd = DataType.int8 if wire == "int8" else None
                for mode in ("sequential", "fused"):
                    body = body_factory(coll, count, cd, mode)
                    world.run(body)  # warmup: jit + gang plan
                    # isolate the cell's cover windows + flight records
                    _trace.collector().clear()
                    marks = _flight_marks()
                    dur = min(max(world.run(body))
                              for _ in range(repetitions))
                    exposed = _exposed_since(marks)
                    nbytes = count * _payload_factor(coll, P) \
                        * dtype.itemsize
                    algbw = nbytes / dur / 1e9 if dur > 0 else 0.0
                    row = {
                        "wire": wire,
                        "collective": coll,
                        "count": count,
                        "bytes": nbytes,
                        "mode": mode,
                        "duration_us": round(dur * 1e6, 2),
                        "busbw_GBps": round(
                            algbw * _busbw_factor(coll, P), 4),
                        "exposed_wire_fraction": exposed,
                    }
                    rows.append(row)
                    if csv_writer:
                        csv_writer.writerow(row)
                    if log:
                        ex = ("-" if exposed is None
                              else f"{exposed:.3f}")
                        log(f"  {wire:>5} {coll:<14} {count:>8} elems "
                            f"{mode:>10} {row['duration_us']:>10.1f} us"
                            f"  exposed {ex}")
    return rows


def _run_once(world, coll: str, count: int, dtype, root: int,
              compress=None, fused=None) -> float:
    """One timed collective across all ranks; returns max duration (s).
    ``compress`` optionally selects a wire-compression dtype
    (constants.DataType) for the collectives that take one — the r17
    compression lanes of the autotuner sweep through here.  ``fused``
    opts the call into the r18 chunked fused lane (allreduce /
    reduce_scatter / allgather only); None leaves the driver default
    (ACCL_FUSED env) in charge."""
    P = world.nranks

    def body(accl, rank):
        made = []

        def mk(factory, *a):
            buf = factory(*a)
            made.append(buf)
            return buf

        try:
            return _timed_body(accl, rank, mk)
        finally:
            # the emulator rungs have a real device-memory allocator:
            # a full 2^4..2^19 sweep leaks gigabytes without this and
            # starves the engine's own scratch allocations mid-schedule
            for buf in made:
                free = getattr(buf, "free", None)
                if free is not None:
                    free()

    def _timed_body(accl, rank, mk):
        data = np.full(count, rank + 1, dtype)
        if coll == "sendrecv":
            src = mk(accl.create_buffer_like, data)
            dst = mk(accl.create_buffer, count, dtype)
            t0 = time.perf_counter()
            nxt, prv = (rank + 1) % P, (rank - 1) % P
            sreq = accl.send(src, count, nxt, tag=1, run_async=True,
                             compress_dtype=compress)
            accl.recv(dst, count, prv, tag=1, compress_dtype=compress)
            sreq.wait(60)
            return time.perf_counter() - t0
        if coll == "bcast":
            buf = mk(accl.create_buffer_like, data)
            t0 = time.perf_counter()
            accl.bcast(buf, count, root, compress_dtype=compress)
            return time.perf_counter() - t0
        if coll == "scatter":
            send = mk(accl.create_buffer_like, np.tile(data, P))
            recv = mk(accl.create_buffer, count, dtype)
            t0 = time.perf_counter()
            accl.scatter(send, recv, count, root,
                         compress_dtype=compress)
            return time.perf_counter() - t0
        if coll == "gather":
            send = mk(accl.create_buffer_like, data)
            recv = mk(accl.create_buffer, count * P, dtype)
            t0 = time.perf_counter()
            accl.gather(send, recv, count, root,
                        compress_dtype=compress)
            return time.perf_counter() - t0
        if coll == "allgather":
            send = mk(accl.create_buffer_like, data)
            recv = mk(accl.create_buffer, count * P, dtype)
            t0 = time.perf_counter()
            accl.allgather(send, recv, count, compress_dtype=compress,
                           fused=fused)
            return time.perf_counter() - t0
        if coll == "reduce":
            send = mk(accl.create_buffer_like, data)
            recv = mk(accl.create_buffer, count, dtype)
            t0 = time.perf_counter()
            accl.reduce(send, recv, count, root, ReduceFunction.SUM,
                        compress_dtype=compress)
            return time.perf_counter() - t0
        if coll == "allreduce":
            send = mk(accl.create_buffer_like, data)
            recv = mk(accl.create_buffer, count, dtype)
            t0 = time.perf_counter()
            accl.allreduce(send, recv, count, ReduceFunction.SUM,
                           compress_dtype=compress, fused=fused)
            return time.perf_counter() - t0
        if coll == "reduce_scatter":
            send = mk(accl.create_buffer_like, np.tile(data, P))
            recv = mk(accl.create_buffer, count, dtype)
            t0 = time.perf_counter()
            accl.reduce_scatter(send, recv, count, ReduceFunction.SUM,
                                compress_dtype=compress, fused=fused)
            return time.perf_counter() - t0
        if coll == "alltoall":
            send = mk(accl.create_buffer_like, np.tile(data, P))
            recv = mk(accl.create_buffer, count * P, dtype)
            t0 = time.perf_counter()
            accl.alltoall(send, recv, count)
            return time.perf_counter() - t0
        raise ValueError(f"unknown collective {coll!r}")

    durations = world.run(body)
    return max(durations)
