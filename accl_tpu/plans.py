"""Persistent collective plans: capture once, validate once, replay at
ring speed.

The reference CCLO gets its call rate by keeping the control plane on
the device: the host writes a 15-word descriptor and the engine does
everything else, and ACCL+ (arxiv 2312.11742) goes further by letting
kernels replay pre-armed command sequences with no per-call host
involvement at all.  This module is that move for the TPU-native stack:
a steady-state sequence of collective calls — exactly what a serving or
training step loop issues — is

- **captured once** (`ACCL.capture_plan(fn)` records the descriptor
  stream through the same :class:`~accl_tpu.analysis.program.
  CollectiveProgram`/``RecordedCall`` machinery the r9 sanitizer's
  record mode and shadow capture use),
- **validated once** (the full static checker suite runs at plan-build
  time — pooled across the ranks of an in-process world when every
  rank captures concurrently, single-rank checks otherwise — so a
  desync/hazard is an ``ACCLError`` naming the finding at capture, not
  a hang at iteration 10⁶),
- **lowered once** (the backend pre-resolves every descriptor into its
  pinned execution plan: buffer bindings, gang pairing, the
  AOT-compiled SPMD program — the ``_gang_plans`` work of
  ``backends/tpu.py``, paid at arm time instead of per call), and
- **replayed** through a fixed-slot submission/completion ring shared
  with the dispatch engine (io_uring-style): a replay is a sequence
  counter bump — no descriptor build, no dict lookups, no per-call
  validation, no per-call Python marshaling (and on the emulator rung,
  no per-call FFI: one native call submits the whole program).

Invalidation contract: ``abort`` / ``reset_errors`` /
``shrink_communicator`` / ``grow_communicator`` fence every plan
touching the affected communicator, on both the driver and the engine
side — a replay after the fence **raises** (explicit plans) or
transparently **re-captures** (the ``ACCL_PLAN_AUTO`` lane); it never
silently runs on a fenced epoch.

Knobs:

- ``ACCL_PLAN=0`` — kill switch: ``capture_plan`` returns an
  :class:`EagerPlan` whose ``replay`` just re-runs the captured
  function through the normal per-call driver path (the A/B lane the
  callrate bench records as ``callrate_r12_plan_off``).
- ``ACCL_PLAN_AUTO=N`` — transparent auto-capture: after ``N``
  identical resident synchronous gang calls, the world's ranks agree
  (through the gang itself — every member marks intent on the same
  instance, so no rank ever replays against an eager peer) to arm a
  one-step plan and route subsequent identical calls through the ring.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from .constants import (
    GANG_OPERATIONS,
    ACCLError,
    CCLOCall,
    ErrorCode,
    Operation,
)
from .observability import flight as _flight
from .observability import metrics as _metrics
from .observability import trace as _trace
from .utils.logging import get_logger

# ---------------------------------------------------------------------------
# gating (same discipline as the sanitizer: module bools, env at import)
# ---------------------------------------------------------------------------
_enabled = os.environ.get("ACCL_PLAN", "1") not in ("", "0")


def enabled() -> bool:
    """False under ``ACCL_PLAN=0`` — every plan API degrades to eager."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Programmatic twin of ``ACCL_PLAN`` (tests toggle this)."""
    global _enabled
    _enabled = bool(on)


def auto_threshold() -> int:
    """``ACCL_PLAN_AUTO``: identical-iteration streak after which the
    driver transparently arms a one-step plan (0 = off, the default).
    Honors the ``ACCL_PLAN=0`` kill switch."""
    from .constants import env_int

    if not _enabled:
        return 0
    return env_int("ACCL_PLAN_AUTO", 0, minimum=0)


#: how long a capture waits for the sibling ranks of an in-process world
#: to reach their own capture_plan before degrading to single-rank
#: validation (the pooled cross-rank checks need every program)
_POOL_TIMEOUT_S = 10.0

_replay_ids = itertools.count(1 << 20)  # flight req ids, driver-disjoint


# ---------------------------------------------------------------------------
# captured step model
# ---------------------------------------------------------------------------
@dataclass
class PlanStep:
    """One captured call: the pre-built descriptor plus the host-side
    staging the driver would have performed around it."""

    call: CCLOCall
    desc: str
    run_async: bool
    sync_in: list = field(default_factory=list)   # [(buffer, count)]
    sync_out: list = field(default_factory=list)  # [(buffer, count)]


class PlanRecorder:
    """Installed by ``ACCL.capture_plan`` for the duration of the
    captured function: ``ACCL._execute`` feeds every outgoing call here
    (the call still executes — capture is a shadow recording, so the
    first iteration's results are real)."""

    def __init__(self, accl):
        self._accl = accl
        self.entries: list = []  # (PlanStep, Request)

    def on_call(self, call: CCLOCall, sync_in: list, sync_out: list,
                run_async: bool, desc: str, req) -> None:
        step = PlanStep(call=call, desc=desc, run_async=run_async,
                        sync_in=[(b, n) for b, n in sync_in
                                 if not b.is_dummy],
                        sync_out=[(b, n) for b, n in sync_out
                                  if not b.is_dummy])
        self.entries.append((step, req))


# ---------------------------------------------------------------------------
# pooled capture-time validation (cross-rank when the world shares the
# process; the same domain identity the runtime sanitizer exchanges on)
# ---------------------------------------------------------------------------
_pool_cv = threading.Condition()
_pools: dict = {}  # (domain, group_idx) -> pool dict


def _sweep_pools_locked() -> None:
    if len(_pools) <= 64:
        return
    horizon = time.monotonic() - 4.0 * _POOL_TIMEOUT_S
    for key in [k for k, p in _pools.items() if p["created"] < horizon]:
        del _pools[key]


def _pooled_findings(key: tuple, rank: int, program,
                     expected: frozenset, eager: int,
                     timeout_s: float):
    """Post this rank's captured program under ``key`` — (domain,
    member-set, per-member-set capture index), so every rank of one
    logical capture pairs on the identical key and disjoint concurrent
    captures never collide — and run the full cross-rank checker suite
    once every expected rank has posted; returns the shared findings
    list, or None when the pool never filled (caller degrades to
    single-rank checks)."""
    from .analysis.checks import check_programs

    with _pool_cv:
        _sweep_pools_locked()
        pool = _pools.get(key)
        if pool is None:
            pool = _pools[key] = {
                "programs": {}, "expected": set(expected),
                "eager": 1 << 62, "findings": None,
                "created": time.monotonic()}
        pool["programs"][rank] = program
        pool["expected"] |= set(expected)
        pool["eager"] = min(pool["eager"], eager)
        if set(pool["programs"]) >= pool["expected"]:
            # last poster runs the checks for the whole group
            pool["findings"] = check_programs(
                pool["programs"], eager_threshold=pool["eager"])
            _pools.pop(key, None)
            _pool_cv.notify_all()
            return pool["findings"]
        deadline = time.monotonic() + timeout_s
        while pool["findings"] is None:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not _pool_cv.wait(remaining):
                if pool["findings"] is not None:
                    break
                return None  # pool never filled; degrade gracefully
        return pool["findings"]


def _single_rank_findings(program) -> list:
    """The checker subset that is sound on one rank's program alone
    (cross-rank order/matching/deadlock checks need every program and
    would false-positive here)."""
    from .analysis.checks import check_buffer_hazards, check_membership

    programs = {program.rank: program}
    return check_membership(programs) + check_buffer_hazards(programs)


# ---------------------------------------------------------------------------
# plan objects
# ---------------------------------------------------------------------------
class PlanTicket:
    """Async replay handle (the plan twin of :class:`~accl_tpu.request.
    Request`): ``wait()`` → ``check()`` drains one in-flight replay."""

    def __init__(self, plan: "CollectivePlan", token, rec):
        self._plan = plan
        self._token = token
        self._rec = rec
        self._error: Optional[ACCLError] = None
        self._done = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._done:
            return True
        plan = self._plan
        budget = plan._accl.call_timeout_s if timeout is None else timeout
        try:
            ok = plan._device.plan_wait(plan._handle, self._token, budget)
        except ACCLError as e:
            self._error = e
            plan._note_replay_error(e)
            ok = True
        if not ok:
            return False
        self._done = True
        if self._error is None:
            plan._finish_replay(self._rec, 0)
        elif self._rec is not None:
            self._rec.finish(getattr(self._error, "code", 0)
                             or int(ErrorCode.DMA_INTERNAL_ERROR),
                             _trace.now_ns())
        return True

    def check(self) -> None:
        if not self._done:
            raise ACCLError("plan replay still in flight — wait() first")
        if self._error is not None:
            raise self._error

    @property
    def done(self) -> bool:
        return self._done


class CollectivePlan:
    """A captured, validated, pre-lowered collective program bound to
    one rank's driver.  ``replay()`` re-executes it through the
    submission ring; see the module docstring for the full contract."""

    def __init__(self, accl, steps: list, members: frozenset,
                 comms: frozenset, handle):
        self._accl = accl
        self._device = accl._device
        self.steps = steps
        self.members = members
        self.comms = comms
        self._handle = handle
        self._invalid: Optional[str] = None
        self.stats = {"replays": 0, "invalidations": 0}
        # flight-record shape for one replay (one record per replay,
        # not per inner call: the ring's whole point is that the inner
        # calls no longer exist as per-call driver events)
        self._comm0 = min(comms) if comms else 0
        self._total_count = sum(s.call.count for s in steps)
        self._staged_in = [pair for s in steps for pair in s.sync_in]
        self._staged_out = [pair for s in steps for pair in s.sync_out]
        # release path: a dead/closed plan must not pin engine-side
        # state (compiled programs, buffer bindings, descriptor
        # storage) forever — the finalizer drops this rank's handle;
        # backends refcount shared rings and no-op after world close
        import weakref

        rel = getattr(self._device, "plan_release", None)
        self._finalizer = (weakref.finalize(self, rel, handle)
                           if rel is not None else None)

    def close(self) -> None:
        """Explicitly release this plan's engine-side resources (also
        happens automatically when the object is garbage-collected).
        A closed plan refuses to replay."""
        self._invalid = self._invalid or "plan closed"
        if self._finalizer is not None:
            self._finalizer()

    # -- lifecycle -----------------------------------------------------
    @property
    def invalidated(self) -> bool:
        return self._invalid is not None

    @property
    def is_eager(self) -> bool:
        return False

    def _invalidate(self, reason: str) -> None:
        if self._invalid is None:
            self._invalid = reason
            self.stats["invalidations"] += 1
            if _metrics.enabled():
                _metrics.default_registry().inc("plans/invalidations")

    def _note_replay_error(self, e: ACCLError) -> None:
        code = int(getattr(e, "code", 0))
        if code & int(ErrorCode.COMM_ABORTED) or "invalidated" in str(e):
            self._invalidate(str(e))

    # -- replay hot path -----------------------------------------------
    def replay(self, run_async: bool = False,
               timeout: Optional[float] = None):
        """One pass through the captured program.  Synchronous by
        default (returns when every step completed); ``run_async=True``
        returns a :class:`PlanTicket`.  Raises — never silently runs —
        when the plan was invalidated by an abort/epoch fence/
        membership change; re-capture on the recovered communicator."""
        accl = self._accl
        if self._invalid is not None:
            raise ACCLError(
                f"plan replay: plan invalidated ({self._invalid}) — "
                f"re-capture the plan on the recovered communicator",
                int(ErrorCode.COMM_ABORTED))
        if accl._aborted_comms and (self.comms & accl._aborted_comms):
            self._invalidate("communicator aborted")
            raise ACCLError(
                f"plan replay: communicator(s) "
                f"{sorted(self.comms & accl._aborted_comms)} aborted "
                f"(COMM_ABORTED) — shrink/reset and re-capture",
                int(ErrorCode.COMM_ABORTED))
        for buf, count in self._staged_in:
            buf.slice(0, count).sync_to_device()
        rec = None
        if accl.flight_recorder is not None and _flight.enabled():
            rec = accl.flight_recorder.new_record(
                next(_replay_ids), "plan_replay", self._comm0, 0,
                "plan", self._total_count, 0, len(self.members), True,
                _trace.now_ns())
            rec.mark_dispatched("plan", _trace.now_ns())
        budget = accl.call_timeout_s if timeout is None else timeout
        try:
            token = self._device.plan_replay(
                self._handle, run_async=run_async, timeout_s=budget)
        except ACCLError as e:
            if rec is not None:
                rec.finish(getattr(e, "code", 0)
                           or int(ErrorCode.DMA_INTERNAL_ERROR),
                           _trace.now_ns())
            self._note_replay_error(e)
            raise
        if run_async:
            return PlanTicket(self, token, rec)
        self._finish_replay(rec, 0)
        return None

    def _finish_replay(self, rec, retcode: int) -> None:
        for buf, count in self._staged_out:
            buf.slice(0, count).sync_from_device()
        if rec is not None:
            rec.finish(retcode, _trace.now_ns())
        self.stats["replays"] += 1
        if _metrics.enabled():
            _metrics.default_registry().inc("plans/replays")


class EagerPlan:
    """The ``ACCL_PLAN=0`` fallback: same surface, no ring — ``replay``
    re-runs the captured function through the unchanged per-call driver
    path, so the kill-switch lane is bit-identical to today."""

    def __init__(self, accl, fn: Callable, args: tuple):
        self._accl = accl
        self._fn = fn
        self._args = args
        self.stats = {"replays": 0, "invalidations": 0}

    @property
    def is_eager(self) -> bool:
        return True

    @property
    def invalidated(self) -> bool:
        return False

    def replay(self, run_async: bool = False,
               timeout: Optional[float] = None):
        self._fn(self._accl, *self._args)
        self.stats["replays"] += 1
        if run_async:
            t = PlanTicket(self, None, None)
            t._done = True
            return t
        return None


# ---------------------------------------------------------------------------
# capture driver (called by ACCL.capture_plan)
# ---------------------------------------------------------------------------
def build_plan(accl, recorder: PlanRecorder, validate: bool = True,
               timeout_s: Optional[float] = None) -> CollectivePlan:
    """Validate the captured program (sanitizer checker suite) and arm
    it on the backend; the heavy lifting behind ``ACCL.capture_plan``."""
    from .analysis.sanitizer import CaptureSession
    from .analysis.findings import ERROR

    if not recorder.entries:
        raise ACCLError("capture_plan: the captured function issued no "
                        "collective calls — nothing to arm")
    unsupported = [s.desc for s, _r in recorder.entries
                   if s.call.stream_flags]
    if unsupported:
        raise ACCLError(
            f"capture_plan: stream-operand calls are not replayable "
            f"({unsupported[0]}) — plans pre-resolve memory operands "
            f"only; keep stream traffic on the eager path")

    # 1. reuse the r9 record machinery: rebuild the rank's
    #    CollectiveProgram from the captured descriptor stream
    session = CaptureSession()
    for step, req in recorder.entries:
        session.record(accl, step.call, step.desc, req, step.run_async)
    world = accl.communicator(0)
    rank = world.ranks[world.local_rank].session
    program = session.programs.get(rank)

    # 2. membership: who has to arm with us (union of gang/p2p peers)
    members: set = {rank}
    comms: set = set()
    for step, _req in recorder.entries:
        op = Operation(step.call.scenario)
        comm = accl.communicator(step.call.comm)
        sessions = [r.session for r in comm.ranks]
        if op in GANG_OPERATIONS:
            members.update(sessions)
            comms.add(step.call.comm)
        elif op in (Operation.send, Operation.recv):
            members.add(sessions[step.call.root_src_dst])
            comms.add(step.call.comm)

    # 3. validation: full cross-rank suite when the world shares the
    #    process (pooled over every capturing rank), single-rank-sound
    #    checks otherwise
    if validate and program is not None:
        budget = _POOL_TIMEOUT_S if timeout_s is None else timeout_s
        domain_fn = getattr(accl._device, "sanitizer_domain", None)
        domain = domain_fn() if domain_fn is not None else None
        findings = None
        if domain is not None and len(members) > 1:
            group = (domain, frozenset(members))
            idx = accl._plan_group_seq.get(group, 0)
            accl._plan_group_seq[group] = idx + 1
            findings = _pooled_findings(
                group + (idx,), rank, program, frozenset(members),
                accl.max_eager_size, budget)
        if findings is None:
            if domain is not None and len(members) > 1:
                get_logger("accl_tpu.plans", rank=rank).warning(
                    "capture_plan: sibling ranks never reached their "
                    "own capture inside %.0fs — cross-rank validation "
                    "degraded to single-rank checks", budget)
            findings = _single_rank_findings(program)
        errors = [f for f in findings if f.severity == ERROR]
        if errors:
            raise ACCLError(
                "capture_plan: sanitizer finding at capture time: "
                + errors[0].render()
                + (f" (+{len(errors) - 1} more)" if len(errors) > 1
                   else ""))

    # 4. lower + arm on the backend (pre-resolve descriptors into the
    #    pinned submission ring)
    arm = getattr(accl._device, "arm_plan", None)
    if arm is None:
        raise ACCLError(
            f"capture_plan: backend {type(accl._device).__name__} has "
            f"no plan ring — only the TPU and emulator engines replay "
            f"plans (ACCL_PLAN=0 selects the eager fallback)")
    budget = accl.call_timeout_s if timeout_s is None else timeout_s
    handle = arm([s.call for s, _r in recorder.entries],
                 frozenset(members), budget)
    plan = CollectivePlan(accl, [s for s, _r in recorder.entries],
                          frozenset(members), frozenset(comms), handle)
    # lifecycle anchor (r13): a capture event per touched comm lets the
    # dump checkers prove a post-fence replay was legitimately re-armed
    # (analysis.checks.check_fence_staleness)
    for c in sorted(comms):
        _flight.mark_event(accl.flight_recorder, _flight.PLAN_CAPTURE_EVENT,
                           int(c), lane="plan")
    if _metrics.enabled():
        _metrics.default_registry().inc("plans/captures")
    import weakref

    accl._plans.append(weakref.ref(plan))
    return plan
