"""Arithmetic/compression routing configuration.

The reference routes every operand through datapath lanes selected by an
"arithmetic configuration": element widths of the uncompressed and
compressed representations, their ratio, and TDEST routing ids for the
compressor, decompressor and arithmetic units
(reference: driver/xrt/include/accl/arithconfig.hpp:32-119).

In the TPU build the same structure selects which emulator arithmetic
lane / Pallas kernel handles a dtype pair, and whether wire payloads are
sent compressed.  The table is uploaded to the native engine at
`ACCL.initialize()` time, exactly as `write_arithconfig` serializes it to
exchange memory in the reference (driver/xrt/src/common.cpp:50-73).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .constants import DATA_TYPE_SIZE, DataType


@dataclass(frozen=True)
class ArithConfig:
    """Datapath routing metadata for one (uncompressed, compressed) pair.

    Mirrors the field set of the reference ArithConfig
    (arithconfig.hpp:32-100): element widths, elems-per-word ratio,
    routing ids for compressor / decompressor / arithmetic function, and
    whether arithmetic runs on the compressed representation.
    """

    uncompressed_elem_bits: int
    compressed_elem_bits: int
    elem_ratio_log: int  # log2(uncompressed/compressed width ratio)
    compressor_tdest: int
    decompressor_tdest: int
    arith_is_compressed: bool
    arith_tdest: tuple[int, ...]  # per ReduceFunction (SUM, MAX)

    @property
    def compression_ratio(self) -> int:
        return 1 << self.elem_ratio_log

    def to_words(self) -> list[int]:
        """Serialize for upload into the engine's config region
        (reference: common.cpp:50-73)."""
        words = [
            self.uncompressed_elem_bits,
            self.compressed_elem_bits,
            self.elem_ratio_log,
            self.compressor_tdest,
            self.decompressor_tdest,
            int(self.arith_is_compressed),
            len(self.arith_tdest),
        ]
        words.extend(self.arith_tdest)
        return words


# Arithmetic lane ids of the emulator/Pallas reduce unit.  One lane per
# (dtype, function) pair, equivalent to the 10 TDEST-selected functions of
# the reference reduce_ops plugin (kernels/plugins/reduce_ops/reduce_ops.cpp:31-107).
ARITH_LANE = {
    (DataType.float32, "sum"): 0,
    (DataType.float32, "max"): 1,
    (DataType.float64, "sum"): 2,
    (DataType.float64, "max"): 3,
    (DataType.int32, "sum"): 4,
    (DataType.int32, "max"): 5,
    (DataType.int64, "sum"): 6,
    (DataType.int64, "max"): 7,
    (DataType.float16, "sum"): 8,
    (DataType.float16, "max"): 9,
    # TPU extension lanes (bf16 is not in the reference's reduce_ops set)
    (DataType.bfloat16, "sum"): 10,
    (DataType.bfloat16, "max"): 11,
}

# Compression lane ids (reference hp_compression plugin: TDEST 0=compress
# fp32->fp16, 1=decompress; hp_compression.cpp:70-144).  The bf16 lanes
# are a TPU-native extension (bf16 is the MXU's 16-bit wire format).
COMPRESS_F32_F16 = 0
DECOMPRESS_F16_F32 = 1
COMPRESS_F32_BF16 = 2
DECOMPRESS_BF16_F32 = 3

_COMPRESSOR_LANES = {
    (DataType.float32, DataType.float16): (COMPRESS_F32_F16,
                                           DECOMPRESS_F16_F32),
    (DataType.float32, DataType.bfloat16): (COMPRESS_F32_BF16,
                                            DECOMPRESS_BF16_F32),
}

#: Compressor lane id -> numpy/jnp dtype name of the wire representation
#: (single source of truth for backends that emulate the wire hop by
#: dtype roundtrip, e.g. backends/tpu.py _wire_roundtrip).
COMPRESSOR_WIRE_DTYPE = {
    COMPRESS_F32_F16: "float16",
    COMPRESS_F32_BF16: "bfloat16",
}


def _cfg(u: DataType, c: DataType, arith_compressed: bool = False) -> ArithConfig:
    ubits = DATA_TYPE_SIZE[u]
    cbits = DATA_TYPE_SIZE[c]
    ratio_log = max(0, (ubits // max(cbits, 1)).bit_length() - 1)
    arith_dtype = c if arith_compressed else u
    comp, decomp = _COMPRESSOR_LANES.get((u, c), (0, 0))
    return ArithConfig(
        uncompressed_elem_bits=ubits,
        compressed_elem_bits=cbits,
        elem_ratio_log=ratio_log,
        compressor_tdest=comp,
        decompressor_tdest=decomp,
        arith_is_compressed=arith_compressed,
        arith_tdest=(
            ARITH_LANE[(arith_dtype, "sum")],
            ARITH_LANE[(arith_dtype, "max")],
        ),
    )


#: Default configs for every supported dtype pair, equivalent to
#: DEFAULT_ARITH_CONFIG (arithconfig.hpp:106-119): identity pairs for
#: {f16,bf16,f32,f64,i32,i64} plus the fp32-over-fp16 compressed pair
#: (arith on the compressed representation, matching the reference's
#: ArithConfig(4,2,0,0,1,true,{4,9}) mixed-precision entry) and a
#: TPU-native fp32-over-bf16 pair.
DEFAULT_ARITH_CONFIG: dict[tuple[DataType, DataType], ArithConfig] = {
    (DataType.float16, DataType.float16): _cfg(DataType.float16, DataType.float16),
    (DataType.bfloat16, DataType.bfloat16): _cfg(DataType.bfloat16,
                                                 DataType.bfloat16),
    (DataType.float32, DataType.float32): _cfg(DataType.float32, DataType.float32),
    (DataType.float64, DataType.float64): _cfg(DataType.float64, DataType.float64),
    (DataType.int32, DataType.int32): _cfg(DataType.int32, DataType.int32),
    (DataType.int64, DataType.int64): _cfg(DataType.int64, DataType.int64),
    (DataType.float32, DataType.float16): _cfg(
        DataType.float32, DataType.float16, arith_compressed=True
    ),
    (DataType.float32, DataType.bfloat16): _cfg(
        DataType.float32, DataType.bfloat16, arith_compressed=True
    ),
}


#: numpy dtype <-> DataType mapping used by the buffer layer.
NUMPY_TO_DATATYPE = {
    np.dtype(np.float16): DataType.float16,
    np.dtype(np.float32): DataType.float32,
    np.dtype(np.float64): DataType.float64,
    np.dtype(np.int32): DataType.int32,
    np.dtype(np.int64): DataType.int64,
    np.dtype(np.int8): DataType.int8,
}

try:  # bf16 numpy dtype ships via ml_dtypes (bundled with jax)
    import ml_dtypes

    NUMPY_TO_DATATYPE[np.dtype(ml_dtypes.bfloat16)] = DataType.bfloat16
except ImportError:  # pragma: no cover
    pass

DATATYPE_TO_NUMPY = {v: k for k, v in NUMPY_TO_DATATYPE.items()}
