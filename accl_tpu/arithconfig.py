"""Arithmetic/compression routing configuration.

The reference routes every operand through datapath lanes selected by an
"arithmetic configuration": element widths of the uncompressed and
compressed representations, their ratio, and TDEST routing ids for the
compressor, decompressor and arithmetic units
(reference: driver/xrt/include/accl/arithconfig.hpp:32-119).

In the TPU build the same structure selects which emulator arithmetic
lane / Pallas kernel handles a dtype pair, and whether wire payloads are
sent compressed.  The table is uploaded to the native engine at
`ACCL.initialize()` time, exactly as `write_arithconfig` serializes it to
exchange memory in the reference (driver/xrt/src/common.cpp:50-73).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .constants import DATA_TYPE_SIZE, DataType, Operation


@dataclass(frozen=True)
class ArithConfig:
    """Datapath routing metadata for one (uncompressed, compressed) pair.

    Mirrors the field set of the reference ArithConfig
    (arithconfig.hpp:32-100): element widths, elems-per-word ratio,
    routing ids for compressor / decompressor / arithmetic function, and
    whether arithmetic runs on the compressed representation.

    r17 extension (append-only serialization): ``block`` selects the
    int8 block-scaled wire lane's geometry (elements per fp32 scale;
    0 = plain cast lane) and ``error_feedback`` arms the engine's
    per-site EQuARX residual fold on egress quantization.
    """

    uncompressed_elem_bits: int
    compressed_elem_bits: int
    elem_ratio_log: int  # log2(uncompressed/compressed width ratio)
    compressor_tdest: int
    decompressor_tdest: int
    arith_is_compressed: bool
    arith_tdest: tuple[int, ...]  # per ReduceFunction (SUM, MAX)
    block: int = 0
    error_feedback: bool = False

    @property
    def compression_ratio(self) -> int:
        return 1 << self.elem_ratio_log

    def to_words(self) -> list[int]:
        """Serialize for upload into the engine's config region
        (reference: common.cpp:50-73).  The r17 block/error-feedback
        words trail the lane list — the native parser reads them when
        present, so pre-r17 7+nlanes-word streams stay decodable."""
        words = [
            self.uncompressed_elem_bits,
            self.compressed_elem_bits,
            self.elem_ratio_log,
            self.compressor_tdest,
            self.decompressor_tdest,
            int(self.arith_is_compressed),
            len(self.arith_tdest),
        ]
        words.extend(self.arith_tdest)
        words.append(self.block)
        words.append(int(self.error_feedback))
        return words


# Arithmetic lane ids of the emulator/Pallas reduce unit.  One lane per
# (dtype, function) pair, equivalent to the 10 TDEST-selected functions of
# the reference reduce_ops plugin (kernels/plugins/reduce_ops/reduce_ops.cpp:31-107).
ARITH_LANE = {
    (DataType.float32, "sum"): 0,
    (DataType.float32, "max"): 1,
    (DataType.float64, "sum"): 2,
    (DataType.float64, "max"): 3,
    (DataType.int32, "sum"): 4,
    (DataType.int32, "max"): 5,
    (DataType.int64, "sum"): 6,
    (DataType.int64, "max"): 7,
    (DataType.float16, "sum"): 8,
    (DataType.float16, "max"): 9,
    # TPU extension lanes (bf16 is not in the reference's reduce_ops set)
    (DataType.bfloat16, "sum"): 10,
    (DataType.bfloat16, "max"): 11,
}

# Compression lane ids (reference hp_compression plugin: TDEST 0=compress
# fp32->fp16, 1=decompress; hp_compression.cpp:70-144).  The bf16 lanes
# are a TPU-native extension (bf16 is the MXU's 16-bit wire format); the
# int8 block-scaled lane (r17) is the EQuARX-style 4:1 quantized wire —
# int8 payload + one fp32 scale per `block` elements, fp32 accumulate.
COMPRESS_F32_F16 = 0
DECOMPRESS_F16_F32 = 1
COMPRESS_F32_BF16 = 2
DECOMPRESS_BF16_F32 = 3
COMPRESS_F32_I8 = 4
DECOMPRESS_I8_F32 = 5

#: default elements per fp32 scale on the int8 wire (ops/quantized.py
#: DEFAULT_BLOCK twin; overridable via ACCL_COMPRESS_BLOCK)
DEFAULT_COMPRESS_BLOCK = 256

_COMPRESSOR_LANES = {
    (DataType.float32, DataType.float16): (COMPRESS_F32_F16,
                                           DECOMPRESS_F16_F32),
    (DataType.float32, DataType.bfloat16): (COMPRESS_F32_BF16,
                                            DECOMPRESS_BF16_F32),
    (DataType.float32, DataType.int8): (COMPRESS_F32_I8,
                                        DECOMPRESS_I8_F32),
}

#: Compressor lane id -> numpy/jnp dtype name of the wire representation
#: (single source of truth for backends that emulate the wire hop by
#: dtype roundtrip, e.g. backends/tpu.py _wire_roundtrip).  The int8
#: lane's wire form is (int8, per-block fp32 scales), not a flat dtype —
#: backends that see "int8" here must route through ops/quantized.py.
COMPRESSOR_WIRE_DTYPE = {
    COMPRESS_F32_F16: "float16",
    COMPRESS_F32_BF16: "bfloat16",
    COMPRESS_F32_I8: "int8",
}


def _cfg(u: DataType, c: DataType, arith_compressed: bool = False) -> ArithConfig:
    ubits = DATA_TYPE_SIZE[u]
    cbits = DATA_TYPE_SIZE[c]
    ratio_log = max(0, (ubits // max(cbits, 1)).bit_length() - 1)
    arith_dtype = c if arith_compressed else u
    comp, decomp = _COMPRESSOR_LANES.get((u, c), (0, 0))
    return ArithConfig(
        uncompressed_elem_bits=ubits,
        compressed_elem_bits=cbits,
        elem_ratio_log=ratio_log,
        compressor_tdest=comp,
        decompressor_tdest=decomp,
        arith_is_compressed=arith_compressed,
        arith_tdest=(
            ARITH_LANE[(arith_dtype, "sum")],
            ARITH_LANE[(arith_dtype, "max")],
        ),
    )


#: Default configs for every supported dtype pair, equivalent to
#: DEFAULT_ARITH_CONFIG (arithconfig.hpp:106-119): identity pairs for
#: {f16,bf16,f32,f64,i32,i64} plus the fp32-over-fp16 compressed pair
#: (arith on the compressed representation, matching the reference's
#: ArithConfig(4,2,0,0,1,true,{4,9}) mixed-precision entry) and a
#: TPU-native fp32-over-bf16 pair.
DEFAULT_ARITH_CONFIG: dict[tuple[DataType, DataType], ArithConfig] = {
    (DataType.float16, DataType.float16): _cfg(DataType.float16, DataType.float16),
    (DataType.bfloat16, DataType.bfloat16): _cfg(DataType.bfloat16,
                                                 DataType.bfloat16),
    (DataType.float32, DataType.float32): _cfg(DataType.float32, DataType.float32),
    (DataType.float64, DataType.float64): _cfg(DataType.float64, DataType.float64),
    (DataType.int32, DataType.int32): _cfg(DataType.int32, DataType.int32),
    (DataType.int64, DataType.int64): _cfg(DataType.int64, DataType.int64),
    (DataType.float32, DataType.float16): _cfg(
        DataType.float32, DataType.float16, arith_compressed=True
    ),
    (DataType.float32, DataType.bfloat16): _cfg(
        DataType.float32, DataType.bfloat16, arith_compressed=True
    ),
}


def int8_block_config(block: int = DEFAULT_COMPRESS_BLOCK,
                      error_feedback: bool = False) -> ArithConfig:
    """The (float32, int8) block-scaled wire pair (r17): 4:1 wire width,
    one fp32 scale per ``block`` elements, fp32 accumulate
    (``arith_is_compressed=False`` — the reduce funnel dequantizes into
    the fp32 accumulator, the EQuARX discipline).  Registered at
    ``ACCL.initialize`` (not in DEFAULT_ARITH_CONFIG) so the block
    geometry can follow ``ACCL_COMPRESS_BLOCK``."""
    if block <= 0 or block > 65536:
        from .constants import ACCLError

        raise ACCLError(
            f"int8 wire lane: block {block} out of range (1..65536)")
    return ArithConfig(
        uncompressed_elem_bits=DATA_TYPE_SIZE[DataType.float32],
        compressed_elem_bits=DATA_TYPE_SIZE[DataType.int8],
        elem_ratio_log=2,
        compressor_tdest=COMPRESS_F32_I8,
        decompressor_tdest=DECOMPRESS_I8_F32,
        arith_is_compressed=False,
        arith_tdest=(
            ARITH_LANE[(DataType.float32, "sum")],
            ARITH_LANE[(DataType.float32, "max")],
        ),
        block=int(block),
        error_feedback=error_feedback,
    )


# ---------------------------------------------------------------------------
# wire-compression policy (r17): per-communicator, size/dtype-threshold
# selection of the compressed wire lane — the ACCL+ "compression on the
# wire path itself" stage, armed at ACCL.initialize like the r16 tuning
# policy.  Disarmed (None) the driver's dispatch is bit-identical static.
# ---------------------------------------------------------------------------

#: collectives the policy may compress by default: the reduce family
#: plus the relay collectives whose wire traffic dominates serving
#: gradients/activations.  p2p and alltoall stay per-call opt-in.
COMPRESSIBLE_OPS = frozenset(int(op) for op in (
    Operation.allreduce, Operation.reduce_scatter, Operation.allgather,
    Operation.reduce, Operation.bcast))


@dataclass
class CompressionPolicy:
    """Arms automatic ``compress_dtype`` selection on a driver.

    ``dtype`` is the wire representation (int8 = block-scaled,
    float16/bfloat16 = the cast lanes); a call is compressed when its
    operands are float32, its scenario is in ``collectives``, and its
    payload is at least ``min_bytes``.  ``per_comm`` overrides the
    decision per communicator id (a nested CompressionPolicy, or None
    to exempt that comm).  ``error_feedback`` selects the EQuARX
    residual lane for int8 (per-comm via per_comm overrides).

    Env arming (read once at ``ACCL.initialize``):
      ``ACCL_COMPRESS``        int8 | float16 | bfloat16 | 0/unset (off)
      ``ACCL_COMPRESS_MIN_BYTES``  payload floor (default 65536)
      ``ACCL_COMPRESS_BLOCK``  int8 scale-block elements (default 256)
      ``ACCL_COMPRESS_EF``     1 = error feedback on the int8 lane
    """

    dtype: DataType = DataType.int8
    min_bytes: int = 64 * 1024
    block: int = DEFAULT_COMPRESS_BLOCK
    error_feedback: bool = False
    collectives: frozenset = COMPRESSIBLE_OPS
    per_comm: dict = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.per_comm is None:
            self.per_comm = {}

    def for_comm(self, comm_id: int) -> "CompressionPolicy | None":
        if comm_id in self.per_comm:
            return self.per_comm[comm_id]
        return self

    def select(self, scenario: int, count: int, comm_id: int,
               elem_dtype: DataType) -> "DataType | None":
        """The per-descriptor decision: the wire dtype to compress with,
        or None (leave the call on the lossless lane).  Pure in its
        arguments + this policy's fields, so the driver's descriptor
        memo stays sound."""
        pol = self.for_comm(comm_id)
        if pol is None:
            return None
        if int(scenario) not in pol.collectives:
            return None
        if elem_dtype != DataType.float32:
            return None
        nbytes = count * (DATA_TYPE_SIZE[DataType.float32] // 8)
        if nbytes < pol.min_bytes:
            return None
        return pol.dtype

    def wants_error_feedback(self, comm_id: int) -> bool:
        pol = self.for_comm(comm_id)
        return bool(pol is not None and pol.error_feedback
                    and pol.dtype == DataType.int8)

    def spec(self) -> dict:
        return {
            "dtype": self.dtype.name,
            "min_bytes": self.min_bytes,
            "block": self.block,
            "error_feedback": self.error_feedback,
            "per_comm": sorted(self.per_comm),
        }


def compress_block_from_env() -> int:
    from .constants import env_int

    return env_int("ACCL_COMPRESS_BLOCK", DEFAULT_COMPRESS_BLOCK,
                   minimum=1)


#: ACCL_COMPRESS values that mean "explicitly off" — shared with
#: ACCL.initialize, which uses an explicit off to DISARM a policy a
#: tuned table installed (unset merely leaves the table's choice)
COMPRESS_OFF_TOKENS = frozenset(("0", "off", "none"))


def compression_policy_from_env() -> "CompressionPolicy | None":
    """``ACCL_COMPRESS`` names the wire dtype (or 0/empty = off, the
    bit-identical default); malformed values raise the naming ACCLError
    (the env clear-error contract)."""
    import os as _os

    from .constants import ACCLError, env_int

    raw = _os.environ.get("ACCL_COMPRESS", "").strip().lower()
    if raw == "" or raw in COMPRESS_OFF_TOKENS:
        return None
    names = {"int8": DataType.int8, "float16": DataType.float16,
             "fp16": DataType.float16, "bfloat16": DataType.bfloat16,
             "bf16": DataType.bfloat16}
    if raw not in names:
        raise ACCLError(
            f"ACCL_COMPRESS={raw!r} is not a wire dtype — want one of "
            f"int8, float16, bfloat16 (or 0/unset for the lossless "
            f"lanes)")
    return CompressionPolicy(
        dtype=names[raw],
        min_bytes=env_int("ACCL_COMPRESS_MIN_BYTES", 64 * 1024,
                          minimum=0),
        block=compress_block_from_env(),
        error_feedback=_os.environ.get("ACCL_COMPRESS_EF", "0") == "1",
    )


#: numpy dtype <-> DataType mapping used by the buffer layer.
NUMPY_TO_DATATYPE = {
    np.dtype(np.float16): DataType.float16,
    np.dtype(np.float32): DataType.float32,
    np.dtype(np.float64): DataType.float64,
    np.dtype(np.int32): DataType.int32,
    np.dtype(np.int64): DataType.int64,
    np.dtype(np.int8): DataType.int8,
}

try:  # bf16 numpy dtype ships via ml_dtypes (bundled with jax)
    import ml_dtypes

    NUMPY_TO_DATATYPE[np.dtype(ml_dtypes.bfloat16)] = DataType.bfloat16
except ImportError:  # pragma: no cover
    pass

DATATYPE_TO_NUMPY = {v: k for k, v in NUMPY_TO_DATATYPE.items()}
