"""Seeded chaos plans: the generalized fault injector.

Replaces the one-shot ``inject_fault`` harness as the way to exercise
the resilience stack: a :class:`ChaosPlan` arms the native engine's
egress funnel with a *probabilistic, seeded* fault schedule — every
eager dataplane segment draws drop / duplicate / delay / corrupt-seqn
from a deterministic xorshift stream, so a failing CI run replays
bit-for-bit from its seed.  Slow-rank (per-message egress stall) and
kill-rank (engine goes silent, local comms abort with ``RANK_FAILED``)
round out the failure modes.

Plan grammar (``ACCL_CHAOS`` env var or :meth:`ChaosPlan.parse`)::

    seed=42,drop=0.01,dup=0.01,delay=0.02,delay_us=2000,corrupt=0.005,
    slow_rank=2:500,kill_rank=3

- ``seed``      — RNG seed (per-rank streams decorrelate off it)
- ``drop``/``dup``/``delay``/``corrupt`` — per-segment probabilities
  (floats in [0, 1); applied to eager data segments only — the
  rendezvous/NACK/abort control plane is never a chaos target, so
  recovery stays deterministic)
- ``delay_us``  — how long a delayed segment is held (default 2000);
  delayed segments are RE-ORDERED past their siblings, opening real
  sequence gaps for the NACK lane to close
- ``drop_rank=R:P`` — rank R's egress ALONE drops with probability P
  (repeatable; overrides the global ``drop`` for that rank) — the
  targeted-peer plan the link-matrix chaos-attribution test uses: all
  loss originates at one known rank, so every NACK/retransmit must
  land on that peer's links
- ``slow_rank=R:US`` — rank R stalls its egress writer US µs/message
  (repeatable for several ranks)
- ``kill_rank=R``    — rank R is marked for :meth:`kill set <kills>`;
  harnesses decide WHEN (usually mid-run) via ``EmuWorld.kill_rank``
- ``join_rank=R``    — rank R's death should be healed by a
  REPLACEMENT join: the harness spawns a joiner
  (``EmuWorld.spawn_replacement``) racing the plan's other faults, so
  the elastic join control plane is chaos-tested too

One-shot ``inject_fault`` remains as sugar: it forces the next draw of
the same funnel, so both paths exercise identical recovery machinery.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from ..constants import ACCLError

_PROB_KEYS = ("drop", "dup", "delay", "corrupt")


def _ppm(p: float) -> int:
    """Probability -> parts-per-million (the engine's integer domain)."""
    return max(0, min(1_000_000, int(round(p * 1_000_000))))


@dataclass
class ChaosPlan:
    """One parsed chaos plan; ``apply(device)`` arms a rank's engine."""

    seed: int = 1
    drop: float = 0.0
    dup: float = 0.0
    delay: float = 0.0
    delay_us: int = 2000
    corrupt: float = 0.0
    #: rank -> targeted egress drop probability (drop_rank=R:P);
    #: overrides the global ``drop`` for that rank only
    drop_ranks: dict = field(default_factory=dict)
    #: rank -> per-message egress stall in µs (slow-rank)
    slow: dict = field(default_factory=dict)
    #: ranks marked for a kill (the harness triggers the WHEN)
    kills: list = field(default_factory=list)
    #: ranks whose death should be healed by a REPLACEMENT join
    #: (elastic membership): the harness spawns a joiner for each —
    #: usually racing the probabilistic faults above, so the join
    #: control plane is exercised under the same chaos the data plane
    #: rides (EmuWorld.spawn_replacement + a grow-policy supervisor)
    joins: list = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        """Parse the ``k=v,...`` grammar (see module docstring)."""
        plan = cls()
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ACCLError(f"ACCL_CHAOS item {item!r}: expected k=v")
            key, val = (s.strip() for s in item.split("=", 1))
            try:
                if key == "seed":
                    plan.seed = int(val, 0)
                elif key in _PROB_KEYS:
                    p = float(val)
                    if not 0.0 <= p < 1.0:
                        raise ValueError("probability must be in [0, 1)")
                    setattr(plan, key, p)
                elif key == "delay_us":
                    plan.delay_us = int(val)
                elif key == "drop_rank":
                    rank_s, _, p_s = val.partition(":")
                    p = float(p_s) if p_s else 0.05
                    if not 0.0 <= p < 1.0:
                        raise ValueError("probability must be in [0, 1)")
                    plan.drop_ranks[int(rank_s)] = p
                elif key == "slow_rank":
                    rank_s, _, us_s = val.partition(":")
                    plan.slow[int(rank_s)] = int(us_s) if us_s else 500
                elif key == "kill_rank":
                    plan.kills.append(int(val))
                elif key == "join_rank":
                    plan.joins.append(int(val))
                else:
                    raise ValueError("unknown key")
            except ValueError as e:
                raise ACCLError(
                    f"ACCL_CHAOS item {item!r}: {e} (grammar: seed=N,"
                    f"drop=P,dup=P,delay=P,delay_us=N,corrupt=P,"
                    f"drop_rank=R:P,slow_rank=R:US,kill_rank=R,"
                    f"join_rank=R)") from e
        return plan

    @classmethod
    def from_env(cls) -> Optional["ChaosPlan"]:
        """The ``ACCL_CHAOS`` plan, or None when unset/empty."""
        spec = os.environ.get("ACCL_CHAOS", "").strip()
        return cls.parse(spec) if spec else None

    @property
    def probabilistic(self) -> bool:
        return any(getattr(self, k) > 0 for k in _PROB_KEYS) \
            or any(p > 0 for p in self.drop_ranks.values())

    def apply(self, device, rank: int) -> None:
        """Arm one rank's engine with this plan (kills NOT included —
        the harness triggers those explicitly, usually mid-run)."""
        set_chaos = getattr(device, "set_chaos", None)
        if set_chaos is None:
            raise ACCLError(
                f"{type(device).__name__} has no chaos injector "
                f"(chaos plans drive the emulator rungs)")
        set_chaos(
            seed=self.seed,
            drop_ppm=_ppm(self.drop_ranks.get(rank, self.drop)),
            dup_ppm=_ppm(self.dup),
            delay_ppm=_ppm(self.delay),
            delay_us=self.delay_us,
            corrupt_ppm=_ppm(self.corrupt),
            slow_us=int(self.slow.get(rank, 0)),
        )

    def spec(self) -> str:
        """Round-trippable rendering of this plan (parse(spec()) == it)."""
        parts = [f"seed={self.seed}"]
        for k in _PROB_KEYS:
            v = getattr(self, k)
            if v > 0:
                parts.append(f"{k}={v:g}")
        if self.delay > 0 or self.delay_us != 2000:
            parts.append(f"delay_us={self.delay_us}")
        for r, pv in sorted(self.drop_ranks.items()):
            parts.append(f"drop_rank={r}:{pv:g}")
        for r, us in sorted(self.slow.items()):
            parts.append(f"slow_rank={r}:{us}")
        for r in self.kills:
            parts.append(f"kill_rank={r}")
        for r in self.joins:
            parts.append(f"join_rank={r}")
        return ",".join(parts)
