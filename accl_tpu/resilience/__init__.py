"""Fault-tolerant collectives: the detect -> recover bridge.

Three cooperating layers over the failure-*detection* machinery the
stack already ships (per-peer sequence numbers, sticky error codes,
receive timeouts — SURVEY §5; flight recorder + watchdog; sanitizer):

1. **Retransmission** (:mod:`.retry`): eager senders keep a bounded
   retransmit store keyed by ``(peer, tag, seqn)``; on a seek miss the
   receiver NACKs and the sender resends, with exponential backoff +
   deterministic jitter on the receiver's NACK cadence
   (``ACCL_RETRY_MAX`` / ``ACCL_RETRY_BASE_US``).  A dropped, duplicated
   or seqn-corrupted segment heals transparently inside the unchanged
   receive budget.

2. **Abort + epoch fencing** (:meth:`accl_tpu.ACCL.abort`): an
   epoch-tagged abort propagates through the control plane so every
   pending request on all live ranks fails fast with ``COMM_ABORTED``
   (``RANK_FAILED`` when a dead peer triggered it); traffic from the
   dead epoch is fenced at the pool boundary.

3. **Shrink** (:mod:`.membership`, ULFM ``MPI_Comm_shrink`` analog,
   ACCL+ arxiv 2312.11742 / HiCCL arxiv 2408.05962 direction): agree on
   the surviving rank set via control-plane heartbeats and build a
   fresh communicator excluding dead ranks, so the caller re-runs the
   collective on the smaller world.

A seeded chaos injector (:mod:`.chaos`, ``ACCL_CHAOS``) drives all of
it in CI: probabilistic drop/dup/delay/corrupt plus slow-rank and
kill-rank, reproducible from one seed (``scripts/chaos_smoke.py``).

See docs/fault_tolerance.md for semantics and knobs.
"""
from .chaos import ChaosPlan
from .membership import probe_alive, shrink
from .retry import DEFAULT_RETRY_BASE_US, DEFAULT_RETRY_MAX, RetryPolicy

__all__ = [
    "ChaosPlan",
    "RetryPolicy",
    "DEFAULT_RETRY_MAX",
    "DEFAULT_RETRY_BASE_US",
    "probe_alive",
    "shrink",
]
