"""Fault-tolerant collectives: the detect -> recover bridge.

Three cooperating layers over the failure-*detection* machinery the
stack already ships (per-peer sequence numbers, sticky error codes,
receive timeouts — SURVEY §5; flight recorder + watchdog; sanitizer):

1. **Retransmission** (:mod:`.retry`): eager senders keep a bounded
   retransmit store keyed by ``(peer, tag, seqn)``; on a seek miss the
   receiver NACKs and the sender resends, with exponential backoff +
   deterministic jitter on the receiver's NACK cadence
   (``ACCL_RETRY_MAX`` / ``ACCL_RETRY_BASE_US``).  A dropped, duplicated
   or seqn-corrupted segment heals transparently inside the unchanged
   receive budget.

2. **Abort + epoch fencing** (:meth:`accl_tpu.ACCL.abort`): an
   epoch-tagged abort propagates through the control plane so every
   pending request on all live ranks fails fast with ``COMM_ABORTED``
   (``RANK_FAILED`` when a dead peer triggered it); traffic from the
   dead epoch is fenced at the pool boundary.

3. **Shrink** (:mod:`.membership`, ULFM ``MPI_Comm_shrink`` analog,
   ACCL+ arxiv 2312.11742 / HiCCL arxiv 2408.05962 direction): agree on
   the surviving rank set via control-plane heartbeats and build a
   fresh communicator excluding dead ranks, so the caller re-runs the
   collective on the smaller world.

4. **Elastic membership** (:mod:`.elastic`, r11): the upward half of
   recovery — a replacement rank joins a LIVE world (native-engine
   Join/Welcome/StateSync control plane syncs epochs + comm-id space
   from a sponsor) and the survivors mint a grown communicator
   (``ACCL.grow_communicator``, mirroring ``shrink_communicator``)
   without draining in-flight traffic on other comms.

5. **Recovery supervisor** (:mod:`.supervisor`, ``ACCL.supervise()`` /
   ``ACCL_SUPERVISE=1``): the automated detect -> abort -> probe ->
   shrink-or-grow -> agree-on-restart -> resume state machine, with
   policy knobs (``ACCL_RECOVERY=shrink|grow|halt``,
   ``ACCL_JOIN_WAIT_S``, ``ACCL_RECOVERY_MAX_ROUNDS``) and every
   transition published through the flight recorder (``recovering``
   state), the ``accl_health`` gauge (``recovering=4``) and the
   metrics registry (membership counters, recovery-latency histogram).

A seeded chaos injector (:mod:`.chaos`, ``ACCL_CHAOS``) drives all of
it in CI: probabilistic drop/dup/delay/corrupt plus slow-rank,
kill-rank and join-rank, reproducible from one seed
(``scripts/chaos_smoke.py``).

See docs/fault_tolerance.md for semantics and knobs.
"""
from .chaos import ChaosPlan
from .elastic import MembershipBoard, grow, join_grown_world
from .membership import probe_alive, shrink
from .retry import DEFAULT_RETRY_BASE_US, DEFAULT_RETRY_MAX, RetryPolicy
from .supervisor import RecoveryPolicy, RecoverySupervisor

__all__ = [
    "ChaosPlan",
    "MembershipBoard",
    "RecoveryPolicy",
    "RecoverySupervisor",
    "RetryPolicy",
    "DEFAULT_RETRY_MAX",
    "DEFAULT_RETRY_BASE_US",
    "grow",
    "join_grown_world",
    "probe_alive",
    "shrink",
]
