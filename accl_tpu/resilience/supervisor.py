"""Recovery supervisor: the automated detect -> resume state machine.

r8 gave the stack a watchdog that *diagnoses* (which ranks arrived,
which are missing); r10 gave it the recovery verbs (abort + epoch
fencing, ULFM shrink); this module closes the loop into a per-rank
supervisor that drives the whole episode without operator code:

    RUNNING --failure--> ABORT -> PROBE -> SHRINK --(grow policy)-->
       JOIN_WAIT -> GROW -> AGREE -> RESUME --> RUNNING

Policy knobs (env or :class:`RecoveryPolicy`):

- ``ACCL_RECOVERY`` = ``shrink`` (default) | ``grow`` | ``halt`` —
  what to do after a classified failure: finish on the survivor set,
  wait for a replacement and heal back toward full size, or give up
  and surface the error;
- ``ACCL_JOIN_WAIT_S`` — how long the grow policy waits for a
  replacement to announce itself on the membership board (default 5);
- ``ACCL_RECOVERY_MAX_ROUNDS`` — recovery episodes before the
  supervisor halts (default 4; a world dying faster than it heals
  must eventually surface, not spin);
- ``ACCL_PROBE_WINDOW_S`` — the liveness probe window (default 2).

Every transition is published three ways (the observability contract
of docs/fault_tolerance.md):

- a ``recovery/<phase>`` record in the rank's flight ring, live in the
  new ``recovering`` state until the phase retires (non-gang — the
  watchdog's stuck-gang scan and the merge hang analysis never see a
  healing world as a hang);
- the ``accl_health`` gauge reads ``recovering`` (4) for the whole
  episode (outranking a stale ``hung``/``aborted`` watchdog verdict);
- metrics: ``membership/*`` event counters and the
  ``recovery/latency_us`` + ``join_wait_us`` histograms.

``ACCL.supervise()`` constructs one; ``ACCL_SUPERVISE=1`` arms it at
``initialize`` (``accl.supervisor``).  The supervisor is loop-level,
not call-level: with it off (the default) the per-call hot path
contains ZERO supervisor code — the ≤2 % callrate gate holds by
construction (bench/results/callrate_r11_elastic_overhead.md).
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

import numpy as np

from ..constants import (
    ACCLError,
    ErrorCode,
    ReduceFunction,
    env_float,
    env_int,
)
from ..observability import flight as _flight
from ..observability import health as _health
from ..observability import metrics as _metrics
from ..observability import trace as _trace
from ..utils.logging import get_logger
from .elastic import MembershipBoard, admit_pending
from .membership import probe_alive

RECOVERY_MODES = ("shrink", "grow", "halt")

#: state names, published through flight records + the state log
S_RUNNING = "running"
S_ABORT = "abort"
S_PROBE = "probe"
S_SHRINK = "shrink"
S_JOIN_WAIT = "join_wait"
S_GROW = "grow"
S_AGREE = "agree"
S_RESUME = "resume"
S_HALTED = "halted"

#: the joiner's neutral contribution to the restart agreement (an
#: allreduce MAX of negated first-incomplete iterations): a fresh
#: member has no completed work and must never raise the restart point
_FRESH_MEMBER = -float(2 ** 30)


class RecoveryPolicy:
    """Resolved supervisor policy (env -> numbers, clear-error knobs)."""

    def __init__(self, mode: Optional[str] = None,
                 join_wait_s: Optional[float] = None,
                 max_rounds: Optional[int] = None,
                 probe_window_s: Optional[float] = None):
        self.mode = (mode if mode is not None
                     else os.environ.get("ACCL_RECOVERY", "shrink"))
        if self.mode not in RECOVERY_MODES:
            raise ACCLError(
                f"ACCL_RECOVERY={self.mode!r} is not a policy — pick one "
                f"of {'/'.join(RECOVERY_MODES)}")
        self.join_wait_s = (join_wait_s if join_wait_s is not None
                            else env_float("ACCL_JOIN_WAIT_S", 5.0,
                                           minimum=0.0))
        self.max_rounds = (max_rounds if max_rounds is not None
                           else env_int("ACCL_RECOVERY_MAX_ROUNDS", 4,
                                        minimum=1))
        self.probe_window_s = (probe_window_s if probe_window_s is not None
                               else env_float("ACCL_PROBE_WINDOW_S", 2.0))
        if not self.probe_window_s > 0:
            # the same clear-error-at-bring-up contract as the sibling
            # knobs: probe_alive hard-rejects a non-positive window, so
            # a typo must fail HERE, not mid-recovery-episode
            raise ACCLError(
                f"ACCL_PROBE_WINDOW_S={self.probe_window_s!r} must be "
                f"> 0 (a zero/negative probe window can never collect "
                f"a pong)")

    def __repr__(self) -> str:
        return (f"RecoveryPolicy(mode={self.mode!r}, "
                f"join_wait_s={self.join_wait_s}, "
                f"max_rounds={self.max_rounds})")


class RecoverySupervisor:
    """One rank's automated recovery driver.

    Wrap the training/serving step in :meth:`run_loop` — the
    supervisor catches classified collective failures and runs the
    full abort -> probe -> shrink/grow -> agree -> resume episode,
    handing the (possibly new) communicator id back to the step
    function.  The step function signature is
    ``step(accl, comm_id, iteration)``; raise-through of
    non-collective exceptions is unchanged."""

    def __init__(self, accl, policy: Optional[RecoveryPolicy] = None,
                 board: Optional[MembershipBoard] = None,
                 registry=None):
        self.accl = accl
        self.policy = policy if policy is not None else RecoveryPolicy()
        self.board = board
        self._registry = (registry if registry is not None
                          else _metrics.default_registry())
        self.state = S_RUNNING
        #: (monotonic_s, state, detail) transition log — uploaded as a
        #: CI artifact by the chaos join drill
        self.state_log: list = []
        self.rounds = 0
        self.comm_id: Optional[int] = None
        self._log = get_logger("accl_tpu.supervisor",
                               rank=getattr(accl, "rank", None))
        self._note(S_RUNNING, "armed")

    # -- observability plumbing -----------------------------------------
    def _note(self, state: str, detail: str = "") -> None:
        self.state = state
        self.state_log.append((time.monotonic(), state, detail))
        self._log.info("supervisor -> %s%s", state,
                       f" ({detail})" if detail else "")

    def _phase(self, name: str, comm_id: int):
        """A flight-ring record for one supervisor phase, live in the
        ``recovering`` state until the context exits."""
        sup = self

        class _Phase:
            def __enter__(self):
                self.rec = None
                fr = sup.accl.flight_recorder
                if fr is not None and _flight.enabled():
                    t = _trace.now_ns()
                    self.rec = fr.new_record(
                        -1, f"recovery/{name}", comm_id, 0, "none", 0, 0,
                        1, False, t)
                    self.rec.mark_recovering(t)
                sup._note(name)
                return self

            def __exit__(self, exc_type, exc, tb):
                if self.rec is not None:
                    self.rec.finish(0 if exc_type is None else
                                    int(ErrorCode.RANK_FAILED),
                                    _trace.now_ns())
                return False

        return _Phase()

    # -- the loop --------------------------------------------------------
    def run_loop(self, step: Callable, iters: int, comm_id: int = 0,
                 on_restart: Optional[Callable[[int], None]] = None,
                 start_iteration: int = 0,
                 fresh_member: bool = False) -> dict:
        """Drive ``step(accl, comm_id, it)`` for ``iters`` iterations
        with automated recovery.  ``on_restart(restart_it)`` lets the
        caller discard results at/after the agreed restart point;
        ``fresh_member=True`` marks a replacement rank that joined with
        no completed work (its vote can never raise the restart).
        Returns an episode summary dict."""
        self.comm_id = comm_id
        it = start_iteration
        restarts: list = []
        while it < iters:
            try:
                step(self.accl, self.comm_id, it)
                it += 1
                continue
            except ACCLError as e:
                code = int(getattr(e, "code", 0))
                # the classified-failure mask: abort finalizations,
                # receive-budget expiry, a wedged engine past the
                # driver budget, and seqn-stream corruption — a rank
                # killed MID-SEGMENT surfaces as PACK_SEQ on peers
                # whose NACK solicitations go unanswered
                classified = code & (
                    int(ErrorCode.COMM_ABORTED)
                    | int(ErrorCode.RANK_FAILED)
                    | int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
                    | int(ErrorCode.DMA_TIMEOUT_ERROR)
                    | int(ErrorCode.PACK_SEQ_NUMBER_ERROR))
                if not classified:
                    raise  # not a membership failure: surface as-is
                self.rounds += 1
                if self.policy.mode == "halt" \
                        or self.rounds > self.policy.max_rounds:
                    self._note(S_HALTED,
                               f"round {self.rounds}, policy "
                               f"{self.policy.mode}")
                    if _metrics.enabled():
                        self._registry.inc("recovery/halts")
                    raise
                it = self._recover(first_incomplete=it,
                                   fresh=fresh_member, cause=e)
                fresh_member = False  # recovered members have history
                restarts.append(it)
                if on_restart is not None:
                    on_restart(it)
        self._note(S_RUNNING, f"loop done at iter {iters}")
        return {"iters": iters, "rounds": self.rounds,
                "comm_id": self.comm_id, "restarts": restarts,
                "state_log": list(self.state_log)}

    # -- one recovery episode --------------------------------------------
    def _recover(self, first_incomplete: int, fresh: bool,
                 cause: ACCLError) -> int:
        accl, pol = self.accl, self.policy
        comm_id = self.comm_id
        t0 = time.monotonic()
        if _metrics.enabled():
            self._registry.inc("recovery/rounds")
        _health.note_recovering(self._registry, True)
        # recovery rides longer clocks than the data plane: members
        # reach each phase skewed by up to one receive budget, and the
        # grow policy legitimately WAITS (join budget + state sync)
        # while peers sit in the admission bcast / restart agreement.
        # Raise the engine receive budget for the episode so those
        # waits never classify as fresh failures, and restore after.
        saved_budget = getattr(accl, "engine_timeout_us",
                               1_000_000)
        saved_call_s = accl.call_timeout_s
        episode_margin_s = 2 * pol.probe_window_s + 15.0 + (
            pol.join_wait_s + 15.0 if pol.mode == "grow" else 0.0)
        accl.set_timeout(saved_budget + int(episode_margin_s * 1e6))
        accl.call_timeout_s = max(saved_call_s,
                                  saved_budget / 1e6 + episode_margin_s
                                  + 10.0)
        try:
            with self._phase(S_ABORT, comm_id):
                # idempotent: the failure that got us here may already
                # have been an abort (epochs are monotonic, re-revoking
                # a revoked comm is a no-op fan-out)
                accl.abort(comm_id, error=int(ErrorCode.RANK_FAILED))
            with self._phase(S_PROBE, comm_id):
                alive = probe_alive(accl, comm_id, pol.probe_window_s)
                deaths = alive.count(False)
                if _metrics.enabled() and deaths:
                    self._registry.inc("membership/rank_deaths", deaths)
                if sum(alive) <= 1 < len(alive):
                    # nobody else answered: THIS rank is the isolated
                    # (killed/partitioned) one — it must not "shrink"
                    # the world down to itself and carry on
                    self._note(S_HALTED, "isolated: no live peers")
                    if _metrics.enabled():
                        self._registry.inc("recovery/halts")
                    raise ACCLError(
                        f"supervisor(comm {comm_id}): no live peers in "
                        f"{pol.probe_window_s:.1f}s probe — this rank "
                        f"is isolated (original failure: {cause})",
                        int(ErrorCode.RANK_FAILED))
            with self._phase(S_SHRINK, comm_id):
                new_comm = accl.shrink_communicator(
                    comm_id, window_s=pol.probe_window_s)
            if pol.mode == "grow":
                if self.board is None:
                    self._log.warning(
                        "grow policy without a membership board — "
                        "falling back to shrink for this episode")
                else:
                    # state-log marker only (no flight record: the wait
                    # itself runs inside admit_pending, whose duration
                    # the grow phase record below covers; the pure wait
                    # portion is published as the join_wait_us
                    # histogram — a zero-length join_wait record here
                    # would misattribute the bottleneck)
                    self._note(S_JOIN_WAIT)
                    with self._phase(S_GROW, new_comm):
                        new_comm, admitted = admit_pending(
                            accl, new_comm, self.board,
                            wait_s=pol.join_wait_s,
                            window_s=pol.probe_window_s,
                            registry=self._registry)
                        self._note(S_GROW,
                                   f"admitted {admitted} joiner(s), "
                                   f"comm {new_comm}")
            self.comm_id = new_comm
            with self._phase(S_AGREE, new_comm):
                restart = self.agree_restart(first_incomplete,
                                             fresh=fresh)
            self._note(S_RESUME, f"iter {restart} on comm {new_comm}")
            return restart
        finally:
            accl.set_timeout(saved_budget)
            accl.call_timeout_s = saved_call_s
            _health.note_recovering(self._registry, False)
            if _metrics.enabled():
                self._registry.observe_value(
                    "recovery/latency_us",
                    (time.monotonic() - t0) * 1e6)

    def agree_restart(self, first_incomplete: int,
                      fresh: bool = False) -> int:
        """Collective restart-point agreement on the CURRENT comm: an
        allreduce(MAX) of each member's negated first-incomplete
        iteration = the MIN over members — nobody may skip work a
        slower survivor never finished.  Fresh members vote neutrally.
        Also the joiner's entry point: a replacement calls this (via
        run_loop's recovery or directly) as its first collective."""
        accl = self.accl
        vote = _FRESH_MEMBER if fresh else -float(first_incomplete)
        sb = accl.create_buffer(1, np.float32)
        sb.host[0] = vote
        rb = accl.create_buffer(1, np.float32)
        accl.allreduce(sb, rb, 1, ReduceFunction.MAX,
                       comm_id=self.comm_id)
        agreed = -float(rb.host[0])
        if agreed <= _FRESH_MEMBER or agreed >= -_FRESH_MEMBER:
            return 0  # every member is fresh: start from the top
        return max(0, int(agreed))
