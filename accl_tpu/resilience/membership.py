"""Membership recovery: liveness probing + ULFM-style shrink.

After a rank dies mid-job the surviving ranks need (1) agreement on who
is still alive and (2) a fresh communicator excluding the dead, so the
collective can re-run on the smaller world — the ULFM
``MPI_Comm_shrink`` recovery pattern, applied to this stack's
communicator model (ACCL+ arxiv 2312.11742 motivates exactly this for
long-running distributed apps).

Liveness comes from the control plane: an explicit ping/pong probe
(:func:`probe_alive`) plus heartbeats piggybacked on the resilience
control messages (NACKs/aborts count as proof of life — the data hot
path stays stamp-free), cross-checked against the watchdog's last-seen
stamps when a flight recorder is live.  Agreement is probabilistic-
by-construction (every survivor probes the same world with the same
window); the deterministic kill scenarios CI drives always agree, and
a disagreement surfaces as the usual create_communicator ordering
error rather than silent corruption.
"""
from __future__ import annotations

from typing import List, Optional

from ..constants import ACCLError, ErrorCode


def probe_alive(accl, comm_id: int = 0, window_s: float = 1.0) -> List[bool]:
    """Per-comm-local-rank liveness, via the backend's heartbeat probe.
    The local rank is always alive.  Backends without a liveness plane
    (record-mode lint devices) report everyone alive — shrink then
    degenerates to a copy, never to a wrong exclusion.

    Validation contract: a non-positive probe window and a backend list
    LONGER than the communicator both raise a decodable ACCLError
    naming the comm — the overlong case used to be silently truncated,
    which would mint a shrunk communicator from a probe of the wrong
    world (a backend handing back world-sized liveness for a sub-comm).
    A SHORT list still pads with dead: a backend that answered for
    fewer ranks proved nothing about the rest."""
    comm = accl.communicator(comm_id)
    if not window_s > 0:
        raise ACCLError(
            f"probe_alive(comm {comm_id}): window_s={window_s!r} must be "
            f"> 0 (a zero/negative window can never collect a pong)")
    probe = getattr(accl.device, "probe_liveness", None)
    alive: Optional[List[bool]] = None
    if probe is not None:
        alive = probe(comm_id, comm.size, window_s)
    if alive is None:
        alive = [True] * comm.size
    alive = list(alive)
    if len(alive) > comm.size:
        raise ACCLError(
            f"probe_alive(comm {comm_id}): backend returned liveness for "
            f"{len(alive)} ranks but the communicator has {comm.size} — "
            f"the probe answered for a different world; refusing to "
            f"truncate (a shrink built from it could exclude the wrong "
            f"ranks)")
    if len(alive) < comm.size:
        alive = alive + [False] * (comm.size - len(alive))
    alive[comm.local_rank] = True
    return alive


def shrink(accl, comm_id: int = 0, window_s: float = 1.0) -> int:
    """Build a fresh communicator over the surviving ranks of
    ``comm_id`` and return its id (ULFM shrink).

    Collective: every surviving rank must call it (same probe window),
    in the same create_communicator order as always.  The dead ranks'
    old traffic stays fenced behind the aborted comm's epoch; the new
    communicator starts with clean sequence state on every member.
    """
    comm = accl.communicator(comm_id)
    alive = probe_alive(accl, comm_id, window_s)
    # map surviving comm-local ranks to WORLD indices (the session field
    # carries the global rank on the emulator rungs and the device index
    # mapping on the TPU rung — the same convention create_communicator
    # and the engines' comm tables already share)
    survivors = [comm.ranks[i].session for i, ok in enumerate(alive) if ok]
    if not survivors:
        raise ACCLError(
            f"shrink(comm {comm_id}): no survivors", int(ErrorCode.RANK_FAILED))
    # a shrink with no dead ranks still mints a fresh comm: the call is
    # collective, so every member's id sequence must advance identically
    return accl.create_communicator(survivors)
