"""Retransmission policy: the driver-side knobs of the NACK lane.

The mechanism itself lives in the native engine (native/src/engine.cpp
``seek_recover`` / ``handle_nack``): senders keep a bounded store of
sent eager segments keyed by ``(comm, peer, tag, seqn)``; a receiver
whose seek misses NACKs the sender for everything from the first
missing seqn and re-seeks with exponential backoff + deterministic
jitter.  This module only resolves the policy (env -> numbers) and
mirrors the backoff math so tests and docs can state the schedule
without reaching into C++.

Knobs:

- ``ACCL_RETRY_MAX`` — NACK rounds per seek (default 4; ``0`` disables
  the whole lane: no store, no NACKs — the pure detect-and-classify
  behavior fault-classification tests rely on).
- ``ACCL_RETRY_BASE_US`` — backoff base in microseconds (default 200);
  round *k* waits ``base * 2**k + jitter`` with ``jitter < base/2 + 1``
  derived deterministically from (rank, seqn, round).

The TOTAL receive budget is unchanged: retransmission slices the same
``ACCL_DEFAULT_TIMEOUT``-driven window the blocking seek always had, so
an unrecoverable loss still classifies on the same clock.  The lane is
self-disabled on lossy transports (the datagram rung has its own
loss-hole resync semantics).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..constants import env_int as _env_int

DEFAULT_RETRY_MAX = 4
DEFAULT_RETRY_BASE_US = 200


@dataclass(frozen=True)
class RetryPolicy:
    """Resolved retransmission policy, applied to a backend at
    :meth:`accl_tpu.ACCL.initialize` via ``device.set_resilience``."""

    max_retries: int = DEFAULT_RETRY_MAX
    base_us: int = DEFAULT_RETRY_BASE_US

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        # a negative knob is a typo, not a policy: raise the naming
        # ACCLError (constants.env_int) instead of silently clamping
        return cls(
            max_retries=_env_int("ACCL_RETRY_MAX", DEFAULT_RETRY_MAX,
                                 minimum=0),
            base_us=_env_int("ACCL_RETRY_BASE_US", DEFAULT_RETRY_BASE_US,
                             minimum=1),
        )

    @property
    def enabled(self) -> bool:
        return self.max_retries > 0

    def backoff_us(self, attempt: int, rank: int = 0, seqn: int = 0) -> int:
        """The engine's backoff schedule, mirrored bit-for-bit
        (native/src/engine.cpp seek_recover): exponential in the
        attempt with a deterministic jitter keyed by (rank, seqn,
        attempt) so concurrent receivers decorrelate while a seeded
        run replays identically."""
        base = self.base_us
        us = base << attempt
        j = ((rank + 1) * 2654435761) ^ ((seqn + 1) * 40503) ^ attempt
        return us + (j & 0xFFFFFFFFFFFFFFFF) % (base // 2 + 1)

    def worst_case_recovery_us(self) -> int:
        """Upper bound on the backoff portion of a fully-exhausted
        recovery (excluding the post-recovery abort-wake slices)."""
        return sum(self.backoff_us(a) for a in range(self.max_retries))
