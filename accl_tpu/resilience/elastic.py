"""Elastic membership: live rank join + communicator grow.

r10 closed the detect -> recover loop *downward* only: a dead rank
meant abort -> :func:`~accl_tpu.resilience.membership.shrink` -> finish
forever on a smaller world.  This module is the missing upward half
(ROADMAP item 5; ACCL+ arxiv 2312.11742 motivates it for long-running
apps that outlive individual members, EQuARX-style serving fleets
arxiv 2506.17615 assume worlds that heal back to full size):

- **grow** (:func:`grow`, surfaced as ``ACCL.grow_communicator``) —
  the survivor-side collective mirroring ``shrink_communicator``: agree
  on the live membership of an existing communicator, splice in the
  new ranks' rows, and mint a FRESH communicator over the union.  Like
  shrink, the dead world stays fenced behind its bumped epoch
  (r10), so in-flight traffic on unrelated comms is never drained.

- **join** (:func:`join_grown_world`) — the joiner side: sync engine
  state from a live sponsor over the native control plane's
  Join/Welcome/StateSync messages (adopt every comm's epoch + abort
  fence; pad the comm-id space with placeholder slots so the next
  upload lands at the same id on every member), then adopt the grown
  communicator the survivors minted.

- **MembershipBoard** — the in-process rendezvous where joiners
  announce themselves and the survivors' recovery supervisor discovers
  them.  Cross-rank *agreement* on who joins does NOT come from the
  board (per-rank reads of shared state race): the lowest-rank
  survivor claims a batch and broadcasts the admitted session list
  over the data plane (:func:`admit_pending`), so every survivor
  splices in exactly the same rows.  A production deployment would
  back the board with its cluster manager; the emulator rungs share a
  process, so a plain object suffices.

Id-alignment invariant (the subtle part): communicator ids are
per-rank upload indices that must agree numerically across the group
(the ``create_communicator`` ordering discipline).  A joiner starts
with ONE communicator (its self-world), while survivors carry the full
history — so the join protocol pads the joiner's driver AND engine
comm tables with placeholders up to the sponsor's count *before* the
grown comm is uploaded anywhere, and the sponsor defers its own grow
upload until the joiner confirms the sync (otherwise the sponsor's
live count already includes the grown comm and the joiner pads one
too far).  Placeholder slots are dead: the driver fast-fails calls on
them and the engine finalizes them ``COMM_ABORTED | RANK_FAILED``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from ..communicator import Communicator, Rank
from ..constants import ACCLError
from ..observability import metrics as _metrics
from .membership import probe_alive

#: cap on joiners admitted per recovery round (the bcast payload is a
#: fixed small buffer; more pending joiners ride the next round)
MAX_JOINS_PER_ROUND = 16

#: default engine-side wait for the Join/Welcome/StateSync answer
JOIN_SYNC_TIMEOUT_S = 10.0


class JoinOffer:
    """One joiner's announcement on the membership board."""

    def __init__(self, session: int, rank_row: Rank):
        self.session = int(session)
        self.rank_row = rank_row
        self.announced_ns = time.monotonic_ns()
        self.claimed = False
        #: leader -> joiner: sync instructions are ready
        self.fulfilled = threading.Event()
        #: joiner -> leader: engine state sync done, comm ids aligned
        self.synced = threading.Event()
        # written by the claiming leader (valid once `fulfilled`):
        self.sponsor_session: Optional[int] = None
        self.rows: Optional[List[Rank]] = None  # full grown-comm rows
        self.grow_id: Optional[int] = None      # the grown comm's id
        self.pad_count: Optional[int] = None    # comm slots before grow
        self.local_rank: Optional[int] = None   # joiner's row index

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return (f"JoinOffer(session={self.session}, "
                f"claimed={self.claimed})")


class MembershipBoard:
    """In-process join rendezvous: joiners announce, the recovery
    leader claims.  Only :meth:`claim_pending` mutates membership, and
    it runs on exactly one rank per round — the agreement itself
    travels over the data plane (see :func:`admit_pending`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._offers: List[JoinOffer] = []

    def announce(self, session: int, rank_row: Rank) -> JoinOffer:
        offer = JoinOffer(session, rank_row)
        with self._lock:
            self._offers.append(offer)
        return offer

    def pending_count(self) -> int:
        with self._lock:
            return sum(1 for o in self._offers if not o.claimed)

    def claim_pending(self, max_n: int = MAX_JOINS_PER_ROUND,
                      ) -> List[JoinOffer]:
        """Atomically claim up to max_n unclaimed offers, in session
        order (deterministic membership for the round)."""
        with self._lock:
            avail = sorted((o for o in self._offers if not o.claimed),
                           key=lambda o: o.session)[:max_n]
            for o in avail:
                o.claimed = True
        return avail

    def offer_for(self, session: int) -> Optional[JoinOffer]:
        with self._lock:
            for o in self._offers:
                if o.session == session:
                    return o
        return None


# ---------------------------------------------------------------------------
# survivor side
# ---------------------------------------------------------------------------
def grow(accl, new_ranks: Sequence[Rank], comm_id: int = 0,
         window_s: float = 1.0) -> int:
    """Mint a grown communicator: the live members of ``comm_id`` plus
    ``new_ranks`` (rows for ranks joining the world — sessions the
    transport can already reach).  Collective over the SURVIVORS of
    ``comm_id`` — every live member must call it with the same rows in
    the same create order; each joiner adopts the identical table
    through :func:`join_grown_world`.  Returns the new comm id."""
    comm = accl.communicator(comm_id)
    new_rows = list(new_ranks)
    if not new_rows:
        raise ACCLError(
            f"grow_communicator(comm {comm_id}): no new ranks given — "
            f"use shrink_communicator/create_communicator for "
            f"same-membership rebuilds")
    alive = probe_alive(accl, comm_id, window_s)
    rows = [comm.ranks[i] for i, ok in enumerate(alive) if ok] + new_rows
    sessions = [r.session for r in rows]
    if len(set(sessions)) != len(sessions):
        raise ACCLError(
            f"grow_communicator(comm {comm_id}): duplicate sessions in "
            f"the grown membership {sessions} — a replacement must join "
            f"with a FRESH session, not a dead rank's")
    # the local row's position among the survivors of comm_id
    local = [i for i, ok in enumerate(alive) if ok].index(comm.local_rank)
    new_id = accl._install_communicator(
        Communicator(rows, local, comm_id=len(accl._communicators)))
    if _metrics.enabled():
        _metrics.default_registry().inc("membership/grows")
    return new_id


def admit_pending(accl, comm_id: int, board: MembershipBoard,
                  wait_s: float = 5.0, window_s: float = 1.0,
                  registry=None) -> tuple:
    """Admit pending joiners into a grown communicator — collective
    over the members of ``comm_id`` (typically the freshly-shrunk
    survivor comm).  Returns ``(new_comm_id, n_admitted)``; with no
    joiner inside ``wait_s`` the comm is returned unchanged.

    Protocol (every transition is data-plane-agreed, the board is only
    a discovery surface):

    1. the lowest-rank member (leader) waits up to ``wait_s`` for an
       announcement, claims a batch, and writes each offer's sync
       instructions (sponsor session, grown rows, pad count, grow id);
    2. the leader broadcasts the admitted session list over
       ``comm_id`` — the agreement point: every member splices in the
       same rows in the same order;
    3. the leader waits for each joiner's engine state sync (the
       joiner must pad its comm-id space BEFORE any member's grow
       upload bumps the sponsor's count);
    4. everyone mints the grown communicator via :func:`grow`.
    """
    comm = accl.communicator(comm_id)
    leader = comm.local_rank == 0
    reg = registry if registry is not None else _metrics.default_registry()
    t0 = time.monotonic()
    claimed: List[JoinOffer] = []
    if leader:
        deadline = t0 + wait_s
        while time.monotonic() < deadline and board.pending_count() == 0:
            time.sleep(0.01)
        claimed = board.claim_pending()
        if _metrics.enabled():
            reg.observe_value("join_wait_us",
                              (time.monotonic() - t0) * 1e6)
        pad_count = len(accl._communicators)
        rows = list(comm.ranks) + [o.rank_row for o in claimed]
        for i, offer in enumerate(claimed):
            offer.sponsor_session = comm.ranks[comm.local_rank].session
            offer.rows = rows
            offer.pad_count = pad_count
            offer.grow_id = pad_count
            offer.local_rank = comm.size + i
            offer.fulfilled.set()
    # agreement point: the admitted session list travels the data plane
    msg = accl.create_buffer(1 + MAX_JOINS_PER_ROUND, np.int32)
    if leader:
        msg.host[:] = 0
        msg.host[0] = len(claimed)
        for i, o in enumerate(claimed):
            msg.host[1 + i] = o.session
    accl.bcast(msg, 1 + MAX_JOINS_PER_ROUND, root=0, comm_id=comm_id)
    n = int(msg.host[0])
    if n == 0:
        return comm_id, 0
    sessions = [int(s) for s in msg.host[1:1 + n]]
    if leader:
        # the joiner pads to OUR comm count; it must finish before the
        # SPONSOR's grow upload bumps it (see the id-alignment
        # invariant above).  A joiner that dies mid-sync must NOT make
        # the leader diverge from the non-leaders (who are already past
        # the bcast and will mint the grown id regardless): log, keep
        # growing with the dead joiner in the table — the next recovery
        # episode shrinks it away — and let the late/dead joiner's own
        # join_grown_world fail its pad-count check cleanly.
        from ..utils.logging import get_logger

        for o in claimed:
            if not o.synced.wait(timeout=JOIN_SYNC_TIMEOUT_S):
                get_logger("accl_tpu.elastic").warning(
                    "admit_pending(comm %d): joiner session %d never "
                    "completed its state sync inside %.0fs — growing "
                    "anyway (the agreement bcast already committed "
                    "every survivor to this membership); a dead "
                    "joiner will be shrunk away next episode",
                    comm_id, o.session, JOIN_SYNC_TIMEOUT_S)
        new_rows = [o.rank_row for o in claimed]
    else:
        offers = [board.offer_for(s) for s in sessions]
        missing = [s for s, o in zip(sessions, offers) if o is None]
        if missing:
            raise ACCLError(
                f"admit_pending(comm {comm_id}): leader admitted "
                f"sessions {missing} unknown to this rank's board — "
                f"the membership boards have diverged")
        new_rows = [o.rank_row for o in offers]
    new_id = grow(accl, new_rows, comm_id=comm_id, window_s=window_s)
    return new_id, n


# ---------------------------------------------------------------------------
# joiner side
# ---------------------------------------------------------------------------
def join_grown_world(accl, offer: JoinOffer,
                     timeout_s: float = 30.0) -> int:
    """Complete a join from the replacement rank's side: wait for the
    leader's sync instructions, run the engine-level Join/Welcome/
    StateSync exchange against the sponsor, pad the driver's comm-id
    space, and adopt the grown communicator.  Returns the grown comm
    id — the first communicator this rank can collectively use."""
    if not offer.fulfilled.wait(timeout=timeout_s):
        raise ACCLError(
            f"join(session {offer.session}): no survivor claimed this "
            f"offer inside {timeout_s:.0f}s — is a grow-policy "
            f"supervisor (or admit_pending) running on the survivors?")
    join_sync = getattr(accl.device, "join_sync", None)
    if join_sync is not None:
        if join_sync(offer.sponsor_session,
                     timeout_s=JOIN_SYNC_TIMEOUT_S) != 0:
            raise ACCLError(
                f"join(session {offer.session}): state sync against "
                f"sponsor session {offer.sponsor_session} timed out "
                f"(sponsor dead?)")
        count = getattr(accl.device, "comm_count", lambda: None)()
        if count is not None and count != offer.pad_count:
            raise ACCLError(
                f"join(session {offer.session}): engine synced "
                f"{count} comm slots but the leader promised "
                f"{offer.pad_count} — the sponsor grew mid-sync; "
                f"re-announce and retry")
    accl._pad_communicators(offer.pad_count)
    offer.synced.set()
    local = next(i for i, r in enumerate(offer.rows)
                 if r.session == offer.session)
    new_id = accl._install_communicator(
        Communicator(list(offer.rows), local, comm_id=offer.grow_id))
    if _metrics.enabled():
        _metrics.default_registry().inc("membership/joins")
    return new_id
