"""The ACCL driver: public collective API and call marshaling.

Equivalent of the reference `ACCL::ACCL` host driver class
(driver/xrt/include/accl/accl.hpp:46-1148, driver/xrt/src/accl.cpp):
every collective builds one 15-word call descriptor, syncs operand
buffers to the device, submits asynchronously through the request queue,
and on completion syncs results back and checks the engine retcode.

The collective *algorithms* do not live here — exactly as in the
reference, where the host only marshals a descriptor and the
device-resident engine decomposes it (SURVEY §1).  Here the engine is
either the native C++ emulator (backends/emu.py) or the JAX/XLA/Pallas
TPU engine (backends/tpu.py).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from . import plans as _plans
from .analysis import sanitizer as _san
from .arithconfig import DEFAULT_ARITH_CONFIG
from .backends.base import CCLODevice
from .buffer import BaseBuffer, DummyBuffer
from .communicator import Communicator, Rank
from .constants import (
    DATA_TYPE_SIZE,
    DEFAULT_EAGER_RX_BUF_SIZE,
    DEFAULT_EAGER_RX_BUFS,
    DEFAULT_MAX_EAGER_SIZE,
    DEFAULT_MAX_RENDEZVOUS_SIZE,
    GANG_OPERATIONS,
    TAG_ANY,
    ACCLError,
    CCLOCall,
    CfgFunc,
    CompressionFlags,
    DataType,
    ErrorCode,
    HostFlags,
    Operation,
    ReduceFunction,
    StreamFlags,
    TuningKey,
)
from .observability import flight as _flight
from .observability import health as _health
from .observability import metrics as _metrics
from .observability import trace as _trace
from .request import Request, RequestQueue
from .utils.logging import get_logger

GLOBAL_COMM = 0  # id of the world communicator, like the reference's comm 0

#: scenarios that form cross-rank gangs in the engines (one instance ==
#: one gang id in the trace); p2p and local ops are single-rank spans.
#: Shared with the flight-recorder analyzer and the collective
#: sanitizer via constants.GANG_OPERATIONS.
_GANG_OPS = GANG_OPERATIONS


def default_timeout() -> int:
    """Default engine receive-timeout in emulated cycles (1 cycle = 1 µs).

    The reference bring-up writes 1e6 (accl.cpp:1112); loaded CI hosts
    need more headroom, so ACCL_DEFAULT_TIMEOUT overrides it — tests that
    temporarily shrink the budget restore to this, not the literal."""
    raw = os.environ.get("ACCL_DEFAULT_TIMEOUT", "1000000")
    try:
        return int(float(raw))  # accept "30000000" and "3e7" alike
    except ValueError as e:
        raise ACCLError(f"ACCL_DEFAULT_TIMEOUT={raw!r} is not a number") from e


class ACCL:
    """One rank's handle on the collective engine.

    Usage mirrors the reference driver: construct with a backend device,
    call :meth:`initialize` with the rank table, then issue collectives.
    """

    def __init__(self, device: CCLODevice):
        self._device = device
        self._queue = RequestQueue()
        self._communicators: list[Communicator] = []
        self._arith_ids: dict[tuple[DataType, DataType], int] = {}
        self._arith_pairs: dict[int, tuple] = {}
        #: error-feedback twins of block-scaled pairs (r17): same dtype
        #: pair, distinct engine config id whose error_feedback word
        #: arms the per-site EQuARX residual fold on egress
        self._arith_ids_ef: dict[tuple[DataType, DataType], int] = {}
        self._initialized = False
        self.max_eager_size = DEFAULT_MAX_EAGER_SIZE
        self.max_rendezvous_size = DEFAULT_MAX_RENDEZVOUS_SIZE
        #: host-side wait budget for synchronous calls; raise it alongside
        #: set_timeout for long-running collectives on slow emulator hosts
        self.call_timeout_s: float = 60.0
        #: engine receive budget (µs) as last written by set_timeout /
        #: initialize — read (and temporarily raised) by the recovery
        #: supervisor so an admission wait can't trip a peer's budget
        self.engine_timeout_us: int = default_timeout()
        self._last_request: Optional[Request] = None
        # descriptor memo: _build is a pure function of its scalar args
        # plus immutable per-buffer facts (address — never reused, the
        # registry only grows — dtype, host-only), so a training loop's
        # repeated call re-derives the same flag algebra every step;
        # the memo collapses that to one dict hit (the reference keeps
        # prepare_call cheap the same way: a handful of field writes).
        # Bounded LRU: fresh buffer addresses mint fresh keys, and a
        # descriptor-heavy workload cycling through > cap distinct
        # descriptors must evict the COLDEST entry, not wholesale-clear
        # — a clear-at-capacity memo re-derived every live call each
        # pass exactly when the memo mattered most.
        from collections import OrderedDict

        self._call_memo: "OrderedDict" = OrderedDict()
        self._call_memo_cap = 512
        #: always-on per-rank flight recorder (observability/flight.py):
        #: created at initialize (the rank is known there); None only
        #: with ACCL_FLIGHT=0
        self.flight_recorder: Optional[_flight.FlightRecorder] = None
        #: collective sanitizer state (analysis/sanitizer.py): per-comm
        #: gang instance counters for the cross-rank fingerprint
        #: exchange, and weak handles on run_async requests so deinit
        #: can name anything the caller never waited
        self._sanitize_seq: dict = {}
        self._async_pending: list = []
        #: communicator ids this driver has aborted (resilience layer):
        #: new calls on them fail fast at submit instead of reaching a
        #: fenced engine; cleared by reset_errors().  The off-path cost
        #: is one falsy check per call.
        self._aborted_comms: set = set()
        #: placeholder comm ids (elastic join protocol): dead slots a
        #: joiner minted to align its id space with the survivors' —
        #: calls on them fail fast with the same falsy-set discipline
        self._placeholder_comms: set = set()
        #: recovery supervisor, armed by supervise() / ACCL_SUPERVISE=1
        #: at initialize; None adds ZERO per-call code (loop-level, not
        #: call-level — the hot path never consults it)
        self.supervisor = None
        #: persistent collective plans (accl_tpu/plans.py): weak refs to
        #: live plans (abort/reset/shrink/grow invalidate them), the
        #: capture recorder installed by capture_plan for the duration
        #: of the captured function, and the per-driver capture group
        #: counter the pooled cross-rank validation pairs on.  Off-path
        #: cost in _execute is one falsy read per lane.
        self._plans: list = []
        self._plan_recorder = None
        #: per-(domain, member-set) capture counters: the pooled
        #: cross-rank validation pairs the K-th capture of the SAME
        #: member group across ranks, so disjoint sub-comm captures
        #: never skew each other's pairing
        self._plan_group_seq: dict = {}
        #: ACCL_PLAN_AUTO state (armed at initialize): streak-detect
        #: identical resident sync gang calls and transparently route
        #: them through a one-step plan ring once every gang member
        #: agreed (None = auto lane off, zero per-call cost)
        self._plan_auto = 0
        self._auto_rings: Optional[dict] = None
        self._auto_last = None
        self._auto_streak = 0
        #: learned algorithm-selection policy (accl_tpu/tuning): armed
        #: at initialize from ACCL_TUNE_TABLE (ACCL_TUNE=0 disarms).
        #: None adds ONE falsy read to _execute — with the knobs unset
        #: dispatch behavior is bit-identical to the static thresholds.
        self._tune_policy = None
        #: wire-compression policy (arithconfig.CompressionPolicy; r17):
        #: armed at initialize from ACCL_COMPRESS (or set_compression /
        #: a tuned table whose cells select a compression lane).  None
        #: (the default) adds one falsy read in _build's memo-miss path
        #: — dispatch is bit-identical static with the knob unset.
        self._compress_policy = None
        #: fused compute/communication lane default (r18): per-call
        #: ``fused=`` overrides; None here resolves to the ACCL_FUSED
        #: env read once at construction.  Unset, every descriptor
        #: carries fused=False and dispatch is bit-identical to r17.
        self._fused_default = os.environ.get(
            "ACCL_FUSED", "0") not in ("", "0")
        #: transparent hierarchical dispatch (r19): memoized per-
        #: (comm, axis-split) composers serving table cells won by the
        #: "hierarchical" lane without the caller constructing one.
        #: Probed only AFTER an armed policy returned "hierarchical"
        #: for a call — no table (or no hier win) never touches it, so
        #: dispatch stays bit-identical when nothing selects the lane.
        self._hier_comms: dict = {}
        self._in_hier = False

    # ------------------------------------------------------------------
    # bring-up (reference: accl.cpp:1082-1130 initialize)
    # ------------------------------------------------------------------
    def initialize(
        self,
        ranks: Sequence[Rank],
        local_rank: int,
        n_egr_rx_bufs: int = DEFAULT_EAGER_RX_BUFS,
        egr_rx_buf_size: int = DEFAULT_EAGER_RX_BUF_SIZE,
        # NB: the reference *driver* defaults the eager threshold to the rx
        # buffer size (1 KB, accl.hpp:103-105), overriding the engine's
        # 32 KB default (ccl_offload_control.c:27-28).
        max_eager_size: Optional[int] = None,
        max_rendezvous_size: int = DEFAULT_MAX_RENDEZVOUS_SIZE,
        timeout: Optional[int] = None,
    ) -> None:
        """Full bring-up sequence (reference order, accl.cpp:1082-1130):
        soft reset, eager rx buffer pool, rendezvous spare buffers,
        communicator, arithmetic configs, tuning, thresholds, enable."""
        if self._initialized:
            raise ACCLError("ACCL already initialized")

        # 1. soft reset (reference: accl.cpp:57-69 soft_reset)
        self._config_call(CfgFunc.reset_periph)

        # 2. eager rx buffers + rendezvous spares live inside the backend
        #    engine (reference writes a table into exchange memory,
        #    accl.cpp:1147-1212; our backends own their pools).
        self._device.setup_rx_buffers(n_egr_rx_bufs, egr_rx_buf_size)

        # 3. communicator (reference: accl.cpp:1435-1443)
        comm = Communicator(list(ranks), local_rank, comm_id=GLOBAL_COMM)
        self._device.upload_communicator(comm)
        self._communicators = [comm]

        # 4. arithmetic configs (reference: accl.cpp:1132-1141), plus
        #    the r17 int8 block-scaled wire pair — registered here (not
        #    in DEFAULT_ARITH_CONFIG) so the scale-block geometry can
        #    follow ACCL_COMPRESS_BLOCK, with an error-feedback twin
        #    whose engine config arms the EQuARX residual fold
        from .arithconfig import compress_block_from_env, int8_block_config

        for key, cfg in DEFAULT_ARITH_CONFIG.items():
            self._arith_ids[key] = self._device.upload_arithconfig(cfg)
        block = compress_block_from_env()
        i8_pair = (DataType.float32, DataType.int8)
        self._arith_ids[i8_pair] = self._device.upload_arithconfig(
            int8_block_config(block))
        self._arith_ids_ef = {
            i8_pair: self._device.upload_arithconfig(
                int8_block_config(block, error_feedback=True)),
        }
        # reverse map id -> (uncompressed, compressed): observability
        # recovers each call's datapath dtype from the descriptor's
        # arithcfg id (first pair wins on backend-deduplicated ids)
        self._arith_pairs = {}
        for pair, aid in self._arith_ids.items():
            self._arith_pairs.setdefault(aid, pair)
        for pair, aid in self._arith_ids_ef.items():
            self._arith_pairs.setdefault(aid, pair)
        self._call_memo.clear()  # memoized arithcfg ids may predate this

        # 5. timeout + protocol thresholds (reference: accl.cpp:1112-1120).
        # The reference default is 1e6 cycles; on shared/loaded CI hosts a
        # 1 s receive budget fires spuriously, so the default is
        # overridable (tests that exercise timeouts pass explicit values).
        if timeout is None:
            timeout = default_timeout()
        self._config_call(CfgFunc.set_timeout, value=timeout)
        self.engine_timeout_us = int(timeout)
        if max_eager_size is None:
            max_eager_size = egr_rx_buf_size
        self.set_max_eager_msg_size(max_eager_size)
        self.set_max_rendezvous_msg_size(max_rendezvous_size)

        # 6. flat-tree tuning registers (reference
        #    configure_tuning_parameters, accl.cpp:1214-1224): gather
        #    fan-in 2 above 32 KB, bcast flat <= 3 ranks, reduce flat
        #    <= 4 ranks or <= min(rndzv/4, 32 KB)
        self.apply_static_tuning()

        # 6.5 learned selection policy (accl_tpu/tuning/autotune.py):
        #     ACCL_TUNE_TABLE names a persisted selection table and
        #     ACCL_TUNE != 0 — the policy's derived crossovers are
        #     written over the firmware-ported constants above, so
        #     Engine::set_tuning / the TPU ring threshold become the
        #     backend of the LEARNED policy.  Both knobs unset: policy
        #     is None and the static writes above stand bit-for-bit.
        from .tuning import autotune as _autotune

        self._tune_policy = _autotune.policy_from_env()
        if self._tune_policy is not None:
            self._tune_policy.install(self)

        # 6.7 wire-compression policy (arithconfig.CompressionPolicy,
        #     r17): ACCL_COMPRESS arms automatic compress_dtype
        #     selection per size/dtype/collective threshold.  The env
        #     knob wins over anything a tuned table installed above —
        #     INCLUDING an explicit ACCL_COMPRESS=0, which disarms a
        #     table-armed policy; both unset leaves dispatch
        #     bit-identical static.
        from .arithconfig import (
            COMPRESS_OFF_TOKENS,
            compression_policy_from_env,
        )

        raw_compress = os.environ.get("ACCL_COMPRESS", "").strip().lower()
        if raw_compress in COMPRESS_OFF_TOKENS:
            self.set_compression(None)
        else:
            env_compress = compression_policy_from_env()
            if env_compress is not None:
                self.set_compression(env_compress)

        # 7. enable transport engines (reference: accl.cpp:1122-1125)
        self._config_call(CfgFunc.enable_pkt)
        self._initialized = True

        # 8. observability bring-up: the always-on flight recorder (the
        #    rank is known now), the process-wide OpenMetrics endpoint
        #    when ACCL_METRICS_PORT is set, and the regression sentinel
        #    when ACCL_SENTINEL names a committed baseline (off = zero
        #    threads, zero per-call work)
        if _flight.enabled():
            self.flight_recorder = _flight.register(
                _flight.FlightRecorder(local_rank))
            # RECEIVE_TIMEOUT forensics (r20): the instant the engine
            # classifies a receive-timeout, the recorder snapshots the
            # per-peer link rows (and, where the backend exposes it,
            # the gang-assembly state) with wall-clock stamps into the
            # flight dump — the standing sub-comm allgather wedge
            # (ROADMAP item 5) ships an artifact, not a bare timeout
            sources = {}
            for attr, key in (("link_stats", "link_rows"),
                              ("engine_stats", "engine_stats")):
                fn = getattr(self._device, attr, None)
                if callable(fn):
                    sources[key] = fn
            gang_fn = getattr(self._device, "gang_assembly_snapshot",
                              None)
            if gang_fn is None:
                eng = getattr(self._device, "_engine", None)
                gang_fn = getattr(eng, "gang_assembly_snapshot", None)
            if callable(gang_fn):
                sources["gang_assembly"] = gang_fn
            if sources:
                self.flight_recorder.set_forensics_sources(sources)
        _health.ensure_exporter_from_env()
        from .observability import sentinel as _sentinel
        from .observability import slo as _slo

        _sentinel.ensure_sentinel_from_env()
        _slo.ensure_slo_from_env()

        # 9. resilience bring-up: ACCL_SUPERVISE=1 arms the recovery
        #    supervisor (resilience/supervisor.py) on this rank — a
        #    loop-level state machine, so the per-call hot path gains
        #    nothing when it is off (the default)
        if os.environ.get("ACCL_SUPERVISE", "0") == "1":
            self.supervisor = self.supervise()

        # 10. plan auto-capture (ACCL_PLAN_AUTO=N; honors ACCL_PLAN=0):
        #     the env is read here, not at import, so tests and worlds
        #     created after an env change see it
        self._plan_auto = _plans.auto_threshold()
        self._auto_rings = {} if self._plan_auto else None

    # ------------------------------------------------------------------
    # properties / config
    # ------------------------------------------------------------------
    @property
    def device(self) -> CCLODevice:
        return self._device

    @property
    def comm(self) -> Communicator:
        return self._communicators[GLOBAL_COMM]

    @property
    def rank(self) -> int:
        return self.comm.local_rank

    @property
    def size(self) -> int:
        return self.comm.size

    def communicator(self, comm_id: int) -> Communicator:
        """The communicator table for an id, or a decodable ACCLError —
        a bad id must not surface as a bare IndexError deep inside a
        collective (the lookup contract the collective sanitizer and
        accl_lint formalize)."""
        if isinstance(comm_id, int) and \
                0 <= comm_id < len(self._communicators):
            comm = self._communicators[comm_id]
            if comm.is_placeholder:
                raise ACCLError(
                    f"communicator {comm_id} is a placeholder slot on "
                    f"this rank — it marks a world this rank joined "
                    f"AFTER (elastic membership); only communicators "
                    f"minted at or after the join are usable here")
            return comm
        if not self._communicators:
            raise ACCLError(
                f"unknown communicator id {comm_id!r}: driver not "
                f"initialized (call initialize() first)")
        raise ACCLError(
            f"unknown communicator id {comm_id!r}: this rank has ids "
            f"0..{len(self._communicators) - 1} (create_communicator "
            f"must run in the same order on every member rank)")

    def arithcfg_id(self, uncompressed: DataType,
                    compressed: Optional[DataType] = None) -> int:
        """Device id of the arithmetic config for a dtype pair — what a
        device-side caller passes to :class:`~accl_tpu.device_api.
        ACCLCommand` (the exchange-memory arithcfg offset the reference's
        HLS bindings take, driver/hls/accl_hls.h:82).  `compressed`
        defaults to the uncompressed dtype (no compression lane)."""
        pair = (uncompressed,
                uncompressed if compressed is None else compressed)
        try:
            return self._arith_ids[pair]
        except KeyError:
            raise ACCLError(
                f"no arithmetic config for dtype pair {pair} — supported "
                f"pairs: {sorted(p for p in self._arith_ids)}") from None

    def create_communicator(self, indices: Sequence[int],
                            tenant: Optional[str] = None) -> int:
        """Create a sub-communicator from global-rank indices; returns its
        id (reference: accl.cpp:971-978).

        Collective and order-sensitive: every member rank must create
        its sub-communicators in the same order so the ids align across
        the group — the same discipline the reference needs for its
        exchange-memory communicator addresses (communicator.cpp:23).

        ``tenant`` labels the communicator's traffic for the per-tenant
        observability plane (r20): flight records, ``tenant/<name>``
        metric families, trace tracks and ``link_matrix(tenant=...)``
        slices all key off it.  Purely driver/telemetry-side — the
        engine ABI is untouched."""
        size = self.comm.size
        bad = [i for i in indices if not 0 <= i < size]
        if bad:
            raise ACCLError(
                f"create_communicator: rank indices {bad} outside the "
                f"world (size {size})")
        new_id = len(self._communicators)
        sub = self.comm.split(indices, new_id)
        self._device.upload_communicator(sub)
        self._communicators.append(sub)
        if tenant is not None:
            self.set_tenant(new_id, tenant)
        return new_id

    def set_tenant(self, comm_id: int, tenant: Optional[str]) -> None:
        """Label (or with ``None`` unlabel) a communicator's traffic
        with a tenant name for per-tenant telemetry.  Names are bounded
        and shell-safe (``[A-Za-z0-9_.-]{1,64}``) because they become
        metric label values and trace track names; the registry's
        ACCL_METRICS_MAX_SERIES guard bounds how many distinct names
        can mint series."""
        comm = self.communicator(comm_id)
        if tenant is not None:
            import re as _re

            if not isinstance(tenant, str) or \
                    not _re.fullmatch(r"[A-Za-z0-9_.\-]{1,64}", tenant):
                raise ACCLError(
                    f"set_tenant: invalid tenant name {tenant!r} — "
                    f"need 1-64 chars of [A-Za-z0-9_.-] (it becomes a "
                    f"metric label and trace track name)")
        comm.tenant = tenant

    def tenant_comm_ids(self, tenant: str) -> list:
        """Ids of this rank's communicators labeled ``tenant`` — the
        slice key ``link_matrix(tenant=...)`` folds over (a tenant's
        traffic is the union of its communicators' link rows)."""
        return [c.id for c in self._communicators
                if not c.is_placeholder and c.tenant == tenant]

    def reserve_communicator(self) -> int:
        """Burn one communicator id with an inert slot, so a sub-group
        this rank is NOT a member of can occupy the same id on its
        members — the :meth:`create_communicator` ordering discipline
        applied to disjoint group families (the hierarchical
        composer's per-axis sub-communicators, accl_tpu/tuning).

        On a world-shared comm table (TPU backend) the pad is
        driver-side only — the members' upload covers the world and a
        second upload with different membership would be rejected.
        Per-rank engine tables (emulator) additionally get an inert
        self-only communicator so the engine-side id spaces stay
        aligned with the wire protocol's comm ids."""
        cid = len(self._communicators)
        if getattr(self._device, "comm_table_is_shared", False):
            self._pad_communicators(cid + 1)
            return cid
        return self.create_communicator([self.rank])

    def set_max_eager_msg_size(self, nbytes: int) -> None:
        """Runtime eager↔rendezvous threshold (reference:
        accl.cpp:1415-1423; validated ≥ rx buffer size by the engine,
        ccl_offload_control.c:2432-2441)."""
        self._config_call(CfgFunc.set_max_eager_msg_size, value=nbytes)
        self.max_eager_size = nbytes

    def set_max_rendezvous_msg_size(self, nbytes: int) -> None:
        self._config_call(CfgFunc.set_max_rendezvous_msg_size, value=nbytes)
        self.max_rendezvous_size = nbytes

    def set_timeout(self, timeout: int) -> None:
        self._config_call(CfgFunc.set_timeout, value=timeout)
        #: last engine receive budget written (µs) — the recovery
        #: supervisor raises it for an episode and restores it after
        self.engine_timeout_us = int(timeout)

    # flat-tree schedule thresholds (reference exchange-memory tuning
    # registers, accl.cpp:1214-1224 / ccl_offload_control.h:86-90) —
    # aliases of the ONE authoritative table, constants.TuningKey
    BCAST_FLAT_TREE_MAX_RANKS = int(TuningKey.BCAST_FLAT_TREE_MAX_RANKS)
    REDUCE_FLAT_TREE_MAX_RANKS = int(
        TuningKey.REDUCE_FLAT_TREE_MAX_RANKS)
    GATHER_FLAT_TREE_MAX_FANIN = int(
        TuningKey.GATHER_FLAT_TREE_MAX_FANIN)
    EGRESS_PIPELINE_DEPTH = int(TuningKey.EGRESS_PIPELINE_DEPTH)
    GATHER_FLAT_TREE_MAX_COUNT = int(
        TuningKey.GATHER_FLAT_TREE_MAX_COUNT)
    REDUCE_FLAT_TREE_MAX_COUNT = int(
        TuningKey.REDUCE_FLAT_TREE_MAX_COUNT)

    def static_tuning(self) -> dict:
        """The firmware-ported static tuning-register values
        (reference configure_tuning_parameters, accl.cpp:1214-1224) —
        the ONE place they are written down: initialize applies them,
        and the autotuner's algorithm lanes restore them after a sweep
        so "static" always means exactly this."""
        return {
            int(TuningKey.GATHER_FLAT_TREE_MAX_FANIN): 2,
            int(TuningKey.GATHER_FLAT_TREE_MAX_COUNT): 32 * 1024,
            int(TuningKey.BCAST_FLAT_TREE_MAX_RANKS): 3,
            int(TuningKey.REDUCE_FLAT_TREE_MAX_RANKS): 4,
            int(TuningKey.REDUCE_FLAT_TREE_MAX_COUNT):
                min(self.max_rendezvous_size // 4, 32 * 1024),
        }

    def apply_static_tuning(self) -> None:
        """Write the static register values of :meth:`static_tuning`."""
        for key, value in self.static_tuning().items():
            self.set_tuning(key, value)

    def set_compression(self, policy) -> None:
        """Arm (or disarm, with ``None``) the wire-compression policy
        (:class:`~accl_tpu.arithconfig.CompressionPolicy`, r17): calls
        matching its collective/dtype/size thresholds get their
        ``compress_dtype`` selected automatically — int8 rides the
        block-scaled engine lane (with the EQuARX error-feedback twin
        when the policy asks), float16/bfloat16 the cast lanes.  The
        descriptor memo is dropped: cached descriptors predate the
        policy's decisions."""
        self._compress_policy = policy
        self._call_memo.clear()

    @property
    def compression_policy(self):
        return self._compress_policy

    def set_tuning(self, key: int, value: int) -> None:
        """Write one runtime tuning register (constants.TuningKey).
        Unknown keys raise an ACCLError naming the key and the known
        set — never a silent no-op (clear-error contract, r16); the
        backend additionally rejects keys it does not implement (e.g.
        RING_THRESHOLD_BYTES on the emulator engine)."""
        from .constants import TUNING_KEY_NAMES, unknown_tuning_key_error

        if key not in TUNING_KEY_NAMES:
            raise unknown_tuning_key_error(
                key, frozenset(TUNING_KEY_NAMES), "any")
        setter = getattr(self._device, "set_tuning", None)
        if setter is not None:
            setter(key, value)

    def get_duration(self, request: Optional[Request] = None) -> float:
        """Duration in ns of a completed call, from the engine's
        performance counter (reference: accl.cpp:1387 get_duration;
        simdevice.cpp:123 cycle→ns scaling).

        Raises ACCLError when no call has been issued or the request is
        still in flight — a silent 0.0 there poisoned bandwidth math
        downstream (0 ns == infinite busbw) without any signal."""
        req = request or self._last_request
        if req is None:
            raise ACCLError("get_duration: no request issued yet")
        if not req.done:
            raise ACCLError(
                f"get_duration: {req.description or 'request'} (id "
                f"{req.id}) has not completed — wait() on it first")
        return req.duration_ns

    # ------------------------------------------------------------------
    # fault tolerance (accl_tpu/resilience; docs/fault_tolerance.md) —
    # the detect -> recover bridge over the failure-detection machinery
    # (seqn discipline + receive timeouts + flight recorder + watchdog)
    # ------------------------------------------------------------------
    def abort(self, comm_id: int = GLOBAL_COMM, error: int = 0) -> None:
        """Abort a communicator (ULFM revoke analog): bump its epoch,
        propagate the abort through the control plane, and fail every
        pending request on all live ranks fast with ``COMM_ABORTED``
        (OR ``error`` in — the watchdog's abort action passes
        ``RANK_FAILED``).  Blocked :meth:`Request.wait` callers wake as
        their engine finalizes them — immediately, not after the
        ``ACCL_DEFAULT_TIMEOUT`` budget.  Stale traffic from the dead
        epoch is fenced at the pool boundary; recover with
        :meth:`shrink_communicator` (dead peer) or
        :meth:`reset_errors` (transient fault)."""
        self.communicator(comm_id)  # raises the naming error on bad ids
        err = int(error) | int(ErrorCode.COMM_ABORTED)
        self._aborted_comms.add(comm_id)
        # lifecycle anchor (r13): the fence event goes into the flight
        # ring so post-mortem dumps can order replays against it
        # (analysis.checks.check_fence_staleness)
        _flight.mark_event(self.flight_recorder, "abort", comm_id, err)
        self._invalidate_plans(comm_id, "communicator aborted")
        handled = self._device.abort_comm(comm_id, err)
        if not handled:
            # backend has no engine-side abort: fail the driver-tracked
            # pending async requests directly so waiters still wake.
            # Only THIS comm's requests — a healthy sibling comm's
            # in-flight calls must not report COMM_ABORTED (the flight
            # record carries the comm; without one — ACCL_FLIGHT=0 —
            # the comm is unknowable and the conservative choice is to
            # fail the request rather than strand its waiter forever)
            for ref in self._async_pending:
                r = ref()
                if r is not None and not r.done and \
                        (r.flight is None or r.flight.comm == comm_id):
                    r.complete(err)

    def shrink_communicator(self, comm_id: int = GLOBAL_COMM,
                            window_s: float = 1.0) -> int:
        """ULFM-style shrink: agree on the surviving rank set of
        ``comm_id`` (liveness from control-plane heartbeats + the
        probe window) and build a fresh communicator excluding dead
        ranks; returns the new comm id.  Collective over the survivors
        — every live rank must call it in the same order, exactly like
        :meth:`create_communicator`.  The usual recovery sequence after
        a rank death is ``abort() -> shrink_communicator() -> re-run
        the collective on the returned comm``."""
        from .resilience.membership import shrink as _shrink

        new_id = _shrink(self, comm_id, window_s)
        _flight.mark_event(self.flight_recorder, "shrink", comm_id)
        # plan fencing: a healed world must never replay a dead comm's
        # plan — fence driver-side plans AND the engine-side ring/cache
        # (the emu engine drains its plan slots here, not only on abort)
        self._invalidate_plans(comm_id, "communicator shrunk")
        inv = getattr(self._device, "invalidate_plans", None)
        if inv is not None:
            inv(comm_id)
        if _metrics.enabled():
            _metrics.default_registry().inc("membership/shrinks")
        return new_id

    def grow_communicator(self, new_ranks, comm_id: int = GLOBAL_COMM,
                          window_s: float = 1.0) -> int:
        """Elastic grow, the mirror of :meth:`shrink_communicator`:
        mint a fresh communicator over the LIVE members of ``comm_id``
        plus ``new_ranks`` (:class:`~accl_tpu.communicator.Rank` rows
        for ranks joining the world — e.g. a replacement for a killed
        member).  Collective over the survivors, in create order; each
        joiner adopts the identical table through
        :func:`accl_tpu.resilience.elastic.join_grown_world` (its
        engine state-synced from a sponsor first, so epochs, abort
        fences and comm-id spaces align).  In-flight traffic on other
        communicators is untouched — the dead world stays fenced
        behind its bumped epoch, it is never drained."""
        from .resilience.elastic import grow as _grow

        new_id = _grow(self, new_ranks, comm_id, window_s)
        _flight.mark_event(self.flight_recorder, "grow", comm_id)
        # same plan-fencing contract as shrink: membership changed, the
        # captured world no longer exists
        self._invalidate_plans(comm_id, "communicator grown")
        inv = getattr(self._device, "invalidate_plans", None)
        if inv is not None:
            inv(comm_id)
        return new_id

    def supervise(self, policy=None, board=None, registry=None):
        """Arm (and return) a recovery supervisor for this rank — the
        automated detect -> abort -> probe -> shrink-or-grow -> agree
        -> resume loop (resilience/supervisor.py; policy via
        ``ACCL_RECOVERY`` / ``ACCL_JOIN_WAIT_S`` /
        ``ACCL_RECOVERY_MAX_ROUNDS`` or an explicit RecoveryPolicy).
        Also armed automatically by ``ACCL_SUPERVISE=1`` at
        :meth:`initialize`."""
        from .resilience.supervisor import RecoverySupervisor

        self.supervisor = RecoverySupervisor(self, policy=policy,
                                             board=board,
                                             registry=registry)
        return self.supervisor

    # ------------------------------------------------------------------
    # persistent collective plans (accl_tpu/plans.py;
    # docs/performance.md "Persistent plans")
    # ------------------------------------------------------------------
    def capture_plan(self, fn, *args, validate: bool = True,
                     timeout_s: Optional[float] = None):
        """Capture ``fn(self, *args)``'s collective calls into a
        persistent plan: recorded once (the calls still execute — the
        capture iteration's results are real), validated once (the
        sanitizer checker suite; an error finding fails the capture
        naming it), lowered once into the backend's pre-resolved
        submission ring, then replayed with ``plan.replay()`` at ring
        speed.  Under ``ACCL_PLAN=0`` returns an eager fallback whose
        replay re-runs ``fn`` through the normal per-call path."""
        if not _plans.enabled():
            fn(self, *args)  # the capture iteration still executes
            return _plans.EagerPlan(self, fn, args)
        if self._plan_recorder is not None:
            raise ACCLError("capture_plan: a capture is already in "
                            "progress on this driver (no nesting)")
        recorder = _plans.PlanRecorder(self)
        self._plan_recorder = recorder
        try:
            fn(self, *args)
        finally:
            self._plan_recorder = None
        return _plans.build_plan(self, recorder, validate=validate,
                                 timeout_s=timeout_s)

    def _invalidate_plans(self, comm_id: Optional[int],
                          reason: str) -> None:
        """Fence live plans touching ``comm_id`` (None = all): part of
        the abort/reset/shrink/grow contract — a replay must raise (or
        transparently re-capture, on the auto lane) after any epoch
        fence, never silently run the dead world's program."""
        live = []
        for ref in self._plans:
            p = ref()
            if p is None:
                continue
            live.append(ref)
            if comm_id is None or comm_id in p.comms:
                p._invalidate(reason)
        self._plans = live
        if self._auto_rings:
            self._auto_rings.clear()
        self._auto_last = None
        self._auto_streak = 0
        # the selection-policy memo keys on (scenario, arithcfg,
        # count, comm): after a membership change the same comm id can
        # mean a different size (and payload bucket), so drop the
        # cached decisions — the next call re-resolves at current size
        if self._tune_policy is not None:
            self._tune_policy._memo.clear()
        # transparent-dispatch composers (r19) memoize an axis split
        # over a specific membership epoch; a fence retires them the
        # same way it retires captured plans
        self._drop_hier_comms()

    def _drop_hier_comms(self) -> None:
        """Retire the transparent-dispatch composer memo (r19): cached
        scratch is freed; the burned sub-comm ids stay (create-order
        discipline), and a later qualifying call re-mints sub-comms in
        gang order on every rank alike."""
        if not self._hier_comms:
            return
        for h in self._hier_comms.values():
            if h:
                try:
                    h.close()
                except ACCLError:
                    pass
        self._hier_comms.clear()

    def _route_hier(self, call: CCLOCall, sync_in: list, sync_out: list,
                    run_async: bool, desc: str):
        """Serve one call through the composer the selection table
        picked for its cell (r19 transparent hierarchical dispatch).
        Returns the last staged call's completed Request, or None when
        the call does not qualify — root-dependent, async, device-
        resident, stream/compressed/fused, sub-communicator, capture
        or sanitizer active — and must ride the flat path.  First
        qualifying call per (comm, axis split) mints the composer:
        lazy construction is create-order aligned because every rank
        reaches the same first qualifying call in gang order."""
        if (run_async or call.comm != GLOBAL_COMM
                or not sync_in or not sync_out
                or call.compression_flags != CompressionFlags.NO_COMPRESSION
                or call.stream_flags != StreamFlags.NO_STREAM
                or call.host_flags != HostFlags.NO_HOST
                or call.fused
                or self._plan_recorder is not None or _san.active()):
            return None
        op = Operation(call.scenario)
        if op.name not in ("allreduce", "reduce_scatter", "allgather"):
            return None
        table = self._tune_policy.table
        meta = table.world or {}
        key = (call.comm, tuple(meta.get("shape") or ()),
               tuple(meta.get("axis_order") or ()))
        h = self._hier_comms.get(key)
        if h is None:
            from .tuning.autotune import fabric_of_table
            from .tuning.compose import HierarchicalComm

            fabric = fabric_of_table(table, self.size)
            if fabric.trivial:
                # nothing to compose across: remember the miss so the
                # next call is one dict probe, and ride the flat path
                self._hier_comms[key] = False
                return None
            h = HierarchicalComm(self, fabric)
            self._hier_comms[key] = h
        elif h is False:
            return None
        sendbuf, recvbuf = sync_in[0][0], sync_out[0][0]
        self._in_hier = True
        try:
            if op is Operation.allreduce:
                h.allreduce(sendbuf, recvbuf, call.count,
                            ReduceFunction(call.function))
            elif op is Operation.reduce_scatter:
                h.reduce_scatter(sendbuf, recvbuf, call.count,
                                 ReduceFunction(call.function))
            else:
                h.allgather(sendbuf, recvbuf, call.count)
        finally:
            self._in_hier = False
        return self._last_request

    def _replay_auto(self, entry, desc: str) -> Optional[Request]:
        """Route one auto-captured call through its plan ring; returns
        a completed Request, or None when the ring was invalidated by
        an epoch fence (the caller falls through to the eager path,
        which re-captures — or fast-fails if the comm is still dead)."""
        call, ring = entry
        rec = None
        if self.flight_recorder is not None and _flight.enabled():
            rec = self.flight_recorder.new_record(
                next(_plans._replay_ids), "plan_replay", call.comm,
                call.tag, "plan", call.count, 0, self.comm.size, True,
                _trace.now_ns())
            rec.mark_dispatched("plan", _trace.now_ns())
        try:
            self._device.plan_replay(ring, run_async=False,
                                     timeout_s=self.call_timeout_s)
        except ACCLError as e:
            code = int(getattr(e, "code", 0))
            if rec is not None:
                rec.finish(code or int(ErrorCode.DMA_INTERNAL_ERROR),
                           _trace.now_ns())
            self._auto_rings.pop(id(call), None)
            if code & int(ErrorCode.COMM_ABORTED) \
                    or "invalidated" in str(e):
                return None  # fenced: transparent re-capture via eager
            raise
        if rec is not None:
            rec.finish(0, _trace.now_ns())
        if _metrics.enabled():
            _metrics.default_registry().inc("plans/replays")
        req = Request(desc, sync=True)
        req.complete(0, 0.0)
        self._last_request = req
        return req

    def _install_communicator(self, comm: Communicator) -> int:
        """Append + upload an explicitly-built communicator (the elastic
        grow/join path, where rows do NOT come from world-comm indices).
        Enforces the id-alignment contract: the object's id must be the
        next slot on this rank."""
        if comm.id != len(self._communicators):
            raise ACCLError(
                f"_install_communicator: comm id {comm.id} is not the "
                f"next slot ({len(self._communicators)}) on this rank — "
                f"the group's create/grow order has diverged")
        self._device.upload_communicator(comm)
        self._communicators.append(comm)
        return comm.id

    def _pad_communicators(self, count: int) -> None:
        """Pad this driver's comm-id space with placeholder slots up to
        ``count`` (elastic join: the engine side is padded by the
        Join/Welcome/StateSync exchange; this is the driver half)."""
        while len(self._communicators) < count:
            cid = len(self._communicators)
            self._communicators.append(Communicator.placeholder(cid))
            self._placeholder_comms.add(cid)

    def reset_errors(self) -> None:
        """Recover a world poisoned by a CLASSIFIED transient fault
        (seqn skew after a drop/corruption that exhausted recovery):
        resynchronize sequence state, drain transient receive and
        retransmit state, clear driver-side abort fencing.  Collective:
        every rank of a quiesced world must call it — after which the
        next collective on the same world must succeed (the
        fixture-reuse contract in tests/test_fault_injection.py)."""
        self._aborted_comms.clear()
        _flight.mark_event(self.flight_recorder, "reset_errors", -1)
        # plan fencing: reset_errors is a world-state discontinuity —
        # every plan (driver + engine side) is invalidated; re-capture
        # on the recovered world (the emu engine drains its own plan
        # slots inside reset_errors, the TPU engine in reset below)
        self._invalidate_plans(None, "reset_errors")
        self._device.reset_errors()

    def resilience_stats(self) -> dict:
        """Engine-side recovery counters (retransmitted segments, NACKs
        sent/received, epoch-fenced drops), or an empty dict for
        backends without the retransmission lane."""
        fn = getattr(self._device, "resilience_stats", None)
        return fn() if fn is not None else {}

    # ------------------------------------------------------------------
    # session lifecycle (reference: open_port/open_con/close_con,
    # accl.hpp:1069-1083, backed by the tcp_session_handler plugin)
    # ------------------------------------------------------------------
    def open_port(self) -> None:
        """Verify the inbound endpoint is live (reference open_port).
        Connectionless backends (inproc, datagram, TPU/ICI) succeed as
        no-ops — as in the reference, where only the TCP design ships
        the session handler."""
        fn = getattr(self._device, "open_port", None)
        if fn is not None and fn() != 0:
            raise ACCLError("open_port failed: transport not listening")

    def open_con(self, comm_id: int = GLOBAL_COMM) -> None:
        """Explicitly open sessions to every peer of a communicator,
        surfacing connection failures as a distinct setup error instead
        of a mid-collective hang (reference open_con)."""
        fn = getattr(self._device, "open_con", None)
        if fn is None:
            return  # connectionless backend
        rc = fn(comm_id)
        if rc > 0:
            raise ACCLError(
                f"open_con failed: no session to peer {rc - 1} "
                f"(comm {comm_id})")
        if rc < 0:
            raise ACCLError(f"open_con: unknown communicator {comm_id}")

    def close_con(self, comm_id: int = GLOBAL_COMM) -> None:
        """Tear down the sessions of a communicator (reference
        close_con).  A later call lazily reconnects on session
        transports."""
        fn = getattr(self._device, "close_con", None)
        if fn is not None and fn(comm_id) < 0:
            raise ACCLError(f"close_con: unknown communicator {comm_id}")

    # ------------------------------------------------------------------
    # buffers
    # ------------------------------------------------------------------
    def create_buffer(self, length: int, dtype=np.float32,
                      host_only: bool = False) -> BaseBuffer:
        """Allocate a paired host+device buffer; with host_only=True the
        device residence is the engine's host-memory region instead (the
        reference's host-only buffers over the external_dma path,
        accl.hpp:774-1004 create_buffer<T> family + buffer.hpp
        is_host_only).  Backends without a distinct host region fall
        back to a normal buffer."""
        if host_only:
            try:
                return self._device.create_buffer(length, np.dtype(dtype),
                                                  host_only=True)
            except TypeError:
                pass  # backend has no host region; plain buffer below
        return self._device.create_buffer(length, np.dtype(dtype))

    def create_buffer_like(self, data: np.ndarray) -> BaseBuffer:
        buf = self.create_buffer(int(np.asarray(data).size), np.asarray(data).dtype)
        buf.host[:] = np.asarray(data).reshape(-1)
        return buf

    def create_buffer_p2p(self, length: int, dtype=np.float32) -> BaseBuffer:
        """Allocate a buffer directly addressable by peer engines without
        host staging (reference: FPGABufferP2P — PCIe-p2p-visible BO,
        fpgabufferp2p.hpp).  On this build every device buffer is already
        peer-addressable (emulator: shared device memory; TPU: HBM
        reachable over ICI), so this maps to the backend's p2p variant
        when it has one and a plain device buffer otherwise."""
        make = getattr(self._device, "create_buffer_p2p", None)
        if make is not None:
            return make(length, np.dtype(dtype))
        return self._device.create_buffer(length, np.dtype(dtype))

    # ------------------------------------------------------------------
    # collectives — each mirrors one reference entry point in accl.cpp
    # ------------------------------------------------------------------
    def send(
        self,
        srcbuf: BaseBuffer,
        count: int,
        dst: int,
        tag: int = TAG_ANY,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        stream_flags: StreamFlags = StreamFlags.NO_STREAM,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
    ):
        """Point-to-point send (reference: accl.cpp:138)."""
        call = self._build(
            Operation.send, count, comm_id, root_src_dst=dst, tag=tag,
            op0=srcbuf, stream_flags=stream_flags, compress_dtype=compress_dtype,
        )
        return self._execute(call, sync_in=[] if from_fpga else [(srcbuf, count)],
                             sync_out=[], run_async=run_async, desc=f"send(dst={dst})")

    def recv(
        self,
        dstbuf: BaseBuffer,
        count: int,
        src: int,
        tag: int = TAG_ANY,
        comm_id: int = GLOBAL_COMM,
        to_fpga: bool = False,
        stream_flags: StreamFlags = StreamFlags.NO_STREAM,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
    ):
        """Point-to-point receive (reference: accl.cpp:252)."""
        call = self._build(
            Operation.recv, count, comm_id, root_src_dst=src, tag=tag,
            res=dstbuf, stream_flags=stream_flags, compress_dtype=compress_dtype,
        )
        return self._execute(call, sync_in=[],
                             sync_out=[] if to_fpga else [(dstbuf, count)],
                             run_async=run_async, desc=f"recv(src={src})")

    def stream_put(
        self,
        srcbuf: BaseBuffer,
        count: int,
        dst: int,
        stream_id: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        run_async: bool = False,
    ):
        """Send into a remote compute stream: the payload is routed to
        stream `stream_id` on the destination instead of a memory buffer
        (reference: accl.cpp:191-250 stream_put; remote routing by header
        strm field, udp_depacketizer.cpp:136-147)."""
        if stream_id < 9:
            raise ACCLError("stream ids < 9 are reserved")  # reference: accl.cpp:197
        call = self._build(
            Operation.send, count, comm_id, root_src_dst=dst, tag=stream_id,
            op0=srcbuf, stream_flags=StreamFlags.RES_STREAM,
        )
        return self._execute(call, sync_in=[] if from_fpga else [(srcbuf, count)],
                             sync_out=[], run_async=run_async,
                             desc=f"stream_put(dst={dst}, strm={stream_id})")

    def copy(
        self,
        srcbuf: BaseBuffer,
        dstbuf: BaseBuffer,
        count: int,
        from_fpga: bool = False,
        to_fpga: bool = False,
        run_async: bool = False,
    ):
        """Local device-side copy (reference: accl.cpp:310)."""
        call = self._build(Operation.copy, count, GLOBAL_COMM, op0=srcbuf, res=dstbuf)
        return self._execute(call, sync_in=[] if from_fpga else [(srcbuf, count)],
                             sync_out=[] if to_fpga else [(dstbuf, count)],
                             run_async=run_async, desc="copy")

    def copy_to_stream(
        self,
        srcbuf: BaseBuffer,
        count: int,
        stream_id: int = 9,
        from_fpga: bool = False,
        run_async: bool = False,
    ):
        """Copy a device buffer into a local kernel stream
        (reference: accl.cpp copy_to_stream — copy with RES_STREAM; the
        result lane is routed to the external-kernel switch port)."""
        if stream_id < 9:
            raise ACCLError("stream ids < 9 are reserved")  # accl.cpp:197
        call = self._build(Operation.copy, count, GLOBAL_COMM, op0=srcbuf,
                           tag=stream_id, stream_flags=StreamFlags.RES_STREAM)
        return self._execute(call, sync_in=[] if from_fpga else [(srcbuf, count)],
                             sync_out=[], run_async=run_async,
                             desc=f"copy_to_stream({stream_id})")

    def copy_from_stream(
        self,
        dstbuf: BaseBuffer,
        count: int,
        to_fpga: bool = False,
        run_async: bool = False,
    ):
        """Copy from the local kernel input stream into a device buffer
        (reference: accl.cpp copy_from_stream — copy with OP0_STREAM)."""
        call = self._build(Operation.copy, count, GLOBAL_COMM, res=dstbuf,
                           stream_flags=StreamFlags.OP0_STREAM)
        return self._execute(call, sync_in=[],
                             sync_out=[] if to_fpga else [(dstbuf, count)],
                             run_async=run_async, desc="copy_from_stream")

    def combine(
        self,
        count: int,
        function: ReduceFunction,
        op0: BaseBuffer,
        op1: BaseBuffer,
        res: BaseBuffer,
        from_fpga: bool = False,
        to_fpga: bool = False,
        run_async: bool = False,
    ):
        """Local elementwise reduction of two device buffers
        (reference: accl.cpp:378)."""
        call = self._build(
            Operation.combine, count, GLOBAL_COMM, function=int(function),
            op0=op0, op1=op1, res=res,
        )
        sync_in = [] if from_fpga else [(op0, count), (op1, count)]
        return self._execute(call, sync_in=sync_in,
                             sync_out=[] if to_fpga else [(res, count)],
                             run_async=run_async, desc=f"combine({function.name})")

    def bcast(
        self,
        buf: BaseBuffer,
        count: int,
        root: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
    ):
        """Broadcast from root (reference: accl.cpp:418)."""
        comm = self.communicator(comm_id)
        is_root = comm.local_rank == root
        call = self._build(
            Operation.bcast, count, comm_id, root_src_dst=root,
            op0=buf if is_root else None, res=None if is_root else buf,
            compress_dtype=compress_dtype,
        )
        sync_in = [(buf, count)] if (is_root and not from_fpga) else []
        sync_out = [(buf, count)] if (not is_root and not to_fpga) else []
        return self._execute(call, sync_in=sync_in, sync_out=sync_out,
                             run_async=run_async, desc=f"bcast(root={root})")

    def scatter(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        root: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
    ):
        """Scatter `count` elements to each rank from root
        (reference: accl.cpp:464)."""
        comm = self.communicator(comm_id)
        is_root = comm.local_rank == root
        call = self._build(
            Operation.scatter, count, comm_id, root_src_dst=root,
            op0=sendbuf if is_root else None, res=recvbuf,
            compress_dtype=compress_dtype,
            op0_dtype=sendbuf.data_type if sendbuf is not None else None,
        )
        sync_in = [(sendbuf, count * comm.size)] if (is_root and not from_fpga) else []
        sync_out = [] if to_fpga else [(recvbuf, count)]
        return self._execute(call, sync_in=sync_in, sync_out=sync_out,
                             run_async=run_async, desc=f"scatter(root={root})")

    def gather(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        root: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
    ):
        """Gather `count` elements from each rank at root
        (reference: accl.cpp:513)."""
        comm = self.communicator(comm_id)
        is_root = comm.local_rank == root
        call = self._build(
            Operation.gather, count, comm_id, root_src_dst=root,
            op0=sendbuf, res=recvbuf if is_root else None,
            compress_dtype=compress_dtype,
            res_dtype=recvbuf.data_type if recvbuf is not None else None,
        )
        sync_in = [] if from_fpga else [(sendbuf, count)]
        sync_out = [(recvbuf, count * comm.size)] if (is_root and not to_fpga) else []
        return self._execute(call, sync_in=sync_in, sync_out=sync_out,
                             run_async=run_async, desc=f"gather(root={root})")

    def allgather(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
        fused: Optional[bool] = None,
    ):
        """All-gather (reference: accl.cpp:571).  ``fused``: see
        allreduce."""
        comm = self.communicator(comm_id)
        call = self._build(
            Operation.allgather, count, comm_id,
            op0=sendbuf, res=recvbuf, compress_dtype=compress_dtype,
            fused=fused,
        )
        return self._execute(call,
                             sync_in=[] if from_fpga else [(sendbuf, count)],
                             sync_out=[] if to_fpga else [(recvbuf, count * comm.size)],
                             run_async=run_async, desc="allgather")

    def reduce(
        self,
        sendbuf: Optional[BaseBuffer],
        recvbuf: Optional[BaseBuffer],
        count: int,
        root: int,
        function: ReduceFunction = ReduceFunction.SUM,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        stream_flags: StreamFlags = StreamFlags.NO_STREAM,
        stream_id: int = 9,
        run_async: bool = False,
    ):
        """Rooted reduction (reference: accl.cpp:627-794, 4 overloads).

        The mem<->stream variants (reference: test.cpp:813-910) are selected
        with `stream_flags`: OP0_STREAM takes the operand from the local
        compute-kernel stream (`sendbuf` may be None; feed bytes with
        `device.push_krnl`), RES_STREAM delivers the root's result to local
        compute stream `stream_id` (`recvbuf` may be None; read it with
        `device.pop_stream`)."""
        comm = self.communicator(comm_id)
        is_root = comm.local_rank == root
        op_stream = bool(stream_flags & StreamFlags.OP0_STREAM)
        res_stream = bool(stream_flags & StreamFlags.RES_STREAM)
        if res_stream and stream_id < 9:
            raise ACCLError("stream ids < 9 are reserved")  # accl.cpp:197
        call = self._build(
            Operation.reduce, count, comm_id, root_src_dst=root,
            function=int(function),
            tag=stream_id if res_stream else TAG_ANY,
            op0=None if op_stream else sendbuf,
            res=recvbuf if (is_root and not res_stream) else None,
            stream_flags=stream_flags, compress_dtype=compress_dtype,
            res_dtype=(recvbuf.data_type
                       if (recvbuf is not None and not res_stream) else None),
        )
        sync_in = [] if (from_fpga or op_stream) else [(sendbuf, count)]
        sync_out = ([(recvbuf, count)]
                    if (is_root and not to_fpga and not res_stream) else [])
        return self._execute(call, sync_in=sync_in,
                             sync_out=sync_out, run_async=run_async,
                             desc=f"reduce(root={root},{function.name})")

    def allreduce(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        function: ReduceFunction = ReduceFunction.SUM,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
        fused: Optional[bool] = None,
    ):
        """All-reduce (reference: accl.cpp:796).  ``fused`` opts the call
        into the r18 chunked compute/communication-overlap lane (None =
        the driver's ACCL_FUSED default)."""
        call = self._build(
            Operation.allreduce, count, comm_id, function=int(function),
            op0=sendbuf, res=recvbuf, compress_dtype=compress_dtype,
            fused=fused,
        )
        return self._execute(call, sync_in=[] if from_fpga else [(sendbuf, count)],
                             sync_out=[] if to_fpga else [(recvbuf, count)],
                             run_async=run_async, desc=f"allreduce({function.name})")

    def reduce_scatter(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        function: ReduceFunction = ReduceFunction.SUM,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        compress_dtype: Optional[DataType] = None,
        run_async: bool = False,
        fused: Optional[bool] = None,
    ):
        """Reduce-scatter: each rank ends with `count` reduced elements
        (reference: accl.cpp:844).  ``fused``: see allreduce."""
        comm = self.communicator(comm_id)
        call = self._build(
            Operation.reduce_scatter, count, comm_id, function=int(function),
            op0=sendbuf, res=recvbuf, compress_dtype=compress_dtype,
            fused=fused,
        )
        return self._execute(call,
                             sync_in=[] if from_fpga else [(sendbuf, count * comm.size)],
                             sync_out=[] if to_fpga else [(recvbuf, count)],
                             run_async=run_async, desc=f"reduce_scatter({function.name})")

    def alltoall(
        self,
        sendbuf: BaseBuffer,
        recvbuf: BaseBuffer,
        count: int,
        comm_id: int = GLOBAL_COMM,
        from_fpga: bool = False,
        to_fpga: bool = False,
        run_async: bool = False,
    ):
        """All-to-all personalized exchange (reference: accl.cpp:892)."""
        comm = self.communicator(comm_id)
        call = self._build(Operation.alltoall, count, comm_id,
                           op0=sendbuf, res=recvbuf)
        return self._execute(call,
                             sync_in=[] if from_fpga else [(sendbuf, count * comm.size)],
                             sync_out=[] if to_fpga else [(recvbuf, count * comm.size)],
                             run_async=run_async, desc="alltoall")

    def barrier(self, comm_id: int = GLOBAL_COMM, run_async: bool = False):
        """Barrier over the communicator (reference: accl.cpp:947)."""
        call = self._build(Operation.barrier, 0, comm_id)
        return self._execute(call, sync_in=[], sync_out=[],
                             run_async=run_async, desc="barrier")

    def nop(self, run_async: bool = False):
        call = self._build(Operation.nop, 0, GLOBAL_COMM)
        return self._execute(call, sync_in=[], sync_out=[],
                             run_async=run_async, desc="nop")

    # ------------------------------------------------------------------
    # marshaling (reference: accl.cpp:1252-1372 prepare_call)
    # ------------------------------------------------------------------
    def _build(
        self,
        scenario: Operation,
        count: int,
        comm_id: int,
        root_src_dst: int = 0,
        function: int = 0,
        tag: int = TAG_ANY,
        op0: Optional[BaseBuffer] = None,
        op1: Optional[BaseBuffer] = None,
        res: Optional[BaseBuffer] = None,
        stream_flags: StreamFlags = StreamFlags.NO_STREAM,
        compress_dtype: Optional[DataType] = None,
        op0_dtype: Optional[DataType] = None,
        res_dtype: Optional[DataType] = None,
        fused: Optional[bool] = None,
    ) -> CCLOCall:
        """Build a call descriptor: select the arithmetic config from the
        (uncompressed, compressed) dtype pair, derive per-operand and
        on-the-wire compression flags, substitute dummies for absent
        operands — the same responsibilities as the reference prepare_call
        (accl.cpp:1252-1372).

        The full reference flag algebra is implemented: mixed-dtype
        operands mark whichever of OP0/OP1/RES holds the *compressed*
        (narrower) representation (accl.cpp:1310-1335); `compress_dtype`
        additionally requests wire compression (ETH_COMPRESSED,
        accl.cpp:1338-1367), and operands already typed as the compressed
        dtype get their per-operand bit as well.

        Cross-rank contract (same as the reference): every rank of a
        collective must derive the same arithcfg + ETH flag, since each
        engine computes the wire format from its own descriptor.  Absent
        operands therefore contribute dtype hints (op0_dtype/res_dtype,
        the reference's data_type_io_* fields) — so mixed-dtype rooted
        collectives must either pass the absent-side buffer everywhere
        (reduce/gather/scatter do this automatically when the buffer
        argument is supplied on every rank) or set compress_dtype, which
        pins the wire format regardless of per-rank operand layout
        (tests/test_compression_matrix.py ROOTED_COMBOS)."""
        # each buffer contributes (address, dtype, host-only): every
        # _build-derived field is a function of those three plus the
        # scalar args.  dtype/host-only are IN the key because emulator
        # backends free and first-fit-REUSE addresses (engine.cpp
        # free_addr) — an address-only key could serve a stale arithcfg
        # for a recycled address with a different dtype; with all three,
        # a recycled address either matches (identical descriptor) or
        # misses.
        # a bad comm id must fail HERE with a decodable error, not as a
        # backend IndexError (or a silent engine hang) later; the slow
        # path is one len() + compare, the raise is delegated.  The
        # world comm on an uninitialized driver stays permissive:
        # local-op descriptors (copy/nop) are buildable pre-bring-up
        if (comm_id < 0 or comm_id >= len(self._communicators)) and \
                (self._communicators or comm_id != GLOBAL_COMM):
            self.communicator(comm_id)  # raises the naming ACCLError

        def _bkey(b):
            return (None if b is None
                    else (b.address, b.data_type, b.is_host_only))

        # per-call fused=None resolves to the driver default HERE so the
        # memo key carries the resolved bool (two calls differing only
        # in fused must not share a descriptor)
        fused = self._fused_default if fused is None else bool(fused)
        memo_key = (scenario, count, comm_id, root_src_dst, function, tag,
                    _bkey(op0), _bkey(op1), _bkey(res),
                    stream_flags, compress_dtype, op0_dtype, res_dtype,
                    fused)
        cached = self._call_memo.get(memo_key)
        if cached is not None:
            self._call_memo.move_to_end(memo_key)
            return cached

        dummy = DummyBuffer()
        op0 = op0 if op0 is not None else dummy
        op1 = op1 if op1 is not None else dummy
        res = res if res is not None else dummy

        # absent operands still contribute their dtype so every rank of a
        # rooted collective derives the same arithcfg + wire format (the
        # reference's data_type_io_* hints, accl.cpp:1259-1281)
        present = [b for b in (op0, op1, res) if not b.is_dummy]
        dtypes = {b.data_type for b in present}
        if op0.is_dummy and op0_dtype is not None:
            dtypes.add(op0_dtype)
        if res.is_dummy and res_dtype is not None:
            dtypes.add(res_dtype)
        dtypes.discard(DataType.none)
        compression = CompressionFlags.NO_COMPRESSION

        # wire-compression policy (r17): fill in compress_dtype for
        # eligible calls when the caller left it unset.  Deterministic
        # in the memo key's fields + the (static-after-arming) policy,
        # so the descriptor memo above stays sound; stream-flagged and
        # mixed-dtype calls are never auto-compressed.  One falsy read
        # when no policy is armed — bit-identical static dispatch.
        if compress_dtype is None and self._compress_policy is not None \
                and stream_flags == StreamFlags.NO_STREAM \
                and len(dtypes) == 1:
            compress_dtype = self._compress_policy.select(
                scenario, count, comm_id, next(iter(dtypes)))

        def flag_operands(compressed_dtype: DataType) -> CompressionFlags:
            flags = CompressionFlags.NO_COMPRESSION
            if not op0.is_dummy and op0.data_type == compressed_dtype:
                flags |= CompressionFlags.OP0_COMPRESSED
            if not op1.is_dummy and op1.data_type == compressed_dtype:
                flags |= CompressionFlags.OP1_COMPRESSED
            if not res.is_dummy and res.data_type == compressed_dtype:
                flags |= CompressionFlags.RES_COMPRESSED
            return flags

        if compress_dtype is None:
            if len(dtypes) <= 1:
                # homogeneous operands: identity pair (accl.cpp:1297-1307)
                dtype = dtypes.pop() if dtypes else DataType.float32
                pair = (dtype, dtype)
                if pair not in self._arith_ids and scenario not in (
                    Operation.config, Operation.nop, Operation.barrier,
                ):
                    raise ACCLError(f"unsupported dtype {dtype!r}")
                arithcfg = self._arith_ids.get(pair, 0)
            elif len(dtypes) == 2:
                # operand compression without wire compression: the
                # narrower dtype is the compressed representation
                # (accl.cpp:1310-1335)
                d1, d2 = sorted(dtypes, key=lambda d: DATA_TYPE_SIZE[d])
                pair = (d2, d1)
                if pair not in self._arith_ids:
                    raise ACCLError(f"no arithmetic config for dtype pair {pair}")
                arithcfg = self._arith_ids[pair]
                compression = flag_operands(d1)
            else:
                raise ACCLError(f"unsupported dtype combination: {dtypes}")
        else:
            # wire compression requested (accl.cpp:1338-1367)
            operand_dtypes = dtypes - {compress_dtype}
            if len(operand_dtypes) > 1:
                raise ACCLError(f"unsupported dtype combination: {dtypes}")
            uncompressed = (operand_dtypes.pop() if operand_dtypes
                            else compress_dtype)
            if uncompressed == compress_dtype:
                # all operands already typed as the wire dtype: identity
                # config; ETH flag is set for descriptor fidelity but the
                # ratio-0 config makes it a no-op in the engine
                pair = (uncompressed, uncompressed)
                if pair not in self._arith_ids:
                    raise ACCLError(f"unsupported dtype {uncompressed!r}")
                arithcfg = self._arith_ids[pair]
                compression = CompressionFlags.ETH_COMPRESSED
            elif compress_dtype == DataType.int8:
                # block-scaled wire lane (r17): the wire form is
                # (int8, per-block fp32 scales) — it has no flat-buffer
                # residence, so per-operand int8 marking is rejected
                # and the ETH flag stands alone.  The EQuARX
                # error-feedback twin is selected per the armed policy.
                pair = (uncompressed, compress_dtype)
                if pair not in self._arith_ids:
                    raise ACCLError(f"no arithmetic config for dtype pair {pair}")
                if uncompressed != DataType.float32:
                    raise ACCLError(
                        f"int8 block-scaled wire lane supports float32 "
                        f"operands only (got {uncompressed.name})")
                if any(not b.is_dummy and b.data_type == DataType.int8
                       for b in (op0, op1, res)):
                    raise ACCLError(
                        "int8 block-scaled wire lane: operands must be "
                        "float32 — a flat int8 buffer cannot hold the "
                        "(int8, per-block scale) wire representation")
                use_ef = (self._compress_policy is not None
                          and self._compress_policy.wants_error_feedback(
                              comm_id))
                arithcfg = (self._arith_ids_ef[pair] if use_ef
                            else self._arith_ids[pair])
                compression = CompressionFlags.ETH_COMPRESSED
            else:
                pair = (uncompressed, compress_dtype)
                if pair not in self._arith_ids:
                    raise ACCLError(f"no arithmetic config for dtype pair {pair}")
                arithcfg = self._arith_ids[pair]
                compression = (CompressionFlags.ETH_COMPRESSED
                               | flag_operands(compress_dtype))

        # host-resident operand markers (reference prepare_call sets
        # OP0/OP1/RES_HOST from Buffer::is_host_only, accl.cpp:1259-1283)
        host_flags = HostFlags.NO_HOST
        if not op0.is_dummy and op0.is_host_only:
            host_flags |= HostFlags.OP0_HOST
        if not op1.is_dummy and op1.is_host_only:
            host_flags |= HostFlags.OP1_HOST
        if not res.is_dummy and res.is_host_only:
            host_flags |= HostFlags.RES_HOST

        call = CCLOCall(
            scenario=scenario,
            count=count,
            comm=comm_id,
            root_src_dst=root_src_dst,
            function=function,
            tag=tag,
            arithcfg=arithcfg,
            compression_flags=compression,
            stream_flags=stream_flags,
            host_flags=host_flags,
            addr_0=op0.address,
            addr_1=op1.address,
            addr_2=res.address,
            fused=fused,
        )
        self._call_memo[memo_key] = call
        while len(self._call_memo) > self._call_memo_cap:
            self._call_memo.popitem(last=False)
        return call

    def _config_call(self, func: CfgFunc, value: int = 0) -> None:
        """Issue an Operation.config descriptor
        (reference: accl.cpp call_config / cfgFunc dispatch fw :2413-2459)."""
        call = CCLOCall(scenario=Operation.config, count=value, function=int(func))
        req = Request(f"config({func.name})")
        self._queue.submit(req, lambda r: self._device.start(call, r))
        if not req.wait(timeout=30.0):
            raise ACCLError(f"config({func.name}) timed out")
        req.check()

    def _execute(
        self,
        call: CCLOCall,
        sync_in: list,
        sync_out: list,
        run_async: bool,
        desc: str,
    ):
        """Submit one call: sync inputs, start async, and either return the
        request handle or wait + sync outputs + check retcode
        (reference: call_async/call_sync accl.cpp:1395-1413)."""
        # abort fast-fail (resilience): a call on an aborted comm must
        # not burn a receive budget against a fenced engine — one falsy
        # set check when no abort ever happened (the off-path case)
        if self._aborted_comms and call.comm in self._aborted_comms:
            raise ACCLError(
                f"{desc}: communicator {call.comm} is aborted "
                f"(COMM_ABORTED) — shrink_communicator() or "
                f"reset_errors() to recover",
                int(ErrorCode.COMM_ABORTED))
        # placeholder fast-fail (elastic join): same falsy-set cost
        if self._placeholder_comms and call.comm in self._placeholder_comms:
            self.communicator(call.comm)  # raises the naming ACCLError
        # learned selection policy (accl_tpu/tuning): one falsy read
        # when no table is armed; armed, one memoized dict probe per
        # descriptor signature — the policy's threshold derivations
        # were written into the backend registers at install, so this
        # consult only records/serves the per-call decision (metrics
        # family tuning/selected/<algorithm>)
        if self._tune_policy is not None:
            alg = self._tune_policy.on_call(self, call)
            # the r18 fused lane is a DESCRIPTOR opt-in, not a backend
            # register: a table cell won by "fused" arms the memoized
            # call object once (idempotent — _build returns the same
            # object per signature, so every later call of this
            # signature rides the fused gang plan)
            if alg == "fused" and not call.fused:
                call.fused = True
            # transparent hierarchical dispatch (r19): a cell won by
            # the composer routes through a memoized per-(comm,
            # axis-split) HierarchicalComm — the caller never
            # constructs one.  Only the plain sync host path on the
            # global communicator qualifies; everything else falls
            # through to the flat engine call.  The _in_hier guard
            # keeps the composer's own staged sub-comm calls (which
            # re-enter _execute) on the flat path.
            elif alg == "hierarchical" and not self._in_hier:
                routed = self._route_hier(call, sync_in, sync_out,
                                          run_async, desc)
                if routed is not None:
                    return routed
        # plan auto-replay (ACCL_PLAN_AUTO, accl_tpu/plans.py): a call
        # whose gang agreed to arm a one-step ring replays through it —
        # no descriptor work, no gang assembly, no per-call request
        # plumbing.  One falsy read when the auto lane is off; the
        # identity check (`is`) is sound because _build memoizes: the
        # steady-state loop returns the SAME CCLOCall object each step.
        # Placed after the abort fast-fail so a fenced comm raises
        # before any replay could run on a dead epoch.
        if self._auto_rings is not None and not run_async:
            entry = self._auto_rings.get(id(call))
            if entry is not None and entry[0] is call \
                    and self._plan_recorder is None \
                    and not _san.active():
                # the recorder/sanitizer guards keep an armed ring from
                # short-circuiting an explicit capture_plan or an
                # ACCL_SANITIZE lane that must observe every call
                replayed = self._replay_auto(entry, desc)
                if replayed is not None:
                    return replayed
                # ring fenced: fall through to the eager path, which
                # re-captures (or fast-fails if the comm is still dead)
        # observability gate first: one module-bool read each when all
        # are off, and t_submit marks user-call entry (operand staging
        # below is inside the submit→queue window by design).  The
        # flight recorder is in the gate because it is ON by default —
        # the always-on black box — so the no-observer fast path only
        # exists under ACCL_FLIGHT=0 + ACCL_METRICS=0 + trace off.
        observe = (self.flight_recorder is not None or _metrics.enabled()
                   or _trace.enabled())
        t_submit = _trace.now_ns() if observe else 0
        # size validation: the descriptor carries the full count, so a
        # short buffer would silently corrupt (the reference throws from
        # its buffer slice bounds)
        for buf, count in (*sync_in, *sync_out):
            if not buf.is_dummy and count > buf.length:
                raise ACCLError(
                    f"{desc}: count {count} exceeds buffer length {buf.length}"
                )
        for buf, count in sync_in:
            if not buf.is_dummy:
                buf.slice(0, count).sync_to_device()

        # sync=True marks a call whose submitter blocks below: backends
        # with a leader-dispatch fast path (backends/tpu.py) may then
        # execute the gang inline on the last-arriving rank's thread
        req = Request(desc, sync=not run_async)
        if observe:
            self._observe_call(call, desc, req, t_submit)
        # collective sanitizer lane (analysis/sanitizer.py): off-path
        # cost is this one module-bool read; with ACCL_SANITIZE=1 the
        # call is validated (comm/root/peer/operand-overlap) and, on
        # in-process worlds, fingerprint-matched against its gang peers
        # BEFORE dispatch — raising here instead of hanging there.  A
        # shadow CaptureSession records the descriptor the same way.
        if _san.active():
            _san.on_call(self, call, desc, req, run_async)
        # plan capture (capture_plan in progress): shadow-record the
        # descriptor + staging spec; the call still executes below, so
        # the capture iteration's results are real.  One falsy read
        # when no capture is installed.
        if self._plan_recorder is not None:
            self._plan_recorder.on_call(call, sync_in, sync_out,
                                        run_async, desc, req)
        # plan auto-capture intent (ACCL_PLAN_AUTO): after N identical
        # resident sync gang calls, mark intent on the request — the
        # engine arms a ring only when EVERY member of the same gang
        # instance carries intent, so no rank ever replays against an
        # eager peer (the agreement rides the gang itself)
        if self._auto_rings is not None and not run_async \
                and not sync_in and not sync_out \
                and self._plan_recorder is None and not _san.active() \
                and call.scenario in _GANG_OPS:
            if call is self._auto_last:
                self._auto_streak += 1
                if self._auto_streak >= self._plan_auto:
                    req.plan_intent = True
            else:
                self._auto_last = call
                self._auto_streak = 1

        if sync_out:  # device-resident results need no completion sync
            def finish(r: Request) -> None:
                if r.retcode == 0:
                    for buf, count in sync_out:
                        if not buf.is_dummy:
                            buf.slice(0, count).sync_from_device()

            req.on_complete = finish
        self._queue.submit(req, lambda r: self._device.start(call, r))
        self._last_request = req
        if run_async:
            # weak handle only: deinit() names still-pending async
            # requests, but tracking must never extend their lifetime
            import weakref

            self._async_pending.append(weakref.ref(req))
            if len(self._async_pending) > 256:
                self._async_pending = [
                    ref for ref in self._async_pending
                    if (r := ref()) is not None and not r.done]
            return req
        if not req.wait(timeout=self.call_timeout_s):
            # disarm the result sync so a late completion can't mutate the
            # user's host buffers after this raise; the flight record
            # (seq, state, lane, age) pins WHERE the call wedged
            req.on_complete = None
            raise ACCLError(f"{desc} timed out waiting for engine "
                            f"completion{req.flight_info()}")
        req.check()
        # plan auto-capture adoption: the engine published a ring on
        # this request (every member of the gang carried intent) —
        # subsequent identical calls route through _replay_auto
        if self._auto_rings is not None and req.plan_ring is not None:
            self._auto_rings[id(call)] = (call, req.plan_ring)
        return req

    def resolve_call_signature(self, call: CCLOCall) -> tuple:
        """(op, nranks, rank, dtype_name, nbytes) for one descriptor —
        the ONE derivation of the metrics signature, shared by the
        observability gate below and the r16 selection policy's table
        lookup (accl_tpu/tuning/autotune.SelectionPolicy.on_call), so
        the tuner always buckets a call exactly the way the metrics it
        was trained on did."""
        op = Operation(call.scenario)
        comm = (self._communicators[call.comm]
                if call.comm < len(self._communicators) else None)
        nranks = comm.size if comm else 1
        rank = comm.local_rank if comm else -1
        pair = self._arith_pairs.get(call.arithcfg)
        dtype_name = pair[0].name if pair else "none"
        # DATA_TYPE_SIZE is in BITS (reference constants.hpp:268-272)
        elem_bytes = (DATA_TYPE_SIZE.get(pair[0], 0) // 8) if pair else 0
        nbytes = (call.count * elem_bytes
                  * _metrics.payload_factor(op.name, nranks))
        return op, nranks, rank, dtype_name, nbytes

    def _observe_call(self, call: CCLOCall, desc: str, req: Request,
                      t_submit: int) -> None:
        """Attach the observability record(s) to one outgoing request:
        the metrics signature (collective, dtype, size bucket — published
        by Request.complete) and, when tracing is on, the TraceSpan with
        its submit timestamp and gang id.  The gang-id key matches the
        engines' FIFO pairing key (scenario, comm, tag), so rank R's Nth
        instance joins the same gang id every engine would assemble."""
        op, nranks, rank, dtype_name, nbytes = \
            self.resolve_call_signature(call)
        # tenant label (r20): rides the issuing communicator; one
        # attribute read (class-level None when unlabeled)
        tenant = (self._communicators[call.comm].tenant
                  if call.comm < len(self._communicators) else None)
        if self.flight_recorder is not None and _flight.enabled():
            req.flight = self.flight_recorder.new_record(
                req.id, op.name, call.comm, call.tag, dtype_name,
                call.count, nbytes, nranks, op in _GANG_OPS, t_submit,
                tenant)
        if _metrics.enabled():
            req.metric = (_metrics.default_registry(), op.name, dtype_name,
                          nbytes, nranks, t_submit, tenant)
        if _trace.enabled():
            span = _trace.new_span(op.name, desc, rank, call.count,
                                   dtype_name, nbytes, nranks)
            span.t_submit = t_submit
            span.tenant = tenant
            if op in _GANG_OPS:
                span.gang_id = _trace.collector().gang_id_for(
                    (int(op), call.comm, call.tag), rank)
            req.trace = span

    # ------------------------------------------------------------------
    # observability (reference: accl.cpp:980-1064 dump utilities, plus
    # the accl_tpu/observability metrics registry + trace collector)
    # ------------------------------------------------------------------
    def metrics(self) -> dict:
        """Snapshot of the metrics registry this driver publishes into:
        per-(collective, dtype, size-bucket) call counts, fixed-bucket
        latency histograms, and derived algorithmic/bus bandwidth, plus
        this device's engine counters (dispatch-lane attribution on the
        TPU backend) merged under ``engine/``-prefixed keys.  In-process
        worlds share one registry, so the snapshot aggregates every
        rank's calls."""
        snap = _metrics.default_registry().snapshot()
        eng = getattr(self._device, "engine_metrics", None)
        if eng is not None:
            for k, v in eng.counters().items():
                snap["counters"][f"engine/{k}"] = v
        return snap

    def dump_metrics(self, as_json: bool = False) -> str:
        """Text (default) or JSON rendering of :meth:`metrics`
        (registry side only — engine counters are in the dict form)."""
        return _metrics.dump_metrics(as_json=as_json)

    def dump_flight_recorder(self, path: Optional[str] = None,
                             merged: bool = False) -> dict:
        """The always-on flight recorder's ring: this rank's last N
        collective records (seq, state, lane, timestamps) — the black
        box to read when a collective wedges.  With ``merged=True``
        returns every live rank's ring through
        :func:`observability.flight.merge_flight_dumps` (desync/hang
        analysis included); with ``path`` also writes the JSON there.
        Also reachable without code: ``SIGUSR1`` dumps all ranks to
        ``ACCL_FLIGHT_DUMP``, and a watchdog fire dumps automatically.
        """
        if self.flight_recorder is None and not merged:
            raise ACCLError(
                "flight recorder is off (ACCL_FLIGHT=0) or the driver "
                "is not initialized")
        doc = (_flight.dump_all() if merged
               else self.flight_recorder.dump())
        if path:
            import json as _json

            with open(path, "w") as f:
                _json.dump(doc, f, indent=1)
        return doc

    def dump_communicator(self, comm_id: int = GLOBAL_COMM) -> str:
        return self.communicator(comm_id).dump()

    def dump_rx_buffers(self) -> str:
        dump = getattr(self._device, "dump_rx_buffers", None)
        return dump() if dump else "<backend has no rx buffer table>"

    def deinit(self) -> None:
        """Tear down the backend.  Async requests still in flight are
        named (flight-recorder seq/state included) through the
        structured logger first — silently dropping them hid both lost
        completions and the leaked-request bug class accl_lint flags."""
        pending = [r for ref in self._async_pending
                   if (r := ref()) is not None and not r.done]
        if pending:
            rank = (self._communicators[GLOBAL_COMM].local_rank
                    if self._communicators else None)
            log = get_logger("accl_tpu.driver", rank=rank)
            log.warning(
                "deinit with %d async request(s) still pending — their "
                "completions (and any engine errors) are dropped:",
                len(pending))
            for r in pending:
                info = r.flight_info() or (
                    f" (id {r.id}, status={r.status.name})")
                log.warning("  pending: %s%s", r.description or "call",
                            info)
        self._async_pending.clear()
        self._device.close()

    def __enter__(self) -> "ACCL":
        return self

    def __exit__(self, *exc) -> None:
        self.deinit()
