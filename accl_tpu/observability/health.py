"""Health surface: hang/desync watchdog + OpenMetrics exporter.

Two always-available production facilities on top of the flight
recorder (flight.py) and the metrics registry (metrics.py):

- :class:`Watchdog` — a daemon thread per engine/world that scans the
  per-rank flight recorders for gang collectives stuck in assembly or
  execution past ``ACCL_WATCHDOG_TIMEOUT`` seconds.  On fire it
  reports *which ranks arrived and which are missing*, each rank's
  last-completed seq and the head-of-queue call every absent rank is
  actually blocked on, writes the merged flight dump to
  ``ACCL_WATCHDOG_DUMP``, flips the ``accl_health`` gauge to ``hung``,
  and bumps the ``watchdog/fires`` counter.  The TPU engine
  additionally feeds its live gang-assembly table through the
  ``introspect`` hook (TpuEngine.gang_assembly_snapshot), so the
  report shows the exact partial gangs inside the scheduler.

- :func:`start_exporter` — an OpenMetrics endpoint on
  ``ACCL_METRICS_PORT`` (stdlib ``http.server`` thread): ``/metrics``
  serves :meth:`MetricsRegistry.to_openmetrics`, ``/healthz`` a JSON
  health summary, ``/flight`` the merged flight dump — the scrape
  surface a production serving fleet points Prometheus at.

Health states (the ``accl_health`` gauge):
``0`` ok · ``1`` degraded (a collective returned a non-zero retcode in
the last minute) · ``2`` hung (watchdog found a stuck gang) · ``3``
aborted (a communicator abort finalized calls in the last minute — a
recovery action in progress, NOT a phantom hang: abort-finalized
flight records are terminal and never re-trigger the stuck-gang scan).

``ACCL_WATCHDOG_ACTION`` selects what a watchdog fire DOES: ``dump``
(default — diagnose only, the pre-r10 behavior) or ``abort`` — the
watchdog additionally aborts the hung communicator through the
backend's abort hook, turning a detected hang into fast COMM_ABORTED/
RANK_FAILED failures every waiter can recover from (shrink + re-run).
"""
from __future__ import annotations

import json
import os
import threading
import weakref
from typing import Callable, Iterable, Optional

from . import flight as _flight
from .metrics import MetricsRegistry, default_registry
from .trace import now_ns

HEALTH_OK = 0
HEALTH_DEGRADED = 1
HEALTH_HUNG = 2
HEALTH_ABORTED = 3
#: a recovery supervisor is mid-episode (detect -> abort -> probe ->
#: shrink/grow -> agree -> resume); distinct from ``aborted`` because a
#: supervised world is actively healing, not merely revoked
HEALTH_RECOVERING = 4
#: the regression sentinel (observability/sentinel.py) found live
#: latency/bandwidth drifted past its thresholds vs the committed
#: baseline — the world is CORRECT but slow.  Reported only while every
#: stronger verdict (degraded/hung/aborted/recovering) is clear: a
#: numerically-higher code must not let "slow" mask a real failure, so
#: the aggregation special-cases it rather than relying on max().
HEALTH_SLOW = 5
HEALTH_NAMES = ("ok", "degraded", "hung", "aborted", "recovering",
                "slow")

#: window after a non-zero retcode during which health reads degraded
DEGRADED_WINDOW_NS = 60 * 10 ** 9


def watchdog_timeout_s() -> float:
    """Stuck-gang threshold in seconds; ``ACCL_WATCHDOG_TIMEOUT=0``
    disables the watchdog entirely.  Malformed values raise the naming
    ACCLError (constants.env_float) — a watchdog silently falling back
    to 300 s because of a typo is a watchdog that fires 5 minutes after
    the operator expected it."""
    from ..constants import env_float

    return env_float("ACCL_WATCHDOG_TIMEOUT", 300.0, minimum=0.0)


#: live watchdogs, for health aggregation: the accl_health gauge on a
#: registry is the MAX verdict over every live watchdog publishing into
#: it — one hung world must not be overwritten by a healthy sibling's
#: sweep, and a freshly-constructed watchdog must not clear a live hang
_watchdogs_lock = threading.Lock()
_watchdogs: "weakref.WeakSet" = weakref.WeakSet()
#: registries with at least one recovery supervisor mid-episode
#: (resilience/supervisor.py note_recovering): id(registry) -> count.
#: A supervised recovery outranks every watchdog verdict — the world
#: is actively healing, and a scrape must say so even while a sibling
#: watchdog still reads the pre-recovery hang.
_recovering: dict = {}
#: registries whose regression sentinel currently holds drift findings
#: (sentinel.py note_slow): id(registry) -> True.  Weakest verdict —
#: surfaces only while everything stronger is clear.
_slow: dict = {}


def note_recovering(registry: MetricsRegistry, active: bool) -> None:
    """Mark (or clear) an active recovery episode on a registry; the
    ``accl_health`` gauge reads ``recovering`` (4) while any episode is
    live, then falls back to the watchdog aggregation."""
    key = id(registry)
    with _watchdogs_lock:
        n = _recovering.get(key, 0) + (1 if active else -1)
        if n > 0:
            _recovering[key] = n
        else:
            _recovering.pop(key, None)
    _publish_health(registry)


def note_slow(registry: MetricsRegistry, active: bool) -> None:
    """Mark (or clear) a live perf-drift verdict on a registry (the
    regression sentinel's hook): ``accl_health`` reads ``slow`` (5)
    while active AND no stronger verdict (degraded/hung/aborted/
    recovering) is in effect — slow must never mask a real failure."""
    key = id(registry)
    with _watchdogs_lock:
        if active:
            _slow[key] = True
        else:
            _slow.pop(key, None)
    _publish_health(registry)


def _publish_health(registry: MetricsRegistry) -> None:
    with _watchdogs_lock:
        verdict = max((w._health for w in _watchdogs
                       if w._registry is registry), default=HEALTH_OK)
        if _recovering.get(id(registry), 0) > 0:
            verdict = HEALTH_RECOVERING
        elif verdict == HEALTH_OK and _slow.get(id(registry)):
            verdict = HEALTH_SLOW
    registry.set_gauge("accl_health", verdict)


class Watchdog:
    """Stuck-gang detector over a set of per-rank flight recorders."""

    def __init__(self, recorders: Iterable, timeout_s: Optional[float] = None,
                 introspect: Optional[Callable[[], list]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 on_fire: Optional[Callable[[dict], None]] = None,
                 dump_path: Optional[str] = None, name: str = "accl",
                 abort_hook: Optional[Callable[[int, dict], None]] = None,
                 action: Optional[str] = None):
        self._recorders = list(recorders)
        self.timeout_s = (watchdog_timeout_s() if timeout_s is None
                          else timeout_s)
        self._introspect = introspect
        #: fire action: "dump" (diagnose only) or "abort" (additionally
        #: abort each hung comm via abort_hook(comm_id, report))
        self.action = (action if action is not None else
                       os.environ.get("ACCL_WATCHDOG_ACTION", "dump"))
        self._abort_hook = abort_hook
        self._registry = registry if registry is not None \
            else default_registry()
        self._on_fire = on_fire
        self._dump_path = dump_path if dump_path is not None else \
            os.environ.get("ACCL_WATCHDOG_DUMP", "accl_watchdog_dump.json")
        self._name = name
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None
        #: most recent fire report (tests and doctor read this)
        self.last_report: Optional[dict] = None
        #: this watchdog's own verdict; the registry gauge aggregates
        #: (max) over every live watchdog on the same registry
        self._health = HEALTH_OK
        with _watchdogs_lock:
            _watchdogs.add(self)
        _publish_health(self._registry)

    # -- lifecycle ------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0 and bool(self._recorders) \
            and _flight.enabled()

    def start(self) -> "Watchdog":
        if self.enabled and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self._name}-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def add_recorder(self, recorder) -> None:
        """Fold a late-joining rank's flight recorder into the scan
        (elastic membership: a replacement spawned mid-run must be
        watched too).  Append is safe against a concurrent sweep —
        CPython list iteration simply starts seeing the new tail."""
        self._recorders.append(recorder)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        with _watchdogs_lock:
            _watchdogs.discard(self)
        _publish_health(self._registry)

    # -- scan loop ------------------------------------------------------
    def _loop(self) -> None:
        interval = min(max(self.timeout_s / 4.0, 0.05), 2.0)
        while not self._stop.wait(interval):
            try:
                self.check()
            except Exception as e:  # pragma: no cover — diagnostics
                # must never take the workload down, but a silently
                # dying scan would be a watchdog that cannot bark
                try:
                    from ..utils.logging import get_logger

                    get_logger("accl_tpu.watchdog").warning(
                        "watchdog scan failed: %s: %s",
                        type(e).__name__, e)
                except Exception:
                    pass

    def check(self) -> Optional[dict]:
        """One scan; returns the fire report when a hang was detected."""
        self._registry.inc("watchdog/checks")
        now = now_ns()
        budget_ns = self.timeout_s * 1e9
        stuck = [rec for r in self._recorders for rec in r.in_flight()
                 if rec.gang and (now - rec.t_submit) > budget_ns]
        if stuck:
            self._health = HEALTH_HUNG
            _publish_health(self._registry)
            if not self._fired:
                self._fired = True
                return self._fire(stuck)
            return None
        self._fired = False
        aborted = any(r.last_abort_ns
                      and now - r.last_abort_ns < DEGRADED_WINDOW_NS
                      for r in self._recorders)
        degraded = any(r.last_error_ns
                       and now - r.last_error_ns < DEGRADED_WINDOW_NS
                       for r in self._recorders)
        self._health = (HEALTH_ABORTED if aborted
                        else HEALTH_DEGRADED if degraded else HEALTH_OK)
        _publish_health(self._registry)
        return None

    def _fire(self, stuck: list) -> dict:
        self._registry.inc("watchdog/fires")
        report = _flight.merge_flight_dumps(
            [r.dump() for r in self._recorders])
        report["watchdog"] = {
            "timeout_s": self.timeout_s,
            "stuck_records": [rec.to_dict() for rec in stuck],
        }
        if self._introspect is not None:
            try:
                report["watchdog"]["engine_gangs"] = self._introspect()
            except Exception:
                report["watchdog"]["engine_gangs"] = None
        if self._dump_path:
            try:
                with open(self._dump_path, "w") as f:
                    json.dump(report, f, indent=1)
            except OSError:
                pass
        # publish AFTER the dump write: last_report is the "fire
        # happened" signal pollers key on, and a poller that saw it must
        # find the dump file already on disk (the pre-r14 order lost
        # that race on a loaded box)
        self.last_report = report
        self._log(report)
        # ACCL_WATCHDOG_ACTION=abort: turn the diagnosis into recovery —
        # abort every hung communicator so stuck waiters fail fast with
        # COMM_ABORTED|RANK_FAILED instead of hanging forever.  Runs
        # AFTER the dump: the black box records the pre-abort truth.
        if self.action == "abort" and self._abort_hook is not None:
            aborted_comms = set()
            for hang in report["analysis"]["hangs"]:
                comm = hang["comm"]
                if comm in aborted_comms:
                    continue
                aborted_comms.add(comm)
                try:
                    self._abort_hook(comm, report)
                except Exception:  # the recovery path must not kill
                    pass           # the watchdog thread
        if self._on_fire is not None:
            try:
                self._on_fire(report)
            except Exception:
                pass
        return report

    def _log(self, report: dict) -> None:
        from ..utils.logging import get_logger

        log = get_logger("accl_tpu.watchdog")
        for hang in report["analysis"]["hangs"]:
            log.error(
                "watchdog: %s (comm %d, count %d, %s) stuck %.1fs — "
                "arrived ranks %s, MISSING ranks %s; missing blocked on "
                "%s; last completed seq per rank %s; dump: %s",
                hang["collective"], hang["comm"], hang["count"],
                hang["dtype"], hang["oldest_age_us"] / 1e6,
                hang["arrived"], hang["missing"],
                {r: (rec["collective"] if rec else "idle")
                 for r, rec in hang["missing_blocked_on"].items()},
                hang["last_completed_seq"], self._dump_path or "<none>")
        for d in report["analysis"]["desyncs"]:
            log.error("watchdog: collective-order DESYNC on comm %d at "
                      "gang index %d: %s", d["comm"], d["index"],
                      d["per_rank"])


# ---------------------------------------------------------------------------
# OpenMetrics / health HTTP endpoint (stdlib http.server thread)
# ---------------------------------------------------------------------------
_exporter_lock = threading.Lock()
_exporter: Optional["MetricsExporter"] = None

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")


class MetricsExporter:
    """Serves /metrics (OpenMetrics), /healthz (JSON), /flight
    (merged flight dump), /retunes (online-tuner history) and /slo
    (per-tenant SLO report) from a daemon thread."""

    def __init__(self, port: int, registry: Optional[MetricsRegistry] = None,
                 host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry if registry is not None else default_registry()
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler API
                try:
                    if self.path.startswith("/metrics"):
                        body = reg.to_openmetrics().encode()
                        ctype = OPENMETRICS_CONTENT_TYPE
                    elif self.path.startswith("/healthz"):
                        body = json.dumps(exporter.health()).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/flight"):
                        body = json.dumps(_flight.dump_all()).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/retunes"):
                        # r19: the online tuner's bounded retune-
                        # history ring (empty doc when no tuner ran)
                        from ..tuning import online as _online

                        body = json.dumps(
                            _online.history_doc()).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/slo"):
                        # r20: the live per-tenant SLO report.  A
                        # scrape IS an evaluation sweep (check() then
                        # doc()) so a pull-only deployment — no
                        # ACCL_SLO_INTERVAL_MS thread — still gets
                        # fresh verdicts at its scrape cadence.  Empty
                        # versioned doc when no tracker is armed.
                        from . import slo as _slo

                        tr = _slo.tracker()
                        if tr is not None:
                            tr.check()
                            doc = tr.doc()
                        else:
                            doc = {"format": _slo.SLO_REPORT_FORMAT,
                                   "version": _slo.SLO_REPORT_VERSION,
                                   "checks": 0, "specs": [],
                                   "tenants": {}, "findings_total": 0}
                        body = json.dumps(doc).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # surface, don't kill the thread
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._registry = reg
        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="accl-metrics-http",
            daemon=True)
        self._thread.start()

    def health(self) -> dict:
        snap = self._registry.snapshot()
        code = int(snap["gauges"].get("accl_health", HEALTH_OK))
        code = min(max(code, 0), len(HEALTH_NAMES) - 1)
        return {
            "health": HEALTH_NAMES[code],
            "accl_health": code,
            "watchdog_fires": snap["counters"].get("watchdog/fires", 0),
            "watchdog_checks": snap["counters"].get("watchdog/checks", 0),
        }

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


def start_exporter(port: Optional[int] = None,
                   registry: Optional[MetricsRegistry] = None,
                   ) -> Optional[MetricsExporter]:
    """Start (or return) the process-wide exporter.  With no explicit
    `port`, reads ``ACCL_METRICS_PORT``: unset/empty = no exporter;
    ``0`` = bind an EPHEMERAL port (parallel CI jobs sharing one env
    cannot collide — the r14 satellite; the chosen port is logged by
    the structured logger and readable via :func:`exporter_port`);
    anything else = that fixed port."""
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            return _exporter
        if port is None:
            raw = os.environ.get("ACCL_METRICS_PORT", "")
            if not raw:
                return None
            from ..constants import env_int

            port = env_int("ACCL_METRICS_PORT", 0, minimum=0)
        _exporter = MetricsExporter(port, registry)
        from ..utils.logging import get_logger

        get_logger().info("OpenMetrics endpoint on http://%s:%d/metrics",
                          _exporter.host, _exporter.port)
        return _exporter


def exporter_port() -> Optional[int]:
    """The live exporter's bound port (the ephemeral-port discovery
    surface for ``ACCL_METRICS_PORT=0``), or None when no exporter is
    running in this process."""
    with _exporter_lock:
        return _exporter.port if _exporter is not None else None


def stop_exporter() -> None:
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            _exporter.close()
            _exporter = None


def ensure_exporter_from_env() -> Optional[MetricsExporter]:
    """Idempotent env-driven start; called from ACCL.initialize and the
    engine bring-up paths so any entrypoint honors ACCL_METRICS_PORT.
    Never raises: a port collision (two local ranks sharing one
    ACCL_METRICS_PORT — only the first can bind) must not take driver
    bring-up down with it."""
    try:
        return start_exporter()
    except OSError as e:
        from ..utils.logging import get_logger

        get_logger().warning(
            "OpenMetrics endpoint disabled (ACCL_METRICS_PORT=%s): %s",
            os.environ.get("ACCL_METRICS_PORT", ""), e)
        return None
