"""Always-on per-rank flight recorder: the collective black box.

Unlike the opt-in tracer (``ACCL_TRACE``), the flight recorder is ON by
default: every rank keeps a fixed-size, lock-cheap ring of the last N
collective records — seq, collective, comm, dtype/shape, dispatch lane,
state transitions (submitted → queued → gang-ready → dispatched →
complete) and monotonic timestamps — so when a gang wedges in
production there is always a recent history to dump, the way the
reference CCLO's host-visible retcode/cycle-counter state machine keeps
a wedged offload engine diagnosable (PAPER §driver/firmware; ACCL+,
arxiv 2312.11742).

Overhead discipline: one small ``__slots__`` object and a bounded
``deque.append`` per call, plus a handful of attribute writes at each
state transition — no locks on the record path (the per-rank seq comes
from an atomic ``itertools.count``; ``deque`` appends are GIL-atomic).
``ACCL_FLIGHT=0`` turns it off entirely; ``ACCL_FLIGHT_CAP`` resizes
the ring (default 512 records per rank).

Dump paths: :meth:`ACCL.dump_flight_recorder`, ``SIGUSR1`` (dumps every
live rank to ``ACCL_FLIGHT_DUMP``), and automatically when the
:class:`~accl_tpu.observability.health.Watchdog` fires.  Cross-rank
dumps merge and diagnose through :func:`merge_flight_dumps` (the
``scripts/accl_doctor.py`` engine): order/shape/dtype desyncs,
missing gang members, stragglers.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import weakref
from typing import Iterable, Optional

from .trace import now_ns

# record states, in lifecycle order (ints: one attribute write per
# transition on the always-on path; names only materialize at dump time)
S_SUBMITTED = 0
S_QUEUED = 1
S_GANG_READY = 2
S_DISPATCHED = 3
S_COMPLETE = 4
S_FAILED = 5
S_ABORTED = 6  # finalized by a communicator abort (COMM_ABORTED), not
#              # an engine fault — a terminal state, never "in flight"
S_RECOVERING = 7  # a recovery-supervisor phase record (non-gang): the
#              # rank is mid detect->abort->probe->shrink/grow->resume;
#              # finish() retires it complete/failed like any record
STATE_NAMES = ("submitted", "queued", "gang_ready", "dispatched",
               "complete", "failed", "aborted", "recovering")

#: states that mean "this record is retired" — the hang analyzer and
#: the watchdog must treat all three alike (an abort in flight is a
#: recovery action, not a phantom hang)
TERMINAL_STATE_NAMES = ("complete", "failed", "aborted")

#: retcode bit marking an abort-finalized call (constants.ErrorCode.
#: COMM_ABORTED; kept as a literal here so the always-on record path
#: adds no import edge)
_COMM_ABORTED_BIT = 1 << 27

#: retcode bit for an engine receive-timeout (constants.ErrorCode.
#: RECEIVE_TIMEOUT_ERROR) — the trigger for the r20 forensic capture
#: (ROADMAP item 5's standing sub-comm allgather wedge ships a bare
#: timeout today; the forensics attach the per-peer link rows and
#: gang-assembly state the post-mortem needs)
_RECEIVE_TIMEOUT_BIT = 1 << 11

#: record fields every dump carries — the schema the CI hang smoke and
#: accl_doctor validate against
RECORD_SCHEMA_KEYS = (
    "seq", "req_id", "rank", "collective", "comm", "tag", "dtype",
    "count", "nbytes", "nranks", "lane", "state", "gang", "retcode",
    "age_us", "t_submit", "t_queue", "t_gang_ready", "t_dispatch",
    "t_complete", "tenant",
)


class FlightRecord:
    """One collective call's black-box record (mutated in place as the
    call moves through the stack; the ring holds the live object, so a
    dump mid-flight shows the exact stage a wedged call reached)."""

    __slots__ = ("seq", "req_id", "rank", "collective", "comm", "tag",
                 "dtype", "count", "nbytes", "nranks", "lane", "state",
                 "gang", "retcode", "tenant", "t_submit", "t_queue",
                 "t_gang_ready", "t_dispatch", "t_complete", "_recorder")

    def __init__(self, recorder: "FlightRecorder", seq: int, req_id: int,
                 collective: str, comm: int, tag: int, dtype: str,
                 count: int, nbytes: int, nranks: int, gang: bool,
                 t_submit: int, tenant: Optional[str] = None):
        self._recorder = recorder
        self.seq = seq
        self.req_id = req_id
        self.rank = recorder.rank
        self.collective = collective
        self.comm = comm
        self.tag = tag
        self.dtype = dtype
        self.count = count
        self.nbytes = nbytes
        self.nranks = nranks
        self.gang = gang
        self.tenant = tenant
        self.lane: Optional[str] = None
        self.state = S_SUBMITTED
        self.retcode = 0
        self.t_submit = t_submit
        self.t_queue = 0
        self.t_gang_ready = 0
        self.t_dispatch = 0
        self.t_complete = 0

    @property
    def in_flight(self) -> bool:
        # a recovery-phase record is a live episode until finish()
        # retires it (it is never gang=True, so the watchdog's
        # stuck-GANG scan and the merge hang analysis both skip it)
        return self.state < S_COMPLETE or self.state == S_RECOVERING

    def age_ns(self, now: Optional[int] = None) -> int:
        """Nanoseconds since submit (in flight) or submit→complete."""
        end = self.t_complete or (now if now is not None else now_ns())
        return max(end - self.t_submit, 0)

    def mark_dispatched(self, lane: str, t: int) -> None:
        """The one dispatch-stamp used by every lane (emu descriptor
        post, local/p2p, gang executor/leader/batched); a lane already
        tagged by an earlier stage (leader pre-tag) is preserved."""
        self.state = S_DISPATCHED
        self.t_dispatch = t
        if self.lane is None:
            self.lane = lane

    def mark_recovering(self, t: int) -> None:
        """Flip a supervisor phase record into the live `recovering`
        state (resilience/supervisor.py publishes one record per
        state-machine transition; finish() retires it)."""
        self.state = S_RECOVERING
        self.t_dispatch = t
        if self.lane is None:
            self.lane = "supervisor"

    def finish(self, retcode: int, t: int) -> None:
        self.retcode = retcode
        self.t_complete = t
        if retcode == 0:
            self.state = S_COMPLETE
        elif retcode & _COMM_ABORTED_BIT:
            self.state = S_ABORTED
        else:
            self.state = S_FAILED
            if retcode & _RECEIVE_TIMEOUT_BIT:
                # RECEIVE_TIMEOUT forensics (r20): capture the link
                # rows + gang-assembly state at classification time —
                # best-effort, never raising on the record path
                self._recorder._note_timeout(self)
        self._recorder._note_finished(self)

    def summary(self, now: Optional[int] = None) -> str:
        """One-line human rendering, used by error embedding and logs."""
        return (f"seq={self.seq} {self.collective} comm={self.comm} "
                f"state={STATE_NAMES[self.state]} lane={self.lane} "
                f"dtype={self.dtype} count={self.count} "
                f"age={self.age_ns(now) / 1e6:.1f}ms")

    def to_dict(self, now: Optional[int] = None) -> dict:
        return {
            "seq": self.seq, "req_id": self.req_id, "rank": self.rank,
            "collective": self.collective, "comm": self.comm,
            "tag": self.tag, "dtype": self.dtype, "count": self.count,
            "nbytes": self.nbytes, "nranks": self.nranks,
            "lane": self.lane, "state": STATE_NAMES[self.state],
            "gang": self.gang, "retcode": self.retcode,
            "tenant": self.tenant,
            "age_us": round(self.age_ns(now) / 1e3, 1),
            "t_submit": self.t_submit, "t_queue": self.t_queue,
            "t_gang_ready": self.t_gang_ready,
            "t_dispatch": self.t_dispatch, "t_complete": self.t_complete,
        }

    def __repr__(self) -> str:  # pragma: no cover — debug aid
        return f"FlightRecord(r{self.rank} {self.summary()})"


class FlightRecorder:
    """Fixed-size ring of one rank's last N FlightRecords."""

    def __init__(self, rank: int, capacity: Optional[int] = None):
        from collections import deque

        from ..constants import env_int

        self.rank = rank
        # env_int raises the naming ACCLError on a malformed knob (the
        # clear-error contract) — construction time, not the record path
        self.capacity = capacity if capacity is not None else env_int(
            "ACCL_FLIGHT_CAP", 512, minimum=1)
        self._records: "deque[FlightRecord]" = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        #: highest seq that reached complete/failed (monotonic
        #: best-effort: lock-free, diagnostic — not a synchronization
        #: primitive)
        self.last_completed_seq = -1
        #: monotonic ns of the most recent non-zero retcode (the
        #: watchdog's "degraded" signal)
        self.last_error_ns = 0
        #: monotonic ns of the most recent COMM_ABORTED finalization
        #: (the watchdog's "aborted" health signal)
        self.last_abort_ns = 0
        #: zero-arg providers polled when a record classifies as
        #: RECEIVE_TIMEOUT (set_forensics_sources) — e.g. the device's
        #: link_stats / the engine's gang_assembly_snapshot
        self._forensics_sources: dict = {}
        #: captured forensic snapshots, newest last (bounded: a timeout
        #: storm must not grow the dump without bound)
        self._forensics: "deque" = deque(maxlen=8)

    # -- record path (always-on; keep it allocation + append only) -----
    def new_record(self, req_id: int, collective: str, comm: int,
                   tag: int, dtype: str, count: int, nbytes: int,
                   nranks: int, gang: bool, t_submit: int,
                   tenant: Optional[str] = None) -> FlightRecord:
        rec = FlightRecord(self, next(self._seq), req_id, collective,
                           comm, tag, dtype, count, nbytes, nranks, gang,
                           t_submit, tenant)
        self._records.append(rec)
        return rec

    def _note_finished(self, rec: FlightRecord) -> None:
        if rec.seq > self.last_completed_seq:
            self.last_completed_seq = rec.seq
        if rec.retcode != 0:
            self.last_error_ns = rec.t_complete
        if rec.state == S_ABORTED:
            self.last_abort_ns = rec.t_complete

    # -- RECEIVE_TIMEOUT forensics (r20, ROADMAP item 5 wedge) ---------
    def set_forensics_sources(self, sources: dict) -> None:
        """Arm zero-arg provider callables (e.g. ``{"link_rows":
        device.link_stats, "gang_assembly": engine.gang_assembly_
        snapshot}``) polled the instant a record classifies as
        RECEIVE_TIMEOUT.  The snapshot carries a WALL-CLOCK stamp
        alongside the monotonic one — the ingredient the detsched
        virtual clock hides, so a wedge under a virtualized schedule
        still correlates with host logs."""
        self._forensics_sources = dict(sources)

    def _note_timeout(self, rec: FlightRecord) -> None:
        if not self._forensics_sources:
            return
        import time as _time

        snap = {
            "seq": rec.seq,
            "req_id": rec.req_id,
            "collective": rec.collective,
            "comm": rec.comm,
            "tag": rec.tag,
            "tenant": rec.tenant,
            "retcode": rec.retcode,
            "t_complete": rec.t_complete,
            "wall_clock": _time.time(),
            "wall_clock_iso": _time.strftime(
                "%Y-%m-%dT%H:%M:%S%z", _time.localtime()),
        }
        for name, fn in self._forensics_sources.items():
            try:
                snap[name] = fn()
            except Exception as e:  # noqa: BLE001 — diagnostics only
                snap[name] = f"<capture failed: {e!r}>"
        self._forensics.append(snap)

    # -- queries --------------------------------------------------------
    def records(self) -> list:
        """Point-in-time snapshot of the ring.  list(deque) copies in
        one C call under the GIL; the retry covers the (not observed,
        but not contractual) case of a mutation surfacing mid-copy —
        a reader must never raise because a rank kept submitting."""
        for _ in range(8):
            try:
                return list(self._records)
            except RuntimeError:  # pragma: no cover — copy/append race
                continue
        return []

    def in_flight(self) -> list:
        # iterate the SNAPSHOT, not the live deque: a Python-level
        # comprehension over the deque can hit "deque mutated during
        # iteration" when another thread appends between items
        return [r for r in self.records() if r.in_flight]

    def __len__(self) -> int:
        return len(self._records)

    def dump(self) -> dict:
        now = now_ns()
        doc = {
            "rank": self.rank,
            "capacity": self.capacity,
            "last_completed_seq": self.last_completed_seq,
            "records": [r.to_dict(now) for r in self.records()],
        }
        if self._forensics:
            # RECEIVE_TIMEOUT forensic snapshots (r20): link rows +
            # gang-assembly state captured at classification time, with
            # wall-clock stamps
            doc["timeout_forensics"] = list(self._forensics)
        return doc


#: lifecycle event names (r13) published as zero-duration records so
#: post-mortem dumps carry the happens-before anchors the
#: analysis.checks lifecycle checkers reason over: fences (abort/
#: shrink/grow/reset) order-stamp when a communicator's old world died;
#: plan_capture marks a re-arm; engine_teardown marks the instant after
#: which NO successful completion may ever publish on that rank.
FENCE_EVENTS = frozenset(("abort", "shrink", "grow", "reset_errors"))
PLAN_CAPTURE_EVENT = "plan_capture"
TEARDOWN_EVENT = "engine_teardown"
#: r19 online tuner: one anchor per hot-swapped selection install (and
#: per revert), so merge_flight_dumps can order retunes against the
#: traffic they reshaped.  Same zero-duration mark_event discipline as
#: the fences — an install IS a fence for captured plans.
RETUNE_EVENT = "retune_install"
RETUNE_REVERT_EVENT = "retune_revert"


def mark_event(recorder: Optional["FlightRecorder"], name: str,
               comm: int = -1, retcode: int = 0,
               lane: str = "fence") -> None:
    """Publish one zero-duration lifecycle event record (cold paths
    only — abort/shrink/grow/reset/plan-arm/teardown).  ``comm=-1``
    means every communicator (reset_errors, teardown)."""
    if recorder is None or not _enabled:
        return
    t = now_ns()
    rec = recorder.new_record(-1, name, comm, 0, "-", 0, 0, 0, False, t)
    rec.lane = lane
    rec.finish(retcode, t)


# ---------------------------------------------------------------------------
# module state: enable switch + live-recorder registry + SIGUSR1
# ---------------------------------------------------------------------------
_enabled = os.environ.get("ACCL_FLIGHT", "1") != "0"
_registry_lock = threading.Lock()
_recorders: list = []  # weakref.ref[FlightRecorder]
_signal_installed = False


def enabled() -> bool:
    """Module-bool gate, same discipline as trace.enabled()."""
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = on


def register(recorder: FlightRecorder) -> FlightRecorder:
    """Track a live recorder for process-wide dumps (SIGUSR1, doctor);
    weak refs, so closed worlds' recorders age out with GC."""
    with _registry_lock:
        _recorders[:] = [r for r in _recorders if r() is not None]
        _recorders.append(weakref.ref(recorder))
    _install_signal_handler()
    return recorder


def recorders() -> list:
    """Live recorders, registration order."""
    with _registry_lock:
        out = [r() for r in _recorders]
    return [r for r in out if r is not None]


def dump_all() -> dict:
    """Every live rank's ring, in one merged+analyzed document."""
    return merge_flight_dumps([r.dump() for r in recorders()])


def dump_all_to(path: str) -> str:
    with open(path, "w") as f:
        json.dump(dump_all(), f, indent=1)
    return path


def _sigusr1(_signum, _frame) -> None:  # pragma: no cover — signal path
    path = os.environ.get("ACCL_FLIGHT_DUMP", "accl_flight_dump.json")
    try:
        dump_all_to(path)
        from ..utils.logging import get_logger

        get_logger().warning("SIGUSR1: flight recorder dumped to %s", path)
    except Exception:
        pass  # never let the diagnostic path kill the process


def _install_signal_handler() -> None:
    """Arm SIGUSR1 -> dump-all (once; only possible from the main
    thread — worker-thread registration silently skips, matching
    signal module semantics)."""
    global _signal_installed
    if _signal_installed:
        return
    try:
        import signal

        # never steal SIGUSR1 from the application: training launchers
        # commonly bind it (checkpoint-on-signal, log rotation) — the
        # dump hook only claims a DEFAULT disposition
        if signal.getsignal(signal.SIGUSR1) not in (signal.SIG_DFL,
                                                    None):
            _signal_installed = True  # decided: leave theirs in place
            return
        signal.signal(signal.SIGUSR1, _sigusr1)
        _signal_installed = True
    except (ValueError, AttributeError, OSError):
        pass


# ---------------------------------------------------------------------------
# cross-rank merge + desync analysis (the accl_doctor engine)
# ---------------------------------------------------------------------------
def first_divergence(seqs: dict, sig_fn) -> Optional[dict]:
    """First position where per-rank ordered sequences disagree.

    ``seqs`` maps rank -> ordered list; ``sig_fn(item)`` projects each
    item to a comparable signature.  A position diverges when two ranks
    hold DIFFERENT non-None signatures there (a rank that simply ran
    out contributes None — uneven depth alone is a hang/straggler
    question, not an order question).  Returns ``{"index", "per_rank"}``
    (rank -> signature or None) or None.  Shared by the post-mortem
    analyzer (:func:`merge_flight_dumps`) and the pre-dispatch static
    checkers (accl_tpu/analysis/checks.py) so both report the same
    first-divergent-seq semantics.
    """
    members = sorted(seqs)
    depth = max((len(v) for v in seqs.values()), default=0)
    for i in range(depth):
        sigs = {r: (sig_fn(seqs[r][i]) if i < len(seqs[r]) else None)
                for r in members}
        distinct = {s for s in sigs.values() if s is not None}
        if len(distinct) > 1:
            return {"index": i, "per_rank": sigs}
    return None


def _load(dump) -> dict:
    """Load one dump dict or path; a path whose JSON is truncated
    mid-record (a crash-time dump) is salvaged via
    :func:`trace.salvage_torn_json` instead of raising — the recovered
    dict carries ``_torn`` = {"tail_bytes_skipped"} so the merge can
    report the skip (r14 satellite)."""
    if isinstance(dump, str):
        with open(dump) as f:
            text = f.read()
        try:
            return json.loads(text)
        except json.JSONDecodeError:
            from .trace import salvage_torn_json

            # merged docs FIRST: a merged doc contains nested per-rank
            # "records" arrays, so probing "records" first would match
            # rank 0's array and silently drop every other rank; a
            # per-rank dump has no "ranks" key and falls through
            try:
                doc, skipped = salvage_torn_json(text, "ranks")
            except ValueError:
                doc, skipped = salvage_torn_json(text, "records")
            doc["_torn"] = {"path": dump,
                            "tail_bytes_skipped": skipped}
            doc.setdefault("rank", -1)
            doc.setdefault("last_completed_seq", -1)
            from ..utils.logging import get_logger

            get_logger("accl_tpu.flight").warning(
                "flight dump %s is truncated mid-record — salvaged %d "
                "record(s), skipped %d torn tail byte(s)",
                dump, len(doc.get("records", [])), skipped)
            return doc
    return dump


def merge_flight_dumps(dumps: Iterable, out_path: Optional[str] = None,
                       ) -> dict:
    """Merge per-rank flight dumps and diagnose cross-rank failure
    modes.  Accepts dump dicts (from :meth:`FlightRecorder.dump`) or
    paths to their JSON files; a dict that already carries a ``ranks``
    list (a previous merge / watchdog report) contributes every rank.

    The analysis pinpoints:

    - ``desyncs`` — the first seq position where two ranks issued
      different gang collectives on one communicator (order/shape/dtype
      mismatch: the classic collective-order bug hierarchical schedules
      amplify, HiCCL arxiv 2408.05962);
    - ``hangs`` — in-flight gang instances past their expected
      membership: which ranks arrived, which are missing, and the
      head-of-queue call each missing rank is actually blocked on;
    - ``stragglers`` — ranks whose completed-gang progress trails the
      furthest rank on the same communicator.
    """
    per_rank: dict = {}
    torn: list = []
    torn_ranks: set = set()
    for d in dumps:
        d = _load(d)
        rds = d["ranks"] if "ranks" in d else [d]
        if "_torn" in d:
            torn.append(dict(d["_torn"],
                             records_recovered=sum(
                                 len(rd.get("records", ())) for rd in rds)))
        for rd in rds:
            rd.setdefault("records", [])
            rd.setdefault("last_completed_seq", -1)
            per_rank[rd["rank"]] = rd
            if "_torn" in d:
                torn_ranks.add(rd["rank"])
    ranks = sorted(per_rank)
    # a full ring has evicted its oldest records, and different ranks
    # evict DIFFERENT amounts (gang/non-gang mixes differ): positional
    # cross-rank comparison is then meaningless and would produce false
    # desync/straggler findings — those analyses are gated per comm on
    # every contributor still holding its full history.  A TORN dump
    # (crash-truncated, r14 satellite) lost its tail the same way, so
    # its ranks gate identically.
    wrapped = {r: r in torn_ranks
               or len(per_rank[r]["records"])
               >= per_rank[r].get("capacity", 1 << 62) for r in ranks}

    # -- per-comm, per-rank ordered gang signatures --------------------
    def sig(rec: dict) -> tuple:
        return (rec["collective"], rec["tag"], rec["count"], rec["dtype"])

    by_comm: dict = {}
    for r in ranks:
        for rec in sorted(per_rank[r]["records"], key=lambda x: x["seq"]):
            if not rec.get("gang"):
                continue
            by_comm.setdefault(rec["comm"], {}).setdefault(
                r, []).append(rec)

    desyncs: list = []
    truncated_comms: list = []
    for comm, seqs in sorted(by_comm.items()):
        members = sorted(seqs)
        if len(members) < 2:
            continue
        if any(wrapped[r] for r in members):
            truncated_comms.append(comm)
            continue
        div = first_divergence(seqs, sig)
        if div is not None:  # first divergence per comm; later ones cascade
            i, sigs = div["index"], div["per_rank"]
            desyncs.append({
                "comm": comm,
                "index": i,
                "per_rank": {
                    str(r): (None if sigs[r] is None else {
                        "collective": sigs[r][0], "tag": sigs[r][1],
                        "count": sigs[r][2], "dtype": sigs[r][3],
                        "seq": seqs[r][i]["seq"]})
                    for r in members},
            })

    # -- hung gang instances -------------------------------------------
    hangs: list = []
    stuck: dict = {}
    for r in ranks:
        for rec in per_rank[r]["records"]:
            if rec.get("gang") and rec["state"] not in TERMINAL_STATE_NAMES:
                key = (rec["collective"], rec["comm"], rec["tag"],
                       rec["count"], rec["dtype"])
                stuck.setdefault(key, {})[r] = rec
    for key, arrived in sorted(stuck.items()):
        coll, comm, tag, count, dtype = key
        nranks = max(rec["nranks"] for rec in arrived.values())
        # communicator membership is not in the dumps (a withheld rank
        # may have issued NOTHING on the comm): when the merged rank set
        # is the whole world (or this is the global comm), every dumped
        # rank is expected; for sub-comms of a larger merge, only ranks
        # seen on that comm can be attributed
        participants = set(by_comm.get(comm, {})) | set(arrived)
        world = (ranks if comm == 0 or len(ranks) <= nranks
                 else sorted(participants))
        missing = [r for r in world if r not in arrived]
        blocked_on = {}
        for r in missing:
            head = next((rec for rec in sorted(per_rank[r]["records"],
                                               key=lambda x: x["seq"])
                         if rec["state"] not in TERMINAL_STATE_NAMES),
                        None)
            blocked_on[str(r)] = head  # None == rank is idle / absent
        hangs.append({
            "collective": coll, "comm": comm, "tag": tag,
            "count": count, "dtype": dtype, "nranks": nranks,
            "arrived": sorted(arrived),
            "missing": missing,
            "oldest_age_us": max(rec["age_us"]
                                 for rec in arrived.values()),
            "arrived_records": {str(r): rec
                                for r, rec in sorted(arrived.items())},
            "missing_blocked_on": blocked_on,
            "last_completed_seq": {
                str(r): per_rank[r]["last_completed_seq"] for r in ranks},
        })

    # -- stragglers -----------------------------------------------------
    stragglers: list = []
    for comm, seqs in sorted(by_comm.items()):
        if any(wrapped[r] for r in seqs):
            continue  # completed-count comparison is eviction-skewed
        done = {r: sum(1 for rec in v if rec["state"] == "complete")
                for r, v in seqs.items()}
        if len(done) < 2:
            continue
        lead = max(done.values())
        behind = {r: n for r, n in done.items() if n < lead}
        if behind:
            stragglers.append({
                "comm": comm, "completed_lead": lead,
                "behind": {str(r): n for r, n in sorted(behind.items())},
            })

    doc = {
        "generated_ns": now_ns(),
        "nranks": len(ranks),
        "ranks": [per_rank[r] for r in ranks],
        "analysis": {
            "desyncs": desyncs,
            "hangs": hangs,
            "stragglers": stragglers,
            # comms whose order analysis was skipped because a rank's
            # ring wrapped (uneven eviction would fake desyncs); hang
            # detection (in-flight records only) still covers them
            "truncated_comms": truncated_comms,
            # crash-truncated dump files the tolerant loader salvaged
            # (r14 satellite): path, records recovered, tail skipped —
            # their ranks' order analysis is gated like a wrapped ring
            "torn_dumps": torn,
            "ok": not desyncs and not hangs,
        },
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
