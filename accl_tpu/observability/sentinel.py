"""Continuous performance-regression sentinel.

Compares the LIVE per-(collective, dtype, size-bucket) latency
histograms and derived bandwidth in the r8 metrics registry against a
COMMITTED baseline (``bench/results`` records), and when p50/p99 or
bus-bandwidth drift past configurable thresholds it logs a structured
finding, bumps the ``sentinel/findings`` counter, and degrades the
``accl_health`` gauge to the new ``slow`` verdict (5) — correct but
slow is a production state of its own, distinct from degraded/hung.
``scripts/perf_doctor.py`` runs the identical comparison offline from
dump files (``--ci`` for the perf gate, where thresholds are advisory
on shared cores but the schema is hard-validated).

Baselines
---------
Three on-disk shapes load into one internal table keyed
``(collective, dtype, size_bucket, lane)``:

- sentinel-native JSON (``{"version": 1, "entries": [...]}`` — what
  :meth:`Baseline.save` writes and what a captured registry snapshot
  converts to via :meth:`Baseline.from_snapshot`);
- a callrate bench record (``bench/results/callrate_*.json``): each
  bench lane's ``latency_us`` becomes that lane's p50==p99 floor for
  the allreduce signature the bench drives;
- a sweep-gate CSV (``bench/results/sweep_gate_baseline_*.csv``):
  per-(collective, bytes) best-of-repetitions duration/bandwidth rows.

Live registry signatures carry no lane, so they match lane ``"live"``
first and the wildcard lane ``"*"`` second; bench-derived entries load
under their bench lane name AND ``"*"`` so an offline report can gate
live histograms against them.

Knobs (see docs/observability.md): ``ACCL_SENTINEL`` (off / ``1`` =
baseline from ``ACCL_SENTINEL_BASELINE`` / a baseline path),
``ACCL_SENTINEL_INTERVAL_MS`` (default 5000), ``ACCL_SENTINEL_P50`` /
``ACCL_SENTINEL_P99`` (drift ratios, default 2.0 / 3.0),
``ACCL_SENTINEL_BW`` (bandwidth floor ratio, default 0.5),
``ACCL_SENTINEL_MIN_CALLS`` (default 20 — don't judge cold
histograms).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

from . import health as _health
from .metrics import (
    LATENCY_BUCKETS_US,
    MetricsRegistry,
    default_registry,
    size_bucket,
)


def quantile_us(hist: list, q: float) -> float:
    """Quantile estimate from a power-of-4 cumulative-count histogram
    (``_CallStats.hist`` shape: one count per LATENCY_BUCKETS_US bound
    + overflow).  Log-interpolates inside the winning bucket — coarse
    buckets make this an estimate, but a p50 drifting 2x across
    power-of-4 bounds is exactly the signal the sentinel needs."""
    total = sum(hist)
    if total == 0:
        return 0.0
    target = q * total
    cum = 0
    prev_ub = 0.25  # log-floor for the first bucket (1 us upper bound)
    for i, ub in enumerate(LATENCY_BUCKETS_US):
        cum += hist[i]
        if cum >= target:
            lo = prev_ub
            frac = (target - (cum - hist[i])) / max(hist[i], 1)
            # geometric interpolation inside the bucket
            return lo * (ub / lo) ** max(min(frac, 1.0), 0.0)
        prev_ub = ub
    return float(LATENCY_BUCKETS_US[-1]) * 4  # overflow bucket


class Baseline:
    """Committed perf expectations keyed (collective, dtype,
    size_bucket, lane)."""

    VERSION = 1

    def __init__(self, entries: Optional[dict] = None, source: str = ""):
        #: (collective, dtype, size_bucket, lane) ->
        #: {"p50_us", "p99_us", "busbw_GBps"} (0.0 = don't gate that axis)
        self.entries: dict = entries or {}
        self.source = source

    # -- constructors ---------------------------------------------------
    @classmethod
    def from_snapshot(cls, snapshot: dict, lane: str = "live",
                      source: str = "snapshot") -> "Baseline":
        """Capture a registry snapshot as the baseline (what a world
        that just passed its perf gate commits)."""
        entries = {}
        for c in snapshot.get("calls", {}).values():
            hist = [c["hist_us"][f"le_{ub}"] for ub in LATENCY_BUCKETS_US]
            hist.append(c["hist_us"]["inf"])
            key = (c["collective"], c["dtype"], c["size_bucket"], lane)
            entries[key] = {
                "p50_us": round(quantile_us(hist, 0.5), 2),
                "p99_us": round(quantile_us(hist, 0.99), 2),
                "busbw_GBps": c.get("busbw_GBps", 0.0),
            }
        return cls(entries, source)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Load any of the three committed shapes by sniffing."""
        if path.endswith(".csv"):
            return cls._load_sweep_csv(path)
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and "entries" in doc:
            entries = {}
            for e in doc["entries"]:
                key = (e["collective"], e["dtype"], e["size_bucket"],
                       e.get("lane", "*"))
                entries[key] = {"p50_us": e.get("p50_us", 0.0),
                                "p99_us": e.get("p99_us", 0.0),
                                "busbw_GBps": e.get("busbw_GBps", 0.0)}
            return cls(entries, path)
        if isinstance(doc, dict) and "lanes" in doc:
            return cls._from_callrate(doc, path)
        if isinstance(doc, dict) and "calls" in doc:
            base = cls.from_snapshot(doc, source=path)
            # snapshot baselines also gate under the wildcard lane
            for (coll, dt, bucket, _lane), v in list(base.entries.items()):
                base.entries.setdefault((coll, dt, bucket, "*"), v)
            return base
        raise ValueError(
            f"unrecognized baseline format: {path} (want a sentinel "
            f"JSON, a callrate record, a registry snapshot, or a "
            f"sweep-gate CSV)")

    @classmethod
    def _from_callrate(cls, doc: dict, source: str) -> "Baseline":
        entries = {}
        count = int(doc.get("count", 0))
        nbytes = count * 4  # the callrate bench drives float32
        bucket = size_bucket(nbytes)
        for lane, row in doc.get("lanes", {}).items():
            lat = float(row.get("latency_us", 0.0))
            if lat <= 0:
                continue
            v = {"p50_us": lat, "p99_us": lat, "busbw_GBps": 0.0}
            entries[("allreduce", "float32", bucket, lane)] = v
            # best lane becomes the wildcard gate for live histograms
            wkey = ("allreduce", "float32", bucket, "*")
            if wkey not in entries or lat < entries[wkey]["p50_us"]:
                entries[wkey] = dict(v)
        return cls(entries, source)

    @classmethod
    def _load_sweep_csv(cls, path: str) -> "Baseline":
        import csv

        best: dict = {}
        with open(path) as f:
            for row in csv.DictReader(f):
                try:
                    nbytes = int(float(row["bytes"]))
                    dur = float(row["duration_us"])
                    bw = float(row.get("busbw_GBps", 0.0))
                except (KeyError, ValueError):
                    continue
                key = (row["collective"], "float32", size_bucket(nbytes),
                       "*")
                cur = best.get(key)
                if cur is None or dur < cur["p50_us"]:
                    best[key] = {"p50_us": dur, "p99_us": dur,
                                 "busbw_GBps": bw}
        return cls(best, path)

    # -- persistence ----------------------------------------------------
    def to_doc(self) -> dict:
        return {
            "version": self.VERSION,
            "source": self.source,
            "entries": [
                {"collective": k[0], "dtype": k[1], "size_bucket": k[2],
                 "lane": k[3], **v}
                for k, v in sorted(self.entries.items())],
        }

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f, indent=1, sort_keys=True)
        return path

    def lookup(self, collective: str, dtype: str, bucket: str,
               lane: str = "live") -> Optional[dict]:
        return self.entries.get((collective, dtype, bucket, lane)) \
            or self.entries.get((collective, dtype, bucket, "*"))

    def merge(self, other: "Baseline") -> "Baseline":
        merged = dict(other.entries)
        merged.update(self.entries)  # self wins on conflicts
        return Baseline(merged, f"{self.source}+{other.source}")


class Sentinel:
    """The live drift checker; one per registry (usually the default)."""

    def __init__(self, baseline: Baseline,
                 registry: Optional[MetricsRegistry] = None,
                 p50_ratio: Optional[float] = None,
                 p99_ratio: Optional[float] = None,
                 bw_ratio: Optional[float] = None,
                 min_calls: Optional[int] = None):
        from ..constants import env_float, env_int

        self.baseline = baseline
        self._registry = registry if registry is not None \
            else default_registry()
        self.p50_ratio = p50_ratio if p50_ratio is not None \
            else env_float("ACCL_SENTINEL_P50", 2.0, minimum=1.0)
        self.p99_ratio = p99_ratio if p99_ratio is not None \
            else env_float("ACCL_SENTINEL_P99", 3.0, minimum=1.0)
        self.bw_ratio = bw_ratio if bw_ratio is not None \
            else env_float("ACCL_SENTINEL_BW", 0.5, minimum=0.0)
        self.min_calls = min_calls if min_calls is not None \
            else env_int("ACCL_SENTINEL_MIN_CALLS", 20, minimum=1)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: last check's findings (tests + perf_doctor read this)
        self.findings: list = []
        #: r19 online-tuner hook: callables invoked with each check's
        #: FRESH findings list (never the repeats) — see subscribe()
        self._subscribers: list = []
        #: (collective, dtype, bucket, axis) -> drift ratio at last
        #: delivery (the WORSEN_RATIO re-delivery reference)
        self._delivered: dict = {}

    # -- the comparison (shared by live sentinel + offline doctor) ------
    def compare_snapshot(self, snapshot: dict) -> list:
        """Structured drift findings for one registry snapshot."""
        findings: list = []
        for c in snapshot.get("calls", {}).values():
            good = c["calls"] - c["errors"]
            if good < self.min_calls:
                continue
            base = self.baseline.lookup(c["collective"], c["dtype"],
                                        c["size_bucket"])
            if base is None:
                continue
            hist = [c["hist_us"][f"le_{ub}"] for ub in LATENCY_BUCKETS_US]
            hist.append(c["hist_us"]["inf"])
            p50 = quantile_us(hist, 0.5)
            p99 = quantile_us(hist, 0.99)

            def finding(axis, live, ref, ratio, kind="latency"):
                findings.append({
                    "collective": c["collective"], "dtype": c["dtype"],
                    "size_bucket": c["size_bucket"], "axis": axis,
                    "live": round(live, 2), "baseline": round(ref, 2),
                    "ratio": round(ratio, 3),
                    "threshold": (self.p50_ratio if axis == "p50_us"
                                  else self.p99_ratio
                                  if axis == "p99_us" else self.bw_ratio),
                    "kind": kind,
                    "baseline_source": self.baseline.source,
                })

            if base.get("p50_us", 0) > 0 and \
                    p50 > base["p50_us"] * self.p50_ratio:
                finding("p50_us", p50, base["p50_us"],
                        p50 / base["p50_us"])
            if base.get("p99_us", 0) > 0 and \
                    p99 > base["p99_us"] * self.p99_ratio:
                finding("p99_us", p99, base["p99_us"],
                        p99 / base["p99_us"])
            live_bw = c.get("busbw_GBps", 0.0)
            ref_bw = base.get("busbw_GBps", 0.0)
            if ref_bw > 0 and live_bw > 0 and \
                    live_bw < ref_bw * self.bw_ratio:
                finding("busbw_GBps", live_bw, ref_bw, live_bw / ref_bw,
                        kind="bandwidth")
        return findings

    #: a persisting finding is RE-delivered to subscribers when its
    #: drift ratio worsens past this factor of the last delivery — the
    #: r19 online tuner's revert path depends on it (a bad install
    #: makes an already-flagged cell WORSE; a merely-persisting finding
    #: must not spam the control plane)
    WORSEN_RATIO = 1.25

    def check(self) -> list:
        """One sweep: compare, publish counters + the slow verdict, log
        each NEW (or materially worsened) finding through the
        structured logger."""
        self._registry.inc("sentinel/checks")

        def _key(f):
            return (f["collective"], f["dtype"], f["size_bucket"],
                    f["axis"])

        def _drift(f):
            # bandwidth findings drift DOWN (live/baseline < 1); fold
            # both kinds into a worsens-upward scale
            return 1.0 / f["ratio"] if f["kind"] == "bandwidth" \
                and f["ratio"] else f["ratio"]

        live_keys = set()
        self.findings = self.compare_snapshot(self._registry.snapshot())
        fresh = []
        for f in self.findings:
            live_keys.add(_key(f))
            last = self._delivered.get(_key(f))
            if last is None or _drift(f) > last * self.WORSEN_RATIO:
                fresh.append(f)
                self._delivered[_key(f)] = _drift(f)
        # a finding that cleared re-arms: if it comes back, deliver it
        for k in list(self._delivered):
            if k not in live_keys:
                del self._delivered[k]
        if fresh:
            self._registry.inc("sentinel/findings", len(fresh))
            from ..utils.logging import get_logger

            log = get_logger("accl_tpu.sentinel")
            for f in fresh:
                log.warning(
                    "perf regression: %s %s %s %s drifted %.2fx past "
                    "baseline (live %.2f vs %.2f, threshold %.2fx, "
                    "baseline %s)",
                    f["collective"], f["dtype"], f["size_bucket"],
                    f["axis"], f["ratio"], f["live"], f["baseline"],
                    f["threshold"], f["baseline_source"])
        if fresh:
            # r19: fan the fresh findings out to subscribers (the
            # online tuner's hypothesis intake).  A subscriber fault
            # must never take the sentinel loop down — the loop is the
            # thing that would report it.
            for fn in list(self._subscribers):
                try:
                    fn(list(fresh))
                except Exception:
                    from ..utils.logging import get_logger

                    get_logger("accl_tpu.sentinel").warning(
                        "sentinel subscriber %r raised; dropping this "
                        "delivery", fn, exc_info=True)
        _health.note_slow(self._registry, bool(self.findings))
        return self.findings

    def subscribe(self, fn) -> None:
        """Register a callback for fresh findings (called from the
        sentinel's check thread with a list of finding dicts).  The
        online tuner subscribes here; idempotent per callable."""
        if fn not in self._subscribers:
            self._subscribers.append(fn)

    def unsubscribe(self, fn) -> None:
        try:
            self._subscribers.remove(fn)
        except ValueError:
            pass

    # -- lifecycle ------------------------------------------------------
    def start(self, interval_s: float = 5.0) -> "Sentinel":
        if self._thread is None:
            self.interval_s = max(interval_s, 0.05)
            self._thread = threading.Thread(
                target=self._loop, name="accl-sentinel", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # pragma: no cover — never kill the host
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        _health.note_slow(self._registry, False)


# ---------------------------------------------------------------------------
# env-driven singleton (ACCL.initialize arms it next to the exporter)
# ---------------------------------------------------------------------------
_sentinel_lock = threading.Lock()
_sentinel: Optional[Sentinel] = None


def ensure_sentinel_from_env(
        registry: Optional[MetricsRegistry] = None) -> Optional[Sentinel]:
    """Idempotent env-driven start: ``ACCL_SENTINEL`` unset/0 = off
    (zero threads, zero per-call work); ``1`` = baseline from
    ``ACCL_SENTINEL_BASELINE``; anything else = a baseline path.  Never
    raises — a bad baseline must not take driver bring-up down."""
    global _sentinel
    raw = os.environ.get("ACCL_SENTINEL", "").strip()
    if not raw or raw == "0":
        return None
    with _sentinel_lock:
        if _sentinel is not None:
            return _sentinel
        path = os.environ.get("ACCL_SENTINEL_BASELINE", "") \
            if raw == "1" else raw
        try:
            baseline = Baseline.load(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            from ..utils.logging import get_logger

            get_logger().warning(
                "regression sentinel disabled (ACCL_SENTINEL=%s): "
                "cannot load baseline %r: %s", raw, path, e)
            return None
        from ..constants import env_int

        interval = env_int("ACCL_SENTINEL_INTERVAL_MS", 5000, minimum=1)
        _sentinel = Sentinel(baseline, registry).start(interval / 1000.0)
        return _sentinel


def stop_sentinel() -> None:
    global _sentinel
    with _sentinel_lock:
        if _sentinel is not None:
            _sentinel.stop()
            _sentinel = None
