"""Native-engine telemetry plane: the r14 stats sampler.

The native engine (and the TPU gang scheduler) keep cheap internal
counters — retransmit-store depth/evictions, NACKs sent/received,
rx-pool occupancy high-water, per-transport queue depths, seek-miss
rate, plan table/token state, wire accept/reject — that until r14 were
only reachable one FFI at a time (resilience_stats, frame_stats) or not
at all.  This module is the one polling loop that snapshots them
(``device.engine_stats()``, backed by the versioned flat-array capi
``accl_engine_stats``) and publishes them into the r8
:class:`~accl_tpu.observability.metrics.MetricsRegistry` as ``engine/*``
families, so /metrics scrapes, ``accl_doctor --live`` and the
regression sentinel all see the engine's interior without new FFI
surface per consumer — the per-stage offload-engine visibility ACCL+
(arxiv 2312.11742) argues turns a collective engine from a black box
into something tunable.

Overhead discipline: ``ACCL_TELEMETRY_INTERVAL_MS=0`` (the default) is
the hard off switch — no sampler thread is ever created and the call
hot path is untouched either way (the engine-side counters are atomics
it already maintained; the sampler only adds a reader).  The measured
on/off callrate record is bench/results/callrate_r14_telemetry_*.json.

Schema versioning: ``ENGINE_STATS_FIELDS_V1`` names the capi field
order (append-only ABI — native/src/engine.cpp Engine::engine_stats is
the producer).  A newer engine returning MORE fields than this build
knows keeps the extras as ``engine/unknown_field_<i>`` gauges; the
doctor renders those as "unrecognized (newer world?)" instead of
crashing the report.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from .metrics import MetricsRegistry, default_registry

#: capi accl_engine_stats version-1 field order (the ABI twin of
#: native/src/engine.cpp Engine::engine_stats — APPEND ONLY)
ENGINE_STATS_FIELDS_V1 = (
    "retrans_store_depth",
    "retrans_store_evictions",
    "retrans_sent",
    "nacks_tx",
    "nacks_rx",
    "fenced_drops",
    "rx_occupancy",
    "rx_occupancy_hwm",
    "rx_staged",
    "rx_staged_hwm",
    "rx_pending",
    "egress_depth",
    "egress_hwm",
    "ingress_depth",
    "seeks",
    "seek_misses",
    "plans_live",
    "plan_tokens",
    "plan_replays",
    "wire_accepted_frames",
    "wire_rejected_frames",
    "tx_msgs",
    "tx_payload_bytes",
    "joins_sponsored",
    "joins_completed",
)

#: monotonic fields — published into the registry as counter DELTAS
#: (``engine/<name>`` counters); everything else is a point-in-time
#: gauge (depths, occupancy, high-water marks), published as the MAX
#: across the sampled ranks of a world.  The TPU backend's extra
#: dispatch-lane fields are classified here too.
COUNTER_FIELDS = frozenset((
    "retrans_store_evictions",
    "retrans_sent",
    "nacks_tx",
    "nacks_rx",
    "fenced_drops",
    "seeks",
    "seek_misses",
    "plan_replays",
    "wire_accepted_frames",
    "wire_rejected_frames",
    "tx_msgs",
    "tx_payload_bytes",
    "joins_sponsored",
    "joins_completed",
    # TPU dispatch-lane counters (TpuDeviceView.engine_stats)
    "plan_auto_captures",
    "leader_dispatches",
    "executor_dispatches",
    "batches",
    "batched_gangs",
))


def interval_ms() -> int:
    """Sampler period; ``0`` (the default) = telemetry OFF — no thread,
    zero added work anywhere.  Malformed values raise the naming
    ACCLError (the constants.env_int clear-error contract)."""
    from ..constants import env_int

    return env_int("ACCL_TELEMETRY_INTERVAL_MS", 0, minimum=0)


class TelemetrySampler:
    """Daemon thread polling per-rank ``engine_stats()`` dicts into a
    MetricsRegistry as ``engine/*`` families.

    ``sources`` is a list of zero-arg callables, one per rank, each
    returning a flat {field: int} dict (EmuDevice.engine_stats /
    TpuDeviceView.engine_stats).  Counters are aggregated as summed
    deltas across ranks (so the family is world-total and survives
    sampler restarts without double counting); gauges as the max across
    ranks (the binding resource is the hottest rank's).  A source that
    raises (e.g. its world closed mid-poll) is skipped — telemetry must
    never take a workload down.
    """

    def __init__(self, sources: Iterable[Callable[[], dict]],
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0, name: str = "accl"):
        self._sources = list(sources)
        self._registry = registry if registry is not None \
            else default_registry()
        self.interval_s = max(interval_s, 0.001)
        self._name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: last published counter totals, per field (summed over ranks):
        #: each sample publishes the positive delta
        self._published: dict = {}
        #: samples taken (tests assert liveness without sleeping blind)
        self.samples = 0

    # -- one poll -------------------------------------------------------
    def sample(self) -> dict:
        """Poll every source once and publish; returns the aggregated
        {field: value} snapshot (counters as running totals)."""
        counters: dict = {}
        gauges: dict = {}
        for src in self._sources:
            try:
                stats = src()
            except Exception:  # noqa: BLE001 — a dead world mid-poll
                continue
            for k, v in stats.items():
                if k == "version":
                    continue
                if k in COUNTER_FIELDS:
                    counters[k] = counters.get(k, 0) + int(v)
                else:
                    gauges[k] = max(gauges.get(k, 0), int(v))
        for k, total in counters.items():
            delta = total - self._published.get(k, 0)
            if delta > 0:
                self._registry.inc(f"engine/{k}", delta)
                self._published[k] = total
        for k, v in gauges.items():
            self._registry.set_gauge(f"engine/{k}", v)
        self.samples += 1
        return {**counters, **gauges}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "TelemetrySampler":
        if self._thread is None and self._sources:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self._name}-telemetry",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        # sample immediately, then on the period: a short-lived world
        # still lands one snapshot in the registry
        while True:
            try:
                self.sample()
            except Exception:  # pragma: no cover — never kill the host
                pass
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


def sampler_from_env(sources: Iterable[Callable[[], dict]],
                     registry: Optional[MetricsRegistry] = None,
                     name: str = "accl") -> Optional[TelemetrySampler]:
    """Arm a sampler per ``ACCL_TELEMETRY_INTERVAL_MS`` — None (and no
    thread, no work) when the knob is 0/unset.  Worlds call this at
    bring-up and ``stop()`` it in close()."""
    ms = interval_ms()
    if ms <= 0:
        return None
    return TelemetrySampler(sources, registry=registry,
                            interval_s=ms / 1000.0, name=name).start()


def decode_engine_stats(values, version: int = 1,
                        total_fields: Optional[int] = None) -> dict:
    """Decode a flat capi stats array into the named dict.  Fields past
    this build's schema knowledge (a NEWER engine) are kept as
    ``unknown_field_<i>`` so nothing is silently dropped; the doctor
    renders them as unrecognized instead of crashing."""
    names = ENGINE_STATS_FIELDS_V1
    out = {"version": version}
    for i, v in enumerate(values):
        if total_fields is not None and i >= total_fields:
            break
        key = names[i] if i < len(names) else f"unknown_field_{i}"
        out[key] = int(v)
    return out
