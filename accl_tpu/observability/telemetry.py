"""Native-engine telemetry plane: the r14 stats sampler.

The native engine (and the TPU gang scheduler) keep cheap internal
counters — retransmit-store depth/evictions, NACKs sent/received,
rx-pool occupancy high-water, per-transport queue depths, seek-miss
rate, plan table/token state, wire accept/reject — that until r14 were
only reachable one FFI at a time (resilience_stats, frame_stats) or not
at all.  This module is the one polling loop that snapshots them
(``device.engine_stats()``, backed by the versioned flat-array capi
``accl_engine_stats``) and publishes them into the r8
:class:`~accl_tpu.observability.metrics.MetricsRegistry` as ``engine/*``
families, so /metrics scrapes, ``accl_doctor --live`` and the
regression sentinel all see the engine's interior without new FFI
surface per consumer — the per-stage offload-engine visibility ACCL+
(arxiv 2312.11742) argues turns a collective engine from a black box
into something tunable.

Overhead discipline: ``ACCL_TELEMETRY_INTERVAL_MS=0`` (the default) is
the hard off switch — no sampler thread is ever created and the call
hot path is untouched either way (the engine-side counters are atomics
it already maintained; the sampler only adds a reader).  The measured
on/off callrate record is bench/results/callrate_r14_telemetry_*.json.

Schema versioning: ``ENGINE_STATS_FIELDS_V1``/``_V2`` name the capi
field order per version (append-only ABI — native/src/engine.cpp
Engine::engine_stats is the producer; v2 appends ``link_rows``).  A
newer engine returning MORE fields than this build knows keeps the
extras as ``engine/unknown_field_<i>`` gauges; the doctor renders
those as "unrecognized (newer world?)" instead of crashing the report.

The wire layer (r15): ``accl_engine_link_stats`` exports flat
per-(comm, peer) counter rows — :data:`LINK_STATS_FIELDS_V2` is the
row schema, :func:`decode_link_stats` the strict decoder (a length
that is not a whole number of rows raises, never mis-slices), and
:func:`link_matrix` folds every rank's rows into the world-level P×P
traffic matrix the HiCCL-style topology autotuner (ROADMAP item 2,
arxiv 2408.05962) will consume.  The sampler publishes the matrix as
``link/*`` metric families.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

from .metrics import MetricsRegistry, default_registry

#: capi accl_engine_stats version-1 field order (the ABI twin of
#: native/src/engine.cpp Engine::engine_stats — APPEND ONLY)
ENGINE_STATS_FIELDS_V1 = (
    "retrans_store_depth",
    "retrans_store_evictions",
    "retrans_sent",
    "nacks_tx",
    "nacks_rx",
    "fenced_drops",
    "rx_occupancy",
    "rx_occupancy_hwm",
    "rx_staged",
    "rx_staged_hwm",
    "rx_pending",
    "egress_depth",
    "egress_hwm",
    "ingress_depth",
    "seeks",
    "seek_misses",
    "plans_live",
    "plan_tokens",
    "plan_replays",
    "wire_accepted_frames",
    "wire_rejected_frames",
    "tx_msgs",
    "tx_payload_bytes",
    "joins_sponsored",
    "joins_completed",
)

#: v2 (r15) appends the link-plane row count — the only new scalar; the
#: per-peer counters themselves ride the separate link_stats array
ENGINE_STATS_FIELDS_V2 = ENGINE_STATS_FIELDS_V1 + ("link_rows",)

#: v3 (r17) appends the quantized-wire accounting pair: wire bytes
#: that left through a compressed lane and their uncompressed
#: equivalent (saved bytes = logical - compressed, published by the
#: sampler as the wire/compressed_saved_bytes family)
ENGINE_STATS_FIELDS_V3 = ENGINE_STATS_FIELDS_V2 + (
    "compressed_tx_bytes",
    "compressed_tx_logical_bytes",
)

#: version -> field table (decode_engine_stats consults this so a v1
#: decoder over a v2 engine keeps field 25 as unknown_field_25 — the
#: forward-compat contract the table-driven tests pin both ways)
ENGINE_STATS_FIELDS_BY_VERSION = {
    1: ENGINE_STATS_FIELDS_V1,
    2: ENGINE_STATS_FIELDS_V2,
    3: ENGINE_STATS_FIELDS_V3,
}

#: capi accl_engine_link_stats per-row field order (the ABI twin of
#: native/src/engine.cpp Engine::link_stats — row stride is its
#: length).  v3 (r17) appends comp_tx_bytes: compressed wire bytes
#: sent to the peer, so the link matrix can attribute quantized
#: traffic per link.
LINK_STATS_FIELDS_V3 = (
    "comm",
    "peer",
    "tx_msgs",
    "tx_bytes",
    "rx_msgs",
    "rx_bytes",
    "retrans_sent",
    "nacks_tx",
    "nacks_rx",
    "fenced_drops",
    "seeks",
    "seek_wait_ns",
    "comp_tx_bytes",
)
#: kept as an alias: r15 consumers named the schema by version
LINK_STATS_FIELDS_V2 = LINK_STATS_FIELDS_V3

#: link-row fields that are per-link COUNTERS (everything but the key)
LINK_COUNTER_FIELDS = LINK_STATS_FIELDS_V3[2:]

#: monotonic fields — published into the registry as counter DELTAS
#: (``engine/<name>`` counters); everything else is a point-in-time
#: gauge (depths, occupancy, high-water marks), published as the MAX
#: across the sampled ranks of a world.  The TPU backend's extra
#: dispatch-lane fields are classified here too.
COUNTER_FIELDS = frozenset((
    "retrans_store_evictions",
    "retrans_sent",
    "nacks_tx",
    "nacks_rx",
    "fenced_drops",
    "seeks",
    "seek_misses",
    "plan_replays",
    "wire_accepted_frames",
    "wire_rejected_frames",
    "tx_msgs",
    "tx_payload_bytes",
    "joins_sponsored",
    "joins_completed",
    # quantized wire accounting (v3, r17)
    "compressed_tx_bytes",
    "compressed_tx_logical_bytes",
    # TPU dispatch-lane counters (TpuDeviceView.engine_stats)
    "plan_auto_captures",
    "leader_dispatches",
    "executor_dispatches",
    "batches",
    "batched_gangs",
))


def interval_ms() -> int:
    """Sampler period; ``0`` (the default) = telemetry OFF — no thread,
    zero added work anywhere.  Malformed values raise the naming
    ACCLError (the constants.env_int clear-error contract)."""
    from ..constants import env_int

    return env_int("ACCL_TELEMETRY_INTERVAL_MS", 0, minimum=0)


class TelemetrySampler:
    """Daemon thread polling per-rank ``engine_stats()`` dicts into a
    MetricsRegistry as ``engine/*`` families.

    ``sources`` is a list of zero-arg callables, one per rank, each
    returning a flat {field: int} dict (EmuDevice.engine_stats /
    TpuDeviceView.engine_stats).  Counters are aggregated as summed
    deltas across ranks (so the family is world-total and survives
    sampler restarts without double counting); gauges as the max across
    ranks (the binding resource is the hottest rank's).  A source that
    raises (e.g. its world closed mid-poll) is skipped — telemetry must
    never take a workload down.
    """

    def __init__(self, sources: Iterable[Callable[[], dict]],
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0, name: str = "accl",
                 link_sources: Optional[Iterable[
                     Tuple[int, Callable[[], list]]]] = None):
        self._sources = list(sources)
        #: (global rank, zero-arg callable returning decoded link rows)
        #: — the wire layer (r15); empty = no link plane on this world
        self._link_sources = list(link_sources or [])
        self._registry = registry if registry is not None \
            else default_registry()
        self.interval_s = max(interval_s, 0.001)
        self._name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: last published counter totals, per field (summed over ranks):
        #: each sample publishes the positive delta
        self._published: dict = {}
        #: last published per-link counter totals, per (src, dst, field)
        self._link_published: dict = {}
        #: most recent world-level link matrix (link_matrix doc), for
        #: perf_doctor/tests without re-polling the engines
        self.last_link_matrix: Optional[dict] = None
        #: samples taken (tests assert liveness without sleeping blind)
        self.samples = 0

    # -- one poll -------------------------------------------------------
    def sample(self) -> dict:
        """Poll every source once and publish; returns the aggregated
        {field: value} snapshot (counters as running totals)."""
        counters: dict = {}
        gauges: dict = {}
        for src in self._sources:
            try:
                stats = src()
            except Exception:  # noqa: BLE001 — a dead world mid-poll
                continue
            for k, v in stats.items():
                if k == "version":
                    continue
                if k in COUNTER_FIELDS:
                    counters[k] = counters.get(k, 0) + int(v)
                else:
                    gauges[k] = max(gauges.get(k, 0), int(v))
        deltas: dict = {}
        for k, total in counters.items():
            delta = total - self._published.get(k, 0)
            if delta > 0:
                self._registry.inc(f"engine/{k}", delta)
                self._published[k] = total
                deltas[k] = delta
        # quantized-wire families (r17): compressed bytes on the wire
        # and the bytes the compressed lanes SAVED vs their logical
        # (uncompressed) traffic — the headline multiplier observable
        comp = deltas.get("compressed_tx_bytes", 0)
        logical = deltas.get("compressed_tx_logical_bytes", 0)
        if comp:
            self._registry.inc("wire/compressed_tx_bytes", comp)
        if logical > comp:
            self._registry.inc("wire/compressed_saved_bytes",
                               logical - comp)
        for k, v in gauges.items():
            self._registry.set_gauge(f"engine/{k}", v)
        self._sample_links()
        self.samples += 1
        return {**counters, **gauges}

    def _sample_links(self) -> None:
        """Poll the link plane (r15) and publish ``link/*`` families:
        one counter per (field, src, dst) link cell plus the world
        total per field — the exported form of the P×P traffic matrix.
        Same delta discipline and same never-take-the-workload-down
        tolerance as the scalar plane."""
        if not self._link_sources:
            return
        per_rank: dict = {}
        for rank, src in self._link_sources:
            try:
                per_rank[rank] = src()
            except Exception:  # noqa: BLE001 — a dead world mid-poll
                continue
        if not per_rank:
            return
        # the real world size comes from the configured sources, not
        # from whoever answered this poll: a dead/closing rank must
        # not shrink the matrix and drop live ranks' cells toward it
        nranks = max(r for r, _src in self._link_sources) + 1
        matrix = link_matrix(per_rank, nranks=nranks)
        self.last_link_matrix = matrix
        for field, cells in matrix["fields"].items():
            world_total = 0
            for s, row in enumerate(cells):
                for d, total in enumerate(row):
                    world_total += total
                    key = (field, s, d)
                    delta = total - self._link_published.get(key, 0)
                    if delta > 0:
                        self._registry.inc(
                            f"link/{field}/r{s}->r{d}", delta)
                        self._link_published[key] = total
            key = (field, "world")
            delta = world_total - self._link_published.get(key, 0)
            if delta > 0:
                self._registry.inc(f"link/{field}", delta)
                self._link_published[key] = world_total

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "TelemetrySampler":
        if self._thread is None and self._sources:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self._name}-telemetry",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        # sample immediately, then on the period: a short-lived world
        # still lands one snapshot in the registry
        while True:
            try:
                self.sample()
            except Exception:  # pragma: no cover — never kill the host
                pass
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


def sampler_from_env(sources: Iterable[Callable[[], dict]],
                     registry: Optional[MetricsRegistry] = None,
                     name: str = "accl",
                     link_sources: Optional[Iterable[
                         Tuple[int, Callable[[], list]]]] = None,
                     ) -> Optional[TelemetrySampler]:
    """Arm a sampler per ``ACCL_TELEMETRY_INTERVAL_MS`` — None (and no
    thread, no work) when the knob is 0/unset.  Worlds call this at
    bring-up and ``stop()`` it in close()."""
    ms = interval_ms()
    if ms <= 0:
        return None
    return TelemetrySampler(sources, registry=registry,
                            interval_s=ms / 1000.0, name=name,
                            link_sources=link_sources).start()


def decode_engine_stats(values, version: int = 1,
                        total_fields: Optional[int] = None) -> dict:
    """Decode a flat capi stats array into the named dict.  ``version``
    selects the field table THIS DECODER applies (a v1 caller decoding
    a v2 engine's array passes 1); fields past the selected schema's
    knowledge (a NEWER engine) are kept as ``unknown_field_<i>`` so
    nothing is silently dropped; the doctor renders them as
    unrecognized instead of crashing."""
    names = ENGINE_STATS_FIELDS_BY_VERSION.get(
        version, ENGINE_STATS_FIELDS_V3 if version > 3
        else ENGINE_STATS_FIELDS_V1)
    out = {"version": version}
    for i, v in enumerate(values):
        if total_fields is not None and i >= total_fields:
            break
        key = names[i] if i < len(names) else f"unknown_field_{i}"
        out[key] = int(v)
    return out


def decode_link_stats(values: Sequence[int]) -> List[dict]:
    """Decode a flat ``accl_engine_link_stats`` array into per-link row
    dicts (:data:`LINK_STATS_FIELDS_V2` order).  The array length MUST
    be a whole number of rows: anything else means the caller and the
    engine disagree on the stride, and slicing anyway would silently
    shift every counter into the wrong field — raise the naming error
    instead (the compat-hardening satellite's contract)."""
    from ..constants import ACCLError

    stride = len(LINK_STATS_FIELDS_V2)
    vals = list(values)
    if len(vals) % stride != 0:
        raise ACCLError(
            f"decode_link_stats: flat array length {len(vals)} is not "
            f"a multiple of the per-peer stride {stride} — the engine "
            f"and this decoder disagree on the link-row schema "
            f"(mixed-version world?); refusing to mis-slice")
    return [
        {name: int(vals[r * stride + i])
         for i, name in enumerate(LINK_STATS_FIELDS_V2)}
        for r in range(len(vals) // stride)
    ]


def link_matrix(per_rank_rows: dict, nranks: Optional[int] = None,
                comm: Optional[int] = 0,
                comms: Optional[Iterable[int]] = None) -> dict:
    """Fold per-rank link rows into the world-level P×P traffic matrix.

    ``per_rank_rows`` maps GLOBAL rank -> decoded link rows (the
    ``link_stats()`` output of that rank's device).  ``comm`` selects
    which communicator's rows to fold (default 0, the global comm,
    whose comm-local peer ranks ARE global ranks); ``comm=None`` folds
    every comm — callers owning sub-communicators must map peers to
    global ranks themselves first.  ``comms`` (r20 tenant slicing)
    overrides ``comm`` with an explicit SET of communicator ids to fold
    — the per-tenant view: a tenant's traffic is the union of its
    communicators' rows.  Peer indices in non-global comms are
    comm-local; slice consumers treat rows/cols as comm-local
    coordinates (world kill/join drills keep sub-comm membership
    contiguous from rank 0, so the slice stays meaningful).

    Returns ``{"nranks": P, "fields": {field: P×P list-of-lists}}``
    with ``matrix[src][dst]`` = rank src's counter toward peer dst for
    the tx-side fields, and rank src's RECEIVE-side observation OF dst
    for rx/nacks_tx/fenced/seek fields (both orientations describe the
    src<->dst link; keeping the observer as the row preserves which
    side measured it)."""
    ranks = sorted(per_rank_rows)
    P = nranks if nranks is not None else (max(ranks) + 1 if ranks else 0)
    comm_set = None if comms is None else {int(c) for c in comms}
    fields = {f: [[0] * P for _ in range(P)] for f in LINK_COUNTER_FIELDS}
    for src, rows in per_rank_rows.items():
        if src >= P:
            continue
        for row in rows:
            if comm_set is not None:
                if row.get("comm") not in comm_set:
                    continue
            elif comm is not None and row.get("comm") != comm:
                continue
            dst = int(row.get("peer", -1))
            if not 0 <= dst < P:
                continue
            for f in LINK_COUNTER_FIELDS:
                fields[f][src][dst] += int(row.get(f, 0))
    doc = {"nranks": P, "comm": comm, "fields": fields}
    if comm_set is not None:
        doc["comm"] = None
        doc["comms"] = sorted(comm_set)
    return doc


def slowest_link(matrix: dict,
                 field: str = "seek_wait_ns") -> Optional[Tuple[int, int]]:
    """The (observer, peer) pair with the largest value of ``field`` in
    a :func:`link_matrix` document — for ``seek_wait_ns`` that is the
    link whose peer kept its receiver blocked longest, i.e. the slowest
    link of the world.  None when the matrix carries no signal."""
    cells = matrix.get("fields", {}).get(field)
    if not cells:
        return None
    best, best_v = None, 0
    for src, row in enumerate(cells):
        for dst, v in enumerate(row):
            if v > best_v:
                best, best_v = (src, dst), v
    return best


def link_imbalance(matrix: dict, field: str = "tx_bytes") -> float:
    """Max/mean ratio over the nonzero cells of one matrix field — the
    congestion-skew observable perf_doctor flags (1.0 = perfectly
    balanced; large = one link carries disproportionate traffic)."""
    cells = matrix.get("fields", {}).get(field, [])
    vals = [v for row in cells for v in row if v > 0]
    if not vals:
        return 1.0
    return max(vals) / (sum(vals) / len(vals))
