"""Per-tenant SLO tracker: declarative latency/bandwidth/availability
objectives with multi-window error-budget burn-rate alerting.

The r8 registry already keeps power-of-4 latency histograms per call
signature, and r20's tenant tagging adds the same histograms per
(tenant, collective, dtype, size_bucket).  This module closes the loop
ROADMAP item 3 needs: declarative SLO specs per (tenant, collective,
size-bucket) loaded from ``ACCL_SLO=path``, sliding-window estimators
over those histograms, and the SRE-style multi-window burn-rate
discipline — a FAST window (small, high threshold) that pages quickly
on a cliff, and a SLOW window (large, low threshold) that catches
sustained slow bleed without flapping — plus a cumulative error budget
whose exhaustion is the chaos-soak drill's failure condition
(``scripts/slo_soak.py``: the drill fails on budget exhaustion, not
just wrong bits).

Windows are counted in ``check()`` sweeps (not wall seconds): the
tracker is deterministic under the detsched virtual clock and under
explicitly-driven drills, exactly like the r14 sentinel.  Violation
counting is histogram-native: an observation violates a ceiling when it
landed in a bucket whose upper bound exceeds the ceiling, so ceilings
are best placed at (or derived from) bucket bounds — the soak drill
derives them from a healthy-phase snapshot via :func:`quantile_us`.

Burn-rate thresholds auto-clamp per objective: a p50 objective's
budget is 0.5, so its burn rate can never exceed 2 — the effective
fast/slow thresholds are ``min(threshold, 0.9/budget)`` and
``min(threshold, 0.5/budget)`` so wide-budget objectives stay
alertable while tight ones (p99) keep the classic SRE semantics.

Findings fan out through the same subscription API as the r19
sentinel (``subscribe(fn)`` with worsening-gated re-delivery and
cleared-key re-arm), and — when a live sentinel is armed — through
that sentinel's subscribers too, so one control plane (the online
tuner, a gateway's load shedder) sees both drift and SLO signals.

Knobs (clear-error per the constants contract): ``ACCL_SLO`` (spec
path; unset = off, zero threads, zero per-call work),
``ACCL_SLO_INTERVAL_MS`` (default 0 = no timer thread; drills and the
``/slo`` endpoint drive ``check()`` explicitly),
``ACCL_SLO_FAST_WINDOW`` / ``ACCL_SLO_SLOW_WINDOW`` (sweeps, default
4 / 16), ``ACCL_SLO_FAST_BURN`` / ``ACCL_SLO_SLOW_BURN`` (default
8.0 / 2.0), ``ACCL_SLO_MIN_CALLS`` (default 4).
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

from . import health as _health
from .metrics import (
    LATENCY_BUCKETS_US,
    MetricsRegistry,
    busbw_factor,
    default_registry,
)
from .sentinel import quantile_us

#: report document identity — perf_doctor --slo and the /slo endpoint
#: validate against these (the same format/version discipline as the
#: r19 /retunes history)
SLO_REPORT_FORMAT = "accl-slo-report"
SLO_REPORT_VERSION = 1

#: spec document identity (the ACCL_SLO file)
SLO_SPEC_FORMAT = "accl-slo-spec"
SLO_SPEC_VERSION = 1

#: verdict ladder, weakest to strongest — precedence folds a tenant's
#: objective verdicts to the STRONGEST one (exhausted beats a page
#: beats a slow bleed beats ok)
VERDICT_NAMES = ("ok", "slow_burn", "fast_burn", "exhausted")
V_OK, V_SLOW_BURN, V_FAST_BURN, V_EXHAUSTED = range(4)

#: objective axes a spec can declare
OBJECTIVE_AXES = ("p50_us", "p99_us", "busbw_GBps", "availability")

#: keys every objective row in the report carries (perf_doctor's
#: schema validation pins these)
OBJECTIVE_SCHEMA_KEYS = (
    "tenant", "collective", "size_bucket", "objective", "target",
    "budget", "calls_fast", "bad_fast", "burn_fast", "calls_slow",
    "bad_slow", "burn_slow", "budget_remaining", "verdict",
)


def load_specs(path: str) -> list:
    """Load + validate an ``ACCL_SLO`` spec file; returns normalized
    spec dicts.  Raises ``ValueError`` naming the defect (the caller
    decides whether that is fatal — driver bring-up treats it as
    disable-with-warning, the soak drill as fatal)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("format") != SLO_SPEC_FORMAT:
        raise ValueError(
            f"{path}: not an {SLO_SPEC_FORMAT} document "
            f"(format={doc.get('format') if isinstance(doc, dict) else None!r})")
    if doc.get("version") != SLO_SPEC_VERSION:
        raise ValueError(
            f"{path}: spec version {doc.get('version')!r} != "
            f"{SLO_SPEC_VERSION}")
    slos = doc.get("slos")
    if not isinstance(slos, list) or not slos:
        raise ValueError(f"{path}: 'slos' must be a non-empty list")
    out = []
    for i, s in enumerate(slos):
        if not isinstance(s, dict) or not s.get("tenant"):
            raise ValueError(f"{path}: slos[{i}] needs a 'tenant'")
        spec = {
            "tenant": str(s["tenant"]),
            "collective": str(s.get("collective", "*")),
            "size_bucket": str(s.get("size_bucket", "*")),
            "availability": float(s.get("availability", 0.99)),
        }
        if not 0.0 < spec["availability"] < 1.0:
            raise ValueError(
                f"{path}: slos[{i}] availability must be in (0, 1)")
        axes = 0
        for axis in ("p50_us", "p99_us", "busbw_GBps"):
            if axis in s:
                v = float(s[axis])
                if v <= 0:
                    raise ValueError(
                        f"{path}: slos[{i}] {axis} must be > 0")
                spec[axis] = v
                axes += 1
        if s.get("track_errors"):
            spec["track_errors"] = True
            axes += 1
        if axes == 0:
            raise ValueError(
                f"{path}: slos[{i}] declares no objective (want one of "
                f"p50_us / p99_us / busbw_GBps ceilings-floors or "
                f"track_errors)")
        out.append(spec)
    return out


def _hist_from_doc(call_doc: dict) -> list:
    hist = [call_doc["hist_us"][f"le_{ub}"] for ub in LATENCY_BUCKETS_US]
    hist.append(call_doc["hist_us"]["inf"])
    return hist


def _bad_above(hist: list, ceiling_us: float) -> int:
    """Observations that violated a latency ceiling: everything in
    buckets whose upper bound exceeds it (histogram-native — an
    observation at exactly a bucket bound counts good)."""
    good = 0
    for ub, n in zip(LATENCY_BUCKETS_US, hist):
        if ub <= ceiling_us:
            good += n
        else:
            break
    return sum(hist) - good


class _WindowState:
    """Per-(tenant, collective, dtype, bucket) sliding window of
    per-sweep deltas against the cumulative registry histograms."""

    __slots__ = ("last_hist", "last_calls", "last_errors", "last_bytes",
                 "last_total_us", "window", "nranks")

    def __init__(self, slow_window: int):
        self.last_hist: Optional[list] = None
        self.last_calls = 0
        self.last_errors = 0
        self.last_bytes = 0
        self.last_total_us = 0.0
        self.nranks = 1
        #: per-sweep delta entries {"hist", "calls", "errors", "bytes",
        #: "total_us"}, newest last
        self.window: "deque" = deque(maxlen=slow_window)

    def advance(self, call_doc: dict) -> None:
        hist = _hist_from_doc(call_doc)
        calls = call_doc["calls"]
        errors = call_doc["errors"]
        nbytes = call_doc["bytes"]
        total_us = call_doc["latency_us"]["total"]
        self.nranks = call_doc.get("nranks", 1)
        if self.last_hist is None:
            delta_hist = list(hist)
            d_calls, d_errors = calls, errors
            d_bytes, d_total = nbytes, total_us
        else:
            delta_hist = [max(a - b, 0)
                          for a, b in zip(hist, self.last_hist)]
            d_calls = max(calls - self.last_calls, 0)
            d_errors = max(errors - self.last_errors, 0)
            d_bytes = max(nbytes - self.last_bytes, 0)
            d_total = max(total_us - self.last_total_us, 0.0)
        self.last_hist = hist
        self.last_calls = calls
        self.last_errors = errors
        self.last_bytes = nbytes
        self.last_total_us = total_us
        self.window.append({"hist": delta_hist, "calls": d_calls,
                            "errors": d_errors, "bytes": d_bytes,
                            "total_us": d_total})

    def idle_sweep(self) -> None:
        """No registry entry changed this sweep — the window still
        advances (an idle tenant's burn decays)."""
        self.window.append({"hist": [0] * (len(LATENCY_BUCKETS_US) + 1),
                            "calls": 0, "errors": 0, "bytes": 0,
                            "total_us": 0.0})

    def fold(self, n: int) -> dict:
        """Sum the newest ``n`` window entries."""
        entries = list(self.window)[-n:]
        hist = [0] * (len(LATENCY_BUCKETS_US) + 1)
        calls = errors = nbytes = 0
        total_us = 0.0
        for e in entries:
            for i, v in enumerate(e["hist"]):
                hist[i] += v
            calls += e["calls"]
            errors += e["errors"]
            nbytes += e["bytes"]
            total_us += e["total_us"]
        return {"hist": hist, "calls": calls, "errors": errors,
                "bytes": nbytes, "total_us": total_us}


class SLOTracker:
    """Evaluates declared SLOs against the live per-tenant histograms;
    one per registry (usually the default)."""

    #: a persisting finding re-delivers to subscribers only when its
    #: burn worsens past this factor — same anti-spam discipline as
    #: Sentinel.WORSEN_RATIO (the r19 control-plane contract)
    WORSEN_RATIO = 1.25

    def __init__(self, specs: list,
                 registry: Optional[MetricsRegistry] = None,
                 fast_window: Optional[int] = None,
                 slow_window: Optional[int] = None,
                 fast_burn: Optional[float] = None,
                 slow_burn: Optional[float] = None,
                 min_calls: Optional[int] = None,
                 source: str = ""):
        from ..constants import env_float, env_int

        self.specs = list(specs)
        self.source = source
        self._registry = registry if registry is not None \
            else default_registry()
        self.fast_window = fast_window if fast_window is not None \
            else env_int("ACCL_SLO_FAST_WINDOW", 4, minimum=1)
        self.slow_window = slow_window if slow_window is not None \
            else env_int("ACCL_SLO_SLOW_WINDOW", 16, minimum=1)
        if self.slow_window < self.fast_window:
            self.slow_window = self.fast_window
        self.fast_burn = fast_burn if fast_burn is not None \
            else env_float("ACCL_SLO_FAST_BURN", 8.0, minimum=1.0)
        self.slow_burn = slow_burn if slow_burn is not None \
            else env_float("ACCL_SLO_SLOW_BURN", 2.0, minimum=0.0)
        self.min_calls = min_calls if min_calls is not None \
            else env_int("ACCL_SLO_MIN_CALLS", 4, minimum=1)
        #: (tenant, collective, dtype, bucket) -> _WindowState
        self._windows: dict = {}
        #: cumulative (bad, total) per objective key — the error budget
        self._budget: dict = {}
        self.checks = 0
        #: last check's objective rows / findings (doc() + tests)
        self.objectives: list = []
        self.findings: list = []
        self._subscribers: list = []
        #: objective key -> burn at last delivery (re-arm on clear)
        self._delivered: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- subscription fan-out (the r19 sentinel API shape) --------------
    def subscribe(self, fn) -> None:
        """Register a callback for fresh findings (list of dicts with
        ``kind="slo"``); idempotent per callable — the same contract as
        :meth:`Sentinel.subscribe`."""
        if fn not in self._subscribers:
            self._subscribers.append(fn)

    def unsubscribe(self, fn) -> None:
        try:
            self._subscribers.remove(fn)
        except ValueError:
            pass

    def _fanout_targets(self) -> list:
        """Own subscribers plus — when a live sentinel is armed — its
        subscribers: one control plane sees drift AND SLO signals."""
        targets = list(self._subscribers)
        from . import sentinel as _sentinel

        live = _sentinel._sentinel
        if live is not None:
            for fn in live._subscribers:
                if fn not in targets:
                    targets.append(fn)
        return targets

    # -- evaluation -----------------------------------------------------
    def _spec_keys(self, spec: dict, tenant_calls: dict) -> list:
        """Window keys a spec matches (collective/size_bucket
        wildcards fold every matching signature of the tenant)."""
        keys = []
        for doc in tenant_calls.values():
            if doc["tenant"] != spec["tenant"]:
                continue
            if spec["collective"] not in ("*", doc["collective"]):
                continue
            if spec["size_bucket"] not in ("*", doc["size_bucket"]):
                continue
            keys.append((doc["tenant"], doc["collective"], doc["dtype"],
                         doc["size_bucket"]))
        return keys

    def _thresholds(self, budget: float) -> tuple:
        """Effective (fast, slow) burn thresholds for one objective —
        clamped so wide-budget objectives (p50: budget 0.5, max burn 2)
        remain alertable."""
        return (min(self.fast_burn, 0.9 / budget),
                min(self.slow_burn, 0.5 / budget))

    def _eval_latency(self, okey: tuple, ceiling: float, budget: float,
                      fast: dict, slow: dict) -> dict:
        bad_fast = _bad_above(fast["hist"], ceiling)
        bad_slow = _bad_above(slow["hist"], ceiling)
        return self._eval_counts(okey, budget, fast["calls"], bad_fast,
                                 slow["calls"], bad_slow)

    def _eval_counts(self, okey: tuple, budget: float, calls_fast: int,
                     bad_fast: int, calls_slow: int,
                     bad_slow: int) -> dict:
        burn_fast = (bad_fast / calls_fast / budget) if calls_fast else 0.0
        burn_slow = (bad_slow / calls_slow / budget) if calls_slow else 0.0
        cum_bad, cum_total = self._budget.get(okey, (0, 0))
        # the newest sweep's contribution to the lifetime budget: the
        # fold windows overlap sweep-to-sweep, so budget accumulation
        # uses only the newest delta (fold(1))
        remaining = 1.0
        if cum_total >= self.min_calls:
            remaining = max(0.0, 1.0 - (cum_bad / cum_total) / budget)
        th_fast, th_slow = self._thresholds(budget)
        if cum_total >= self.min_calls and remaining <= 0.0:
            verdict = V_EXHAUSTED
        elif calls_fast >= self.min_calls and burn_fast >= th_fast:
            verdict = V_FAST_BURN
        elif calls_slow >= self.min_calls and burn_slow >= th_slow:
            verdict = V_SLOW_BURN
        else:
            verdict = V_OK
        return {"budget": round(budget, 6),
                "calls_fast": calls_fast, "bad_fast": bad_fast,
                "burn_fast": round(burn_fast, 3),
                "calls_slow": calls_slow, "bad_slow": bad_slow,
                "burn_slow": round(burn_slow, 3),
                "budget_remaining": round(remaining, 4),
                "verdict": VERDICT_NAMES[verdict]}

    def _accumulate_budget(self, okey: tuple, bad_new: int,
                           total_new: int) -> None:
        cum_bad, cum_total = self._budget.get(okey, (0, 0))
        self._budget[okey] = (cum_bad + bad_new, cum_total + total_new)

    def check(self) -> list:
        """One evaluation sweep: advance every tenant window by the
        registry's deltas, evaluate every spec's objectives, publish
        per-tenant verdict/budget gauges, and fan FRESH findings out to
        subscribers.  Returns the findings list (repeat findings
        included; delivery is what's gated)."""
        self._registry.inc("slo/checks")
        self.checks += 1
        snap = self._registry.snapshot()
        tenant_calls = snap.get("tenant_calls", {})
        seen = set()
        for key_str, doc in tenant_calls.items():
            key = (doc["tenant"], doc["collective"], doc["dtype"],
                   doc["size_bucket"])
            seen.add(key)
            st = self._windows.get(key)
            if st is None:
                st = self._windows[key] = _WindowState(self.slow_window)
            st.advance(doc)
        for key, st in self._windows.items():
            if key not in seen:
                st.idle_sweep()

        objectives: list = []
        findings: list = []
        tenant_verdicts: dict = {}
        tenant_budget: dict = {}
        for spec in self.specs:
            keys = self._spec_keys(spec, tenant_calls)
            states = [self._windows[k] for k in keys
                      if k in self._windows]
            tenant = spec["tenant"]
            tenant_verdicts.setdefault(tenant, V_OK)
            if not states:
                continue
            # fold the spec's matching signatures together: the spec is
            # the unit of objective, not the dtype-level signature
            fast = {"hist": [0] * (len(LATENCY_BUCKETS_US) + 1),
                    "calls": 0, "errors": 0, "bytes": 0, "total_us": 0.0}
            slow = {k: (list(v) if isinstance(v, list) else v)
                    for k, v in fast.items()}
            newest = {k: (list(v) if isinstance(v, list) else v)
                      for k, v in fast.items()}
            nranks = 1
            for st in states:
                nranks = max(nranks, st.nranks)
                for dst, n in ((fast, self.fast_window),
                               (slow, self.slow_window), (newest, 1)):
                    fold = st.fold(n)
                    for i, v in enumerate(fold["hist"]):
                        dst["hist"][i] += v
                    for fld in ("calls", "errors", "bytes", "total_us"):
                        dst[fld] += fold[fld]

            def emit(axis, target, row):
                row.update({
                    "tenant": tenant,
                    "collective": spec["collective"],
                    "size_bucket": spec["size_bucket"],
                    "objective": axis,
                    "target": target,
                    # sliding-window estimates (rendering/debugging)
                    "p50_fast_us": round(quantile_us(fast["hist"], 0.5), 2),
                    "p99_fast_us": round(quantile_us(fast["hist"], 0.99), 2),
                    "kind": "slo",
                })
                objectives.append(row)
                v = VERDICT_NAMES.index(row["verdict"])
                tenant_verdicts[tenant] = max(tenant_verdicts[tenant], v)
                if row.get("budget_remaining") is not None:
                    cur = tenant_budget.get(tenant, 1.0)
                    tenant_budget[tenant] = min(cur,
                                                row["budget_remaining"])
                if v > V_OK:
                    findings.append(dict(row))

            for axis, budget in (("p50_us", 0.5),
                                 ("p99_us", 1.0 - spec["availability"])):
                if axis not in spec:
                    continue
                ceiling = spec[axis]
                okey = (tenant, spec["collective"], spec["size_bucket"],
                        axis)
                new = self._eval_newest_latency(states, ceiling)
                self._accumulate_budget(okey, *new)
                row = self._eval_latency(okey, ceiling, budget, fast,
                                         slow)
                emit(axis, ceiling, row)
            if "busbw_GBps" in spec:
                floor = spec["busbw_GBps"]
                bw_fast = self._window_busbw(spec, fast, nranks)
                bw_slow = self._window_busbw(spec, slow, nranks)
                if bw_fast > 0 and bw_fast < floor / 2:
                    verdict = V_FAST_BURN
                elif bw_fast > 0 and bw_fast < floor:
                    verdict = V_SLOW_BURN
                else:
                    verdict = V_OK
                emit("busbw_GBps", floor, {
                    "budget": None,
                    "calls_fast": fast["calls"],
                    "bad_fast": round(bw_fast, 6),
                    "burn_fast": (round(floor / bw_fast, 3)
                                  if bw_fast > 0 else 0.0),
                    "calls_slow": slow["calls"],
                    "bad_slow": round(bw_slow, 6),
                    "burn_slow": (round(floor / bw_slow, 3)
                                  if bw_slow > 0 else 0.0),
                    "budget_remaining": None,
                    "verdict": VERDICT_NAMES[verdict]})
            if spec.get("track_errors"):
                budget = 1.0 - spec["availability"]
                okey = (tenant, spec["collective"], spec["size_bucket"],
                        "availability")
                new_bad = new_total = 0
                for st in states:
                    f1 = st.fold(1)
                    new_bad += f1["errors"]
                    new_total += f1["calls"]
                self._accumulate_budget(okey, new_bad, new_total)
                row = self._eval_counts(okey, budget, fast["calls"],
                                        fast["errors"], slow["calls"],
                                        slow["errors"])
                emit("availability", spec["availability"], row)

        self.objectives = objectives
        self.findings = findings

        # per-tenant verdict surfaces: the labeled accl_health samples
        # (tenant/<t>/health gauges) + budget gauges
        for tenant, v in tenant_verdicts.items():
            self._registry.set_gauge(f"tenant/{tenant}/health", v)
            self._registry.set_gauge(
                f"tenant/{tenant}/slo_budget_remaining",
                round(tenant_budget.get(tenant, 1.0), 4))
        _health.note_slow(self._registry, bool(findings))

        # fresh-delivery gating + cleared-key re-arm (sentinel shape)
        def _fkey(f):
            return (f["tenant"], f["collective"], f["size_bucket"],
                    f["objective"])

        def _severity(f):
            base = VERDICT_NAMES.index(f["verdict"]) * 1000.0
            return base + max(f.get("burn_fast") or 0.0,
                              f.get("burn_slow") or 0.0)

        live_keys = set()
        fresh = []
        for f in findings:
            live_keys.add(_fkey(f))
            last = self._delivered.get(_fkey(f))
            sev = _severity(f)
            if last is None or sev > last * self.WORSEN_RATIO:
                fresh.append(f)
                self._delivered[_fkey(f)] = sev
        for k in list(self._delivered):
            if k not in live_keys:
                del self._delivered[k]
        if fresh:
            self._registry.inc("slo/findings", len(fresh))
            from ..utils.logging import get_logger

            log = get_logger("accl_tpu.slo")
            for f in fresh:
                log.warning(
                    "SLO %s: tenant=%s %s %s %s burn_fast=%.2f "
                    "burn_slow=%.2f budget_remaining=%s",
                    f["verdict"], f["tenant"], f["collective"],
                    f["size_bucket"], f["objective"], f["burn_fast"],
                    f["burn_slow"], f["budget_remaining"])
            for fn in self._fanout_targets():
                try:
                    fn(list(fresh))
                except Exception:
                    from ..utils.logging import get_logger

                    get_logger("accl_tpu.slo").warning(
                        "SLO subscriber %r raised; dropping this "
                        "delivery", fn, exc_info=True)
        return findings

    def _eval_newest_latency(self, states: list,
                             ceiling: float) -> tuple:
        """(bad, total) of ONLY the newest sweep across a spec's
        matching windows — the budget accumulator's increment (the
        fast/slow folds overlap between sweeps and would double-count).
        """
        bad = total = 0
        for st in states:
            f1 = st.fold(1)
            bad += _bad_above(f1["hist"], ceiling)
            total += f1["calls"]
        return bad, total

    @staticmethod
    def _window_busbw(spec: dict, fold: dict, nranks: int) -> float:
        """Windowed bus bandwidth (GB/s) from a fold's byte and
        latency-sum deltas (bytes / ns, nccl-tests correction)."""
        if fold["total_us"] <= 0 or fold["bytes"] <= 0:
            return 0.0
        algbw = fold["bytes"] / (fold["total_us"] * 1e3)
        coll = spec["collective"]
        return algbw * (busbw_factor(coll, nranks)
                        if coll != "*" else 1.0)

    # -- report ---------------------------------------------------------
    def doc(self) -> dict:
        """The versioned SLO report: per-tenant verdicts + budget
        remaining + every objective row from the last check — what the
        exporter's ``/slo`` endpoint serves and ``perf_doctor --slo``
        validates/renders."""
        tenants: dict = {}
        for row in self.objectives:
            t = tenants.setdefault(row["tenant"], {
                "verdict": "ok", "budget_remaining": 1.0,
                "objectives": []})
            t["objectives"].append(
                {k: row.get(k) for k in OBJECTIVE_SCHEMA_KEYS
                 if k in row or k in ("budget", "budget_remaining")}
                | {"p50_fast_us": row.get("p50_fast_us"),
                   "p99_fast_us": row.get("p99_fast_us")})
            if VERDICT_NAMES.index(row["verdict"]) > \
                    VERDICT_NAMES.index(t["verdict"]):
                t["verdict"] = row["verdict"]
            if row.get("budget_remaining") is not None:
                t["budget_remaining"] = min(t["budget_remaining"],
                                            row["budget_remaining"])
        for spec in self.specs:
            tenants.setdefault(spec["tenant"], {
                "verdict": "ok", "budget_remaining": 1.0,
                "objectives": []})
        return {
            "format": SLO_REPORT_FORMAT,
            "version": SLO_REPORT_VERSION,
            "source": self.source,
            "checks": self.checks,
            "fast_window": self.fast_window,
            "slow_window": self.slow_window,
            "fast_burn": self.fast_burn,
            "slow_burn": self.slow_burn,
            "specs": [dict(s) for s in self.specs],
            "tenants": tenants,
            "findings_total": len(self.findings),
        }

    # -- lifecycle ------------------------------------------------------
    def start(self, interval_s: float) -> "SLOTracker":
        if self._thread is None and interval_s > 0:
            self.interval_s = max(interval_s, 0.05)
            self._thread = threading.Thread(
                target=self._loop, name="accl-slo", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # pragma: no cover — never kill the host
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


# ---------------------------------------------------------------------------
# env-driven singleton (ACCL.initialize arms it next to the sentinel)
# ---------------------------------------------------------------------------
_slo_lock = threading.Lock()
_tracker: Optional[SLOTracker] = None


def tracker() -> Optional[SLOTracker]:
    """The live env-armed tracker, if any (the /slo endpoint's
    source)."""
    return _tracker


def ensure_slo_from_env(
        registry: Optional[MetricsRegistry] = None) -> Optional[SLOTracker]:
    """Idempotent env-driven start: ``ACCL_SLO`` unset/0 = off (zero
    threads, zero per-call work); otherwise a spec path.  Never raises
    — a bad spec must not take driver bring-up down (the soak drill
    validates specs fatally via :func:`load_specs` itself)."""
    global _tracker
    raw = os.environ.get("ACCL_SLO", "").strip()
    if not raw or raw == "0":
        return None
    with _slo_lock:
        if _tracker is not None:
            return _tracker
        try:
            specs = load_specs(raw)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            from ..utils.logging import get_logger

            get_logger().warning(
                "SLO tracker disabled (ACCL_SLO=%s): %s", raw, e)
            return None
        from ..constants import env_int

        interval = env_int("ACCL_SLO_INTERVAL_MS", 0, minimum=0)
        _tracker = SLOTracker(specs, registry, source=raw)
        if interval > 0:
            _tracker.start(interval / 1000.0)
        return _tracker


def stop_slo() -> None:
    global _tracker
    with _slo_lock:
        if _tracker is not None:
            _tracker.stop()
            _tracker = None
