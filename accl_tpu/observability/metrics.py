"""Metrics registry: counters, fixed-bucket latency histograms, and
derived algorithmic/bus bandwidth per (collective, dtype, size bucket).

This replaces the ad-hoc ``TpuEngine.stats`` dict: both backends, the
driver, and the bench harnesses (bench/callrate.py, bench/sweep.py)
publish into a :class:`MetricsRegistry`, queryable via
``ACCL.metrics()`` / ``ACCL.dump_metrics()`` (text + JSON).  The
bandwidth conventions (payload and busbw correction factors) are the
nccl-tests ones HiCCL (arxiv 2408.05962) uses as the lingua franca for
comparing collective implementations — the same factors bench/sweep.py
records in its CSVs.

Metrics are always on by default (a handful of dict ops per call);
``ACCL_METRICS=0`` turns the driver's per-call publishing off for
overhead-critical runs.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional

from ..constants import env_int

#: fixed histogram bucket upper bounds, microseconds (powers of 4 —
#: 1 µs .. ~16.8 s, 13 buckets + overflow): coarse enough to stay
#: allocation-free per observation, fine enough to separate the
#: dispatch lanes the callrate bench distinguishes
LATENCY_BUCKETS_US = tuple(4 ** k for k in range(13))

#: collectives whose per-rank payload is count*P elements (the driver
#: count is per-peer / per-chunk) — the nccl-tests size convention
_XP_COLLECTIVES = ("allgather", "reduce_scatter", "alltoall")

#: HELP text for the exporter's well-known families (OpenMetrics `# HELP`
#: lines) — the schema contract exporter consumers (dashboards,
#: alerting) pin in tests/test_flight_recorder.py.  Keys are the FINAL
#: metric names after prefixing/sanitizing (see to_openmetrics name()).
METRIC_HELP = {
    "accl_health": ("world health gauge: 0=ok 1=degraded 2=hung "
                    "3=aborted 4=recovering 5=slow"),
    "accl_watchdog_checks": "watchdog scan sweeps executed",
    "accl_watchdog_fires": "watchdog hang detections (one per episode)",
    "accl_membership_joins": ("replacement ranks admitted through the "
                              "elastic join protocol"),
    "accl_membership_grows": ("communicators grown back toward full "
                              "size (ACCL.grow_communicator)"),
    "accl_membership_shrinks": ("ULFM-style shrinks to a survivor set "
                                "(ACCL.shrink_communicator)"),
    "accl_membership_rank_deaths": ("peer ranks declared dead by a "
                                    "recovery supervisor probe"),
    "accl_recovery_rounds": "recovery-supervisor episodes entered",
    "accl_recovery_halts": ("recovery episodes that gave up (halt "
                            "policy or max rounds exhausted)"),
    "accl_recovery_latency_us": ("end-to-end recovery episode latency, "
                                 "detect -> resume"),
    "accl_join_wait_us": ("time a grow-policy supervisor spent waiting "
                          "for a replacement to announce itself"),
    "accl_plans_captures": ("persistent collective plans captured + "
                            "armed (ACCL.capture_plan)"),
    "accl_plans_replays": ("plan replays issued through the submission "
                           "ring (sync + async + auto lanes)"),
    "accl_plans_invalidations": ("plans fenced by an abort/epoch bump/"
                                 "membership change/reset — each one "
                                 "is a replay that was REFUSED instead "
                                 "of running on a dead world"),
    "accl_wire_accepted_frames": ("ingress wire frames that passed "
                                  "structural validation"),
    "accl_wire_rejected_frames": ("ingress wire frames rejected as "
                                  "malformed (truncated/unknown type/"
                                  "count mismatch/out-of-range comm) — "
                                  "nonzero means a corrupting transport "
                                  "or hostile peer"),
    # ---- engine telemetry families (r14, observability/telemetry.py:
    # the ACCL_TELEMETRY_INTERVAL_MS sampler over accl_engine_stats) ----
    "accl_engine_retrans_store_depth": (
        "live slots in the eager retransmit store (gauge, max rank)"),
    "accl_engine_retrans_store_evictions": (
        "retransmit-store ring wraps over a live slot — a NACK after "
        "an eviction can no longer be served"),
    "accl_engine_retrans_sent": "eager segments retransmitted on NACK",
    "accl_engine_nacks_tx": "NACK solicitations sent (receiver side)",
    "accl_engine_nacks_rx": "NACK solicitations received (sender side)",
    "accl_engine_fenced_drops": ("ingress frames dropped at an abort/"
                                 "epoch fence"),
    "accl_engine_rx_occupancy": "rx-pool buffers RESERVED right now",
    "accl_engine_rx_occupancy_hwm": ("rx-pool occupancy high-water "
                                     "since bring-up"),
    "accl_engine_rx_staged": ("ingress messages parked in the rx-pool "
                              "staging queue (pool exhausted)"),
    "accl_engine_rx_staged_hwm": "rx-pool staging-queue high-water",
    "accl_engine_rx_pending": "rx notifications queued, not yet sought",
    "accl_engine_egress_depth": ("segments staged in the egress "
                                 "pipeline right now"),
    "accl_engine_egress_hwm": "egress staging high-water since bring-up",
    "accl_engine_ingress_depth": ("transport deliveries executing "
                                  "inside the engine right now"),
    "accl_engine_seeks": "recovered-seek entries (blocking rx matches)",
    "accl_engine_seek_misses": ("seeks that timed out after the whole "
                                "recovery budget — misses/seeks is the "
                                "seek-miss rate"),
    "accl_engine_plans_live": "valid persistent plans armed engine-side",
    "accl_engine_plan_tokens": "plan replay tokens in flight/unclaimed",
    "accl_engine_plan_replays": "plan replays queued through the ring",
    "accl_engine_wire_accepted_frames": ("ingress frames that passed "
                                         "structural validation"),
    "accl_engine_wire_rejected_frames": ("ingress frames rejected as "
                                         "malformed"),
    "accl_engine_tx_msgs": "egress messages handed to the transport",
    "accl_engine_tx_payload_bytes": ("egress payload bytes handed to "
                                     "the transport"),
    "accl_engine_joins_sponsored": "elastic joins answered as sponsor",
    "accl_engine_joins_completed": "elastic joins completed as joiner",
    # ---- quantized wire lane (r17, engine stats v3) ----
    "accl_engine_compressed_tx_bytes": (
        "egress payload bytes that left through a compressed wire lane "
        "(f16/bf16 cast or int8 block-scaled)"),
    "accl_engine_compressed_tx_logical_bytes": (
        "uncompressed-equivalent bytes of the compressed egress "
        "traffic (saved = logical - compressed)"),
    "accl_wire_compressed_tx_bytes": (
        "wire bytes sent compressed, summed across the world's "
        "compressed lanes (r17 quantized wire)"),
    "accl_wire_compressed_saved_bytes": (
        "wire bytes SAVED by compression vs the logical uncompressed "
        "traffic — the bandwidth-multiplier observable"),
    # ---- per-link wire telemetry (r15, accl_engine_link_stats) ----
    "accl_engine_link_rows": ("(comm, peer) link rows the engine's "
                              "per-link counter plane is tracking "
                              "(gauge, max rank)"),
    # TPU gang-scheduler twin fields (TpuDeviceView.engine_stats)
    "accl_engine_plan_ring_refs": ("per-rank plan handles pinning live "
                                   "TPU plan rings"),
    "accl_engine_plan_ring_generation": ("max per-comm fence generation "
                                         "(abort/rebuild bumps it)"),
    "accl_engine_plan_ring_replays": "replays issued on live TPU rings",
    "accl_engine_plan_auto_captures": ("plan rings armed by the "
                                       "ACCL_PLAN_AUTO streak detector"),
    "accl_engine_leader_dispatches": ("gangs executed inline on the "
                                      "last-arriving rank's thread"),
    "accl_engine_executor_dispatches": "gangs executed on the executor",
    "accl_engine_batches": "fused executor dispatch batches",
    "accl_engine_batched_gangs": "gangs fused into executor batches",
    "accl_engine_ready_depth": ("complete gangs queued behind the "
                                "executor right now"),
    # ---- per-call collective families (observe_call) ----
    "accl_collective_calls": ("collective calls completed, per "
                              "(collective, dtype, size_bucket)"),
    "accl_collective_errors": "collective calls with non-zero retcode",
    "accl_collective_bytes": "per-rank payload bytes moved",
    "accl_collective_latency_us": ("submit→complete latency histogram "
                                   "(power-of-4 µs buckets)"),
    "accl_collective_algbw_gbps": "algorithmic bandwidth (nccl-tests)",
    "accl_collective_busbw_gbps": ("bus bandwidth (nccl-tests "
                                   "correction factors)"),
    # ---- registry self-protection (r20 cardinality guard) ----
    "accl_metrics_dropped_series": (
        "new metric series refused because the registry hit "
        "ACCL_METRICS_MAX_SERIES — nonzero means a dynamic label "
        "(tenant name, peer id) is minting unbounded families"),
    # ---- per-tenant collective families (r20, observe_call tenant=) ----
    "accl_tenant_collective_calls": ("collective calls completed per "
                                     "(tenant, collective, dtype, "
                                     "size_bucket)"),
    "accl_tenant_collective_errors": ("per-tenant collective calls with "
                                      "non-zero retcode"),
    "accl_tenant_collective_bytes": "per-tenant per-rank payload bytes",
    "accl_tenant_collective_latency_us": (
        "per-tenant submit->complete latency histogram (power-of-4 µs "
        "buckets) — the SLOTracker's estimator substrate"),
    "accl_tenant_collective_algbw_gbps": (
        "per-tenant algorithmic bandwidth (nccl-tests)"),
    "accl_tenant_collective_busbw_gbps": (
        "per-tenant bus bandwidth (nccl-tests correction factors)"),
    # ---- regression sentinel (r14, observability/sentinel.py) ----
    "accl_sentinel_checks": "sentinel comparison sweeps executed",
    "accl_sentinel_findings": ("sentinel drift findings (p50/p99/"
                               "bandwidth past threshold vs baseline)"),
    # ---- per-tenant SLO tracker (r20, observability/slo.py) ----
    "accl_slo_checks": "SLO tracker evaluation sweeps executed",
    "accl_slo_findings": ("fresh SLO findings delivered (fast/slow "
                          "burn-rate breaches, budget exhaustion, "
                          "busbw floor breaches) per tenant objective"),
    # ---- online tuner retune episodes (r19, tuning/online.py) ----
    "accl_tuning_retunes_proposed": ("retune hypotheses opened from a "
                                     "sentinel finding or fabric "
                                     "re-score (one cell or one axis, "
                                     "never a full sweep)"),
    "accl_tuning_retunes_verified": ("retune hypotheses whose "
                                     "challenger won the interleaved "
                                     "best-of A/B against the "
                                     "incumbent"),
    "accl_tuning_retunes_installed": ("retune selections hot-swapped "
                                      "into the live SelectionPolicy "
                                      "and backend registers"),
    "accl_tuning_retunes_rejected": ("retune hypotheses dropped: "
                                     "challenger lost the A/B, "
                                     "hysteresis margin unmet, or "
                                     "cooldown suppressed the cell"),
    "accl_tuning_retunes_reverted": ("installed retunes rolled back "
                                     "after a post-install sentinel "
                                     "regression on the same cell"),
    # ---- TPU per-engine registry bare names (TpuEngine.metrics — the
    # dispatch-lane counters ACCL.metrics() merges under engine/ keys;
    # HELP here keeps the per-engine registry itself exportable) ----
    "accl_leader_dispatches": ("gangs executed inline on the last-"
                               "arriving rank's thread"),
    "accl_executor_dispatches": "gangs executed on the executor thread",
    "accl_batches": "fused executor dispatch batches",
    "accl_batched_gangs": "gangs fused into executor batches",
    "accl_plan_replays": "plan replays issued through the ring",
    "accl_plan_auto_captures": ("plan rings armed by the ACCL_PLAN_AUTO "
                                "streak detector"),
    "accl_compressed_tx_bytes": ("wire bytes the gang scheduler moved "
                                 "through a compressed lane (r17)"),
    "accl_compressed_tx_logical_bytes": (
        "uncompressed-equivalent bytes of the compressed gang traffic"),
}

#: HELP for families minted with dynamic name parts (bench lane labels,
#: unknown newer-engine fields): matched by prefix after sanitizing.
#: The schema-completeness test (tests/test_telemetry.py) enforces that
#: every ``inc``/``set_gauge``/``observe_value`` literal in the tree
#: resolves through METRIC_HELP or one of these prefixes.
METRIC_HELP_PREFIXES = {
    "accl_callrate_": ("callrate bench lane gauge (calls_per_s / "
                       "latency_us / overhead_vs_raw_x per lane)"),
    "accl_sweep_": "bench sweep peak bus-bandwidth gauge per collective",
    "accl_engine_unknown_field_": ("engine stats field past this "
                                   "build's schema (newer engine)"),
    # r15 wire layer: one counter per (field, src->dst) link cell plus
    # the world total per field — the exported P×P traffic matrix
    # (observability/telemetry.py link_matrix / TelemetrySampler)
    "accl_link_": ("per-link wire counter (tx/rx msgs+bytes, "
                   "retransmits, NACKs, fenced drops, seek wait) per "
                   "src->dst link cell, world total when unsuffixed"),
    # r16 learned algorithm selection (accl_tpu/tuning): one counter
    # per algorithm the armed SelectionPolicy chose for a descriptor
    # signature (flat/tree/ring/hierarchical/static)
    "accl_tuning_selected_": ("calls whose descriptor signature the "
                              "ACCL_TUNE_TABLE selection policy "
                              "resolved to this algorithm lane"),
    # r20 per-tenant observability: verdict/budget gauges and any other
    # tenant-scoped family minted by the SLO tracker
    # (observability/slo.py) under tenant/<name>/...
    "accl_tenant_": ("per-tenant observability family (SLO verdicts, "
                     "budget-remaining, burn rates) under the "
                     "tenant/<name>/ namespace"),
}


def metric_help_for(name: str) -> Optional[str]:
    """HELP text for a FINAL (sanitized, prefixed) family name — exact
    entries first, then the dynamic-name prefixes.  None = the family
    is unknown to this build (the doctor renders it as unrecognized
    instead of crashing; the schema test fails the build that MINTED an
    unknown name)."""
    if name in METRIC_HELP:
        return METRIC_HELP[name]
    for prefix, text in METRIC_HELP_PREFIXES.items():
        if name.startswith(prefix):
            return text
    return None


def payload_factor(coll: str, p: int) -> int:
    """Per-rank payload in units of `count` elements."""
    return p if coll in _XP_COLLECTIVES else 1


def busbw_factor(coll: str, p: int) -> float:
    """Bus-bandwidth correction factors (nccl-tests conventions)."""
    if p <= 1:
        return 1.0
    if coll == "allreduce":
        return 2.0 * (p - 1) / p
    if coll in _XP_COLLECTIVES:
        return (p - 1) / p
    return 1.0


def size_bucket(nbytes: int) -> str:
    """Power-of-two size-bucket label (upper bound, human units)."""
    if nbytes <= 0:
        return "0B"
    ub = 1 << max(nbytes - 1, 0).bit_length()
    for unit in ("B", "KiB", "MiB", "GiB"):
        if ub < 1024:
            return f"<={ub}{unit}"
        ub //= 1024
    return f"<={ub}TiB"


class _CallStats:
    __slots__ = ("calls", "errors", "total_ns", "min_ns", "max_ns",
                 "total_bytes", "total_engine_ns", "nranks", "hist")

    def __init__(self, nbuckets: int):
        self.calls = 0
        self.errors = 0
        self.total_ns = 0.0
        self.min_ns = float("inf")
        self.max_ns = 0.0
        self.total_bytes = 0
        self.total_engine_ns = 0.0
        self.nranks = 1
        self.hist = [0] * (nbuckets + 1)  # + overflow


#: default hard bound on distinct series across every table in one
#: registry (counters + gauges + value histograms + call signatures +
#: tenant call signatures) — generous for real worlds, small enough
#: that a tenant-name bug (unbounded labels) cannot OOM the exporter
DEFAULT_MAX_SERIES = 4096

#: the overflow family itself — exempt from the bound so the drop is
#: always countable even at capacity
_DROPPED_SERIES = "metrics/dropped_series"


class MetricsRegistry:
    """Thread-safe counters + gauges + per-call-signature stats.

    New-series creation is bounded by ``ACCL_METRICS_MAX_SERIES``
    (constants env contract: a malformed value raises a clear
    :class:`~accl_tpu.constants.ACCLError` naming the knob).  Once the
    bound is hit, observations that would mint a NEW series are dropped
    and counted under ``metrics/dropped_series``; existing series keep
    updating normally.
    """

    def __init__(self, max_series: Optional[int] = None):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._calls: dict = {}
        #: per-(tenant, collective, dtype, size_bucket) call stats —
        #: the r20 tenant dimension (observe_call tenant=...)
        self._tenant_calls: dict = {}
        #: named value histograms (power-of-4 µs buckets, same shape as
        #: the per-call latency histograms): recovery latency, join
        #: wait — anything that is a distribution but not a collective
        self._values: dict = {}
        self._max_series = (
            max_series if max_series is not None
            else env_int("ACCL_METRICS_MAX_SERIES", DEFAULT_MAX_SERIES,
                         minimum=16))

    # -- cardinality guard (call under self._lock) ---------------------
    def _admit_locked(self, table: dict, key) -> bool:
        """True if `key` may be inserted into `table`: already present,
        or the registry still has series headroom.  A refused insert is
        counted under the (exempt) overflow family."""
        if key in table:
            return True
        total = (len(self._counters) + len(self._gauges)
                 + len(self._values) + len(self._calls)
                 + len(self._tenant_calls))
        if total < self._max_series:
            return True
        self._counters[_DROPPED_SERIES] = \
            self._counters.get(_DROPPED_SERIES, 0) + 1
        return False

    # -- counters / gauges --------------------------------------------
    def inc(self, name: str, value: int = 1) -> None:
        with self._lock:
            if name != _DROPPED_SERIES and \
                    not self._admit_locked(self._counters, name):
                return
            self._counters[name] = self._counters.get(name, 0) + value

    # -- named value histograms ---------------------------------------
    def observe_value(self, name: str, value_us: float) -> None:
        """One observation into the named histogram (µs domain, fixed
        power-of-4 buckets — allocation-free after the first)."""
        with self._lock:
            st = self._values.get(name)
            if st is None:
                if not self._admit_locked(self._values, name):
                    return
                st = self._values[name] = {
                    "count": 0, "sum_us": 0.0,
                    "hist": [0] * (len(LATENCY_BUCKETS_US) + 1)}
            st["count"] += 1
            st["sum_us"] += value_us
            for i, ub in enumerate(LATENCY_BUCKETS_US):
                if value_us <= ub:
                    st["hist"][i] += 1
                    break
            else:
                st["hist"][-1] += 1

    def counter(self, name: str) -> int:
        return self._counters.get(name, 0)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            if not self._admit_locked(self._gauges, name):
                return
            self._gauges[name] = value

    # -- per-call stats ------------------------------------------------
    @staticmethod
    def _record_call_locked(st: "_CallStats", nbytes: int,
                            duration_ns: float, nranks: int, ok: bool,
                            engine_ns: float) -> None:
        st.calls += 1
        st.nranks = nranks
        if not ok:
            st.errors += 1
            return
        st.total_ns += duration_ns
        st.min_ns = min(st.min_ns, duration_ns)
        st.max_ns = max(st.max_ns, duration_ns)
        st.total_bytes += nbytes
        st.total_engine_ns += engine_ns
        us = duration_ns / 1e3
        for i, ub in enumerate(LATENCY_BUCKETS_US):
            if us <= ub:
                st.hist[i] += 1
                break
        else:
            st.hist[-1] += 1

    def observe_call(self, collective: str, dtype: str, nbytes: int,
                     duration_ns: float, nranks: int = 1, ok: bool = True,
                     engine_ns: float = 0.0,
                     tenant: Optional[str] = None) -> None:
        """Record one completed call: count, latency histogram bucket,
        byte volume (bandwidth is derived at snapshot time).  With
        `tenant`, the same observation also lands in the per-tenant
        table (its own latency histogram per signature — the SLO
        tracker's estimator substrate)."""
        key = (collective, dtype, size_bucket(nbytes))
        with self._lock:
            st = self._calls.get(key)
            if st is None:
                if not self._admit_locked(self._calls, key):
                    st = None
                else:
                    st = self._calls[key] = \
                        _CallStats(len(LATENCY_BUCKETS_US))
            if st is not None:
                self._record_call_locked(st, nbytes, duration_ns, nranks,
                                         ok, engine_ns)
            if tenant is None:
                return
            tkey = (tenant,) + key
            tst = self._tenant_calls.get(tkey)
            if tst is None:
                if not self._admit_locked(self._tenant_calls, tkey):
                    return
                tst = self._tenant_calls[tkey] = \
                    _CallStats(len(LATENCY_BUCKETS_US))
            self._record_call_locked(tst, nbytes, duration_ns, nranks,
                                     ok, engine_ns)

    # -- query ---------------------------------------------------------
    @staticmethod
    def _call_doc(coll: str, dtype: str, bucket: str,
                  st: "_CallStats") -> dict:
        good = st.calls - st.errors
        avg_ns = st.total_ns / good if good else 0.0
        algbw = st.total_bytes / st.total_ns if st.total_ns > 0 else 0.0
        return {
            "collective": coll,
            "dtype": dtype,
            "size_bucket": bucket,
            "calls": st.calls,
            "errors": st.errors,
            "nranks": st.nranks,
            "bytes": st.total_bytes,
            "latency_us": {
                "min": round(st.min_ns / 1e3, 2) if good else 0.0,
                "avg": round(avg_ns / 1e3, 2),
                "max": round(st.max_ns / 1e3, 2),
                # exact sum: the OpenMetrics histogram _sum
                # (avg*calls would re-round)
                "total": round(st.total_ns / 1e3, 2),
            },
            "hist_us": {
                **{f"le_{ub}": n for ub, n in
                   zip(LATENCY_BUCKETS_US, st.hist)},
                "inf": st.hist[-1],
            },
            # 6 decimals: a small-message call is a few µGB/s
            # and must not round to a flat 0.0
            "algbw_GBps": round(algbw, 6),
            "busbw_GBps": round(
                algbw * busbw_factor(coll, st.nranks), 6),
        }

    def snapshot(self) -> dict:
        """Full registry state; bandwidths in GB/s (bytes/ns)."""
        with self._lock:
            calls = {}
            for (coll, dtype, bucket), st in self._calls.items():
                calls["|".join((coll, dtype, bucket))] = \
                    self._call_doc(coll, dtype, bucket, st)
            tenant_calls = {}
            for (tenant, coll, dtype, bucket), st in \
                    self._tenant_calls.items():
                doc = self._call_doc(coll, dtype, bucket, st)
                doc["tenant"] = tenant
                tenant_calls["|".join((tenant, coll, dtype, bucket))] = doc
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "values": {k: {"count": v["count"],
                                   "sum_us": round(v["sum_us"], 2),
                                   "hist": list(v["hist"])}
                               for k, v in self._values.items()},
                    "calls": calls,
                    "tenant_calls": tenant_calls}

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), indent=2, sort_keys=True)

    def to_text(self) -> str:
        """Aligned human-readable dump (the dump_metrics text mode)."""
        snap = self.snapshot()
        lines = ["== counters =="]
        for k in sorted(snap["counters"]):
            lines.append(f"  {k:<40} {snap['counters'][k]}")
        if snap["gauges"]:
            lines.append("== gauges ==")
            for k in sorted(snap["gauges"]):
                lines.append(f"  {k:<40} {snap['gauges'][k]:.3f}")
        if snap["values"]:
            lines.append("== value histograms (us) ==")
            for k in sorted(snap["values"]):
                v = snap["values"][k]
                avg = v["sum_us"] / v["count"] if v["count"] else 0.0
                lines.append(f"  {k:<40} n={v['count']} avg={avg:.1f}")
        lines.append("== calls ==")
        hdr = (f"  {'collective':<16} {'dtype':<10} {'size':<10} "
               f"{'calls':>7} {'err':>4} {'avg_us':>10} {'min_us':>10} "
               f"{'max_us':>10} {'algbw':>11} {'busbw':>11}")
        lines.append(hdr)
        for k in sorted(snap["calls"]):
            c = snap["calls"][k]
            lines.append(
                f"  {c['collective']:<16} {c['dtype']:<10} "
                f"{c['size_bucket']:<10} {c['calls']:>7} {c['errors']:>4} "
                f"{c['latency_us']['avg']:>10.2f} "
                f"{c['latency_us']['min']:>10.2f} "
                f"{c['latency_us']['max']:>10.2f} "
                f"{c['algbw_GBps']:>11.6f} {c['busbw_GBps']:>11.6f}")
        return "\n".join(lines)

    def to_openmetrics(self) -> str:
        """OpenMetrics text exposition (the /metrics scrape body served
        by observability.health.start_exporter).

        Layout: counters as ``accl_<name>_total``, gauges as
        ``accl_<name>`` (names already carrying the ``accl_`` prefix —
        e.g. the watchdog's ``accl_health`` — are not double-prefixed),
        and the per-signature call stats as labeled families:
        ``accl_collective_calls_total`` / ``_errors_total`` /
        ``_bytes_total``, an ``accl_collective_latency_us`` histogram
        with cumulative power-of-4 buckets, and ``accl_collective_
        algbw_gbps`` / ``busbw_gbps`` gauges."""
        import re

        def name(n: str) -> str:
            n = re.sub(r"[^a-zA-Z0-9_:]", "_", n)
            return n if n.startswith("accl_") else f"accl_{n}"

        def esc(v) -> str:
            return str(v).replace("\\", r"\\").replace('"', r'\"')

        snap = self.snapshot()
        out = []

        def describe(n: str) -> None:
            text = metric_help_for(n)
            if text is not None:
                out.append(f"# HELP {n} {text}")

        for k in sorted(snap["counters"]):
            n = name(k)
            describe(n)
            out.append(f"# TYPE {n} counter")
            out.append(f"{n}_total {snap['counters'][k]}")
        tenant_health = {}
        for k in sorted(snap["gauges"]):
            m = re.match(r"^tenant/([^/]+)/health$", k)
            if m:
                # per-tenant health rides the accl_health family as a
                # labeled sample (the SLO tracker's verdict surface)
                tenant_health[m.group(1)] = snap["gauges"][k]
                continue
            n = name(k)
            describe(n)
            out.append(f"# TYPE {n} gauge")
            out.append(f"{n} {snap['gauges'][k]}")
        if tenant_health:
            describe("accl_health")
            out.append("# TYPE accl_health gauge")
            for t in sorted(tenant_health):
                out.append(
                    f'accl_health{{tenant="{esc(t)}"}} {tenant_health[t]}')
        for k in sorted(snap["values"]):
            n = name(k)
            v = snap["values"][k]
            describe(n)
            out.append(f"# TYPE {n} histogram")
            cum = 0
            for ub, cnt in zip(LATENCY_BUCKETS_US, v["hist"]):
                cum += cnt
                out.append(f'{n}_bucket{{le="{ub}"}} {cum}')
            cum += v["hist"][-1]
            out.append(f'{n}_bucket{{le="+Inf"}} {cum}')
            out.append(f"{n}_sum {v['sum_us']}")
            out.append(f"{n}_count {v['count']}")
        def emit_call_tables(table: dict, base: str) -> None:
            if not table:
                return
            for fam, kind in ((f"{base}_calls", "counter"),
                              (f"{base}_errors", "counter"),
                              (f"{base}_bytes", "counter"),
                              (f"{base}_latency_us", "histogram"),
                              (f"{base}_algbw_gbps", "gauge"),
                              (f"{base}_busbw_gbps", "gauge")):
                describe(fam)
                out.append(f"# TYPE {fam} {kind}")
            for k in sorted(table):
                c = table[k]
                lbl = (f'collective="{esc(c["collective"])}",'
                       f'dtype="{esc(c["dtype"])}",'
                       f'size_bucket="{esc(c["size_bucket"])}"')
                if "tenant" in c:
                    lbl = f'tenant="{esc(c["tenant"])}",' + lbl
                out.append(f"{base}_calls_total{{{lbl}}} {c['calls']}")
                out.append(f"{base}_errors_total{{{lbl}}} {c['errors']}")
                out.append(f"{base}_bytes_total{{{lbl}}} {c['bytes']}")
                cum = 0
                for ub in LATENCY_BUCKETS_US:
                    cum += c["hist_us"][f"le_{ub}"]
                    out.append(f"{base}_latency_us_bucket"
                               f'{{{lbl},le="{ub}"}} {cum}')
                cum += c["hist_us"]["inf"]
                out.append(f"{base}_latency_us_bucket"
                           f'{{{lbl},le="+Inf"}} {cum}')
                out.append(f"{base}_latency_us_sum"
                           f"{{{lbl}}} {c['latency_us']['total']}")
                out.append(f"{base}_latency_us_count{{{lbl}}} {cum}")
                out.append(f"{base}_algbw_gbps{{{lbl}}} {c['algbw_GBps']}")
                out.append(f"{base}_busbw_gbps{{{lbl}}} {c['busbw_GBps']}")

        emit_call_tables(snap["calls"], "accl_collective")
        emit_call_tables(snap.get("tenant_calls", {}),
                         "accl_tenant_collective")
        out.append("# EOF")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._calls.clear()
            self._tenant_calls.clear()
            self._values.clear()


_default = MetricsRegistry()
_metrics_enabled = os.environ.get("ACCL_METRICS", "1") != "0"


def default_registry() -> MetricsRegistry:
    """The process-wide registry every rank's driver publishes into —
    in-process worlds (EmuWorld/TpuWorld) aggregate across ranks, the
    natural unit the bench harnesses report on."""
    return _default


def enabled() -> bool:
    return _metrics_enabled


def set_enabled(on: bool) -> None:
    global _metrics_enabled
    _metrics_enabled = on


def dump_metrics(registry: Optional[MetricsRegistry] = None,
                 as_json: bool = False) -> str:
    reg = registry if registry is not None else _default
    return reg.to_json() if as_json else reg.to_text()


# ---------------------------------------------------------------------------
# OpenMetrics validator (r14): the schema contract, enforced by
# construction.  tests/test_telemetry.py runs every exporter body
# through this, and the METRIC_HELP completeness test closes the drift
# class where a new family ships without HELP text — a scrape consumer
# should never meet an undocumented family.
# ---------------------------------------------------------------------------
def validate_openmetrics(text: str, require_help: bool = True) -> list:
    """Validate an OpenMetrics exposition body; returns a list of
    problem strings (empty = valid).  Checks the subset of the spec the
    exporter promises: ``# TYPE`` precedes a family's samples, sample
    names extend their declared family correctly (``_total`` for
    counters; ``_bucket``/``_sum``/``_count`` for histograms), values
    parse as numbers, histogram buckets are cumulative-monotonic with a
    ``+Inf`` bound, label syntax is well-formed, and the body ends with
    ``# EOF``.  With ``require_help``, every declared family must also
    resolve through :func:`metric_help_for`."""
    import re

    problems: list = []
    lines = text.splitlines()
    if not lines or lines[-1].strip() != "# EOF":
        problems.append("missing terminal '# EOF' line")
    types: dict = {}
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(\{([a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*)?\})?'
        r' (\S+)$')
    # histogram cumulative check state: (family, labels-sans-le) -> last
    hist_last: dict = {}

    def family_of(name: str) -> Optional[str]:
        for fam, kind in types.items():
            if kind == "counter" and name == f"{fam}_total":
                return fam
            if kind == "histogram" and name in (
                    f"{fam}_bucket", f"{fam}_sum", f"{fam}_count"):
                return fam
            if kind == "gauge" and name == fam:
                return fam
        return None

    for i, ln in enumerate(lines, 1):
        if not ln.strip():
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split(None, 3)
            if len(parts) < 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "info"):
                problems.append(f"line {i}: malformed TYPE line: {ln!r}")
                continue
            types[parts[2]] = parts[3]
            continue
        if ln.startswith("#"):
            continue
        m = sample_re.match(ln)
        if m is None:
            problems.append(f"line {i}: unparsable sample: {ln!r}")
            continue
        name, labels, value = m.group(1), m.group(3) or "", m.group(5)
        try:
            float(value)
        except ValueError:
            problems.append(f"line {i}: non-numeric value {value!r}")
        fam = family_of(name)
        if fam is None:
            problems.append(
                f"line {i}: sample {name!r} has no matching TYPE "
                f"declaration (or wrong suffix for its family kind)")
            continue
        if types[fam] == "histogram" and name == f"{fam}_bucket":
            le = None
            rest = []
            for pair in labels.split(","):
                if pair.startswith('le="'):
                    le = pair[4:-1]
                elif pair:
                    rest.append(pair)
            if le is None:
                problems.append(f"line {i}: histogram bucket without le")
                continue
            key = (fam, ",".join(rest))
            cum = float(value)
            if key in hist_last and cum < hist_last[key]:
                problems.append(
                    f"line {i}: histogram {fam} buckets not cumulative")
            hist_last[key] = cum
            if le == "+Inf":
                hist_last.pop(key, None)
    for key in hist_last:
        problems.append(f"histogram {key[0]} missing le=\"+Inf\" bucket")
    if require_help:
        for fam in types:
            if metric_help_for(fam) is None:
                problems.append(
                    f"family {fam} has no METRIC_HELP entry (add one — "
                    f"the schema contract scrape consumers pin)")
    return problems
